"""Continuous-batching request scheduler with SLO telemetry.

The serving tier's control loop: requests are admitted into the in-flight
decode batch at TOKEN granularity — between any two decode steps a waiting
request can be prefilled into a free slot (vLLM/Orca-style continuous
batching), instead of waiting for the whole batch to drain (static
batching, kept here as the measured baseline). When the paged KV pool runs
dry, the scheduler PREEMPTS: the youngest running request is evicted, its
pages freed, and it re-queues at the FRONT of the waiting line with its
generated prefix folded into the prompt (recompute-on-resume — the pages
are rebuilt by a fresh prefill when capacity returns).

Per-request SLO latency flows through the PR 1 telemetry registry:
time-to-first-token (arrival -> first prefill logit) and
time-per-output-token (mean decode interval) histograms, plus
admitted/completed/preempted counters and running/waiting gauges. The
clock is injectable so admission/preemption order is testable under a
seeded synthetic arrival trace.

Round 13 (replica fleet): requests carry an optional TTL
(`Request.deadline_s` — expiry frees pool pages immediately,
outcome="expired") and can be client-cancelled (`cancel(rid)`,
outcome="cancelled"); the scheduler drains (`drain()` /
`resume_admission()` — stop admissions, finish in-flight) and evacuates
(`evacuate()` — the preemption-resume path applied to every request at
once) for the fleet's hot-swap and failure-survival protocols
(inference/fleet.py).

Round 17 — prefix sharing + speculative decoding:

- Admission consults the pool's prefix index (`prefix_cache=True`,
  default): a prompt whose leading FULL pages match a resident chain
  shares those pages ref-counted (the last prompt token is always
  recomputed — its logits emit the first generated token) and streams only
  the suffix, so prefill work drops to O(new suffix) and shared system
  prompts occupy the pool once. Every running request publishes its
  committed full pages back into the index; completion retains them
  (refcount-zero LRU), while preemption/evacuation frees with
  retain=False so a recycled page can never serve a stale chain.
- `spec_decode=SpecDecodeConfig(...)` turns decode steps into
  draft-then-verify: an n-gram self-draft proposer guesses up to
  `draft_len` continuation tokens from the request's own context, and ONE
  engine.extend() call (the multi-query paged-attention program) verifies
  the whole chain — each position's greedy argmax either matches the next
  draft (accept, keep reading) or replaces it (reject; later drafts'
  stale K/V writes sit past seq_len, masked and overwritten, and surplus
  tail pages are rolled back to the pool). Greedy verify emits EXACTLY
  the tokens plain decode would — byte-identical outputs, fewer steps.
  Prompt streaming rides the same program `draft_len + 1` tokens per
  step (chunked prefill at chunk granularity).

Round 19 — overload protection & multi-tenant QoS (inference/qos.py):

- Requests carry `tenant` + `priority` (0 = highest class). With a
  `qos=QoSPolicy(...)`, submit() gates through per-tenant token buckets
  and the brownout ladder, dequeue order is strict-priority then
  deficit-round-robin over token debt, and a blocked high-priority head
  may PREEMPT a strictly lower-class running request through the same
  pool-dry preempt-resume machinery (exact-output resume guarantee
  intact). Overload sheds work EXPLICITLY: `outcome="shed"` with a
  `retry_after_s` hint and a reason label on the lifecycle counter —
  bounded waiting line (lowest eligible class loses the slot), queue-wait
  bound, rate limit, deadline-unmeetable (TTL shorter than the provable
  minimum service time at the measured EWMA step latency), and brownout
  step 3. The ladder (spec off -> cap low-priority max_new -> shed lowest
  class) degrades only in output-exact ways: greedy spec-off is
  byte-identical, a capped budget is an exact prefix.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import telemetry
from ..telemetry import metrics as _metrics
from ..telemetry import request_trace as _rt
from ..telemetry import timeline as _tl
from .kv_cache import PoolExhausted, chain_extend, prefix_chain_keys
from .qos import BROWNOUT_STEPS, QoSPolicy

__all__ = [
    "Request",
    "ContinuousBatchingScheduler",
    "SpecDecodeConfig",
    "StaticBatchingScheduler",
    "replay",
    "percentiles",
]

_TTFT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, 30.0, 60.0,
)


def _ttft_hist():
    return _metrics.histogram(
        "paddle_tpu_serving_ttft_seconds",
        "time-to-first-token: request arrival -> first prefill logit",
        buckets=_TTFT_BUCKETS,
    )


def _tpot_hist():
    return _metrics.histogram(
        "paddle_tpu_serving_tpot_seconds",
        "time-per-output-token: mean decode interval per request",
        buckets=_TTFT_BUCKETS,
    )


def _req_counter():
    return _metrics.counter(
        "paddle_tpu_serving_requests_total",
        "request lifecycle events; `reason` distinguishes shed/reject "
        "causes (empty on plain lifecycle transitions)",
        label_names=("event", "reason"),
    )


def _brownout_step_gauge():
    return _metrics.gauge(
        "paddle_tpu_qos_brownout_step",
        "current brownout ladder rung (0 = normal, 3 = shedding lowest class)",
    )


def _brownout_transitions(direction: str, to: str):
    return _metrics.counter(
        "paddle_tpu_qos_brownout_transitions_total",
        "brownout ladder transitions by direction and destination rung",
        label_names=("direction", "to"),
    ).labels(direction=direction, to=to)


def _queue_gauge(state: str):
    return _metrics.gauge(
        "paddle_tpu_serving_queue",
        "scheduler occupancy by state",
        label_names=("state",),
    ).labels(state=state)


def _spec_counter(event: str):
    return _metrics.counter(
        "paddle_tpu_spec_decode_tokens_total",
        "speculative-decode tokens by event (drafted = proposed by the "
        "n-gram self-draft, accepted = verified equal to the greedy chain)",
        label_names=("event",),
    ).labels(event=event)


@dataclass
class SpecDecodeConfig:
    """Speculative decoding knobs: `draft_len` tokens are proposed per
    decode step by an n-gram self-draft (the most recent earlier occurrence
    of the context's final `ngram` tokens proposes its continuation — the
    zero-extra-model proposer that exploits the repetition heavy serving
    traffic actually has) and verified in one engine.extend() call."""

    draft_len: int = 3
    ngram: int = 2

    def __post_init__(self):
        if self.draft_len < 1:
            raise ValueError("SpecDecodeConfig.draft_len must be >= 1")
        if self.ngram < 1:
            raise ValueError("SpecDecodeConfig.ngram must be >= 1")


@dataclass
class Request:
    """One generation request. `prompt` is token ids; the scheduler fills
    the runtime fields."""

    rid: int
    prompt: List[int]
    max_new_tokens: int = 16
    arrival_time: float = 0.0
    # per-request TTL in scheduler-clock seconds from submit(); an expired
    # request frees its pool pages IMMEDIATELY instead of pinning them for
    # a client that will never read the answer (outcome="expired")
    deadline_s: Optional[float] = None
    # fleet session-affinity key: follow-on requests of one conversation
    # carry the same session so the router sends them to the replica that
    # (may) hold their warm KV pages; None = no affinity
    session: Optional[object] = None
    # QoS identity: tenant keys the token bucket + fair-share debt;
    # priority is the preemption/shed class (0 = highest — a P0 may evict
    # a strictly larger-priority victim's pages, brownout acts on
    # priorities >= the configured low class)
    tenant: str = "default"
    priority: int = 1

    # runtime (scheduler-owned)
    generated: List[int] = field(default_factory=list)
    pages: List[int] = field(default_factory=list)
    preemptions: int = 0
    # terminal disposition: "completed" | "expired" | "cancelled" |
    # "shed" (None while in flight); the fleet also reads it for
    # zero-loss accounting. A shed request carries the retry hint.
    outcome: Optional[str] = None
    # a shed request carries WHY (one of qos.SHED_REASONS) and when to
    # retry — the client-facing half of the explicit-backpressure contract
    shed_reason: Optional[str] = None
    retry_after_s: Optional[float] = None
    # brownout step 2 bookkeeping: the pre-cap generation budget (None =
    # never capped) — recovery tests pin that a capped survivor's output
    # is an exact prefix of its uncapped greedy chain
    qos_orig_max_new: Optional[int] = None
    # absolute clock at submit() — arrival_time is a REPLAY-relative offset
    # and must never be differenced against absolute timestamps
    submitted_time: Optional[float] = None
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    token_times: List[float] = field(default_factory=list)
    # token-streamed admission: prompt tokens already written to the cache
    # (cursor == len(prompt) once the request is generating)
    cursor: int = 0
    # recompute-on-resume: prompt tokens re-prefilled after a preemption
    # include the already-generated prefix; `_prompt_len` keeps the original
    _prompt_len: Optional[int] = None
    # prefix cache: prompt tokens served from shared pages instead of
    # recomputed (cumulative across resumes); speculative decoding: tokens
    # proposed by the draft / verified equal to the greedy chain
    cached_tokens: int = 0
    drafted: int = 0
    accepted: int = 0
    # committed full pages already published into the prefix index, and
    # the chain digest AFTER them (== the last registered page's key) so
    # each new page's key costs O(block_size), not O(context)
    _registered_pages: int = 0
    _chain_digest: bytes = b""
    # request-scoped trace handle (telemetry.request_trace) — None unless
    # FLAGS_request_trace sampled this request; travels WITH the request
    # across preemption/evacuation/re-dispatch so the phase chain stays
    # unbroken end to end
    trace: Optional[object] = field(default=None, repr=False, compare=False)

    @property
    def prompt_len(self) -> int:
        return self._prompt_len if self._prompt_len is not None else len(self.prompt)

    @property
    def context_len(self) -> int:
        return len(self.prompt) + len(self.generated)

    @property
    def done(self) -> bool:
        return self.finish_time is not None

    def ttft(self) -> Optional[float]:
        """submit -> first token, scheduler-clock seconds (replay computes
        its arrival-inclusive TTFT itself — arrival_time is an offset on a
        different time base)."""
        if self.first_token_time is None or self.submitted_time is None:
            return None
        return self.first_token_time - self.submitted_time

    def tpot(self) -> Optional[float]:
        """Mean decode interval; None until a second token exists."""
        if len(self.token_times) < 2:
            return None
        return (self.token_times[-1] - self.token_times[0]) / (len(self.token_times) - 1)


class ContinuousBatchingScheduler:
    """Token-level admission into the in-flight decode batch.

    step() = [complete finished] -> [admit waiting while slots + pages
    allow] -> [grow running sequences' page allocation, preempting when the
    pool is dry] -> [one decode step for everyone running].
    """

    def __init__(self, engine, *, max_running: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 prefix_cache: bool = True,
                 spec_decode: Optional[SpecDecodeConfig] = None,
                 qos: Optional[QoSPolicy] = None,
                 admission_mode: str = "auto"):
        self.engine = engine
        self.max_running = int(max_running or engine.max_batch)
        if self.max_running > engine.max_batch:
            raise ValueError("max_running exceeds the engine's decode capacity")
        self.eos_id = eos_id
        self.clock = clock
        self.prefix_cache = bool(prefix_cache)
        self.spec = spec_decode
        # "auto" (default): idle-scheduler admissions run a bucketed prefill
        # program, busy ones stream. "streamed": NEVER bucketed — the
        # disaggregated fleet's decode tier runs this, so it serves streamed
        # prefill (tier-degradation intake) without ever compiling a prefill
        # bucket, keeping its compile family decode-only
        if admission_mode not in ("auto", "streamed"):
            raise ValueError(
                f"admission_mode {admission_mode!r} is not 'auto' or 'streamed'")
        self.admission_mode = admission_mode
        # shared across a fleet's replicas: buckets/debt/ladder are
        # fleet-wide state, the scheduler only consults it
        self.qos = qos
        self.waiting: List[Request] = []
        self.running: List[Request] = []
        self.finished: List[Request] = []
        self.preempted_total = 0
        self.shed_total = 0
        # measured per-step latency (same 0.8/0.2 blend the fleet router
        # drains by) — the deadline-shed and retry-after estimates
        self.ewma_step_s: Optional[float] = None
        # drain mode (fleet hot-swap protocol): admissions stop, in-flight
        # work keeps decoding to completion, submit() still accepts (the
        # caller is expected to route elsewhere; anything queued here just
        # waits out the drain)
        self.draining = False

    # ---- queue surface ----
    def drain(self) -> None:
        """Stop admitting new work into decode slots (in-flight requests
        run to completion). The fleet swap protocol: drain -> swap weights
        -> resume_admission."""
        self.draining = True

    def resume_admission(self) -> None:
        self.draining = False

    def submit(self, req: Request) -> None:
        max_ctx = self.engine.max_seq_len
        # prompt_len, not len(prompt): a preempted/evacuated request folds
        # its generated prefix into the prompt, but its FINAL context is
        # still original-prompt + max_new (re-validating the folded length
        # would reject a legal request mid-recovery)
        total = req.prompt_len + req.max_new_tokens
        if total > max_ctx:
            self._count_reject("context_overflow")
            raise ValueError(
                f"request {req.rid}: prompt_len {req.prompt_len} + "
                f"max_new_tokens {req.max_new_tokens} = {total} "
                f"exceeds max_seq_len {max_ctx}"
            )
        pool = self.engine.pool
        if pool.blocks_for_tokens(total) > pool.num_blocks - 1:
            # would deadlock at its final preemption-resume: even an empty
            # pool could never hold the full context
            self._count_reject("pool_capacity")
            raise ValueError(
                f"request {req.rid}: full context of {total} tokens "
                f"(prompt_len {req.prompt_len} + max_new_tokens "
                f"{req.max_new_tokens}) needs {pool.blocks_for_tokens(total)} "
                f"pages; the pool has {pool.num_blocks - 1} usable "
                f"(num_blocks {pool.num_blocks} minus the reserved page)"
            )
        # preserved across re-dispatch (like _prompt_len): a request
        # evacuated off a dead replica keeps its ORIGINAL submit clock, so
        # its TTL and client-perceived TTFT never silently restart
        if req.submitted_time is None:
            req.submitted_time = self.clock()
        if self.qos is not None and self._qos_submit_gate(req):
            return  # shed: terminal, counted, retryable
        self.waiting.append(req)
        if req.trace is None:
            req.trace = _rt.start(
                req.rid, req.submitted_time,
                prompt_len=req.prompt_len, max_new=req.max_new_tokens,
            )
            if req.trace is not None:
                req.trace.phase("queue", self.clock())
        elif req.trace.phase_name != "preempt":
            # re-dispatch of an already-traced request (fleet migration off
            # a draining replica): it queues again; an open "preempt" span
            # (evacuation/preemption) instead runs until re-admission
            req.trace.phase("queue", self.clock(), cause="requeue")
        if telemetry.enabled():
            _req_counter().labels(event="submitted", reason="").inc()
            self._sync_gauges()

    @staticmethod
    def _count_reject(reason: str) -> None:
        """Validation rejections (the ValueError paths) get the same
        reason-labeled visibility as sheds — a dashboard must be able to
        tell WHY requests bounce, not just that they did."""
        if telemetry.enabled():
            _req_counter().labels(event="rejected", reason=reason).inc()

    def _qos_submit_gate(self, req: Request) -> bool:
        """Admission-time QoS gates in cheapest-first order; returns True
        when the request was shed (terminal — caller must not queue it)."""
        qos = self.qos
        now = self.clock()
        # brownout step 3: new lowest-class work is refused while the
        # ladder is at the top rung; retry after the recovery cooldown
        if qos.brownout.sheds(req.priority):
            self._shed_submit(req, now, "brownout",
                              retry_after=qos.brownout.cfg.cooldown_s)
            return True
        ok, retry = qos.rate_gate(req, now)
        if not ok:
            self._shed_submit(req, now, "rate_limit", retry_after=retry)
            return True
        emit_bound = (self.spec.draft_len + 1) if self.spec is not None else 1
        if qos.deadline_unmeetable(req, self.ewma_step_s, emit_bound):
            # no retry hint: a TTL the engine provably cannot meet will
            # not be meetable a bucket-refill later either
            self._shed_submit(req, now, "deadline_unmeetable")
            return True
        if qos.queue_full(len(self.waiting)):
            victim = qos.queue_full_victim(self.waiting, req)
            retry = (round(self.ewma_step_s * max(1, len(self.waiting)), 6)
                     if self.ewma_step_s else None)
            if victim is req:
                self._shed_submit(req, now, "queue_full", retry_after=retry)
                return True
            # the newcomer strictly outranks the lowest queued class:
            # the victim sheds, the newcomer takes its slot
            self.waiting.remove(victim)
            self._shed(victim, now, "queue_full", retry_after=retry)
        return False

    def _shed_submit(self, req: Request, now: float, reason: str,
                     retry_after: Optional[float] = None) -> None:
        """Shed at the submit boundary: the request still counts as
        submitted (offered load) and gets a trace so the span chain
        contract holds for EVERY terminal path."""
        if req.trace is None:
            req.trace = _rt.start(
                req.rid, req.submitted_time,
                prompt_len=req.prompt_len, max_new=req.max_new_tokens,
            )
            if req.trace is not None:
                req.trace.phase("queue", now)
        if telemetry.enabled():
            _req_counter().labels(event="submitted", reason="").inc()
        self._shed(req, now, reason, retry_after=retry_after)

    def _shed(self, req: Request, now: float, reason: str,
              retry_after: Optional[float] = None) -> None:
        """Terminal overload rejection: explicit, counted, retryable.
        Waiting/new requests hold no pages, so _finish's free is a no-op;
        the request lands in `finished` with outcome="shed" (zero-loss
        fleet accounting sees it like any other terminal outcome)."""
        req.outcome = "shed"
        req.shed_reason = reason
        req.retry_after_s = retry_after
        self.shed_total += 1
        _tl.emit("qos", "shed", severity="warn", rid=req.rid, reason=reason,
                 priority=req.priority, retry_after_s=retry_after)
        if self.qos is not None:
            self.qos.note_shed(reason)
        self._finish(req, now, reason=reason)
        if telemetry.enabled():
            self._sync_gauges()

    def idle(self) -> bool:
        return not self.waiting and not self.running

    def _sync_gauges(self) -> None:
        _queue_gauge("running").set(len(self.running))
        _queue_gauge("waiting").set(len(self.waiting))

    # ---- lifecycle ----
    def _finish(self, req: Request, now: float, reason: str = "") -> None:
        req.finish_time = now
        req.outcome = req.outcome or "completed"
        # retain=True: a finished request's registered (committed, full)
        # pages stay resident at refcount zero, LRU-evictable — the warm
        # prefix cache a follow-on request with the same system prompt hits
        self.engine.pool.free(req.pages, owner=req.rid, retain=True)
        req.pages = []
        self.finished.append(req)
        if req.trace is not None:
            extra = {"reason": reason} if reason else {}
            if req.retry_after_s is not None:
                extra["retry_after_s"] = req.retry_after_s
            req.trace.close(
                now, req.outcome,
                generated=(len(req.prompt) - req.prompt_len) + len(req.generated),
                preemptions=req.preemptions,
                cached_tokens=req.cached_tokens,
                drafted=req.drafted,
                accepted=req.accepted,
                **extra,
            )
        if telemetry.enabled():
            _req_counter().labels(event=req.outcome, reason=reason).inc()
            tpot = req.tpot()
            if tpot is not None:
                _tpot_hist().observe(tpot)
        # every terminal disposition lands on the incident timeline: the
        # completed ones are the denominator, the shed/expired/cancelled
        # ones are what an SLO-burn triage window needs to see
        _tl.emit("scheduler", "request.finish",
                 severity="info" if req.outcome == "completed" else "warn",
                 rid=req.rid, outcome=req.outcome, reason=reason,
                 generated=len(req.generated), preemptions=req.preemptions)

    def cancel(self, rid: int) -> bool:
        """Client-side cancellation: drop the request wherever it is and
        free its pages IMMEDIATELY (a stuck/gone client must not pin pool
        pages for the rest of the process). Returns False when `rid` is not
        in flight (already finished or never submitted)."""
        for queue in (self.waiting, self.running):
            for req in queue:
                if req.rid == rid:
                    queue.remove(req)
                    req.outcome = "cancelled"
                    self._finish(req, self.clock())
                    if telemetry.enabled():
                        self._sync_gauges()
                    return True
        return False

    def _expire_due(self, now: float) -> None:
        """Per-request TTL: requests past their deadline_s (scheduler-clock
        seconds since submit) finish with outcome="expired" and free their
        pages right now — the serving-tier analogue of a dead client."""
        for queue in (self.waiting, self.running):
            for req in list(queue):
                if (
                    req.deadline_s is not None
                    and req.submitted_time is not None
                    and now - req.submitted_time > req.deadline_s
                ):
                    queue.remove(req)
                    req.outcome = "expired"
                    self._finish(req, now)

    def _reset_for_resume(self, req: Request) -> Request:
        """Recompute-on-resume bookkeeping shared by preemption and fleet
        evacuation: generated tokens fold into the prompt (their K/V is
        rebuilt by a fresh prefill/stream on whatever engine resumes the
        request) and the streaming cursor rewinds. Pages must already be
        freed by the caller."""
        if req._prompt_len is None:
            req._prompt_len = len(req.prompt)
        req.prompt = req.prompt + req.generated
        req.generated = []
        req.cursor = 0
        req._registered_pages = 0
        req._chain_digest = b""
        return req

    def _preempt_one(self, cause: str = "pool_dry",
                     below_priority: Optional[int] = None) -> bool:
        """Evict the lowest-class request with the least sunk work
        (priority descending, then still-streaming first, then youngest)
        back to the front of the waiting queue, recompute-on-resume.
        `below_priority` restricts victims to strictly lower classes —
        the QoS priority-preemption path; equal-priority traffic (the
        default) keeps the original pool-dry victim order exactly."""
        candidates = (
            [r for r in self.running if r.priority > below_priority]
            if below_priority is not None else self.running
        )
        if not candidates:
            return False
        victim = max(
            candidates,
            key=lambda r: (r.priority, r.first_token_time is None,
                           r.first_token_time or 0.0, r.rid),
        )
        self.running.remove(victim)
        # retain=False: an evicted context is conceptually discarded — its
        # refcount-zero pages go straight back to the free list and their
        # index entries drop, so a preemption-freed page can NEVER serve a
        # later prefix hit after being overwritten by a new owner
        self.engine.pool.free(victim.pages, owner=victim.rid, retain=False)
        victim.pages = []
        self._reset_for_resume(victim)
        victim.preemptions += 1
        self.preempted_total += 1
        self.waiting.insert(0, victim)
        if victim.trace is not None:
            # the preempt span runs until re-admission (recompute resumes)
            victim.trace.phase("preempt", self.clock(), cause=cause)
        if telemetry.enabled():
            _req_counter().labels(
                event="preempted",
                reason="" if cause == "pool_dry" else cause,
            ).inc()
        _tl.emit("scheduler", "preempt", severity="warn", rid=victim.rid,
                 cause=cause, preemptions=victim.preemptions)
        return True

    def evacuate(self) -> List[Request]:
        """Pull EVERY in-flight and queued request out of this scheduler,
        reset for recompute-on-resume (the preemption path generalized to
        the whole replica), and return them in resume order (running
        first — they have the most sunk work — then waiting). The fleet
        calls this when a replica's circuit breaker opens: the requests are
        re-submitted to a healthy replica and their K/V pages are rebuilt
        from the folded prompt there."""
        evacuated: List[Request] = []
        now = self.clock()
        for req in self.running:
            # same retain=False contract as preemption (the PR 11 path):
            # evacuated pages leave the index before they can be recycled
            self.engine.pool.free(req.pages, owner=req.rid, retain=False)
            req.pages = []
            evacuated.append(self._reset_for_resume(req))
        # waiting requests hold no pages; a preemption-requeued one is
        # already in resume form
        evacuated.extend(self.waiting)
        for req in evacuated:
            if req.trace is not None:
                # cause-labeled: distinguishable from pool_dry preemption
                req.trace.phase("preempt", now, cause="evacuation")
        self.running = []
        self.waiting = []
        if telemetry.enabled():
            self._sync_gauges()
        return evacuated

    def adopt_running(self, req: Request) -> None:
        """Attach an in-flight request whose KV pages are ALREADY resident
        in this scheduler's pool (the fleet's prefill->decode KV migration):
        no re-validation, no clock re-stamping — the request keeps decoding
        exactly where it left off. The caller owns the page handoff (pages
        allocated here, CRC-verified) and the prefix-registration reset so
        this pool republishes the chain itself."""
        if len(self.running) >= self.max_running:
            raise RuntimeError(
                f"adopt_running: no free decode slot for request {req.rid}")
        self.running.append(req)
        if telemetry.enabled():
            self._sync_gauges()

    def _emit_token(self, req: Request, logits: np.ndarray, now: float) -> None:
        token = int(np.argmax(logits))
        req.generated.append(token)
        req.token_times.append(now)
        # every emitted token belongs to the decode phase — keyed on the
        # trace's own phase, not first_token_time, because a mid-decode
        # preemption re-opens a prefill span on resume (first_token_time
        # stays set) and the post-resume tokens must flip back to decode
        if req.trace is not None and req.trace.phase_name != "decode":
            req.trace.phase("decode", now)
        if req.first_token_time is None:
            req.first_token_time = now
            if telemetry.enabled() and req.submitted_time is not None:
                # both timestamps from the scheduler clock: queue wait
                # inside the scheduler is included, replay-offset arrival
                # bookkeeping is not (it lives on a different time base)
                _ttft_hist().observe(max(0.0, now - req.submitted_time))
        total_generated = (len(req.prompt) - req.prompt_len) + len(req.generated)
        if total_generated >= req.max_new_tokens or (
            self.eos_id is not None and token == self.eos_id
        ):
            self._finish(req, now)

    @staticmethod
    def _tokens_needed(req: Request) -> int:
        """Cache slots this step's write for `req` must be covered for:
        streaming writes prompt[cursor] at position cursor; generation
        writes generated[-1] at position context_len - 1."""
        if req.cursor < len(req.prompt):
            return req.cursor + 1
        return req.context_len

    def _try_admit(self) -> Optional[int]:
        """Admit the oldest waiting request into a free decode slot;
        returns the number of tokens emitted by the admission (1 for a
        bucketed prefill, 0 for a streamed one), or None when blocked.

        Two admission paths (the continuous-batching TPOT trade): with
        NOTHING in flight there is no one to stall, so the prompt runs
        through a bucketed prefill program in one shot (TTFT-optimal).
        With decode in flight, a monolithic prefill between two decode
        steps would stretch every in-flight request's inter-token interval
        — instead the prompt is STREAMED through the request's own decode
        slot one token per step (chunked prefill at token granularity), so
        admission never stalls anyone else's decode cadence.

        Round 17: admission consults the prefix index first. A hit shares
        the resident pages (refcounted) and ALWAYS streams — only the
        un-cached suffix flows through decode slots, and the bucketed
        prefill (which writes every prompt position) never touches shared
        pages. The last prompt token is never served from cache: its
        logits emit the first generated token, so at least one position
        always recomputes.
        """
        if self.draining or not self.waiting or len(self.running) >= self.max_running:
            return None
        # QoS dequeue order: strict priority, then deficit-round-robin
        # over token debt (single-tenant equal-priority traffic selects
        # index 0 — the pre-QoS FIFO, preemption-requeue order included)
        idx = self.qos.select(self.waiting) if self.qos is not None else 0
        req = self.waiting[idx]
        pool = self.engine.pool
        shared: List[int] = []
        if self.prefix_cache and req.cursor == 0:
            n_shareable = (len(req.prompt) - 1) // pool.block_size
            if n_shareable > 0:
                keys = prefix_chain_keys(req.prompt, pool.block_size)[:n_shareable]
                shared = pool.acquire_prefix(keys, owner=req.rid)
        if not self.running and not shared and self.admission_mode == "auto":
            need = pool.blocks_for_tokens(len(req.prompt) + 1)
            if need <= pool.available():
                self.waiting.pop(idx)
                self._qos_on_admit(req)
                req.pages = pool.alloc(need, owner=req.rid)
                if req.trace is not None:
                    self._trace_admit(req, mode="bucketed")
                logits = self.engine.prefill(req.prompt, req.pages)
                req.cursor = len(req.prompt)
                if telemetry.enabled():
                    _req_counter().labels(event="admitted", reason="").inc()
                self._emit_token(req, logits, self.clock())
                if not req.done:
                    self.running.append(req)
                self._register_committed(req)
                return 1
            # bucketed allocation doesn't fit: fall through and stream the
            # prompt page-by-page instead (the pool-constrained path)
        # streamed admission: one fresh page holds the first uncached write
        if pool.available() < 1:
            if shared:
                # admission blocked after the lookup took refs — hand them
                # back (retained, still indexed) so nothing leaks
                pool.free(shared, owner=req.rid, retain=True)
            return None
        self.waiting.pop(idx)
        self._qos_on_admit(req)
        cached = len(shared) * pool.block_size
        req.pages = list(shared) + pool.alloc(1, owner=req.rid)
        req.cursor = cached
        req.cached_tokens += cached
        # shared pages are already indexed; the chain digest resumes from
        # the last hit page's key (keys ARE the chain digests)
        req._registered_pages = len(shared)
        req._chain_digest = keys[len(shared) - 1] if shared else b""
        self.running.append(req)
        if req.trace is not None:
            self._trace_admit(req, mode="streamed", cached=cached)
        if telemetry.enabled():
            _req_counter().labels(event="admitted", reason="").inc()
        return 0

    def _trace_admit(self, req: Request, mode: str, cached: int = 0) -> None:
        """Open the prefill span; `recompute_tokens` counts the generated
        prefix folded into the prompt by preemption/evacuation — the K/V
        this prefill rebuilds rather than computes for the first time —
        and `cached_tokens` the prompt tokens served from shared prefix
        pages (never recomputed at all)."""
        req.trace.phase(
            "prefill", self.clock(), mode=mode,
            recompute_tokens=len(req.prompt) - req.prompt_len,
            cached_tokens=cached,
        )

    def _qos_on_admit(self, req: Request) -> None:
        """Dequeue accounting + brownout step-2 budget cap. The cap is an
        exact PREFIX of the uncapped greedy chain (greedy decode is
        deterministic), and recovery keeps the original budget in
        `qos_orig_max_new` so tests can pin prefix-exactness."""
        if self.qos is None:
            return
        self.qos.charge(req)
        cap = self.qos.brownout.max_new_cap(req.priority)
        if cap is not None and req.max_new_tokens > cap:
            # never cap below what a resume has already folded/generated
            # (+1 so the request still terminates on its next token)
            already = (len(req.prompt) - req.prompt_len) + len(req.generated)
            budget = max(cap, already + 1)
            if budget < req.max_new_tokens:
                if req.qos_orig_max_new is None:
                    req.qos_orig_max_new = req.max_new_tokens
                req.max_new_tokens = budget
                if req.trace is not None:
                    req.trace.event("qos_max_new_capped", self.clock(),
                                    cap=budget, orig=req.qos_orig_max_new)

    def _qos_priority_preempt(self) -> bool:
        """A blocked high-priority head may evict ONE strictly
        lower-class running request through the pool-dry preempt-resume
        machinery (the victim resumes later with the exact-output
        guarantee). Returns True when a victim was evicted — the caller
        retries admission."""
        if (self.qos is None or self.draining or not self.waiting
                or not self.running):
            return False
        head = self.waiting[self.qos.select(self.waiting)]
        return self._preempt_one(cause="priority",
                                 below_priority=head.priority)

    # ---- prefix-index registration ----
    def _kv_committed(self, req: Request) -> int:
        """Cache positions holding FINAL K/V: a streaming request has
        written [0, cursor); a generating one everything except the newest
        token (whose K/V lands when it is fed back in)."""
        if req.cursor < len(req.prompt):
            return req.cursor
        return req.context_len - 1

    def _register_committed(self, req: Request) -> None:
        """Publish the request's committed FULL pages into the prefix
        index (idempotent; shared pages are already registered). Draft
        positions are never committed, so a speculatively-written page can
        only register after its tokens are verified."""
        if not self.prefix_cache or not req.pages:
            return
        pool = self.engine.pool
        bs = pool.block_size
        full = self._kv_committed(req) // bs
        if full <= req._registered_pages:
            return
        tokens = req.prompt + req.generated
        h = req._chain_digest
        for i in range(req._registered_pages, full):
            h = chain_extend(h, tokens[i * bs:(i + 1) * bs])
            pool.register_prefix(h, req.pages[i])
        req._chain_digest = h
        req._registered_pages = full

    # ---- speculative decoding ----
    def _propose_ngram(self, req: Request, k: int) -> List[int]:
        """n-gram self-draft: the most recent earlier occurrence of the
        context's final `ngram` tokens proposes the k tokens that followed
        it. Zero extra model weights; exact greedy verify makes a bad guess
        cost only wasted FLOPs, never a wrong token."""
        n = self.spec.ngram
        seq = req.prompt + req.generated
        if k <= 0 or len(seq) <= n:
            return []
        tail = seq[-n:]
        for i in range(len(seq) - n - 1, -1, -1):
            if seq[i:i + n] == tail:
                return list(seq[i + n:i + n + k])
        return []

    def _plan_row(self, req: Request) -> Tuple[str, List[int], List[int]]:
        """One request's extend-row plan: (kind, tokens, positions).
        Streaming rows chunk up to Q prompt tokens per step (chunked
        prefill at chunk granularity); generating rows carry the committed
        last token plus up to draft_len n-gram drafts to verify."""
        Q = self.spec.draft_len + 1
        if req.cursor < len(req.prompt):
            take = min(Q, len(req.prompt) - req.cursor)
            toks = list(req.prompt[req.cursor:req.cursor + take])
            poss = list(range(req.cursor, req.cursor + take))
            return "stream", toks, poss
        ctx = req.context_len
        total_gen = (len(req.prompt) - req.prompt_len) + len(req.generated)
        rem = req.max_new_tokens - total_gen
        # a chain of d drafts can emit d+1 tokens and writes K/V through
        # position ctx-1+d — cap by the generation budget AND the table
        budget = min(self.spec.draft_len, rem - 1,
                     self.engine.max_seq_len - ctx)
        drafts = self._propose_ngram(req, budget) if budget > 0 else []
        if drafts:
            req.drafted += len(drafts)
            if telemetry.enabled():
                _spec_counter("drafted").inc(len(drafts))
        toks = [req.generated[-1]] + drafts
        poss = list(range(ctx - 1, ctx - 1 + len(toks)))
        return "draft", toks, poss

    def _spec_decode_step(self, alive: List[Request], plans: Dict) -> int:
        """One verify/extend tick: every alive row's plan runs through a
        single engine.extend() call; draft rows commit their greedy-
        verified chain (byte-identical to plain decode — each emitted token
        IS the argmax the plain path would have produced), then roll back
        surplus tail pages the rejected drafts grew."""
        pool = self.engine.pool
        Q = self.spec.draft_len + 1
        logits = self.engine.extend(
            [plans[r.rid][1] for r in alive],
            [plans[r.rid][2] for r in alive],
            [r.pages for r in alive],
            q_len=Q,
        )
        now = self.clock()
        produced = 0
        for i, r in enumerate(alive):
            kind, toks, _poss = plans[r.rid]
            if kind == "stream":
                r.cursor += len(toks)
                if r.cursor == len(r.prompt):
                    # the last prompt token's logits ARE the first
                    # generated token
                    self._emit_token(r, logits[i, len(toks) - 1], now)
                    produced += 1
                continue
            drafts = toks[1:]
            j = 0
            while j < len(toks):
                self._emit_token(r, logits[i, j], now)
                produced += 1
                if r.done:
                    break
                if j < len(drafts) and drafts[j] == r.generated[-1]:
                    # draft j matches the greedy chain: its K/V is already
                    # written and logits[i, j+1] verified it — keep reading
                    r.accepted += 1
                    if telemetry.enabled():
                        _spec_counter("accepted").inc()
                    j += 1
                else:
                    break
            if not r.done and drafts:
                # rollback: rejected drafts' stale K/V sits past seq_len
                # (masked, overwritten on commit); surplus TAIL pages the
                # draft chain grew go back to the pool now — they are
                # exclusively owned and never registered (only committed
                # full pages enter the index)
                keep = pool.blocks_for_tokens(self._tokens_needed(r))
                while len(r.pages) > keep:
                    pool.free([r.pages.pop()], owner=r.rid, retain=False)
        return produced

    def step(self) -> int:
        """One scheduler tick; returns the number of tokens produced.

        With QoS: sweep the queue-wait bound, feed measured pressure into
        the brownout ladder (transitions counted + trace-annotated), gate
        speculative decoding off at rung >= 1 (greedy verify is
        byte-identical, so this degrades only step count), and blend this
        tick's wall into `ewma_step_s` — the drain estimate the
        deadline/retry-after hints run on."""
        t_start = self.clock()
        if self.qos is not None:
            self._qos_pre_step(t_start)
        spec_saved = self.spec
        if (self.spec is not None and self.qos is not None
                and not self.qos.brownout.spec_allowed()):
            self.spec = None
        try:
            produced = self._step_inner()
        finally:
            self.spec = spec_saved
        dt = self.clock() - t_start
        if dt > 0.0:
            self.ewma_step_s = (dt if self.ewma_step_s is None
                                else 0.8 * self.ewma_step_s + 0.2 * dt)
        return produced

    def _qos_pre_step(self, now: float) -> None:
        qos = self.qos
        bound = qos.config.max_queue_wait_s
        if bound is not None:
            for req in list(self.waiting):
                if (req.submitted_time is not None
                        and now - req.submitted_time > bound):
                    self.waiting.remove(req)
                    self._shed(req, now, "queue_wait")
        pool = self.engine.pool
        pool_frac = pool.occupancy()
        if qos.config.max_waiting:
            queue_frac = len(self.waiting) / qos.config.max_waiting
        else:
            # unbounded line: scale depth against a few batches' worth so
            # sustained backlog still reads as pressure
            queue_frac = len(self.waiting) / float(4 * self.max_running)
        for direction, to_step in qos.update_pressure(now, pool_frac, queue_frac):
            if telemetry.enabled():
                _brownout_step_gauge().set(to_step)
                _brownout_transitions(direction, BROWNOUT_STEPS[to_step]).inc()
            _rt.record_event(
                "qos", "brownout", now, direction=direction, step=to_step,
                rung=BROWNOUT_STEPS[to_step],
                pressure=round(qos.last_pressure, 4),
            )
            _tl.emit("qos", "brownout",
                     severity="warn" if direction == "up" else "info",
                     direction=direction, step=to_step,
                     rung=BROWNOUT_STEPS[to_step],
                     pressure=round(qos.last_pressure, 4))

    def _step_inner(self) -> int:
        produced = 0
        # TTL sweep first: an expired request must not consume an admission
        # slot or grow pages this very tick
        self._expire_due(self.clock())
        # admission: fill free decode slots from the waiting line; a
        # blocked high-priority head may preempt a strictly lower-class
        # running victim (its pages free, admission retries)
        while True:
            emitted = self._try_admit()
            if emitted is not None:
                produced += emitted
                continue
            if not self._qos_priority_preempt():
                break

        if not self.running:
            if telemetry.enabled():
                self._sync_gauges()
            return produced

        # speculative plans first: growth must cover every position the
        # draft chain will write, not just the next token
        plans: Dict[int, Tuple[str, List[int], List[int]]] = {}
        if self.spec is not None:
            for req in self.running:
                plans[req.rid] = self._plan_row(req)

        # growth: every running sequence needs pages covering the K/V slots
        # this step writes; allocate at block boundaries, preempting until
        # the pool yields one
        pool = self.engine.pool
        for req in list(self.running):
            if req not in self.running:
                # evicted by an earlier iteration's preemption — allocating
                # into it now would leak the page at re-admission
                continue
            if self.spec is not None:
                need_tokens = plans[req.rid][2][-1] + 1
            else:
                need_tokens = self._tokens_needed(req)
            if need_tokens > self.engine.max_seq_len:
                # capacity guard (submit() bounds this; belt-and-braces)
                self._finish(req, self.clock())
                continue
            while pool.blocks_for_tokens(need_tokens) > len(req.pages):
                try:
                    req.pages.extend(pool.alloc(1, owner=req.rid))
                except PoolExhausted:
                    if req in self.running and len(self.running) == 1:
                        raise  # nothing left to evict but ourselves
                    if not self._preempt_one():
                        raise
                    if req not in self.running:
                        break  # we were the victim
            # copy-on-write guard: no position this step writes may land in
            # a page another request still reads. Full-page-aligned sharing
            # makes this structurally unreachable in steady state, but the
            # evacuate/resume and rollback races are exactly where a silent
            # scribble would corrupt a neighbor — clone instead.
            if req in self.running and req.pages:
                if self.spec is not None:
                    _, _, poss = plans[req.rid]
                    lo, hi = poss[0], poss[-1]
                else:
                    hi = self._tokens_needed(req) - 1
                    lo = hi
                for pi in range(lo // pool.block_size,
                                min(hi // pool.block_size, len(req.pages) - 1) + 1):
                    if pool.refcount(req.pages[pi]) > 1:
                        req.pages[pi] = pool.make_private(req.pages[pi], owner=req.rid)
        alive = [r for r in self.running if r.pages]

        if alive and self.spec is not None:
            produced += self._spec_decode_step(alive, plans)
            self.running = [r for r in self.running if not r.done]
        elif alive:
            rows = []
            for r in alive:
                if r.cursor < len(r.prompt):  # streaming its prompt in
                    rows.append((r, r.prompt[r.cursor], r.cursor))
                else:
                    rows.append((r, r.generated[-1], r.context_len - 1))
            logits = self.engine.decode(
                tokens=[t for _, t, _ in rows],
                positions=[p for _, _, p in rows],
                seq_lens=[p + 1 for _, _, p in rows],
                page_rows=[r.pages for r, _, _ in rows],
            )
            now = self.clock()
            for (r, _, _), lg in zip(rows, logits):
                if r.cursor < len(r.prompt):
                    r.cursor += 1
                    if r.cursor == len(r.prompt):
                        # the last prompt token's logits ARE the first
                        # generated token
                        self._emit_token(r, lg, now)
                        produced += 1
                else:
                    self._emit_token(r, lg, now)
                    produced += 1
            self.running = [r for r in self.running if not r.done]
        if self.prefix_cache:
            for r in self.running:
                self._register_committed(r)
        if telemetry.enabled():
            self._sync_gauges()
            active_tokens = sum(self._tokens_needed(r) for r in self.running)
            pool.note_fragmentation(active_tokens)
        return produced


class StaticBatchingScheduler:
    """The baseline continuous batching is measured against: requests are
    taken in arrival order in fixed groups of `batch_size`; a group decodes
    until EVERY member hits its budget (finished slots idle), and no new
    request enters until the whole group drains."""

    def __init__(self, engine, *, batch_size: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.engine = engine
        self.batch_size = int(batch_size or engine.max_batch)
        self.eos_id = eos_id
        self.clock = clock
        self.waiting: List[Request] = []
        self.running: List[Request] = []
        self.finished: List[Request] = []
        self.preempted_total = 0

    def submit(self, req: Request) -> None:
        req.submitted_time = self.clock()
        self.waiting.append(req)

    def idle(self) -> bool:
        return not self.waiting and not self.running

    def _emit(self, req: Request, logits: np.ndarray, now: float) -> None:
        token = int(np.argmax(logits))
        req.generated.append(token)
        req.token_times.append(now)
        if req.first_token_time is None:
            req.first_token_time = now

    def _done(self, req: Request) -> bool:
        return len(req.generated) >= req.max_new_tokens or (
            self.eos_id is not None and req.generated
            and req.generated[-1] == self.eos_id
        )

    def step(self) -> int:
        produced = 0
        pool = self.engine.pool
        if not self.running and self.waiting:
            group, self.waiting = self.waiting[: self.batch_size], self.waiting[self.batch_size:]
            for req in group:
                req.pages = pool.alloc(
                    pool.blocks_for_tokens(len(req.prompt) + req.max_new_tokens)
                )
                logits = self.engine.prefill(req.prompt, req.pages)
                self._emit(req, logits, self.clock())
                produced += 1
            self.running = group
        if not self.running:
            return produced
        live = [r for r in self.running if not self._done(r)]
        if live:
            logits = self.engine.decode(
                tokens=[r.generated[-1] for r in live],
                positions=[r.context_len - 1 for r in live],
                seq_lens=[r.context_len for r in live],
                page_rows=[r.pages for r in live],
            )
            now = self.clock()
            for r, lg in zip(live, logits):
                self._emit(r, lg, now)
                produced += 1
        if all(self._done(r) for r in self.running):
            now = self.clock()
            for r in self.running:
                r.finish_time = now
                pool.free(r.pages)
                r.pages = []
                self.finished.append(r)
            self.running = []
        return produced


def replay(scheduler, requests: Sequence[Request], *,
           clock: Callable[[], float] = time.monotonic,
           max_wall_s: float = 600.0) -> Dict:
    """Feed `requests` to `scheduler` honoring their arrival_time offsets
    (seconds from replay start) and run until everything drains. Returns
    aggregate serving stats: tokens/s over generated tokens + p50/p99
    TTFT/TPOT in milliseconds."""
    pending = sorted(requests, key=lambda r: (r.arrival_time, r.rid))
    t0 = clock()
    i = 0
    while i < len(pending) or not scheduler.idle():
        now = clock() - t0
        if clock() - t0 > max_wall_s:
            raise TimeoutError(f"replay exceeded {max_wall_s}s wall budget")
        while i < len(pending) and pending[i].arrival_time <= now:
            scheduler.submit(pending[i])
            i += 1
        if scheduler.idle():
            # nothing in flight: don't burn a step, wait for the next arrival
            if i < len(pending):
                time.sleep(min(0.001, max(0.0, pending[i].arrival_time - now)))
            continue
        scheduler.step()
    wall = clock() - t0

    done = list(scheduler.finished)
    # arrival_time is an offset from t0; ttft/token_times are absolute clock
    # values — normalize before differencing
    ttfts = [r.first_token_time - (t0 + r.arrival_time) for r in done
             if r.first_token_time is not None]
    # TPOT percentiles over POOLED inter-token intervals (vLLM's ITL
    # convention): a per-request-mean p99 degenerates to "worst request's
    # mean", which one OS/GC blip in a short request dominates
    tpots = [iv for r in done for iv in np.diff(r.token_times)]
    total_tokens = sum(
        (len(r.prompt) - r.prompt_len) + len(r.generated) for r in done
    )
    out = {
        "n_requests": len(done),
        "generated_tokens": int(total_tokens),
        "wall_s": round(wall, 4),
        "tokens_per_sec": round(total_tokens / wall, 2) if wall > 0 else None,
        "preempted": getattr(scheduler, "preempted_total", 0),
    }
    out.update(percentiles("ttft_ms", [t * 1000 for t in ttfts]))
    out.update(percentiles("tpot_ms", [t * 1000 for t in tpots]))
    return out


def percentiles(name: str, values: Sequence[float]) -> Dict[str, Optional[float]]:
    if not values:
        return {f"p50_{name}": None, f"p99_{name}": None}
    arr = np.asarray(values, np.float64)
    return {
        f"p50_{name}": round(float(np.percentile(arr, 50)), 3),
        f"p99_{name}": round(float(np.percentile(arr, 99)), 3),
    }
