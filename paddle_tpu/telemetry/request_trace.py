"""Request-scoped tracing & SLO attribution for the serving stack.

The serving tier (PRs 8/11/13) reports *that* p99 TTFT/TPOT moved via the
pooled histograms; this layer answers *why* for any given request. Every
sampled request carries a trace handle through its whole life — scheduler
queue, chunked-prefill streaming, decode, pool-dry preemption, fleet
evacuation, swap drain — as a chain of contiguous, cause-labeled PHASE
spans, so each request's wall time decomposes exactly into named
components:

    queue_wait | prefill | decode | preempt       (disjoint, sum == wall)
    swap_overlap                                  (overlay, informational)

Because a phase transition closes the old span and opens the new one at
the SAME timestamp, the components sum to the measured wall time by
construction — the consistency ratio is therefore a tracing-health gate
(ring eviction or a missed transition shows up as a sum shortfall), and
`tools/perf_gate.py` enforces it on bench captures.

Design (the flight-recorder shape, request-keyed):

- A bounded, thread-safe ring (`FLAGS_request_trace_ring`) of finished
  spans + point events in one global recorder. Handles only exist for
  sampled requests, so the off path costs one attribute read per site
  (`req.trace is None`); global lanes (engine dispatch, kv pool, fleet)
  check the cached `enabled()` bool like every other telemetry site.
- Sampling is DETERMINISTIC per request id (`FLAGS_request_trace_sample`
  fraction via a multiplicative hash), so a replayed trace samples the
  same requests every run.
- Exports: chrome-trace with ONE LANE PER REQUEST (merged with the
  per-rank lanes via `profiler/trace_merge.py --requests`), a JSON-lines
  event log, and `slo_breakdown()` — the TTFT/TPOT decomposition with a
  p99 blame table and SLO burn-rate that feeds `perf_report()['serving']`
  and the bench `serving`/`fleet` records (`detail.slo_breakdown`).

CLI:
    python -m paddle_tpu.telemetry.request_trace report events.jsonl \
        [--slo-ttft-ms F] [--slo-tpot-ms F] [--slo-target 0.99] [--json]
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from ..framework import flags as _flags

__all__ = [
    "RequestTrace",
    "RequestTraceRecorder",
    "enabled",
    "sampled",
    "start",
    "record_event",
    "record_span",
    "recorder",
    "set_recorder",
    "reset",
    "analyze",
    "slo_breakdown",
    "serving_section",
    "to_chrome_trace",
    "dump_json_lines",
]

_flags.define_flag(
    "FLAGS_request_trace",
    False,
    "request-scoped serving traces: sampled requests carry phase spans "
    "(queue/prefill/decode/preempt, cause-labeled) through the scheduler/"
    "engine/kv-pool/fleet path, exported as per-request chrome-trace lanes "
    "+ JSON-lines + the TTFT/TPOT slo_breakdown; off = ~zero cost (one "
    "attribute read per site)",
)
_flags.define_flag(
    "FLAGS_request_trace_sample",
    1.0,
    "fraction of requests traced when FLAGS_request_trace is on; the "
    "decision is a deterministic hash of the request id, so a replayed "
    "trace samples the same requests every run",
)
_flags.define_flag(
    "FLAGS_request_trace_ring",
    65536,
    "finished spans/events kept in the request-trace ring (oldest evicted; "
    "evictions are counted and surface as a consistency shortfall in the "
    "breakdown rather than silent truncation)",
)

# cached gate, kept in sync by the flag watcher (same discipline as
# telemetry.metrics: hot paths read one plain bool, never the flags lock)
_enabled = bool(_flags.get_flag("FLAGS_request_trace"))
_sample = float(_flags.get_flag("FLAGS_request_trace_sample"))


def _sync_enabled(_value) -> None:
    global _enabled
    _enabled = bool(_flags.get_flag("FLAGS_request_trace"))


def _sync_sample(_value) -> None:
    global _sample
    _sample = float(_flags.get_flag("FLAGS_request_trace_sample"))


_flags.watch_flag("FLAGS_request_trace", _sync_enabled)
_flags.watch_flag("FLAGS_request_trace_sample", _sync_sample)


def enabled() -> bool:
    return _enabled


def _hash01(rid: int) -> float:
    """[0, 1) deterministic per request id (Knuth multiplicative hash)."""
    return ((int(rid) * 2654435761) & 0xFFFFFFFF) / 4294967296.0


def sampled(rid: int) -> bool:
    if not _enabled:
        return False
    s = _sample
    if s >= 1.0:
        return True
    return _hash01(rid) < s


# span/phase names (the breakdown components)
PHASES = ("queue", "prefill", "decode", "preempt")
# global lanes (non-request-keyed events ride the same ring)
LANES = ("request", "engine", "kv_pool", "fleet", "qos")


class RequestTraceRecorder:
    """Bounded thread-safe ring of finished spans + point events.

    Records are plain JSON-clean dicts:
      span:  {"type": "span", "lane", "rid", "name", "t0", "t1", "attrs"}
      event: {"type": "event", "lane", "rid", "name", "t", "attrs"}
    `rid` is None on global-lane records. Timestamps are whatever clock the
    instrumented site runs on (the scheduler's injectable clock in serving);
    the chrome export maps them onto the wall clock via a (clock_ns,
    unix_ns) pair captured at the FIRST record, trace_merge-compatible.
    """

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(_flags.get_flag("FLAGS_request_trace_ring"))
        self._ring: deque = deque(maxlen=max(int(capacity), 16))
        self._lock = threading.Lock()
        self._appended = 0
        # open phase handles, for orphan detection (chaos tests + report)
        self._open: Dict[int, "RequestTrace"] = {}
        self._clock_sync: Optional[dict] = None

    # ---- append ----
    def _append(self, rec: dict, t_for_sync: float) -> None:
        with self._lock:
            if self._clock_sync is None:
                # the first record pins this recorder's clock onto the wall
                # clock (trace_merge's alignment pair); a fake test clock
                # still maps consistently, just not onto real wall time
                self._clock_sync = {
                    "perf_ns": int(t_for_sync * 1e9),
                    "unix_ns": time.time_ns(),
                }
            self._appended += 1
            self._ring.append(rec)

    def add_span(self, lane: str, name: str, t0: float, t1: float,
                 rid: Optional[int] = None, attrs: Optional[dict] = None) -> None:
        self._append(
            {"type": "span", "lane": lane, "rid": rid, "name": name,
             "t0": float(t0), "t1": float(t1), "attrs": dict(attrs or {})},
            t0,
        )

    def add_event(self, lane: str, name: str, t: float,
                  rid: Optional[int] = None, attrs: Optional[dict] = None) -> None:
        self._append(
            {"type": "event", "lane": lane, "rid": rid, "name": name,
             "t": float(t), "attrs": dict(attrs or {})},
            t,
        )

    # ---- read ----
    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    @property
    def dropped(self) -> int:
        """Records evicted from the ring (appended - retained)."""
        with self._lock:
            return self._appended - len(self._ring)

    def open_spans(self) -> List[tuple]:
        """(rid, phase) for every trace whose current phase never closed —
        must be empty once traffic drains (the no-orphaned-spans contract)."""
        with self._lock:
            return [(tr.rid, tr._phase) for tr in self._open.values()
                    if tr._phase is not None]

    def clock_sync(self) -> Optional[dict]:
        with self._lock:
            return dict(self._clock_sync) if self._clock_sync else None

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._appended = 0
            self._open.clear()
            self._clock_sync = None


class RequestTrace:
    """One sampled request's phase machine. NOT thread-safe by itself —
    exactly one scheduler/fleet owns a request at any instant (evacuation
    hands the whole object over), which is the same single-writer contract
    the Request's runtime fields already rely on."""

    __slots__ = ("rid", "_rec", "_phase", "_t0", "_attrs")

    def __init__(self, rid: int, rec: RequestTraceRecorder):
        self.rid = int(rid)
        self._rec = rec
        self._phase: Optional[str] = None
        self._t0: float = 0.0
        self._attrs: dict = {}
        with rec._lock:
            rec._open[id(self)] = self

    @property
    def phase_name(self) -> Optional[str]:
        return self._phase

    def event(self, name: str, t: float, **attrs) -> None:
        self._rec.add_event("request", name, t, rid=self.rid, attrs=attrs)

    def phase(self, name: str, t: float, **attrs) -> None:
        """Close the open phase span at `t` and open `name` at the SAME
        instant — contiguity is what makes the components sum to the wall
        time exactly."""
        if self._phase is not None:
            self._rec.add_span("request", self._phase, self._t0, t,
                               rid=self.rid, attrs=self._attrs)
        self._phase = name
        self._t0 = float(t)
        self._attrs = attrs

    def close(self, t: float, outcome: str, **attrs) -> None:
        """Terminal transition: close the open phase and record the
        `finish` event carrying the outcome. Every terminal path
        (completed/expired/cancelled) runs through here, so a drained
        system has zero open spans."""
        if self._phase is not None:
            self._rec.add_span("request", self._phase, self._t0, t,
                               rid=self.rid, attrs=self._attrs)
            self._phase = None
        attrs = dict(attrs)
        attrs["outcome"] = outcome
        self._rec.add_event("request", "finish", t, rid=self.rid, attrs=attrs)
        with self._rec._lock:
            self._rec._open.pop(id(self), None)


# ---------------------------------------------------------------------------
# module-level default recorder + instrumentation entry points
# ---------------------------------------------------------------------------

_default_recorder = RequestTraceRecorder()


def recorder() -> RequestTraceRecorder:
    return _default_recorder


def set_recorder(rec: RequestTraceRecorder) -> RequestTraceRecorder:
    global _default_recorder
    _default_recorder = rec
    return rec


def reset() -> None:
    _default_recorder.reset()


def start(rid: int, t: float, **attrs) -> Optional[RequestTrace]:
    """Sampling gate + handle creation, called once per request at submit.
    Returns None when tracing is off or the request is not sampled — every
    downstream site then costs one `req.trace is None` read."""
    if not sampled(rid):
        return None
    tr = RequestTrace(rid, _default_recorder)
    if attrs:
        tr.event("submit", t, **attrs)
    return tr


def record_event(lane: str, name: str, t: Optional[float] = None,
                 rid: Optional[int] = None, **attrs) -> None:
    """Global-lane point event (engine dispatch, kv pool, fleet routing);
    no-op unless tracing is enabled."""
    if not _enabled:
        return
    _default_recorder.add_event(
        lane, name, time.monotonic() if t is None else t, rid=rid, attrs=attrs
    )


def record_span(lane: str, name: str, t0: float, t1: float,
                rid: Optional[int] = None, **attrs) -> None:
    """Global-lane span (swap drain window); no-op unless enabled."""
    if not _enabled:
        return
    _default_recorder.add_span(lane, name, t0, t1, rid=rid, attrs=attrs)


# ---------------------------------------------------------------------------
# exports
# ---------------------------------------------------------------------------

# chrome-trace pid blocks: request lanes live far above any real rank pid
# so a merged trace can never collide lanes. The `compile` lane carries the
# compile-cache ledger's spans (round 18) so cold-start compile activity
# interleaves with the request/engine lanes in a merged trace.
REQUEST_PID_BASE = 100000
_GLOBAL_LANE_PIDS = {"engine": 90001, "kv_pool": 90002, "fleet": 90003,
                     "compile": 90004, "qos": 90005}


def to_chrome_trace(rec: Optional[RequestTraceRecorder] = None) -> dict:
    """Chrome-trace dict: one lane (pid) per request plus one lane per
    global source; `metadata.request_lanes` marks it for trace_merge's
    `--requests` path (lanes are preserved, not flattened onto a rank)."""
    rec = rec or _default_recorder
    events: List[dict] = []
    named = set()

    def _pid(r):
        if r["rid"] is not None:
            return REQUEST_PID_BASE + int(r["rid"])
        return _GLOBAL_LANE_PIDS.get(r["lane"], 90000)

    def _name_lane(pid, label):
        if pid in named:
            return
        named.add(pid)
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": label}})
        events.append({"ph": "M", "name": "process_sort_index", "pid": pid,
                       "tid": 0, "args": {"sort_index": pid}})

    for r in rec.records():
        pid = _pid(r)
        label = (f"request {r['rid']}" if r["rid"] is not None
                 else f"serving {r['lane']}")
        _name_lane(pid, label)
        args = dict(r["attrs"])
        if r["rid"] is not None:
            args["rid"] = r["rid"]
        if r["type"] == "span":
            events.append({
                "ph": "X", "name": r["name"], "cat": f"serving_{r['lane']}",
                "pid": pid, "tid": 0, "ts": r["t0"] * 1e6,
                "dur": max(0.0, (r["t1"] - r["t0"]) * 1e6), "args": args,
            })
        else:
            events.append({
                "ph": "i", "name": r["name"], "cat": f"serving_{r['lane']}",
                "pid": pid, "tid": 0, "ts": r["t"] * 1e6, "s": "p",
                "args": args,
            })
    meta = {"request_lanes": True}
    cs = rec.clock_sync()
    if cs:
        meta["clock_sync"] = cs
    return {"traceEvents": events, "metadata": meta}


def dump_chrome_trace(path: str, rec: Optional[RequestTraceRecorder] = None) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(rec), f)
    return path


def to_json_lines(rec: Optional[RequestTraceRecorder] = None) -> str:
    """One JSON object per line: every span/event in ring order, preceded
    by a header line carrying the clock-sync pair + eviction count."""
    rec = rec or _default_recorder
    header = {
        "type": "header", "version": 1, "dropped": rec.dropped,
        "clock_sync": rec.clock_sync(),
    }
    lines = [json.dumps(header, sort_keys=True)]
    lines.extend(json.dumps(r, sort_keys=True) for r in rec.records())
    return "\n".join(lines)


def dump_json_lines(path: str, rec: Optional[RequestTraceRecorder] = None) -> str:
    with open(path, "w") as f:
        f.write(to_json_lines(rec))
        f.write("\n")
    return path


def load_json_lines(path: str, with_header: bool = False):
    """Read an event log back: the span/event records, or with
    `with_header` a `(header, records)` pair (header `{}` if absent)."""
    header: dict = {}
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("type") in ("span", "event"):
                out.append(rec)
            elif rec.get("type") == "header" and not header:
                header = rec
    return (header, out) if with_header else out


# ---------------------------------------------------------------------------
# analysis: the TTFT/TPOT decomposition
# ---------------------------------------------------------------------------

def _pctl(values: Sequence[float], q: float) -> Optional[float]:
    if not values:
        return None
    vs = sorted(values)
    if len(vs) == 1:
        return vs[0]
    pos = (len(vs) - 1) * q / 100.0
    lo = int(pos)
    hi = min(lo + 1, len(vs) - 1)
    return vs[lo] + (vs[hi] - vs[lo]) * (pos - lo)


def _stats_ms(values: Sequence[float]) -> dict:
    if not values:
        return {"n": 0, "mean": None, "p50": None, "p99": None}
    return {
        "n": len(values),
        "mean": round(sum(values) / len(values) * 1000, 3),
        "p50": round(_pctl(values, 50) * 1000, 3),
        "p99": round(_pctl(values, 99) * 1000, 3),
    }


def _overlap(t0: float, t1: float, windows: Sequence[tuple]) -> float:
    total = 0.0
    for w0, w1 in windows:
        total += max(0.0, min(t1, w1) - max(t0, w0))
    return total


def analyze(records: Optional[Sequence[dict]] = None) -> dict:
    """Aggregate the ring (or a loaded event log) per request. Returns the
    raw per-request table the breakdown/report summarize:
      rid -> {start, finish, outcome, components{phase: s}, ttft_s,
              decode_start, generated, preemptions, causes{cause: n},
              swap_overlap_s}
    plus the global-lane aggregates (engine bucket events, kv pool
    occupancy, swap windows)."""
    if records is None:
        records = _default_recorder.records()
    swap_windows = [
        (r["t0"], r["t1"]) for r in records
        if r["type"] == "span" and r["lane"] == "fleet"
        and r["name"] == "swap_drain"
    ]
    per: Dict[int, dict] = {}

    def _req(rid):
        return per.setdefault(rid, {
            "rid": rid, "start": None, "finish": None, "outcome": None,
            "components": {p: 0.0 for p in PHASES}, "decode_start": None,
            "generated": None, "preemptions": 0, "causes": {},
            "swap_overlap_s": 0.0, "pages_allocated": 0, "pages_freed": 0,
            "pages_shared": 0, "routes": [], "first_span": None,
            # round 17: prefix-cache + speculative-decode attribution
            "cached_tokens": 0, "drafted": 0, "accepted": 0,
        })

    engine = {"bucket_hits": 0, "bucket_compiles": 0, "compile_s_total": 0.0}
    pool_peak_used = 0
    for r in records:
        lane = r["lane"]
        if lane == "request":
            rid = r["rid"]
            q = _req(rid)
            if r["type"] == "span":
                t0, t1 = r["t0"], r["t1"]
                name = r["name"]
                if q["start"] is None or t0 < q["start"]:
                    q["start"] = t0
                    q["first_span"] = name
                if name in q["components"]:
                    q["components"][name] += t1 - t0
                if name == "decode" and q["decode_start"] is None:
                    q["decode_start"] = t0
                cause = r["attrs"].get("cause")
                if cause:
                    q["causes"][cause] = q["causes"].get(cause, 0) + 1
                q["swap_overlap_s"] += _overlap(t0, t1, swap_windows)
            elif r["name"] == "finish":
                q["finish"] = r["t"]
                q["outcome"] = r["attrs"].get("outcome")
                if r["attrs"].get("generated") is not None:
                    q["generated"] = r["attrs"]["generated"]
                if r["attrs"].get("preemptions") is not None:
                    q["preemptions"] = r["attrs"]["preemptions"]
                # round 17: where the TTFT/TPOT wins came from — prompt
                # tokens served from shared prefix pages, and draft tokens
                # proposed/verified by speculative decoding
                for fld in ("cached_tokens", "drafted", "accepted"):
                    if r["attrs"].get(fld) is not None:
                        q[fld] = r["attrs"][fld]
            elif r["name"] == "route":
                q["routes"].append({
                    "replica": r["attrs"].get("replica"),
                    "reason": r["attrs"].get("reason"),
                })
        elif lane == "engine" and r["type"] == "event":
            ev = r["attrs"].get("event")
            if ev == "hit":
                engine["bucket_hits"] += 1
            elif ev == "compile":
                engine["bucket_compiles"] += 1
                engine["compile_s_total"] += float(r["attrs"].get("dur_s") or 0.0)
        elif lane == "kv_pool" and r["type"] == "event":
            used = r["attrs"].get("used")
            if used is not None:
                pool_peak_used = max(pool_peak_used, int(used))
            rid = r["rid"]
            if rid is not None:
                q = _req(rid)
                n = int(r["attrs"].get("n") or 0)
                if r["name"] == "alloc":
                    q["pages_allocated"] += n
                elif r["name"] == "free":
                    q["pages_freed"] += n
                elif r["name"] == "share":
                    q["pages_shared"] += n
    for q in per.values():
        if q["start"] is not None and q["decode_start"] is not None:
            q["ttft_s"] = q["decode_start"] - q["start"]
        else:
            q["ttft_s"] = None
        if q["start"] is not None and q["finish"] is not None:
            q["wall_s"] = q["finish"] - q["start"]
        else:
            q["wall_s"] = None
        # every traced lifecycle opens with a queue span; anything else as
        # the earliest retained span means the ring evicted the head of
        # this request's trace — its wall_s and component sums SHRINK
        # TOGETHER, so consistency alone cannot see the loss
        q["truncated"] = (q["first_span"] is not None
                          and q["first_span"] != "queue")
    return {
        "requests": per,
        "engine": engine,
        "kv_pool": {"peak_used_pages": pool_peak_used},
        "swap_windows": swap_windows,
    }


def slo_breakdown(
    records: Optional[Sequence[dict]] = None,
    *,
    slo_ttft_ms: Optional[float] = None,
    slo_tpot_ms: Optional[float] = None,
    slo_target: float = 0.99,
    rec: Optional[RequestTraceRecorder] = None,
) -> dict:
    """The decomposition record: per-component TTFT/TPOT attribution with
    a p99 blame table, trace-health consistency, and (with SLO targets)
    the burn rate. This is what `perf_report()['serving']` and the bench
    `detail.slo_breakdown` carry, and what perf_gate gates."""
    rec = rec or _default_recorder
    if records is None:
        records = rec.records()
    a = analyze(records)
    done = [q for q in a["requests"].values()
            if q["wall_s"] is not None and q["wall_s"] > 0]
    n = len(done)
    out = {
        "n_traced": n,
        # from the live recorder; the CLI overrides both when summarizing a
        # loaded log (the log's header carries its own eviction count)
        "open_spans": len(rec.open_spans()),
        "dropped_records": rec.dropped,
        # requests whose leading spans the ring evicted: their consistency
        # ratio still reads ~1.0 (wall shrinks with the lost spans), so the
        # count is the honest eviction signal perf_gate fails on
        "truncated_requests": sum(
            1 for q in a["requests"].values() if q["truncated"]),
        "engine": a["engine"],
        "kv_pool": a["kv_pool"],
        "swap_windows": len(a["swap_windows"]),
    }
    if not n:
        out["consistency"] = None
        return out

    # consistency: component sum / measured wall, per request — contiguous
    # phases make this ≈1.0 exactly; a shortfall means evicted/missed spans
    ratios = [sum(q["components"].values()) / q["wall_s"] for q in done]
    out["consistency"] = {
        "mean": round(sum(ratios) / n, 4),
        "min": round(min(ratios), 4),
        "max_abs_err_frac": round(max(abs(r - 1.0) for r in ratios), 4),
    }

    ttfts = [q["ttft_s"] for q in done if q["ttft_s"] is not None]
    walls = [q["wall_s"] for q in done]
    out["ttft_ms"] = _stats_ms(ttfts)
    out["e2e_ms"] = _stats_ms(walls)
    # traced TPOT: decode-phase wall over the decode interval count
    tpots = []
    for q in done:
        if q["decode_start"] is not None and q["generated"] and q["generated"] > 1:
            tpots.append((q["finish"] - q["decode_start"]) / (q["generated"] - 1))
    out["tpot_ms"] = _stats_ms(tpots)

    # per-component totals: TTFT side = everything before decode starts
    # (queue + prefill + preempt-before-first-token approximated by all
    # preempt time for requests still prefilling); e2e side = everything
    comp_e2e = {p: [q["components"][p] for q in done] for p in PHASES}
    ttft_side = ("queue", "prefill", "preempt")
    comp_ttft: Dict[str, List[float]] = {p: [] for p in ttft_side}
    for q in done:
        if q["ttft_s"] is None:
            continue
        for p in ttft_side:
            comp_ttft[p].append(q["components"][p])
    rename = {"queue": "queue_wait"}
    out["components_mean_ms"] = {
        rename.get(p, p): round(sum(v) / len(v) * 1000, 3) if v else 0.0
        for p, v in comp_e2e.items()
    }
    out["components_mean_ms"]["swap_overlap"] = round(
        sum(q["swap_overlap_s"] for q in done) / n * 1000, 3
    )
    out["ttft_p99_components_ms"] = {
        rename.get(p, p): round((_pctl(v, 99) or 0.0) * 1000, 3)
        for p, v in comp_ttft.items()
    }
    out["e2e_p99_components_ms"] = {
        rename.get(p, p): round((_pctl(v, 99) or 0.0) * 1000, 3)
        for p, v in comp_e2e.items()
    }
    # blame table: components ranked by their share of the p99-tail TTFT —
    # "what should I fix to move p99" in one read
    p99_ttft = _pctl(ttfts, 99) if ttfts else None
    blame = []
    if p99_ttft:
        tail = [q for q in done
                if q["ttft_s"] is not None and q["ttft_s"] >= p99_ttft * 0.999]
        for p in ttft_side:
            tot = sum(q["components"][p] for q in tail)
            tail_ttft = sum(q["ttft_s"] for q in tail)
            blame.append({
                "component": rename.get(p, p),
                "p99_ms": out["ttft_p99_components_ms"][rename.get(p, p)],
                "share_of_p99_ttft": round(tot / tail_ttft, 4) if tail_ttft else 0.0,
            })
        blame.sort(key=lambda b: -b["share_of_p99_ttft"])
    out["ttft_p99_blame"] = blame

    causes: Dict[str, int] = {}
    outcomes: Dict[str, int] = {}
    for q in done:
        for c, k in q["causes"].items():
            causes[c] = causes.get(c, 0) + k
        if q["outcome"]:
            outcomes[q["outcome"]] = outcomes.get(q["outcome"], 0) + 1
    out["causes"] = causes
    out["outcomes"] = outcomes
    out["preemptions"] = sum(q["preemptions"] for q in done)
    out["pages_allocated"] = sum(q["pages_allocated"] for q in done)
    out["pages_shared"] = sum(q["pages_shared"] for q in done)
    # round 17: attribution for WHERE TTFT/TPOT wins come from — prefix
    # reuse (prompt tokens never recomputed) and speculative decoding
    # (tokens committed per verify step beyond the baseline one)
    out["cached_tokens"] = sum(q["cached_tokens"] for q in done)
    out["prefix_hit_requests"] = sum(1 for q in done if q["cached_tokens"])
    drafted = sum(q["drafted"] for q in done)
    accepted = sum(q["accepted"] for q in done)
    out["spec"] = {
        "drafted_tokens": drafted,
        "accepted_tokens": accepted,
        "accept_rate": round(accepted / drafted, 4) if drafted else None,
    }

    if slo_ttft_ms is not None or slo_tpot_ms is not None:
        budget = max(1e-9, 1.0 - float(slo_target))
        slo: dict = {"target": float(slo_target)}
        if slo_ttft_ms is not None and ttfts:
            viol = sum(1 for t in ttfts if t * 1000 > slo_ttft_ms)
            slo.update(ttft_target_ms=float(slo_ttft_ms), ttft_violations=viol,
                       ttft_burn_rate=round((viol / len(ttfts)) / budget, 3))
        if slo_tpot_ms is not None and tpots:
            viol = sum(1 for t in tpots if t * 1000 > slo_tpot_ms)
            slo.update(tpot_target_ms=float(slo_tpot_ms), tpot_violations=viol,
                       tpot_burn_rate=round((viol / len(tpots)) / budget, 3))
        out["slo"] = slo
    return out


def serving_section() -> dict:
    """`perf_report()['serving']`: the live recorder's decomposition, or an
    explicit unavailable marker when nothing was traced."""
    rec = _default_recorder
    if not any(r["lane"] == "request" for r in rec.records()):
        return {
            "available": False,
            "reason": ("no traced requests (FLAGS_request_trace off, "
                       "sampling excluded everything, or no serving traffic)"),
        }
    bd = slo_breakdown(rec=rec)
    bd["available"] = True
    return bd


# ---------------------------------------------------------------------------
# CLI: python -m paddle_tpu.telemetry.request_trace report events.jsonl
# ---------------------------------------------------------------------------

def _format_report(bd: dict) -> str:
    lines = []
    lines.append(
        f"request trace report: {bd['n_traced']} traced request(s), "
        f"{bd.get('dropped_records', 0)} ring-evicted record(s), "
        f"{bd.get('truncated_requests', 0)} truncated trace(s), "
        f"{bd.get('open_spans') or 0} orphaned open span(s)"
    )
    cons = bd.get("consistency")
    if cons:
        flag = "" if cons["max_abs_err_frac"] <= 0.05 else "  ** INCONSISTENT **"
        lines.append(
            f"consistency (component-sum / wall): mean {cons['mean']:.4f}, "
            f"min {cons['min']:.4f}, max err {cons['max_abs_err_frac']:.2%}{flag}"
        )
    if not bd["n_traced"]:
        return "\n".join(lines)
    for label, key in (("TTFT", "ttft_ms"), ("E2E", "e2e_ms"), ("TPOT", "tpot_ms")):
        s = bd.get(key) or {}
        if s.get("n"):
            lines.append(
                f"{label}: p50 {s['p50']:.2f} ms  p99 {s['p99']:.2f} ms  "
                f"mean {s['mean']:.2f} ms  (n={s['n']})"
            )
    lines.append("p99 TTFT blame table (share of the tail request's TTFT):")
    lines.append(f"  {'component':<12} {'p99 ms':>10} {'share':>8}")
    for b in bd.get("ttft_p99_blame", []):
        lines.append(
            f"  {b['component']:<12} {b['p99_ms']:>10.2f} "
            f"{b['share_of_p99_ttft']:>8.1%}"
        )
    mean = bd.get("components_mean_ms") or {}
    lines.append(
        "mean components (ms): "
        + ", ".join(f"{k}={v:.2f}" for k, v in mean.items())
    )
    if bd.get("causes"):
        lines.append(
            "preempt causes: "
            + ", ".join(f"{k}={v}" for k, v in sorted(bd["causes"].items()))
        )
    if bd.get("outcomes"):
        lines.append(
            "outcomes: "
            + ", ".join(f"{k}={v}" for k, v in sorted(bd["outcomes"].items()))
        )
    if bd.get("cached_tokens"):
        lines.append(
            f"prefix cache: {bd['cached_tokens']} prompt token(s) served from "
            f"shared pages across {bd.get('prefix_hit_requests', 0)} request(s), "
            f"{bd.get('pages_shared', 0)} page share(s)"
        )
    spec = bd.get("spec") or {}
    if spec.get("drafted_tokens"):
        lines.append(
            f"speculative decode: {spec['drafted_tokens']} drafted, "
            f"{spec['accepted_tokens']} accepted "
            f"(accept rate {spec['accept_rate']:.1%})"
        )
    slo = bd.get("slo")
    if slo:
        parts = [f"target {slo['target']:.2%}"]
        if "ttft_burn_rate" in slo:
            parts.append(
                f"TTFT<{slo['ttft_target_ms']:.0f}ms: "
                f"{slo['ttft_violations']} violation(s), "
                f"burn rate {slo['ttft_burn_rate']:.2f}x"
            )
        if "tpot_burn_rate" in slo:
            parts.append(
                f"TPOT<{slo['tpot_target_ms']:.0f}ms: "
                f"{slo['tpot_violations']} violation(s), "
                f"burn rate {slo['tpot_burn_rate']:.2f}x"
            )
        lines.append("SLO: " + "; ".join(parts))
    eng = bd.get("engine") or {}
    if eng.get("bucket_hits") or eng.get("bucket_compiles"):
        lines.append(
            f"engine buckets: {eng['bucket_hits']} hit(s), "
            f"{eng['bucket_compiles']} compile(s) "
            f"({eng['compile_s_total']:.3f} s compiling)"
        )
    kv = bd.get("kv_pool") or {}
    if kv.get("peak_used_pages"):
        lines.append(f"kv pool: peak {kv['peak_used_pages']} page(s) in use, "
                     f"{bd.get('pages_allocated', 0)} page-alloc(s) attributed")
    if bd.get("swap_windows"):
        lines.append(f"swap drain windows: {bd['swap_windows']}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.telemetry.request_trace",
        description="decompose a request-trace event log into TTFT/TPOT "
                    "components with a p99 blame table and SLO burn rate",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("report", help="summarize a JSON-lines event log")
    rp.add_argument("events", help="events.jsonl written by dump_json_lines()")
    rp.add_argument("--slo-ttft-ms", type=float, default=None)
    rp.add_argument("--slo-tpot-ms", type=float, default=None)
    rp.add_argument("--slo-target", type=float, default=0.99,
                    help="SLO attainment target for the burn rate (default 0.99)")
    rp.add_argument("--json", action="store_true",
                    help="emit the breakdown as JSON instead of the table")
    args = p.parse_args(argv)
    header, records = load_json_lines(args.events, with_header=True)
    bd = slo_breakdown(
        records,
        slo_ttft_ms=args.slo_ttft_ms,
        slo_tpot_ms=args.slo_tpot_ms,
        slo_target=args.slo_target,
    )
    # the live recorder's state is irrelevant to a loaded log: orphans are
    # request lanes with no terminal event, evictions come from the header
    finished = {r["rid"] for r in records
                if r["type"] == "event" and r["name"] == "finish"}
    traced = {r["rid"] for r in records
              if r["lane"] == "request" and r["rid"] is not None}
    bd["open_spans"] = len(traced - finished)
    bd["dropped_records"] = header.get("dropped", 0)
    if args.json:
        print(json.dumps(bd, sort_keys=True, indent=1))
    else:
        print(_format_report(bd))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
