"""Static-graph Executor: whole-program jit replay.

Reference parity: python/paddle/base/executor.py:1158 `Executor.run(program,
feed, fetch_list)` + the C++ StandaloneExecutor/PirInterpreter
(paddle/fluid/framework/new_executor/pir_interpreter.h:32). TPU-native: the
instruction list replays inside ONE `jax.jit` — dependency analysis,
multi-stream scheduling, fusion, and memory planning are all XLA's job, which
is precisely the CinnJitInstruction end-state the reference was converging
toward. Gradients (append_backward) ride `jax.value_and_grad` over the same
replay; optimizer updates are extra pure instructions whose results are
written back to the persistable tensors after each run.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .program import Program, default_main_program


class _OptUpdate:
    """One parameter's pure update: (new_param, new_accums) =
    update_fn(param, grad, lr, *accums). `clip` (shared per minimize call)
    applies global-norm scaling across the group before updates; `wd` is the
    coupled L2 decay folded into the gradient (decoupled decay lives inside
    the update fn, see optimizer_hooks)."""

    __slots__ = ("param_var", "grad_var", "update_fn", "accum_tensors", "lr", "clip", "wd")

    def __init__(self, param_var, grad_var, update_fn, accum_tensors, lr, clip=None, wd=0.0):
        self.param_var = param_var
        self.grad_var = grad_var
        self.update_fn = update_fn
        self.accum_tensors = accum_tensors  # persistable state (momentum etc.)
        self.lr = lr
        self.clip = clip
        self.wd = wd


def append_backward(loss: Tensor, parameter_list=None, no_grad_set=None):
    """paddle.static.append_backward parity (python/paddle/base/backward.py):
    registers grad computation for every trainable parameter the program
    read; returns [(param, grad_placeholder)] — grads are fetchable."""
    prog = default_main_program()
    loss_var = prog._id2var.get(id(loss))
    if loss_var is None:
        raise ValueError("loss is not an output of the current default_main_program")
    from ..nn.layer import Parameter

    if parameter_list is None:
        params = [
            prog._var_tensors[v]
            for v in prog.param_vars
            if isinstance(prog._var_tensors.get(v), Parameter) and not prog._var_tensors[v].stop_gradient
        ]
    else:
        params = list(parameter_list)
    pairs = []
    param_vars, grad_vars = [], []
    for p in params:
        pv = prog.var_of(p)
        g = Tensor(jnp.zeros_like(p._value), stop_gradient=True, name=(p.name or f"v{pv}") + "@GRAD")
        gv = prog._new_var(g)
        param_vars.append(pv)
        grad_vars.append(gv)
        pairs.append((p, g))
    prog.grad_requests.append((loss_var, param_vars, grad_vars))
    prog._compiled.clear()
    return pairs


class Executor:
    """paddle.static.Executor parity."""

    def __init__(self, place=None):
        self.place = place

    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, np.ndarray]] = None,
        fetch_list: Optional[Sequence] = None,
        return_numpy: bool = True,
        **kwargs,
    ):
        # loaded inference program (static.load_inference_model)
        from .io import _InferenceProgram

        if isinstance(program, _InferenceProgram):
            return program._run(feed or {}, return_numpy)
        from .extras import CompiledProgram

        if isinstance(program, CompiledProgram):
            program = program._program
        program = program if program is not None else default_main_program()
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        fetch_vars = []
        for f in fetch_list:
            if isinstance(f, Tensor):
                vid = program._id2var.get(id(f))
                if vid is None:
                    raise ValueError(f"fetch target {f.name or f} is not in this program")
                fetch_vars.append(vid)
            elif isinstance(f, str):  # fetch by feed/var name
                if f in program.feed_vars:
                    fetch_vars.append(program.feed_vars[f])
                else:
                    named = [v for v, t in program._var_tensors.items() if t.name == f]
                    if not named:
                        raise ValueError(f"no variable named {f!r} in program")
                    fetch_vars.append(named[-1])
            else:
                raise TypeError(f"fetch_list entries must be Tensor or str, got {type(f)}")

        compiled = self._compile(program, tuple(sorted(feed)), tuple(fetch_vars))

        feed_arrays = [jnp.asarray(feed[n]) for n in sorted(feed)]
        param_arrays = [program._var_tensors[v]._value for v in program.param_vars]
        accum_arrays = [
            [a._value for a in upd.accum_tensors] for upd in program.opt_updates
        ]
        lr_arrays = [jnp.asarray(upd.lr() if callable(upd.lr) else upd.lr, jnp.float32) for upd in program.opt_updates]
        fetches, updated, new_accums = compiled(feed_arrays, param_arrays, accum_arrays, lr_arrays)

        # write back persistables (optimizer-touched params + accumulators)
        pos_of = {v: i for i, v in enumerate(program.param_vars)}
        updated_positions = sorted({pos_of[u.param_var] for u in program.opt_updates})
        for i, new in zip(updated_positions, updated):
            program._var_tensors[program.param_vars[i]]._replace_value(new)
        for upd, accs in zip(program.opt_updates, new_accums):
            for t, new in zip(upd.accum_tensors, accs):
                t._replace_value(new)

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    # ---- compilation ----
    @staticmethod
    def _program_structure_key(program: Program):
        """Structural identity of the instruction list. Every OpInstr carries
        a process-global monotonic serial (program.py `_op_serial`) that is
        never reused, so an op REPLACED in-place (same op count — which a
        length-based key can't see) gets a fresh serial and therefore a new
        key; the stale compiled callable is evicted instead of silently
        replayed. Deliberately O(#ops) per run: detecting an in-place
        `program.ops[i] = ...` edit requires looking at the list — a cached
        key invalidated only at record_op/append_backward would miss exactly
        that mutation — and run() is already O(#params + #ops) in its
        feed/param marshalling, so one flat int tuple adds no new asymptote."""
        ops_key = tuple(op.seq for op in program.ops)
        grads_key = tuple(
            (loss, tuple(pvs), tuple(gvs)) for loss, pvs, gvs in program.grad_requests
        )
        opts_key = tuple((u.param_var, u.grad_var) for u in program.opt_updates)
        return (ops_key, grads_key, opts_key)

    def _compile(self, program: Program, feed_names, fetch_vars):
        from .. import telemetry as _tm

        telemetry_on = _tm.enabled()
        structure = self._program_structure_key(program)
        key = (feed_names, fetch_vars, structure)
        hit = program._compiled.get(key)
        if telemetry_on:
            _tm.counter(
                "paddle_tpu_executor_compile_cache_total",
                "static Executor compiled-program cache lookups", ("result",),
            ).labels(result="hit" if hit is not None else "miss").inc()
        if hit is not None:
            return hit
        # evict entries for the same (feed, fetch) signature whose program
        # structure went stale — they can never hit again
        stale = [k for k in program._compiled if k[0] == feed_names and k[1] == fetch_vars]
        for k in stale:
            del program._compiled[k]
        if stale and telemetry_on:
            _tm.counter(
                "paddle_tpu_executor_compile_cache_evictions_total",
                "stale compiled-program cache entries dropped on recompile",
            ).inc(len(stale))

        feed_var_ids = [program.feed_vars[n] for n in feed_names]
        grad_requests = list(program.grad_requests)
        opt_updates = list(program.opt_updates)

        def forward_env(feed_arrays, param_arrays):
            return program.replay_env(dict(zip(feed_var_ids, feed_arrays)), param_arrays)

        pos_of_param = {v: i for i, v in enumerate(program.param_vars)}
        updated_positions = sorted({pos_of_param[u.param_var] for u in opt_updates})

        def replay(feed_arrays, param_arrays, accum_arrays, lr_arrays):
            env = None
            grad_vals = {}
            # one grad pass PER request (losses must not contaminate each
            # other), differentiating only wrt that request's parameters
            for loss_var, pvars, gvars in grad_requests:
                sel = [pos_of_param[pv] for pv in pvars]

                def loss_fn(sel_arrays, _lv=loss_var, _sel=sel):
                    full = list(param_arrays)
                    for i, a in zip(_sel, sel_arrays):
                        full[i] = a
                    e = forward_env(feed_arrays, full)
                    return jnp.sum(e[_lv]), e

                (_, env), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    [param_arrays[i] for i in sel]
                )
                for gv, g in zip(gvars, grads):
                    grad_vals[gv] = g
            if env is None:
                env = forward_env(feed_arrays, param_arrays)
            env.update(grad_vals)

            new_params = list(param_arrays)
            # coupled L2 decay folds into the gradient; global-norm clip
            # scales each minimize-call's gradient group jointly (parity with
            # the eager step(): clip -> decay -> update)
            eff_grads = []
            for upd in opt_updates:
                g = env.get(upd.grad_var)
                if g is None:
                    raise RuntimeError("optimizer update without computed gradient")
                eff_grads.append(g)
            from ..nn.clip import ClipGradByGlobalNorm

            clip_groups = {}
            for i, upd in enumerate(opt_updates):
                if isinstance(upd.clip, ClipGradByGlobalNorm):
                    clip_groups.setdefault(id(upd.clip), (upd.clip, []))[1].append(i)
            for clip, idxs in clip_groups.values():
                gn = jnp.sqrt(sum(jnp.sum(jnp.square(eff_grads[i].astype(jnp.float32))) for i in idxs))
                scale = jnp.minimum(1.0, clip.clip_norm / jnp.maximum(gn, 1e-12))
                for i in idxs:
                    eff_grads[i] = (eff_grads[i].astype(jnp.float32) * scale).astype(eff_grads[i].dtype)
            new_accums = []
            for upd, accs, lr, g in zip(opt_updates, accum_arrays, lr_arrays, eff_grads):
                i = pos_of_param[upd.param_var]
                if upd.wd:
                    g = g + jnp.asarray(upd.wd, g.dtype) * new_params[i].astype(g.dtype)
                res = upd.update_fn(new_params[i], g, lr, *accs)
                new_p, new_a = res[0], list(res[1:])
                new_params[i] = new_p
                new_accums.append(new_a)
            fetches = [env[v] for v in fetch_vars]
            # only parameters an optimizer touched leave the jit — frozen
            # weights must not round-trip through outputs every run
            updated = [new_params[i] for i in updated_positions]
            return fetches, updated, new_accums

        compiled = jax.jit(replay)
        if telemetry_on:
            compiled = self._timed_first_call(compiled)
        program._compiled[key] = compiled
        return compiled

    @staticmethod
    def _timed_first_call(compiled):
        """Observe trace+XLA-compile wall time: jax.jit is lazy, so the real
        compile cost lands on the first invocation — time that one."""
        import threading
        import time

        done = [False]
        done_lock = threading.Lock()

        def wrapper(*args, **kwargs):
            if done[0]:
                return compiled(*args, **kwargs)
            t0 = time.perf_counter()
            out = compiled(*args, **kwargs)
            dt = time.perf_counter() - t0
            with done_lock:
                first, done[0] = not done[0], True
            from .. import telemetry as _tm

            # re-check the gate at observe time: telemetry may have been
            # disabled between _compile and the first run, and the disabled
            # contract is "record nothing"
            if first and _tm.enabled():
                _tm.histogram(
                    "paddle_tpu_executor_compile_seconds",
                    "wall time of a static Executor program's first "
                    "(tracing + XLA compile) run",
                ).observe(dt)
            return out

        return wrapper


def global_scope():
    """Minimal Scope analog (paddle.static.global_scope)."""

    class _Scope:
        def find_var(self, name):
            prog = default_main_program()
            for t in prog._var_tensors.values():
                if t.name == name:
                    return t
            return None

    return _Scope()


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        return self.scope

    def __exit__(self, *exc):
        return False
