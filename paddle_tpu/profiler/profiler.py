"""Profiler orchestration over host events + XLA's xplane device tracer.

Reference parity: python/paddle/profiler/profiler.py — `Profiler` (:346) with
the CLOSED/READY/RECORD(_AND_RETURN) state machine (:79), `make_scheduler`,
`export_chrome_tracing` callbacks, `profiler.step()` driving state
transitions. TPU-native: the device tracer is jax.profiler (XLA xplane dumps,
viewable in TensorBoard/XProf) instead of CUPTI; host spans are recorded by
utils.RecordEvent and exported as chrome://tracing JSON.
"""
from __future__ import annotations

import json
import os
import socket
import time
from enum import Enum
from typing import Callable, Iterable, Optional, Union

from .utils import TracerEventType, _disable_host_tracer, _enable_host_tracer, RecordEvent
from .profiler_statistic import StatisticData, SortedKeys, _build_summary_table


class SummaryView(Enum):
    """Summary view selector (reference profiler.py:46); accepted by
    Profiler.summary(views=...) to filter which tables print."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3  # last step of a record window: collect + callback


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1  # accepted for API compat; maps to the accelerator target
    TPU = 2
    CUSTOM_DEVICE = 3


def make_scheduler(
    *, closed: int, ready: int, record: int, repeat: int = 0, skip_first: int = 0
) -> Callable[[int], ProfilerState]:
    """python/paddle/profiler/profiler.py make_scheduler parity: cycle of
    [closed, ready, record] phases, repeated `repeat` times (0 = forever),
    after skipping `skip_first` steps."""
    if record < 1:
        raise ValueError(f"record must be >= 1, got {record}")
    if closed < 0 or ready < 0 or skip_first < 0 or repeat < 0:
        raise ValueError("closed/ready/skip_first/repeat must be non-negative")
    num_cycle = closed + ready + record

    def getter(step: int) -> ProfilerState:
        assert step >= 0
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        period_index = step // num_cycle
        if repeat > 0 and period_index >= repeat:
            return ProfilerState.CLOSED
        pos = step % num_cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos < num_cycle - 1:
            return ProfilerState.RECORD
        return ProfilerState.RECORD_AND_RETURN

    return getter


def _default_state_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None) -> Callable:
    """on_trace_ready callback writing chrome://tracing JSON per record window."""

    def handle_fn(prof: "Profiler"):
        nonlocal worker_name
        if not worker_name:
            worker_name = f"host_{socket.gethostname()}pid_{os.getpid()}"
        os.makedirs(dir_name, exist_ok=True)
        filename = f"{worker_name}_time_{time.strftime('%Y_%m_%d_%H_%M_%S')}.paddle_trace.json"
        prof.export(os.path.join(dir_name, filename), format="json")

    return handle_fn


def export_protobuf(dir_name: str, worker_name: Optional[str] = None) -> Callable:
    """Reference exports a protobuf; the xplane .pb from jax.profiler plays
    that role (written to <dir>/plugins/profile by the device tracer). The
    host events are still dumped as JSON next to it."""
    return export_chrome_tracing(dir_name, worker_name)


def _has_tpu() -> bool:
    import jax

    try:
        return jax.devices()[0].platform != "cpu"
    except Exception:
        return False


class Profiler:
    """paddle.profiler.Profiler parity (profiler.py:346).

    with Profiler(targets=[ProfilerTarget.CPU, ProfilerTarget.TPU],
                  scheduler=(2, 5)) as p:
        for it in loop:
            train_step()
            p.step()
    """

    def __init__(
        self,
        *,
        targets: Optional[Iterable[ProfilerTarget]] = None,
        scheduler: Union[Callable[[int], ProfilerState], tuple, None] = None,
        on_trace_ready: Optional[Callable] = None,
        record_shapes: bool = False,
        profile_memory: bool = False,
        timer_only: bool = False,
        emit_nvtx: bool = False,  # API compat; no NVTX on TPU
        custom_device_types: list = [],
        with_flops: bool = False,
    ):
        if targets is None:
            targets = [ProfilerTarget.CPU]
            if _has_tpu():
                targets.append(ProfilerTarget.TPU)
        self.targets = list(targets)
        self._device_tracing = any(
            t in (ProfilerTarget.TPU, ProfilerTarget.GPU, ProfilerTarget.CUSTOM_DEVICE) for t in self.targets
        )
        if scheduler is None:
            self._scheduler = _default_state_scheduler
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            start = max(start, 0)
            if end <= start:
                raise ValueError(f"scheduler window ({start}, {end}) records no steps")
            self._scheduler = make_scheduler(closed=max(start - 1, 0), ready=min(start, 1), record=end - start, repeat=1)
        else:
            self._scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.record_shapes = record_shapes
        self.profile_memory = profile_memory
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self.profiler_result = None
        self._trace_dir = None
        self._device_trace_active = False
        self._step_record: Optional[RecordEvent] = None
        self._timer = None

    # ---- lifecycle ----
    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def start(self):
        from . import timer as timer_mod

        self._timer = timer_mod.benchmark()
        self._timer.begin()
        if self.timer_only:
            return
        self.current_state = self._scheduler(self.step_num)
        if self.current_state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._start_tracers()
        self._step_record = RecordEvent(f"ProfileStep#{self.step_num}", TracerEventType.ProfileStep)
        self._step_record.begin()

    def stop(self):
        if self._timer is not None:
            self._timer.end()
        if self.timer_only:
            return
        if self._step_record is not None:
            self._step_record.end()
            self._step_record = None
        if self.current_state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._collect()
            if self.on_trace_ready:
                self.on_trace_ready(self)
        self.current_state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None):
        if self._timer is not None:
            self._timer.step(num_samples)
        if self.timer_only:
            return
        if self._step_record is not None:
            self._step_record.end()
        prev = self.current_state
        self.step_num += 1
        self.current_state = self._scheduler(self.step_num)
        self._transition(prev, self.current_state)
        self._step_record = RecordEvent(f"ProfileStep#{self.step_num}", TracerEventType.ProfileStep)
        self._step_record.begin()

    def step_info(self, unit=None):
        if self._timer is None:
            return ""
        return self._timer.step_info(unit)

    # ---- state transitions ----
    def _transition(self, prev: ProfilerState, new: ProfilerState):
        recording = prev in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        will_record = new in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if prev == ProfilerState.RECORD_AND_RETURN:
            # window closes at the step boundary: collect + fire callback
            self._collect()
            if self.on_trace_ready:
                self.on_trace_ready(self)
            recording = False
        if will_record and not recording:
            self._start_tracers()
        elif recording and not will_record:
            self._collect()
            if self.on_trace_ready:
                self.on_trace_ready(self)

    def _start_tracers(self):
        _enable_host_tracer()
        if self._device_tracing and not self._device_trace_active:
            import jax

            self._trace_dir = self._trace_dir or os.path.join(
                os.environ.get("PADDLE_TPU_PROFILE_DIR", "/tmp/paddle_tpu_profile"),
                time.strftime("%Y%m%d_%H%M%S"),
            )
            try:
                jax.profiler.start_trace(self._trace_dir)
                self._device_trace_active = True
            except Exception:
                self._device_trace_active = False  # tracer busy / unsupported

    def _collect(self):
        events = _disable_host_tracer()
        if self._device_trace_active:
            import jax

            try:
                jax.profiler.stop_trace()
            finally:
                self._device_trace_active = False
        # snapshot the live-HBM census at window close so MemoryView reports
        # the memory state of the steps just profiled
        try:
            from . import perf_attribution as _pa

            census = _pa.live_array_census(set_gauges=False)
        except Exception:
            census = None
        self.profiler_result = StatisticData(
            events, device_trace_dir=self._trace_dir, memory_census=census
        )

    # ---- reporting ----
    def export(self, path: str, format: str = "json"):
        if self.profiler_result is None:
            return
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.profiler_result.to_chrome_trace(), f)

    def summary(self, sorted_by=SortedKeys.CPUTotal, op_detail=True, thread_sep=False, time_unit="ms", views=None):
        """Print summary tables (reference profiler.py:849). ``views``
        filters which tables print (SummaryView or list of them); this
        tracer produces the operator/kernel table, so any selection that
        includes OperatorView/KernelView/OverView prints it."""
        if self.profiler_result is None:
            return
        from .profiler_statistic import _build_distributed_table, _build_memory_table

        if views is not None and isinstance(views, SummaryView):
            views = [views]
        op_wanted = views is None or bool(
            {SummaryView.OperatorView, SummaryView.KernelView, SummaryView.OverView}.intersection(views)
        )
        dist_wanted = views is None or SummaryView.DistributedView in views
        mem_wanted = views is None or SummaryView.MemoryView in views
        if op_wanted:
            print(_build_summary_table(self.profiler_result, sorted_by=sorted_by, time_unit=time_unit))
        if dist_wanted:
            dist = _build_distributed_table(self.profiler_result, time_unit=time_unit)
            if dist:
                print(dist)
        if mem_wanted and getattr(self.profiler_result, "memory_census", None):
            from . import perf_attribution as _pa

            mem = _build_memory_table(
                self.profiler_result.memory_census, watermark=_pa.watermark()
            )
            if mem:
                print(mem)


def load_profiler_result(filename: str):
    with open(filename) as f:
        return json.load(f)
