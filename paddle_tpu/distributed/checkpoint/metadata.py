"""Checkpoint metadata structures.

Reference parity: python/paddle/distributed/checkpoint/metadata.py —
LocalTensorMetadata/LocalTensorIndex + a global Metadata map describing, for
every saved tensor, which file holds which slice of the global shape. The
re-sharding load path (load_state_dict.py) intersects saved slices with the
slices the target placement needs.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple


@dataclass
class LocalTensorMetadata:
    """One saved shard: where it sits in the global tensor."""

    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str
    file_name: str


@dataclass
class TensorMetadata:
    global_shape: Tuple[int, ...]
    dtype: str
    shards: List[LocalTensorMetadata] = field(default_factory=list)
    # the tensor's PartitionSpec AT SAVE TIME, serialized to plain tuples by
    # spec_layout.spec_to_meta (None for unsharded/single-device tensors).
    # Purely descriptive for the reshard-on-load path — the loader targets
    # the DESTINATION placement and only needs the shard offsets above —
    # but it lets tools and the reshard telemetry tell a topology change
    # from a same-layout reload. getattr(..., "partition_spec", None) for
    # pre-portability pickles.
    partition_spec: Tuple = None


@dataclass
class Metadata:
    state_dict_metadata: Dict[str, TensorMetadata] = field(default_factory=dict)
    flat_mapping: Dict[str, str] = field(default_factory=dict)  # structured name aliases
    # shard file -> CRC32 of its bytes, recorded at save time BEFORE the
    # shard hits disk; load verifies these to detect torn/corrupt steps.
    # (default_factory keeps pickles from the pre-checksum format loadable —
    # readers must getattr(..., "file_checksums", {}).)
    file_checksums: Dict[str, int] = field(default_factory=dict)
    # the SAVING mesh, serialized by spec_layout.mesh_to_meta:
    # {"axes": [(name, size), ...], "n_devices": N}. None on pre-portability
    # checkpoints and pure host-tensor saves. Loaders compare it against the
    # current global mesh to count reshard-on-load events.
    mesh: Dict = None


def slices_overlap(off_a, shape_a, off_b, shape_b):
    """Do two hyper-rectangles intersect? Used by the re-sharding loader."""
    for oa, sa, ob, sb in zip(off_a, shape_a, off_b, shape_b):
        if oa + sa <= ob or ob + sb <= oa:
            return False
    return True


def intersection(off_a, shape_a, off_b, shape_b):
    """Intersection rectangle in global coords: (offset, shape)."""
    off = tuple(max(oa, ob) for oa, ob in zip(off_a, off_b))
    end = tuple(min(oa + sa, ob + sb) for oa, sa, ob, sb in zip(off_a, shape_a, off_b, shape_b))
    return off, tuple(e - o for o, e in zip(off, end))
