"""Fleet hybrid-parallel tests on the 8-device CPU mesh.

Reference parity: test/collective/fleet/ (hybrid_parallel_mp_layers.py,
hybrid_parallel_pp_layer.py, test_fleet_base.py...) — TP/SP/PP numerics are
checked against dense single-device equivalents, the reference's own test
strategy (TestDistBase compares dist loss vs single-proc loss).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
from paddle_tpu.distributed import fleet


@pytest.fixture(scope="module", autouse=True)
def _init():
    dist.init_parallel_env()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)


def test_topology():
    topo = fleet.CommunicateTopology(["data", "pipe", "model"], [2, 2, 2])
    assert topo.world_size() == 8
    assert topo.get_rank(data=1, pipe=0, model=1) == 5
    assert topo.get_coord(5) == (1, 0, 1)
    assert topo.get_comm_group("model", 0) == [0, 1]
    assert topo.get_axis_list("data", 0) == [0, 1, 2, 3]
    comm = topo.get_comm_list("pipe")
    assert [0, 2] in comm


def test_hcg():
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_pipe_parallel_world_size() == 2
    assert hcg.get_parallel_mode() == "hybrid"
    assert dict(hcg.mesh.shape)["mp"] == 2
    pm = hcg.process_mesh
    assert pm.get_dim_size("dp") == 2


def test_distributed_strategy():
    s = fleet.DistributedStrategy()
    s.amp = True
    s.amp_configs = {"init_loss_scaling": 1024.0}
    assert s.amp_configs["init_loss_scaling"] == 1024.0
    assert s.amp_configs["incr_ratio"] == 2.0  # defaults survive merge
    s.hybrid_configs = {"mp_degree": 4}
    assert s.hybrid_configs["mp_degree"] == 4
    assert s.hybrid_configs["dp_degree"] == -1  # infer-from-world default


def test_column_row_parallel_matches_dense():
    """col(gather_output=False) -> row(input_is_parallel) == dense 2-layer."""
    paddle.seed(42)
    col = fleet.ColumnParallelLinear(8, 16, gather_output=False)
    row = fleet.RowParallelLinear(16, 8, input_is_parallel=True)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    out = row(col(x))
    # dense reference with the same weights
    ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) @ row.weight.numpy() + row.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_vocab_parallel_embedding():
    paddle.seed(1)
    emb = fleet.VocabParallelEmbedding(32, 8)
    ids = paddle.to_tensor(np.random.RandomState(1).randint(0, 32, (4, 6)))
    out = emb(ids)
    np.testing.assert_allclose(out.numpy(), emb.weight.numpy()[ids.numpy()], rtol=1e-6)
    # vocab dim physically sharded over mp
    from jax.sharding import PartitionSpec as P

    assert emb.weight._raw().sharding.spec == P("mp", None)


def test_tp_grads_match_dense():
    paddle.seed(7)
    col = fleet.ColumnParallelLinear(6, 8, gather_output=False)
    row = fleet.RowParallelLinear(8, 6, input_is_parallel=True)
    x = paddle.to_tensor(np.random.RandomState(2).randn(4, 6).astype(np.float32))
    loss = row(col(x)).mean()
    loss.backward()

    wc, bc = col.weight.numpy(), col.bias.numpy()
    wr, br = row.weight.numpy(), row.bias.numpy()
    dense_c, dense_r = nn.Linear(6, 8), nn.Linear(8, 6)
    dense_c.weight.set_value(wc), dense_c.bias.set_value(bc)
    dense_r.weight.set_value(wr), dense_r.bias.set_value(br)
    loss2 = dense_r(dense_c(x)).mean()
    loss2.backward()
    np.testing.assert_allclose(col.weight.grad.numpy(), dense_c.weight.grad.numpy(), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(row.weight.grad.numpy(), dense_r.weight.grad.numpy(), rtol=1e-4, atol=1e-6)


def test_parallel_cross_entropy():
    pce = fleet.ParallelCrossEntropy()
    logits = paddle.to_tensor(np.random.RandomState(3).randn(4, 32).astype(np.float32))
    labels = paddle.to_tensor(np.random.RandomState(4).randint(0, 32, (4,)))
    loss = pce(logits, labels)
    from paddle_tpu.nn import functional as F

    ref = F.cross_entropy(logits, labels, reduction="none")
    np.testing.assert_allclose(loss.numpy(), ref.numpy(), rtol=1e-5)


def test_sequence_parallel_linears():
    from paddle_tpu.distributed.fleet.utils import sequence_parallel_utils as spu

    paddle.seed(11)
    col = spu.ColumnSequenceParallelLinear(8, 16, gather_output=False)
    row = spu.RowSequenceParallelLinear(16, 8, input_is_parallel=True)
    # [s, b, h] with seq sharded over mp between blocks
    x = paddle.to_tensor(np.random.RandomState(5).randn(8, 2, 8).astype(np.float32))
    xs = spu.ScatterOp.apply(x)
    out = row(col(xs))
    ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) @ row.weight.numpy() + row.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)
    g = spu.GatherOp.apply(out)
    np.testing.assert_allclose(g.numpy(), out.numpy(), rtol=1e-6)


def test_rng_tracker():
    from paddle_tpu.distributed.fleet.meta_parallel import get_rng_state_tracker

    tracker = get_rng_state_tracker()
    tracker.reset()
    tracker.add("model_parallel_rng", 123)
    with tracker.rng_state("model_parallel_rng"):
        a = paddle.rand([4])
    with pytest.raises(ValueError):
        tracker.add("model_parallel_rng", 99)
    with pytest.raises(ValueError):
        with tracker.rng_state("nope"):
            pass
    assert a.shape == [4]


def test_recompute_grads_match():
    from paddle_tpu.distributed.fleet import recompute

    paddle.seed(0)
    block = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 8))
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    x.stop_gradient = False

    loss1 = block(x).mean()
    loss1.backward()
    g1 = block[0].weight.grad.numpy().copy()
    xg1 = x.grad.numpy().copy()
    block.clear_gradients()
    x.grad = None

    recompute(block, x)  # discovery probe
    block.clear_gradients()
    x.grad = None
    loss2 = recompute(block, x).mean()  # checkpointed path
    loss2.backward()
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-6)
    np.testing.assert_allclose(g1, block[0].weight.grad.numpy(), rtol=1e-5)
    np.testing.assert_allclose(xg1, x.grad.numpy(), rtol=1e-5)


def test_recompute_sequential_all_grads_flow():
    """Regression: chunk lambdas must not alias in the discovery cache —
    every chunk's params get grads (id-reuse bug)."""
    from paddle_tpu.distributed.fleet import recompute_sequential

    paddle.seed(5)
    seq = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 8))
    x = paddle.to_tensor(np.random.RandomState(4).randn(4, 8).astype(np.float32))
    for _ in range(2):  # second pass uses cached chunk discovery
        seq.clear_gradients()
        loss = recompute_sequential({"segments": 2}, seq, x).mean()
        loss.backward()
        for i in (0, 2, 4):
            assert seq[i].weight.grad is not None, f"layer {i} grad missing"
            assert float(np.abs(seq[i].weight.grad.numpy()).sum()) > 0


def test_segment_layers_never_empty():
    from paddle_tpu.distributed.fleet.meta_parallel import SegmentLayers

    class _D:
        pass

    descs = [nn.Linear(2, 2), nn.Linear(2, 2), nn.Linear(64, 64)]
    seg = SegmentLayers(descs, num_parts=3, method="parameter")
    b = seg.do_segment()
    assert all(b[i + 1] > b[i] for i in range(3)), b


def test_train_batch_validates_micro_batch_contract():
    from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer, PipelineParallel

    hcg = fleet.get_hybrid_communicate_group()
    strategy = fleet.DistributedStrategy()
    strategy.pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 3}
    pipe = PipelineLayer(
        layers=[LayerDesc(nn.Linear, 4, 4), LayerDesc(nn.Linear, 4, 4)],
        num_stages=2, loss_fn=nn.MSELoss(),
    )
    engine = PipelineParallel(pipe, hcg, strategy)
    opt = paddle.optimizer.SGD(0.1, parameters=pipe.parameters())
    xs = paddle.to_tensor(np.zeros((8, 4), np.float32))
    with pytest.raises(ValueError):
        engine.train_batch((xs, xs), opt)


def test_pipeline_stage_world_mismatch_raises():
    from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer, PipelineParallel

    hcg = fleet.get_hybrid_communicate_group()  # pp degree 2
    pipe = PipelineLayer(
        layers=[LayerDesc(nn.Linear, 4, 4)], num_stages=1, loss_fn=nn.MSELoss()
    )
    with pytest.raises(ValueError, match="pp degree"):
        PipelineParallel(pipe, hcg, fleet.DistributedStrategy())


def test_pipeline_layer_segmentation():
    from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer

    layers = [LayerDesc(nn.Linear, 8, 8) for _ in range(6)]
    pipe = PipelineLayer(layers=layers, num_stages=2)
    assert pipe.segment_parts == [0, 3, 6]
    assert pipe.get_stage_from_index(0) == 0
    assert pipe.get_stage_from_index(4) == 1
    x = paddle.to_tensor(np.random.RandomState(6).randn(2, 8).astype(np.float32))
    out = pipe(x)
    assert out.shape == [2, 8]


def test_shared_layer_desc_ties_weights():
    from paddle_tpu.distributed.fleet import PipelineLayer, SharedLayerDesc

    descs = [
        SharedLayerDesc("emb", nn.Linear, None, "weight", 4, 4),
        nn.ReLU(),
        SharedLayerDesc("emb", nn.Linear, None, "weight", 4, 4),
    ]
    pipe = PipelineLayer(layers=descs, num_stages=1)
    assert pipe.run_function[0] is pipe.run_function[2]


def test_pipeline_parallel_train_batch():
    """train_batch (micro-batch accumulation) == single-batch step numerics."""
    from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer, PipelineParallel

    paddle.seed(3)
    hcg = fleet.get_hybrid_communicate_group()
    strategy = fleet.DistributedStrategy()
    strategy.pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 2}

    def build():
        paddle.seed(3)
        return PipelineLayer(
            layers=[LayerDesc(nn.Linear, 8, 8), LayerDesc(nn.ReLU), LayerDesc(nn.Linear, 8, 4)],
            num_stages=2,
            loss_fn=nn.MSELoss(),
        )

    pipe = build()
    engine = PipelineParallel(pipe, hcg, strategy)
    opt = paddle.optimizer.SGD(0.1, parameters=pipe.parameters())
    xs = np.random.RandomState(7).randn(8, 8).astype(np.float32)
    ys = np.random.RandomState(8).randn(8, 4).astype(np.float32)
    loss = engine.train_batch((paddle.to_tensor(xs), paddle.to_tensor(ys)), opt)

    ref = build()
    opt2 = paddle.optimizer.SGD(0.1, parameters=ref.parameters())
    out = ref(paddle.to_tensor(xs))
    ref_loss = nn.MSELoss()(out, paddle.to_tensor(ys))
    ref_loss.backward()
    opt2.step()
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    w_pipe = pipe.run_function[0].weight.numpy()
    w_ref = ref.run_function[0].weight.numpy()
    np.testing.assert_allclose(w_pipe, w_ref, rtol=1e-4, atol=1e-6)


def test_spmd_pipeline_matches_sequential():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from paddle_tpu.distributed.fleet.meta_parallel import pipeline_spmd, stack_stage_params

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("pp",))
    S, M, D = 8, 16, 4
    rng = np.random.RandomState(0)
    Ws = [rng.randn(D, D).astype(np.float32) * 0.3 for _ in range(S)]
    params = stack_stage_params([{"w": jnp.asarray(w)} for w in Ws], mesh)
    mbs = jnp.asarray(rng.randn(M, 2, D).astype(np.float32))
    run = pipeline_spmd(lambda p, x: jnp.tanh(x @ p["w"]), mesh)
    out = jax.jit(run)(params, mbs)
    ref = np.asarray(mbs)
    for w in Ws:
        ref = np.tanh(ref @ w)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)
    grads = jax.grad(lambda p, m: run(p, m).sum())(params, mbs)
    assert grads["w"].shape == (S, D, D)


def test_fleet_distributed_model_and_optimizer():
    model = nn.Linear(4, 4)
    m = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(paddle.optimizer.AdamW(0.001, parameters=model.parameters()))
    x = paddle.to_tensor(np.random.RandomState(9).randn(4, 4).astype(np.float32))
    loss = m(x).mean()
    loss.backward()
    opt.step()
    assert fleet.worker_num() >= 1
    assert fleet.is_first_worker()


def test_pipeline_uniform_spmd_path_matches_single_device():
    """Uniform stages: compiled SPMD schedule engages; stage params are
    placed on their pp rank; loss + updated weights == single device
    (reference test_dist_base.py:959 criterion)."""
    from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer, PipelineParallel

    hcg = fleet.get_hybrid_communicate_group()
    strategy = fleet.DistributedStrategy()
    strategy.pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 2}

    def build():
        paddle.seed(11)
        return PipelineLayer(
            layers=[LayerDesc(nn.Linear, 8, 8), LayerDesc(nn.Tanh),
                    LayerDesc(nn.Linear, 8, 8), LayerDesc(nn.Tanh)],
            num_stages=2,
            loss_fn=nn.MSELoss(),
        )

    pipe = build()
    engine = PipelineParallel(pipe, hcg, strategy)
    assert engine._spmd, "uniform stages must take the compiled SPMD schedule"
    # placement: the two stages' params live on different pp devices
    d0 = next(iter(pipe.stage_module(0).state_dict().values()))._value.devices()
    d1 = next(iter(pipe.stage_module(1).state_dict().values()))._value.devices()
    assert d0 != d1, (d0, d1)

    opt = paddle.optimizer.SGD(0.1, parameters=pipe.parameters())
    xs = np.random.RandomState(0).randn(8, 8).astype(np.float32)
    ys = np.random.RandomState(1).randn(8, 8).astype(np.float32)
    loss = engine.train_batch((paddle.to_tensor(xs), paddle.to_tensor(ys)), opt)

    ref = build()
    ropt = paddle.optimizer.SGD(0.1, parameters=ref.parameters())
    rloss = nn.MSELoss()(ref(paddle.to_tensor(xs)), paddle.to_tensor(ys))
    rloss.backward()
    ropt.step()
    np.testing.assert_allclose(float(loss), float(rloss), rtol=1e-5)
    for k in range(2):
        for (n1, t1), (n2, t2) in zip(
            sorted(pipe.stage_module(k).state_dict().items()),
            sorted(ref.stage_module(k).state_dict().items()),
        ):
            np.testing.assert_allclose(t1.numpy(), t2.numpy(), rtol=1e-4, atol=1e-6, err_msg=n1)


def test_pipeline_interleave_vpp_matches_single_device():
    """VPP: 4 uniform chunks round-robin on 2 pp ranks (circular schedule)."""
    from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "pp_degree": 2}
    strategy.pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 2}
    fleet.init(is_collective=True, strategy=strategy)

    def build(v):
        paddle.seed(12)
        return PipelineLayer(
            layers=[LayerDesc(nn.Linear, 6, 6), LayerDesc(nn.Tanh),
                    LayerDesc(nn.Linear, 6, 6), LayerDesc(nn.Tanh),
                    LayerDesc(nn.Linear, 6, 6), LayerDesc(nn.Tanh),
                    LayerDesc(nn.Linear, 6, 6), LayerDesc(nn.Tanh)],
            num_stages=2,
            loss_fn=nn.MSELoss(),
            num_virtual_pipeline_stages=v,
        )

    try:
        pipe = build(2)
        engine = fleet.distributed_model(pipe)
        from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
            PipelineParallelWithInterleave,
        )

        assert isinstance(engine, PipelineParallelWithInterleave)
        assert engine._spmd
        # round-robin placement: chunks 0,2 on rank 0; chunks 1,3 on rank 1
        devs = [next(iter(pipe.stage_module(k).state_dict().values()))._value.devices()
                for k in range(4)]
        assert devs[0] == devs[2] and devs[1] == devs[3] and devs[0] != devs[1]

        opt = paddle.optimizer.SGD(0.1, parameters=pipe.parameters())
        xs = np.random.RandomState(2).randn(8, 6).astype(np.float32)
        ys = np.random.RandomState(3).randn(8, 6).astype(np.float32)
        loss = engine.train_batch((paddle.to_tensor(xs), paddle.to_tensor(ys)), opt)

        ref = build(1)  # single chunk stream, same layer stack
        ropt = paddle.optimizer.SGD(0.1, parameters=ref.parameters())
        rloss = nn.MSELoss()(ref(paddle.to_tensor(xs)), paddle.to_tensor(ys))
        rloss.backward()
        ropt.step()
        np.testing.assert_allclose(float(loss), float(rloss), rtol=1e-5)
        # updated weights must match layer-by-layer — a transposed grad-row
        # mapping (row = c*pp+d vs d*v+c) would scramble chunk updates
        for i in (0, 2, 4, 6):
            np.testing.assert_allclose(
                pipe.run_function[i].weight.numpy(),
                ref.run_function[i].weight.numpy(),
                rtol=1e-4, atol=1e-6, err_msg=f"layer {i} weight",
            )
    finally:
        # restore module-level topology for later tests
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)


def test_pipeline_nonuniform_places_stages():
    """Non-uniform stages: r4 — they now take the COMPILED hetero schedule
    (flat-padded superstructure + lax.switch), params still placed per pp
    rank, numerics still == single device."""
    from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer, PipelineParallel

    hcg = fleet.get_hybrid_communicate_group()
    strategy = fleet.DistributedStrategy()
    strategy.pipeline_configs = {"accumulate_steps": 2, "micro_batch_size": 3}

    def build():
        paddle.seed(13)
        return PipelineLayer(
            layers=[LayerDesc(nn.Linear, 5, 16), LayerDesc(nn.GELU), LayerDesc(nn.Linear, 16, 2)],
            num_stages=2,
            loss_fn=nn.MSELoss(),
        )

    pipe = build()
    engine = PipelineParallel(pipe, hcg, strategy)
    assert engine._spmd and engine._spmd_hetero
    d0 = pipe.run_function[0].weight._value.devices()
    d1 = pipe.run_function[2].weight._value.devices()
    assert d0 != d1

    opt = paddle.optimizer.AdamW(0.01, parameters=pipe.parameters())
    xs = np.random.RandomState(4).randn(6, 5).astype(np.float32)
    ys = np.random.RandomState(5).randn(6, 2).astype(np.float32)
    loss = engine.train_batch((paddle.to_tensor(xs), paddle.to_tensor(ys)), opt)

    ref = build()
    ropt = paddle.optimizer.AdamW(0.01, parameters=ref.parameters())
    rloss = nn.MSELoss()(ref(paddle.to_tensor(xs)), paddle.to_tensor(ys))
    rloss.backward()
    ropt.step()
    np.testing.assert_allclose(float(loss), float(rloss), rtol=1e-5)
    np.testing.assert_allclose(
        pipe.run_function[2].weight.numpy(), ref.run_function[2].weight.numpy(),
        rtol=1e-4, atol=1e-6,
    )


def test_uniform_stages_rejects_differing_activations():
    """Same param shapes but different param-free layers must NOT take the
    stacked SPMD path (would silently run chunk 0's functions everywhere)."""
    from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer

    pipe = PipelineLayer(
        layers=[LayerDesc(nn.Linear, 8, 8), LayerDesc(nn.Tanh),
                LayerDesc(nn.Linear, 8, 8), LayerDesc(nn.Sigmoid)],
        num_stages=2, loss_fn=nn.MSELoss(),
    )
    assert not pipe.uniform_stages()
    pipe2 = PipelineLayer(
        layers=[LayerDesc(nn.Linear, 8, 8), LayerDesc(nn.Dropout, 0.1),
                LayerDesc(nn.Linear, 8, 8), LayerDesc(nn.Dropout, 0.5)],
        num_stages=2, loss_fn=nn.MSELoss(),
    )
    assert not pipe2.uniform_stages()
