"""paddle.quantization namespace.

Reference parity: python/paddle/quantization/ — QuantConfig (per-layer /
per-type quanter wiring), QAT (quantize-aware training via fake quant with
straight-through gradients), PTQ (observer insertion + convert). TPU-native:
fake quant is the STE identity trick `x + stop_gradient(q(x) - x)` (works
under jax AD and jit); int8 simulation stays in the bf16/f32 compute graph,
which is how XLA consumes quantization anyway (scale annotations, not int
kernels, on current TPU gens).
"""
from .config import QuantConfig  # noqa: F401
from .observers import (  # noqa: F401
    AbsmaxObserver,
    AVGObserver,
    BaseObserver,
    absmax_scale,
    dequantize_absmax,
    quantize_absmax,
    running_absmax,
    running_avg,
)
from .ptq import PTQ  # noqa: F401
from .qat import QAT  # noqa: F401
from .quanters import (  # noqa: F401
    BaseQuanter,
    FakeQuanterWithAbsMaxObserver,
    QuanterFactory,
    quanter,
)

__all__ = [
    "QuantConfig",
    "QAT",
    "PTQ",
    "BaseQuanter",
    "BaseObserver",
    "quanter",
    "FakeQuanterWithAbsMaxObserver",
    "AbsmaxObserver",
    "AVGObserver",
    "absmax_scale",
    "running_absmax",
    "running_avg",
    "quantize_absmax",
    "dequantize_absmax",
]
