"""Multi-tenant QoS & overload protection for the serving tier.

The scheduler/fleet admit whatever fits; this module decides WHAT should
fit when offered load exceeds capacity. Three mechanisms, all reversible:

- **Per-tenant token buckets + weighted-fair dequeue.** Every request
  carries a ``tenant`` and an integer ``priority`` (0 = highest class).
  A tenant's bucket refills at ``rate_tokens_per_s`` (token debt = prompt
  + generation budget, the work a request actually costs the pool); an
  empty bucket sheds the request with a ``retry_after_s`` hint instead of
  letting one tenant queue out everyone else. Dequeue order is strict
  priority, then deficit-round-robin over normalized token debt: the
  tenant that has consumed the least service per unit weight goes next,
  and a tenant idle for a while re-enters at the current debt floor so
  idle time never banks burst credit.

- **Bounded queues with explicit backpressure.** The waiting line takes a
  size bound (overflow sheds the lowest eligible class — the new request
  only wins a slot by strictly outranking a queued victim), a queue-wait
  bound, and deadline-aware admission: a request whose TTL is provably
  unreachable at the measured per-step latency (EWMA, the same estimate
  the fleet router drains by) is shed at submit, when retrying elsewhere
  is still cheap. Every shed is a counted, terminal, retryable outcome
  (``outcome="shed"``), never silent queue growth.

- **A reversible brownout ladder.** Driven by measured pressure (pool
  occupancy, queue depth, and externally-fed SLO burn), the ladder
  degrades chosen work one rung at a time and un-winds the same way:

  ====  ==================  ==============================================
  step  name                effect
  ====  ==================  ==============================================
  0     normal              nothing degraded
  1     spec_off            speculative decoding disabled (greedy verify
                            emits the same bytes, so outputs are
                            IDENTICAL — only the step count changes)
  2     max_new_capped      low-priority admissions get their generation
                            budget capped (an exact PREFIX of the
                            uncapped greedy chain)
  3     shed_low            new lowest-class submissions are shed
  ====  ==================  ==============================================

  Escalation is immediate (one rung per pressure reading at/above the
  enter threshold); recovery requires the pressure to sit at/below the
  exit threshold AND a cooldown to pass (hysteresis — a ladder that
  flaps between rungs every tick degrades everyone a little instead of
  someone predictably). Each transition is telemetry-counted and
  trace-annotated in the ``qos`` lane.

One ``QoSPolicy`` instance is shared across a fleet's replicas: buckets
and DRR debt are fleet-wide (a tenant cannot dodge its quota by spraying
replicas), and the brownout ladder is global — the hottest replica's
pressure escalates it, recovery waits for the cooldown.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "TenantConfig",
    "BrownoutConfig",
    "QoSConfig",
    "TokenBucket",
    "BrownoutController",
    "QoSPolicy",
    "jain_fairness",
    "tenant_report",
]

# shed reasons — the `reason` label values on
# paddle_tpu_serving_requests_total{event="shed"} (plus the two submit
# validation rejections, which count event="rejected")
SHED_REASONS = (
    "rate_limit",        # tenant token bucket empty
    "queue_full",        # waiting line at its size bound
    "queue_wait",        # sat queued past max_queue_wait_s
    "deadline_unmeetable",  # TTL provably unreachable at measured drain
    "brownout",          # ladder step 3: lowest class refused
)
REJECT_REASONS = ("context_overflow", "pool_capacity")

BROWNOUT_STEPS = ("normal", "spec_off", "max_new_capped", "shed_low")


@dataclass
class TenantConfig:
    """One tenant's share and quota. ``weight`` scales the fair-share
    dequeue (2.0 drains twice the token debt of 1.0 under contention);
    ``rate_tokens_per_s`` bounds sustained admission in token-debt units
    (prompt + max_new per request), ``burst_tokens`` the bucket depth
    (default: one second of rate, floored at one max-size request's
    worth is the caller's job to choose)."""

    weight: float = 1.0
    rate_tokens_per_s: Optional[float] = None
    burst_tokens: Optional[float] = None

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError("TenantConfig.weight must be > 0")
        if self.rate_tokens_per_s is not None and self.rate_tokens_per_s <= 0:
            raise ValueError("TenantConfig.rate_tokens_per_s must be > 0")


@dataclass
class BrownoutConfig:
    """Ladder thresholds. Hysteresis: ``enter_pressure`` must exceed
    ``exit_pressure`` or the ladder would flap on a flat signal."""

    enter_pressure: float = 0.85
    exit_pressure: float = 0.60
    cooldown_s: float = 0.5
    # step 2: generation budget cap applied to low-priority admissions
    capped_max_new: int = 8
    # priority >= this is the "low class" steps 2/3 act on
    low_priority: int = 2
    # pressure FLOOR applied while the fleet reports itself degraded (a
    # tiered fleet off its disaggregated rung is running double duty on
    # half the chips — the ladder should lean pessimistic before queues
    # actually back up). 0.0 = off (default: degradation alone never
    # escalates the ladder).
    degraded_pressure_floor: float = 0.0

    def __post_init__(self):
        if not (0.0 < self.exit_pressure < self.enter_pressure <= 1.0):
            raise ValueError(
                "BrownoutConfig requires 0 < exit_pressure < enter_pressure <= 1"
            )
        if self.capped_max_new < 1:
            raise ValueError("BrownoutConfig.capped_max_new must be >= 1")
        if not (0.0 <= self.degraded_pressure_floor <= 1.0):
            raise ValueError(
                "BrownoutConfig.degraded_pressure_floor must be in [0, 1]"
            )


@dataclass
class QoSConfig:
    """Policy knobs. Everything defaults OFF (unbounded, unlimited) so a
    scheduler constructed without explicit QoS behaves exactly as before."""

    tenants: Dict[str, TenantConfig] = field(default_factory=dict)
    default_tenant: TenantConfig = field(default_factory=TenantConfig)
    # waiting-line size bound (per scheduler) and held-line bound (fleet)
    max_waiting: Optional[int] = None
    max_queue_wait_s: Optional[float] = None
    # shed at submit when the TTL is provably unreachable at the measured
    # per-step latency
    deadline_shed: bool = True
    brownout: BrownoutConfig = field(default_factory=BrownoutConfig)

    def tenant(self, name: str) -> TenantConfig:
        return self.tenants.get(name, self.default_tenant)


class TokenBucket:
    """Deterministic token bucket (caller supplies ``now``; shares the
    scheduler's injectable clock so admission is replay-testable)."""

    __slots__ = ("rate", "burst", "tokens", "_t")

    def __init__(self, rate: float, burst: float, now: float = 0.0):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._t = float(now)

    def refill(self, now: float) -> None:
        if now > self._t:
            self.tokens = min(self.burst, self.tokens + (now - self._t) * self.rate)
        self._t = now

    def try_take(self, n: float, now: float) -> bool:
        self.refill(now)
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def retry_after(self, n: float) -> float:
        """Seconds until `n` tokens will be available (0 when they are)."""
        deficit = min(n, self.burst) - self.tokens
        return max(0.0, deficit / self.rate)


class BrownoutController:
    """The ladder state machine. ``update()`` moves at most one rung per
    pressure reading; escalation is immediate, recovery waits out the
    cooldown below the exit threshold (hysteresis)."""

    def __init__(self, cfg: Optional[BrownoutConfig] = None):
        self.cfg = cfg or BrownoutConfig()
        self.step = 0
        self.transitions = 0
        self._last_change: Optional[float] = None

    @property
    def step_name(self) -> str:
        return BROWNOUT_STEPS[self.step]

    def update(self, pressure: float, now: float) -> List[Tuple[str, int]]:
        """Returns the transition taken (at most one) as
        ``[(direction, new_step)]`` — empty when the rung holds."""
        cfg = self.cfg
        if pressure >= cfg.enter_pressure and self.step < len(BROWNOUT_STEPS) - 1:
            self.step += 1
            self.transitions += 1
            self._last_change = now
            return [("escalate", self.step)]
        if (
            pressure <= cfg.exit_pressure
            and self.step > 0
            and (self._last_change is None
                 or now - self._last_change >= cfg.cooldown_s)
        ):
            self.step -= 1
            self.transitions += 1
            self._last_change = now
            return [("recover", self.step)]
        return []

    # ---- effect queries (what the current rung degrades) ----
    def spec_allowed(self) -> bool:
        return self.step < 1

    def max_new_cap(self, priority: int) -> Optional[int]:
        if self.step >= 2 and priority >= self.cfg.low_priority:
            return self.cfg.capped_max_new
        return None

    def sheds(self, priority: int) -> bool:
        return self.step >= 3 and priority >= self.cfg.low_priority


class QoSPolicy:
    """Shared admission/fairness/brownout state. The scheduler owns the
    queues and the metrics; this object owns the DECISIONS — which
    request dequeues next, whether a submit is over quota, who the
    queue-full victim is, and what the current brownout rung degrades."""

    def __init__(self, config: Optional[QoSConfig] = None):
        self.config = config or QoSConfig()
        self.brownout = BrownoutController(self.config.brownout)
        self._buckets: Dict[str, TokenBucket] = {}
        # normalized token debt per tenant (service consumed / weight) —
        # the DRR virtual time fair dequeue runs on
        self._debt: Dict[str, float] = {}
        self.shed_counts: Dict[str, int] = {}
        # externally-fed SLO burn (fraction of requests blowing budget);
        # slo_breakdown() is too heavy to recompute per tick, so the
        # fleet/bench feed it at their own cadence
        self._slo_burn = 0.0
        # externally-fed fleet degradation flag (a tiered fleet off its
        # disaggregated rung sets this); floors pressure at
        # brownout.degraded_pressure_floor while held
        self.degraded = False
        self.last_pressure = 0.0

    # ---- token-debt accounting ----
    @staticmethod
    def cost(req) -> float:
        """A request's token debt: prompt positions it writes + tokens it
        may generate (prompt_len folds resumes in, so a preemption resume
        is never double-charged for its recompute)."""
        return float(req.prompt_len + req.max_new_tokens)

    def rate_gate(self, req, now: float) -> Tuple[bool, Optional[float]]:
        """(admit?, retry_after_s). Unlimited tenants always pass."""
        cfg = self.config.tenant(req.tenant)
        if cfg.rate_tokens_per_s is None:
            return True, None
        bucket = self._buckets.get(req.tenant)
        if bucket is None:
            burst = (cfg.burst_tokens if cfg.burst_tokens is not None
                     else cfg.rate_tokens_per_s)
            bucket = self._buckets[req.tenant] = TokenBucket(
                cfg.rate_tokens_per_s, burst, now
            )
        # Clamp to the burst: a single request larger than the bucket would
        # otherwise be permanently inadmissible.  The bucket bounds the
        # sustained rate; one oversized request just drains it to empty.
        n = min(self.cost(req), bucket.burst)
        if bucket.try_take(n, now):
            return True, None
        return False, round(bucket.retry_after(n), 6)

    # ---- weighted-fair dequeue (strict priority, then DRR) ----
    def select(self, waiting: Sequence) -> int:
        """Index of the request to dequeue next: best (lowest) priority
        class first; within it, the tenant with the least normalized
        token debt (FIFO within a tenant). Single-tenant equal-priority
        traffic reduces to index 0 — exactly the pre-QoS FIFO."""
        if len(waiting) <= 1:
            return 0
        best_prio = min(r.priority for r in waiting)
        heads: Dict[str, int] = {}
        for i, r in enumerate(waiting):
            if r.priority == best_prio and r.tenant not in heads:
                heads[r.tenant] = i
        if len(heads) == 1:
            return next(iter(heads.values()))
        # a tenant entering (or re-entering after idling) starts at the
        # debt floor of the tenants already being served: idle time must
        # not bank credit it can burst through later
        known = [self._debt[t] for t in heads if t in self._debt]
        floor = min(known) if known else 0.0
        for t in heads:
            self._debt[t] = max(self._debt.get(t, 0.0), floor)
        tenant = min(heads, key=lambda t: (self._debt[t], heads[t]))
        return heads[tenant]

    def charge(self, req) -> None:
        """Account a dequeue: debt grows by cost/weight, so a weight-2
        tenant drains twice the tokens before parity."""
        w = self.config.tenant(req.tenant).weight
        self._debt[req.tenant] = self._debt.get(req.tenant, 0.0) + self.cost(req) / w

    # ---- bounded queues ----
    def queue_full(self, depth: int) -> bool:
        return (self.config.max_waiting is not None
                and depth >= self.config.max_waiting)

    def queue_full_victim(self, waiting: Sequence, req):
        """Who loses the slot when the line is full: the lowest class
        among the queued requests and the newcomer. The newcomer only
        displaces a queued victim by STRICTLY outranking it (ties keep
        the queued request — it has waited longer); within the victim
        class the most recent submit sheds."""
        worst = None
        for r in waiting:
            if worst is None or (r.priority, r.submitted_time or 0.0) >= (
                worst.priority, worst.submitted_time or 0.0
            ):
                worst = r
        if worst is not None and worst.priority > req.priority:
            return worst
        return req

    def deadline_unmeetable(self, req, ewma_step_s: Optional[float],
                            emit_bound: int) -> bool:
        """True when the TTL provably cannot be met: even generating at
        the per-step emit upper bound (1 token/step plain, draft_len+1
        speculative) for every remaining step, max_new tokens take longer
        than the whole deadline. Conservative by construction — queue
        wait and prompt streaming are ignored, so a True here is a
        certainty, not a forecast."""
        if (not self.config.deadline_shed or req.deadline_s is None
                or ewma_step_s is None or ewma_step_s <= 0.0):
            return False
        min_steps = req.max_new_tokens / max(1, emit_bound)
        return min_steps * ewma_step_s > req.deadline_s

    # ---- pressure / brownout ----
    def note_slo_burn(self, frac: float) -> None:
        """Feed the SLO-burn pressure component (fraction of recent
        requests over budget, e.g. from slo_breakdown()['slo'])."""
        self._slo_burn = min(1.0, max(0.0, float(frac)))

    def set_degraded(self, flag: bool) -> None:
        """Fleet hook: a tiered fleet off its disaggregated rung (decode
        or prefill tier dead — half the chips doing both phases) marks
        the shared policy degraded; while held, pressure readings are
        floored at ``brownout.degraded_pressure_floor`` so the ladder
        escalates BEFORE the thinner fleet's queues actually back up.
        Cleared automatically when the fleet re-splits."""
        self.degraded = bool(flag)

    def pressure(self, pool_frac: float, queue_frac: float) -> float:
        """Composite pressure: the WORST of pool occupancy, queue depth
        (vs max_waiting), and fed SLO burn — any one resource saturating
        is overload, averaging would hide it. A degraded fleet floors
        the reading (see ``set_degraded``)."""
        p = max(
            min(1.0, max(0.0, pool_frac)),
            min(1.0, max(0.0, queue_frac)),
            self._slo_burn,
        )
        if self.degraded:
            p = max(p, self.config.brownout.degraded_pressure_floor)
        self.last_pressure = p
        return p

    def update_pressure(self, now: float, pool_frac: float,
                        queue_frac: float) -> List[Tuple[str, int]]:
        return self.brownout.update(self.pressure(pool_frac, queue_frac), now)

    def note_shed(self, reason: str) -> None:
        self.shed_counts[reason] = self.shed_counts.get(reason, 0) + 1


# ---------------------------------------------------------------------------
# fairness reporting
# ---------------------------------------------------------------------------

def jain_fairness(shares: Sequence[float]) -> Optional[float]:
    """Jain's index J = (Σx)² / (n·Σx²) over per-tenant weighted service;
    1.0 = perfectly fair, 1/n = one tenant took everything."""
    xs = [float(x) for x in shares if x is not None]
    if not xs:
        return None
    sq = sum(x * x for x in xs)
    if sq == 0.0:
        return None
    s = sum(xs)
    return round((s * s) / (len(xs) * sq), 4)


def tenant_report(finished: Sequence, config: Optional[QoSConfig] = None) -> Dict:
    """Per-tenant outcome/service breakdown over a drained replay, plus
    the Jain fairness index over weight-normalized generated tokens —
    the number bench records and perf_gate gates."""
    cfg = config or QoSConfig()
    per: Dict[str, Dict] = {}
    for r in finished:
        t = getattr(r, "tenant", "default")
        d = per.setdefault(t, {
            "requests": 0, "completed": 0, "shed": 0, "expired": 0,
            "cancelled": 0, "generated_tokens": 0, "tpots_ms": [],
        })
        d["requests"] += 1
        outcome = r.outcome or "completed"
        if outcome in d:
            d[outcome] += 1
        d["generated_tokens"] += (len(r.prompt) - r.prompt_len) + len(r.generated)
        tpot = r.tpot()
        if tpot is not None:
            d["tpots_ms"].append(tpot * 1000.0)
    shares = []
    for t, d in per.items():
        tpots = sorted(d.pop("tpots_ms"))
        d["p99_tpot_ms"] = (
            round(tpots[min(len(tpots) - 1, int(0.99 * len(tpots)))], 3)
            if tpots else None
        )
        d["weighted_share"] = round(
            d["generated_tokens"] / cfg.tenant(t).weight, 3
        )
        shares.append(d["weighted_share"])
    return {
        "tenants": per,
        "fairness_index": jain_fairness([s for s in shares if s > 0]),
    }
