"""Compilation-lifecycle observability + persistent compile cache.

One subsystem, two inseparable halves (round 18):

- **Observability** (`ledger`): every `lower()`/`compile()` across the
  four compile entry points — static `Executor`, `to_static`, the
  `InferenceEngine` shape buckets, the fused-optimizer engine — emits a
  structured event (origin, stable program fingerprint, signature, wall
  seconds, hit|miss|restore|shared|persist outcome) into a bounded store
  with `paddle_tpu_compile_*` telemetry, compile spans in the request
  trace's chrome lanes, and a cold-start timeline report
  (`python -m paddle_tpu.compile_cache report`) decomposing the
  engine-load -> first-token wall.

- **Cache** (`store`): compiled executables persisted keyed by
  (program fingerprint, topology meta, jax version) in an atomic
  CRC-verified layout (PR 2's torn-write discipline), restored instead of
  recompiled on the next process — plus an in-process shared registry so
  fleet replicas with identical signatures compile once. Point the process
  at a directory with `configure(path)` or the
  `PADDLE_TPU_COMPILE_CACHE_DIR` env var (exported ahead by the elastic
  relaunch path so restarted workers land on a warm cache).
"""
from . import fingerprint, ledger, report, store  # noqa: F401
from .fingerprint import (  # noqa: F401
    aval_signature,
    entry_key,
    fingerprint_text,
    topology_meta,
)
from .ledger import (  # noqa: F401
    events,
    record,
    reset,
    reset_timeline,
    summary,
)
from .report import cold_start_report, format_report  # noqa: F401
from .store import (  # noqa: F401
    CompileCacheStore,
    active_store,
    clear_shared,
    configure,
    make_meta,
    serialization_available,
    shared_get,
    shared_put,
    store_dir,
)

__all__ = [
    "fingerprint",
    "ledger",
    "report",
    "store",
    "aval_signature",
    "entry_key",
    "fingerprint_text",
    "topology_meta",
    "events",
    "record",
    "reset",
    "reset_timeline",
    "summary",
    "cold_start_report",
    "format_report",
    "CompileCacheStore",
    "active_store",
    "clear_shared",
    "configure",
    "make_meta",
    "serialization_available",
    "shared_get",
    "shared_put",
    "store_dir",
]
