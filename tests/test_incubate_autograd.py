"""paddle.incubate.autograd (jvp/vjp/Jacobian/Hessian/forward_grad) and the
r4 incubate.nn fused Layer wrappers.

Reference: python/paddle/incubate/autograd/functional.py (vjp:22, jvp:80,
Jacobian:170, Hessian:257), primapi.py (forward_grad:25, grad:108),
incubate/nn/__init__.py (FusedMultiTransformer, FusedEcMoe, FusedDropoutAdd,
FusedBiasDropoutResidualLayerNorm).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate import autograd as iag


def _t(a, sg=False):
    t = paddle.to_tensor(np.asarray(a, dtype=np.float32))
    t.stop_gradient = sg
    return t


class TestVjpJvp:
    def test_vjp_matmul_ones(self):
        # reference doc example: func(x) = x @ x, x = ones(2,2) -> vjp = 4s
        x = _t(np.ones((2, 2)))
        _, g = iag.vjp(lambda x: paddle.matmul(x, x), x)
        np.testing.assert_allclose(g.numpy(), np.full((2, 2), 4.0), rtol=1e-6)

    def test_vjp_custom_cotangent(self):
        x = _t(np.ones((2, 2)))
        v = _t([[1.0, 0.0], [0.0, 0.0]])
        _, g = iag.vjp(lambda x: paddle.matmul(x, x), x, v)
        np.testing.assert_allclose(g.numpy(), [[2.0, 1.0], [1.0, 0.0]], rtol=1e-6)

    def test_jvp_matmul_ones(self):
        x = _t(np.ones((2, 2)))
        _, j = iag.jvp(lambda x: paddle.matmul(x, x), x)
        np.testing.assert_allclose(j.numpy(), np.full((2, 2), 4.0), rtol=1e-6)

    def test_jvp_fd_verification(self):
        # finite-difference check on a nonlinear multi-input func
        rng = np.random.RandomState(0)
        a0, b0 = rng.randn(3, 4).astype(np.float32), rng.randn(4, 2).astype(np.float32)
        va, vb = rng.randn(3, 4).astype(np.float32), rng.randn(4, 2).astype(np.float32)

        def f(a, b):
            return paddle.tanh(paddle.matmul(a, b))

        _, j = iag.jvp(f, [_t(a0), _t(b0)], [_t(va), _t(vb)])
        eps = 1e-3
        f_p = np.tanh((a0 + eps * va) @ (b0 + eps * vb))
        f_m = np.tanh((a0 - eps * va) @ (b0 - eps * vb))
        fd = (f_p - f_m) / (2 * eps)
        np.testing.assert_allclose(j.numpy(), fd, rtol=1e-2, atol=1e-3)

    def test_jvp_vjp_transpose_identity(self):
        # <v, J u> == <J^T v, u> ties forward and reverse modes together
        rng = np.random.RandomState(1)
        x0 = rng.randn(5).astype(np.float32)
        u = rng.randn(5).astype(np.float32)

        def f(x):
            return paddle.sin(x) * x

        _, ju = iag.jvp(f, _t(x0), _t(u))
        v = rng.randn(5).astype(np.float32)
        _, jtv = iag.vjp(f, _t(x0), _t(v))
        lhs = float(np.sum(v * ju.numpy()))
        rhs = float(np.sum(jtv.numpy() * u))
        assert abs(lhs - rhs) < 1e-4

    def test_jvp_multi_output(self):
        x = _t(np.ones((2,)))
        ys, js = iag.jvp(lambda x: (x * x, x + 1.0), x)
        assert isinstance(js, tuple) and len(js) == 2
        np.testing.assert_allclose(js[0].numpy(), [2.0, 2.0], rtol=1e-6)
        np.testing.assert_allclose(js[1].numpy(), [1.0, 1.0], rtol=1e-6)


class TestForwardGrad:
    def test_forward_grad_matches_jvp(self):
        rng = np.random.RandomState(2)
        x0 = rng.randn(4).astype(np.float32)
        v = rng.randn(4).astype(np.float32)
        x = _t(x0)
        y = paddle.exp(paddle.sin(x))
        fg = iag.forward_grad(y, x, _t(v))
        expected = np.exp(np.sin(x0)) * np.cos(x0) * v
        np.testing.assert_allclose(fg.numpy(), expected, rtol=1e-4, atol=1e-5)

    def test_grad_api(self):
        x = _t(np.array([1.0, 2.0]))
        y = x * x
        g = iag.grad(y, x)
        np.testing.assert_allclose(g.numpy(), [2.0, 4.0], rtol=1e-6)

    def test_prim_flags(self):
        from paddle_tpu.incubate.autograd import prim_enabled
        assert not prim_enabled()
        iag.enable_prim()
        assert prim_enabled()
        iag.disable_prim()
        assert not prim_enabled()


class TestJacobianHessian:
    def test_jacobian_full(self):
        # reference doc example: func(x, y) = matmul(x, y) at x = [[1,2],[3,4]]
        x = _t([[1.0, 2.0], [3.0, 4.0]])
        J = iag.Jacobian(lambda a, b: paddle.matmul(a, b), [x, x])
        full = J[:, :]
        assert tuple(full.shape) == (4, 8)
        expected_row0 = [1., 3., 0., 0., 1., 0., 2., 0.]
        np.testing.assert_allclose(full.numpy()[0], expected_row0, rtol=1e-6)

    def test_hessian_quadratic(self):
        # f(x) = x^T A x has Hessian A + A^T
        rng = np.random.RandomState(3)
        A = rng.randn(4, 4).astype(np.float32)
        At = paddle.to_tensor(A)

        def f(x):
            return paddle.sum(x * paddle.matmul(At, x))

        x = _t(rng.randn(4).astype(np.float32))
        H = iag.Hessian(f, x)
        np.testing.assert_allclose(H[:, :].numpy(), A + A.T, rtol=1e-4, atol=1e-5)

    def test_hessian_rejects_vector_output(self):
        x = _t(np.ones((3,)))
        with pytest.raises(ValueError):
            iag.Hessian(lambda x: x * x, x)


class TestFusedLayers:
    def test_fused_dropout_add_eval(self):
        from paddle_tpu.incubate.nn import FusedDropoutAdd
        layer = FusedDropoutAdd(p=0.5)
        layer.eval()
        x = _t(np.ones((2, 3)))
        y = _t(np.full((2, 3), 2.0))
        np.testing.assert_allclose(layer(x, y).numpy(), np.full((2, 3), 3.0), rtol=1e-6)

    def test_fused_dropout_add_train_p0(self):
        from paddle_tpu.incubate.nn import FusedDropoutAdd
        layer = FusedDropoutAdd(p=0.0)
        x = _t(np.ones((2, 3)))
        y = _t(np.zeros((2, 3)))
        np.testing.assert_allclose(layer(x, y).numpy(), np.ones((2, 3)), rtol=1e-6)

    def test_fused_ec_moe_matches_functional(self):
        from paddle_tpu.incubate.nn import FusedEcMoe
        from paddle_tpu.incubate.nn import functional as IF
        paddle.seed(0)
        layer = FusedEcMoe(8, 16, 4, act_type="gelu")
        # weights init to nonzero for a meaningful check
        rng = np.random.RandomState(0)
        layer.bmm_weight0.set_value(paddle.to_tensor(rng.randn(4, 8, 16).astype(np.float32)))
        layer.bmm_weight1.set_value(paddle.to_tensor(rng.randn(4, 16, 8).astype(np.float32)))
        x = _t(rng.randn(2, 5, 8).astype(np.float32))
        gate = _t(rng.randn(2, 5, 4).astype(np.float32))
        out = layer(x, gate)
        ref = IF.fused_ec_moe(x, gate, layer.bmm_weight0, layer.bmm_bias0,
                              layer.bmm_weight1, layer.bmm_bias1, "gelu")
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-6)
        assert out.shape == [2, 5, 8]

    def test_fused_bias_dropout_residual_layer_norm(self):
        from paddle_tpu.incubate.nn import FusedBiasDropoutResidualLayerNorm
        paddle.seed(0)
        layer = FusedBiasDropoutResidualLayerNorm(8, dropout_rate=0.0)
        layer.eval()
        rng = np.random.RandomState(0)
        x = _t(rng.randn(2, 4, 8).astype(np.float32))
        res = _t(rng.randn(2, 4, 8).astype(np.float32))
        out = layer(x, res)
        # oracle: layer_norm(x + bias + residual), bias/ln defaults 0/1
        h = x.numpy() + res.numpy()
        mu = h.mean(-1, keepdims=True)
        var = h.var(-1, keepdims=True)
        expected = (h - mu) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(out.numpy(), expected, rtol=1e-4, atol=1e-5)

    def test_fused_multi_transformer_runs_and_matches_functional(self):
        from paddle_tpu.incubate.nn import FusedMultiTransformer
        paddle.seed(0)
        layer = FusedMultiTransformer(
            embed_dim=16, num_heads=2, dim_feedforward=32, num_layers=2,
        )
        layer.eval()
        assert len(layer.qkv_weights) == 2
        assert tuple(layer.qkv_weights[0].shape) == (3, 2, 8, 16)
        rng = np.random.RandomState(0)
        src = _t(rng.randn(2, 6, 16).astype(np.float32))
        out = layer(src)
        assert out.shape == [2, 6, 16]
        assert np.isfinite(out.numpy()).all()
        # grads flow to every parameter family
        loss = paddle.sum(out * out)
        loss.backward()
        for fam in (layer.qkv_weights, layer.ffn1_weights, layer.ln_scales):
            assert fam[0].grad is not None
