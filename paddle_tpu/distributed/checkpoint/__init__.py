"""paddle.distributed.checkpoint namespace (reference: python/paddle/distributed/checkpoint/)."""
from .load_state_dict import load_state_dict  # noqa: F401
from .metadata import LocalTensorMetadata, Metadata, TensorMetadata  # noqa: F401
from .save_state_dict import save_state_dict  # noqa: F401

__all__ = ["save_state_dict", "load_state_dict", "Metadata", "TensorMetadata", "LocalTensorMetadata"]
