"""The un-forfeitable bench capture (fast tier-1 lane, NOT `slow`).

r05's driver capture was lost entirely (`BENCH_r05.json` rc=124,
parsed=null) because bench.py printed its single JSON line only after ALL
configs completed. These tests pin the round-6 contract: under an
artificially tiny `BENCH_DEADLINE_S` the run still exits 0, every stdout
line is a complete parsable JSON snapshot, and the last line lists every
config as measured or EXPLICITLY skipped — the driver can never again read
`parsed: null` from a timed-out run.
"""
import json
import os
import subprocess
import sys

BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py")
CONFIGS = {"seq128", "passes", "seq4096", "llama3_shape", "resnet50",
           "ppocr_e2e", "serving", "fleet", "qos", "input_stream",
           "moe_longcontext"}


def _run_bench(deadline_s):
    env = dict(os.environ)
    env["BENCH_DEADLINE_S"] = str(deadline_s)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("BENCH_CHILD", None)
    return subprocess.run(
        [sys.executable, BENCH], env=env, capture_output=True, text=True,
        timeout=240,
    )


def test_tiny_deadline_yields_explicit_skips():
    r = _run_bench(0.1)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.strip()]
    assert lines, "bench printed nothing"

    # EVERY line is a complete snapshot (the driver may catch any of them)
    snaps = [json.loads(l) for l in lines]
    for s in snaps:
        assert set(s) >= {"metric", "value", "unit", "vs_baseline", "detail"}
        assert set(s["detail"]["configs"]) == CONFIGS

    last = snaps[-1]
    for k, status in last["detail"]["configs"].items():
        assert status == "skipped:deadline", (k, status)
    # the headline's skip is recorded in the detail too, not silently null
    assert last["detail"]["seq128"] == {"skipped": "deadline"}
    assert last["value"] is None
    # snapshot-and-extend: one line per resolved config plus the terminal one
    assert len(lines) >= len(CONFIGS)


def test_measured_config_carries_attribution():
    """Round-8 contract: every MEASURED config's record carries a
    `attribution` block — XLA cost/memory numbers + roofline — or an
    explicit `attribution: unavailable` marker; silence is not an option.
    Runs the real bench pipeline on a seconds-scale shrunken ERNIE (the
    dims override is recorded in the result)."""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        BENCH_DEADLINE_S="200",
        BENCH_SKIP_VISION="1", BENCH_SKIP_4096="1", BENCH_SKIP_LLAMA="1",
        BENCH_SKIP_SERVING="1",  # the serving replay has its own tier-1 test
        # shrink the headline model to tier-1 scale; dims land in the record
        BENCH_STEPS="10", BENCH_BATCH="2", BENCH_SEQ="16",
        BENCH_VOCAB="256", BENCH_HIDDEN="64", BENCH_LAYERS="2",
        BENCH_FFN="128", BENCH_HEADS="4",
        # shrink the co-measured peak + the don't-even-start estimates
        BENCH_PEAK_N="256", BENCH_EST_SEQ128="5", BENCH_EST_PEAK="1",
        PADDLE_TPU_TELEMETRY="1",
    )
    env.pop("BENCH_CHILD", None)
    r = subprocess.run(
        [sys.executable, BENCH], env=env, capture_output=True, text=True,
        timeout=220,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    last = json.loads(r.stdout.strip().splitlines()[-1])
    assert last["detail"]["configs"]["seq128"] == "measured", last["detail"]["configs"]
    assert last["detail"]["dims_override"]["hidden"] == 64

    # round-15 contract: the passes probe is measured in-parent and carries
    # the gated fusion-coverage fields
    assert last["detail"]["configs"]["passes"] == "measured", last["detail"]["configs"]
    pblock = last["detail"]["passes"]
    assert pblock["matches"]["fuse_attention"] >= 2
    assert pblock["matches"]["fuse_norm_matmul"] >= 1
    assert pblock["outputs_identical"] is True
    assert pblock["pipeline_ms"] > 0
    assert pblock["n_ops_after"] < pblock["n_ops_recorded"]

    attr = last["detail"]["attribution"]
    if attr.get("attribution") == "unavailable":
        # explicit marker: allowed only on platforms without cost analysis,
        # and it must say why
        assert attr.get("why") or attr.get("error")
    else:
        # well-formed block: real numbers, roofline fields included (CPU
        # supports cost analysis, so this is the branch this runner takes)
        assert attr["flops"] > 0
        assert attr["hbm_bytes"] > 0
        assert attr["program_memory_bytes"] > 0
        assert attr["peak_hbm_bytes"] > 0
        assert attr["compile_seconds"] > 0
        assert 0 < attr["mfu"] < 10
        assert attr["bound"] in ("compute", "memory")
        assert attr["platform"]


def test_sigterm_still_emits_terminal_snapshot():
    """Round-9 contract: the driver's timeout sends SIGTERM — bench must
    answer with a complete terminal JSON line as its LAST output (pending
    configs become explicit `skipped:sigterm`), so the driver's short
    stdout tail always contains a parsable record."""
    import select
    import signal

    env = dict(os.environ)
    env["BENCH_DEADLINE_S"] = "3000"  # deadline far away: SIGTERM is the exit
    env["JAX_PLATFORMS"] = "cpu"
    # shrink the headline so the first compile is short: SIGTERM delivery
    # waits out whatever C-level XLA call is in flight, so a full-size
    # headline compile adds ~10s of pure latency to this test
    env.update(
        BENCH_STEPS="10", BENCH_BATCH="2", BENCH_SEQ="16",
        BENCH_VOCAB="256", BENCH_HIDDEN="64", BENCH_LAYERS="2",
        BENCH_FFN="128", BENCH_HEADS="4",
        BENCH_PEAK_N="256", BENCH_EST_SEQ128="5", BENCH_EST_PEAK="1",
    )
    env.pop("BENCH_CHILD", None)
    p = subprocess.Popen(
        [sys.executable, BENCH], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )
    try:
        # signal as soon as the FIRST snapshot line lands (headline just
        # resolved, the other configs are pending — each needs a child
        # spawn, so they cannot all resolve in the signal-delivery gap) or
        # after 3s mid-headline, whichever comes first; a fixed sleep alone
        # races bench finishing entirely on a fast host
        select.select([p.stdout], [], [], 3.0)
        p.send_signal(signal.SIGTERM)
        out, err = p.communicate(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()
            p.communicate()
    assert p.returncode == 0, err[-2000:]
    lines = [l for l in out.strip().splitlines() if l.strip()]
    assert lines, "SIGTERM produced no terminal snapshot"
    last = json.loads(lines[-1])
    assert set(last["detail"]["configs"]) == CONFIGS
    for k, status in last["detail"]["configs"].items():
        assert status != "pending", (k, status)
    assert any(s.startswith("skipped:sigterm")
               for s in last["detail"]["configs"].values())


def test_input_stream_child_prefetch_wins_and_is_attributed():
    """Round-12 acceptance: the input-bound config's prefetch-ON step time
    beats prefetch-OFF on the same seeded stream, and the difference is
    attributed to the pipeline's own input_wait_s measurements (the field
    the guardian records per step). Runs the real child builder at
    seconds scale (knobs recorded in input_dims)."""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu", BENCH_CHILD="input_stream",
        BENCH_INPUT_SAMPLES="512", BENCH_INPUT_BATCH="16",
        BENCH_INPUT_FEATURES="256", BENCH_INPUT_HIDDEN="512",
        BENCH_INPUT_CLASSES="32", BENCH_INPUT_READER_WORK="60000",
        BENCH_INPUT_STEPS="15", PADDLE_TPU_TELEMETRY="1",
    )
    r = subprocess.run(
        [sys.executable, BENCH], env=env, capture_output=True, text=True,
        timeout=220,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    res = json.loads(r.stdout.strip().splitlines()[-1])
    assert res["input_dims"]["reader_work"] == 60000  # shrink is recorded
    # the headline comparison: overlap must win on the same stream
    assert res["ms_per_step"] < res["prefetch_off"]["ms_per_step"], res
    assert res["final_loss"] == res["prefetch_off"]["final_loss"]
    # and the win must be explained by the pipeline's own wait metric:
    # hidden wait accounts for (most of) the step-time delta
    wa = res["wait_attribution"]
    assert wa["step_delta_ms"] > 0
    assert wa["explained_fraction"] is not None
    assert 0.5 <= wa["explained_fraction"] <= 2.0, wa
    assert res["p99_input_wait_ms"] >= 0
    assert res["samples_per_sec"] > res["prefetch_off"]["samples_per_sec"]
    assert res["verdict"]["verdict"] in (
        "starved", "input_limited", "compute"
    )
    # attribution block rides the record like every measured config
    attr = res["attribution"]
    assert attr.get("flops") or attr.get("attribution") == "unavailable"


def test_moe_longcontext_child_reports_drops():
    """ROADMAP-5, round 20: the MoE + long-context child runs COMPILED
    (to_static over the sep×ep mesh) and its record carries real
    attribution (FLOPs/HBM — never the unavailable marker), the post-step
    drop counters, the fuse_moe match count, and the persistent-cache
    cold/warm compile walls."""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu", BENCH_CHILD="moe_longcontext",
        BENCH_MOE_SEQ="64", BENCH_MOE_DMODEL="32", BENCH_MOE_HEADS="4",
        BENCH_MOE_KV_HEADS="2", BENCH_MOE_EXPERTS="4", BENCH_MOE_FFN="64",
        BENCH_MOE_STEPS="3", PADDLE_TPU_TELEMETRY="1",
    )
    r = subprocess.run(
        [sys.executable, BENCH], env=env, capture_output=True, text=True,
        timeout=280,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    res = json.loads(r.stdout.strip().splitlines()[-1])
    assert res["seq"] == 64 and res["experts"] == 4  # shrink recorded
    assert res["heads"] == "4q/2kv"  # GQA shape in the record
    assert res["compiled"] is True
    assert res["tokens_per_sec"] > 0
    assert res["sep_ep_dims"]["sep"] == 1 and res["sep_ep_dims"]["ep"] == 1
    drops = res["moe_drops"]
    assert drops["routed_per_step"] == 2 * 64 * 2  # 2 layers x T x top_k
    assert 0 <= drops["dropped_per_step"] <= drops["routed_per_step"]
    assert drops["per_layer"]["moe0"]["routed"] == 128
    # the compiled config carries MEASURED attribution — regressing back
    # to the explicit unavailable marker is a perf_gate hard failure now
    attr = res["attribution"]
    assert "attribution" not in attr, attr
    assert attr["program"] == "moe_longcontext_step"
    assert attr["flops"] > 0 and attr["hbm_bytes"] > 0
    assert "mfu" in attr  # dt>0 guaranteed by the plain-average fallback
    # the fusion probe: both layers' dispatch->expert->combine chains match
    assert res["matches"]["fuse_moe"] == 2
    # persistent-cache round trip: cold miss, then a warm restore (or an
    # honest miss when executable serialization is unavailable)
    cc = res["compile_cache"]
    assert cc["cold"]["outcome"] == "miss"
    if cc["serialization_available"]:
        assert cc["warm"]["outcome"] == "restore"
        assert cc["warm"]["wall_s"] >= 0
    else:
        assert cc["warm"]["outcome"] in ("miss", None)


def test_moe_longcontext_eager_escape_hatch():
    """BENCH_MOE_EAGER=1 restores the eager step: the record says so
    (compiled false, explicit unavailable attribution naming the hatch)
    and the drop counters still flow through the same post-step read."""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu", BENCH_CHILD="moe_longcontext",
        BENCH_MOE_EAGER="1",
        BENCH_MOE_SEQ="64", BENCH_MOE_DMODEL="32", BENCH_MOE_HEADS="4",
        BENCH_MOE_KV_HEADS="2", BENCH_MOE_EXPERTS="4", BENCH_MOE_FFN="64",
        BENCH_MOE_STEPS="3", PADDLE_TPU_TELEMETRY="1",
    )
    r = subprocess.run(
        [sys.executable, BENCH], env=env, capture_output=True, text=True,
        timeout=280,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    res = json.loads(r.stdout.strip().splitlines()[-1])
    assert res["compiled"] is False
    assert res["attribution"]["attribution"] == "unavailable"
    assert "BENCH_MOE_EAGER" in res["attribution"]["why"]
    assert res["moe_drops"]["routed_per_step"] == 2 * 64 * 2


def test_deadline_skip_reason_survives_env_skips():
    env = dict(os.environ)
    env.update(
        BENCH_DEADLINE_S="0.1", JAX_PLATFORMS="cpu",
        BENCH_SKIP_VISION="1", BENCH_SKIP_4096="1", BENCH_SKIP_LLAMA="1",
    )
    env.pop("BENCH_CHILD", None)
    r = subprocess.run(
        [sys.executable, BENCH], env=env, capture_output=True, text=True,
        timeout=240,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    last = json.loads(r.stdout.strip().splitlines()[-1])
    cfg = last["detail"]["configs"]
    # env skips and deadline skips stay distinguishable in the record
    assert cfg["resnet50"] == "skipped:env"
    assert cfg["ppocr_e2e"] == "skipped:env"
    assert cfg["seq4096"] == "skipped:env"
    assert cfg["llama3_shape"] == "skipped:env"
    assert cfg["seq128"] == "skipped:deadline"


def test_qos_child_overload_replay_record():
    """Round-19 acceptance at tier-1 scale: the QoS child runs the
    >= 2x-capacity mixed-tenant burst for real and the record carries the
    gated fields (fairness_index, p99_tpot_gold_ms, gold_p99_vs_uncontended,
    qos_dims) plus the zero-loss/shed accounting the gate reads."""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu", BENCH_CHILD="qos",
        BENCH_QOS_VOCAB="512", BENCH_QOS_HIDDEN="64", BENCH_QOS_FFN="128",
        BENCH_QOS_HEADS="4", BENCH_QOS_KV_HEADS="2", BENCH_QOS_MAX_SEQ="64",
        BENCH_QOS_REQUESTS="24", BENCH_QOS_SUBMIT_PROBE="300",
        PADDLE_TPU_TELEMETRY="1",
    )
    r = subprocess.run(
        [sys.executable, BENCH], env=env, capture_output=True, text=True,
        timeout=220,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    res = json.loads(r.stdout.strip().splitlines()[-1])
    assert res["qos_dims"]["hidden"] == 64          # shrink is recorded
    assert res["overload_factor"] >= 2.0            # the acceptance floor
    # zero-loss: every offered request is terminal exactly once
    assert res["completed"] + res["shed"] == res["n_requests"]
    assert res["shed"] == sum(res["sheds_by_reason"].values())
    # gated fields present and sane
    assert res["fairness_index"] is None or 0.0 < res["fairness_index"] <= 1.0
    assert res["p99_tpot_gold_ms"] is None or res["p99_tpot_gold_ms"] > 0
    assert "gold_p99_vs_uncontended" in res
    assert set(res["per_tenant_p99_tpot_ms"]) >= {"gold", "bronze"}
    # the round-19 BASELINE number: per-submit QoS overhead is measured
    assert isinstance(res["submit_overhead_us"], float)
    attr = res["attribution"]
    assert attr.get("flops") or attr.get("attribution") == "unavailable"
