"""Pallas TPU kernels (flash attention first; more hot ops over time).

Reference parity: the role of paddle/phi/kernels/gpu/flash_attn_kernel.cu and
the fused CUDA ops in paddle/fluid/operators/fused/ — but written as Pallas
TPU kernels (MXU-tiled, VMEM-resident softmax accumulators) per
/opt/skills/guides/pallas_guide.md. Falls back to the XLA-fused reference
implementation when the platform or shapes don't fit the kernel grid.
"""
from __future__ import annotations

import functools
import math

import jax
from jax import numpy as jnp

_BLOCK_Q = 128
_BLOCK_K = 128


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def flash_attention_usable(q, causal, dropout_p, k=None, v=None) -> bool:
    """Kernel constraints: TPU platform, no dropout, self-attention shapes
    (q==k==v layout), seq multiple of the block, head_dim <= 256. [B,S,H,D]."""
    if dropout_p > 0.0:
        return False
    if not _on_tpu():
        return False
    if q.ndim != 4:
        return False
    for other in (k, v):
        if other is not None and tuple(other.shape) != tuple(q.shape):
            return False  # cross-attention / kv-cache: fall back to XLA chain
    b, s, h, d = q.shape
    return s % _BLOCK_Q == 0 and d <= 256 and s >= _BLOCK_Q


def _ref_attention_bshd(q, k, v, causal, sm_scale):
    """XLA reference chain (used for the backward pass until the Pallas
    backward kernel lands — flash backward recomputes anyway)."""
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    d = qh.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh).astype(jnp.float32) * scale
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(cm, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1).astype(qh.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    return jnp.swapaxes(out, 1, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_bshd(q, k, v, causal=False, sm_scale=None):
    return _flash_attention_fwd_impl(q, k, v, causal, sm_scale)


def _flash_fwd(q, k, v, causal, sm_scale):
    return _flash_attention_fwd_impl(q, k, v, causal, sm_scale), (q, k, v)


def _flash_bwd(causal, sm_scale, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: _ref_attention_bshd(a, b, c, causal, sm_scale), q, k, v)
    return vjp(g)


flash_attention_bshd.defvjp(_flash_fwd, _flash_bwd)


def _flash_attention_fwd_impl(q, k, v, causal=False, sm_scale=None):
    # Mosaic rejects i64 grid/index types, and the framework enables x64
    # globally (paddle dtype semantics) — trace the kernel with x64 off.
    # All kernel dtypes are explicit so numerics are unchanged.
    with jax.enable_x64(False):
        return _flash_attention_fwd_x32(q, k, v, causal, sm_scale)


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale"))
def _flash_attention_fwd_x32(q, k, v, causal=False, sm_scale=None):
    """Flash attention on [B, S, H, D]: online-softmax over K blocks.

    Grid: (batch*heads, q_blocks); each program instance streams K/V blocks
    through VMEM keeping the (m, l, acc) running softmax state — the standard
    TPU flash pattern (pallas_guide.md)."""
    from jax.experimental import pallas as pl

    b, s, h, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    # -> [B*H, S, D]
    qr = jnp.swapaxes(q, 1, 2).reshape(b * h, s, d)
    kr = jnp.swapaxes(k, 1, 2).reshape(b * h, s, d)
    vr = jnp.swapaxes(v, 1, 2).reshape(b * h, s, d)

    n_q = s // _BLOCK_Q

    def kernel(q_ref, k_ref, v_ref, o_ref):
        qi = pl.program_id(1)
        qb = q_ref[...].astype(jnp.float32) * scale

        # (BQ, 1) 2-D running stats: Mosaic wants >=2-D vregs in loop carry
        m0 = jnp.full((_BLOCK_Q, 1), -1e30, jnp.float32)
        l0 = jnp.zeros((_BLOCK_Q, 1), jnp.float32)
        acc0 = jnp.zeros((_BLOCK_Q, d), jnp.float32)

        n_k = s // _BLOCK_K
        # NB: no traced floordiv here — x64 mode + pallas floor_divide
        # recurses in promote_dtypes (jax 0.9); BLOCK_Q % BLOCK_K == 0 so a
        # static ratio multiply is exact.
        kmax = (qi + 1) * (_BLOCK_Q // _BLOCK_K) if causal else n_k

        def body(ki, carry):
            m, l, acc = carry
            # all index math in i32: x64 mode makes fori_loop indices i64,
            # which Mosaic's arith.muli/trunc legalization rejects
            ki = jnp.asarray(ki, jnp.int32)
            kb = k_ref[pl.dslice(ki * _BLOCK_K, _BLOCK_K), :].astype(jnp.float32)
            vb = v_ref[pl.dslice(ki * _BLOCK_K, _BLOCK_K), :].astype(jnp.float32)
            logits = qb @ kb.T  # [BQ, BK] on MXU
            if causal:
                qpos = qi * _BLOCK_Q + jax.lax.broadcasted_iota(jnp.int32, (_BLOCK_Q, _BLOCK_K), 0)
                kpos = ki * _BLOCK_K + jax.lax.broadcasted_iota(jnp.int32, (_BLOCK_Q, _BLOCK_K), 1)
                logits = jnp.where(qpos >= kpos, logits, -1e30)
            m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
            p = jnp.exp(logits - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * alpha + p @ vb
            return m_new, l_new, acc_new

        m, l, acc = jax.lax.fori_loop(
            jnp.asarray(0, jnp.int32), jnp.asarray(kmax, jnp.int32), body, (m0, l0, acc0)
        )
        o_ref[...] = (acc / l).astype(o_ref.dtype)

    out = pl.pallas_call(
        kernel,
        grid=(b * h, n_q),
        in_specs=[
            pl.BlockSpec((None, _BLOCK_Q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, s, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, s, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, _BLOCK_Q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
    )(qr, kr, vr)

    return jnp.swapaxes(out.reshape(b, h, s, d), 1, 2)
