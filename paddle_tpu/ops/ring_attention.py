"""Ring attention: exact attention over a sequence-sharded ring of devices.

The reference has NO long-context attention (SURVEY §2.3: the `sep` mesh axis
and `SegmentParallel` engine exist, but no ring/Ulysses/context-parallel
kernels — reference python/paddle/distributed/fleet/base/topology.py:68,
fleet/meta_parallel/segment_parallel.py:26 are scheduling shells only).
This module designs the capability TPU-first:

- q/k/v live sequence-sharded over a mesh axis (the `sep` axis of the
  hybrid topology). Each device keeps its q shard resident and streams the
  k/v shards around the ring with `lax.ppermute` (ICI neighbor exchange,
  overlapped by XLA with the block attention compute).
- Per-step block attention uses the online-softmax (m, l, acc) recurrence —
  the same flash-attention algebra as ops/pallas.py, so the result is exact
  (not approximate) regardless of ring size.
- The ring loop is a `lax.scan`, so the whole thing is reverse-mode
  differentiable: the VJP of `ppermute` is the inverse permute and scan
  replays blockwise — memory stays O(S_local) activations per device.

Layout convention is paddle's [batch, seqlen, heads, head_dim]; seqlen is the
sharded axis.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
from jax import lax
from jax import numpy as jnp

_NEG_INF = -1e30


def _ring_flash_local(q, k, v, *, axis_name, causal, sm_scale):
    """Ring attention with the Pallas flash kernel computing each chunk
    (r4 VERDICT Weak #3: at the local chunk sizes where sep is actually
    used, the kernel is ~4-5x faster than the per-chunk XLA einsum chain).

    Each ring step runs `flash_attention_bshd_lse` on the resident kv
    chunk — the diagonal chunk causal, past chunks full, future chunks
    skipped — and chunk outputs merge in log-space:
        out = sum_i o_i * exp(lse_i - LSE),  LSE = logaddexp_i lse_i
    which is exact because o_i is the chunk-normalized attention and
    lse_i its logsumexp. The merge is elementwise (XLA-fused); the
    whole loop differentiates through the kernel's custom VJP (the lse
    cotangent folds into the flash backward's delta term)."""
    from .pallas import flash_attention_bshd_lse

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    def merge(out_run, lse_run, o_i, lse_i):
        new_lse = jnp.logaddexp(lse_run, lse_i)
        w_old = jnp.swapaxes(jnp.exp(lse_run - new_lse), 1, 2)[..., None]
        w_new = jnp.swapaxes(jnp.exp(lse_i - new_lse), 1, 2)[..., None]
        return out_run * w_old + o_i.astype(jnp.float32) * w_new, new_lse

    def step(carry, t):
        out_run, lse_run, kc, vc = carry
        src = (idx - t) % n  # global chunk id of the kv shard we hold now

        def attend(args, chunk_causal):
            o_r, l_r, kc, vc = args
            o_i, lse_i = flash_attention_bshd_lse(
                q, kc, vc, causal=chunk_causal, sm_scale=sm_scale
            )
            o_r, l_r = merge(o_r, l_r, o_i, lse_i)
            return o_r, l_r

        if causal:
            # t=0 is always the diagonal (src == idx) so lse_run is finite
            # after the first step; future chunks (src > idx) are fully
            # masked and skipped — the classic uneven ring-causal load
            br = jnp.where(src > idx, 0, jnp.where(src < idx, 1, 2))
            out_run, lse_run = lax.switch(
                br,
                [
                    lambda a: (a[0], a[1]),                    # skip
                    functools.partial(attend, chunk_causal=False),  # past
                    functools.partial(attend, chunk_causal=True),   # diag
                ],
                (out_run, lse_run, kc, vc),
            )
        else:
            out_run, lse_run = attend((out_run, lse_run, kc, vc), False)
        k_next = lax.ppermute(kc, axis_name, perm)
        v_next = lax.ppermute(vc, axis_name, perm)
        return (out_run, lse_run, k_next, v_next), None

    out0 = jnp.zeros((b, s, h, d), jnp.float32)
    lse0 = jnp.full((b, h, s), -jnp.inf, jnp.float32)
    (out, _, _, _), _ = lax.scan(step, (out0, lse0, k, v), jnp.arange(n))
    return out.astype(q.dtype)


from .pallas import repeat_kv as _repeat_kv  # shared GQA fallback helper
from ..framework.jax_compat import shard_map as _shard_map


def ring_attention_local(
    q,
    k,
    v,
    *,
    axis_name: str,
    causal: bool = False,
    sm_scale: Optional[float] = None,
):
    """Per-shard ring attention body. MUST run inside shard_map/psum scope
    where `axis_name` is bound (e.g. the `sep` axis).

    q: [B, S_loc, H, D] local query shard (global seq position
       axis_index * S_loc + i).
    k/v: [B, S_loc, Hkv, D] local key/value shards, Hkv | H (GQA).
    Returns the local output shard [B, S_loc, H, D] in q.dtype.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    hkv = k.shape[2]
    if h % hkv != 0:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    n_rep = h // hkv

    from .pallas import _FLASH_MIN_SK, flash_attention_usable

    if flash_attention_usable(q, False, 0.0, k, v) and s >= _FLASH_MIN_SK:
        # long local chunks ride the Pallas kernel (GQA handled natively —
        # no repeat); short chunks keep the einsum online-softmax below,
        # where the XLA chain wins (same crossover as the sdpa dispatch)
        return _ring_flash_local(
            q, k, v, axis_name=axis_name, causal=causal, sm_scale=sm_scale
        )

    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    # [B, H, S, D] fp32 query, pre-scaled
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale

    perm = [(j, (j + 1) % n) for j in range(n)]

    qpos = idx * s + lax.broadcasted_iota(jnp.int32, (s, s), 0)

    def _attend(m, l, acc, kc, vc, src):
        kh = jnp.swapaxes(_repeat_kv(kc, n_rep), 1, 2).astype(jnp.float32)
        vh = jnp.swapaxes(_repeat_kv(vc, n_rep), 1, 2).astype(jnp.float32)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh)  # MXU
        if causal:
            kpos = src * s + lax.broadcasted_iota(jnp.int32, (s, s), 1)
            mask = qpos >= kpos  # [Sq, Sk] in global positions
            logits = jnp.where(mask, logits, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        if causal:
            p = jnp.where(mask, p, 0.0)  # kill exp(0) rows of all-masked blocks
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vh)
        return m_new, l_new, acc_new

    def step(carry, t):
        m, l, acc, kc, vc = carry
        src = (idx - t) % n  # global chunk id of the kv shard we hold now
        if causal:
            # future chunks (src > idx) are fully masked — skip their einsums
            # entirely (about half the ring steps; load is uneven per rank,
            # the classic ring-causal tradeoff)
            m, l, acc = lax.cond(
                src > idx,
                lambda m, l, acc, kc, vc, src: (m, l, acc),
                _attend,
                m, l, acc, kc, vc, src,
            )
        else:
            m, l, acc = _attend(m, l, acc, kc, vc, src)
        k_next = lax.ppermute(kc, axis_name, perm)
        v_next = lax.ppermute(vc, axis_name, perm)
        return (m, l, acc, k_next, v_next), None

    m0 = jnp.full((b, h, s), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    acc0 = jnp.zeros((b, h, s, d), jnp.float32)
    (m, l, acc, _, _), _ = lax.scan(step, (m0, l0, acc0, k, v), jnp.arange(n))

    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def ring_attention_op(q, k, v, *, mesh, axis_name: str = "sep",
                      causal: bool = False, sm_scale: Optional[float] = None):
    """Tensor-level entry recorded as ONE `ring_attention` op on the
    framework tape (core.apply): eager callers get the jitted whole-array
    ring below; `capture_program`/`to_static` see a single fixed-arity op
    whose closure carries the static mesh/axis/causal config — the
    long-context capture path the static pass pipeline and the compiled
    bench config consume. q/k/v are paddle Tensors [B, S, H, D]."""
    from ..core.apply import apply as _apply

    def fn(qv, kv, vv):
        return ring_attention(
            qv, kv, vv, mesh=mesh, axis_name=axis_name, causal=causal,
            sm_scale=sm_scale,
        )

    return _apply("ring_attention", fn, q, k, v)


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis_name", "causal", "sm_scale")
)
def ring_attention(q, k, v, *, mesh, axis_name: str = "sep", causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Whole-array entry: q/k/v are GLOBAL [B, S, H, D]; the seq axis is
    shard_mapped over `axis_name` of `mesh` and each shard runs the ring.

    Exact long-context attention: per-device memory is O(S/n * S/n) logits and
    O(S/n) activations, so global S scales linearly with ring size.
    """
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name, None, None)
    fn = _shard_map(
        functools.partial(
            ring_attention_local, axis_name=axis_name, causal=causal, sm_scale=sm_scale
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
