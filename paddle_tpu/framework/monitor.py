"""Named stats counters.

Reference parity: paddle/fluid/platform/monitor.cc (STAT_INT registry used
for framework-internal counters) + python/paddle/distributed/metric's simple
counters. Thread-safe int/float counters and gauges with a snapshot API.
"""
from __future__ import annotations

import threading
from collections import defaultdict

_lock = threading.Lock()
_counters: dict = defaultdict(int)
_gauges: dict = {}


def add(name: str, value=1):
    with _lock:
        _counters[name] += value


def set_gauge(name: str, value):
    with _lock:
        _gauges[name] = value


def get(name: str):
    with _lock:
        if name in _counters:
            return _counters[name]
        return _gauges.get(name)


def snapshot():
    with _lock:
        return {"counters": dict(_counters), "gauges": dict(_gauges)}


def reset(name: str = None):
    with _lock:
        if name is None:
            _counters.clear()
            _gauges.clear()
        else:
            _counters.pop(name, None)
            _gauges.pop(name, None)
