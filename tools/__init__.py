# makes tools/ importable so `python -m tools.trace_lint` and
# `python -m tools.perf_gate` resolve from a repo-root checkout
