"""Sharded checkpoint load with re-sharding and integrity verification.

Reference parity: python/paddle/distributed/checkpoint/load_state_dict.py —
reads the global metadata, then for every target tensor fills each local
shard by intersecting the slices it needs with the slices on disk, so a
checkpoint saved on one mesh/placement loads onto any other (the flatten
mapping / re-shard path). TPU-native: the target layout is the jax sharding
already attached to the destination tensor; per-device blocks are assembled
host-side and joined with jax.make_array_from_single_device_arrays, so no
full-size global materialization is needed for sharded tensors.

Integrity: `path` may be a checkpoint ROOT of `step_<N>/` directories (the
save_state_dict format) or a legacy flat directory. For a root, steps are
tried newest-first and a step is used only if it is COMPLETE (marker +
metadata present) and every shard file matches its recorded CRC32
(FLAGS_ckpt_verify_crc) — a torn or corrupt latest step is skipped with a
diagnostic and the newest complete one restores instead, so a SIGKILL
mid-save never strands the job.
"""
from __future__ import annotations

import glob
import os
import pickle
import sys

import jax
import numpy as np

from ...core.tensor import Tensor
from ...framework import flags as _flags
from ..resilience import fault_injection as _fi
from ..resilience.retry import RetryPolicy
from ..sharding import spec_layout as _sl
from .metadata import Metadata, intersection, slices_overlap
from .save_state_dict import (
    COMPLETE_MARKER,
    STEP_PREFIX,
    _crc32_file,
    _flatten_state_dict,
    list_steps,
)

_flags.define_flag(
    "FLAGS_ckpt_verify_crc", True,
    "verify shard-file CRC32s recorded in checkpoint metadata when selecting "
    "a step to load (catches torn/corrupt writes at the cost of one read)",
)
_flags.define_flag(
    "FLAGS_ckpt_read_retries", 3,
    "attempts for each checkpoint shard-file read at load/reshard time "
    "(transient IO errors back off with full jitter like the store retries; "
    "chaos plans hook the ckpt.read_shard site)",
)


def _read_policy() -> RetryPolicy:
    return RetryPolicy(
        max_attempts=max(1, int(_flags.get_flag("FLAGS_ckpt_read_retries"))),
        base_s=0.05, max_backoff_s=1.0, deadline_s=30.0,
    )


def _open_shard(path, file_name):
    """One shard-file open+mmap, behind the ckpt.read_shard chaos site and
    the read retry policy (a reshard-on-load after an elastic restart reads
    MANY remote shards — the flakiest moment of the recovery path)."""

    def attempt():
        _fi.fault_point("ckpt.read_shard", file=file_name)
        return np.load(os.path.join(path, file_name), mmap_mode="r")

    return _read_policy().call(attempt, site="ckpt.read_shard")


class CheckpointCorrupt(RuntimeError):
    """A step directory failed integrity verification."""


def _read_metadata(path) -> Metadata:
    merged = Metadata()
    files = sorted(glob.glob(os.path.join(path, "*.metadata")))
    if not files:
        raise FileNotFoundError(f"no .metadata files under {path}")
    for fp in files:
        with open(fp, "rb") as f:
            part: Metadata = pickle.load(f)
        for name, tm in part.state_dict_metadata.items():
            if name in merged.state_dict_metadata:
                merged.state_dict_metadata[name].shards.extend(tm.shards)
            else:
                merged.state_dict_metadata[name] = tm
        merged.flat_mapping.update(part.flat_mapping)
        # pre-checksum pickles lack the field entirely
        merged.file_checksums.update(getattr(part, "file_checksums", {}))
        # pre-portability pickles lack the saving-mesh record; all
        # processes of one save recorded the same mesh, first one wins
        if merged.mesh is None:
            merged.mesh = getattr(part, "mesh", None)
    return merged


def verify_step(step_dir, require_marker=True) -> Metadata:
    """Integrity-check one checkpoint directory: completeness marker,
    readable metadata, every referenced shard present, CRC32s matching.
    Returns the merged metadata on success, raises CheckpointCorrupt on any
    violation."""
    if require_marker and not os.path.exists(os.path.join(step_dir, COMPLETE_MARKER)):
        raise CheckpointCorrupt(f"{step_dir}: no {COMPLETE_MARKER} marker (torn save)")
    try:
        meta = _read_metadata(step_dir)
    except FileNotFoundError as e:
        raise CheckpointCorrupt(f"{step_dir}: {e}") from e
    except Exception as e:  # truncated/corrupt pickle
        raise CheckpointCorrupt(f"{step_dir}: unreadable metadata ({type(e).__name__}: {e})") from e
    referenced = {
        sh.file_name
        for tm in meta.state_dict_metadata.values()
        for sh in tm.shards
    }
    for fname in sorted(referenced):
        fp = os.path.join(step_dir, fname)
        if not os.path.exists(fp):
            raise CheckpointCorrupt(f"{step_dir}: shard {fname} missing")
    if _flags.get_flag("FLAGS_ckpt_verify_crc"):
        for fname, want in sorted(meta.file_checksums.items()):
            fp = os.path.join(step_dir, fname)
            if not os.path.exists(fp):
                raise CheckpointCorrupt(f"{step_dir}: checksummed file {fname} missing")
            got = _crc32_file(fp)
            if got != want:
                raise CheckpointCorrupt(
                    f"{step_dir}: {fname} CRC32 mismatch (got {got:#x}, recorded {want:#x})"
                )
    return meta


def _record_fallback(reason: str) -> None:
    from ... import telemetry as _tm

    if _tm.enabled():
        _tm.counter(
            "paddle_tpu_ckpt_fallbacks_total",
            "checkpoint steps skipped at load for integrity violations", ("reason",),
        ).labels(reason=reason).inc()


def select_checkpoint_dir(path):
    """Resolve `path` to the directory to actually load: `path` itself for a
    legacy flat checkpoint, else the newest COMPLETE + checksum-valid
    `step_<N>/`. Returns (dir, merged Metadata)."""
    steps = list_steps(path)
    if not steps:
        if glob.glob(os.path.join(path, "*.metadata")):
            # legacy flat layout: trust-but-verify (no marker requirement).
            # Only when NO step dirs exist — a pre-upgrade flat checkpoint
            # that later saves step_N/ alongside must not shadow the newer
            # steps with its stale weights.
            return path, verify_step(path, require_marker=False)
        raise FileNotFoundError(f"no checkpoint steps (or .metadata files) under {path}")
    last_err = None
    for step in reversed(steps):
        base = os.path.join(path, f"{STEP_PREFIX}{step}")
        # base + the `.old` a same-step overwrite leaves if it dies between
        # its two renames — that copy is complete, don't strand the job
        for step_dir in (base, base + ".old"):
            if not os.path.isdir(step_dir):
                continue
            try:
                return step_dir, verify_step(step_dir)
            except CheckpointCorrupt as e:
                reason = "torn" if COMPLETE_MARKER in str(e) else "corrupt"
                _record_fallback(reason)
                try:
                    from ...telemetry import timeline as _tl

                    # site label names the save-side fault family that
                    # produces each rejection shape (torn = publish died,
                    # corrupt = shard/metadata bytes flipped), so an
                    # injected save corruption is chaos-coverage-matched by
                    # the fallback it forces at load
                    _tl.emit("checkpoint", "load.fallback", severity="warn",
                             labels={"site": "ckpt.publish" if reason == "torn"
                                     else "ckpt.write_shard",
                                     "reason": reason},
                             step_dir=os.path.basename(step_dir))
                except Exception:
                    pass
                sys.stderr.write(
                    f"[paddle_tpu.checkpoint] skipping {os.path.basename(step_dir)}: "
                    f"{e}; falling back to the previous complete step\n"
                )
                last_err = e
    raise CheckpointCorrupt(
        f"no complete, uncorrupted checkpoint step under {path} "
        f"({len(steps)} step(s) rejected; last: {last_err})"
    )


def _fill_block(path, tm, offset, shape, dtype, mmap_cache=None):
    """Assemble the block [offset, offset+shape) of the global tensor from
    the saved shards that overlap it. `mmap_cache` (file_name -> mmap array)
    bounds file opens to one per shard file per load call instead of
    O(device-blocks x shards) (ADVICE r1)."""
    block = np.zeros(shape, dtype=dtype)
    filled = np.zeros(shape, dtype=bool) if tm.shards else None
    for sh in tm.shards:
        if not slices_overlap(offset, shape, sh.global_offset, sh.local_shape):
            continue
        ioff, ishape = intersection(offset, shape, sh.global_offset, sh.local_shape)
        if mmap_cache is not None:
            src = mmap_cache.get(sh.file_name)
            if src is None:
                src = _open_shard(path, sh.file_name)
                mmap_cache[sh.file_name] = src
        else:
            src = _open_shard(path, sh.file_name)
        src_sel = tuple(slice(o - go, o - go + s) for o, go, s in zip(ioff, sh.global_offset, ishape))
        dst_sel = tuple(slice(o - bo, o - bo + s) for o, bo, s in zip(ioff, offset, ishape))
        block[dst_sel] = src[src_sel]
        if filled is not None:
            filled[dst_sel] = True
    if filled is not None and not filled.all():
        raise ValueError("checkpoint does not cover the requested slice (missing shards)")
    return block


def _record_reshard(tensors_resharded: int, cross_mesh: bool) -> None:
    """Reshard-on-load telemetry: how many tensors changed layout, and
    whether the whole load crossed topologies (saving mesh != ours) — the
    signal the elastic-restart path is exercising its recovery muscle."""
    from ... import telemetry as _tm

    if not _tm.enabled():
        return
    _tm.counter(
        "paddle_tpu_ckpt_reshard_loads_total",
        "checkpoint loads by layout relationship", ("kind",),
    ).labels(kind="cross_topology" if cross_mesh else "same_topology").inc()
    if tensors_resharded:
        _tm.counter(
            "paddle_tpu_ckpt_reshard_tensors_total",
            "tensors whose placement at load differed from their saved layout",
        ).inc(tensors_resharded)


def load_state_dict(state_dict, path, process_group=None, coordinator_rank=0):
    """Fill `state_dict`'s tensors in place from the checkpoint at `path`,
    re-sharding as needed to each tensor's current placement. `path` may be
    a step directory, a legacy flat checkpoint, or a checkpoint root (newest
    complete step wins — see module doc)."""
    path, meta = select_checkpoint_dir(path)
    flat = _flatten_state_dict(state_dict)
    mmap_cache: dict = {}  # one open mmap per shard file for this call
    missing = []
    saved_mesh = getattr(meta, "mesh", None)
    cross_mesh = (
        saved_mesh is not None
        and _sl.mesh_to_meta(_sl.global_mesh_or_none()) not in (None, saved_mesh)
    )
    tensors_resharded = 0
    for name, t in flat.items():
        tm = meta.state_dict_metadata.get(name) or meta.state_dict_metadata.get(meta.flat_mapping.get(name, ""))
        if tm is None:
            missing.append(name)
            continue
        if not isinstance(t, Tensor):
            raise TypeError(f"load_state_dict target '{name}' must be a Tensor")
        if tuple(t.shape) != tuple(tm.global_shape):
            raise ValueError(f"'{name}': target shape {tuple(t.shape)} != saved {tuple(tm.global_shape)}")
        dtype = np.dtype(tm.dtype)
        sharding = t._value.sharding
        if _sl.sharding_to_meta(sharding)["spec"] != getattr(tm, "partition_spec", None):
            tensors_resharded += 1
        index_map = sharding.addressable_devices_indices_map(tuple(tm.global_shape))
        if index_map and tm.global_shape:
            per_device = []
            devices = []
            for dev, idx in index_map.items():
                offset = tuple(sl.start or 0 for sl in idx)
                shape = tuple(
                    (sl.stop if sl.stop is not None else dim) - (sl.start or 0)
                    for sl, dim in zip(idx, tm.global_shape)
                )
                block = _fill_block(path, tm, offset, shape, dtype, mmap_cache)
                per_device.append(jax.device_put(block.astype(t._value.dtype), dev))
                devices.append(dev)
            new_val = jax.make_array_from_single_device_arrays(
                tuple(tm.global_shape), sharding, per_device
            )
        else:  # scalar or fully-replicated trivial case
            block = _fill_block(path, tm, (0,) * len(tm.global_shape), tuple(tm.global_shape), dtype, mmap_cache)
            new_val = jax.device_put(block.astype(t._value.dtype), sharding)
        t._replace_value(new_val)
    if missing:
        raise KeyError(f"tensors missing from checkpoint: {missing}")
    _record_reshard(tensors_resharded, cross_mesh)
    try:
        from ...telemetry import timeline as _tl

        _tl.emit("checkpoint", "load.completed",
                 severity="warn" if cross_mesh else "info",
                 path=str(path), tensors=len(flat),
                 resharded=int(tensors_resharded),
                 cross_topology=bool(cross_mesh))
    except Exception:
        pass
    return state_dict
