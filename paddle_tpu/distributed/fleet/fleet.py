"""Fleet orchestration singleton.

Reference parity: python/paddle/distributed/fleet/fleet.py (init:167,
distributed_optimizer:1302) + fleet/model.py:32 distributed_model.
TPU-native design: init builds the hybrid mesh topology
(HybridCommunicateGroup over a multi-axis jax Mesh); distributed_model /
distributed_optimizer wrap per the strategy — the wrapping sets shardings,
GSPMD does the communication.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from .. import parallel_env
from ..parallel import DataParallel
from .base.distributed_strategy import DistributedStrategy
from .base.topology import (
    CommunicateTopology,
    HybridCommunicateGroup,
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)


class _FleetState:
    def __init__(self):
        self.initialized = False
        self.strategy: Optional[DistributedStrategy] = None
        self.hcg: Optional[HybridCommunicateGroup] = None
        self.is_collective = False


_state = _FleetState()

_ORDER_TO_TOPO_NAME = {"dp": "data", "pp": "pipe", "sharding": "sharding", "sep": "sep", "mp": "model"}
_DEGREE_KEY = {"dp": "dp_degree", "pp": "pp_degree", "sharding": "sharding_degree", "sep": "sep_degree", "mp": "mp_degree"}
# canonical spec_layout role -> hybrid order key
_ROLE_TO_ORDER = {"data": "dp", "pp": "pp", "fsdp": "sharding", "sep": "sep", "tp": "mp"}


def _apply_elastic_plan(degrees, order):
    """Honor PADDLE_ELASTIC_PLAN (exported by the launch controller's
    `_elastic_restart`): after an elastic shrink the relaunched worker's
    script still carries its ORIGINAL hybrid_configs, which no longer fit
    the surviving world — fleet.init would die on 'topology world size >
    available devices' and crash-loop the pod. The plan (canonical-role
    degrees from ElasticManager.plan_world) overrides the strategy's
    degrees so init lands on the mesh reshard-on-load targets."""
    import json
    import sys

    raw = os.environ.get("PADDLE_ELASTIC_PLAN")
    if not raw:
        return degrees
    try:
        plan = json.loads(raw)
        planned = {
            order_key: int(plan.get(role, 1))
            for role, order_key in _ROLE_TO_ORDER.items()
        }
    except Exception as e:
        sys.stderr.write(
            f"[fleet] ignoring unparseable PADDLE_ELASTIC_PLAN {raw!r} "
            f"({type(e).__name__}: {e}) — keeping the strategy's degrees\n"
        )
        return degrees
    new = {k: planned.get(k, 1) for k in order}
    if new != degrees:
        sys.stderr.write(
            f"[fleet] elastic restart: overriding hybrid degrees {degrees} "
            f"-> {new} from PADDLE_ELASTIC_PLAN\n"
        )
    return new


def init(role_maker=None, is_collective: bool = False, strategy: Optional[DistributedStrategy] = None):
    """paddle.distributed.fleet.init."""
    parallel_env.init_parallel_env()
    strategy = strategy or DistributedStrategy()
    _state.strategy = strategy
    _state.is_collective = is_collective
    _state.initialized = True
    strategy._apply_comm_watchdog()

    hybrid = strategy.hybrid_configs
    order = strategy.hybrid_parallel_order
    world = jax.device_count()
    degrees = {k: int(hybrid.get(_DEGREE_KEY[k], 1)) for k in order}
    # dp_degree == -1 (or unset remainder): infer from world size
    known = 1
    for k, d in degrees.items():
        if k != "dp" and d > 0:
            known *= d
    if degrees.get("dp", 1) in (-1, 0):
        degrees["dp"] = max(1, world // known)
    degrees = _apply_elastic_plan(degrees, order)

    names = [_ORDER_TO_TOPO_NAME[k] for k in order]
    dims = [degrees[k] for k in order]
    topo = CommunicateTopology(hybrid_group_names=names, dims=dims)
    hcg = HybridCommunicateGroup(topo)
    set_hybrid_communicate_group(hcg)
    _state.hcg = hcg
    return None


def is_first_worker() -> bool:
    return parallel_env.get_rank() == 0


def worker_index() -> int:
    return parallel_env.get_rank()


def worker_num() -> int:
    return jax.process_count()


def node_num() -> int:
    return jax.process_count()


def local_rank() -> int:
    return 0


def worker_endpoints(to_string=False):
    eps = parallel_env.ParallelEnv().trainer_endpoints
    return ",".join(eps) if to_string else eps


def barrier_worker():
    from ..collective import barrier

    barrier()


def init_worker(scopes=None):
    return None


def stop_worker():
    return None


def get_strategy() -> Optional[DistributedStrategy]:
    return _state.strategy


def distributed_model(model):
    """Wrap a model per the active strategy (fleet/model.py:32).

    - mp/pp layers (mpu.*, PipelineLayer) are already mesh-aware at
      construction; they pass through.
    - pure data parallel wraps in DataParallel (batch sharding).
    """
    if not _state.initialized:
        init()
    hcg = _state.hcg
    from .meta_parallel.pipeline_parallel import (
        PipelineParallel,
        PipelineParallelWithInterleave,
    )
    from .meta_parallel.parallel_layers.pp_layers import PipelineLayer

    if hcg.get_pipe_parallel_world_size() > 1 and isinstance(model, PipelineLayer):
        cls = PipelineParallelWithInterleave if model._num_virtual > 1 else PipelineParallel
        return cls(model, hcg, _state.strategy)
    if hcg.get_parallel_mode() == "data_parallel" and jax.device_count() > 1:
        return DataParallel(model)
    return model


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    """Wrap the optimizer per the strategy (fleet.py:1302).

    Sharding stage-1 (optimizer-state sharding over the sharding axis) is
    applied via shard_optimizer; TP/PP-aware grad clip is already correct
    because norms are computed on global arrays (a sharded param's norm IS
    the global norm — there are no partial per-rank norms to fix up).
    """
    strategy = strategy or _state.strategy or DistributedStrategy()
    hcg = _state.hcg
    if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
        from ...distributed.auto_parallel.api import shard_optimizer
        from ...distributed.auto_parallel.placement import Replicate, Shard
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = hcg.axis_name("sharding")
        mesh = hcg.mesh

        def _shard_acc(name, param, acc):
            x = acc._raw()
            if x.ndim >= 1 and x.shape[0] % mesh.shape[axis] == 0:
                sh = NamedSharding(mesh, P(axis))
                acc._replace_value(jax.device_put(x, sh))
            return None

        shard_optimizer(optimizer, _shard_acc)
    return optimizer


class Fleet:
    """Object surface for `from paddle.distributed.fleet import Fleet`."""

    init = staticmethod(init)
    is_first_worker = staticmethod(is_first_worker)
    worker_index = staticmethod(worker_index)
    worker_num = staticmethod(worker_num)
    worker_endpoints = staticmethod(worker_endpoints)
    barrier_worker = staticmethod(barrier_worker)
    distributed_model = staticmethod(distributed_model)
    distributed_optimizer = staticmethod(distributed_optimizer)
