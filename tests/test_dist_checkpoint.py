"""Distributed checkpoint: shard save + re-sharding load across meshes,
topology portability (mesh/spec metadata, cross-topology restore), and the
step-directory hygiene the elastic-restart path leans on."""
import glob
import os
import pickle
import shutil

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import ProcessMesh, Replicate, Shard


def test_save_load_replicated(tmp_path):
    sd = {"w": paddle.to_tensor(np.arange(12, dtype="float32").reshape(3, 4)), "b": paddle.to_tensor([1.0, 2.0])}
    dist.checkpoint.save_state_dict(sd, str(tmp_path / "ckpt"))
    target = {"w": paddle.zeros([3, 4]), "b": paddle.zeros([2])}
    dist.checkpoint.load_state_dict(target, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(target["w"].numpy(), sd["w"].numpy())
    np.testing.assert_allclose(target["b"].numpy(), sd["b"].numpy())


def test_save_sharded_load_resharded(tmp_path):
    mesh = ProcessMesh([0, 1, 2, 3, 4, 5, 6, 7], dim_names=["x"])
    data = np.arange(64, dtype="float32").reshape(8, 8)
    t = dist.shard_tensor(data, mesh, [Shard(0)])
    dist.checkpoint.save_state_dict({"w": t}, str(tmp_path / "ckpt"))

    # load onto a different placement: shard along axis 1
    target = dist.shard_tensor(np.zeros((8, 8), "float32"), mesh, [Shard(1)])
    dist.checkpoint.load_state_dict({"w": target}, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(np.asarray(target._value), data)
    # target keeps its own sharding
    assert "w" and target._value.sharding.is_fully_replicated is False


def test_save_sharded_load_2d_mesh(tmp_path):
    mesh1 = ProcessMesh([0, 1, 2, 3, 4, 5, 6, 7], dim_names=["x"])
    data = np.random.RandomState(0).randn(16, 8).astype("float32")
    t = dist.shard_tensor(data, mesh1, [Shard(0)])
    dist.checkpoint.save_state_dict({"layer.w": t}, str(tmp_path / "c2"))

    mesh2 = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["dp", "mp"])
    target = dist.shard_tensor(np.zeros((16, 8), "float32"), mesh2, [Shard(1), Shard(0)])
    dist.checkpoint.load_state_dict({"layer.w": target}, str(tmp_path / "c2"))
    np.testing.assert_allclose(np.asarray(target._value), data, rtol=1e-6)


def test_nested_state_dict_and_missing(tmp_path):
    sd = {"model": {"w": paddle.ones([2, 2])}, "opt": {"m": paddle.zeros([2])}}
    dist.checkpoint.save_state_dict(sd, str(tmp_path / "c3"))
    tgt = {"model": {"w": paddle.zeros([2, 2])}}
    dist.checkpoint.load_state_dict(tgt, str(tmp_path / "c3"))
    np.testing.assert_allclose(tgt["model"]["w"].numpy(), 1.0)
    bad = {"model": {"nope": paddle.zeros([2, 2])}}
    with pytest.raises(KeyError):
        dist.checkpoint.load_state_dict(bad, str(tmp_path / "c3"))


def test_shape_mismatch_raises(tmp_path):
    dist.checkpoint.save_state_dict({"w": paddle.ones([4])}, str(tmp_path / "c4"))
    with pytest.raises(ValueError):
        dist.checkpoint.load_state_dict({"w": paddle.zeros([5])}, str(tmp_path / "c4"))


# ---------------------------------------------------------------------------
# topology portability (round 10)
# ---------------------------------------------------------------------------


def _fleet_tp(dp, tp):
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": tp}
    fleet.init(is_collective=True, strategy=strategy)
    return fleet


class _TpNet(paddle.nn.Layer):
    def __init__(self, fleet, seed):
        super().__init__()
        paddle.seed(seed)
        self.col = fleet.ColumnParallelLinear(16, 32, gather_output=False)
        self.row = fleet.RowParallelLinear(32, 4, input_is_parallel=True)

    def forward(self, x):
        return self.row(self.col(x))


def _train_step(model, opt, x, y):
    loss = paddle.nn.MSELoss()(model(paddle.to_tensor(x)), paddle.to_tensor(y))
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss)


def test_metadata_records_spec_and_saving_mesh(tmp_path):
    """Round-10 format: every tensor's PartitionSpec and the saving mesh
    land in the step metadata (plain tuples — no jax objects pickled)."""
    fleet = _fleet_tp(4, 2)
    net = _TpNet(fleet, seed=3)
    step_dir = dist.checkpoint.save_state_dict(net.state_dict(), str(tmp_path / "ck"))
    (meta_fp,) = glob.glob(os.path.join(step_dir, "*.metadata"))
    with open(meta_fp, "rb") as f:
        meta = pickle.load(f)
    assert meta.mesh is not None and meta.mesh["n_devices"] == 8
    assert ("mp", 2) in meta.mesh["axes"] and ("dp", 4) in meta.mesh["axes"]
    specs = {k: tm.partition_spec for k, tm in meta.state_dict_metadata.items()}
    assert specs["col.weight"] == (None, "mp")
    assert specs["row.weight"] == ("mp", None)
    assert specs["col.bias"] == ("mp",)
    assert specs["row.bias"] == (None,)


def test_reshard_roundtrip_dp4tp2_to_dp2tp4_bit_identical(tmp_path):
    """THE portability criterion: a dp=4 x tp=2 save loads bit-identically
    into dp=2 x tp=4 — params AND optimizer state, with the optimizer
    running the fused flat-bucket engine on both sides (state crosses the
    engine's param->(bucket, offset, shape) index maps both directions)."""
    x = np.random.RandomState(0).randn(8, 16).astype("float32")
    y = np.random.RandomState(1).randn(8, 4).astype("float32")
    root = str(tmp_path / "ck")
    paddle.set_flags({"FLAGS_fused_optimizer": True})
    try:
        fleet = _fleet_tp(4, 2)
        net = _TpNet(fleet, seed=31)
        opt = paddle.optimizer.AdamW(0.01, parameters=net.parameters())
        for _ in range(2):  # builds the fused buckets + real moment state
            _train_step(net, opt, x, y)
        msd, osd = net.state_dict(), opt.state_dict()
        want = {f"model.{k}": np.asarray(t.numpy()) for k, t in msd.items()}
        opt_tensors = {k: t for k, t in osd.items() if isinstance(t, paddle.Tensor)}
        want.update({f"opt.{k}": np.asarray(t.numpy()) for k, t in opt_tensors.items()})
        dist.checkpoint.save_state_dict({"model": msd, "opt": osd}, root)

        # the other factorization of the same 8 devices
        fleet = _fleet_tp(2, 4)
        net2 = _TpNet(fleet, seed=77)  # different init: load must overwrite
        opt2 = paddle.optimizer.AdamW(0.01, parameters=net2.parameters())
        opt_tgt = {
            k: paddle.zeros(list(t.shape), dtype=str(t.numpy().dtype))
            for k, t in opt_tensors.items()
        }
        dist.checkpoint.load_state_dict({"model": net2.state_dict(), "opt": opt_tgt}, root)

        got = {f"model.{k}": np.asarray(t.numpy()) for k, t in net2.state_dict().items()}
        got.update({f"opt.{k}": np.asarray(t.numpy()) for k, t in opt_tgt.items()})
        assert set(got) == set(want)
        for k in sorted(want):
            np.testing.assert_array_equal(got[k], want[k], err_msg=k)
        # the load really resharded: tp layout on the NEW mesh factorization
        w = net2.col.weight._value
        assert w.sharding.spec[1] == "mp" and len(w.devices()) == 8

        # fused engine rebuilds its buckets from the restored per-param
        # state (handed over as host values — placement is the engine's
        # call); one more step must run and track the dp=4 x tp=2 run
        opt2.set_state_dict(
            {**{k: t.numpy() for k, t in opt_tgt.items()}, "@step": osd["@step"]}
        )
        cont_a = _train_step(net, opt, x, y)
        cont_b = _train_step(net2, opt2, x, y)
        np.testing.assert_allclose(cont_b, cont_a, rtol=1e-6)
    finally:
        paddle.set_flags({"FLAGS_fused_optimizer": False})


def test_legacy_flat_layout_cross_topology_load(tmp_path):
    """A pre-step-format flat checkpoint (files directly under the root)
    still loads — including onto a DIFFERENT topology than it was saved
    from (legacy saves predate the mesh metadata entirely)."""
    mesh1 = ProcessMesh([0, 1, 2, 3, 4, 5, 6, 7], dim_names=["x"])
    data = np.random.RandomState(3).randn(16, 8).astype("float32")
    t = dist.shard_tensor(data, mesh1, [Shard(0)])
    root = tmp_path / "legacy"
    step_dir = dist.checkpoint.save_state_dict({"w": t}, str(root))
    # demote to the legacy flat layout: files at the root, no step dirs
    for fp in os.listdir(step_dir):
        if fp != "COMPLETE":
            os.rename(os.path.join(step_dir, fp), os.path.join(root, fp))
    shutil.rmtree(step_dir)

    mesh2 = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["a", "b"])
    target = dist.shard_tensor(np.zeros((16, 8), "float32"), mesh2, [Shard(1), Shard(0)])
    dist.checkpoint.load_state_dict({"w": target}, str(root))
    np.testing.assert_array_equal(np.asarray(target._value), data)


def test_reshard_falls_back_past_torn_newest_step(tmp_path):
    """A cross-topology load whose newest step is torn (no COMPLETE marker —
    the save died mid-publish) must reshard from the newest COMPLETE step
    instead of stranding the job."""
    mesh1 = ProcessMesh([0, 1, 2, 3, 4, 5, 6, 7], dim_names=["x"])
    good = np.arange(64, dtype="float32").reshape(8, 8)
    bad = -np.ones((8, 8), "float32")
    root = str(tmp_path / "ck")
    dist.checkpoint.save_state_dict({"w": dist.shard_tensor(good, mesh1, [Shard(0)])}, root, step=1)
    torn_dir = dist.checkpoint.save_state_dict(
        {"w": dist.shard_tensor(bad, mesh1, [Shard(0)])}, root, step=2
    )
    os.remove(os.path.join(torn_dir, "COMPLETE"))

    mesh2 = ProcessMesh([[0, 1], [2, 3], [4, 5], [6, 7]], dim_names=["dp", "mp"])
    target = dist.shard_tensor(np.zeros((8, 8), "float32"), mesh2, [Shard(1), Shard(0)])
    dist.checkpoint.load_state_dict({"w": target}, root)
    np.testing.assert_array_equal(np.asarray(target._value), good)


def test_stale_old_dir_pruned_on_next_successful_save(tmp_path):
    """A same-step overwrite that died between its rmtree and rename leaves
    `step_<N>.old` next to a COMPLETE `step_<N>` — the next successful save
    prunes it."""
    root = str(tmp_path / "ck")
    d1 = dist.checkpoint.save_state_dict({"w": paddle.ones([2])}, root, step=1)
    # simulate the interrupted overwrite: complete base + leftover .old
    shutil.copytree(d1, d1 + ".old")
    assert os.path.isdir(d1 + ".old")
    dist.checkpoint.save_state_dict({"w": paddle.ones([2])}, root, step=2)
    assert not os.path.exists(d1 + ".old"), ".old next to a COMPLETE base must be pruned"
    assert os.path.isdir(d1)


def test_orphan_old_dir_is_kept_and_loadable(tmp_path):
    """When the overwrite died BETWEEN its two renames, `.old` is the only
    copy of that step: later saves must NOT prune it, and the loader still
    falls back to it when newer steps are torn."""
    root = str(tmp_path / "ck")
    d1 = dist.checkpoint.save_state_dict({"w": paddle.full([2], 7.0)}, root, step=1)
    os.rename(d1, d1 + ".old")  # first rename landed, second never did
    d2 = dist.checkpoint.save_state_dict({"w": paddle.full([2], 9.0)}, root, step=2)
    assert os.path.isdir(d1 + ".old"), "orphan .old is load-bearing, must survive"
    os.remove(os.path.join(d2, "COMPLETE"))  # newest torn -> fall back to the .old
    tgt = {"w": paddle.zeros([2])}
    dist.checkpoint.load_state_dict(tgt, root)
    np.testing.assert_array_equal(tgt["w"].numpy(), np.full((2,), 7.0, "float32"))


def test_shard_read_faults_are_retried(tmp_path):
    """Reshard-time shard reads run under the ckpt.read_shard chaos site
    with the read retry policy: transient IO faults do not kill the load."""
    from paddle_tpu.distributed import resilience as rz

    mesh = ProcessMesh([0, 1, 2, 3, 4, 5, 6, 7], dim_names=["x"])
    data = np.random.RandomState(5).randn(8, 8).astype("float32")
    root = str(tmp_path / "ck")
    dist.checkpoint.save_state_dict({"w": dist.shard_tensor(data, mesh, [Shard(0)])}, root)
    rz.install_plan(rz.FaultPlan().add("ckpt.read_shard", "fail", times=2))
    try:
        target = dist.shard_tensor(np.zeros((8, 8), "float32"), mesh, [Shard(1)])
        dist.checkpoint.load_state_dict({"w": target}, root)
    finally:
        rz.install_plan(None)
    np.testing.assert_array_equal(np.asarray(target._value), data)


def test_reshard_load_counts_into_telemetry(tmp_path):
    """Reshard events are observable: cross-layout loads bump the reshard
    counters (the elastic path's recovery telemetry)."""
    from paddle_tpu import telemetry as tm

    mesh = ProcessMesh([0, 1, 2, 3, 4, 5, 6, 7], dim_names=["x"])
    data = np.arange(64, dtype="float32").reshape(8, 8)
    root = str(tmp_path / "ck")
    was_enabled = tm.enabled()
    tm.enable()
    try:
        dist.checkpoint.save_state_dict({"w": dist.shard_tensor(data, mesh, [Shard(0)])}, root)
        fam = tm.default_registry().get("paddle_tpu_ckpt_reshard_tensors_total")
        before = fam.value if fam else 0
        target = dist.shard_tensor(np.zeros((8, 8), "float32"), mesh, [Shard(1)])
        dist.checkpoint.load_state_dict({"w": target}, root)
        fam = tm.default_registry().get("paddle_tpu_ckpt_reshard_tensors_total")
        assert fam is not None and fam.value >= before + 1
        loads = tm.default_registry().get("paddle_tpu_ckpt_reshard_loads_total")
        assert loads is not None
    finally:
        if not was_enabled:
            tm.disable()


def test_cross_topology_load_labels_telemetry(tmp_path):
    """Saving under one global mesh and loading under another must show up
    as kind=cross_topology — the saving mesh rides the metadata and the
    loader compares it against ITS mesh (the signal the elastic path's
    recovery is counted by)."""
    from paddle_tpu import telemetry as tm

    x = np.random.RandomState(0).randn(8, 16).astype("float32")
    was_enabled = tm.enabled()
    tm.enable()
    try:
        fleet = _fleet_tp(4, 2)
        net = _TpNet(fleet, seed=5)
        root = str(tmp_path / "ck")
        dist.checkpoint.save_state_dict({"model": net.state_dict()}, root)

        fleet = _fleet_tp(2, 4)  # different factorization -> different mesh
        net2 = _TpNet(fleet, seed=6)
        loads = tm.default_registry().get("paddle_tpu_ckpt_reshard_loads_total")
        before = loads.labels(kind="cross_topology").value if loads else 0
        dist.checkpoint.load_state_dict({"model": net2.state_dict()}, root)
        loads = tm.default_registry().get("paddle_tpu_ckpt_reshard_loads_total")
        assert loads is not None
        assert loads.labels(kind="cross_topology").value == before + 1
        np.testing.assert_array_equal(
            net2.col.weight.numpy(), net.col.weight.numpy()
        )
    finally:
        if not was_enabled:
            tm.disable()
