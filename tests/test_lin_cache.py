"""_lin_cache lifetime + boundedness (VERDICT r2 Weak #3 / next-round #6).

The cached-linearization key must HOLD the op fn's code object (so a GC'd
function's code address can never be reused by a different function and
alias its cache slot), and the cache must be LRU-bounded.
"""
import gc
import weakref

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import apply as apply_mod
from paddle_tpu.core.apply import apply


def _make_op(scale):
    # scale lands in the closure -> part of the cache key
    def op(x):
        return x * scale
    return op


def test_key_holds_code_object():
    x = paddle.to_tensor(np.ones((4,), np.float32))
    x.stop_gradient = False

    fn = _make_op(3.0)
    code_ref = weakref.ref(fn.__code__)
    out = apply("lincache_probe_hold", fn, x)
    assert float(out.numpy()[0]) == 3.0

    del fn, out
    gc.collect()
    # the code object survives inside the cache key -> its address can't be
    # recycled for a different function while the cached entry exists
    assert code_ref() is not None, "cache key no longer holds the code object"


def test_redefined_fn_no_stale_hit():
    x = paddle.to_tensor(np.ones((4,), np.float32))
    x.stop_gradient = False

    fn1 = _make_op(2.0)
    out1 = apply("lincache_probe_redef", fn1, x)
    assert float(out1.numpy()[0]) == 2.0
    del fn1, out1
    gc.collect()

    # a NEW function (new code object, different closure) must miss
    fn2 = _make_op(5.0)
    out2 = apply("lincache_probe_redef", fn2, x)
    assert float(out2.numpy()[0]) == 5.0

    y = paddle.to_tensor(np.ones((4,), np.float32))
    y.stop_gradient = False
    loss = apply("lincache_probe_redef", fn2, y).sum()
    loss.backward()
    np.testing.assert_allclose(y.grad.numpy(), np.full((4,), 5.0), rtol=1e-6)


def test_lru_eviction_bounds_cache():
    x = paddle.to_tensor(np.ones((2,), np.float32))
    x.stop_gradient = False

    old_cap = apply_mod._LIN_CACHE_CAP
    apply_mod._LIN_CACHE_CAP = 8
    try:
        baseline = dict(apply_mod._lin_cache)
        apply_mod._lin_cache.clear()
        fns = [_make_op(float(i)) for i in range(20)]
        for i, fn in enumerate(fns):
            out = apply(f"lincache_evict_{i}", fn, x)
            assert float(out.numpy()[0]) == float(i)
        assert len(apply_mod._lin_cache) <= 8
        # oldest entries evicted, newest retained
        names = [k[0] for k in apply_mod._lin_cache]
        assert "lincache_evict_19" in names
        assert "lincache_evict_0" not in names
    finally:
        apply_mod._LIN_CACHE_CAP = old_cap
        apply_mod._lin_cache.update(baseline)


def test_sdpa_dispatch_closure_hits_cache():
    """The sdpa dispatch closure must reference the pallas FUNCTIONS, not the
    module: a module in a closure cell makes _closure_sig bail, silently
    re-tracing the vjp on every call (regression for the cached-fast-path
    comment in nn/functional/attention.py)."""
    import paddle_tpu.nn.functional as F

    def tensors(seed):
        out = []
        for i in range(3):
            t = paddle.to_tensor(
                np.random.RandomState(seed + i).randn(1, 8, 2, 4).astype(np.float32)
            )
            t.stop_gradient = False
            out.append(t)
        return out

    q, k, v = tensors(0)
    F.scaled_dot_product_attention(q, k, v)
    keys_after_first = set(apply_mod._lin_cache.keys())
    sdpa_keys = [k_ for k_ in keys_after_first if k_[0] == "scaled_dot_product_attention"]
    assert sdpa_keys, "sdpa closure is not cacheable (cache key is None)"

    q2, k2, v2 = tensors(10)
    out = F.scaled_dot_product_attention(q2, k2, v2)
    assert set(apply_mod._lin_cache.keys()) == keys_after_first, (
        "second sdpa call with identical shapes must hit the cached "
        "linearization, not add a new entry"
    )
    loss = out.sum()
    loss.backward()
    assert q2.grad is not None  # the cached pullback still differentiates
