"""paddle.distributed.launch namespace (reference: python/paddle/distributed/launch/)."""
from .controller import CollectiveController, Context  # noqa: F401
from .job import Container, Pod  # noqa: F401
from .main import launch, parse_args  # noqa: F401
from .master import HTTPMaster, KVClient, KVServer  # noqa: F401
