"""save/load_inference_model for static programs.

Reference parity: python/paddle/static/io.py — freeze a program to a
deployable artifact. TPU-native: the artifact is the same jax.export
(StableHLO) format paddle_tpu.jit.save uses; params are baked in as
constants.
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from ..core.tensor import Tensor
from .program import Program, default_main_program


class _InferenceProgram:
    """Result of load_inference_model; Executor.run dispatches to _run."""

    def __init__(self, exported, feed_names, n_fetch):
        self._exported = exported
        self.feed_names = feed_names
        self.n_fetch = n_fetch

    def _run(self, feed, return_numpy=True):
        args = [jnp.asarray(feed[n]) for n in self.feed_names]
        out = self._exported.call(*args)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


def _export_program(feed_vars, fetch_vars, program):
    """Export the feed->fetch computation of `program` (weights baked in as
    constants, declared -1 feed dims kept symbolic). Shared by
    save_inference_model and serialize_program so both honor dynamic
    batch dims. Returns (exported, feed_names)."""
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    feed_ids, feed_names = [], []
    for fv in feed_vars:
        vid = program._id2var.get(id(fv))
        if vid is None or vid not in program.feed_vars.values():
            raise ValueError("feed_vars must be static.data placeholders of this program")
        feed_ids.append(vid)
        feed_names.append(fv.name)
    fetch_ids = []
    for fv in fetch_vars:
        vid = program._id2var.get(id(fv))
        if vid is None:
            raise ValueError("fetch_vars must be outputs of this program")
        fetch_ids.append(vid)

    # verify before lowering to StableHLO (flag-gated): exporting a
    # malformed program must fail with a named diagnostic, not an XLA error
    from .analysis import verifier as _verifier

    if _verifier.verify_enabled():
        _verifier.verify(program, feed_names=feed_names, fetch_vars=fetch_ids)

    # pass pipeline before export lowering (FLAGS_program_passes): the
    # frozen artifact ships the same dead-op-free, fusion-rewritten form
    # the Executor compiles — rewritten on a clone, caller's program intact
    from . import passes as _passes

    work = program
    if _passes.pipeline_enabled():
        work, _pass_result = _passes.run_default_pipeline(
            program, fetch_vars=fetch_ids, feed_names=feed_names
        )

    param_arrays = [work._var_tensors[v]._value for v in work.param_vars]

    def infer_fn(*feed_arrays):
        env = work.replay_env(dict(zip(feed_ids, feed_arrays)), param_arrays)
        return tuple(env[v] for v in fetch_ids)

    # dynamic batch: feed placeholders keep their declared -1 dims
    scope = jax_export.SymbolicScope()
    specs = []
    si = 0
    for fv in feed_vars:
        declared = program.feed_shapes.get(fv.name) or tuple(fv._raw().shape)
        dims = []
        dynamic = False
        for d in declared:
            if d in (-1, None):
                dims.append(f"s{si}")
                si += 1
                dynamic = True
            else:
                dims.append(str(int(d)))
        shape = jax_export.symbolic_shape(",".join(dims), scope=scope) if dynamic else tuple(int(d) for d in declared)
        specs.append(jax.ShapeDtypeStruct(shape, fv._value.dtype))

    return jax_export.export(jax.jit(infer_fn))(*specs), feed_names


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, program=None, **kwargs):
    """Freeze `program` (default: current main) to path_prefix.pdmodel +
    .pdmeta. Weights are constants inside the StableHLO blob."""
    program = program or default_main_program()
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    exported, feed_names = _export_program(feed_vars, fetch_vars, program)
    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".pdmeta", "wb") as f:
        pickle.dump({"feed_names": feed_names, "n_fetch": len(fetch_vars)}, f)
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns [program, feed_target_names, fetch_targets] like the
    reference; fetch_targets are positional indices here (the artifact is a
    compiled function, not a mutable graph)."""
    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path_prefix + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    prog = _InferenceProgram(exported, meta["feed_names"], meta["n_fetch"])
    return [prog, list(meta["feed_names"]), list(range(meta["n_fetch"]))]


def named_program_params(program):
    """(key, tensor) for every persistable param — THE naming contract all
    state save/load/serialize paths share (parameter name, positional
    param_{i} fallback for unnamed ones)."""
    for i, vid in enumerate(program.param_vars):
        t = program._var_tensors[vid]
        yield (getattr(t, "name", None) or f"param_{i}"), t


def save(program, model_path, protocol=4, **configs):
    """Save a Program's persistable parameters (reference static/io.py
    paddle.static.save: model_path + '.pdparams'). Keys from
    named_program_params."""
    state = {k: np.asarray(t._value) for k, t in named_program_params(program)}
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    path = model_path if model_path.endswith(".pdparams") else model_path + ".pdparams"
    with open(path, "wb") as f:
        pickle.dump(state, f, protocol=protocol)
    return path


def load(program, model_path, executor=None, var_list=None):
    """Load parameters saved by static.save back into the Program's
    persistable tensors (reference paddle.static.load)."""
    path = model_path if model_path.endswith(".pdparams") else model_path + ".pdparams"
    with open(path, "rb") as f:
        state = pickle.load(f)
    # var_list entries may be tensors (matched by identity, or by name when
    # set — tensors from a rebuilt program carry names but new ids) or key
    # strings
    wanted_ids = wanted_keys = None
    if var_list is not None:
        wanted_ids = {id(v) for v in var_list if not isinstance(v, str)}
        wanted_keys = {v for v in var_list if isinstance(v, str)}
        wanted_keys |= {
            getattr(v, "name", None) for v in var_list
            if not isinstance(v, str) and getattr(v, "name", None)
        }
    for key, t in named_program_params(program):
        if var_list is not None and id(t) not in wanted_ids and key not in wanted_keys:
            continue
        if key in state:
            t.set_value(jnp.asarray(state[key]))


def _export_blob(feed_vars, fetch_vars, program):
    """Serialize the feed->fetch computation of `program` to bytes (the
    StableHLO export save_inference_model writes to .pdmodel); shares
    _export_program so dynamic -1 feed dims stay symbolic."""
    return bytes(_export_program(feed_vars, fetch_vars, program)[0].serialize())
