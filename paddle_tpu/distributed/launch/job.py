"""Job abstractions for the launcher.

Reference parity: python/paddle/distributed/launch/job/ — Job/Pod/Container.
A Container is one managed subprocess with its env and log file; a Pod is
the set of containers on this node. TPU-native default is one container per
node (the single controller drives every local chip), vs. the reference's
one-per-GPU.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Dict, List, Optional


class Container:
    def __init__(self, entrypoint: List[str], env: Dict[str, str], out: Optional[str] = None):
        self.entrypoint = entrypoint
        self.env = dict(env)
        self.out = out
        self.proc: Optional[subprocess.Popen] = None
        self._log_fh = None
        self.restarts = 0

    def start(self):
        full_env = {**os.environ, **self.env}
        stdout = None
        if self.out:
            os.makedirs(os.path.dirname(self.out) or ".", exist_ok=True)
            self._log_fh = open(self.out, "ab")
            stdout = self._log_fh
        self.proc = subprocess.Popen(self.entrypoint, env=full_env, stdout=stdout, stderr=subprocess.STDOUT if stdout else None)

    @property
    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    @property
    def exit_code(self):
        return None if self.proc is None else self.proc.poll()

    def terminate(self, force=False):
        if self.proc is None:
            return
        if self.alive:
            self.proc.kill() if force else self.proc.terminate()
        if self._log_fh:
            self._log_fh.close()
            self._log_fh = None

    def wait(self, timeout=None):
        if self.proc is not None:
            try:
                self.proc.wait(timeout)
            except subprocess.TimeoutExpired:
                pass

    def __repr__(self):
        return f"Container(rank={self.env.get('PADDLE_TRAINER_ID')}, alive={self.alive}, exit={self.exit_code})"


class Pod:
    def __init__(self, name: str = None):
        self.name = name or f"pod_{os.getpid()}"
        self.containers: List[Container] = []

    def add_container(self, entrypoint, env, out=None):
        self.containers.append(Container(entrypoint, env, out))

    def deploy(self):
        for c in self.containers:
            c.start()

    def is_running(self):
        return any(c.alive for c in self.containers)

    def failed_containers(self):
        return [c for c in self.containers if c.exit_code not in (None, 0)]

    def join(self, timeout=None):
        deadline = None if timeout is None else time.time() + timeout
        for c in self.containers:
            t = None if deadline is None else max(0, deadline - time.time())
            c.wait(t)

    def stop(self, force=False):
        for c in self.containers:
            c.terminate(force=force)

    def exit_codes(self):
        return [c.exit_code for c in self.containers]
