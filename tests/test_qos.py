"""Overload protection & multi-tenant QoS (round 19).

The ISSUE acceptance bars pinned here:

* an overload replay at >= 2x decode capacity with mixed tenants and
  priorities loses and duplicates ZERO tokens, sheds only from the lowest
  eligible class (or an over-quota tenant), and keeps high-priority p99
  TPOT within tolerance of an uncontended baseline;
* the brownout ladder is reversible and EXACT — it un-winds to rung 0,
  surviving greedy requests are byte-identical to the no-brownout oracle,
  and a step-2-capped request's output is an exact prefix of its uncapped
  chain;
* priority preemption rides the pool-dry preempt-resume machinery, so the
  evicted victim's final output is byte-identical to its oracle;
* cancellation and TTL expiry mid-prefill-stream free pages the same step
  and close the trace chain (no orphaned spans), including under FaultPlan
  chaos;
* a dead fleet still expires its held requests (the TTL sweep runs from
  submit(), not only step()).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.resilience import fault_injection as fi
from paddle_tpu.inference.engine import InferenceEngine
from paddle_tpu.inference.fleet import ReplicaFleet, ReplicaStatus
from paddle_tpu.inference.qos import (
    BROWNOUT_STEPS,
    BrownoutConfig,
    BrownoutController,
    QoSConfig,
    QoSPolicy,
    TenantConfig,
    TokenBucket,
    jain_fairness,
    tenant_report,
)
from paddle_tpu.inference.scheduler import (
    ContinuousBatchingScheduler,
    Request,
    SpecDecodeConfig,
)
from paddle_tpu.telemetry import metrics as tm
from paddle_tpu.telemetry import request_trace as rt


@pytest.fixture(scope="module")
def tiny_model():
    from paddle_tpu.models.llama import llama_tiny

    paddle.seed(0)
    m = llama_tiny(num_key_value_heads=2)
    m.eval()
    return m


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    yield
    fi.clear_plan()


@pytest.fixture()
def traced():
    paddle.set_flags({"FLAGS_request_trace": True,
                      "FLAGS_request_trace_sample": 1.0})
    rt.reset()
    yield rt.recorder()
    paddle.set_flags({"FLAGS_request_trace": False})
    rt.reset()


def _engine(model, **kw):
    opts = dict(max_seq_len=64, block_size=8, max_batch=4)
    opts.update(kw)
    return InferenceEngine(model, **opts)


def _greedy_oracle(model, prompt, n):
    cur = list(prompt)
    for _ in range(n):
        with paddle.no_grad():
            lg = model(paddle.to_tensor(np.asarray([cur], np.int64))).numpy()[0, -1]
        cur.append(int(lg.argmax()))
    return cur[len(prompt):]


def _produced(req):
    """Full client-visible output (folds a preemption resume back out)."""
    return req.prompt[req.prompt_len:] + list(req.generated)


def _counter_val(name, **labels):
    fam = tm.default_registry().get(name)
    return fam.labels(**labels).value if fam is not None else 0.0


# ---------------------------------------------------------------------------
# policy units (no model)
# ---------------------------------------------------------------------------

def test_token_bucket_refill_take_and_retry_hint():
    b = TokenBucket(rate=10.0, burst=20.0, now=0.0)
    assert b.try_take(20, now=0.0)          # full burst drains to zero
    assert not b.try_take(1, now=0.0)
    assert b.retry_after(5) == pytest.approx(0.5)
    assert not b.try_take(5, now=0.25)      # only 2.5 refilled
    assert b.try_take(5, now=0.5)           # 5 available exactly
    b2 = TokenBucket(rate=1.0, burst=4.0, now=0.0)
    b2.refill(100.0)
    assert b2.tokens == 4.0                 # refill caps at burst


def test_rate_gate_clamps_oversized_cost_to_burst():
    """A single request costing more than the burst drains the bucket to
    empty instead of being permanently inadmissible."""
    pol = QoSPolicy(QoSConfig(tenants={
        "t": TenantConfig(rate_tokens_per_s=10.0, burst_tokens=20.0)}))
    big = Request(rid=0, prompt=list(range(100)), max_new_tokens=8, tenant="t")
    ok, retry = pol.rate_gate(big, now=0.0)
    assert ok and retry is None
    ok, retry = pol.rate_gate(big, now=0.0)  # bucket now empty
    assert not ok and retry == pytest.approx(2.0)  # 20 clamped tokens @ 10/s


def test_select_strict_priority_then_weighted_fair():
    pol = QoSPolicy(QoSConfig(tenants={
        "a": TenantConfig(weight=2.0), "b": TenantConfig(weight=1.0)}))

    def mk(rid, tenant, priority):
        return Request(rid=rid, prompt=[1] * 4, max_new_tokens=4,
                       tenant=tenant, priority=priority)

    # the lone priority-0 request outranks everything regardless of debt
    waiting = [mk(0, "a", 1), mk(1, "b", 1), mk(2, "b", 0)]
    assert pol.select(waiting) == 2
    # weighted-fair within a class: weight-2 tenant drains ~2x the tokens
    waiting = ([mk(10 + i, "a", 1) for i in range(12)]
               + [mk(30 + i, "b", 1) for i in range(12)])
    took = {"a": 0, "b": 0}
    for _ in range(9):
        i = pol.select(waiting)
        r = waiting.pop(i)
        pol.charge(r)
        took[r.tenant] += 1
    assert took["a"] == 6 and took["b"] == 3


def test_select_single_tenant_reduces_to_fifo():
    """Pre-QoS traffic (one tenant, one class) must dequeue in exactly the
    old FIFO order — preempt-requeue-at-front semantics depend on it."""
    pol = QoSPolicy()
    waiting = [Request(rid=i, prompt=[1], max_new_tokens=2) for i in range(5)]
    for _ in range(5):
        assert pol.select(waiting) == 0
        pol.charge(waiting.pop(0))


def test_idle_tenant_reenters_at_debt_floor():
    """Idle time must not bank credit: a tenant returning after a long
    absence starts at the floor, it does not burst ahead on stale debt."""
    pol = QoSPolicy()

    def mk(rid, tenant):
        return Request(rid=rid, prompt=[1] * 10, max_new_tokens=10, tenant=tenant)

    for i in range(50):  # tenant "busy" accumulates real debt
        pol.charge(mk(i, "busy"))
    waiting = [mk(100, "busy"), mk(101, "fresh"), mk(102, "busy"), mk(103, "fresh")]
    picks = []
    for _ in range(4):
        i = pol.select(waiting)
        r = waiting.pop(i)
        pol.charge(r)
        picks.append(r.tenant)
    # floor lift: strict alternation, not fresh-drains-everything-first
    assert picks == ["busy", "fresh", "busy", "fresh"]


def test_queue_full_victim_rules():
    pol = QoSPolicy(QoSConfig(max_waiting=2))

    def mk(rid, priority, t):
        r = Request(rid=rid, prompt=[1], max_new_tokens=2, priority=priority)
        r.submitted_time = t
        return r

    waiting = [mk(0, 2, 0.0), mk(1, 2, 1.0)]
    assert pol.queue_full(2) and not pol.queue_full(1)
    # equal class: the newcomer sheds (queued requests have waited longer)
    newcomer = mk(2, 2, 2.0)
    assert pol.queue_full_victim(waiting, newcomer) is newcomer
    # strictly outranking newcomer displaces the LATEST lowest-class entry
    high = mk(3, 0, 2.0)
    assert pol.queue_full_victim(waiting, high) is waiting[1]


def test_brownout_ladder_hysteresis_and_degradations():
    cfg = BrownoutConfig(enter_pressure=0.8, exit_pressure=0.5,
                         cooldown_s=1.0, capped_max_new=4, low_priority=2)
    bc = BrownoutController(cfg)
    assert BROWNOUT_STEPS[bc.step] == "normal"
    assert bc.update(0.9, now=0.0) == [("escalate", 1)]
    assert not bc.spec_allowed()
    assert bc.max_new_cap(2) is None          # cap only arms at rung 2
    assert bc.update(0.95, now=0.1) == [("escalate", 2)]
    assert bc.max_new_cap(2) == 4 and bc.max_new_cap(1) is None
    assert not bc.sheds(2)                    # shed only arms at rung 3
    assert bc.update(0.9, now=0.2) == [("escalate", 3)]
    assert bc.sheds(2) and not bc.sheds(0)
    assert bc.update(0.9, now=5.0) == []      # hot: rung 3 is the top
    assert bc.update(0.4, now=5.5) == [("recover", 2)]
    # recovery needs pressure <= exit AND the cooldown since last change
    assert bc.update(0.4, now=6.0) == []      # cooldown not elapsed
    assert bc.update(0.6, now=7.0) == []      # between thresholds: hold
    assert bc.update(0.4, now=7.1) == [("recover", 1)]
    assert bc.update(0.4, now=7.5) == []      # cooldown again
    assert bc.update(0.4, now=8.2) == [("recover", 0)]
    assert bc.spec_allowed() and bc.transitions == 6


def test_jain_fairness_index():
    assert jain_fairness([5.0, 5.0, 5.0]) == 1.0
    assert jain_fairness([9.0, 0.0001, 0.0001]) == pytest.approx(1 / 3, abs=1e-3)
    assert jain_fairness([]) is None
    assert jain_fairness([0.0, 0.0]) is None


def test_deadline_unmeetable_math():
    pol = QoSPolicy()
    r = Request(rid=0, prompt=[1] * 4, max_new_tokens=10, deadline_s=1.0)
    assert not pol.deadline_unmeetable(r, None, 1)          # ewma cold
    assert pol.deadline_unmeetable(r, 0.5, 1)               # 5s floor > 1s
    assert not pol.deadline_unmeetable(r, 0.5, 8)           # spec emit bound
    assert not pol.deadline_unmeetable(
        Request(rid=1, prompt=[1], max_new_tokens=10), 0.5, 1)  # no TTL
    pol2 = QoSPolicy(QoSConfig(deadline_shed=False))
    assert not pol2.deadline_unmeetable(r, 0.5, 1)


def test_config_validation():
    with pytest.raises(ValueError):
        TenantConfig(weight=0.0)
    with pytest.raises(ValueError):
        TenantConfig(rate_tokens_per_s=-1.0)
    with pytest.raises(ValueError):
        BrownoutConfig(enter_pressure=0.5, exit_pressure=0.6)
    with pytest.raises(ValueError):
        BrownoutConfig(enter_pressure=1.5)


# ---------------------------------------------------------------------------
# scheduler admission gates (fake clock, no decode needed)
# ---------------------------------------------------------------------------

def _gated_sched(model, qos, **kw):
    """A scheduler with admission paused (drain) so submit-time gates can
    be tested without any decode running."""
    eng = _engine(model, **kw.pop("engine", {}))
    t = [0.0]
    sched = ContinuousBatchingScheduler(eng, clock=lambda: t[0], qos=qos, **kw)
    sched.drain()
    return sched, t


def test_validation_rejects_name_field_and_bound(tiny_model):
    eng = _engine(tiny_model)
    sched = ContinuousBatchingScheduler(eng)
    before = _counter_val("paddle_tpu_serving_requests_total",
                          event="rejected", reason="context_overflow")
    with pytest.raises(ValueError) as exc:
        sched.submit(Request(rid=7, prompt=list(range(60)), max_new_tokens=10))
    msg = str(exc.value)
    # the message names the offending fields AND the violated bound
    for part in ("request 7", "prompt_len 60", "max_new_tokens 10",
                 "70", "exceeds max_seq_len 64"):
        assert part in msg
    assert _counter_val("paddle_tpu_serving_requests_total",
                        event="rejected", reason="context_overflow") == before + 1

    small = _engine(tiny_model, num_blocks=4)   # 3 usable pages = 24 tokens
    sched2 = ContinuousBatchingScheduler(small)
    before = _counter_val("paddle_tpu_serving_requests_total",
                          event="rejected", reason="pool_capacity")
    with pytest.raises(ValueError) as exc:
        sched2.submit(Request(rid=8, prompt=list(range(20)), max_new_tokens=12))
    msg = str(exc.value)
    for part in ("request 8", "32", "4", "pages", "usable"):
        assert part in msg
    assert _counter_val("paddle_tpu_serving_requests_total",
                        event="rejected", reason="pool_capacity") == before + 1


def test_rate_limit_shed_with_retry_hint(tiny_model):
    qos = QoSPolicy(QoSConfig(tenants={
        "free": TenantConfig(rate_tokens_per_s=10.0, burst_tokens=12.0)}))
    sched, t = _gated_sched(tiny_model, qos)
    before = _counter_val("paddle_tpu_serving_requests_total",
                          event="shed", reason="rate_limit")
    r0 = Request(rid=0, prompt=[1] * 4, max_new_tokens=8, tenant="free")
    sched.submit(r0)                       # cost 12 drains the burst
    assert r0 in sched.waiting
    r1 = Request(rid=1, prompt=[2] * 4, max_new_tokens=8, tenant="free")
    sched.submit(r1)
    assert r1.outcome == "shed" and r1.shed_reason == "rate_limit"
    assert r1.retry_after_s == pytest.approx(1.2)   # 12 tokens @ 10/s
    assert r1 in sched.finished and r1 not in sched.waiting
    assert sched.shed_total == 1 and qos.shed_counts == {"rate_limit": 1}
    assert _counter_val("paddle_tpu_serving_requests_total",
                        event="shed", reason="rate_limit") == before + 1
    t[0] = 1.3                             # bucket refilled past the cost
    r2 = Request(rid=2, prompt=[3] * 4, max_new_tokens=8, tenant="free")
    sched.submit(r2)
    assert r2 in sched.waiting


def test_bounded_queue_overflow_and_priority_displacement(tiny_model):
    qos = QoSPolicy(QoSConfig(max_waiting=2))
    sched, t = _gated_sched(tiny_model, qos)
    r0 = Request(rid=0, prompt=[1] * 4, max_new_tokens=4, priority=2)
    t[0] = 0.1
    sched.submit(r0)
    r1 = Request(rid=1, prompt=[2] * 4, max_new_tokens=4, priority=2)
    t[0] = 0.2
    sched.submit(r1)
    # equal class at a full line: the NEWCOMER sheds
    r2 = Request(rid=2, prompt=[3] * 4, max_new_tokens=4, priority=2)
    t[0] = 0.3
    sched.submit(r2)
    assert r2.outcome == "shed" and r2.shed_reason == "queue_full"
    assert sched.waiting == [r0, r1]
    # a strictly-outranking newcomer displaces the latest lowest-class entry
    r3 = Request(rid=3, prompt=[4] * 4, max_new_tokens=4, priority=0)
    t[0] = 0.4
    sched.submit(r3)
    assert r1.outcome == "shed" and r1.shed_reason == "queue_full"
    assert sched.waiting == [r0, r3] and r3.outcome is None
    assert sched.shed_total == 2


def test_queue_wait_bound_sheds_stale_work(tiny_model):
    qos = QoSPolicy(QoSConfig(max_queue_wait_s=1.0))
    sched, t = _gated_sched(tiny_model, qos)
    r0 = Request(rid=0, prompt=[1] * 4, max_new_tokens=4)
    sched.submit(r0)
    t[0] = 0.5
    sched.step()
    assert r0 in sched.waiting             # within the bound
    t[0] = 1.6
    sched.step()
    assert r0.outcome == "shed" and r0.shed_reason == "queue_wait"
    assert sched.waiting == []


def test_deadline_unmeetable_shed_at_submit(tiny_model):
    sched, t = _gated_sched(tiny_model, QoSPolicy())
    sched.ewma_step_s = 0.5                # warm drain estimate: 0.5 s/step
    r0 = Request(rid=0, prompt=[1] * 4, max_new_tokens=10, deadline_s=1.0)
    sched.submit(r0)                       # needs >= 5 s, TTL is 1 s
    assert r0.outcome == "shed" and r0.shed_reason == "deadline_unmeetable"
    assert r0.retry_after_s is None        # provably unmeetable: no hint
    r1 = Request(rid=1, prompt=[1] * 4, max_new_tokens=10, deadline_s=30.0)
    sched.submit(r1)
    assert r1 in sched.waiting


# ---------------------------------------------------------------------------
# priority preemption (exact-output bar)
# ---------------------------------------------------------------------------

def test_priority_preemption_exact_output(tiny_model):
    eng = _engine(tiny_model, max_batch=2)
    sched = ContinuousBatchingScheduler(eng, qos=QoSPolicy())
    rng = np.random.RandomState(3)
    low = [Request(rid=i, prompt=rng.randint(0, 1024, (6,)).tolist(),
                   max_new_tokens=16, priority=2) for i in range(2)]
    for r in low:
        sched.submit(r)
    for _ in range(3):
        sched.step()
    assert len(sched.running) == 2
    before = _counter_val("paddle_tpu_serving_requests_total",
                          event="preempted", reason="priority")
    high = Request(rid=9, prompt=rng.randint(0, 1024, (5,)).tolist(),
                   max_new_tokens=8, priority=0)
    sched.submit(high)
    sched.step()                           # slots full -> evict one low
    assert high in sched.running
    assert _counter_val("paddle_tpu_serving_requests_total",
                        event="preempted", reason="priority") == before + 1
    while not sched.idle():
        sched.step()
    victims = [r for r in low if r.preemptions > 0]
    assert len(victims) == 1 and sched.preempted_total == 1
    # the exact-output bar: EVERY request (the resumed victim included)
    # matches its full-forward greedy oracle byte for byte
    for r in low + [high]:
        assert r.outcome == "completed"
        assert _produced(r) == _greedy_oracle(
            tiny_model, r.prompt[:r.prompt_len], r.max_new_tokens), r.rid
    assert eng.pool.used() == 0


def test_pool_dry_preemption_order_unchanged_without_qos(tiny_model):
    """Equal-priority traffic through a QoS scheduler preempts the exact
    victim the pre-QoS order would have picked (youngest, still-streaming
    first) — pinned so the QoS layer cannot silently reorder recovery."""
    eng = _engine(tiny_model, num_blocks=8)
    sched = ContinuousBatchingScheduler(eng, qos=QoSPolicy())
    rng = np.random.RandomState(5)
    reqs = [Request(rid=i, prompt=rng.randint(0, 1024, (8,)).tolist(),
                    max_new_tokens=12) for i in range(3)]
    for r in reqs:
        sched.submit(r)
    while not sched.idle():
        sched.step()
    for r in reqs:
        assert _produced(r) == _greedy_oracle(
            tiny_model, r.prompt[:r.prompt_len], r.max_new_tokens), r.rid


# ---------------------------------------------------------------------------
# brownout ladder through the scheduler (reversible + exact)
# ---------------------------------------------------------------------------

def test_brownout_escalates_degrades_and_unwinds_exactly(tiny_model, traced):
    qos = QoSPolicy(QoSConfig(brownout=BrownoutConfig(
        enter_pressure=0.8, exit_pressure=0.5, cooldown_s=1.0,
        capped_max_new=4, low_priority=2)))
    eng = _engine(tiny_model)
    t = [0.0]
    sched = ContinuousBatchingScheduler(
        eng, clock=lambda: t[0], qos=qos,
        spec_decode=SpecDecodeConfig(draft_len=3, ngram=2),
    )
    # spec-friendly repetitive prompt: would draft aggressively at rung 0
    survivor = Request(rid=0, prompt=[5, 6, 5, 6, 5, 6, 5, 6],
                       max_new_tokens=10, priority=0)
    sched.submit(survivor)
    qos.note_slo_burn(1.0)                 # force pressure to 1.0
    t[0] = 1.0
    sched.step()                           # rung 1: spec off
    assert qos.brownout.step == 1 and sched.spec is not None  # restored
    t[0] = 2.0
    sched.step()                           # rung 2: cap arms
    capped = Request(rid=1, prompt=[7] * 6, max_new_tokens=12, priority=2)
    sched.submit(capped)
    t[0] = 3.0
    sched.step()                           # rung 3 + capped admission
    assert qos.brownout.step == 3
    assert capped.max_new_tokens == 4 and capped.qos_orig_max_new == 12
    shed = Request(rid=2, prompt=[8] * 4, max_new_tokens=4, priority=2)
    sched.submit(shed)                     # rung 3 refuses low-class work
    assert shed.outcome == "shed" and shed.shed_reason == "brownout"
    assert shed.retry_after_s == pytest.approx(1.0)  # the recovery cooldown
    vip = Request(rid=3, prompt=[9] * 4, max_new_tokens=4, priority=0)
    sched.submit(vip)                      # high class still admitted
    assert vip in sched.waiting

    # recovery: pressure off, cooldown elapsing -> one rung per step
    qos.note_slo_burn(0.0)
    steps_at = []
    while not sched.idle() or qos.brownout.step > 0:
        t[0] += 2.0
        sched.step()
        steps_at.append(qos.brownout.step)
        assert len(steps_at) < 60, "ladder failed to unwind"
    assert qos.brownout.step == 0          # fully un-wound
    assert steps_at[:3] == [2, 1, 0]       # one rung per cooled reading
    fam = tm.default_registry().get("paddle_tpu_qos_brownout_step")
    assert fam is not None and fam.value == 0.0
    trans = tm.default_registry().get("paddle_tpu_qos_brownout_transitions_total")
    assert trans.labels(direction="escalate", to="shed_low").value >= 1
    assert trans.labels(direction="recover", to="normal").value >= 1

    # EXACTNESS: the high-priority survivor is byte-identical to the
    # no-brownout oracle (spec-off changes pacing, never tokens); the
    # capped request's 4 tokens are an exact prefix of its uncapped chain
    assert survivor.drafted == 0           # spec really was off
    assert _produced(survivor) == _greedy_oracle(tiny_model, survivor.prompt[:8], 10)
    assert _produced(vip) == _greedy_oracle(tiny_model, [9] * 4, 4)
    got = _produced(capped)
    assert len(got) == 4
    assert got == _greedy_oracle(tiny_model, [7] * 6, 12)[:4]
    # every brownout transition left a qos-lane trace event
    qos_events = [r for r in rt.recorder().records()
                  if r["lane"] == "qos" and r["name"] == "brownout"]
    assert len(qos_events) == qos.brownout.transitions
    assert {e["attrs"]["rung"] for e in qos_events} >= {"spec_off", "normal"}


# ---------------------------------------------------------------------------
# the overload replay acceptance bar
# ---------------------------------------------------------------------------

def test_overload_replay_zero_loss_fair_sheds_bounded_p99(tiny_model):
    """>= 2x capacity, mixed tenants and priorities: nothing lost, nothing
    duplicated, sheds only from the lowest class present or the over-quota
    tenant, and the priority-0 class's p99 TPOT stays within tolerance of
    an uncontended run of the same requests."""
    rng = np.random.RandomState(11)
    specs = []                             # (rid, tenant, priority, prompt)
    for rid, tenant, priority in (
        [(i, "gold", 0) for i in range(4)]
        + [(10 + i, "silver", 1) for i in range(6)]
        + [(20 + i, "bronze", 2) for i in range(6)]
        + [(30 + i, "free", 2) for i in range(4)]
    ):
        specs.append((rid, tenant, priority,
                      rng.randint(0, 1024, (int(rng.randint(4, 10)),)).tolist()))

    def build(only_tenant=None):
        return [Request(rid=rid, prompt=list(p), max_new_tokens=6,
                        tenant=t, priority=pr)
                for rid, t, pr, p in specs
                if only_tenant is None or t == only_tenant]

    # uncontended baseline: the gold class alone on a fresh engine
    base = ContinuousBatchingScheduler(_engine(tiny_model))
    base_gold = build("gold")
    for r in base_gold:
        base.submit(r)
    while not base.idle():
        base.step()
    base_tpots = sorted(r.tpot() for r in base_gold if r.tpot() is not None)

    cfg = QoSConfig(
        tenants={
            "gold": TenantConfig(weight=4.0),
            "silver": TenantConfig(weight=2.0),
            "bronze": TenantConfig(weight=1.0),
            "free": TenantConfig(weight=1.0, rate_tokens_per_s=10.0,
                                 burst_tokens=24.0),
        },
        # no max_waiting: sheds can then ONLY come from the rate limit or
        # the brownout ladder (both lowest-eligible by construction)
        brownout=BrownoutConfig(enter_pressure=0.95, exit_pressure=0.5,
                                cooldown_s=0.05, capped_max_new=4,
                                low_priority=2),
    )
    qos = QoSPolicy(cfg)
    eng = _engine(tiny_model)
    sched = ContinuousBatchingScheduler(eng, qos=qos)
    reqs = build()                         # 20 requests, 4 decode slots
    gold = [r for r in reqs if r.tenant == "gold"]
    order = list(reqs)
    rng.shuffle(order)
    for r in order:
        sched.submit(r)
    steps = 0
    while not sched.idle():
        sched.step()
        steps += 1
        assert steps < 2000
    assert eng.pool.used() == 0

    # --- zero loss / zero duplication: every request terminal exactly once
    assert len(sched.finished) == len(reqs)
    assert sorted(r.rid for r in sched.finished) == sorted(r.rid for r in reqs)
    for r in reqs:
        assert r.outcome in ("completed", "shed"), (r.rid, r.outcome)
        if r.outcome == "completed":
            got = _produced(r)
            want = _greedy_oracle(tiny_model, r.prompt[:r.prompt_len],
                                  len(got))
            assert got == want, r.rid      # exact prefix, no dup/lost tokens
            assert len(got) in (r.max_new_tokens, r.qos_orig_max_new or r.max_new_tokens)

    # --- every shed is from the lowest class present or the over-quota tenant
    sheds = [r for r in reqs if r.outcome == "shed"]
    for r in sheds:
        if r.shed_reason == "rate_limit":
            assert r.tenant == "free"
        else:
            assert r.priority == 2, (r.rid, r.shed_reason)
    assert all(r.outcome == "completed" for r in gold)
    assert sched.shed_total == len(sheds)
    assert sum(qos.shed_counts.values()) == len(sheds)

    # --- fairness + per-tenant report over the drained replay
    rep = tenant_report(sched.finished, cfg)
    assert set(rep["tenants"]) == {"gold", "silver", "bronze", "free"}
    assert rep["tenants"]["gold"]["completed"] == 4
    if rep["fairness_index"] is not None:
        assert 0.0 < rep["fairness_index"] <= 1.0

    # --- the p99-TPOT bar: contended gold within tolerance of uncontended.
    # Both runs decode gold in (at most) full batches of 4 on this engine;
    # the generous envelope absorbs CI wall-clock noise, while still
    # failing if priority admission stops protecting the gold class.
    over_tpots = sorted(r.tpot() for r in gold if r.tpot() is not None)
    if base_tpots and over_tpots:
        assert over_tpots[-1] <= 5.0 * base_tpots[-1] + 0.05


# ---------------------------------------------------------------------------
# cancellation / TTL mid-prefill-stream (trace + page hygiene, chaos)
# ---------------------------------------------------------------------------

def test_cancel_mid_prefill_stream_frees_pages_and_closes_trace(
        tiny_model, traced):
    eng = _engine(tiny_model)
    sched = ContinuousBatchingScheduler(eng)
    anchor = Request(rid=0, prompt=[1, 2, 3, 4], max_new_tokens=12)
    sched.submit(anchor)
    sched.step()                           # anchor running: B must STREAM
    streamer = Request(rid=1, prompt=list(range(10, 50)), max_new_tokens=4)
    sched.submit(streamer)
    sched.step()
    assert streamer in sched.running
    assert streamer.cursor < len(streamer.prompt)   # genuinely mid-stream
    used_before = eng.pool.used()
    assert sched.cancel(1)
    # pages freed the SAME step, not at the next harvest
    assert eng.pool.used() < used_before
    assert streamer.pages == [] and streamer.outcome == "cancelled"
    while not sched.idle():
        sched.step()
    finishes = {r["rid"]: r["attrs"]["outcome"]
                for r in rt.recorder().records()
                if r["type"] == "event" and r["name"] == "finish"}
    assert finishes == {0: "completed", 1: "cancelled"}
    assert rt.recorder().open_spans() == []
    assert eng.pool.used() == 0


def test_ttl_expiry_mid_prefill_stream(tiny_model, traced):
    eng = _engine(tiny_model)
    t = [0.0]
    sched = ContinuousBatchingScheduler(eng, clock=lambda: t[0])
    anchor = Request(rid=0, prompt=[1, 2, 3], max_new_tokens=8)
    sched.submit(anchor)
    sched.step()
    doomed = Request(rid=1, prompt=list(range(100, 140)), max_new_tokens=4,
                     deadline_s=0.5)
    sched.submit(doomed)
    sched.step()
    assert doomed in sched.running and doomed.cursor < len(doomed.prompt)
    t[0] = 1.0                             # past the TTL mid-stream
    used_before = eng.pool.used()
    sched.step()                           # expiry sweep runs first
    assert doomed.outcome == "expired" and doomed.pages == []
    assert eng.pool.used() < used_before
    while not sched.idle():
        sched.step()
    finishes = {r["rid"]: r["attrs"]["outcome"]
                for r in rt.recorder().records()
                if r["type"] == "event" and r["name"] == "finish"}
    assert finishes == {0: "completed", 1: "expired"}
    assert rt.recorder().open_spans() == []
    assert eng.pool.used() == 0


def test_no_orphaned_spans_under_fleet_chaos(tiny_model, traced):
    """FaultPlan kills a replica while work (including a mid-stream TTL
    request) is in flight: every request still reaches exactly one terminal
    outcome and the trace chain closes — zero orphaned spans."""
    engines = [_engine(tiny_model, max_batch=2) for _ in range(2)]
    fleet = ReplicaFleet(engines)
    rng = np.random.RandomState(7)
    reqs = [Request(rid=i, prompt=rng.randint(0, 1024, (6,)).tolist(),
                    max_new_tokens=6) for i in range(4)]
    reqs.append(Request(rid=4, prompt=list(range(200, 240)),
                        max_new_tokens=4, deadline_s=0.15))
    for r in reqs:
        fleet.submit(r)
    fleet.step()
    fi.install_plan(fi.FaultPlan().add("fleet.replica_step.1", "fail", times=2))
    steps = 0
    while not fleet.idle():
        fleet.step()
        steps += 1
        assert steps < 500
    fi.clear_plan()
    outcomes = {r.rid: r.outcome for r in reqs}
    assert all(o in ("completed", "expired") for o in outcomes.values())
    assert len(fleet.finished) == len(reqs)          # exactly-once terminal
    assert rt.recorder().open_spans() == []
    assert all(e.pool.used() == 0 for e in engines)


# ---------------------------------------------------------------------------
# fleet: held-queue TTL on submit (the dead-fleet fix) + bounded holds
# ---------------------------------------------------------------------------

def test_dead_fleet_expires_held_requests_on_submit(tiny_model):
    eng = _engine(tiny_model)
    t = [0.0]
    fleet = ReplicaFleet([eng], clock=lambda: t[0])
    fleet.replicas[0].status = ReplicaStatus.DOWN
    before = _counter_val("paddle_tpu_serving_requests_total",
                          event="expired", reason="")
    doomed = Request(rid=0, prompt=[1, 2, 3], max_new_tokens=4, deadline_s=1.0)
    fleet.submit(doomed)
    assert doomed in fleet._pending
    t[0] = 2.0
    # the fleet is DEAD — step() would raise NoHealthyReplica and callers
    # stop stepping; the sweep MUST run from submit() or `doomed` is held
    # past its TTL forever
    other = Request(rid=1, prompt=[4, 5], max_new_tokens=4)
    fleet.submit(other)
    assert doomed not in fleet._pending
    assert doomed.outcome == "expired" and doomed in fleet.finished
    assert _counter_val("paddle_tpu_serving_requests_total",
                        event="expired", reason="") == before + 1
    assert other in fleet._pending


def test_dead_fleet_held_queue_is_bounded(tiny_model):
    eng = _engine(tiny_model)
    t = [0.0]
    qos = QoSPolicy(QoSConfig(max_waiting=2))
    fleet = ReplicaFleet([eng], clock=lambda: t[0], qos=qos)
    fleet.replicas[0].status = ReplicaStatus.DOWN
    low = [Request(rid=i, prompt=[1] * 3, max_new_tokens=4, priority=2)
           for i in range(2)]
    for r in low:
        t[0] += 0.1
        fleet.submit(r)
    assert len(fleet._pending) == 2
    # equal class: the newcomer sheds; the line never grows past the bound
    extra = Request(rid=5, prompt=[2] * 3, max_new_tokens=4, priority=2)
    t[0] += 0.1
    fleet.submit(extra)
    assert extra.outcome == "shed" and extra.shed_reason == "queue_full"
    assert len(fleet._pending) == 2
    # an outranking newcomer displaces the latest low-class hold
    vip = Request(rid=6, prompt=[3] * 3, max_new_tokens=4, priority=0)
    t[0] += 0.1
    fleet.submit(vip)
    assert low[1].outcome == "shed" and vip in fleet._pending
    assert len(fleet._pending) == 2
    assert fleet.shed_total == 2
    # zero-loss accounting still balances: all 4 submits are either held
    # or terminally shed into fleet.finished
    assert len(fleet._pending) + len(fleet.finished) == 4


def test_fleet_shares_one_policy_across_replicas(tiny_model):
    """The rate bucket is FLEET-wide: a tenant cannot multiply its quota
    by the replica count."""
    engines = [_engine(tiny_model) for _ in range(2)]
    t = [0.0]
    qos = QoSPolicy(QoSConfig(tenants={
        "free": TenantConfig(rate_tokens_per_s=10.0, burst_tokens=12.0)}))
    fleet = ReplicaFleet(engines, clock=lambda: t[0], qos=qos)
    for rep in fleet.replicas:
        assert rep.sched.qos is qos
        rep.sched.drain()                  # hold work in the queues
    r0 = Request(rid=0, prompt=[1] * 4, max_new_tokens=8, tenant="free")
    fleet.submit(r0)                       # drains the shared bucket
    r1 = Request(rid=1, prompt=[2] * 4, max_new_tokens=8, tenant="free")
    fleet.submit(r1)                       # whichever replica: same bucket
    assert r1.outcome == "shed" and r1.shed_reason == "rate_limit"
    assert fleet.shed_total == 1


def test_degraded_fleet_floors_brownout_pressure(tiny_model):
    """Round 21: a tiered fleet knocked off its disaggregated rung (decode
    tier dead -> monolithic) marks the shared QoS policy degraded, which
    FLOORS the pressure reading at degraded_pressure_floor — the brownout
    ladder escalates on an otherwise idle half-fleet instead of waiting
    for its queues to back up. Recovery (revive -> re-split) clears it."""
    qos = QoSPolicy(QoSConfig(brownout=BrownoutConfig(
        enter_pressure=0.8, exit_pressure=0.5, cooldown_s=0.0,
        degraded_pressure_floor=0.9)))
    assert qos.pressure(0.0, 0.0) == 0.0        # floor off while split
    fi.install_plan(fi.FaultPlan().add("fleet.replica_step.1", "fail",
                                       times=None))
    fleet = ReplicaFleet([_engine(tiny_model), _engine(tiny_model)],
                         tiers=["prefill", "decode"], breaker_threshold=1,
                         qos=qos)
    try:
        out = fleet.generate([[1, 2, 3, 4]], max_new_tokens=4)
    finally:
        fi.clear_plan()
    assert out == [_greedy_oracle(tiny_model, [1, 2, 3, 4], 4)]
    assert fleet.mode() == "monolithic"
    assert qos.degraded
    assert qos.pressure(0.0, 0.0) == 0.9        # floored while degraded
    # the prefill replica's ticks fed the floored reading into the ladder
    assert qos.brownout.step >= 1
    fleet.revive(1)
    assert fleet.mode() == "disaggregated"
    assert not qos.degraded                     # re-split clears the floor
    assert qos.pressure(0.0, 0.0) == 0.0
    with pytest.raises(ValueError):
        BrownoutConfig(degraded_pressure_floor=1.5)


# ---------------------------------------------------------------------------
# predictor wiring
# ---------------------------------------------------------------------------

def test_llm_predictor_qos_wiring(tiny_model, tmp_path):
    import paddle_tpu.inference as inf

    prefix = str(tmp_path / "llm")
    inf.save_llm(tiny_model, prefix)
    cfg = inf.Config(prefix)
    cfg.enable_llm_engine(
        max_new_tokens=4, max_seq_len=32, block_size=8, max_batch=2,
        prefill_buckets=(16,), decode_batch_buckets=(2,),
        qos=QoSConfig(max_waiting=16),
    )
    pred = inf.create_predictor(cfg)
    # QoS always runs through a fleet backend, even at one replica, so the
    # policy state (buckets/debt/ladder) is shared and observable
    assert pred.fleet() is not None and len(pred.fleet().replicas) == 1
    assert isinstance(pred.qos(), QoSPolicy)
    assert pred.fleet().qos is pred.qos()
    rng = np.random.RandomState(9)
    ids = rng.randint(0, 1024, (1, 10)).astype(np.int64)
    (out,) = pred.run([ids, np.array([10])])
    m2 = inf.load_llm(prefix)
    assert list(out[0]) == _greedy_oracle(m2, list(ids[0]), 4)
    assert pred.qos().brownout.step == 0
