"""r3 loss-surface completion vs the torch oracle (namespace parity audit;
reference python/paddle/nn/functional/loss.py + nn/layer/loss.py)."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

R = np.random.RandomState(3)
X = R.randn(5, 7).astype("float32")
Y = R.randn(5, 7).astype("float32")
BIN = (R.rand(5, 7) > 0.5).astype("float32")
SGN = np.where(R.rand(5, 7) > 0.5, 1.0, -1.0).astype("float32")
LBL = R.randint(0, 7, (5,)).astype("int64")


def _chk(ours, theirs, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(float(ours.numpy()), float(theirs.numpy()), rtol=rtol, atol=atol)


@pytest.mark.parametrize("reduction", ["mean", "sum"])
def test_gaussian_nll_loss(reduction):
    var = (np.abs(Y) + 0.1).astype("float32")
    for full in (False, True):
        ours = F.gaussian_nll_loss(paddle.to_tensor(X), paddle.to_tensor(Y),
                                   paddle.to_tensor(var), full=full, reduction=reduction)
        ref = torch.nn.functional.gaussian_nll_loss(
            torch.from_numpy(X), torch.from_numpy(Y), torch.from_numpy(var),
            full=full, reduction=reduction)
        _chk(ours, ref)


@pytest.mark.parametrize("log_input", [True, False])
def test_poisson_nll_loss(log_input):
    tgt = np.abs(Y).astype("float32") + 0.5
    for full in (False, True):
        ours = F.poisson_nll_loss(paddle.to_tensor(X), paddle.to_tensor(tgt),
                                  log_input=log_input, full=full)
        ref = torch.nn.functional.poisson_nll_loss(
            torch.from_numpy(X), torch.from_numpy(tgt), log_input=log_input, full=full)
        _chk(ours, ref)


def test_soft_margin_loss():
    ours = F.soft_margin_loss(paddle.to_tensor(X), paddle.to_tensor(SGN))
    ref = torch.nn.functional.soft_margin_loss(torch.from_numpy(X), torch.from_numpy(SGN))
    _chk(ours, ref)
    layer = nn.SoftMarginLoss(reduction="sum")
    ours2 = layer(paddle.to_tensor(X), paddle.to_tensor(SGN))
    ref2 = torch.nn.functional.soft_margin_loss(torch.from_numpy(X), torch.from_numpy(SGN), reduction="sum")
    _chk(ours2, ref2)


def test_multi_label_soft_margin_loss():
    ours = F.multi_label_soft_margin_loss(paddle.to_tensor(X), paddle.to_tensor(BIN))
    ref = torch.nn.functional.multilabel_soft_margin_loss(torch.from_numpy(X), torch.from_numpy(BIN))
    _chk(ours, ref)


@pytest.mark.parametrize("p", [1, 2])
def test_multi_margin_loss(p):
    ours = F.multi_margin_loss(paddle.to_tensor(X), paddle.to_tensor(LBL), p=p)
    ref = torch.nn.functional.multi_margin_loss(torch.from_numpy(X), torch.from_numpy(LBL), p=p)
    _chk(ours, ref)


def test_pairwise_distance():
    ours = F.pairwise_distance(paddle.to_tensor(X), paddle.to_tensor(Y))
    ref = torch.nn.functional.pairwise_distance(torch.from_numpy(X), torch.from_numpy(Y))
    np.testing.assert_allclose(ours.numpy(), ref.numpy(), rtol=1e-4, atol=1e-5)


def test_triplet_margin_with_distance_loss():
    a, pos, neg = X, Y, R.randn(5, 7).astype("float32")
    ours = F.triplet_margin_with_distance_loss(
        paddle.to_tensor(a), paddle.to_tensor(pos), paddle.to_tensor(neg))
    ref = torch.nn.functional.triplet_margin_with_distance_loss(
        torch.from_numpy(a), torch.from_numpy(pos), torch.from_numpy(neg))
    _chk(ours, ref)
    # custom distance + swap, against a hand-rolled oracle
    ours2 = F.triplet_margin_with_distance_loss(
        paddle.to_tensor(a), paddle.to_tensor(pos), paddle.to_tensor(neg),
        distance_function=lambda u, v: ((u - v) ** 2).sum(-1), swap=True)
    dp = ((a - pos) ** 2).sum(-1)
    dn = np.minimum(((a - neg) ** 2).sum(-1), ((pos - neg) ** 2).sum(-1))
    want = np.maximum(dp - dn + 1.0, 0).mean()
    np.testing.assert_allclose(float(ours2.numpy()), want, rtol=1e-4)


def test_loss_layers_smoke_and_grad():
    lay = nn.GaussianNLLLoss()
    x = paddle.to_tensor(X)
    x.stop_gradient = False
    var = paddle.to_tensor((np.abs(Y) + 0.1).astype("float32"))
    loss = lay(x, paddle.to_tensor(Y), var)
    loss.backward()
    assert x.grad is not None and np.isfinite(x.grad.numpy()).all()

    for layer, args in [
        (nn.PoissonNLLLoss(), (paddle.to_tensor(X), paddle.to_tensor(np.abs(Y) + 0.5))),
        (nn.HingeEmbeddingLoss(), (paddle.to_tensor(X), paddle.to_tensor(SGN))),
        (nn.CosineEmbeddingLoss(), (paddle.to_tensor(X), paddle.to_tensor(Y), paddle.to_tensor(SGN[:, 0]))),
        (nn.MultiLabelSoftMarginLoss(), (paddle.to_tensor(X), paddle.to_tensor(BIN))),
        (nn.MultiMarginLoss(), (paddle.to_tensor(X), paddle.to_tensor(LBL))),
        (nn.TripletMarginWithDistanceLoss(), (paddle.to_tensor(X), paddle.to_tensor(Y), paddle.to_tensor(Y + 1))),
    ]:
        out = layer(*args)
        assert np.isfinite(float(out.numpy()))


def test_hsigmoid_rnnt_layers():
    paddle.seed(0)
    lay = nn.HSigmoidLoss(feature_size=7, num_classes=6)
    assert tuple(lay.weight.shape) == (5, 7) and tuple(lay.bias.shape) == (5, 1)
    out = lay(paddle.to_tensor(X), paddle.to_tensor(LBL % 6))
    assert out.shape[0] == 5 and np.isfinite(out.numpy()).all()

    B, T, U, V = 2, 4, 3, 5
    logits = R.randn(B, T, U, V).astype("float32")
    labels = R.randint(1, V, (B, U - 1)).astype("int32")
    lay2 = nn.RNNTLoss()
    loss = lay2(paddle.to_tensor(logits), paddle.to_tensor(labels),
                paddle.to_tensor(np.full((B,), T, "int32")),
                paddle.to_tensor(np.full((B,), U - 1, "int32")))
    assert np.isfinite(float(loss.numpy()))


def test_pool_unpool_layers_roundtrip():
    x = paddle.to_tensor(R.randn(1, 2, 6, 6).astype("float32"))
    pooled, idx = F.max_pool2d(x, 2, 2, return_mask=True)
    unpool = nn.MaxUnPool2D(2, 2)
    rec = unpool(pooled, idx)
    assert tuple(rec.shape) == (1, 2, 6, 6)
    # every pooled max lands back; everything else zero
    assert np.allclose(np.sort(rec.numpy()[rec.numpy() != 0]), np.sort(pooled.numpy().ravel()))

    fr = nn.FractionalMaxPool2D(output_size=3)
    out = fr(x)
    assert tuple(out.shape) == (1, 2, 3, 3)


def test_softmax2d_unflatten_layers():
    x = paddle.to_tensor(R.randn(2, 3, 4, 5).astype("float32"))
    out = nn.Softmax2D()(x)
    np.testing.assert_allclose(out.numpy().sum(1), np.ones((2, 4, 5)), rtol=1e-5)
    with pytest.raises(ValueError):
        nn.Softmax2D()(paddle.to_tensor(X))

    u = nn.Unflatten(1, [2, 2])(paddle.to_tensor(R.randn(3, 4).astype("float32")))
    assert tuple(u.shape) == (3, 2, 2)


def test_inplace_functional_activations():
    x = paddle.to_tensor(X.copy())
    r = F.tanh_(x)
    assert r is x
    np.testing.assert_allclose(x.numpy(), np.tanh(X), rtol=1e-6)
    x2 = paddle.to_tensor(X.copy())
    F.leaky_relu_(x2, 0.1)
    np.testing.assert_allclose(x2.numpy(), np.where(X > 0, X, 0.1 * X), rtol=1e-6)
    x3 = paddle.to_tensor(X.copy())
    F.hardtanh_(x3)
    np.testing.assert_allclose(x3.numpy(), np.clip(X, -1, 1), rtol=1e-6)
    x4 = paddle.to_tensor(X.copy())
    F.thresholded_relu_(x4, 0.5)
    np.testing.assert_allclose(x4.numpy(), np.where(X > 0.5, X, 0.0), rtol=1e-6)


def test_sparse_attention_vs_dense_oracle():
    B, H, S, D = 1, 2, 6, 4
    q = R.randn(B, H, S, D).astype("float32")
    k = R.randn(B, H, S, D).astype("float32")
    v = R.randn(B, H, S, D).astype("float32")
    # banded CSR: row i attends to {i-1, i}
    offs, cols = [], []
    for h in range(H):
        off, col = [0], []
        for i in range(S):
            cs = [j for j in (i - 1, i) if j >= 0]
            col += cs
            off.append(len(col))
        offs.append(off)
        cols.append(col)
    offs = np.asarray([offs], np.int32)
    cols = np.asarray([cols], np.int32)

    out = F.sparse_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(offs), paddle.to_tensor(cols)).numpy()

    for h in range(H):
        lg = q[0, h] @ k[0, h].T / np.sqrt(D)
        mask = np.zeros((S, S), bool)
        for i in range(S):
            for j in (i - 1, i):
                if j >= 0:
                    mask[i, j] = True
        lg = np.where(mask, lg, -np.inf)
        p = np.exp(lg - lg.max(-1, keepdims=True)); p /= p.sum(-1, keepdims=True)
        np.testing.assert_allclose(out[0, h], p @ v[0, h], rtol=2e-4, atol=2e-5)


def test_flash_attention_with_sparse_mask_semantics():
    B, S, H, D = 1, 5, 2, 4
    q = R.randn(B, S, H, D).astype("float32")
    k = R.randn(B, S, H, D).astype("float32")
    v = R.randn(B, S, H, D).astype("float32")
    # column j visible to rows < start[j]
    start = np.asarray([[[3, 4, 5, 2, 5], [5, 5, 1, 5, 5]]], np.int32)  # [B,H,S]
    out = F.flash_attention_with_sparse_mask(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(start)).numpy()
    for h in range(2):
        lg = q[0, :, h] @ k[0, :, h].T / np.sqrt(D)
        keep = np.arange(S)[:, None] < start[0, h][None, :]
        lg = np.where(keep, lg, -np.inf)
        with np.errstate(invalid="ignore"):
            p = np.exp(lg - lg.max(-1, keepdims=True))
            p = np.nan_to_num(p / p.sum(-1, keepdims=True))
        np.testing.assert_allclose(out[0, :, h], p @ v[0, :, h], rtol=2e-4, atol=2e-5)
