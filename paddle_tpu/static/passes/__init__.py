"""paddle_tpu.static.passes — graph pass & fusion framework.

The PIR/CINN analogue over the recorded Program (PAPER.md L2a-L2c):
`PassManager` runs an ordered, flag-gated (`FLAGS_program_passes`, default
on) pipeline of analysis-backed rewrites before `Executor._compile` and
program-export lowering, with `verify()` re-run after every rewriting
pass and `FLAGS_print_after_pass` to_text() diffs on demand. Patterns are
DRR-style declarative sub-DAG specs (drr.py) over ProgramGraph def-use
chains; replacements are single fused ops.

Default pipeline order (import order below defines it):
  1. dead_op_elimination        every compiled signature ships dead-op-free
  2. constant_fold_scalars      scalar lit-only ops fold to literals
  3. redundant_cast_reshape_elim identity casts/reshapes forward through
  4. fuse_attention             rope+sdpa / matmul-softmax chain -> flash
  5. fuse_norm_matmul           rms/layer_norm -> linear/matmul epilogue
  6. fuse_moe                   MoE dispatch -> expert FFN -> combine collapse
  7. fuse_bias_dropout_residual add -> dropout -> add collapse

Custom passes: subclass ProgramPass, decorate with @register_pass (use
`before="fuse_attention"` to insert mid-pipeline), and every later
Executor compile-miss runs it. `run_default_pipeline(program, ...)`
rewrites a CLONE and returns (rewritten_program, PipelineResult) — the
caller's Program is never mutated.
"""
from .pass_base import (  # noqa: F401
    PassContext,
    PassManager,
    PassStats,
    PipelineResult,
    ProgramPass,
    default_pipeline,
    get_pass,
    pipeline_enabled,
    register_pass,
)
from .drr import (  # noqa: F401
    Match,
    OpPat,
    Pattern,
    apply_matches,
    build_cluster_instr,
    find_matches,
)

# pipeline passes, registered in canonical order
from .dce_pass import DeadOpEliminationPass, eliminate_dead_ops  # noqa: F401
from .canonicalize import (  # noqa: F401
    ConstantFoldScalarsPass,
    RedundantCastReshapeElimPass,
)
from .fusion import (  # noqa: F401
    FuseAttentionPass,
    FuseBiasDropoutResidualPass,
    FuseMoEDispatchCombinePass,
    FuseNormMatmulPass,
    PatternRewritePass,
)

# the newest pipeline result, for introspection (bench reads its OWN
# result object; this is the debugging handle)
LAST_RESULT = [None]


def run_default_pipeline(program, fetch_vars=(), feed_names=None, clone=True):
    """Run the default pipeline; returns (program, PipelineResult).

    `clone=True` (the Executor/export contract) rewrites a clone() so the
    caller's recorded Program survives untouched — a later run with a
    different fetch set must still see every recorded op. When the
    pipeline rewrote anything, `verify()` runs once more on the rewritten
    program (the post-pipeline verification the Executor relies on);
    failures carry 'post-pipeline' context."""
    work = program.clone() if clone else program
    mgr = PassManager()
    result = mgr.run(work, fetch_vars=fetch_vars, feed_names=feed_names)
    from ..analysis import verifier as _verifier

    # post-pipeline verify only when something was rewritten: an unchanged
    # clone is byte-for-byte the program the caller verified pre-pipeline,
    # and the manager already re-verified after every changing pass
    if result.changed and _verifier.verify_enabled():
        try:
            _verifier.verify(work, feed_names=feed_names, fetch_vars=fetch_vars)
        except _verifier.ProgramVerifyError as e:
            raise _verifier.ProgramVerifyError(
                e.diagnostics, context="post-pipeline"
            ) from e
    LAST_RESULT[0] = result
    return work, result


__all__ = [
    "PassContext",
    "PassManager",
    "PassStats",
    "PipelineResult",
    "ProgramPass",
    "OpPat",
    "Pattern",
    "Match",
    "find_matches",
    "apply_matches",
    "build_cluster_instr",
    "register_pass",
    "get_pass",
    "default_pipeline",
    "pipeline_enabled",
    "run_default_pipeline",
    "eliminate_dead_ops",
    "DeadOpEliminationPass",
    "ConstantFoldScalarsPass",
    "RedundantCastReshapeElimPass",
    "FuseAttentionPass",
    "FuseNormMatmulPass",
    "FuseBiasDropoutResidualPass",
    "PatternRewritePass",
]
