"""Tensor creation ops.

Reference parity: python/paddle/tensor/creation.py (to_tensor, zeros, ones,
full, arange, linspace, eye, tril, triu, meshgrid, ...). Kernels are jnp —
XLA materializes constants on device.
"""
from __future__ import annotations

import numpy as np
import jax
from jax import numpy as jnp

from ..core.apply import apply
from ..core.tensor import Tensor, _ensure_tensor
from ..framework import dtype as dtype_mod
from ..framework import random as random_mod


def _dt(dtype, default=None):
    if dtype is None:
        return default
    return dtype_mod.convert_dtype(dtype)


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor (python/paddle/tensor/creation.py)."""
    if isinstance(data, Tensor):
        val = data._value
        if dtype is not None:
            val = val.astype(dtype_mod.convert_dtype(dtype))
        t = Tensor(val, stop_gradient=stop_gradient)
    elif isinstance(data, (jax.Array, jax.core.Tracer)):
        val = data if dtype is None else data.astype(dtype_mod.convert_dtype(dtype))
        t = Tensor(val, stop_gradient=stop_gradient)
    else:
        if dtype is None:
            a = np.asarray(data)
            if a.dtype == np.float64:
                a = a.astype(dtype_mod.get_default_dtype())
            val = jnp.asarray(a)
        else:
            val = jnp.asarray(data, dtype=dtype_mod.convert_dtype(dtype))
        t = Tensor(val, stop_gradient=stop_gradient)
    if place is not None:
        t = t.to(device=place)
        t.stop_gradient = stop_gradient
    return t


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy().tolist()]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s) if not isinstance(s, Tensor) else int(s.numpy()) for s in shape]


def zeros(shape, dtype=None) -> Tensor:
    return Tensor(jnp.zeros(_shape_list(shape), _dt(dtype, dtype_mod.get_default_dtype())))


def ones(shape, dtype=None) -> Tensor:
    return Tensor(jnp.ones(_shape_list(shape), _dt(dtype, dtype_mod.get_default_dtype())))


def full(shape, fill_value, dtype=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = dtype_mod.bool_
        elif isinstance(fill_value, int):
            dtype = dtype_mod.int64
        else:
            dtype = dtype_mod.get_default_dtype()
    return Tensor(jnp.full(_shape_list(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None) -> Tensor:
    return zeros(shape, dtype)


def zeros_like(x, dtype=None) -> Tensor:
    x = _ensure_tensor(x)
    return Tensor(jnp.zeros(x._value.shape, _dt(dtype, x._value.dtype)))


def ones_like(x, dtype=None) -> Tensor:
    x = _ensure_tensor(x)
    return Tensor(jnp.ones(x._value.shape, _dt(dtype, x._value.dtype)))


def full_like(x, fill_value, dtype=None) -> Tensor:
    x = _ensure_tensor(x)
    return Tensor(jnp.full(x._value.shape, fill_value, _dt(dtype, x._value.dtype)))


def empty_like(x, dtype=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None) -> Tensor:
    def _scalar(v):
        return v.item() if isinstance(v, Tensor) else v

    start, end, step = _scalar(start), _scalar(end), _scalar(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        vals = (start, end, step)
        dtype = dtype_mod.int64 if all(isinstance(v, (int, np.integer)) for v in vals) else dtype_mod.get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=_dt(dtype)))


def linspace(start, stop, num, dtype=None) -> Tensor:
    def _scalar(v):
        return v.item() if isinstance(v, Tensor) else v

    return Tensor(
        jnp.linspace(_scalar(start), _scalar(stop), int(_scalar(num)), dtype=_dt(dtype, dtype_mod.get_default_dtype()))
    )


def logspace(start, stop, num, base=10.0, dtype=None) -> Tensor:
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=_dt(dtype, dtype_mod.get_default_dtype())))


def eye(num_rows, num_columns=None, dtype=None) -> Tensor:
    return Tensor(jnp.eye(int(num_rows), None if num_columns is None else int(num_columns), dtype=_dt(dtype, dtype_mod.get_default_dtype())))


def diag(x, offset=0, padding_value=0) -> Tensor:
    x = _ensure_tensor(x)

    def f(v):
        if v.ndim == 1:
            out = jnp.diag(v, k=offset)
            if padding_value != 0:
                mask = jnp.eye(out.shape[0], out.shape[1], k=offset, dtype=bool)
                out = jnp.where(mask, out, jnp.asarray(padding_value, out.dtype))
            return out
        return jnp.diagonal(v, offset=offset)

    return apply("diag", f, x)


def diagflat(x, offset=0) -> Tensor:
    x = _ensure_tensor(x)
    return apply("diagflat", lambda v: jnp.diagflat(v, k=offset), x)


def diag_embed(x, offset=0, dim1=-2, dim2=-1) -> Tensor:
    x = _ensure_tensor(x)

    def f(v):
        n = v.shape[-1] + abs(offset)
        out = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(v)
        if (dim1, dim2) != (-2, -1):
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out

    return apply("diag_embed", f, x)


def diagonal(x, offset=0, axis1=0, axis2=1) -> Tensor:
    x = _ensure_tensor(x)
    return apply("diagonal", lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2), x)


def tril(x, diagonal=0) -> Tensor:
    x = _ensure_tensor(x)
    return apply("tril", lambda v: jnp.tril(v, k=diagonal), x)


def triu(x, diagonal=0) -> Tensor:
    x = _ensure_tensor(x)
    return apply("triu", lambda v: jnp.triu(v, k=diagonal), x)


def tril_indices(row, col, offset=0, dtype=dtype_mod.int64):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype)))


def triu_indices(row, col=None, offset=0, dtype=dtype_mod.int64):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype)))


def meshgrid(*args):
    ts = [_ensure_tensor(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    outs = jnp.meshgrid(*[t.value for t in ts], indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None) -> Tensor:
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    out = Tensor(x.value)
    if output is not None:
        output._become(out)
        return output
    return out


def clone(x) -> Tensor:
    return _ensure_tensor(x).clone()


def complex(real, imag) -> Tensor:
    return apply("complex", lambda r, i: jax.lax.complex(r, i), _ensure_tensor(real), _ensure_tensor(imag))


def polar(abs_t, angle) -> Tensor:
    return apply(
        "polar",
        lambda a, th: jax.lax.complex(a * jnp.cos(th), a * jnp.sin(th)),
        _ensure_tensor(abs_t),
        _ensure_tensor(angle),
    )


def clone_detached(x) -> Tensor:
    return Tensor(_ensure_tensor(x)._value)


# ---- random creation (python/paddle/tensor/random.py) ----

def _key():
    return random_mod.next_key()


def rand(shape, dtype=None) -> Tensor:
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0) -> Tensor:
    d = _dt(dtype, dtype_mod.get_default_dtype())
    return Tensor(jax.random.uniform(_key(), _shape_list(shape), dtype=jnp.float32, minval=min, maxval=max).astype(d))


def randn(shape, dtype=None) -> Tensor:
    d = _dt(dtype, dtype_mod.get_default_dtype())
    return Tensor(jax.random.normal(_key(), _shape_list(shape), dtype=jnp.float32).astype(d))


def standard_normal(shape, dtype=None) -> Tensor:
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None) -> Tensor:
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = _ensure_tensor(mean).value
        s = _ensure_tensor(std).value
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(_key(), shp, dtype=jnp.float32) * s + m)
    if shape is None:
        shape = [1]
    return Tensor(jax.random.normal(_key(), _shape_list(shape), dtype=jnp.float32) * std + mean)


def randint(low=0, high=None, shape=(1,), dtype=dtype_mod.int64) -> Tensor:
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_key(), _shape_list(shape), low, high, dtype=_dt(dtype)))


def randint_like(x, low=0, high=None, dtype=None) -> Tensor:
    x = _ensure_tensor(x)
    if high is None:
        low, high = 0, low
    d = _dt(dtype, x.dtype)
    return Tensor(jax.random.randint(_key(), x._value.shape, low, high).astype(d))


def randperm(n, dtype=dtype_mod.int64) -> Tensor:
    return Tensor(jax.random.permutation(_key(), int(n)).astype(_dt(dtype)))


def bernoulli(x) -> Tensor:
    x = _ensure_tensor(x)
    return Tensor(jax.random.bernoulli(_key(), x.value.astype(jnp.float32)).astype(x._value.dtype))


def poisson(x) -> Tensor:
    x = _ensure_tensor(x)
    return Tensor(jax.random.poisson(_key(), x.value.astype(jnp.float32)).astype(x._value.dtype))


def multinomial(x, num_samples=1, replacement=False) -> Tensor:
    x = _ensure_tensor(x)
    v = x.value
    if v.ndim == 1:
        v = v[None]
        squeeze = True
    else:
        squeeze = False
    logits = jnp.log(jnp.maximum(v, 1e-30))
    if replacement:
        out = jax.random.categorical(_key(), logits, axis=-1, shape=(v.shape[0], num_samples) if num_samples else None)
        out = out.reshape(v.shape[0], num_samples)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(_key(), v.shape, dtype=logits.dtype)
        out = jnp.argsort(-(logits + g), axis=-1)[:, :num_samples]
    out = out.astype(jnp.int64)
    if squeeze:
        out = out[0]
    return Tensor(out)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1) -> Tensor:
    x = _ensure_tensor(x)
    g = jax.random.gumbel(_key(), x._value.shape, dtype=jnp.float32)

    def f(v):
        y = jax.nn.softmax((v + g.astype(v.dtype)) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y).at[...].set(0.0)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis) if hasattr(jnp, "put_along_axis") else y_hard.at[idx].set(1.0)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y

    return apply("gumbel_softmax", f, x)


def binomial(count, prob, name=None) -> Tensor:
    """paddle.binomial (tensor/random.py binomial; phi binomial_kernel):
    elementwise Binomial(count, prob) sampling. Implemented as a sum of
    Bernoulli draws when count is small, else normal approximation clipped
    (the standard device-friendly scheme)."""
    count = _ensure_tensor(count)
    prob = _ensure_tensor(prob)
    c = count.value.astype(jnp.float32)
    p = prob.value.astype(jnp.float32)
    c, p = jnp.broadcast_arrays(c, p)  # paddle allows broadcastable shapes
    # under tracing the max count is unknowable -> normal approximation
    # (valid for any count; exact Bernoulli-sum only for concrete small counts)
    cmax = int(np.asarray(jnp.max(c))) if not isinstance(c, jax.core.Tracer) else None
    if cmax is not None and cmax <= 64:
        draws = jax.random.uniform(_key(), (max(int(cmax), 1),) + tuple(c.shape))
        idx = jnp.arange(max(cmax, 1)).reshape((-1,) + (1,) * c.ndim)
        out = jnp.sum((draws < p[None]) & (idx < c[None]), axis=0)
    else:
        mean = c * p
        std = jnp.sqrt(jnp.maximum(c * p * (1 - p), 1e-9))
        out = jnp.clip(jnp.round(mean + std * jax.random.normal(_key(), c.shape)), 0, c)
    return Tensor(out.astype(jnp.int64))


def standard_gamma(x, name=None) -> Tensor:
    """paddle.standard_gamma (tensor/random.py): Gamma(alpha=x, scale=1)."""
    x = _ensure_tensor(x)
    v = x.value
    return Tensor(jax.random.gamma(_key(), v.astype(jnp.float32)).astype(v.dtype))


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None):
    """Standalone learnable parameter (reference tensor/creation.py
    create_parameter; LayerHelper-free TPU design reuses the initializer
    resolution of Layer.create_parameter)."""
    from ..nn.initializer import _resolve_attr
    from ..nn.layer import Parameter
    from ..framework import dtype as _dt

    d = _dt.convert_dtype(dtype)
    init, pname, trainable, lr, reg, need_clip = _resolve_attr(attr, is_bias, default_initializer)
    value = init(tuple(int(s) for s in shape), d)
    p = Parameter(value, trainable=trainable, name=pname or name)
    p.optimize_attr = {"learning_rate": lr}
    p.regularizer = reg
    p.need_clip = need_clip
    return p


def create_tensor(dtype, name=None, persistable=False):
    """Uninitialized variable holder (reference tensor/creation.py
    create_tensor — a 0-size LoDTensor to be written later; here an empty
    jax array of the dtype, filled by assign/set_value)."""
    from ..framework import dtype as _dt

    t = Tensor(jnp.zeros((0,), _dt.convert_dtype(dtype)), name=name)
    t.persistable = persistable
    return t
