"""Global flags registry.

Reference parity: paddle/common/flags.cc (141 PHI_DEFINE_EXPORTED_* flags) +
python/paddle/base/framework.py set_flags/get_flags. TPU-native design: a
plain python registry seeded from FLAGS_* environment variables; flags that
map to XLA behavior translate into jax config updates where applicable.
"""
from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_registry: dict = {}
_meta: dict = {}
_watchers: dict = {}  # flag name -> [callback(value)]


def watch_flag(name: str, fn):
    """Register a callback fired (outside the registry lock) whenever
    set_flags changes `name` — lets hot paths cache a flag as a plain bool
    instead of taking this lock per event (see telemetry.metrics)."""
    with _lock:
        _watchers.setdefault(name, []).append(fn)


def define_flag(name: str, default, doc: str = ""):
    """Analog of PHI_DEFINE_EXPORTED_* (paddle/common/flags.h:38)."""
    with _lock:
        if name in _registry:
            return
        env = os.environ.get(name)
        value = default
        if env is not None:
            if isinstance(default, bool):
                value = env.lower() in ("1", "true", "yes", "on")
            elif isinstance(default, int):
                value = int(env)
            elif isinstance(default, float):
                value = float(env)
            else:
                value = env
        _registry[name] = value
        _meta[name] = doc


def set_flags(flags: dict):
    """paddle.set_flags analog. All-or-nothing: validate every key before
    applying any, so a typo can't leave the registry half-updated with
    watchers unfired (which would desync cached gates like telemetry's)."""
    with _lock:
        unknown = [k for k in flags if k not in _registry]
        if unknown:
            raise KeyError(f"unknown flag {unknown[0]!r}; define_flag it first")
        _registry.update(flags)
        fired = [(fn, v) for k, v in flags.items() for fn in _watchers.get(k, ())]
    for fn, v in fired:
        fn(v)


def get_flags(flags):
    """paddle.get_flags analog; accepts str or list of str."""
    if isinstance(flags, str):
        flags = [flags]
    with _lock:
        return {k: _registry[k] for k in flags}


def get_flag(name: str):
    with _lock:
        return _registry[name]


# Core flags (subset of paddle/common/flags.cc that is meaningful on TPU).
define_flag("FLAGS_check_nan_inf", False, "scan op outputs for nan/inf (debug)")
define_flag(
    "FLAGS_to_static_donate",
    True,
    "donate state buffers (params/optimizer moments/grads) to to_static "
    "compiled steps: saves the per-step state copy + halves the state "
    "memory high-water mark; disable if you hold detach()-aliases of "
    "parameters or param.grad array references across compiled steps "
    "(donated arrays raise 'deleted' on read)",
)
define_flag("FLAGS_use_bf16_default", False, "prefer bfloat16 in AMP on TPU")
define_flag(
    "FLAGS_fused_optimizer",
    False,
    "route Adam/AdamW updates through the flat-bucket one-pass Pallas "
    "optimizer engine (ops/fused_optimizer.py): params/moments/grads are "
    "flattened into contiguous same-dtype buckets and each bucket updates "
    "in ONE kernel streaming tiles through VMEM once — replacing XLA's "
    "per-tensor fusion scatter (~9 ms of the 53 ms seq-128 step)",
)
define_flag("FLAGS_jit_guard_shapes", True, "retrace to_static programs on input shape change")
define_flag(
    "FLAGS_verify_program",
    True,
    "run the static.analysis verifier (SSA single-assignment, "
    "use-before-def, feed/param coverage, dangling fetch/grad/opt refs, "
    "op-output arity, donation hazards) before Executor._compile and "
    "program-export lowering, so malformed programs fail with a diagnostic "
    "naming the op/var instead of an XLA traceback; costs ~O(#ops) python "
    "per COMPILE (cache hits never re-verify)",
)
define_flag(
    "FLAGS_program_passes",
    True,
    "run the static.passes rewrite pipeline (dead-op elimination, scalar "
    "constant folding, redundant cast/reshape elimination, DRR fusion "
    "patterns: attention cluster -> Pallas flash, norm+matmul, "
    "bias+dropout+residual) over a CLONE of the recorded Program on every "
    "Executor compile-miss and before program-export lowering; the "
    "verifier re-runs after each rewriting pass. The caller's Program is "
    "never mutated. Disable to replay the capture exactly as recorded",
)
define_flag(
    "FLAGS_print_after_pass",
    "",
    "comma-separated pass names (or 'all') whose to_text() diff is printed "
    "to stderr after the pass rewrites a program — the --print-after-pass "
    "debugging surface of the pass pipeline; empty disables",
)
# Training guardian (framework/guardian.py): state-failure guards layered on
# the PR 2 process/IO resilience — numerical anomaly policy, last-known-good
# rollback ring, cross-rank desync digest, crash flight recorder.
define_flag(
    "FLAGS_guardian_policy",
    "raise",
    "what TrainingGuardian.step does on a numerical anomaly: 'raise' (dump "
    "flight recorder + FloatingPointError), 'skip_step' (drop the update, "
    "count the step as skipped in GradScaler accounting), or 'rollback' "
    "(restore the newest last-known-good snapshot and re-seed the generator)",
)
define_flag(
    "FLAGS_guardian_abs_ceiling",
    0.0,
    "abs-magnitude ceiling for the guardian's fused numerics check over "
    "loss/grads/params (0 disables the ceiling; non-finiteness is always "
    "checked when FLAGS_check_nan_inf is on)",
)
define_flag(
    "FLAGS_lkg_interval",
    100,
    "steps between last-known-good on-device snapshots of params + optimizer "
    "state (fused-bucket aware); the rollback policy restores the newest one",
)
define_flag("FLAGS_lkg_ring", 2, "how many last-known-good snapshots to keep")
define_flag(
    "FLAGS_desync_interval",
    0,
    "steps between cross-rank desync digest checks (param-bucket checksums + "
    "RNG state + step counter all-reduced over the group); 0 disables the "
    "periodic check — explicit check_desync() calls always run",
)
define_flag(
    "FLAGS_flight_recorder_len",
    256,
    "per-step records kept in the guardian flight recorder ring (dumped as "
    "JSON to the crash dir on watchdog escalation or guardian abort)",
)
define_flag("FLAGS_eager_delete_tensor_gb", 0.0, "no-op on TPU; XLA owns HBM")
define_flag("FLAGS_log_level", 0, "framework verbosity")
define_flag("FLAGS_benchmark", False, "block_until_ready after each op (timing)")
