# filled by model-zoo milestone
