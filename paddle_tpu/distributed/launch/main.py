"""`python -m paddle_tpu.distributed.launch` CLI.

Reference parity: python/paddle/distributed/launch/main.py:20 — the launch
entry that builds env per local process, deploys, and watches. Arguments keep
the reference's names (--nnodes, --nproc_per_node, --master, --log_dir,
--job_id, --devices, elastic --max_restart).
"""
from __future__ import annotations

import argparse

from .controller import CollectiveController, Context


def parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--master", default=None, help="rank-0 rendezvous endpoint host:port (multi-node)")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--nproc_per_node", type=int, default=1, help="TPU default: 1 controller per node")
    p.add_argument("--node_rank", type=int, default=None, help="explicit node rank (skips rendezvous)")
    p.add_argument("--job_id", default="default")
    p.add_argument("--devices", "--gpus", default=None, help="visible device ids, comma separated")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--port", type=int, default=10071, help="coordinator port for single-node multi-proc")
    p.add_argument("--max_restart", type=int, default=0, help="elastic: restarts before giving up")
    p.add_argument(
        "--elastic_timeout", type=float, default=0,
        help="> 0 enables elastic membership: heartbeat staleness (s) after "
        "which a node is dead and the pod relaunches with new ranks",
    )
    p.add_argument("--poll_interval", type=float, default=1.0)
    p.add_argument(
        "--restart_backoff", type=float, default=0.5,
        help="base seconds between pod restarts (doubles per consecutive "
        "restart, full jitter, capped at 30s) so a crash-looping pod doesn't "
        "burn its restart budget racing zombies",
    )
    p.add_argument(
        "--restart_healthy_window", type=float, default=300.0,
        help="seconds the pod must run clean after a restart before the "
        "restart budget (--max_restart) and backoff reset; 0 disables",
    )
    p.add_argument("--module", "-m", action="store_true", help="run script as a python module")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch(argv=None) -> int:
    args = parse_args(argv)
    ctx = Context(args)
    controller = CollectiveController(ctx)
    if args.elastic_timeout > 0 and args.master:
        import socket

        from ..fleet.elastic.manager import ElasticManager

        controller.enable_elastic(
            ElasticManager(
                endpoint=args.master.replace("http://", ""),
                job_id=args.job_id,
                np=args.nnodes,
                host=socket.gethostname(),
                timeout=args.elastic_timeout,
            )
        )
    return controller.run()


if __name__ == "__main__":
    raise SystemExit(launch())
