"""Collective hang watchdog.

Reference parity: paddle/phi/core/distributed/comm_task.h:36 (CommTask,
IsTimeout :127) + comm_task_manager.h:37 (CommTaskManager — a background
thread that detects hung/errored NCCL collectives and aborts the process
with diagnostics).

TPU-native design: compiled collectives are XLA program internals — a hang
surfaces as a host thread blocked in dispatch/compile (tunnel) or in a
blocking wait (store rendezvous, block_until_ready). So the watchdog tracks
HOST-SIDE blocking sections: every eager collective dispatch and every store
wait registers a CommTask; a daemon thread scans them and escalates through
a ladder instead of killing the process blind:

1. **warn** — a task older than FLAGS_comm_watchdog_warn_s (but under its
   hard deadline) gets ONE stderr warning + telemetry counter, so a
   slowly-degrading link shows up before the abort;
2. **dump** — past the hard deadline the default handler writes the full
   diagnostic dump (op, group ranks, elapsed, every other in-flight task),
   every thread's stack via `faulthandler`, and a telemetry snapshot;
3. **abort** — flushes stderr (the dump must survive buffered pipes under
   `launch`) and invokes the abort handler — default `os._exit(1)`,
   matching the reference's abort-on-hang semantics.

Tests/graceful users install their own hard-deadline handler via
`set_timeout_handler` (replacing stages 2+3), or keep the diagnostics and
swap only the final abort via `set_abort_handler`.

Config: FLAGS_enable_comm_watchdog (default True),
FLAGS_comm_watchdog_timeout_s (default 600, the reference's default
CommTask timeout scale), FLAGS_comm_watchdog_warn_s (soft deadline), or
per-task timeouts; DistributedStrategy maps its `comm_watchdog_timeout`
hybrid config here (see fleet/fleet.py).
"""
from __future__ import annotations

import faulthandler
import itertools
import os
import sys
import threading
import time
from typing import Callable, Optional

from ..framework import flags as _flags

_flags.define_flag("FLAGS_enable_comm_watchdog", True, "abort on hung collectives/store waits")
_flags.define_flag("FLAGS_comm_watchdog_timeout_s", 600.0, "seconds before a comm task is declared hung")
_flags.define_flag(
    "FLAGS_comm_watchdog_margin_s", 30.0,
    "extra grace added to a blocking call's OWN timeout before the watchdog "
    "declares it stuck (a wait is only 'hung' once past its own deadline)",
)
_flags.define_flag(
    "FLAGS_comm_watchdog_warn_s", 300.0,
    "soft deadline: a comm task older than this (but not yet hung) emits one "
    "warning with diagnostics; 0 disables the warn stage",
)


def _record_task_metric(name: str, op: str) -> None:
    """Publish a comm-task lifecycle event into the telemetry registry."""
    from .. import telemetry as _tm

    if _tm.enabled():
        _tm.counter(name, "comm watchdog task lifecycle", ("op",)).labels(op=op).inc()


class CommTask:
    __slots__ = ("tid", "op", "info", "start", "timeout", "warned")

    def __init__(self, tid, op, info, timeout):
        self.tid = tid
        self.op = op
        self.info = info
        self.start = time.monotonic()
        self.timeout = timeout
        self.warned = False

    def elapsed(self) -> float:
        return time.monotonic() - self.start

    def is_timeout(self) -> bool:
        return self.elapsed() > self.timeout

    def describe(self) -> str:
        extra = ", ".join(f"{k}={v}" for k, v in self.info.items())
        return f"CommTask[{self.tid}] op={self.op} elapsed={self.elapsed():.1f}s timeout={self.timeout:.0f}s {extra}"


def flush_diagnostics() -> None:
    """Make the dump survive the process: write a telemetry snapshot to
    stderr (the retry/fault/collective counters are the post-mortem) and
    flush — under `launch`, worker stderr rides a buffered pipe and an
    unflushed abort loses everything after the last newline."""
    try:
        from .. import telemetry as _tm

        if _tm.enabled():
            sys.stderr.write("--- telemetry snapshot ---\n")
            sys.stderr.write(_tm.to_prometheus())
            # JSON-lines for machine post-mortems, LENIENT mode: a gauge
            # that went NaN may be the whole story of this crash — skip
            # and count it (loud marker line) instead of letting
            # allow_nan=False throw away the entire snapshot
            sys.stderr.write("\n--- telemetry snapshot (jsonl) ---\n")
            sys.stderr.write(_tm.to_json_lines(strict=False))
            sys.stderr.write("\n")
    except Exception:
        pass  # diagnostics must never mask the abort
    try:
        # the incident-timeline tail is the cross-subsystem event order
        # leading up to the hang (injections, migrations, mode changes);
        # tail() is NaN-lenient so the dump survives poisoned payloads
        from ..telemetry import timeline as _tl

        if _tl.enabled():
            import json as _json

            sys.stderr.write("--- incident timeline tail (jsonl) ---\n")
            for rec in _tl.tail(256):
                sys.stderr.write(_json.dumps(rec, sort_keys=True))
                sys.stderr.write("\n")
            if _tl.dropped():
                sys.stderr.write(
                    f"(+{_tl.dropped()} older event(s) ring-evicted)\n"
                )
    except Exception:
        pass
    try:
        sys.stderr.flush()
    except Exception:
        pass


def _default_abort(task: CommTask) -> None:
    os._exit(1)


def _default_handler(task: CommTask, dump: str) -> None:
    """Hard-deadline stages of the escalation ladder: dump, then abort."""
    try:
        from ..telemetry import timeline as _tl

        _tl.emit("watchdog", "escalation", severity="fatal",
                 op=task.op, elapsed_s=round(task.elapsed(), 3),
                 timeout_s=task.timeout)
    except Exception:
        pass
    sys.stderr.write(
        f"\n=== paddle_tpu comm watchdog: HUNG COLLECTIVE DETECTED ===\n"
        f"{task.describe()}\n--- all in-flight comm tasks ---\n{dump}\n"
        f"--- all thread stacks ---\n"
    )
    try:
        faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
    except Exception:
        pass
    flush_diagnostics()
    try:
        # the guardian flight recorders are the per-step post-mortem (loss,
        # grad norm, skip/rollback/desync events, collective latencies) —
        # dump them to the crash dir before the process dies
        from ..framework import guardian as _guardian

        for p in _guardian.dump_flight_recorders(reason=f"watchdog:{task.op}"):
            sys.stderr.write(f"flight recorder dumped: {p}\n")
    except Exception:
        pass  # diagnostics must never mask the abort
    try:
        sys.stderr.flush()
    except Exception:
        pass
    sys.stderr.write("aborting process (reference CommTaskManager semantics)\n")
    try:
        sys.stderr.flush()
    except Exception:
        pass
    CommTaskManager.instance()._abort_handler(task)


class CommTaskManager:
    """Singleton scanning thread over in-flight comm tasks."""

    _instance: Optional["CommTaskManager"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._tasks: dict = {}
        self._lock = threading.Lock()
        self._ids = itertools.count()
        self._thread: Optional[threading.Thread] = None
        self._handler: Callable = _default_handler
        self._abort_handler: Callable = _default_abort
        self._warn_handler: Optional[Callable] = None
        self._wake = threading.Event()

    @classmethod
    def instance(cls) -> "CommTaskManager":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    # ---- task lifecycle ----
    def start_task(self, op: str, timeout: Optional[float] = None, **info) -> Optional[int]:
        if not _flags.get_flag("FLAGS_enable_comm_watchdog"):
            return None
        if timeout is None:
            timeout = float(_flags.get_flag("FLAGS_comm_watchdog_timeout_s"))
        t = CommTask(next(self._ids), op, info, timeout)
        with self._lock:
            self._tasks[t.tid] = t
            self._ensure_thread()
        self._wake.set()
        _record_task_metric("paddle_tpu_comm_tasks_started_total", op)
        return t.tid

    def end_task(self, tid: Optional[int]) -> None:
        if tid is None:
            return
        with self._lock:
            self._tasks.pop(tid, None)

    def set_timeout_handler(self, fn: Optional[Callable]) -> Callable:
        prev = self._handler
        self._handler = fn or _default_handler
        return prev

    def set_abort_handler(self, fn: Optional[Callable]) -> Callable:
        """Swap the ladder's final stage (default os._exit(1)) while keeping
        the dump/flush diagnostics — what a graceful shutdown hook or a chaos
        test observing the full warn→dump→abort ordering wants."""
        prev = self._abort_handler
        self._abort_handler = fn or _default_abort
        return prev

    def set_warn_handler(self, fn: Optional[Callable]) -> Optional[Callable]:
        prev = self._warn_handler
        self._warn_handler = fn
        return prev

    def _warn(self, task: CommTask) -> None:
        task.warned = True
        _record_task_metric("paddle_tpu_comm_tasks_warned_total", task.op)
        try:
            from ..telemetry import timeline as _tl

            _tl.emit("watchdog", "soft_deadline", severity="warn",
                     op=task.op, elapsed_s=round(task.elapsed(), 3),
                     timeout_s=task.timeout)
        except Exception:
            pass
        sys.stderr.write(
            f"[paddle_tpu comm watchdog] WARNING: {task.describe()} — past the "
            f"soft deadline (FLAGS_comm_watchdog_warn_s), will abort at "
            f"{task.timeout:.0f}s\n"
        )
        try:
            sys.stderr.flush()
        except Exception:
            pass
        if self._warn_handler is not None:
            try:
                self._warn_handler(task)
            except Exception:
                pass

    def active_tasks(self):
        with self._lock:
            return list(self._tasks.values())

    # ---- scanner ----
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._scan_loop, name="paddle-tpu-comm-watchdog", daemon=True
            )
            self._thread.start()

    def _scan_loop(self):
        while True:
            # block until a task registers (start_task sets the event) —
            # zero idle wakeups when nothing is in flight
            self._wake.wait()
            self._wake.clear()
            while True:
                with self._lock:
                    tasks = list(self._tasks.values())
                if not tasks:
                    break
                warn_s = float(_flags.get_flag("FLAGS_comm_watchdog_warn_s"))
                for t in tasks:
                    if t.is_timeout():
                        dump = "\n".join(x.describe() for x in tasks)
                        with self._lock:
                            self._tasks.pop(t.tid, None)
                        _record_task_metric("paddle_tpu_comm_tasks_timeout_total", t.op)
                        try:
                            self._handler(t, dump)
                        except Exception:
                            pass
                    elif not t.warned and 0 < warn_s <= t.elapsed():
                        # soft deadline: one warning per task, then keep
                        # counting down to the hard deadline
                        self._warn(t)
                # scan at 1/10 of the smallest remaining margin (to a warn OR
                # hard deadline), bounded
                def _next_deadline(t):
                    hard = t.timeout - t.elapsed()
                    if not t.warned and 0 < warn_s:
                        return min(hard, max(warn_s - t.elapsed(), 0.0))
                    return hard

                margin = min((_next_deadline(t) for t in tasks), default=0.5)
                time.sleep(min(max(margin / 10, 0.02), 0.5))


class comm_task:
    """Context manager wrapping one blocking communication section."""

    def __init__(self, op: str, timeout: Optional[float] = None, **info):
        self._op = op
        self._timeout = timeout
        self._info = info
        self._tid = None

    def __enter__(self):
        self._tid = CommTaskManager.instance().start_task(
            self._op, self._timeout, **self._info
        )
        return self

    def __exit__(self, *exc):
        CommTaskManager.instance().end_task(self._tid)
        return False


def set_timeout_handler(fn: Optional[Callable]) -> Callable:
    return CommTaskManager.instance().set_timeout_handler(fn)


def set_abort_handler(fn: Optional[Callable]) -> Callable:
    return CommTaskManager.instance().set_abort_handler(fn)


def set_warn_handler(fn: Optional[Callable]) -> Optional[Callable]:
    return CommTaskManager.instance().set_warn_handler(fn)
