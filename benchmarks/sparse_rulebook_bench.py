"""Sparse-conv rulebook build at point-cloud scale: vectorized vs the r4
dict-probe build (kept inline here as the A/B reference).

Operating point (r4 VERDICT next-round #6): 100k active sites, 3^3 kernel —
a typical outdoor-lidar detection layer. The vectorized build must match
the dict build's pairs exactly (asserted) and be >= 50x faster.

Run: python benchmarks/sparse_rulebook_bench.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from paddle_tpu.sparse.conv_engine import build_rulebook


def dict_build_subm(coords, spatial_shape, kernel, dilation):
    """The r4 per-site dict-probe build (reference for the A/B)."""
    nd = len(spatial_shape)
    offsets = np.stack(
        np.meshgrid(*[np.arange(k) for k in kernel], indexing="ij"), -1
    ).reshape(-1, nd)
    key_of = lambda arr: [tuple(c) for c in arr.tolist()]
    in_map = {k: i for i, k in enumerate(key_of(coords))}
    center = [k // 2 for k in kernel]
    pairs = []
    for off in offsets:
        rel = (off - np.asarray(center)) * np.asarray(dilation)
        nb = coords.copy()
        nb[:, 1:] = coords[:, 1:] + rel
        ii, oi = [], []
        for out_i, k in enumerate(key_of(nb)):
            in_i = in_map.get(k)
            if in_i is not None:
                ii.append(in_i)
                oi.append(out_i)
        pairs.append((np.asarray(ii, np.int32), np.asarray(oi, np.int32)))
    return pairs


def main():
    rng = np.random.RandomState(0)
    nnz, shape = 100_000, (400, 400, 40)
    flat = rng.choice(shape[0] * shape[1] * shape[2], nnz, replace=False)
    sp = np.stack(np.unravel_index(flat, shape), axis=1)
    coords = np.concatenate([np.zeros((nnz, 1), np.int64), sp], axis=1)

    t0 = time.perf_counter()
    _, pairs_fast, _ = build_rulebook(
        coords, shape, 3, 1, 1, 1, subm=True
    )
    t_fast = time.perf_counter() - t0

    t0 = time.perf_counter()
    pairs_dict = dict_build_subm(coords, shape, (3, 3, 3), (1, 1, 1))
    t_dict = time.perf_counter() - t0

    n_pairs = sum(len(ii) for ii, _ in pairs_fast)
    # pair ORDER within an offset is unspecified (each out site appears at
    # most once per offset, so scatter-add is order-invariant) — compare
    # the (in, out) pair SETS
    for (fi, fo), (di, do) in zip(pairs_fast, pairs_dict):
        np.testing.assert_array_equal(fi[np.argsort(fo)], di[np.argsort(do)])
        np.testing.assert_array_equal(np.sort(fo), np.sort(do))

    print(
        f"subm rulebook @ {nnz} sites x 3^3: vectorized {t_fast*1000:.1f} ms  "
        f"dict {t_dict*1000:.1f} ms  -> {t_dict/t_fast:.1f}x  "
        f"({n_pairs} gather pairs)"
    )


if __name__ == "__main__":
    main()
