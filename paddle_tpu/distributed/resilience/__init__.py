"""Unified resilience layer for the distributed runtime.

At pod scale, preemptions, torn checkpoints, and rendezvous races are the
steady state — this package gives every failure path one vocabulary:

- `fault_injection` — deterministic `FaultPlan`s (named injection points
  with fail-N-times / delay / corrupt actions, seedable, activatable via the
  `PADDLE_TPU_FAULT_PLAN` env var) wired into TCPStore ops, eager collective
  dispatch, checkpoint shard IO, and the serving replica fleet
  (`fleet.route` on every routing decision, `fleet.replica_step.<idx>` on
  every per-replica scheduler tick — a `fail*N` spec on one of those kills
  a specific replica deterministically mid-decode, a `delay` spec trips the
  heartbeat breaker), so chaos tests drive REAL failure handling instead of
  hand-rolled monkeypatches.
- `retry` — `RetryPolicy`: exponential backoff with full jitter under an
  overall deadline, publishing per-site attempt/giveup counters into the
  telemetry registry. Applied to TCPStore connect/op reconnects and launch
  rendezvous; the launcher's restart backoff shares its delay schedule.

The watchdog escalation ladder (warn → thread-stack dump + telemetry flush →
abort) lives in `distributed/comm_watchdog.py` and the atomic, checksummed
checkpoint format in `distributed/checkpoint/` — both consume the primitives
here.
"""
from .fault_injection import (  # noqa: F401
    FaultAction,
    FaultInjected,
    FaultPlan,
    clear_plan,
    corrupt_file,
    corrupt_value,
    current_plan,
    fault_point,
    install_plan,
    plan_from_spec,
)
from .retry import (  # noqa: F401
    RetryError,
    RetryPolicy,
    backoff_delay,
    default_store_policy,
)

__all__ = [
    "FaultAction",
    "FaultInjected",
    "FaultPlan",
    "install_plan",
    "clear_plan",
    "current_plan",
    "plan_from_spec",
    "fault_point",
    "corrupt_file",
    "corrupt_value",
    "RetryPolicy",
    "RetryError",
    "backoff_delay",
    "default_store_policy",
]
