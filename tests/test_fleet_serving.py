"""Replica fleet (round 13): SLO-aware routing with session affinity,
FaultPlan-driven replica failure survival, and zero-downtime weight
hot-swap — all in-process, tier-1 fast.

The two ISSUE acceptance bars pinned here: a FaultPlan-injected replica
kill mid-decode re-dispatches every in-flight request with EXACT final
outputs (recompute-from-prompt on the new home), and a swap-during-replay
leaves the swapped replica's logits BYTE-identical to a cold-started
engine on the same weights (the pinned-out_shardings invariant).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.resilience import fault_injection as fi
from paddle_tpu.inference.engine import InferenceEngine
from paddle_tpu.inference.fleet import (
    NoHealthyReplica,
    ReplicaFleet,
    ReplicaStatus,
    fleet_replay,
)
from paddle_tpu.inference.scheduler import Request
from paddle_tpu.telemetry import metrics as tm


@pytest.fixture(scope="module")
def tiny_model():
    from paddle_tpu.models.llama import llama_tiny

    paddle.seed(0)
    m = llama_tiny(num_key_value_heads=2)
    m.eval()
    return m


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    yield
    fi.clear_plan()


def _engine(model, **kw):
    opts = dict(max_seq_len=64, block_size=8, max_batch=4)
    opts.update(kw)
    return InferenceEngine(model, **opts)


def _greedy_oracle(model, prompt, n):
    cur = list(prompt)
    for _ in range(n):
        with paddle.no_grad():
            lg = model(paddle.to_tensor(np.asarray([cur], np.int64))).numpy()[0, -1]
        cur.append(int(lg.argmax()))
    return cur[len(prompt):]


def _outputs(fleet):
    return {r.rid: r.prompt[r.prompt_len:] + list(r.generated)
            for r in fleet.finished}


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

def test_routing_least_loaded_and_session_affinity(tiny_model):
    fleet = ReplicaFleet([_engine(tiny_model), _engine(tiny_model)])
    routed = tm.counter(
        "paddle_tpu_fleet_routed_total", "", ("reason",))
    aff_before = routed.labels(reason="affinity").value
    r0 = Request(rid=0, prompt=[1, 2, 3], max_new_tokens=2, session="a")
    r1 = Request(rid=1, prompt=[4, 5, 6], max_new_tokens=2, session="b")
    r2 = Request(rid=2, prompt=[7, 8, 9], max_new_tokens=2, session="a")
    fleet.submit(r0)  # both empty -> replica 0
    fleet.submit(r1)  # least-loaded -> replica 1
    fleet.submit(r2)  # session "a" homes on replica 0 despite equal load
    assert fleet._session_home == {"a": 0, "b": 1}
    assert {r.rid for r in fleet.replicas[0].sched.waiting} == {0, 2}
    assert {r.rid for r in fleet.replicas[1].sched.waiting} == {1}
    assert routed.labels(reason="affinity").value == aff_before + 1
    while not fleet.idle():
        fleet.step()
    got = _outputs(fleet)
    for r in (r0, r1, r2):
        assert got[r.rid] == _greedy_oracle(tiny_model, r.prompt, 2)


def test_route_fault_site_is_deterministic(tiny_model):
    fleet = ReplicaFleet([_engine(tiny_model)])
    fi.install_plan(fi.FaultPlan().add("fleet.route", "fail", times=1))
    with pytest.raises(fi.FaultInjected):
        fleet.submit(Request(rid=0, prompt=[1, 2], max_new_tokens=1))
    fi.clear_plan()
    fleet.submit(Request(rid=1, prompt=[1, 2], max_new_tokens=1))
    while not fleet.idle():
        fleet.step()
    assert len(fleet.finished) == 1


# ---------------------------------------------------------------------------
# replica failure survival
# ---------------------------------------------------------------------------

def test_replica_kill_mid_decode_redispatches_with_exact_outputs(tiny_model):
    """The ISSUE acceptance bar: FaultPlan kills replica 1 mid-decode; its
    in-flight requests evacuate (generated tokens fold into the prompt)
    and finish on replica 0 with final outputs EXACTLY equal to the
    no-fault greedy oracle — zero lost, zero duplicated."""
    fleet = ReplicaFleet([_engine(tiny_model), _engine(tiny_model)])
    prompts = [[1 + i, 7 + i, 20 + i, 31 + i] for i in range(6)]
    reqs = [Request(rid=i, prompt=list(p), max_new_tokens=8)
            for i, p in enumerate(prompts)]
    for r in reqs:
        fleet.submit(r)
    # everyone admitted and decoding before the fault arms
    for _ in range(3):
        fleet.step()
    assert fleet.replicas[1].busy()
    evac = tm.counter("paddle_tpu_fleet_evacuated_requests_total", "")
    evac_before = evac.value
    fi.install_plan(
        fi.FaultPlan().add("fleet.replica_step.1", "fail", times=2)
    )
    while not fleet.idle():
        fleet.step()
    assert fleet.replicas[1].status == ReplicaStatus.DOWN
    assert fleet.evacuated_total >= 1
    assert evac.value > evac_before
    assert fleet.failures_total == 2  # breaker threshold, then dead = unstepped
    rids = [r.rid for r in fleet.finished]
    assert sorted(rids) == list(range(6)) and len(set(rids)) == 6
    got = _outputs(fleet)
    for i, p in enumerate(prompts):
        assert got[i] == _greedy_oracle(tiny_model, p, 8), i
    # the survivors returned every page
    assert fleet.replicas[0].engine.pool.used() == 0
    fam = tm.default_registry().get("paddle_tpu_fleet_replicas")
    assert fam.labels(state="down", tier="none").value == 1


def test_one_failure_opens_circuit_halfway_then_recovers(tiny_model):
    """A single step fault (below breaker_threshold) marks the replica
    draining — no new admissions — and ONE good step closes the circuit."""
    fleet = ReplicaFleet([_engine(tiny_model), _engine(tiny_model)],
                         breaker_threshold=2)
    for i in range(4):
        fleet.submit(Request(rid=i, prompt=[3 + i, 9 + i], max_new_tokens=4))
    fleet.step()
    assert fleet.replicas[1].busy()
    fi.install_plan(fi.FaultPlan().add("fleet.replica_step.1", "fail", times=1))
    fleet.step()
    assert fleet.replicas[1].status == ReplicaStatus.DRAINING
    fleet.step()  # plan exhausted: the step succeeds, circuit closes
    assert fleet.replicas[1].status == ReplicaStatus.HEALTHY
    while not fleet.idle():
        fleet.step()
    assert len(fleet.finished) == 4 and fleet.evacuated_total == 0


def _warm(eng):
    """Compile the (single) prefill/decode buckets outside any measured
    step so heartbeat tests see millisecond steps, not compile seconds."""
    pages = eng.pool.alloc(1)
    eng.prefill([1, 2, 3], pages)
    eng.decode([1], [3], [4], [pages])
    eng.pool.reset()


def test_heartbeat_deadline_trips_breaker(tiny_model):
    """A DELAY fault — a hung/slow step, no exception raised — trips the
    breaker through the replica's OWN step wall time (a shared tick clock
    would blame the stall on healthy peers); its requests finish elsewhere
    with exact outputs."""
    engines = [
        _engine(tiny_model, prefill_buckets=(16,), decode_batch_buckets=(4,))
        for _ in range(2)
    ]
    for e in engines:
        _warm(e)
    fleet = ReplicaFleet(engines, heartbeat_deadline_s=0.25,
                         breaker_threshold=1)
    prompts = [[2, 4, 6], [3, 5, 7], [8, 9, 10], [11, 12, 13]]
    for i, p in enumerate(prompts):
        fleet.submit(Request(rid=i, prompt=list(p), max_new_tokens=4))
    fleet.step()  # warmed engines: well under the deadline
    assert all(r.status == ReplicaStatus.HEALTHY for r in fleet.replicas)
    assert fleet.replicas[1].busy()
    fi.install_plan(
        fi.FaultPlan().add("fleet.replica_step.1", "delay", times=1, arg=0.4)
    )
    fleet.step()  # the delayed step blows the 0.25 s heartbeat deadline
    assert fleet.replicas[1].status == ReplicaStatus.DOWN
    assert fleet.replicas[0].status == ReplicaStatus.HEALTHY  # peer unblamed
    while not fleet.idle():
        fleet.step()
    got = _outputs(fleet)
    for i, p in enumerate(prompts):
        assert got[i] == _greedy_oracle(tiny_model, p, 4), i


def test_route_chaos_never_drops_internal_redispatch(tiny_model):
    """The fleet.route chaos site models CLIENT-facing routing: a
    permanently-faulted route must still let evacuation/migration/held
    re-dispatch through (those requests live only in local lists — a raise
    there would silently lose them and void the zero-loss invariant)."""
    fleet = ReplicaFleet([_engine(tiny_model), _engine(tiny_model)])
    prompts = [[1 + i, 9 + i, 17 + i] for i in range(4)]
    for i, p in enumerate(prompts):
        fleet.submit(Request(rid=i, prompt=list(p), max_new_tokens=6))
    for _ in range(2):
        fleet.step()
    assert fleet.replicas[1].busy()
    plan = (fi.FaultPlan()
            .add("fleet.replica_step.1", "fail", times=2)
            .add("fleet.route", "fail", times=None))  # route perma-faulted
    fi.install_plan(plan)
    while not fleet.idle():
        fleet.step()
    # the kill's evacuation re-dispatched internally without touching the
    # client-facing chaos site, and nothing was lost
    assert plan.triggered.get("fleet.route") is None
    assert fleet.evacuated_total >= 1
    got = _outputs(fleet)
    for i, p in enumerate(prompts):
        assert got[i] == _greedy_oracle(tiny_model, p, 6), i


def test_submit_route_fault_retry_does_not_inflate_lost(tiny_model):
    """A route chaos raise leaves the request with the caller UNcounted:
    the retry must not skew submitted_total (zero-loss accounting)."""
    fleet = ReplicaFleet([_engine(tiny_model)])
    r0 = Request(rid=0, prompt=[1, 2, 3], max_new_tokens=2)
    fi.install_plan(fi.FaultPlan().add("fleet.route", "fail", times=1))
    with pytest.raises(fi.FaultInjected):
        fleet.submit(r0)
    assert fleet.submitted_total == 0
    assert r0.submitted_time is None  # TTL clock untouched by the reject
    fleet.submit(r0)  # client retry succeeds (plan exhausted)
    while not fleet.idle():
        fleet.step()
    assert fleet.submitted_total == 1 and len(fleet.finished) == 1


def test_replay_event_on_final_completion_still_fires(tiny_model):
    """An event whose completed-count threshold is first reached by the
    fleet-emptying step must still fire (and a swap it starts is driven
    to completion by the same loop)."""
    eng = _engine(tiny_model)
    fleet = ReplicaFleet([eng])
    reqs = [Request(rid=i, prompt=[1 + i, 5 + i], max_new_tokens=2)
            for i in range(2)]
    fleet_replay(
        fleet, reqs,
        events=[(len(reqs), lambda: fleet.request_swap(dict(eng.params)))],
    )
    assert fleet.swaps_completed == 1 and eng.weights_version == 1


def test_session_home_is_bounded_lru(tiny_model):
    fleet = ReplicaFleet([_engine(tiny_model)], session_cache_size=2)
    for i, s in enumerate(("a", "b", "c")):
        fleet.submit(Request(rid=i, prompt=[1, 2], max_new_tokens=1, session=s))
    assert list(fleet._session_home) == ["b", "c"]  # "a" evicted, LRU order
    while not fleet.idle():
        fleet.step()


def test_all_replicas_down_raises_no_healthy(tiny_model):
    fleet = ReplicaFleet([_engine(tiny_model)], breaker_threshold=1)
    fleet.submit(Request(rid=0, prompt=[1, 2, 3], max_new_tokens=4))
    fi.install_plan(fi.FaultPlan().add("fleet.replica_step.0", "fail", times=1))
    fleet.step()  # breaker opens fully; the request is held at the fleet
    assert fleet.replicas[0].status == ReplicaStatus.DOWN
    assert not fleet.idle()
    with pytest.raises(NoHealthyReplica):
        fleet.step()


def test_affinity_broken_only_by_replica_death(tiny_model):
    fleet = ReplicaFleet([_engine(tiny_model), _engine(tiny_model)],
                         breaker_threshold=1)
    r0 = Request(rid=0, prompt=[5, 6, 7], max_new_tokens=6, session="s")
    fleet.submit(r0)
    home = fleet._session_home["s"]
    fleet.step()
    fi.install_plan(
        fi.FaultPlan().add(f"fleet.replica_step.{home}", "fail", times=1)
    )
    while not fleet.idle():
        fleet.step()
    # the session re-homed on the survivor and the output is still exact
    assert fleet._session_home["s"] == 1 - home
    assert _outputs(fleet)[0] == _greedy_oracle(tiny_model, [5, 6, 7], 6)


# ---------------------------------------------------------------------------
# zero-downtime weight hot-swap
# ---------------------------------------------------------------------------

def test_swap_during_replay_byte_identical_to_cold_start(tiny_model, tmp_path):
    """Mid-replay, a topology-portable step_<N>/ checkpoint of DIFFERENT
    weights streams into one drained replica at a time; traffic keeps
    flowing (zero loss), every replica ends on the new version, and a
    probe prefill on the swapped replica is BYTE-identical to a
    cold-started engine built from the new weights."""
    from paddle_tpu.distributed import checkpoint as ckpt
    from paddle_tpu.models.llama import llama_tiny

    paddle.seed(77)
    new_model = llama_tiny(num_key_value_heads=2)
    new_model.eval()
    root = str(tmp_path / "rollout")
    ckpt.save_state_dict({"model": new_model.state_dict()}, root, step=5)

    fleet = ReplicaFleet([_engine(tiny_model), _engine(tiny_model)])
    rng = np.random.RandomState(3)
    reqs = [
        Request(rid=i, prompt=rng.randint(0, 1024, (6,)).tolist(),
                max_new_tokens=6, arrival_time=0.001 * i)
        for i in range(8)
    ]
    stats = fleet_replay(
        fleet, reqs, events=[(2, lambda: fleet.request_swap(root))]
    )
    assert stats["lost"] == 0 and stats["duplicated"] == 0
    assert stats["completed"] == 8
    assert stats["swaps_completed"] == 1
    assert len(fleet.swap_windows) == 1
    assert [r.engine.weights_version for r in fleet.replicas] == [1, 1]
    assert all(r.status == ReplicaStatus.HEALTHY for r in fleet.replicas)

    cold = _engine(new_model)
    probe = rng.randint(0, 1024, (9,)).tolist()
    for rep in fleet.replicas:
        rep.engine.pool.reset()
        pages = rep.engine.pool.alloc(rep.engine.pool.blocks_for_tokens(9))
        lg = rep.engine.prefill(probe, pages)
        cold.pool.reset()
        cpages = cold.pool.alloc(cold.pool.blocks_for_tokens(9))
        assert np.array_equal(lg, cold.prefill(probe, cpages)), (
            "post-swap logits must be byte-identical to a cold-started engine"
        )
    swaps = tm.default_registry().get("paddle_tpu_fleet_swaps_total")
    assert swaps.labels(event="completed").value >= 1
    assert swaps.labels(event="replica_swapped").value >= 2


def test_same_weights_swap_preserves_exact_outputs(tiny_model, tmp_path):
    """A swap that streams the SAME weights (the dryrun/bench shape) runs
    the full drain/load machinery without changing a single output token —
    replayed ids equal the no-swap single-engine oracle."""
    from paddle_tpu.distributed import checkpoint as ckpt

    root = str(tmp_path / "same")
    ckpt.save_state_dict({"model": tiny_model.state_dict()}, root, step=1)
    fleet = ReplicaFleet([_engine(tiny_model), _engine(tiny_model)])
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, 1024, (int(n),)).tolist()
               for n in (5, 9, 7, 11, 6, 8)]
    reqs = [Request(rid=i, prompt=list(p), max_new_tokens=6,
                    arrival_time=0.001 * i) for i, p in enumerate(prompts)]
    stats = fleet_replay(
        fleet, reqs, events=[(1, lambda: fleet.request_swap(root))]
    )
    assert stats["lost"] == 0 and stats["swaps_completed"] == 1
    got = _outputs(fleet)
    for i, p in enumerate(prompts):
        assert got[i] == _greedy_oracle(tiny_model, p, 6), i


def test_single_replica_swap_holds_traffic_no_loss(tiny_model):
    """With ONE replica, a swap is a brief full drain: requests arriving
    mid-swap are HELD at the fleet (never dropped, never routed to a
    draining replica) and served after re-admission."""
    eng = _engine(tiny_model)
    fleet = ReplicaFleet([eng])
    r0 = Request(rid=0, prompt=[1, 2, 3, 4], max_new_tokens=6)
    fleet.submit(r0)
    fleet.step()  # r0 in flight
    fleet.request_swap(dict(eng.params))  # mapping source: same weights
    r1 = Request(rid=1, prompt=[5, 6, 7], max_new_tokens=3)
    fleet.submit(r1)
    assert [r.rid for r in fleet._pending] == [1]  # held: no healthy replica
    while not fleet.idle():
        fleet.step()
    assert eng.weights_version == 1
    got = _outputs(fleet)
    assert got[0] == _greedy_oracle(tiny_model, [1, 2, 3, 4], 6)
    assert got[1] == _greedy_oracle(tiny_model, [5, 6, 7], 3)
    held = tm.default_registry().get("paddle_tpu_fleet_held_requests")
    assert held is not None and held.labels(tier="none").value == 0


def test_fleet_cancel_harvests_immediately(tiny_model):
    """Cancelling the fleet's last in-flight request must land its
    terminal record in fleet.finished right away — idle() ignores the
    schedulers' finished lists, so a deferred harvest would read as a
    lost request to any idle-driven loop."""
    fleet = ReplicaFleet([_engine(tiny_model)])
    fleet.submit(Request(rid=0, prompt=[1, 2, 3], max_new_tokens=30))
    fleet.step()
    assert fleet.cancel(0) is True
    assert fleet.idle()
    assert [r.rid for r in fleet.finished] == [0]
    assert fleet.finished[0].outcome == "cancelled"
    assert fleet.replicas[0].engine.pool.used() == 0
    assert fleet.cancel(0) is False


def test_idle_half_open_replica_recovers(tiny_model):
    """A DRAINING (half-open) replica whose queues emptied has no step
    left to prove itself on — the tick must close its circuit, or a
    single-replica fleet holds new traffic forever."""
    fleet = ReplicaFleet([_engine(tiny_model)], breaker_threshold=2)
    fleet.submit(Request(rid=0, prompt=[1, 2, 3], max_new_tokens=8))
    fleet.step()
    fi.install_plan(fi.FaultPlan().add("fleet.replica_step.0", "fail", times=1))
    fleet.step()
    assert fleet.replicas[0].status == ReplicaStatus.DRAINING
    assert fleet.cancel(0)  # queues empty while still half-open
    fleet.submit(Request(rid=1, prompt=[4, 5, 6], max_new_tokens=2))
    assert [r.rid for r in fleet._pending] == [1]  # held: not healthy yet
    while not fleet.idle():
        fleet.step()
    assert fleet.replicas[0].status == ReplicaStatus.HEALTHY
    assert _outputs(fleet)[1] == _greedy_oracle(tiny_model, [4, 5, 6], 2)


def test_failed_swap_aborts_cleanly_and_fleet_stays_live(tiny_model):
    """A broken swap source (missing checkpoint) surfaces the error but
    must NOT wedge the fleet: the target resumes on its old weights, the
    rollout state clears, and a corrective swap can be requested."""
    eng = _engine(tiny_model)
    fleet = ReplicaFleet([eng])
    fleet.submit(Request(rid=0, prompt=[1, 2, 3], max_new_tokens=2))
    fleet.step()  # busy: the swap below starts as a drain
    fleet.request_swap("/definitely/not/a/checkpoint")
    with pytest.raises(FileNotFoundError):
        for _ in range(50):
            fleet.step()
    assert fleet._swap is None
    assert fleet.replicas[0].status == ReplicaStatus.HEALTHY
    assert eng.weights_version == 0
    fleet.submit(Request(rid=1, prompt=[4, 5], max_new_tokens=2))
    while not fleet.idle():
        fleet.step()
    assert _outputs(fleet)[1] == _greedy_oracle(tiny_model, [4, 5], 2)
    fleet.request_swap(dict(eng.params))  # corrective rollout is accepted
    while not fleet.idle():
        fleet.step()
    assert fleet.swaps_completed == 1 and eng.weights_version == 1
    swaps = tm.default_registry().get("paddle_tpu_fleet_swaps_total")
    assert swaps.labels(event="failed").value >= 1


def test_rollout_with_no_surviving_target_counts_aborted(tiny_model):
    """Every swap target dying mid-rollout must not report a completed
    swap (nor record a blip window over nothing)."""
    fleet = ReplicaFleet([_engine(tiny_model)], breaker_threshold=1)
    fleet.submit(Request(rid=0, prompt=[1, 2, 3], max_new_tokens=8))
    fleet.step()
    fleet.request_swap(dict(fleet.replicas[0].engine.params))  # drain starts
    fi.install_plan(fi.FaultPlan().add("fleet.replica_step.0", "fail", times=1))
    fleet.step()  # breaker opens fully mid-drain; target leaves the rollout
    assert fleet.replicas[0].status == ReplicaStatus.DOWN
    with pytest.raises(NoHealthyReplica):
        fleet.step()  # the abort is processed, then the dead fleet raises
    assert fleet._swap is None
    assert fleet.swaps_completed == 0 and fleet.swap_windows == []
    swaps = tm.default_registry().get("paddle_tpu_fleet_swaps_total")
    assert swaps.labels(event="aborted").value >= 1


def test_double_swap_request_rejected(tiny_model):
    eng = _engine(tiny_model)
    fleet = ReplicaFleet([eng])
    fleet.submit(Request(rid=0, prompt=[1, 2], max_new_tokens=3))
    fleet.step()
    fleet.request_swap(dict(eng.params))
    with pytest.raises(RuntimeError, match="already in progress"):
        fleet.request_swap(dict(eng.params))
    while not fleet.idle():
        fleet.step()


def test_swap_completes_despite_preemption_on_drain_target(tiny_model):
    """Pool-pressure preemption DURING a drain re-queues its victim on the
    drain target itself, where blocked admission would deadlock the swap —
    the fleet must keep migrating the target's waiting queue every tick."""
    eng = InferenceEngine(tiny_model, max_seq_len=48, block_size=8,
                          max_batch=2, num_blocks=6,
                          decode_batch_buckets=(2,), prefill_buckets=(16, 32))
    fleet = ReplicaFleet([eng])
    rng = np.random.RandomState(6)
    p0 = rng.randint(0, 1024, (15,)).tolist()
    p1 = rng.randint(0, 1024, (15,)).tolist()
    fleet.submit(Request(rid=0, prompt=list(p0), max_new_tokens=12))
    fleet.submit(Request(rid=1, prompt=list(p1), max_new_tokens=12))
    for _ in range(3):
        fleet.step()  # both in flight, pages filling
    fleet.request_swap(dict(eng.params))
    for _ in range(500):
        if fleet.idle():
            break
        fleet.step()
    else:
        pytest.fail("swap deadlocked: fleet never went idle")
    assert fleet.swaps_completed == 1 and eng.weights_version == 1
    got = _outputs(fleet)
    assert got[0] == _greedy_oracle(tiny_model, p0, 12)
    assert got[1] == _greedy_oracle(tiny_model, p1, 12)
    assert eng.pool.used() == 0


def test_all_replicas_draining_recovers_without_raising(tiny_model):
    """Half-open circuits on EVERY replica must not be fatal: one good
    step closes them and held traffic flushes — NoHealthyReplica is
    reserved for all replicas fully DOWN."""
    fleet = ReplicaFleet([_engine(tiny_model), _engine(tiny_model)],
                         breaker_threshold=2)
    fleet.submit(Request(rid=0, prompt=[1, 2, 3], max_new_tokens=4))
    fleet.submit(Request(rid=1, prompt=[4, 5, 6], max_new_tokens=4))
    fleet.step()  # one request on each replica
    fi.install_plan(fi.FaultPlan().add("fleet.replica_step.*", "fail", times=2))
    fleet.step()  # both replicas fail once -> both DRAINING
    assert all(r.status == ReplicaStatus.DRAINING for r in fleet.replicas)
    fleet.submit(Request(rid=2, prompt=[7, 8, 9], max_new_tokens=2))
    assert [r.rid for r in fleet._pending] == [2]  # held, not crashed
    fleet.step()  # plan exhausted: good steps close both circuits
    assert all(r.status == ReplicaStatus.HEALTHY for r in fleet.replicas)
    while not fleet.idle():
        fleet.step()
    got = _outputs(fleet)
    assert sorted(got) == [0, 1, 2]
    assert got[2] == _greedy_oracle(tiny_model, [7, 8, 9], 2)


def test_ttl_clock_survives_redispatch_and_held_queue(tiny_model):
    """A request's TTL measures from its ORIGINAL submit: evacuation off a
    dead replica must not restart the deadline, and a request held at the
    fleet (no healthy replica) must still be able to expire."""
    t = [0.0]
    fleet = ReplicaFleet([_engine(tiny_model), _engine(tiny_model)],
                         clock=lambda: t[0], breaker_threshold=1)
    r0 = Request(rid=0, prompt=[1, 2, 3, 4], max_new_tokens=40, deadline_s=0.5)
    fleet.submit(r0)
    assert r0.submitted_time == 0.0
    fleet.step()  # in flight on replica 0
    fi.install_plan(fi.FaultPlan().add("fleet.replica_step.0", "fail", times=1))
    t[0] = 0.3
    fleet.step()  # killed -> evacuated -> re-submitted on replica 1
    assert fleet.replicas[0].status == ReplicaStatus.DOWN
    assert r0.submitted_time == 0.0  # NOT restarted by the re-dispatch
    t[0] = 0.6  # past the ORIGINAL deadline
    fleet.step()
    assert r0.outcome == "expired"
    assert fleet.replicas[1].engine.pool.used() == 0

    # held-at-fleet expiry: replica 1 is the only survivor and is draining
    # for a swap, so a new TTL'd request parks at the fleet — and expires
    # there instead of waiting forever
    fleet.submit(Request(rid=1, prompt=[5, 6, 7], max_new_tokens=30))
    fleet.step()
    fleet.request_swap(dict(fleet.replicas[1].engine.params))
    r2 = Request(rid=2, prompt=[8, 9], max_new_tokens=2, deadline_s=0.1)
    fleet.submit(r2)
    assert r2 in fleet._pending
    t[0] = 1.0
    fleet.step()
    assert r2.outcome == "expired" and r2 not in fleet._pending
    while not fleet.idle():
        fleet.step()
    assert {r.rid: r.outcome for r in fleet.finished}[1] == "completed"


# ---------------------------------------------------------------------------
# predictor wiring
# ---------------------------------------------------------------------------

def test_llm_predictor_fleet_backed(tiny_model, tmp_path):
    import paddle_tpu.inference as inf

    prefix = str(tmp_path / "llm")
    inf.save_llm(tiny_model, prefix)
    cfg = inf.Config(prefix)
    cfg.enable_llm_engine(
        max_new_tokens=4, llm_replicas=2, max_seq_len=32, block_size=8,
        max_batch=2, prefill_buckets=(16,), decode_batch_buckets=(2,),
    )
    assert cfg.llm_replicas() == 2
    pred = inf.create_predictor(cfg)
    assert isinstance(pred, inf.LLMPredictor)
    assert pred.fleet() is not None
    assert len(pred.fleet().replicas) == 2

    rng = np.random.RandomState(9)
    ids = np.zeros((2, 10), np.int64)
    ids[0, :10] = rng.randint(0, 1024, 10)
    ids[1, :6] = rng.randint(0, 1024, 6)
    (out,) = pred.run([ids, np.array([10, 6])])
    m2 = inf.load_llm(prefix)
    for b, L in ((0, 10), (1, 6)):
        assert list(out[b]) == _greedy_oracle(m2, list(ids[b, :L]), 4)

    # repeated run() must not leak served requests into the fleet's
    # harvest list (a long-lived predictor would grow without bound)
    (out2,) = pred.run([ids, np.array([10, 6])])
    assert np.array_equal(out, out2)
    assert pred.fleet().finished == []

    clone = pred.clone()
    assert clone.fleet() is not None
    assert clone.fleet() is not pred.fleet()
    pred.try_shrink_memory()  # resets every replica pool without error


# ---------------------------------------------------------------------------
# bench capture contract
# ---------------------------------------------------------------------------

def test_fleet_bench_child_record():
    """BENCH_CHILD=fleet at tier-1 scale: the record carries every field
    tools/perf_gate.py gates (scaling_vs_1replica throughput,
    p99_tpot_swap_ms time, n_replicas/fleet_dims shape) plus per-width
    sub-records proving the swap AND the kill actually ran mid-replay."""
    import json
    import os
    import subprocess
    import sys

    bench = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py")
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu", BENCH_CHILD="fleet",
        BENCH_FLEET_VOCAB="512", BENCH_FLEET_HIDDEN="64",
        BENCH_FLEET_LAYERS="2", BENCH_FLEET_HEADS="4",
        BENCH_FLEET_KV_HEADS="2", BENCH_FLEET_FFN="176",
        BENCH_FLEET_MAX_SEQ="64", BENCH_FLEET_BLOCK="8",
        BENCH_FLEET_BATCH="4", BENCH_FLEET_REQUESTS="10",
        BENCH_FLEET_REPLICAS="1,2",
        BENCH_FLEET_BURST_REQUESTS="8",
        PADDLE_TPU_TELEMETRY="1",
    )
    r = subprocess.run([sys.executable, bench], env=env, capture_output=True,
                       text=True, timeout=360)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    for k in ("n_replicas", "n_requests", "tokens_per_sec", "p99_tpot_ms",
              "p99_tpot_swap_ms", "scaling_vs_1replica", "swap_blip_ratio",
              "replicas", "fleet_dims", "attribution",
              # round 21: the disaggregated A/B fields perf_gate gates
              "p99_ttft_burst_ms", "disagg_p99_tpot_ms",
              "ttft_burst_improvement", "fleet_prefix_hit_rate",
              "local_prefix_hit_rate", "migration_failures",
              "migration_cost_per_page_ms", "disagg_dims"):
        assert k in rec, k
    # the A/B's robustness bars: zero integrity failures, handoffs ran,
    # fleet-global prefix routing at least matches replica-local serving
    assert rec["migration_failures"] == 0
    assert rec["migrations"] >= 1
    assert rec["fleet_prefix_hit_rate"] >= rec["local_prefix_hit_rate"]
    assert rec["disagg_dims"]["prefill_replicas"] == 1
    assert rec["n_replicas"] == 2
    assert rec["fleet_dims"]["hidden"] == 64  # shrunken run records its dims
    widest = rec["replicas"]["2"]
    assert widest["completed"] == 10  # zero loss through swap + kill
    assert widest["swaps_completed"] == 1
    assert widest["replica_failures"] >= 2  # the FaultPlan kill fired
    assert rec["replicas"]["1"]["tokens_per_sec"] > 0
    # round 16: the chaos run is request-traced — the capture's breakdown
    # covers the swap window and carries cause-labeled evacuation counts
    bd = rec["slo_breakdown"]
    assert bd["n_traced"] == 10 and bd["open_spans"] == 0
    assert abs(bd["consistency"]["mean"] - 1.0) <= 0.05
    assert bd["swap_windows"] >= 1
    assert bd["causes"].get("evacuation", 0) >= 1


# ---------------------------------------------------------------------------
# round 20: disaggregated prefill/decode tiers — KV migration, fleet-global
# prefix routing, degradation ladder
# ---------------------------------------------------------------------------

def _disagg(model, *, decode_dtype="int8", **kw):
    """1 prefill (full-precision) + 1 decode replica fleet, shared tiny
    geometry; decode_dtype=None keeps the decode tier full-precision."""
    dc = _engine(model) if decode_dtype is None else _engine(
        model, kv_dtype=decode_dtype)
    return ReplicaFleet([_engine(model), dc],
                        tiers=["prefill", "decode"], **kw)


_PROMPTS = [[1, 2, 3, 4, 5], [7, 8, 9, 10, 11, 12, 13],
            [2, 4, 6, 8, 10, 12, 14, 16, 18]]


def _oracle_all(model, n=10):
    return [_greedy_oracle(model, p, n) for p in _PROMPTS]


def test_disagg_migrates_every_request_exactly(tiny_model):
    """The happy path: every request prefills on the prefill tier, its
    pages migrate, and decode finishes on the decode tier — outputs
    byte-identical to a monolithic oracle (same-dtype tiers make the
    handoff a pure page move, so exactness is unconditional), source
    pages retained (not leaked) behind the prefix index."""
    fleet = _disagg(tiny_model, decode_dtype=None)
    out = fleet.generate(_PROMPTS, max_new_tokens=10)
    assert out == _oracle_all(tiny_model)
    assert fleet.migrations_total == len(_PROMPTS)
    assert fleet.migration_failures == 0
    assert fleet.migration_fallbacks == 0
    assert fleet.migrated_pages_total > 0
    pf, dc = fleet.replicas
    # both pools returned every page (retained pages are reclaimable)
    assert pf.engine.pool.used() == 0
    assert dc.engine.pool.used() == 0


def test_disagg_int8_decode_tier_is_deterministic(tiny_model):
    """Cross-dtype tiers (f32 prefill → int8 decode): requantization at
    the migrate boundary means outputs may differ from an f32 oracle by
    quantization noise, but the pipeline is DETERMINISTIC — two
    identical runs are byte-identical — and every handoff completes
    cleanly. (Requant math exactness is pinned one test down.)"""
    out1 = _disagg(tiny_model).generate(_PROMPTS, max_new_tokens=10)
    fleet = _disagg(tiny_model)
    out2 = fleet.generate(_PROMPTS, max_new_tokens=10)
    assert out1 == out2
    assert fleet.migrations_total == len(_PROMPTS)
    assert fleet.migration_failures == 0


def test_migrated_int8_pages_match_quantize_on_write(tiny_model):
    """Requantization at migrate must be byte-identical to the decode
    pool's own quantize-on-write math: export f32 pages, convert, and
    check the int8 planes equal quantize_absmax(absmax_scale(x)) of the
    source — plus a CRC round-trip through import/export."""
    from paddle_tpu.inference import kv_cache as kvc
    from paddle_tpu.quantization.observers import absmax_scale, quantize_absmax
    import jax.numpy as jnp

    eng_f32 = _engine(tiny_model)
    eng_i8 = _engine(tiny_model, kv_dtype="int8")
    # put real KV into the f32 pool by running a prompt
    sched_out = eng_f32.pool
    fleet = ReplicaFleet([eng_f32])
    fleet.generate([_PROMPTS[2]], max_new_tokens=2)
    # the finished request retained its pages in the index — steal them
    pages = list(sched_out._retained.keys())[:1] or [1]
    payload = kvc.export_pages(eng_f32.pool, pages)
    conv = kvc.convert_payload(payload, "int8")
    for li in range(len(payload["k"])):
        src = jnp.asarray(payload["k"][li])
        sc = absmax_scale(src, axis=-1)
        want = np.asarray(quantize_absmax(src, sc[..., None]))
        assert np.array_equal(conv["k"][li], want)
        assert np.allclose(conv["k_scale"][li], np.asarray(sc))
    crcs = kvc.payload_page_crcs(conv)
    new_pages = eng_i8.pool.alloc(len(pages))
    kvc.import_pages(eng_i8.pool, new_pages, conv)
    back = kvc.export_pages(eng_i8.pool, new_pages)
    assert kvc.payload_page_crcs(back) == crcs
    # lossy direction is refused, never silently dequantized
    with pytest.raises(ValueError):
        kvc.convert_payload(conv, "f32")


@pytest.mark.parametrize("action,times", [
    ("fail", 1),        # torn handoff before export
    ("corrupt", 1),     # byte flipped in flight — CRC must catch it
    ("fail", None),     # perma-faulted site — fallback cap, then monolithic
])
def test_migration_chaos_recovers_byte_identical(tiny_model, action, times):
    """ISSUE acceptance: a FaultPlan kill mid-migration at the migrate
    site → every request completes byte-identical to the no-fault oracle
    via recompute-on-resume, zero lost/duplicated, no page leaked into
    the destination pool, and migration_failures stays 0 (chaos is an
    EXPECTED fault, not an accounting failure). Same-dtype tiers: the
    exactness claim is the point here; the recompute fallback IS the
    preemption path, whose byte-safety the scheduler suite pins."""
    fi.install_plan(fi.FaultPlan().add(
        "fleet.kv_migrate.*", action, times=times, arg=5))
    fleet = _disagg(tiny_model, decode_dtype=None)
    out = fleet.generate(_PROMPTS, max_new_tokens=10)
    fi.clear_plan()
    assert out == _oracle_all(tiny_model)
    assert fleet.migration_failures == 0
    assert fleet.migration_fallbacks >= 1
    if action == "corrupt":
        assert fleet.migration_crc_rejects >= 1
    if times is None:
        # perma-fault: capped requests finish monolithically on prefill
        assert all(
            n <= 2 for n in ([2] if not fleet._migrate_fallback_counts
                             else fleet._migrate_fallback_counts.values()))
        assert fleet.migrations_total == 0
    pf, dc = fleet.replicas
    assert pf.engine.pool.used() == 0
    assert dc.engine.pool.used() == 0


def test_tier_route_fault_site_raises_to_caller(tiny_model):
    fleet = _disagg(tiny_model)
    fi.install_plan(fi.FaultPlan().add("fleet.tier_route", "fail", times=1))
    with pytest.raises(fi.FaultInjected):
        fleet.submit(Request(rid=0, prompt=[1, 2], max_new_tokens=1))
    fi.clear_plan()
    fleet.submit(Request(rid=1, prompt=[1, 2], max_new_tokens=1))
    while not fleet.idle():
        fleet.step()
    assert len(fleet.finished) == 1


def test_disagg_chaos_coverage_zero_unobserved(tiny_model):
    """Incident-timeline coverage gate on the disagg fleet: every FaultPlan
    injection (a tier_route probe and an in-flight kv_migrate corruption)
    must be causally matched by a same-site timeline event — zero
    unobserved faults, no orphans — and triage blames the injected cause
    first. This is the fast-lane twin of the dryrun `disagg` scenario."""
    from paddle_tpu.telemetry import timeline as tl

    prev = paddle.get_flags("FLAGS_incident_timeline")["FLAGS_incident_timeline"]
    paddle.set_flags({"FLAGS_incident_timeline": True})
    tl.reset()
    try:
        fleet = _disagg(tiny_model, decode_dtype=None)
        fi.install_plan(fi.FaultPlan().add("fleet.tier_route", "fail", times=1))
        with pytest.raises(fi.FaultInjected):
            fleet.submit(Request(rid=99, prompt=[1, 2], max_new_tokens=1))
        fi.clear_plan()
        fi.install_plan(fi.FaultPlan().add(
            "fleet.kv_migrate.*", "corrupt", times=1, arg=5))
        out = fleet.generate(_PROMPTS, max_new_tokens=10)
        fi.clear_plan()
        assert out == _oracle_all(tiny_model)
        cov = tl.chaos_coverage()
        assert cov["injected"] == 2
        assert cov["observed"] == 2
        assert cov["unobserved_faults"] == 0
        assert cov["orphans"] == []
        blame = tl.triage()["blame"]
        assert blame and blame[0]["kind"] == "fault.injected"
    finally:
        paddle.set_flags({"FLAGS_incident_timeline": prev})
        tl.reset()


def test_decode_tier_death_degrades_to_monolithic(tiny_model):
    """Dead decode tier + live prefill tier = DEGRADED, not down: mode
    drops to monolithic, the prefill tier serves both phases, outputs
    stay exact, and the replica gauge carries the tier label."""
    fi.install_plan(fi.FaultPlan().add("fleet.replica_step.1", "fail",
                                       times=None))
    fleet = _disagg(tiny_model, breaker_threshold=1)
    out = fleet.generate(_PROMPTS, max_new_tokens=10)
    fi.clear_plan()
    assert out == _oracle_all(tiny_model)
    assert fleet.mode() == "monolithic"
    assert fleet.replicas[1].status == ReplicaStatus.DOWN
    fam = tm.default_registry().get("paddle_tpu_fleet_replicas")
    assert fam.labels(state="down", tier="decode").value == 1
    assert fam.labels(state="healthy", tier="prefill").value == 1
    mode = tm.default_registry().get("paddle_tpu_fleet_mode")
    assert mode.labels(mode="monolithic").value == 1
    assert mode.labels(mode="disaggregated").value == 0


def test_prefill_tier_death_streams_prefill_on_decode(tiny_model):
    """Dead prefill tier: decode replicas accept streamed prefill — and
    because their admission is streamed-only, NO prefill bucket is ever
    compiled on the decode tier even while it serves whole requests."""
    fi.install_plan(fi.FaultPlan().add("fleet.replica_step.0", "fail",
                                       times=None))
    fleet = _disagg(tiny_model, decode_dtype=None, breaker_threshold=1)
    out = fleet.generate(_PROMPTS, max_new_tokens=10)
    fi.clear_plan()
    assert out == _oracle_all(tiny_model)
    assert fleet.mode() == "streamed_prefill"
    dc = fleet.replicas[1]
    assert not any(k[0] == "prefill" for k in dc.engine._compiled)


def test_revive_resplits_one_replica_at_a_time(tiny_model):
    """Recovery rung: revive the dead decode tier mid-backlog — mode
    returns to disaggregated, the re-split queue drains the prefill
    replica's decode-phase backlog one replica at a time, and everything
    still matches the oracle."""
    fi.install_plan(fi.FaultPlan().add("fleet.replica_step.1", "fail",
                                       times=None))
    fleet = _disagg(tiny_model, decode_dtype=None, breaker_threshold=1)
    reqs = [Request(rid=i, prompt=list(p), max_new_tokens=10)
            for i, p in enumerate(_PROMPTS)]
    for r in reqs:
        fleet.submit(r)
    # run monolithic until the first request finishes
    while len(fleet.finished) < 1:
        fleet.step()
    assert fleet.mode() == "monolithic"
    fi.clear_plan()
    fleet.revive(1)
    assert fleet.mode() == "disaggregated"
    assert fleet._resplit == [0]  # the rollout queue armed
    while not fleet.idle():
        fleet.step()
    assert fleet._resplit is None  # fully re-split
    got = _outputs(fleet)
    oracle = _oracle_all(tiny_model)
    for i in range(len(_PROMPTS)):
        assert got[i] == oracle[i], i
    assert fleet.migration_failures == 0


def test_per_tier_prewarm_zero_cross_tier_compiles(tiny_model):
    """Satellite: prewarm warms each tier's OWN bucket family — the
    decode tier compiles zero prefill buckets, and serving traffic after
    prewarm triggers zero new compiles anywhere (ledger-verified)."""
    from paddle_tpu import compile_cache as _cc
    fleet = _disagg(tiny_model, decode_dtype=None)
    fleet.prewarm()
    pf, dc = fleet.replicas
    assert any(k[0] == "prefill" for k in pf.engine._compiled)
    assert any(k[0] == "decode" for k in pf.engine._compiled)
    assert not any(k[0] == "prefill" for k in dc.engine._compiled)
    assert any(k[0] == "decode" for k in dc.engine._compiled)
    before = len([e for e in _cc.events()
                  if e.get("origin") == "serving" and e["outcome"] == "miss"])
    out = fleet.generate(_PROMPTS, max_new_tokens=10)
    after = len([e for e in _cc.events()
                 if e.get("origin") == "serving" and e["outcome"] == "miss"])
    assert out == _oracle_all(tiny_model)
    assert after == before  # fully warm: zero cross-tier (or any) compiles


def test_fleet_prefix_owner_routes_to_chain_holder(tiny_model):
    """Fleet-global prefix routing: after a request completes on one
    replica, a sessionless request SHARING its prefix routes to that
    replica (reason=prefix) and serves prompt pages from the retained
    chain instead of recomputing them."""
    fleet = ReplicaFleet([_engine(tiny_model), _engine(tiny_model)])
    routed = tm.counter("paddle_tpu_fleet_routed_total", "", ("reason",))
    prefix_before = routed.labels(reason="prefix").value
    long_prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17]
    r0 = Request(rid=0, prompt=list(long_prompt), max_new_tokens=2)
    fleet.submit(r0)
    while not fleet.idle():
        fleet.step()
    assert fleet._prefix_owner  # ownership published at harvest
    r1 = Request(rid=1, prompt=list(long_prompt), max_new_tokens=2)
    fleet.submit(r1)
    while not fleet.idle():
        fleet.step()
    assert routed.labels(reason="prefix").value == prefix_before + 1
    assert fleet.prefix_routed_total == 1
    assert r1.cached_tokens > 0  # the chain actually served pages
    assert (r1.prompt[r1.prompt_len:] + list(r1.generated)
            == _greedy_oracle(tiny_model, long_prompt, 2))


def test_prefix_ownership_fails_over_on_replica_death(tiny_model):
    """A dead replica's chain entries drop from the fleet map — prefix
    intake must never route toward pages nobody can serve."""
    fleet = ReplicaFleet([_engine(tiny_model), _engine(tiny_model)],
                         breaker_threshold=1)
    long_prompt = list(range(1, 18))
    fleet.generate([long_prompt], max_new_tokens=2)
    owner_idx = next(iter(fleet._prefix_owner.values()))
    # a prefix-sharing request routes TO the owner — whose every step
    # now faults, so the breaker kills it with the request in flight
    fi.install_plan(fi.FaultPlan().add(
        f"fleet.replica_step.{owner_idx}", "fail", times=None))
    fleet.submit(Request(rid=5, prompt=list(long_prompt), max_new_tokens=2))
    while not fleet.idle():
        fleet.step()
    fi.clear_plan()
    assert fleet.replicas[owner_idx].status == ReplicaStatus.DOWN
    assert owner_idx not in set(fleet._prefix_owner.values())
    # the evacuated request still finished exactly on the survivor
    got = _outputs(fleet)
    assert got[5] == _greedy_oracle(tiny_model, long_prompt, 2)
    # a NEW prefix-sharing request routes fine (least-loaded survivor)
    out = fleet.generate([long_prompt], max_new_tokens=2)
    assert out[0] == _greedy_oracle(tiny_model, long_prompt, 2)


def test_hot_swap_invalidates_prefix_fleet_wide(tiny_model):
    """request_swap broadcasts invalidation BEFORE the rollout starts:
    the router's owner map and every replica's local index empty out —
    no post-swap request can be routed toward old-weight K/V."""
    eng0, eng1 = _engine(tiny_model), _engine(tiny_model)
    fleet = ReplicaFleet([eng0, eng1])
    fleet.generate([list(range(1, 18))], max_new_tokens=2)
    assert fleet._prefix_owner
    fleet.request_swap(dict(eng0.params))
    assert not fleet._prefix_owner
    assert len(eng0.pool._prefix) == 0 and len(eng1.pool._prefix) == 0
    while not fleet.idle():
        fleet.step()
    assert eng0.weights_version == 1 and eng1.weights_version == 1


def test_tiered_fleet_validation(tiny_model):
    e = _engine(tiny_model)
    with pytest.raises(ValueError, match="at least one prefill"):
        ReplicaFleet([e, _engine(tiny_model)], tiers=["decode", "decode"])
    with pytest.raises(ValueError, match="tiers has"):
        ReplicaFleet([e], tiers=["prefill", "decode"])
    with pytest.raises(ValueError, match="unknown tier"):
        ReplicaFleet([e, _engine(tiny_model)], tiers=["prefill", "draft"])
    with pytest.raises(ValueError, match="share KV geometry"):
        ReplicaFleet(
            [e, InferenceEngine(tiny_model, max_seq_len=32, block_size=8,
                                max_batch=4)],
            tiers=["prefill", "decode"])


def test_all_down_tiered_reports_per_tier_detail(tiny_model):
    fi.install_plan(
        fi.FaultPlan()
        .add("fleet.replica_step.0", "fail", times=None)
        .add("fleet.replica_step.1", "fail", times=None))
    fleet = _disagg(tiny_model, breaker_threshold=1)
    fleet.submit(Request(rid=0, prompt=[1, 2, 3], max_new_tokens=4))
    with pytest.raises(NoHealthyReplica, match=r"\[prefill: .*\[decode: "):
        for _ in range(50):
            fleet.step()
    fi.clear_plan()


def test_disagg_replay_accounting_zero_loss_under_chaos(tiny_model):
    """fleet_replay over a tiered fleet with migrate-site chaos: zero
    lost, zero duplicated, migration fields surfaced in the stats."""
    fi.install_plan(fi.FaultPlan().add("fleet.kv_migrate.*", "fail",
                                       times=2))
    fleet = _disagg(tiny_model)
    reqs = [Request(rid=i, prompt=list(p), max_new_tokens=8,
                    arrival_time=0.01 * i)
            for i, p in enumerate(_PROMPTS)]
    stats = fleet_replay(fleet, reqs, max_wall_s=120)
    fi.clear_plan()
    assert stats["lost"] == 0 and stats["duplicated"] == 0
    assert stats["migration_failures"] == 0
    assert stats["migration_fallbacks"] >= 1
    assert stats["migrations"] >= 1
    assert stats["completed"] == len(_PROMPTS)
