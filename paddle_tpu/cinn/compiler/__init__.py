"""reference cinn/compiler: compile(program) — here jax.jit IS the compile
step; this namespace keeps configs importable."""


def compile(*args, **kwargs):  # noqa: A001
    raise RuntimeError(
        "CINN compile is subsumed by XLA (paddle_tpu.jit.to_static / jax.jit)")


__all__ = ["compile"]
