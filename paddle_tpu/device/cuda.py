"""paddle.device.cuda compat surface (reference: python/paddle/device/cuda/).

This framework targets TPU; these functions answer honestly about the
accelerator jax sees (paddle code probing "cuda" keeps working), and the
stream/event API maps to the no-op Stream/Event in paddle_tpu.device.
"""
from __future__ import annotations

import jax


def device_count():
    try:
        return len([d for d in jax.devices() if d.platform != "cpu"])
    except Exception:
        return 0


def current_device_id():
    return 0


def get_device_name(device_id=0):
    devs = jax.devices()
    return devs[min(device_id, len(devs) - 1)].device_kind


def get_device_capability(device_id=0):
    return (0, 0)  # CUDA compute capability has no TPU analog


def get_device_properties(device=None):
    class _Props:
        def __init__(self, d):
            self.name = d.device_kind
            self.major, self.minor = 0, 0
            self.total_memory = getattr(d, "memory_stats", lambda: {})().get("bytes_limit", 0)
            self.multi_processor_count = 0

    devs = jax.devices()
    return _Props(devs[0])


def max_memory_allocated(device=None):
    stats = _stats(device)
    return stats.get("peak_bytes_in_use", 0)


def max_memory_reserved(device=None):
    return max_memory_allocated(device)


def memory_allocated(device=None):
    return _stats(device).get("bytes_in_use", 0)


def memory_reserved(device=None):
    return memory_allocated(device)


def _stats(device):
    try:
        d = jax.devices()[0]
        return d.memory_stats() or {}
    except Exception:
        return {}


def empty_cache():
    return None


def synchronize(device=None):
    from . import synchronize as _sync

    return _sync(device)


def stream_guard(stream):
    from . import stream_guard as _sg

    return _sg(stream)


def current_stream(device=None):
    from . import current_stream as _cs

    return _cs(device)


def __getattr__(name):
    # reference device/cuda/__init__.py exports Stream/Event here too — the
    # ordering no-ops from paddle_tpu.device (XLA's dispatch queue orders
    # work). Lazy: this module imports before the parent finishes defining
    # them.
    if name in ("Stream", "Event"):
        import paddle_tpu.device as _d

        return getattr(_d, name)
    raise AttributeError(name)


def __dir__():
    return sorted(list(globals()) + ["Stream", "Event"])
