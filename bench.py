"""Benchmark: ERNIE-3.0-base MLM pretrain throughput on one TPU chip.

The BASELINE.json headline metric is "ERNIE-3.0 tokens/sec/chip" (the
reference publishes no number — BASELINE.md records published: {} — so
vs_baseline reports measured MFU as the comparable hardware-efficiency
figure; see BASELINE.md).

Timing methodology (round 2): the axon tunnel DEFERS device execution until
a host fetch — `block_until_ready` alone returns early, which made round-1
numbers phantom (3.9 ms/step "measured" vs ~80 ms real). Every timed region
here therefore ends in a host fetch of a scalar that data-depends on the
work, and step time is the SLOPE between a short and a long run, which
cancels the ~100 ms constant fetch latency. Peak is measured the same way:
matmuls chained inside one compiled fori_loop reduced to a fetched scalar.

Run: python bench.py            -> one JSON line on stdout
Env: BENCH_STEPS / BENCH_BATCH / BENCH_SEQ to override.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import numpy as np
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models import ErnieForMaskedLM, ErnieModel

    steps = max(10, int(os.environ.get("BENCH_STEPS", 30)))
    batch = int(os.environ.get("BENCH_BATCH", 64))
    seq = int(os.environ.get("BENCH_SEQ", 128))

    paddle.seed(0)
    model = ErnieForMaskedLM(
        ErnieModel(
            vocab_size=40000, hidden_size=768, num_hidden_layers=12,
            num_attention_heads=12, intermediate_size=3072,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        )
    )
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(), weight_decay=0.01)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 40000, (batch, seq)).astype(np.int64))
    labels = paddle.to_tensor(rng.randint(0, 40000, (batch, seq)).astype(np.int64))

    @paddle.jit.to_static
    def train_step(ids, labels):
        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            loss, _ = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    def run(n):
        """n steps ending in a host fetch (forces the whole chain)."""
        t0 = time.perf_counter()
        for _ in range(n):
            loss = train_step(ids, labels)
        val = float(loss.numpy())
        return time.perf_counter() - t0, val

    # warmup: recording run + compile + steady steps
    run(3)
    short = max(2, steps // 4)
    t_short, _ = run(short)
    t_long, final_loss = run(steps)
    # slope: per-step time with the constant fetch latency cancelled
    dt_step = (t_long - t_short) / (steps - short)

    tokens_per_sec = batch * seq / dt_step

    # MFU: 6 * matmul-params per token (fwd+bwd). Word embeddings are a
    # lookup on input BUT also the tied MLM decoder matmul, so they count
    # once; position/token-type embeddings are pure lookups and don't.
    n_params = sum(p.size for p in model.parameters())
    pos = model.ernie.embeddings.position_embeddings.weight.size
    tok = model.ernie.embeddings.token_type_embeddings.weight.size
    flops_per_token = 6 * (n_params - pos - tok)
    achieved = tokens_per_sec * flops_per_token
    # Peak is MEASURED on this device (chained bf16 matmuls inside one
    # compiled loop, scalar-reduced and host-fetched), not read from a spec
    # table: tunneled/virtualized backends report a device_kind whose public
    # TFLOPs bear no relation to what the tunnel delivers.
    peak = _measured_peak_flops()
    mfu = achieved / peak if peak else 0.0

    print(
        json.dumps(
            {
                "metric": "ernie3.0-base tokens/sec/chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(mfu, 4),
                "detail": {
                    "steps": steps,
                    "batch": batch,
                    "seq": seq,
                    "ms_per_step": round(dt_step * 1000, 2),
                    "final_loss": final_loss,
                    "measured_peak_tflops": round(peak / 1e12, 1),
                    "mfu_note": "vs_baseline = model FLOPs / measured bf16 matmul peak on this device; reference publishes no number",
                },
            }
        )
    )


def _measured_peak_flops(n=16384, iters=10):
    """Best sustained bf16 matmul rate: the chain runs inside ONE compiled
    fori_loop (no per-iter dispatch) and ends in a host-fetched scalar so
    deferred-execution backends can't skip the work."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    a = jnp.asarray(np.random.randn(n, n), jnp.bfloat16)
    b = jnp.asarray(np.eye(n) + 1e-3, jnp.bfloat16)

    @jax.jit
    def chain(a, b):
        c = jax.lax.fori_loop(0, iters, lambda i, c: c @ b, a)
        return jnp.sum(c.astype(jnp.float32))

    float(chain(a, b))  # warm + compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(chain(a, b))
        best = min(best, time.perf_counter() - t0)
    return 2 * n**3 * iters / best


if __name__ == "__main__":
    main()
