"""Unified mesh/SpecLayout sharding layer (round 10): the one global mesh,
the declarative per-parameter table, serialization for checkpoint metadata,
and the elastic largest-valid-mesh policy."""
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.elastic import manager as elastic_manager
from paddle_tpu.distributed.sharding import spec_layout as sl


def _fleet_init(**hybrid):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = hybrid
    fleet.init(is_collective=True, strategy=strategy)


# ---------------------------------------------------------------------------
# the global mesh
# ---------------------------------------------------------------------------


def test_fleet_init_registers_the_global_mesh():
    _fleet_init(dp_degree=4, mp_degree=2)
    mesh = sl.global_mesh()
    assert mesh.shape["dp"] == 4 and mesh.shape["mp"] == 2
    assert mesh is fleet.get_hybrid_communicate_group().mesh
    assert sl.mesh_degrees(mesh) == {"data": 4, "fsdp": 1, "tp": 2, "pp": 1, "sep": 1}


def test_build_mesh_axis_order_and_bounds():
    mesh = sl.build_mesh(data=2, tp=2, pp=2)
    assert mesh.devices.shape == (2, 2, 1, 1, 2)
    assert mesh.axis_names == ("dp", "pp", "sharding", "sep", "mp")
    with pytest.raises(ValueError):
        sl.build_mesh(data=16, tp=2)


# ---------------------------------------------------------------------------
# SpecLayout canonical layouts
# ---------------------------------------------------------------------------


def test_canonical_layout_specs():
    lo = sl.layout()
    assert lo.column_weight() == P(None, "mp")
    assert lo.column_bias() == P("mp")
    assert lo.row_weight() == P("mp", None)
    assert lo.vocab_embedding() == P("mp", None)
    assert lo.replicated(2) == P(None, None)
    assert lo.seq_activation(3) == P("mp", None, None)
    assert lo.tp_activation(3) == P(None, None, "mp")
    assert lo.batch_activation(2) == P("dp", None)
    assert lo.stage_stacked(3) == P("pp", None, None)
    assert lo.stage_stacked(3, inner=P(None, "mp")) == P("pp", None, "mp")
    # ZeRO first-divisible-dim shard
    assert lo.fsdp_shard((8, 4), 4) == P("sharding", None)
    assert lo.fsdp_shard((6, 4), 4) == P(None, None)
    assert lo.fsdp_shard((8,), 4, axis="dp") == P("dp")


def test_mp_layers_compile_through_the_table():
    _fleet_init(dp_degree=4, mp_degree=2)
    col = fleet.ColumnParallelLinear(16, 32, gather_output=False)
    row = fleet.RowParallelLinear(32, 4, input_is_parallel=True)
    lo = fleet.get_hybrid_communicate_group().layout
    assert col.weight._value.sharding.spec == lo.column_weight()
    assert col.bias._value.sharding.spec == lo.column_bias()
    assert row.weight._value.sharding.spec == lo.row_weight()
    # the replicated row bias is EXPLICITLY mesh-placed (reshard-on-load
    # targets it; an uncommitted single-device default would strand it)
    assert row.bias._value.sharding.spec == lo.replicated(1)
    assert len(row.bias._value.devices()) == 8


# ---------------------------------------------------------------------------
# LayoutTable
# ---------------------------------------------------------------------------


def test_layout_table_rules_and_fallback():
    table = sl.transformer_layout_table(dp=4)
    assert table.spec_for("enc.layers.0.self_attn.q_proj.weight", (64, 64)) == P(None, "mp")
    assert table.spec_for("enc.layers.0.self_attn.out_proj.weight", (64, 64)) == P("mp", None)
    assert table.spec_for("enc.layers.0.linear1.weight", (64, 256)) == P(None, "mp")
    assert table.spec_for("enc.layers.0.linear2.weight", (256, 64)) == P("mp", None)
    assert table.spec_for("embeddings.word_embeddings.weight", (1024, 64)) == P("mp", None)
    # biases miss the weight rules and fall back to the ZeRO-over-dp shard
    assert table.spec_for("enc.layers.0.self_attn.q_proj.bias", (64,)) == P("dp")
    assert table.spec_for("embeddings.layer_norm.weight", (6,)) == P(*[None])
    assert table.spec_for("pos_embeddings.weight", (128, 64)) == P("dp", None)
    assert table.spec_for("scalar_state", ()) == P()


def test_layout_table_custom_axis_names_and_roles():
    lo = sl.SpecLayout(data_axis="dp", tp_axis="tp")
    table = sl.LayoutTable(
        rules=[("*.w", "column"), ("*.frozen", lambda l, n, s: l.replicated(len(s)))],
        layout=lo,
        default="fsdp:2",
    )
    assert table.spec_for("block.w", (4, 4)) == P(None, "tp")
    assert table.spec_for("block.frozen", (4, 4)) == P(None, None)
    assert table.spec_for("other", (4, 4)) == P("sharding", None)
    with pytest.raises(ValueError):
        sl.LayoutTable([("*", "no_such_role")]).spec_for("x", (2,))


# ---------------------------------------------------------------------------
# serialization (checkpoint metadata)
# ---------------------------------------------------------------------------


def test_spec_and_mesh_meta_round_trip():
    spec = P(None, ("sharding", "mp"), "dp")
    meta = sl.spec_to_meta(spec)
    assert meta == (None, ("sharding", "mp"), "dp")
    assert sl.meta_to_spec(meta) == spec
    assert sl.spec_to_meta(None) is None and sl.meta_to_spec(None) is None

    mesh = sl.build_mesh(data=4, tp=2)
    mm = sl.mesh_to_meta(mesh)
    assert mm["n_devices"] == 8
    assert ("dp", 4) in mm["axes"] and ("mp", 2) in mm["axes"]

    t = paddle.to_tensor(np.zeros((4, 4), "float32"))
    sm = sl.sharding_to_meta(t._value.sharding)
    assert sm["spec"] is None or isinstance(sm["spec"], tuple)


# ---------------------------------------------------------------------------
# elastic policy
# ---------------------------------------------------------------------------


def test_plan_elastic_degrees_policy():
    # tp survives a single-device loss; dp absorbs it
    assert sl.plan_elastic_degrees(7, {"data": 4, "tp": 2}) == {
        "tp": 2, "pp": 1, "sep": 1, "fsdp": 1, "data": 3, "world": 6,
    }
    # tp shrinks only to a divisor, and only when the survivors force it
    assert sl.plan_elastic_degrees(3, {"data": 2, "tp": 4})["tp"] == 2
    assert sl.plan_elastic_degrees(1, {"tp": 8}) == {
        "tp": 1, "pp": 1, "sep": 1, "fsdp": 1, "data": 1, "world": 1,
    }
    # pp yields after tp
    plan = sl.plan_elastic_degrees(5, {"tp": 2, "pp": 2})
    assert plan["tp"] == 2 and plan["pp"] == 2 and plan["world"] == 4


def test_elastic_manager_mirror_stays_in_lockstep():
    """fleet.elastic.manager mirrors plan_elastic_degrees so the launcher
    process never imports jax — the two implementations must agree."""
    cases = [
        (7, {"data": 4, "tp": 2}),
        (6, {"data": 2, "tp": 4}),
        (5, {"tp": 4, "pp": 2}),
        (12, {"data": 2, "tp": 2, "pp": 2, "fsdp": 2}),
        (1, {"tp": 8, "sep": 3}),
        (9, {}),
    ]
    for n, degrees in cases:
        assert elastic_manager.plan_elastic_degrees(n, degrees) == sl.plan_elastic_degrees(
            n, degrees
        ), (n, degrees)
    assert elastic_manager.CANONICAL_AXES == sl.CANONICAL_AXES


def test_largest_valid_mesh_builds_on_survivors():
    mesh = sl.largest_valid_mesh(7, {"data": 4, "tp": 2})
    assert mesh.devices.size == 6
    assert mesh.shape["dp"] == 3 and mesh.shape["mp"] == 2


def test_degree_keys_accept_fleet_names_and_warn_on_typos(capsys):
    """Operators key degrees by fleet axis names (mp/dp/sharding) as often
    as by canonical roles; both must plan identically, and a typo'd key
    must warn instead of silently planning tp=1 (which would reshard a
    tp-sharded model fully replicated — an HBM OOM on real hardware)."""
    assert sl.plan_elastic_degrees(7, {"dp": 4, "mp": 2}) == sl.plan_elastic_degrees(
        7, {"data": 4, "tp": 2}
    )
    assert elastic_manager.plan_elastic_degrees(7, {"dp": 4, "mp": 2}) == (
        sl.plan_elastic_degrees(7, {"data": 4, "tp": 2})
    )
    # a prior plan's "world" output round-trips silently
    plan = sl.plan_elastic_degrees(8, {"tp": 2})
    assert sl.plan_elastic_degrees(8, plan) == plan
    capsys.readouterr()
    sl.plan_elastic_degrees(8, {"tp ": 2})
    assert "unknown parallel-degree key 'tp '" in capsys.readouterr().err
    elastic_manager.plan_elastic_degrees(8, {"modelp": 2})
    assert "unknown parallel-degree key 'modelp'" in capsys.readouterr().err


def test_fleet_init_honors_elastic_plan_env(monkeypatch):
    """The loop the launcher closes: a relaunched worker still carries its
    ORIGINAL hybrid_configs (dp=4 x mp=2 needs 8 devices); with
    PADDLE_ELASTIC_PLAN exported by _elastic_restart, fleet.init lands on
    the planned survivors' mesh instead of dying on world-size > devices
    and crash-looping the pod."""
    plan = sl.plan_elastic_degrees(6, {"data": 4, "tp": 2})
    monkeypatch.setenv("PADDLE_ELASTIC_PLAN", __import__("json").dumps(plan))
    _fleet_init(dp_degree=4, mp_degree=2)  # stale degrees: would need 8
    mesh = fleet.get_hybrid_communicate_group().mesh
    assert mesh.shape["dp"] == 3 and mesh.shape["mp"] == 2
    assert mesh.devices.size == 6
    monkeypatch.delenv("PADDLE_ELASTIC_PLAN")
    _fleet_init(dp_degree=4, mp_degree=2)  # plan gone: back to the strategy
    assert fleet.get_hybrid_communicate_group().mesh.devices.size == 8


def test_fleet_init_survives_garbage_elastic_plan(monkeypatch, capsys):
    monkeypatch.setenv("PADDLE_ELASTIC_PLAN", "{not json")
    _fleet_init(dp_degree=2, mp_degree=2)
    assert fleet.get_hybrid_communicate_group().mesh.shape["dp"] == 2
    assert "unparseable PADDLE_ELASTIC_PLAN" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# group-sharded + pipeline layouts ride the same table
# ---------------------------------------------------------------------------


def test_group_sharded_placement_uses_fsdp_layout():
    from paddle_tpu.distributed.fleet.meta_parallel.sharding import (
        group_sharded_utils as gsu,
    )

    assert gsu.shard_axis_spec((8, 2), 8, "sharding") == sl.layout().fsdp_shard((8, 2), 8)
    assert gsu.shard_axis_spec((6, 2), 8, "sharding") == P(None, None)


def test_stacked_stage_spec_matches_layout():
    from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import _stacked_spec

    assert _stacked_spec(3, "pp") == sl.layout().stage_stacked(3)
    assert _stacked_spec(2, "custom_pp") == P("custom_pp", None)
