"""Step-level performance attribution: XLA cost/memory capture + HBM census.

Reference parity: python/paddle/profiler/profiler_statistic.py's per-op
FLOPs/memory tables are fed by CUPTI on GPU; a TPU-native rebuild gets the
same answer from XLA itself — `compiled.cost_analysis()` (FLOPs, HBM bytes
accessed) and `compiled.memory_analysis()` (argument/output/temp/peak
memory) captured AT COMPILE TIME for every compiled program. The XProf
"where did the step go" roles covered here:

1. **Per-program cost records** — the static `Executor` compile path, the
   `to_static` trace, and the fused-optimizer bucket kernels call
   `record_compiled(origin, name, ...)` when a program finishes compiling;
   each record carries FLOPs, bytes accessed, the memory breakdown, and the
   compile wall time, and the latest numbers per origin land in the
   telemetry registry (`paddle_tpu_program_*` gauges).

2. **Live-HBM accounting** — `live_array_census()` walks
   `jax.live_arrays()` into count/bytes by dtype (and by annotated module,
   see `annotate_module`); `sample_watermark()` is the cheap step-boundary
   probe that tracks the process-lifetime high-water mark (sampled by
   `Optimizer.step`, by guardian anomalies, and included in flight-recorder
   crash dumps).

3. **Roofline** — `roofline(flops, bytes, seconds)` reports achieved vs
   peak FLOP/s and HBM bytes/s against a per-platform peak table
   (`DEFAULT_PEAK_TABLE`, CPU fallback included) so `bench.py` can emit
   `detail.attribution` (mfu, bandwidth utilization, compute/memory bound)
   alongside every timing.

4. **`perf_report()`** — the queryable JSON summary
   (`paddle.profiler.perf_report()`): programs + census + watermark.

Gating: collection sites check `telemetry.enabled()` (the
`PADDLE_TPU_TELEMETRY` flag) — disabled means record nothing and pay one
cached-bool read. Explicit queries (`perf_report`, `live_array_census`)
always work; they read what was collected.
"""
from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

from .. import telemetry as _tm

# bounded record store: old programs age out instead of growing without
# limit under long guard-cache-thrashing runs
_MAX_RECORDS = 256

_lock = threading.Lock()
_records: deque = deque(maxlen=_MAX_RECORDS)
_serial = [0]
_watermark: Dict[str, object] = {
    "peak_hbm_bytes": 0,
    "peak_at": None,
    "peak_tag": None,
    "live_bytes": 0,
    "live_count": 0,
    "samples": 0,
}
# module annotation registry: name -> [weakref to framework Tensor]
_module_tensors: Dict[str, list] = {}


# ---------------------------------------------------------------------------
# per-program cost/memory records
# ---------------------------------------------------------------------------

def _as_cost_dict(ca) -> dict:
    """Normalize cost_analysis() across jax versions: older jax returns a
    one-element list of dicts, newer returns the dict directly."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return {}
    return dict(ca)


_MEM_FIELDS = (
    ("generated_code_size_in_bytes", "generated_code_bytes"),
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
)


def _as_memory_dict(ma) -> dict:
    """Normalize memory_analysis(): a CompiledMemoryStats object (attrs) or
    a mapping, depending on backend/version."""
    if ma is None:
        return {}
    out = {}
    for attr, name in _MEM_FIELDS:
        v = getattr(ma, attr, None)
        if v is None and isinstance(ma, dict):
            v = ma.get(attr)
        if v is not None:
            out[name] = int(v)
    if out:
        # aliased (donated) argument bytes are reused by outputs, so they
        # count once; this is the program's device-memory footprint, not the
        # process high-water mark (that's the live-array watermark)
        out["peak_bytes"] = (
            out.get("argument_bytes", 0)
            + out.get("output_bytes", 0)
            + out.get("temp_bytes", 0)
            + out.get("generated_code_bytes", 0)
            - out.get("alias_bytes", 0)
        )
    return out


def record_compiled(
    origin: str,
    name: str,
    lowered=None,
    compiled=None,
    compile_seconds: Optional[float] = None,
    extra: Optional[dict] = None,
) -> Optional[dict]:
    """Capture one compiled program's XLA cost + memory analysis.

    Call sites are compile paths (static Executor, to_static, fused bucket
    build) — this must never break them: every analysis read is fenced, and
    a platform without cost analysis still yields a record (marked
    ``available: False``) so the caller can report "attribution
    unavailable" instead of silently dropping the program.

    Returns the record, or None when telemetry is disabled.
    """
    if not _tm.enabled():
        return None
    cost: dict = {}
    mem: dict = {}
    for src in (compiled, lowered):
        if src is None or cost:
            continue
        try:
            cost = _as_cost_dict(src.cost_analysis())
        except Exception:
            cost = {}
    if compiled is not None:
        try:
            mem = _as_memory_dict(compiled.memory_analysis())
        except Exception:
            mem = {}
    flops = float(cost.get("flops", 0.0) or 0.0)
    nbytes = float(cost.get("bytes accessed", 0.0) or 0.0)
    with _lock:
        _serial[0] += 1
        serial = _serial[0]
    rec = {
        "serial": serial,
        "origin": str(origin),
        "name": str(name),
        "platform": platform_name(),
        "flops": flops,
        "bytes_accessed": nbytes,
        "transcendentals": float(cost.get("transcendentals", 0.0) or 0.0),
        "memory": mem,
        "peak_memory_bytes": int(mem.get("peak_bytes", 0)),
        "compile_seconds": (
            float(compile_seconds) if compile_seconds is not None else None
        ),
        "recorded_at": time.time(),
        "available": bool(cost) or bool(mem),
    }
    if extra:
        rec.update(extra)
    with _lock:
        _records.append(rec)
    try:
        _tm.counter(
            "paddle_tpu_perf_programs_recorded_total",
            "compiled programs captured by the attribution layer", ("origin",),
        ).labels(origin=rec["origin"]).inc()
        # latest-per-origin gauges: bounded cardinality (origins are the few
        # compile paths, not per-program names) — per-program detail lives
        # in perf_report()
        _tm.gauge(
            "paddle_tpu_program_flops",
            "FLOPs of the most recently compiled program", ("origin",),
        ).labels(origin=rec["origin"]).set(flops)
        _tm.gauge(
            "paddle_tpu_program_hbm_bytes",
            "HBM bytes accessed by the most recently compiled program",
            ("origin",),
        ).labels(origin=rec["origin"]).set(nbytes)
        _tm.gauge(
            "paddle_tpu_program_peak_memory_bytes",
            "XLA memory-analysis footprint of the most recently compiled "
            "program", ("origin",),
        ).labels(origin=rec["origin"]).set(rec["peak_memory_bytes"])
    except Exception:
        pass  # a telemetry schema clash must never break a compile path
    return rec


def program_records(origin: Optional[str] = None,
                    name: Optional[str] = None) -> List[dict]:
    """Recorded programs in compile order (oldest first), optionally
    filtered by origin and/or name. Returns copies."""
    with _lock:
        recs = list(_records)
    if origin is not None:
        recs = [r for r in recs if r["origin"] == origin]
    if name is not None:
        recs = [r for r in recs if r["name"] == name]
    return [dict(r) for r in recs]


# ---------------------------------------------------------------------------
# live-HBM accounting
# ---------------------------------------------------------------------------

def annotate_module(name: str, module) -> None:
    """Tag a Layer (or an iterable of Tensors) so the census reports its
    live bytes under `by_module[name]`. Weak references: annotation never
    extends tensor lifetime, and dead entries are pruned at census time."""
    if hasattr(module, "state_dict"):
        tensors = list(module.state_dict().values())
    else:
        tensors = list(module)
    refs = []
    for t in tensors:
        try:
            refs.append(weakref.ref(t))
        except TypeError:
            pass
    with _lock:
        _module_tensors[str(name)] = refs


def _live_totals() -> Tuple[int, int, Dict[str, dict]]:
    """(count, bytes, by_dtype) over jax.live_arrays(). Metadata-only: no
    device sync — nbytes/dtype are host-side attributes."""
    import jax

    by_dtype: Dict[str, dict] = {}
    total = 0
    count = 0
    for a in jax.live_arrays():
        try:
            nb = int(a.nbytes)
            dt = str(a.dtype)
        except Exception:
            continue  # a buffer deleted mid-walk
        total += nb
        count += 1
        st = by_dtype.setdefault(dt, {"count": 0, "bytes": 0})
        st["count"] += 1
        st["bytes"] += nb
    return count, total, by_dtype


def _module_census() -> Dict[str, dict]:
    import jax

    out: Dict[str, dict] = {}
    with _lock:
        items = list(_module_tensors.items())
    for name, refs in items:
        live = []
        cnt, nb = 0, 0
        for r in refs:
            t = r()
            if t is None:
                continue
            live.append(r)
            v = getattr(t, "_value", None)
            if v is None or isinstance(v, jax.core.Tracer):
                continue
            deleted = getattr(v, "is_deleted", None)
            if deleted is not None and deleted():
                continue  # donated-away buffer
            try:
                nb += int(v.nbytes)
                cnt += 1
            except Exception:
                continue
        with _lock:
            if name in _module_tensors:
                _module_tensors[name] = live  # prune dead weakrefs
        if cnt:
            out[name] = {"count": cnt, "bytes": nb}
    return out


def live_array_census(set_gauges: bool = True) -> dict:
    """Full census of live device arrays: count/bytes by dtype and by
    annotated module. Explicit query — works with telemetry disabled; the
    gauges only publish when it is enabled."""
    count, total, by_dtype = _live_totals()
    by_module = _module_census()
    census = {
        "count": count,
        "bytes": total,
        "by_dtype": by_dtype,
        "by_module": by_module,
    }
    if set_gauges and _tm.enabled():
        try:
            _tm.gauge(
                "paddle_tpu_hbm_live_arrays", "live device arrays"
            ).set(count)
            _tm.gauge(
                "paddle_tpu_hbm_live_bytes_total", "live device bytes"
            ).set(total)
            g = _tm.gauge(
                "paddle_tpu_hbm_live_bytes",
                "live device bytes by dtype", ("dtype",),
            )
            for dt, st in by_dtype.items():
                g.labels(dtype=dt).set(st["bytes"])
            gm = _tm.gauge(
                "paddle_tpu_hbm_module_bytes",
                "live device bytes by annotated module", ("module",),
            )
            for m, st in by_module.items():
                gm.labels(module=m).set(st["bytes"])
        except Exception:
            pass
    return census


# step-boundary sampling throttle: jax.live_arrays() costs O(live buffers)
# in Python wrapper construction (~20 us/array), so per-step sampling at
# thousands of live arrays would dominate a fast step. The probe
# self-throttles to >= max(_MIN_SAMPLE_GAP_S, 50x its own last cost) between
# samples, bounding steady-state overhead at ~2% while still catching the
# high-water mark's growth; rare/explicit callers (guardian anomalies,
# bench, tests) pass force=True.
_MIN_SAMPLE_GAP_S = 0.25
_sample_state = {"next_at": 0.0}


def sample_watermark(tag: str = "step", force: bool = False) -> Optional[dict]:
    """Step-boundary probe: total live bytes + high-water mark.

    Called per optimizer step and on guardian anomalies — it skips the
    by-dtype/by-module breakdown (that's the full census) and is a no-op
    when telemetry is disabled. Throttled (see _MIN_SAMPLE_GAP_S) unless
    `force`. Returns the watermark snapshot (the last one when throttled).
    """
    if not _tm.enabled():
        return None
    now = time.monotonic()
    if not force and now < _sample_state["next_at"]:
        return watermark()
    t0 = time.perf_counter()
    count, total, _ = _live_totals()
    _sample_state["next_at"] = now + max(
        _MIN_SAMPLE_GAP_S, 50.0 * (time.perf_counter() - t0)
    )
    with _lock:
        _watermark["live_bytes"] = total
        _watermark["live_count"] = count
        _watermark["samples"] = int(_watermark["samples"]) + 1
        if total > int(_watermark["peak_hbm_bytes"]):
            _watermark["peak_hbm_bytes"] = total
            _watermark["peak_at"] = time.time()
            _watermark["peak_tag"] = str(tag)
        snap = dict(_watermark)
    try:
        _tm.gauge(
            "paddle_tpu_hbm_live_bytes_total", "live device bytes"
        ).set(total)
        _tm.gauge(
            "paddle_tpu_hbm_watermark_bytes",
            "high-water mark of live device bytes (sampled at step "
            "boundaries and on guardian anomalies)",
        ).set(snap["peak_hbm_bytes"])
    except Exception:
        pass
    return snap


def watermark() -> dict:
    with _lock:
        return dict(_watermark)


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------

# per-chip bf16 matmul peak FLOP/s and HBM bandwidth (published numbers;
# bench.py still CO-MEASURES its matmul peak — this table serves quick
# attribution and the CPU fallback where nothing is co-measured)
DEFAULT_PEAK_TABLE = {
    "tpu v4": {"flops_per_s": 275e12, "bytes_per_s": 1.2e12},
    "tpu v5e": {"flops_per_s": 197e12, "bytes_per_s": 0.82e12},
    "tpu v5p": {"flops_per_s": 459e12, "bytes_per_s": 2.77e12},
    "tpu v6e": {"flops_per_s": 918e12, "bytes_per_s": 1.64e12},
    # conservative single-socket host numbers so CPU runs report a finite,
    # comparable utilization instead of failing the lookup
    "cpu": {"flops_per_s": 1.0e11, "bytes_per_s": 5.0e10},
}


def platform_name() -> str:
    """Lowercased device kind ('tpu v4', 'cpu', ...)."""
    try:
        import jax

        d = jax.devices()[0]
        kind = getattr(d, "device_kind", None) or d.platform
        return str(kind).lower()
    except Exception:
        return "unknown"


def peak_for(platform: Optional[str] = None,
             peak_table: Optional[dict] = None) -> Tuple[str, dict]:
    """(matched platform key, {flops_per_s, bytes_per_s}) with substring
    matching ('TPU v4 lite' matches 'tpu v4') and a CPU fallback."""
    table = peak_table if peak_table is not None else DEFAULT_PEAK_TABLE
    p = (platform or platform_name()).lower()
    if p in table:
        return p, dict(table[p])
    for k in table:
        if k != "cpu" and (k in p or p in k):
            return k, dict(table[k])
    fb = table.get("cpu", DEFAULT_PEAK_TABLE["cpu"])
    return "cpu", dict(fb)


def roofline(flops, bytes_accessed, seconds, platform: Optional[str] = None,
             peak_table: Optional[dict] = None) -> dict:
    """Achieved-vs-peak utilization for one measured region.

    `flops`/`bytes_accessed` come from the program's cost record, `seconds`
    from a real measurement (slope-timed step, profiled span). `mfu` is
    achieved FLOP/s over peak FLOP/s; `hbm_util` likewise for bandwidth;
    `bound` names the roofline regime the measurement sits in.
    """
    seconds = float(seconds)
    if seconds <= 0:
        raise ValueError(f"roofline needs a positive duration, got {seconds}")
    plat, peak = peak_for(platform, peak_table)
    achieved_f = float(flops) / seconds
    achieved_b = float(bytes_accessed) / seconds
    mfu = achieved_f / peak["flops_per_s"]
    hbm_util = achieved_b / peak["bytes_per_s"]
    return {
        "platform": plat,
        "seconds": seconds,
        "flops": float(flops),
        "bytes": float(bytes_accessed),
        "achieved_flops_per_s": achieved_f,
        "achieved_bytes_per_s": achieved_b,
        "peak_flops_per_s": float(peak["flops_per_s"]),
        "peak_bytes_per_s": float(peak["bytes_per_s"]),
        "mfu": mfu,
        "hbm_util": hbm_util,
        "bound": "compute" if mfu >= hbm_util else "memory",
    }


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------

_REPORT_KEYS = (
    "version", "generated_at", "platform", "telemetry_enabled",
    "programs", "live_arrays", "hbm_watermark", "input_pipeline",
    "serving", "compilation",
)
_PROGRAM_KEYS = (
    "serial", "origin", "name", "platform", "flops", "bytes_accessed",
    "memory", "peak_memory_bytes", "compile_seconds", "recorded_at",
    "available",
)


def _input_pipeline_section() -> dict:
    """The starved-vs-slow join (round 12): the streaming tier's wait
    totals + rolling-window verdict, annotated against the device-side
    story this report carries. A 'starved' step is one the roofline records
    CANNOT explain — the device was idle waiting for the host — which is
    exactly the case where chasing `programs[]` mfu would mislead."""
    try:
        from ..io.streaming import stats as _instats

        section = _instats.summary()
    except Exception as e:  # the report must not die on a partial install
        return {"verdict": "unavailable", "error": str(e)[-200:]}
    hints = {
        "starved": "host input pipeline bounds the step; device attribution "
                   "(programs[]) cannot explain the step time — fix the "
                   "reader/prefetch, not the kernels",
        "input_limited": "input wait is a visible slice of the step; both "
                         "host and device stories apply",
        "compute": "device-bound: see programs[] cost records + roofline",
    }
    section["attribution_hint"] = hints.get(section.get("verdict"))
    return section


def _serving_section() -> dict:
    """The request-trace SLO decomposition (round 16): per-component
    TTFT/TPOT attribution over sampled serving requests, or an explicit
    unavailable marker. The component sums equal the measured request wall
    time by construction (contiguous phase spans), so the `consistency`
    field doubles as a tracing-health check perf_gate enforces."""
    try:
        from ..telemetry import request_trace as _rt

        return _rt.serving_section()
    except Exception as e:  # the report must render without the serving tier
        return {"available": False, "reason": f"request_trace failed: {e}"}


def _compilation_section() -> dict:
    """The compile-lifecycle ledger rollup (round 18): event/hit/miss/
    restore counts and compile seconds by origin, plus the persistent
    store's size/entry footprint when one is configured. Answers 'what did
    cold start cost and how much of it did the cache absorb' from the same
    report that already attributes steady-state FLOPs."""
    try:
        from .. import compile_cache as _cc

        section = _cc.summary()
    except Exception as e:  # the report must render without the ledger
        return {"available": False, "reason": f"compile ledger failed: {e}"}
    try:
        st = _cc.active_store()
        section["store"] = st.stats() if st is not None else None
    except Exception:
        section["store"] = None
    return section


def perf_report(origin: Optional[str] = None) -> dict:
    """The queryable attribution summary (exported as
    `paddle.profiler.perf_report`): every recorded program's FLOPs / bytes /
    memory / compile time, the live-array census, the HBM watermark, and
    the input-pipeline starved-vs-slow verdict. Plain JSON-serializable
    dict."""
    return {
        "version": 1,
        "generated_at": time.time(),
        "platform": platform_name(),
        "telemetry_enabled": _tm.enabled(),
        "programs": program_records(origin),
        "live_arrays": live_array_census(set_gauges=False),
        "hbm_watermark": watermark(),
        "input_pipeline": _input_pipeline_section(),
        "serving": _serving_section(),
        "compilation": _compilation_section(),
    }


def validate_report(report: dict) -> dict:
    """Schema check for perf_report() output (used by tests and by consumers
    reading a report back from JSON). Raises ValueError on a malformed
    report; returns it unchanged otherwise."""
    missing = [k for k in _REPORT_KEYS if k not in report]
    if missing:
        raise ValueError(f"perf report missing keys: {missing}")
    for i, rec in enumerate(report["programs"]):
        bad = [k for k in _PROGRAM_KEYS if k not in rec]
        if bad:
            raise ValueError(f"program record {i} missing keys: {bad}")
    census = report["live_arrays"]
    for k in ("count", "bytes", "by_dtype", "by_module"):
        if k not in census:
            raise ValueError(f"live_arrays census missing {k!r}")
    if "peak_hbm_bytes" not in report["hbm_watermark"]:
        raise ValueError("hbm_watermark missing peak_hbm_bytes")
    if "verdict" not in report["input_pipeline"]:
        raise ValueError("input_pipeline missing verdict")
    if "available" not in report["serving"]:
        raise ValueError("serving section missing 'available'")
    if report["serving"].get("available") and report["serving"].get("n_traced"):
        # round 17: a populated serving section must attribute where the
        # latency wins come from (prefix reuse + speculative decoding) —
        # zeros are fine, absence means the breakdown regressed
        for k in ("cached_tokens", "spec"):
            if k not in report["serving"]:
                raise ValueError(f"serving section missing {k!r}")
    comp = report["compilation"]
    if "available" not in comp:
        raise ValueError("compilation section missing 'available'")
    if comp.get("available"):
        # round 18: a live ledger must carry the cold-start accounting —
        # zero counts are fine, absent keys mean the rollup regressed
        for k in ("hits", "misses", "hit_rate", "total_compile_seconds",
                  "by_origin"):
            if k not in comp:
                raise ValueError(f"compilation section missing {k!r}")
    return report


def snapshot_for_crash(max_programs: int = 8) -> dict:
    """Compact attribution snapshot for flight-recorder crash dumps: the
    watermark plus the newest programs' headline numbers — enough to answer
    'was this an OOM-adjacent step' without the full report."""
    recs = program_records()[-max_programs:]
    return {
        "platform": platform_name(),
        "hbm_watermark": watermark(),
        "programs": [
            {
                "origin": r["origin"],
                "name": r["name"],
                "flops": r["flops"],
                "bytes_accessed": r["bytes_accessed"],
                "peak_memory_bytes": r["peak_memory_bytes"],
                "compile_seconds": r["compile_seconds"],
            }
            for r in recs
        ],
    }


def reset() -> None:
    """Clear records, watermark, and module annotations (tests)."""
    with _lock:
        _records.clear()
        _module_tensors.clear()
        _watermark.update(
            peak_hbm_bytes=0, peak_at=None, peak_tag=None,
            live_bytes=0, live_count=0, samples=0,
        )
        _sample_state["next_at"] = 0.0
