"""Vision ops (reference: python/paddle/vision/ops.py — nms, roi_align,
roi_pool, deform_conv2d, box handling).

TPU-native design: all ops are pure-jax, static-shape, gather/scatter based —
nms is the O(n^2) mask formulation (one [N,N] IoU matrix on the MXU + a scan,
instead of the reference's sequential CUDA kernel), roi_align is bilinear
gather, deform_conv2d is the sampling-grid gather + matmul formulation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.apply import apply, apply_nograd
from ..core.tensor import Tensor


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# boxes
# ---------------------------------------------------------------------------

def box_area(boxes):
    b = _v(boxes)
    return Tensor((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def _iou_matrix(a, b):
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-10)


def box_iou(boxes1, boxes2):
    return Tensor(_iou_matrix(_v(boxes1), _v(boxes2)))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """paddle.vision.ops.nms parity. Returns kept indices (by descending
    score when scores are given, else box order)."""
    b = _v(boxes)
    n = b.shape[0]
    if scores is not None:
        s = _v(scores)
        order = jnp.argsort(-s)
    else:
        order = jnp.arange(n)
    sorted_boxes = b[order]
    if category_idxs is not None:
        # class-aware: offset boxes per category so cross-class boxes never overlap
        cat = _v(category_idxs)[order]
        span = jnp.max(b[:, 2:]) + 1.0
        sorted_boxes = sorted_boxes + (cat.astype(sorted_boxes.dtype) * span)[:, None] * jnp.ones(
            (1, 4), sorted_boxes.dtype
        )
    iou = _iou_matrix(sorted_boxes, sorted_boxes)

    def body(i, keep):
        # suppress i if any kept higher-score box overlaps it too much
        sup = jnp.any(jnp.where(jnp.arange(n) < i, (iou[i] > iou_threshold) & keep, False))
        return keep.at[i].set(~sup)

    keep = jax.lax.fori_loop(0, n, body, jnp.ones(n, bool))
    kept_sorted = jnp.nonzero(keep, size=n, fill_value=-1)[0]
    kept = jnp.where(kept_sorted >= 0, order[jnp.clip(kept_sorted, 0)], -1)
    kept_np = np.asarray(kept)
    kept_np = kept_np[kept_np >= 0]
    if top_k is not None:
        kept_np = kept_np[:top_k]
    return Tensor(jnp.asarray(kept_np, jnp.int64))


# ---------------------------------------------------------------------------
# roi align / pool
# ---------------------------------------------------------------------------

def roi_align(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0, sampling_ratio=-1, aligned=True, name=None):
    """Bilinear-sampled RoIAlign. x: [N,C,H,W]; boxes: [R,4] (x1,y1,x2,y2);
    boxes_num: [N] rois per image."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    ratio = 2 if sampling_ratio <= 0 else sampling_ratio

    bn = _v(boxes_num) if boxes_num is not None else None

    def fn(xv, bv):
        n, c, h, w = xv.shape
        r = bv.shape[0]
        if bn is not None:
            img_idx = jnp.repeat(jnp.arange(n), np.asarray(bn), total_repeat_length=r)
        else:
            img_idx = jnp.zeros((r,), jnp.int32)
        offset = 0.5 if aligned else 0.0
        x1 = bv[:, 0] * spatial_scale - offset
        y1 = bv[:, 1] * spatial_scale - offset
        x2 = bv[:, 2] * spatial_scale - offset
        y2 = bv[:, 3] * spatial_scale - offset
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        # sample grid: [R, ph, ratio] y coords, [R, pw, ratio] x coords
        iy = (jnp.arange(ratio) + 0.5) / ratio
        gy = y1[:, None, None] + (jnp.arange(ph)[None, :, None] + iy[None, None, :]) * bin_h[:, None, None]
        gx = x1[:, None, None] + (jnp.arange(pw)[None, :, None] + iy[None, None, :]) * bin_w[:, None, None]

        def bilinear(img, yy, xx):
            # img: [C,H,W]; yy/xx: [...]: bilinear sample each channel
            y0 = jnp.clip(jnp.floor(yy), 0, h - 1).astype(jnp.int32)
            x0 = jnp.clip(jnp.floor(xx), 0, w - 1).astype(jnp.int32)
            y1i = jnp.clip(y0 + 1, 0, h - 1)
            x1i = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy - y0, 0, 1)
            wx = jnp.clip(xx - x0, 0, 1)
            valid = (yy >= -1) & (yy <= h) & (xx >= -1) & (xx <= w)
            ia = img[:, y0, x0]
            ib = img[:, y0, x1i]
            ic = img[:, y1i, x0]
            id_ = img[:, y1i, x1i]
            out = ia * (1 - wy) * (1 - wx) + ib * (1 - wy) * wx + ic * wy * (1 - wx) + id_ * wy * wx
            return out * valid.astype(out.dtype)

        def one_roi(ri):
            img = xv[img_idx[ri]]  # [C,H,W]
            yy = gy[ri]  # [ph, ratio]
            xx = gx[ri]  # [pw, ratio]
            # full sample grid [ph*ratio, pw*ratio]
            ys = yy.reshape(-1)
            xs = xx.reshape(-1)
            grid_y = jnp.broadcast_to(ys[:, None], (ys.shape[0], xs.shape[0]))
            grid_x = jnp.broadcast_to(xs[None, :], (ys.shape[0], xs.shape[0]))
            samples = bilinear(img, grid_y, grid_x)  # [C, ph*ratio, pw*ratio]
            samples = samples.reshape(c, ph, ratio, pw, ratio)
            return samples.mean((2, 4))  # [C, ph, pw]

        return jax.vmap(one_roi)(jnp.arange(r))

    return apply("roi_align", fn, x, boxes)


def roi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0, name=None):
    """Max-pool RoI (reference roi_pool): nearest bins, max within each."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bn = _v(boxes_num) if boxes_num is not None else None

    def fn(xv, bv):
        n, c, h, w = xv.shape
        r = bv.shape[0]
        if bn is not None:
            img_idx = jnp.repeat(jnp.arange(n), np.asarray(bn), total_repeat_length=r)
        else:
            img_idx = jnp.zeros((r,), jnp.int32)
        x1 = jnp.round(bv[:, 0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(bv[:, 1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.maximum(jnp.round(bv[:, 2] * spatial_scale).astype(jnp.int32), x1 + 1)
        y2 = jnp.maximum(jnp.round(bv[:, 3] * spatial_scale).astype(jnp.int32), y1 + 1)

        def one_roi(ri):
            img = xv[img_idx[ri]]
            # exact bin max via masked reduction over the full feature map
            # (static shapes; XLA fuses the where+max — the TPU-friendly form
            # of the reference's per-bin pixel loop)
            iy = jnp.arange(h, dtype=jnp.float32)
            ix = jnp.arange(w, dtype=jnp.float32)
            biny = jnp.floor((iy - y1[ri]) * ph / jnp.maximum(y2[ri] - y1[ri], 1))
            binx = jnp.floor((ix - x1[ri]) * pw / jnp.maximum(x2[ri] - x1[ri], 1))
            in_y = (iy >= y1[ri]) & (iy < y2[ri])
            in_x = (ix >= x1[ri]) & (ix < x2[ri])
            mask_y = (biny[:, None] == jnp.arange(ph)[None, :]) & in_y[:, None]  # [h, ph]
            mask_x = (binx[:, None] == jnp.arange(pw)[None, :]) & in_x[:, None]  # [w, pw]
            neg = jnp.asarray(-jnp.inf, img.dtype)
            tmp = jnp.max(
                jnp.where(mask_y.T[None, :, :, None], img[:, None, :, :], neg), axis=2
            )  # [c, ph, w]
            out = jnp.max(
                jnp.where(mask_x[None, None, :, :], tmp[:, :, :, None], neg), axis=2
            )  # [c, ph, pw]
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return jax.vmap(one_roi)(jnp.arange(r))

    return apply("roi_pool", fn, x, boxes)


# ---------------------------------------------------------------------------
# deformable conv
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1, deformable_groups=1, groups=1, mask=None, name=None):
    """Deformable conv v1/v2 (reference: vision/ops.py deform_conv2d) as
    bilinear gather + matmul — the canonical TPU formulation."""
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError("deform_conv2d: groups/deformable_groups > 1 not yet supported")

    def fn(xv, ov, wv, *rest):
        rest = list(rest)
        bv = rest.pop(0) if bias is not None else None
        mv = rest.pop(0) if mask is not None else None
        n, c, h, w = xv.shape
        oc, ic, kh, kw = wv.shape
        sh, sw = stride
        ph_, pw_ = padding
        dh, dw = dilation
        oh = (h + 2 * ph_ - dh * (kh - 1) - 1) // sh + 1
        ow = (w + 2 * pw_ - dw * (kw - 1) - 1) // sw + 1
        xp = jnp.pad(xv, ((0, 0), (0, 0), (ph_, ph_), (pw_, pw_)))
        hp, wp = h + 2 * ph_, w + 2 * pw_
        # base sampling positions [oh, ow, kh, kw]
        base_y = (jnp.arange(oh) * sh)[:, None, None, None] + (jnp.arange(kh) * dh)[None, None, :, None]
        base_x = (jnp.arange(ow) * sw)[None, :, None, None] + (jnp.arange(kw) * dw)[None, None, None, :]
        base_y = jnp.broadcast_to(base_y, (oh, ow, kh, kw)).astype(jnp.float32)
        base_x = jnp.broadcast_to(base_x, (oh, ow, kh, kw)).astype(jnp.float32)
        # offsets: [N, 2*kh*kw, oh, ow] (y0,x0,y1,x1,... per kernel point)
        off = ov.reshape(n, kh * kw, 2, oh, ow)
        off_y = jnp.moveaxis(off[:, :, 0], 1, -1).reshape(n, oh, ow, kh, kw)
        off_x = jnp.moveaxis(off[:, :, 1], 1, -1).reshape(n, oh, ow, kh, kw)
        sy = base_y[None] + off_y
        sx = base_x[None] + off_x

        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy = sy - y0
        wx = sx - x0

        def gather(img, yy, xx):
            yi = jnp.clip(yy, 0, hp - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, wp - 1).astype(jnp.int32)
            valid = (yy >= 0) & (yy <= hp - 1) & (xx >= 0) & (xx <= wp - 1)
            return img[:, yi, xi] * valid.astype(img.dtype)  # [C, ...]

        def one_image(img, yy0, xx0, wyy, wxx, m):
            a = gather(img, yy0, xx0)
            b = gather(img, yy0, xx0 + 1)
            cc = gather(img, yy0 + 1, xx0)
            d = gather(img, yy0 + 1, xx0 + 1)
            s = (
                a * (1 - wyy) * (1 - wxx)
                + b * (1 - wyy) * wxx
                + cc * wyy * (1 - wxx)
                + d * wyy * wxx
            )  # [C, oh, ow, kh, kw]
            if m is not None:
                s = s * m[None]
            # contract (C,kh,kw) against weight
            return jnp.einsum("cyxhw,ochw->oyx", s, wv)

        if mv is not None:
            mm = jnp.moveaxis(mv.reshape(n, kh * kw, oh, ow), 1, -1).reshape(n, oh, ow, kh, kw)
        else:
            mm = None
        out = jax.vmap(lambda im, a1, a2, a3, a4, m5: one_image(im, a1, a2, a3, a4, m5))(
            xp, y0, x0, wy, wx, mm if mm is not None else jnp.ones((n, oh, ow, kh, kw), xv.dtype)
        )
        if bv is not None:
            out = out + bv[None, :, None, None]
        return out

    args = [x, offset, weight] + ([bias] if bias is not None else []) + ([mask] if mask is not None else [])
    return apply("deform_conv2d", fn, *args)


# ---------------------------------------------------------------------------
# fpn
# ---------------------------------------------------------------------------

def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level, refer_scale, pixel_offset=False, rois_num=None, name=None):
    """Assign each RoI to an FPN level by scale (reference fpn.py). Returns
    (multi_rois, restore_ind, rois_num_per_level)."""
    rois = _v(fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    scale = jnp.sqrt(jnp.clip((rois[:, 2] - rois[:, 0] + off) * (rois[:, 3] - rois[:, 1] + off), 1e-6))
    level = jnp.floor(jnp.log2(scale / refer_scale + 1e-8)) + refer_level
    level = jnp.clip(level, min_level, max_level).astype(jnp.int32)
    level_np = np.asarray(level)
    rois_np = np.asarray(rois)
    multi_rois, rois_num_per_level, order = [], [], []
    for lv in range(min_level, max_level + 1):
        idx = np.nonzero(level_np == lv)[0]
        multi_rois.append(Tensor(jnp.asarray(rois_np[idx])))
        rois_num_per_level.append(Tensor(jnp.asarray([len(idx)], jnp.int32)))
        order.append(idx)
    order = np.concatenate(order) if order else np.zeros(0, np.int64)
    restore = np.argsort(order)
    return multi_rois, Tensor(jnp.asarray(restore, jnp.int32)), rois_num_per_level


# ---------------------------------------------------------------------------
# detection op family (reference python/paddle/vision/ops.py + phi kernels)
# ---------------------------------------------------------------------------

def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _expand_aspect_ratios(aspect_ratios, flip):
    """phi ExpandAspectRatios: 1.0 first, dedupe, flip adds 1/ar."""
    out = [1.0]
    for ar in aspect_ratios:
        dup = any(abs(ar - o) < 1e-6 for o in out)
        if not dup:
            out.append(float(ar))
            if flip:
                out.append(1.0 / float(ar))
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes (vision/ops.py:427; phi/kernels/cpu/prior_box_kernel.cc).
    Returns (boxes [H, W, P, 4], variances [H, W, P, 4]) normalized xyxy."""
    fh, fw = int(input._raw().shape[2]), int(input._raw().shape[3])
    ih, iw = int(image._raw().shape[2]), int(image._raw().shape[3])
    ars = _expand_aspect_ratios(aspect_ratios, flip)
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    min_sizes = [float(s) for s in min_sizes]
    max_sizes = [float(s) for s in (max_sizes or [])]

    cx = (np.arange(fw) + offset) * step_w
    cy = (np.arange(fh) + offset) * step_h
    whs = []
    for i, ms in enumerate(min_sizes):
        per = []
        sq = [(ms / 2.0, ms / 2.0)]
        mx = [(np.sqrt(ms * max_sizes[i]) / 2.0,) * 2] if max_sizes else []
        arv = [
            (ms * np.sqrt(ar) / 2.0, ms / np.sqrt(ar) / 2.0)
            for ar in ars
            if abs(ar - 1.0) >= 1e-6
        ]
        if min_max_aspect_ratios_order:
            per = sq + mx + arv
        else:
            per = [
                (ms * np.sqrt(ar) / 2.0, ms / np.sqrt(ar) / 2.0) for ar in ars
            ] + mx
        whs.extend(per)
    whs = np.asarray(whs)  # [P, 2] half sizes
    P = whs.shape[0]
    gx, gy = np.meshgrid(cx, cy)  # [fh, fw]
    boxes = np.stack(
        [
            (gx[..., None] - whs[None, None, :, 0]) / iw,
            (gy[..., None] - whs[None, None, :, 1]) / ih,
            (gx[..., None] + whs[None, None, :, 0]) / iw,
            (gy[..., None] + whs[None, None, :, 1]) / ih,
        ],
        axis=-1,
    ).astype(np.float32)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32), boxes.shape).copy()
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(var))


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode boxes vs priors (vision/ops.py:573;
    phi/kernels/cpu/box_coder_kernel.cc)."""
    pb = _t(prior_box)
    tb = _t(target_box)
    var_t = prior_box_var if isinstance(prior_box_var, Tensor) else None
    var_l = (
        None
        if prior_box_var is None
        else (list(prior_box_var) if not isinstance(prior_box_var, Tensor) else None)
    )
    norm = 0.0 if box_normalized else 1.0

    def dims(b):
        w = b[..., 2] - b[..., 0] + norm
        h = b[..., 3] - b[..., 1] + norm
        cx = b[..., 0] + w / 2
        cy = b[..., 1] + h / 2
        return cx, cy, w, h

    if code_type in ("encode_center_size", 0):
        def f(pbv, tbv, *rest):
            pcx, pcy, pw, ph = dims(pbv[None, :, :])  # [1, M, .]
            tcx, tcy, tw, th = dims(tbv[:, None, :])  # [N, 1, .]
            out = jnp.stack(
                [
                    (tcx - pcx) / pw,
                    (tcy - pcy) / ph,
                    jnp.log(jnp.abs(tw / pw)),
                    jnp.log(jnp.abs(th / ph)),
                ],
                axis=-1,
            )
            if rest:
                out = out / rest[0][None, :, :]
            elif var_l is not None:
                out = out / jnp.asarray(var_l, out.dtype)
            return out

        args = [pb, tb] + ([var_t] if var_t is not None else [])
        return apply("box_coder_encode", f, *args)

    # decode_center_size: target_box [N, M, 4] deltas, prior [M, 4]
    def f(pbv, tbv, *rest):
        pshape = (1, -1, 4) if axis == 0 else (-1, 1, 4)
        pbb = pbv.reshape(pshape)
        pcx, pcy, pw, ph = dims(pbb)
        d = tbv
        if rest:
            v = rest[0].reshape(pshape)
            d = d * v
        elif var_l is not None:
            d = d * jnp.asarray(var_l, d.dtype)
        cx = d[..., 0] * pw + pcx
        cy = d[..., 1] * ph + pcy
        w = jnp.exp(d[..., 2]) * pw
        h = jnp.exp(d[..., 3]) * ph
        return jnp.stack(
            [cx - w / 2, cy - h / 2, cx + w / 2 - norm, cy + h / 2 - norm], axis=-1
        )

    args = [pb, tb] + ([var_t] if var_t is not None else [])
    return apply("box_coder_decode", f, *args)


def _box_iou_xyxy(a, b, normalized=True):
    """IoU of [..., 4] xyxy boxes, broadcasting."""
    off = 0.0 if normalized else 1.0
    ix1 = jnp.maximum(a[..., 0], b[..., 0])
    iy1 = jnp.maximum(a[..., 1], b[..., 1])
    ix2 = jnp.minimum(a[..., 2], b[..., 2])
    iy2 = jnp.minimum(a[..., 3], b[..., 3])
    iw = jnp.clip(ix2 - ix1 + off, 0)
    ih = jnp.clip(iy2 - iy1 + off, 0)
    inter = iw * ih
    aa = (a[..., 2] - a[..., 0] + off) * (a[..., 3] - a[..., 1] + off)
    ab = (b[..., 2] - b[..., 0] + off) * (b[..., 3] - b[..., 1] + off)
    return inter / jnp.maximum(aa + ab - inter, 1e-10)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """YOLOv3 box decode (vision/ops.py:266; phi yolo_box_kernel).
    x: [N, C, H, W] -> boxes [N, A*H*W, 4] xyxy, scores [N, A*H*W, classes]."""
    x = _t(x)
    img_size = _t(img_size)
    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    A = an.shape[0]
    scale, bias = float(scale_x_y), -0.5 * (float(scale_x_y) - 1.0)

    def f(v, imgs):
        N, C, H, W = v.shape
        attrs = 5 + class_num
        if iou_aware:
            iou_pred = jax.nn.sigmoid(v[:, :A].reshape(N, A, 1, H, W))
            vb = v[:, A:].reshape(N, A, attrs, H, W)
        else:
            vb = v.reshape(N, A, attrs, H, W)
        gx = jnp.arange(W).reshape(1, 1, 1, W)
        gy = jnp.arange(H).reshape(1, 1, H, 1)
        imw = imgs[:, 1].astype(v.dtype).reshape(N, 1, 1, 1)
        imh = imgs[:, 0].astype(v.dtype).reshape(N, 1, 1, 1)
        bx = (gx + jax.nn.sigmoid(vb[:, :, 0]) * scale + bias) * imw / W
        by = (gy + jax.nn.sigmoid(vb[:, :, 1]) * scale + bias) * imh / H
        bw = jnp.exp(vb[:, :, 2]) * an[:, 0].reshape(1, A, 1, 1) * imw / (downsample_ratio * W)
        bh = jnp.exp(vb[:, :, 3]) * an[:, 1].reshape(1, A, 1, 1) * imh / (downsample_ratio * H)
        conf = jax.nn.sigmoid(vb[:, :, 4])
        if iou_aware:
            conf = conf ** (1.0 - iou_aware_factor) * iou_pred[:, :, 0] ** iou_aware_factor
        keep = conf >= conf_thresh
        x1, y1 = bx - bw / 2, by - bh / 2
        x2, y2 = bx + bw / 2, by + bh / 2
        if clip_bbox:
            x1 = jnp.clip(x1, 0)
            y1 = jnp.clip(y1, 0)
            x2 = jnp.minimum(x2, imw - 1)
            y2 = jnp.minimum(y2, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]  # [N,A,H,W,4]
        scores = jax.nn.sigmoid(vb[:, :, 5:]) * (conf * keep)[:, :, None]  # [N,A,cls,H,W]
        boxes = boxes.reshape(N, A * H * W, 4)
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(N, A * H * W, class_num)
        return boxes, scores

    return apply("yolo_box", f, x, img_size)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (vision/ops.py:58; phi/kernels/cpu/yolo_loss_kernel.cc):
    coord sce/l1 + class bce at matched cells, objectness bce with
    ignore_thresh masking. Returns per-image loss [N]."""
    x, gt_box, gt_label = _t(x), _t(gt_box), _t(gt_label)
    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask = list(anchor_mask)
    M = len(mask)
    scale, bias = float(scale_x_y), -0.5 * (float(scale_x_y) - 1.0)
    smooth = min(1.0 / class_num, 1.0 / 40) if use_label_smooth else 0.0
    pos_l, neg_l = 1.0 - smooth, smooth

    def sce(logit, label):
        # SigmoidCrossEntropy as in the kernel
        return jnp.maximum(logit, 0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))

    def f(v, gtb, gtl, *rest):
        N, C, H, W = v.shape
        input_size = downsample_ratio * H
        vb = v.reshape(N, M, 5 + class_num, H, W)
        score = rest[0] if rest else jnp.ones(gtb.shape[:2], v.dtype)
        valid = (gtb[..., 2] > 0) & (gtb[..., 3] > 0)  # [N, B]

        # ---- pred boxes (normalized cxcywh) for ignore mask ----
        gx = jnp.arange(W).reshape(1, 1, 1, W)
        gy = jnp.arange(H).reshape(1, 1, H, 1)
        man = an[mask]
        px = (gx + jax.nn.sigmoid(vb[:, :, 0]) * scale + bias) / W
        py = (gy + jax.nn.sigmoid(vb[:, :, 1]) * scale + bias) / H
        pw = jnp.exp(vb[:, :, 2]) * man[:, 0].reshape(1, M, 1, 1) / input_size
        ph = jnp.exp(vb[:, :, 3]) * man[:, 1].reshape(1, M, 1, 1) / input_size
        pred = jnp.stack([px - pw / 2, py - ph / 2, px + pw / 2, py + ph / 2], -1)
        g_xyxy = jnp.stack(
            [gtb[..., 0] - gtb[..., 2] / 2, gtb[..., 1] - gtb[..., 3] / 2,
             gtb[..., 0] + gtb[..., 2] / 2, gtb[..., 1] + gtb[..., 3] / 2], -1)
        iou = _box_iou_xyxy(
            pred[:, :, :, :, None, :], g_xyxy[:, None, None, None, :, :]
        )  # [N, M, H, W, B]
        iou = jnp.where(valid[:, None, None, None, :], iou, 0.0)
        best_iou = iou.max(axis=-1)
        ignore = best_iou > ignore_thresh  # [N, M, H, W]

        # ---- per-gt best anchor (shifted IoU over ALL anchors) ----
        ga = jnp.minimum(gtb[..., 2:3], an[:, 0] / input_size)  # [N, B, A]
        gb = jnp.minimum(gtb[..., 3:4], an[:, 1] / input_size)
        inter = ga * gb
        union = gtb[..., 2:3] * gtb[..., 3:4] + (an[:, 0] / input_size) * (an[:, 1] / input_size) - inter
        an_iou = inter / jnp.maximum(union, 1e-10)
        best_n = jnp.argmax(an_iou, axis=-1)  # [N, B]
        mask_arr = np.full(an.shape[0], -1, np.int32)
        for mi, a_ in enumerate(mask):
            mask_arr[a_] = mi
        gtm = jnp.asarray(mask_arr)[best_n]  # [N, B] mask idx or -1
        gtm = jnp.where(valid, gtm, -1)
        matched = gtm >= 0
        gi = jnp.clip((gtb[..., 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gtb[..., 1] * H).astype(jnp.int32), 0, H - 1)

        # ---- coord + class loss at matched cells ----
        bidx = jnp.arange(N)[:, None]
        midx = jnp.clip(gtm, 0)
        sel = vb[bidx, midx, :, gj, gi]  # [N, B, 5+cls]
        tx = gtb[..., 0] * W - gi
        ty = gtb[..., 1] * H - gj
        man_w = jnp.asarray(an[:, 0])[jnp.clip(best_n, 0)]
        man_h = jnp.asarray(an[:, 1])[jnp.clip(best_n, 0)]
        tw = jnp.log(jnp.maximum(gtb[..., 2] * input_size / man_w, 1e-9))
        th = jnp.log(jnp.maximum(gtb[..., 3] * input_size / man_h, 1e-9))
        box_scale = (2.0 - gtb[..., 2] * gtb[..., 3]) * score
        coord = (
            sce(sel[..., 0], tx) + sce(sel[..., 1], ty)
            + jnp.abs(sel[..., 2] - tw) + jnp.abs(sel[..., 3] - th)
        ) * box_scale
        labels = jax.nn.one_hot(jnp.clip(gtl, 0), class_num) * (pos_l - neg_l) + neg_l
        cls = jnp.sum(sce(sel[..., 5:], labels), -1) * score
        per_gt = jnp.where(matched, coord + cls, 0.0)

        # ---- objectness ----
        obj_target = jnp.zeros((N, M, H, W), v.dtype)
        obj_target = obj_target.at[bidx, midx, gj, gi].max(
            jnp.where(matched, score, 0.0)
        )
        positive = obj_target > 1e-5
        obj_logit = vb[:, :, 4]
        obj_loss = jnp.where(
            positive,
            sce(obj_logit, 1.0) * obj_target,
            jnp.where(ignore, 0.0, sce(obj_logit, 0.0)),
        )
        return per_gt.sum(-1) + obj_loss.sum((1, 2, 3))

    args = [x, gt_box, gt_label] + ([_t(gt_score)] if gt_score is not None else [])
    return apply("yolo_loss", f, *args)


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (vision/ops.py:2236; phi/kernels/cpu/matrix_nms_kernel.cc).
    Host-side (data-dependent output size, inference op)."""
    bb = np.asarray(_t(bboxes)._raw())  # [N, M, 4]
    sc = np.asarray(_t(scores)._raw())  # [N, C, M]
    N, C, Mb = sc.shape
    all_out, all_idx, rois_num = [], [], []
    for i in range(N):
        dets = []
        for c in range(C):
            if c == background_label:
                continue
            s = sc[i, c]
            perm = np.where(s > score_threshold)[0]
            if perm.size == 0:
                continue
            perm = perm[np.argsort(-s[perm], kind="stable")]
            if nms_top_k > -1 and perm.size > nms_top_k:
                perm = perm[:nms_top_k]
            boxes_c = bb[i, perm]
            n = perm.size
            iou = np.asarray(
                _box_iou_xyxy(
                    jnp.asarray(boxes_c)[:, None, :], jnp.asarray(boxes_c)[None, :, :],
                    normalized,
                )
            )
            iou = np.tril(iou, -1)
            iou_max = iou.max(axis=1)  # max overlap with higher-scored
            if use_gaussian:
                decay = np.exp((iou_max[None, :] ** 2 - iou ** 2) / gaussian_sigma)
            else:
                decay = (1.0 - iou) / np.maximum(1.0 - iou_max[None, :], 1e-10)
            decay = np.where(np.tril(np.ones_like(iou), -1) > 0, decay, np.inf)
            min_decay = np.minimum(decay.min(axis=1), 1.0)
            ds = s[perm] * min_decay
            keep = ds > post_threshold
            for j in np.where(keep)[0]:
                dets.append((float(ds[j]), c, perm[j], boxes_c[j]))
        dets.sort(key=lambda d: -d[0])
        if keep_top_k > -1:
            dets = dets[:keep_top_k]
        out = np.array(
            [[d[1], d[0], *d[3]] for d in dets], np.float32
        ).reshape(-1, 6)
        idx = np.array([i * Mb + d[2] for d in dets], np.int64)
        all_out.append(out)
        all_idx.append(idx)
        rois_num.append(len(dets))
    out = Tensor(jnp.asarray(np.concatenate(all_out) if all_out else np.zeros((0, 6), np.float32)))
    res = [out]
    if return_index:
        res.append(Tensor(jnp.asarray(np.concatenate(all_idx))))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.array(rois_num, np.int32))))
    return tuple(res) if len(res) > 1 else out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000, nms_thresh=0.5,
                       min_size=0.1, eta=1.0, pixel_offset=False,
                       return_rois_num=False, name=None):
    """RPN proposal generation (vision/ops.py:2038; phi
    generate_proposals_kernel). Host-side (inference op): decode -> clip ->
    filter small -> topk -> NMS -> topk."""
    sc = np.asarray(_t(scores)._raw())       # [N, A, H, W]
    bd = np.asarray(_t(bbox_deltas)._raw())  # [N, 4A, H, W]
    ims = np.asarray(_t(img_size)._raw())    # [N, 2] (h, w)
    anc = np.asarray(_t(anchors)._raw()).reshape(-1, 4)
    var = np.asarray(_t(variances)._raw()).reshape(-1, 4)
    N, A, H, W = sc.shape
    off = 1.0 if pixel_offset else 0.0
    rois, roi_probs, rois_num = [], [], []
    for i in range(N):
        s = sc[i].transpose(1, 2, 0).reshape(-1)           # HWA
        d = bd[i].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s, kind="stable")
        if pre_nms_top_n > 0:
            order = order[:pre_nms_top_n]
        a = anc[order]
        dd = d[order] * var[order]
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        acx = a[:, 0] + aw / 2
        acy = a[:, 1] + ah / 2
        cx = dd[:, 0] * aw + acx
        cy = dd[:, 1] * ah + acy
        w = np.exp(np.minimum(dd[:, 2], np.log(1000.0 / 16))) * aw
        h = np.exp(np.minimum(dd[:, 3], np.log(1000.0 / 16))) * ah
        props = np.stack(
            [cx - w / 2, cy - h / 2, cx + w / 2 - off, cy + h / 2 - off], axis=1
        )
        imh, imw = ims[i, 0], ims[i, 1]
        props[:, 0] = np.clip(props[:, 0], 0, imw - off)
        props[:, 1] = np.clip(props[:, 1], 0, imh - off)
        props[:, 2] = np.clip(props[:, 2], 0, imw - off)
        props[:, 3] = np.clip(props[:, 3], 0, imh - off)
        ss = s[order]
        pw = props[:, 2] - props[:, 0] + off
        ph = props[:, 3] - props[:, 1] + off
        keep = (pw >= min_size) & (ph >= min_size)
        props, ss = props[keep], ss[keep]
        # greedy NMS
        sel = []
        idxs = np.arange(len(ss))
        while idxs.size and (post_nms_top_n <= 0 or len(sel) < post_nms_top_n):
            j = idxs[0]
            sel.append(j)
            if idxs.size == 1:
                break
            iou = np.asarray(
                _box_iou_xyxy(jnp.asarray(props[j]), jnp.asarray(props[idxs[1:]]), not pixel_offset)
            )
            idxs = idxs[1:][iou <= nms_thresh]
        rois.append(props[sel])
        roi_probs.append(ss[sel].reshape(-1, 1))
        rois_num.append(len(sel))
    rois_t = Tensor(jnp.asarray(np.concatenate(rois).astype(np.float32)))
    probs_t = Tensor(jnp.asarray(np.concatenate(roi_probs).astype(np.float32)))
    if return_rois_num:
        return rois_t, probs_t, Tensor(jnp.asarray(np.array(rois_num, np.int32)))
    return rois_t, probs_t




# ---------------------------------------------------------------------------
# r3 vision-ops completion (namespace parity audit)
# ---------------------------------------------------------------------------

def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI pooling (reference vision/ops.py psroi_pool;
    R-FCN): input channels C = out_c * ph * pw; output bin (i, j) average-
    pools its OWN channel group over the bin's spatial window."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bn = _v(boxes_num) if boxes_num is not None else None

    def fn(xv, bv):
        n, c, h, w = xv.shape
        if c % (ph * pw):
            raise ValueError(
                f"psroi_pool: input channels ({c}) must be divisible by "
                f"output_size^2 ({ph}*{pw})")
        out_c = c // (ph * pw)
        r = bv.shape[0]
        if bn is not None:
            img_idx = jnp.repeat(jnp.arange(n), np.asarray(bn), total_repeat_length=r)
        else:
            img_idx = jnp.zeros((r,), jnp.int32)

        def one(roi, ii):
            x1, y1, x2, y2 = roi * spatial_scale
            rh = jnp.maximum(y2 - y1, 0.1) / ph
            rw = jnp.maximum(x2 - x1, 0.1) / pw
            img = xv[ii]                                    # [C, H, W]
            grid = img.reshape(out_c, ph, pw, h, w)
            ys = jnp.arange(h, dtype=jnp.float32)[:, None]
            xs = jnp.arange(w, dtype=jnp.float32)[None, :]
            outs = []
            for i in range(ph):
                for j in range(pw):
                    y_lo = y1 + i * rh
                    y_hi = y1 + (i + 1) * rh
                    x_lo = x1 + j * rw
                    x_hi = x1 + (j + 1) * rw
                    m = ((ys >= jnp.floor(y_lo)) & (ys < jnp.ceil(y_hi))
                         & (xs >= jnp.floor(x_lo)) & (xs < jnp.ceil(x_hi)))
                    denom = jnp.maximum(jnp.sum(m), 1.0)
                    outs.append(jnp.sum(grid[:, i, j] * m[None], axis=(-2, -1)) / denom)
            return jnp.stack(outs, -1).reshape(out_c, ph, pw)

        return jax.vmap(one)(bv.astype(jnp.float32), img_idx)

    return apply("psroi_pool", fn, _t(x), _t(boxes))


from ..nn.layer import Layer as _Layer  # noqa: E402  (nn.layer has no import cycle with ops)


class RoIAlign(_Layer):
    """Layer form of roi_align (reference vision/ops.py RoIAlign)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size, self.spatial_scale, aligned=aligned)


class RoIPool(_Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


class PSRoIPool(_Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


class DeformConv2D(_Layer):
    """Layer form of deform_conv2d owning weight/bias (reference
    vision/ops.py DeformConv2D). A real nn.Layer: its parameters register
    with parent layers, optimizers and state_dict."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, deformable_groups=1, groups=1, weight_attr=None, bias_attr=None):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, (list, tuple)) else (kernel_size, kernel_size)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, k[0], k[1]], attr=weight_attr)
        self.bias = (
            None if bias_attr is False
            else self.create_parameter([out_channels], attr=bias_attr, is_bias=True)
        )
        self.args = (stride, padding, dilation, deformable_groups, groups)

    def forward(self, x, offset, mask=None):
        s, p, d, dg, g = self.args
        return deform_conv2d(x, offset, self.weight, self.bias, s, p, d, dg, g, mask)


def read_file(filename, name=None):
    """Raw file bytes as a uint8 tensor (reference vision/ops.py read_file)."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to [C, H, W] uint8 (reference
    vision/ops.py decode_jpeg; the nvjpeg op's role, PIL-backed on host)."""
    import io

    from PIL import Image

    data = bytes(np.asarray(_v(x), np.uint8))
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
