"""Patch Tensor with operator dunders and method forms of the op library.

Reference parity: python/paddle/base/dygraph/math_op_patch.py +
tensor_patch_methods.py (monkey-patch the eager Tensor with python methods).
"""
from __future__ import annotations

from ..core.tensor import Tensor
from . import creation, einsum as einsum_mod, linalg, logic, manipulation, math, search


def _method(fn):
    def m(self, *args, **kwargs):
        return fn(self, *args, **kwargs)

    m.__name__ = fn.__name__
    return m


def _rmethod(fn):
    def m(self, other):
        return fn(other, self)

    return m


def patch_tensor():
    T = Tensor
    # arithmetic dunders
    T.__add__ = _method(math.add)
    T.__radd__ = _rmethod(math.add)
    T.__sub__ = _method(math.subtract)
    T.__rsub__ = _rmethod(math.subtract)
    T.__mul__ = _method(math.multiply)
    T.__rmul__ = _rmethod(math.multiply)
    T.__truediv__ = _method(math.divide)
    T.__rtruediv__ = _rmethod(math.divide)
    T.__floordiv__ = _method(math.floor_divide)
    T.__rfloordiv__ = _rmethod(math.floor_divide)
    T.__mod__ = _method(math.mod)
    T.__rmod__ = _rmethod(math.mod)
    T.__pow__ = _method(math.pow)
    T.__rpow__ = _rmethod(math.pow)
    T.__matmul__ = _method(linalg.matmul)
    T.__rmatmul__ = _rmethod(linalg.matmul)
    T.__neg__ = _method(math.neg)
    T.__abs__ = _method(math.abs)
    T.__invert__ = _method(logic.bitwise_not)
    T.__and__ = _method(logic.bitwise_and)
    T.__or__ = _method(logic.bitwise_or)
    T.__xor__ = _method(logic.bitwise_xor)
    T.__lshift__ = _method(logic.bitwise_left_shift)
    T.__rshift__ = _method(logic.bitwise_right_shift)
    # comparisons
    T.__eq__ = _method(logic.equal)
    T.__ne__ = _method(logic.not_equal)
    T.__lt__ = _method(logic.less_than)
    T.__le__ = _method(logic.less_equal)
    T.__gt__ = _method(logic.greater_than)
    T.__ge__ = _method(logic.greater_equal)

    # method forms
    for mod in (math, manipulation, linalg, search, logic):
        for name in dir(mod):
            if name.startswith("_"):
                continue
            fn = getattr(mod, name)
            if not callable(fn) or isinstance(fn, type):
                continue
            if not hasattr(T, name):
                setattr(T, name, _method(fn))

    # paddle inplace-suffixed methods (functional under the hood, then _become)
    def _inplace(fn):
        def m(self, *args, **kwargs):
            self._become(fn(self, *args, **kwargs))
            return self

        return m

    for name, fn in [
        ("add_", math.add),
        ("subtract_", math.subtract),
        ("multiply_", math.multiply),
        ("divide_", math.divide),
        ("scale_", math.scale),
        ("clip_", math.clip),
        ("exp_", math.exp),
        ("sqrt_", math.sqrt),
        ("rsqrt_", math.rsqrt),
        ("abs_", math.abs),
        ("ceil_", math.ceil),
        ("floor_", math.floor),
        ("round_", math.round),
        ("reciprocal_", math.reciprocal),
        ("tanh_", math.tanh),
        ("cast_", manipulation.cast),
        ("flatten_", manipulation.flatten),
        ("fill_", lambda self, v: creation.full_like(self, v)),
        ("zero_", lambda self: creation.zeros_like(self)),
    ]:
        setattr(T, name, _inplace(fn))
    # the rest of the reference tensor_method_func inplace family comes from
    # inplace.py's _MECHANICAL table (erfinv_, lerp_, log1p_, not_equal_,
    # put_along_axis_, sigmoid_, ... — one table generates function AND
    # method forms via patch_tensor_inplace)

    # non-method-module functions the reference patches as methods
    # (tensor/__init__.py tensor_method_func): creation views + signal
    for name, fn in [
        ("diag", creation.diag),
        ("diagonal", creation.diagonal),
        ("diagflat", creation.diagflat),
        ("diag_embed", creation.diag_embed),
        ("tril", creation.tril),
        ("triu", creation.triu),
        ("polar", creation.polar),
        ("multinomial", creation.multinomial),
    ]:
        if not hasattr(T, name):
            setattr(T, name, _method(fn))

    # stft/istft live in paddle.signal, which imports ops — bind lazily to
    # avoid the import cycle at patch time
    def _signal_method(name):
        def m(self, *args, **kwargs):
            from .. import signal as signal_mod

            return getattr(signal_mod, name)(self, *args, **kwargs)

        m.__name__ = name
        return m

    T.stft = _signal_method("stft")
    T.istft = _signal_method("istft")
    # create_parameter/create_tensor are patched verbatim in the reference
    # (first arg is shape/dtype, not self) — same binding here
    T.create_parameter = staticmethod(creation.create_parameter)
    T.create_tensor = staticmethod(creation.create_tensor)

    T.mean = _method(math.mean)
    T.sum = _method(math.sum)
    T.max = _method(math.max)
    T.min = _method(math.min)
    T.item = T.item  # keep

    # uniform_ for initializers
    def uniform_(self, min=-1.0, max=1.0, seed=0):
        self.set_value(creation.uniform(self.shape, dtype=self.dtype, min=min, max=max)._value)
        return self

    def normal_(self, mean=0.0, std=1.0):
        self.set_value(creation.normal(mean, std, self.shape)._value.astype(self._value.dtype))
        return self

    T.uniform_ = uniform_
    T.normal_ = normal_
