"""nn.Layer base class.

Reference parity: python/paddle/nn/layer/layers.py:332 (Layer): parameter /
buffer / sublayer registries, forward hooks, train/eval mode, to(), state_dict
/ set_state_dict, named_* traversals, apply(). TPU-native: parameters are
Tensors holding jax.Arrays (possibly sharded — placements attach here for the
auto-parallel path).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Iterator, Optional, Tuple

import numpy as np
from jax import numpy as jnp

from ..core.tensor import Tensor
from ..core import state as core_state
from ..framework import dtype as dtype_mod


class Parameter(Tensor):
    """Trainable tensor (analog of paddle Parameter / EagerParamBase,
    python/paddle/base/framework.py)."""

    # placements/process_mesh live on Tensor as dist-attr properties
    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip", "is_distributed",
                 "sequence_parallel")

    def __init__(self, value, trainable=True, name=None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters: "OrderedDict[str, Parameter]" = OrderedDict()
        self._buffers: "OrderedDict[str, Tensor]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ---- registration ----
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            self.__dict__.pop(name, None)
            params[name] = value
            self._sub_layers.pop(name, None)
            self._buffers.pop(name, None)
            return
        layers = self.__dict__.get("_sub_layers")
        if isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            self.__dict__.pop(name, None)
            layers[name] = value
            if params is not None:
                params.pop(name, None)
            self._buffers.pop(name, None)
            return
        bufs = self.__dict__.get("_buffers")
        if bufs is not None and name in bufs:
            if value is None or isinstance(value, Tensor):
                bufs[name] = value
                return
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._buffers) + list(self._sub_layers)

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[str(name)] = None
        else:
            self._parameters[str(name)] = parameter
        return parameter

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable=True):
        self._buffers[str(name)] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(str(name))
        return tensor

    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ) -> Parameter:
        """Analog of Layer.create_parameter (layers.py) using initializers."""
        from .initializer import Constant, XavierUniform, _resolve_attr

        dtype = dtype_mod.convert_dtype(dtype or self._dtype)
        init, name, trainable, lr, reg, need_clip = _resolve_attr(attr, is_bias, default_initializer)
        if init is None:
            # attr=False => no parameter (reference layers.py: bias_attr
            # False skips the bias entirely and forward receives None)
            return None
        value = init(tuple(shape), dtype)
        p = Parameter(value, trainable=trainable, name=name)
        p.optimize_attr = {"learning_rate": lr}
        p.regularizer = reg
        p.need_clip = need_clip
        return p

    def create_tensor(self, name=None, dtype=None):
        return Tensor(jnp.zeros((), dtype_mod.convert_dtype(dtype or self._dtype)), name=name)

    # ---- traversal ----
    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix):
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield (layer_prefix + pname, p)
            if not include_sublayers:
                break

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix):
            for bname, b in layer._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    yield (layer_prefix + bname, b)
            if not include_sublayers:
                break

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix.rstrip("."), self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}{name}"
            yield sub_prefix, sub
            yield from sub.named_sublayers(prefix=sub_prefix + ".")

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return [l for l in self._sub_layers.values() if l is not None]

    def named_children(self):
        return [(n, l) for n, l in self._sub_layers.items() if l is not None]

    def _walk(self, prefix=""):
        yield ("", prefix, self)
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            yield from ((n, p, l) for n, p, l in sub._walk(f"{prefix}{name}."))

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # ---- mode ----
    def _set_mode(self, training: bool):
        from ..jit.api import _bump_mode_epoch

        changed = False
        for layer in self.sublayers(include_self=True):
            if layer.training != training:
                layer.training = training
                changed = True
        if changed:  # only invalidate jit guards when a mode actually flipped
            _bump_mode_epoch()
        return self

    def train(self):
        return self._set_mode(True)

    def eval(self):
        return self._set_mode(False)

    # ---- hooks ----
    class _HookHandle:
        _next_id = [0]

        def __init__(self, store):
            self._store = store
            self._id = Layer._HookHandle._next_id[0]
            Layer._HookHandle._next_id[0] += 1

        def remove(self):
            self._store.pop(self._id, None)

    def register_forward_pre_hook(self, hook):
        h = Layer._HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[h._id] = hook
        return h

    def register_forward_post_hook(self, hook):
        h = Layer._HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[h._id] = hook
        return h

    # ---- call ----
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # ---- state dict ----
    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix, include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix, include_sublayers=include_sublayers):
            bare = name.rsplit(".", 1)[-1]
            owner = self
            # skip non-persistable buffers
            if bare in self._find_buffer_owner(name)._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def _find_buffer_owner(self, qualified):
        parts = qualified.split(".")[:-1]
        layer = self
        for p in parts:
            layer = layer._sub_layers.get(p, layer)
        return layer

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Returns (missing_keys, unexpected_keys) like the reference."""
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            target = own[k]
            val = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            if tuple(val.shape) != tuple(target._value.shape):
                raise ValueError(
                    f"shape mismatch for {k}: loaded {tuple(val.shape)} vs param {tuple(target._value.shape)}"
                )
            target._replace_value(val.astype(target._value.dtype))
            if isinstance(target, Parameter):
                target.stop_gradient = not target.trainable
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # ---- dtype/device movement ----
    def to(self, device=None, dtype=None, blocking=None):
        def move(t: Tensor):
            if t is None:
                return
            new = t
            if dtype is not None:
                d = dtype_mod.convert_dtype(dtype)
                if dtype_mod.is_floating_point_dtype(t.dtype):
                    new = new.astype(d)
            if device is not None:
                new = new.to(device=device)
            if new is not t:
                t._replace_value(new._value)
                if isinstance(t, Parameter):
                    t.stop_gradient = not t.trainable

        for _, p in self.named_parameters():
            move(p)
        for _, b in self.named_buffers():
            move(b)
        if dtype is not None:
            self._dtype = dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{self.__class__.__name__}({extra}"]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub_repr}")
        return "\n".join(lines) + ")" if len(lines) > 1 else lines[0] + ")"
