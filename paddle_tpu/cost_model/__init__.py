"""paddle.cost_model (reference: python/paddle/cost_model/cost_model.py):
static-program op cost profiling. Here profiling is the XLA device profile
(paddle_tpu.profiler / benchmarks/profile_xplane.py); this API reports that
pointer on use."""


class CostModel:
    def __init__(self):
        pass

    def profile_measure(self, *a, **k):
        raise RuntimeError(
            "per-op cost profiling runs through paddle_tpu.profiler "
            "(XLA xplane device profile), not a static-graph cost model")


__all__ = ['CostModel']
