"""Named stats counters — deprecation shim over `paddle_tpu.telemetry`.

Reference parity: paddle/fluid/platform/monitor.cc (STAT_INT registry used
for framework-internal counters). This module used to hold its own flat
dicts; it now forwards into the unified telemetry registry
(`paddle_tpu.telemetry.metrics`) so monitor stats appear in the same
Prometheus/JSON exports as every other runtime metric. Prefer
`paddle_tpu.telemetry.counter(...)` / `.gauge(...)` in new code.

Legacy semantics preserved: `add()` accepts decrements, a counter and a
gauge may share a name (the gauge exports under `<name>__gauge` in that
case), and `get()` on a name that was never recorded returns 0 (counter
semantics), not None.
"""
from __future__ import annotations

import threading
import warnings

from ..telemetry import metrics as _metrics

_lock = threading.Lock()
# logical monitor name -> registry family name (may be suffixed on a
# counter/gauge name collision, which the old dual-dict API allowed)
_counter_fams: dict = {}
_gauge_fams: dict = {}
_warned = [False]


def _deprecation_note():
    if not _warned[0]:
        _warned[0] = True
        warnings.warn(
            "paddle_tpu.framework.monitor is a compatibility shim; use "
            "paddle_tpu.telemetry.counter()/gauge() for labeled metrics and "
            "unified export",
            DeprecationWarning,
            stacklevel=3,
        )


def _family(name: str, factory, fams: dict, suffix: str):
    with _lock:
        fam_name = fams.get(name)
    if fam_name is not None:
        return _metrics.default_registry().get(fam_name) or factory(fam_name)
    fam_name = name
    try:
        fam = factory(fam_name)
    except (TypeError, ValueError):
        # name taken by another kind/schema in the shared registry
        fam_name = name + suffix
        fam = factory(fam_name)
    with _lock:
        fams[name] = fam_name
    return fam


def add(name: str, value=1):
    _deprecation_note()
    fam = _family(name, _metrics.counter, _counter_fams, "__counter")
    # legacy STAT_INT semantics allowed decrements (add(name, -1)); route
    # through the shim-only signed path so old callers keep working
    fam._default()._add_signed(value)


def set_gauge(name: str, value):
    _deprecation_note()
    _family(name, _metrics.gauge, _gauge_fams, "__gauge").set(value)


def _read(fam_name):
    fam = _metrics.default_registry().get(fam_name)
    if fam is None or fam.kind == "histogram" or fam.label_names:
        return None
    return fam.value


def get(name: str):
    with _lock:
        c, g = _counter_fams.get(name), _gauge_fams.get(name)
    # old flat-dict priority: counters first, then gauges
    for fam_name in (c, g):
        if fam_name is not None:
            v = _read(fam_name)
            if v is not None:
                return v
    # non-shim name: read 0 for anything not representable as a flat scalar
    v = _read(name)
    return 0 if v is None else v


def snapshot():
    with _lock:
        owned = [("counters", dict(_counter_fams)), ("gauges", dict(_gauge_fams))]
    out = {"counters": {}, "gauges": {}}
    for section, fams in owned:
        for n, f in fams.items():
            v = _read(f)
            if v is not None:
                out[section][n] = v
    return out


def reset(name: str = None):
    reg = _metrics.default_registry()
    with _lock:
        # only monitor-owned families — never delete live telemetry metrics
        # that happen to share the default registry
        names = [name] if name is not None else sorted(set(_counter_fams) | set(_gauge_fams))
        for n in names:
            for fams in (_counter_fams, _gauge_fams):
                fam_name = fams.pop(n, None)
                if fam_name is not None:
                    reg.unregister(fam_name)
