"""Pipeline model partition descriptors.

Reference parity: python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py
(LayerDesc:56, SharedLayerDesc:76, SegmentLayers:92, PipelineLayer:257).

TPU-native design: the controller owns ALL stages (no per-rank partial
build), so PipelineLayer materializes every layer and records the
stage-segment map. Stage placement is a sharding concern: the uniform-stage
fast path stacks per-stage params over the mesh's pp axis and runs the
circular shard_map pipeline (see ../spmd_pipeline.py); the general path
executes stages in order inside one program, with micro-batch scheduling
supplying the pipelining semantics (PipelineParallel.train_batch).
"""
from __future__ import annotations

import math
from typing import Callable, List, Optional, Sequence, Union

from .....nn.layer import Layer


class LayerDesc:
    """Deferred layer constructor (reference :56)."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer appearing in several stages (reference :76) —
    e.g. embedding + output projection. Single-controller: the SAME built
    Layer object is reused, so tying is free (no broadcast sync needed)."""

    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Split N layers into num_parts stages (reference :92)."""

    def __init__(self, layers_desc, num_parts, method="uniform", num_virtual_pipeline_stage=None):
        self.layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method
        assert len(layers_desc) >= num_parts, "number of layers must be >= number of stages"

    def do_segment(self) -> List[int]:
        """Returns stage boundaries: len num_parts+1, stage i = [b[i], b[i+1])."""
        n = len(self.layers_desc)
        if self.method == "uniform":
            return self._uniform(n, self.num_parts)
        if self.method.startswith("layer:"):
            # segment so layers of the named class are evenly spread
            name = self.method.split(":", 1)[1]
            weights = [1 if self._layer_name(d) == name else 0 for d in self.layers_desc]
            if sum(weights) == 0:
                return self._uniform(n, self.num_parts)
            return self._by_weight(weights)
        if self.method == "parameter":
            weights = [self._param_count(d) for d in self.layers_desc]
            return self._by_weight(weights)
        raise ValueError(f"unknown segment method {self.method}")

    @staticmethod
    def _layer_name(desc):
        if isinstance(desc, LayerDesc):
            return desc.layer_func.__name__
        return type(desc).__name__

    @staticmethod
    def _param_count(desc):
        if isinstance(desc, LayerDesc):
            # estimate from ctor args without building: fall back to 1
            return 1
        if isinstance(desc, Layer):
            return max(1, sum(int(math.prod(p.shape)) for p in desc.parameters()))
        return 1

    @staticmethod
    def _uniform(n, parts):
        bounds = [0]
        base, extra = divmod(n, parts)
        for i in range(parts):
            bounds.append(bounds[-1] + base + (1 if i < extra else 0))
        return bounds

    def _by_weight(self, weights):
        """Greedy balanced partition; every stage is guaranteed >= 1 layer
        (the reference asserts non-empty stages)."""
        n = len(weights)
        total = sum(weights)
        target = total / self.num_parts
        bounds = [0]
        acc = 0.0
        for i, w in enumerate(weights):
            acc += w
            remaining_layers = n - (i + 1)
            remaining_parts = self.num_parts - len(bounds)
            if remaining_parts == 0:
                break
            # close a stage when it reached its share, but never leave fewer
            # layers than still-open stages
            if (acc >= target * len(bounds) and remaining_layers >= remaining_parts) or (
                remaining_layers == remaining_parts
            ):
                bounds.append(i + 1)
        while len(bounds) < self.num_parts:
            bounds.append(bounds[-1] + 1)
        bounds.append(n)
        assert all(bounds[i + 1] > bounds[i] for i in range(self.num_parts)), (
            f"empty pipeline stage in partition {bounds}"
        )
        return bounds


class PipelineLayer(Layer):
    """Reference parity: pp_layers.py:257.

    layers: list of Layer / LayerDesc / SharedLayerDesc / callables.
    loss_fn: applied by PipelineParallel.train_batch after the last stage.
    """

    def __init__(
        self,
        layers: Sequence[Union[Layer, LayerDesc, Callable]],
        num_stages: Optional[int] = None,
        topology=None,
        loss_fn=None,
        seg_method: str = "uniform",
        recompute_interval: int = 0,
        recompute_ctx=None,
        num_virtual_pipeline_stages=None,
    ):
        super().__init__()
        from ...base.topology import get_hybrid_communicate_group

        hcg = get_hybrid_communicate_group()
        if num_stages is None:
            num_stages = hcg.get_pipe_parallel_world_size() if hcg else 1
        self._num_stages = num_stages
        # VPP (reference :942): segment into num_stages * v chunks; chunk k
        # is placed on pp rank k % num_stages (round-robin interleave)
        self._num_virtual = int(num_virtual_pipeline_stages or 1)
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        self._topology = topology

        # build all layers (controller owns every stage)
        self._shared: dict = {}
        built: List = []
        self._shared_forward: dict = {}
        for i, d in enumerate(layers):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self._shared:
                    self._shared[d.layer_name] = d.build_layer()
                layer = self._shared[d.layer_name]
                if d.forward_func is not None:
                    self._shared_forward[i] = (layer, d.forward_func)
                built.append(layer)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            else:
                built.append(d)  # Layer instance or plain callable (lambda)
        self.run_function = built
        for i, l in enumerate(built):
            if isinstance(l, Layer):
                setattr(self, f"_stage_layer_{i}", l)

        seg = SegmentLayers(
            [layers[i] if isinstance(layers[i], LayerDesc) else built[i] for i in range(len(built))],
            num_parts=num_stages * self._num_virtual,
            method=seg_method,
        )
        self.segment_parts = seg.do_segment()
        self._stage_modules: dict = {}
        # set by PipelineParallel when pp_degree > 1: chunk k's device; the
        # forward then hops activations stage-to-stage (tape-visible op)
        self._stage_devices: Optional[list] = None

    @property
    def num_stages(self):
        return self._num_stages

    @property
    def num_chunks(self) -> int:
        """Total stage chunks = num_stages * num_virtual (VPP)."""
        return self._num_stages * self._num_virtual

    def get_stage_from_index(self, layer_idx: int) -> int:
        """pp RANK owning the layer (chunk k lives on rank k % num_stages —
        the reference's interleave placement)."""
        for k in range(self.num_chunks):
            if self.segment_parts[k] <= layer_idx < self.segment_parts[k + 1]:
                return k % self._num_stages
        raise IndexError(layer_idx)

    def stage_layers(self, stage: int) -> List:
        return self.run_function[self.segment_parts[stage] : self.segment_parts[stage + 1]]

    def stage_module(self, stage: int) -> "_PipelineStage":
        """Stage chunk as a Layer (own state_dict) for functional staging."""
        if stage not in self._stage_modules:
            self._stage_modules[stage] = _PipelineStage(self, stage)
        return self._stage_modules[stage]

    def uniform_stages(self) -> bool:
        """True when every stage chunk has the identical param/buffer
        structure AND no cross-stage weight tying / bare callables — the
        precondition for stacking per-stage params over the pp mesh axis
        (spmd_pipeline compiled schedule)."""
        if self._shared:
            return False
        if any(not isinstance(l, Layer) for l in self.run_function):
            return False
        sig0 = None
        for k in range(self.num_chunks):
            sd = self.stage_module(k).state_dict()
            param_sig = tuple(
                (name, tuple(t.shape), str(t._value.dtype))
                for name, t in sorted(sd.items())
            )
            # layer types AND scalar config must match too — two chunks with
            # identical param shapes but e.g. Tanh vs Sigmoid, or Dropout
            # p=0.1 vs 0.5, would otherwise silently run chunk 0's functions
            layer_sig = tuple(
                (type(l).__name__, _scalar_config(l)) for l in self.stage_layers(k)
            )
            sig = (param_sig, layer_sig)
            if sig0 is None:
                sig0 = sig
            elif sig != sig0:
                return False
        return True

    def forward_stage(self, x, stage: int):
        for i in range(self.segment_parts[stage], self.segment_parts[stage + 1]):
            fn = self.run_function[i]
            if i in self._shared_forward:
                layer, ffn = self._shared_forward[i]
                x = ffn(layer, x)
            elif isinstance(x, tuple):
                x = fn(*x)
            else:
                x = fn(x)
        return x

    def forward(self, x):
        for s in range(self.num_chunks):
            if self._stage_devices is not None:
                from ..pipeline_parallel import _to_device

                x = _to_device(x, self._stage_devices[s])
            x = self.forward_stage(x, s)
        return x


def _scalar_config(layer) -> tuple:
    """Hashable signature of a Layer's scalar configuration (activation
    choice lives in the type name; things like dropout p, eps, strides live
    in plain attributes)."""
    out = []
    for k, v in sorted(vars(layer).items()):
        if isinstance(v, (int, float, bool, str, type(None))):
            out.append((k, v))
        elif isinstance(v, (tuple, list)) and all(
            isinstance(e, (int, float, bool, str)) for e in v
        ):
            out.append((k, tuple(v)))
    return tuple(out)


class _PipelineStage(Layer):
    """One stage chunk of a PipelineLayer as a standalone Layer: registers
    the chunk's sublayers (so state_dict covers exactly the chunk) and
    forwards through them in order."""

    def __init__(self, pipeline_layer: "PipelineLayer", stage: int):
        super().__init__()
        self._pl = [pipeline_layer]  # list: keep parent out of the sublayer tree
        self._stage = stage
        for j, l in enumerate(pipeline_layer.stage_layers(stage)):
            if isinstance(l, Layer):
                setattr(self, f"l{j}", l)

    def forward(self, x):
        return self._pl[0].forward_stage(x, self._stage)
