"""Remaining paddle.distributed surface (r3 namespace parity audit).

Reference parity, per name:
- ParallelMode: fleet/base/topology.py:37 (mode constants)
- ReduceType: base.core ReduceType (partial-reduce kinds for Partial placements)
- DistAttr: auto_parallel/api.py:65 (mesh + dims_mapping record)
- InMemoryDataset/QueueDataset: fleet/dataset/dataset.py — the PS-era text
  dataset surface; TPU-native subset documented on the classes
- CountFilterEntry/ProbabilityEntry/ShowClickEntry: fleet/entry — sparse
  table accessor configs (plain records here; the PS backend they configure
  is an out-of-scope decision, PARITY.md §2.1)
- gloo_init_parallel_env / gloo_barrier / gloo_release: the CPU-only gloo
  bootstrap (distributed/parallel.py) — mapped onto the native TCPStore
  rendezvous this framework already uses for CPU coordination
"""
from __future__ import annotations

import numpy as np


class ParallelMode:
    """fleet/base/topology.py:37 parity."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class ReduceType:
    """base.core.ReduceType parity (reduce kinds for Partial placements)."""

    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


class DistAttr:
    """auto_parallel/api.py:65 DistAttr: (process_mesh, sharding_specs)
    record used by shard_tensor's attr-style API."""

    def __init__(self, mesh, sharding_specs):
        self.process_mesh = mesh
        self.sharding_specs = list(sharding_specs)

    @property
    def dims_mapping(self):
        names = list(self.process_mesh.dim_names)
        return [
            (names.index(s) if s in names else -1) for s in self.sharding_specs
        ]

    def __repr__(self):
        return f"DistAttr(mesh={self.process_mesh}, specs={self.sharding_specs})"


# ---------------------------------------------------------------------------
# fleet dataset surface
# ---------------------------------------------------------------------------

class _DatasetBase:
    def __init__(self):
        self._filelist = []
        self._parse_fn = None
        self._batch_size = 1
        self._thread = 1
        self._use_var = []

    def init(self, batch_size=1, thread_num=1, use_var=None, pipe_command=None,
             input_type=0, fs_name="", fs_ugi="", download_cmd="cat", **kwargs):
        """Reference Dataset.init. pipe_command (an external parsing binary)
        has no TPU analog — pass parse_fn= (a python callable line -> sample)
        instead; identity (whitespace-split floats) is the default."""
        self._batch_size = batch_size
        self._thread = thread_num
        self._use_var = use_var or []
        self._parse_fn = kwargs.get("parse_fn")
        if pipe_command not in (None, "cat"):
            raise NotImplementedError(
                "pipe_command external parsers have no TPU analog; pass "
                "parse_fn= (python callable) instead"
            )
        return self

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def _parse(self, line):
        if self._parse_fn is not None:
            return self._parse_fn(line)
        return np.asarray([float(v) for v in line.split()], np.float32)


class InMemoryDataset(_DatasetBase):
    """fleet InMemoryDataset subset: text samples loaded to host memory,
    local shuffle, iteration as a paddle_tpu.io-compatible iterable."""

    def __init__(self):
        super().__init__()
        self._samples = []

    def load_into_memory(self):
        self._samples = []
        for path in self._filelist:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        self._samples.append(self._parse(line))

    def local_shuffle(self):
        np.random.shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num=12):
        # single-host: same as local shuffle (multi-host PS shuffle is the
        # out-of-scope PS decision)
        self.local_shuffle()

    def get_memory_data_size(self, fleet=None):
        return len(self._samples)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._samples)

    def release_memory(self):
        self._samples = []

    def __iter__(self):
        return iter(self._samples)

    def __len__(self):
        return len(self._samples)


class QueueDataset(_DatasetBase):
    """fleet QueueDataset subset: streaming iteration over the filelist
    (no memory residency)."""

    def __iter__(self):
        for path in self._filelist:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield self._parse(line)


class CountFilterEntry:
    """Sparse-table accessor config (fleet entry_attr): admit a key after
    `count` shows."""

    def __init__(self, count):
        if count < 1:
            raise ValueError("count must be >= 1")
        self._count = count

    def _to_attr(self):
        return f"count_filter_entry:{self._count}"

    def __repr__(self):
        return self._to_attr()


class ProbabilityEntry:
    def __init__(self, probability):
        if not 0 < probability <= 1:
            raise ValueError("probability must be in (0, 1]")
        self._probability = probability

    def _to_attr(self):
        return f"probability_entry:{self._probability}"

    def __repr__(self):
        return self._to_attr()


class ShowClickEntry:
    def __init__(self, show_name, click_name):
        self._show = show_name
        self._click = click_name

    def _to_attr(self):
        return f"show_click_entry:{self._show}:{self._click}"

    def __repr__(self):
        return self._to_attr()


# ---------------------------------------------------------------------------
# gloo compat
# ---------------------------------------------------------------------------

def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """CPU-side rendezvous (reference gloo_init_parallel_env): joins the
    native TCPStore at server_endpoint (rank 0 hosts it)."""
    from ..native.store import TCPStore

    host, _, port = server_endpoint.rpartition(":")
    store = TCPStore(host or "127.0.0.1", int(port), is_master=(rank_id == 0),
                     world_size=rank_num, timeout=60.0)
    global _GLOO_STORE, _GLOO_RANKS
    _GLOO_STORE = store
    _GLOO_RANKS = (rank_id, rank_num)
    store.add("gloo_init", 1)
    # block until ALL rank_num ranks have joined (add(key, 0) reads the
    # counter) — waiting on mere key existence would be self-satisfying
    import time

    deadline = time.monotonic() + 120
    while store.add("gloo_init", 0) < rank_num:
        if time.monotonic() > deadline:
            raise TimeoutError("gloo_init_parallel_env: ranks did not all join")
        time.sleep(0.01)
    # every rank passes this line within one store round-trip of the last
    # joiner — record the (perf_ns, unix_ns) pair the trace merge uses to
    # align per-rank host-tracer clocks into one timeline
    try:
        from ..profiler import trace_merge as _trace_merge

        _trace_merge.note_rendezvous(rank_id, rank_num)
    except Exception:
        pass
    return store


_GLOO_STORE = None
_GLOO_RANKS = (0, 1)
_GLOO_BARRIERS = [0]


def gloo_barrier():
    """Store-based barrier over the gloo bootstrap group."""
    if _GLOO_STORE is None:
        raise RuntimeError("gloo_barrier: call gloo_init_parallel_env first")
    _GLOO_BARRIERS[0] += 1
    key = f"gloo_barrier_{_GLOO_BARRIERS[0]}"
    n = _GLOO_STORE.add(key, 1)
    rank, world = _GLOO_RANKS
    import time

    deadline = time.monotonic() + 60
    while _GLOO_STORE.add(key, 0) < world:
        if time.monotonic() > deadline:
            raise TimeoutError("gloo_barrier timed out")
        time.sleep(0.01)


def gloo_release():
    """Tear down the gloo bootstrap group."""
    global _GLOO_STORE
    if _GLOO_STORE is not None:
        try:
            _GLOO_STORE.close()
        except Exception:
            pass
        _GLOO_STORE = None
