"""Numeric table, round 3 expansion (VERDICT r2 next-round #3).

Row format: (name, op_fn, np_ref, arrays, kwargs, flags)
flags: "g" — also check gradients vs the jax.grad oracle
       "b" — also sweep bfloat16 (forward, loose tolerance)
Per-op bf16 tolerance overrides live in BF16_TOL (the reference's
white-list pattern: test/white_list/op_accuracy_white_list.py).
"""
import numpy as np
import pytest
import scipy.special as sps

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import check_forward, check_grad

R = np.random.RandomState(7)
A = R.randn(4, 6).astype("float32")
B = R.randn(4, 6).astype("float32")
C = R.randn(6, 3).astype("float32")
P = (np.abs(A) + 0.5).astype("float32")          # positive
U = (R.rand(4, 6) * 0.8 + 0.1).astype("float32")  # in (0,1)
V1 = R.randn(6).astype("float32")
W1 = R.randn(6).astype("float32")
I64 = R.randint(0, 4, (6,)).astype("int64")
SQ = (A[:4, :4] @ A[:4, :4].T + 4 * np.eye(4)).astype("float32")  # SPD
IMG = R.randn(2, 3, 8, 8).astype("float32")

T = []  # the table


def row(name, op, ref, arrays, kwargs=None, flags=""):
    T.append((name, op, ref, arrays, kwargs or {}, flags))


# ---- elementwise unary ----
row("abs", paddle.abs, np.abs, (A,), flags="gb")
row("neg", paddle.neg, np.negative, (A,), flags="gb")
row("exp", paddle.exp, np.exp, (A,), flags="gb")
row("log", paddle.log, np.log, (P,), flags="gb")
row("sqrt", paddle.sqrt, np.sqrt, (P,), flags="gb")
row("sin", paddle.sin, np.sin, (A,), flags="gb")
row("cos", paddle.cos, np.cos, (A,), flags="gb")
row("tan", paddle.tan, np.tan, (U,), flags="gb")
row("asin", paddle.asin, np.arcsin, (U - 0.5,), flags="gb")
row("acos", paddle.acos, np.arccos, (U - 0.5,), flags="gb")
row("atan", paddle.atan, np.arctan, (A,), flags="gb")
row("floor", paddle.floor, np.floor, (A * 3,), flags="b")
row("ceil", paddle.ceil, np.ceil, (A * 3,), flags="b")
row("round", paddle.round, np.round, (A * 3,), flags="b")
row("tanh", paddle.tanh, np.tanh, (A,), flags="gb")
row("sigmoid", F.sigmoid, sps.expit, (A,), flags="gb")
row("erfinv", paddle.erfinv, sps.erfinv, (U - 0.5,), flags="g")
row("digamma", paddle.digamma, sps.digamma, (P,), flags="g")
row("lgamma", paddle.lgamma, sps.gammaln, (P,), flags="g")
row("gammaln", paddle.gammaln, sps.gammaln, (P,), flags="g")
row("gammainc", paddle.gammainc, sps.gammainc, (P, P + 0.3), flags="")
row("gammaincc", paddle.gammaincc, sps.gammaincc, (P, P + 0.3), flags="")
row("multigammaln", lambda x: paddle.multigammaln(x, 2), lambda v: sps.multigammaln(v, 2), (P + 1.0,), flags="")
row("polygamma", lambda x: paddle.polygamma(x, 1), lambda v: sps.polygamma(1, v), (P,), flags="")
row("i0", paddle.i0, sps.i0, (A,), flags="g")
row("i0e", paddle.i0e, sps.i0e, (A,), flags="") if hasattr(paddle, "i0e") else None
row("i1", paddle.i1, sps.i1, (A,), flags="") if hasattr(paddle, "i1") else None
row("logit", paddle.logit, sps.logit, (U,), flags="g")
row("signbit", paddle.signbit, np.signbit, (A,), flags="")
row("isnan", paddle.isnan, np.isnan, (np.array([1.0, np.nan], "float32"),))
row("isinf", paddle.isinf, np.isinf, (np.array([1.0, np.inf], "float32"),))
row("isfinite", paddle.isfinite, np.isfinite, (np.array([1.0, np.inf, np.nan], "float32"),))
row("frexp", paddle.frexp, lambda v: tuple(np.frexp(v)), (P,), flags="")

# ---- elementwise binary ----
row("add", paddle.add, np.add, (A, B), flags="gb")
row("subtract", paddle.subtract, np.subtract, (A, B), flags="gb")
row("multiply", paddle.multiply, np.multiply, (A, B), flags="gb")
row("divide", paddle.divide, np.divide, (A, P), flags="gb")
row("floor_divide", paddle.floor_divide, np.floor_divide, (A * 5, P), flags="")
row("mod", paddle.mod, np.mod, (A * 5, P), flags="")
row("remainder", paddle.remainder, np.mod, (A * 5, P), flags="")
row("pow", paddle.pow, np.power, (P, B), flags="g")
row("atan2", paddle.atan2, np.arctan2, (A, B), flags="g")
row("copysign", paddle.copysign, np.copysign, (A, B), flags="")
row("ldexp", paddle.ldexp, np.ldexp, (A, I64[:6].astype("int32") % 3), flags="")
row("nextafter", paddle.nextafter, np.nextafter, (A, B), flags="") if hasattr(paddle, "nextafter") else None
row("lerp", paddle.lerp, lambda x, y, w: x + w * (y - x), (A, B, U), flags="g")
row("inner", paddle.inner, np.inner, (V1, W1), flags="g")

# ---- comparisons / logic / bitwise ----
row("equal", paddle.equal, np.equal, (I64, I64))
row("not_equal", paddle.not_equal, np.not_equal, (I64, I64 * 0 + 1))
row("less_than", paddle.less_than, np.less, (A, B))
row("less_equal", paddle.less_equal, np.less_equal, (A, B))
row("greater_than", paddle.greater_than, np.greater, (A, B))
row("greater_equal", paddle.greater_equal, np.greater_equal, (A, B))
row("logical_and", paddle.logical_and, np.logical_and, (A > 0, B > 0))
row("logical_or", paddle.logical_or, np.logical_or, (A > 0, B > 0))
row("logical_xor", paddle.logical_xor, np.logical_xor, (A > 0, B > 0))
row("logical_not", paddle.logical_not, np.logical_not, (A > 0,))
row("bitwise_and", paddle.bitwise_and, np.bitwise_and, (I64, I64 + 1))
row("bitwise_or", paddle.bitwise_or, np.bitwise_or, (I64, I64 + 1))
row("bitwise_xor", paddle.bitwise_xor, np.bitwise_xor, (I64, I64 + 1))
row("bitwise_not", paddle.bitwise_not, np.bitwise_not, (I64,))
row("bitwise_left_shift", paddle.bitwise_left_shift, np.left_shift, (I64, I64 % 3))
row("bitwise_right_shift", paddle.bitwise_right_shift, np.right_shift, (I64 * 8, I64 % 3))
row("isclose", paddle.isclose, np.isclose, (A, A + 1e-9))

# ---- reductions ----
row("sum", paddle.sum, np.sum, (A,), flags="gb")
row("mean", paddle.mean, np.mean, (A,), flags="gb")
row("max", paddle.max, np.max, (A,), flags="gb")
row("min", paddle.min, np.min, (A,), flags="gb")
row("prod", paddle.prod, np.prod, (U,), flags="g")
row("median", paddle.median, None, (A[0],), flags="")
row("nanmedian", paddle.nanmedian, None, (np.array([1.0, np.nan, 3.0, 2.0], "float32"),), flags="")
row("quantile", lambda x: paddle.quantile(x, 0.5), lambda v: np.quantile(v, 0.5).astype("float32"), (A,), flags="")
row("nanquantile", lambda x: paddle.nanquantile(x, 0.5), lambda v: np.nanquantile(v, 0.5).astype("float32"), (A,), flags="")
row("logcumsumexp", lambda x: paddle.logcumsumexp(x, axis=1), lambda v: np.log(np.cumsum(np.exp(v), 1)), (A,), flags="g")
row("all", paddle.all, np.all, (A > -10,))
row("any", paddle.any, np.any, (A > 2,))
row("norm_fro", lambda x: paddle.linalg.norm(x), lambda v: np.linalg.norm(v), (A,), flags="g")
row("norm_1", lambda x: paddle.linalg.norm(x, p=1, axis=1), lambda v: np.abs(v).sum(1), (A,), flags="g")
row("dist", lambda x, y: paddle.dist(x, y, 2), lambda x, y: np.linalg.norm((x - y).ravel()), (A, B), flags="g")

# ---- sorting / search / indexing ----
row("sort", lambda x: paddle.sort(x, axis=1), lambda v: np.sort(v, 1), (A,))
row("argsort", lambda x: paddle.argsort(x, axis=1), lambda v: np.argsort(v, 1, kind="stable"), (A,))
row("argmax", lambda x: paddle.argmax(x, axis=1), lambda v: np.argmax(v, 1), (A,))
row("argmin", lambda x: paddle.argmin(x, axis=1), lambda v: np.argmin(v, 1), (A,))
row("topk", lambda x: paddle.topk(x, 2, axis=1)[0], lambda v: -np.sort(-v, 1)[:, :2], (A,))
row("kthvalue", lambda x: paddle.kthvalue(x, 2, axis=1)[0], lambda v: np.sort(v, 1)[:, 1], (A,))
row("mode", lambda x: paddle.mode(x, axis=1)[0], None, (np.array([[1.0, 1.0, 2.0], [3.0, 3.0, 1.0]], "float32"),))
row("unique", lambda x: paddle.unique(x), np.unique, (np.array([3.0, 1.0, 3.0, 2.0], "float32"),))
row("unique_consecutive", lambda x: paddle.unique_consecutive(x), None, (np.array([1.0, 1.0, 2.0, 2.0, 1.0], "float32"),))
row("nonzero", lambda x: paddle.nonzero(x), lambda v: np.stack(np.nonzero(v), 1), (np.array([0.0, 2.0, 0.0, 3.0], "float32"),))
row("index_select", lambda x, i: paddle.index_select(x, i, axis=0), lambda v, i: v[i], (A, I64[:3]))
row("index_sample", paddle.index_sample, None, (A, np.array([[0, 1], [2, 3], [1, 0], [3, 2]], "int64")))
row("index_add", lambda x, i, v: paddle.index_add(x, i, 0, v), None, (A, np.array([0, 2], "int64"), B[:2]))
row("take", lambda x, i: paddle.take(x, i), lambda v, i: v.ravel()[i], (A, np.array([0, 5, 11], "int64")))
row("take_along_axis", lambda x, i: paddle.take_along_axis(x, i, 1), lambda v, i: np.take_along_axis(v, i, 1), (A, np.zeros((4, 1), "int64")))
row("put_along_axis", lambda x, i, v: paddle.put_along_axis(x, i, v, 1), None, (A, np.zeros((4, 1), "int64"), np.ones((4, 1), "float32")))
row("masked_select", paddle.masked_select, lambda v, m: v[m], (A, A > 0))
row("masked_fill", lambda x, m: paddle.masked_fill(x, m, -1.0), lambda v, m: np.where(m, -1.0, v), (A, A > 0))
row("where", lambda x, y: paddle.where(paddle.to_tensor(A > 0), x, y), lambda x, y: np.where(A > 0, x, y), (A, B), flags="g")
row("gather", lambda x, i: paddle.gather(x, i, axis=0), lambda v, i: v[i], (A, I64[:3]))
row("gather_nd", paddle.gather_nd, None, (A, np.array([[0, 1], [3, 2]], "int64")))
row("scatter", lambda x, i, u: paddle.scatter(x, i, u), None, (A, np.array([0, 2], "int64"), B[:2]))
row("diag", paddle.diag, np.diag, (V1,))
row("diagflat", paddle.diagflat, np.diagflat, (V1,))
row("diagonal", paddle.diagonal, np.diagonal, (A[:4, :4],))
row("diag_embed", paddle.diag_embed, None, (V1,))
row("tril", paddle.tril, np.tril, (A,), flags="gb")
row("triu", paddle.triu, np.triu, (A,), flags="gb")

# ---- manipulation ----
row("concat", lambda x, y: paddle.concat([x, y], axis=0), lambda x, y: np.concatenate([x, y], 0), (A, B), flags="gb")
row("stack2", lambda x, y: paddle.stack([x, y]), lambda x, y: np.stack([x, y]), (A, B), flags="gb")
row("split", lambda x: paddle.split(x, 2, axis=1)[0], lambda v: np.split(v, 2, 1)[0], (A,), flags="g")
row("chunk", lambda x: paddle.chunk(x, 2, axis=0)[1], lambda v: np.split(v, 2, 0)[1], (A,))
row("tile", lambda x: paddle.tile(x, [2, 1]), lambda v: np.tile(v, (2, 1)), (A,), flags="g")
row("expand", lambda x: paddle.expand(x, [3, 4, 6]), lambda v: np.broadcast_to(v, (3, 4, 6)), (A,), flags="g")
row("reshape", lambda x: paddle.reshape(x, [6, 4]), lambda v: v.reshape(6, 4), (A,), flags="gb")
row("transpose", lambda x: paddle.transpose(x, [1, 0]), lambda v: v.T, (A,), flags="gb")
row("squeeze", lambda x: paddle.squeeze(x[None]), lambda v: v, (A,))
row("unsqueeze", lambda x: paddle.unsqueeze(x, 0), lambda v: v[None], (A,))
row("flatten", paddle.flatten, lambda v: v.ravel(), (A,), flags="g")
row("unflatten", lambda x: paddle.unflatten(x, 1, [2, 3]), lambda v: v.reshape(4, 2, 3), (A,))
row("flip2", lambda x: paddle.flip(x, axis=[0, 1]), lambda v: v[::-1, ::-1], (A,))
row("reverse", lambda x: paddle.reverse(x, [0]), lambda v: v[::-1], (A,))
row("moveaxis", lambda x: paddle.moveaxis(x, 0, 1), lambda v: np.moveaxis(v, 0, 1), (A,))
row("swapaxes", lambda x: paddle.swapaxes(x, 0, 1), lambda v: np.swapaxes(v, 0, 1), (A,))
row("repeat_interleave", lambda x: paddle.repeat_interleave(x, 2, axis=0), lambda v: np.repeat(v, 2, 0), (A,))
row("broadcast_to", lambda x: paddle.broadcast_to(x, [3, 4, 6]), lambda v: np.broadcast_to(v, (3, 4, 6)), (A,))
row("hstack", lambda x, y: paddle.hstack([x, y]), lambda x, y: np.hstack([x, y]), (A, B))
row("vstack", lambda x, y: paddle.vstack([x, y]), lambda x, y: np.vstack([x, y]), (A, B))
row("dstack", lambda x, y: paddle.dstack([x, y]), lambda x, y: np.dstack([x, y]), (A, B))
row("column_stack", lambda x, y: paddle.column_stack([x, y]), lambda x, y: np.column_stack([x, y]), (V1, W1))
row("row_stack", lambda x, y: paddle.row_stack([x, y]), lambda x, y: np.vstack([x, y]), (V1, W1))
row("hsplit", lambda x: paddle.hsplit(x, 2)[0], lambda v: np.hsplit(v, 2)[0], (A,))
row("vsplit", lambda x: paddle.vsplit(x, 2)[0], lambda v: np.vsplit(v, 2)[0], (A,))
row("tensor_split", lambda x: paddle.tensor_split(x, 3, axis=1)[0], lambda v: np.array_split(v, 3, 1)[0], (A,))
row("unbind", lambda x: paddle.unbind(x, axis=0)[1], lambda v: v[1], (A,))
row("as_strided_T", lambda x: paddle.as_strided(x, [6, 4], [1, 6]), lambda v: np.lib.stride_tricks.as_strided(v, (6, 4), (4, 24)), (A,)) if hasattr(paddle, "as_strided") else None
row("pad_constant", lambda x: F.pad(x[None, None], [1, 1, 1, 1]), lambda v: np.pad(v, ((1, 1), (1, 1)))[None, None], (A,))
row("cast", lambda x: paddle.cast(x, "int32"), lambda v: v.astype("int32"), (A * 3,))
row("clip", lambda x: paddle.clip(x, -0.5, 0.5), lambda v: np.clip(v, -0.5, 0.5), (A,), flags="gb")
row("bucketize", lambda x, e: paddle.bucketize(x, e), lambda v, e: np.searchsorted(e, v), (A, np.array([-1.0, 0.0, 1.0], "float32"))) if hasattr(paddle, "bucketize") else None
row("combinations", lambda x: paddle.combinations(x, 2), None, (V1[:4],))
row("pdist", paddle.pdist, None, (A,))

# ---- linalg ----
row("matmul", paddle.matmul, np.matmul, (A, C), flags="gb")
row("bmm", paddle.bmm, np.matmul, (np.stack([A[:3, :3]] * 2), np.stack([A[:3, :3]] * 2)), flags="g")
row("mv", paddle.mv, lambda m, v: m @ v, (A, V1), flags="g")
row("addmm", lambda i, x, y: paddle.addmm(i, x, y), lambda i, x, y: i + x @ y, (np.zeros((4, 3), "float32"), A, C), flags="g")
row("cholesky", lambda x: paddle.linalg.cholesky(x), np.linalg.cholesky, (SQ,))
row("inv", paddle.linalg.inv, np.linalg.inv, (SQ,))
row("pinv", paddle.linalg.pinv, np.linalg.pinv, (A,))
row("det", paddle.linalg.det, np.linalg.det, (SQ,))
row("slogdet", lambda x: paddle.linalg.slogdet(x)[1], lambda v: np.linalg.slogdet(v)[1], (SQ,))
row("matrix_power", lambda x: paddle.linalg.matrix_power(x, 3), lambda v: np.linalg.matrix_power(v, 3), (SQ,))
row("solve", paddle.linalg.solve, np.linalg.solve, (SQ, V1[:4]))
row("triangular_solve", lambda a, b: paddle.linalg.triangular_solve(a, b, upper=False),
    lambda a, b: np.linalg.solve(np.tril(a), b), (SQ, V1[:4].reshape(4, 1)))
row("matrix_rank", paddle.linalg.matrix_rank, np.linalg.matrix_rank, (SQ,))
row("eigvalsh", lambda x: paddle.linalg.eigvalsh(x), np.linalg.eigvalsh, (SQ,))
row("qr_r", lambda x: paddle.linalg.qr(x)[1], None, (A,))
row("svdvals", lambda x: paddle.linalg.svd(x)[1], lambda v: np.linalg.svd(v)[1], (A,))
row("lstsq", lambda a, b: paddle.linalg.lstsq(a, b)[0], lambda a, b: np.linalg.lstsq(a, b, rcond=None)[0], (A.T[:6, :4], V1[:6].reshape(6, 1))) if hasattr(paddle.linalg, "lstsq") else None
row("cond2", lambda x: paddle.linalg.cond(x), lambda v: np.linalg.cond(v), (SQ,)) if hasattr(paddle.linalg, "cond") else None
row("histogramdd", None, None, None) if False else None

# ---- activations (nn.functional) ----
row("relu", F.relu, lambda v: np.maximum(v, 0), (A,), flags="gb")
row("relu6", F.relu6, lambda v: np.clip(v, 0, 6), (A * 4,), flags="gb")
row("gelu", F.gelu, lambda v: 0.5 * v * (1 + sps.erf(v / np.sqrt(2))), (A,), flags="gb")
row("silu", F.silu, lambda v: v * sps.expit(v), (A,), flags="gb")
row("softplus", F.softplus, lambda v: np.log1p(np.exp(-np.abs(v))) + np.maximum(v, 0), (A,), flags="gb")
row("mish", F.mish, lambda v: v * np.tanh(np.log1p(np.exp(v))), (A,), flags="g")
row("elu", F.elu, lambda v: np.where(v > 0, v, np.expm1(v)), (A,), flags="g")
row("celu", F.celu, lambda v: np.where(v > 0, v, np.expm1(v)), (A,), flags="g")
row("selu", F.selu, lambda v: 1.0507009873554805 * np.where(v > 0, v, 1.6732632423543772 * np.expm1(v)), (A,), flags="g")
row("leaky_relu", F.leaky_relu, lambda v: np.where(v > 0, v, 0.01 * v), (A,), flags="gb")
row("hardtanh", F.hardtanh, lambda v: np.clip(v, -1, 1), (A * 2,), flags="g")
row("hardsigmoid", F.hardsigmoid, lambda v: np.clip(v / 6 + 0.5, 0, 1), (A * 4,), flags="g")
row("hardswish", F.hardswish, lambda v: v * np.clip(v + 3, 0, 6) / 6, (A * 4,), flags="g")
row("hardshrink", F.hardshrink, lambda v: np.where(np.abs(v) > 0.5, v, 0), (A,), flags="")
row("softshrink", F.softshrink, lambda v: np.sign(v) * np.maximum(np.abs(v) - 0.5, 0), (A,), flags="g")
row("tanhshrink", F.tanhshrink, lambda v: v - np.tanh(v), (A,), flags="g")
row("thresholded_relu", F.thresholded_relu, lambda v: np.where(v > 1.0, v, 0), (A * 2,), flags="")
row("log_sigmoid", F.log_sigmoid, lambda v: sps.log_expit(v), (A,), flags="g")
row("softmax", lambda x: F.softmax(x, axis=-1), lambda v: sps.softmax(v, -1), (A,), flags="gb")
row("log_softmax", lambda x: F.log_softmax(x, axis=-1), lambda v: sps.log_softmax(v, -1), (A,), flags="gb")
row("glu", F.glu, lambda v: v[:, :3] * sps.expit(v[:, 3:]), (A,), flags="g")
row("swish", F.swish, lambda v: v * sps.expit(v), (A,), flags="g") if hasattr(F, "swish") else None
row("normalize", lambda x: F.normalize(x, axis=1), lambda v: v / np.maximum(np.linalg.norm(v, axis=1, keepdims=True), 1e-12), (A,), flags="g")
row("linear", F.linear, lambda x, w: x @ w, (A, C), flags="gb")
row("embedding", lambda i, w: F.embedding(i, w), lambda i, w: w[i], (I64, A), flags="")
row("one_hot", lambda i: F.one_hot(i, 5), lambda i: np.eye(5, dtype="float32")[i], (I64 % 5,))
row("label_smooth", lambda x: F.label_smooth(x, epsilon=0.1), lambda v: v * 0.9 + 0.1 / v.shape[-1], (U,))

# ---- losses ----
row("mse_loss", F.mse_loss, lambda a, b: ((a - b) ** 2).mean(), (A, B), flags="g")
row("l1_loss", F.l1_loss, lambda a, b: np.abs(a - b).mean(), (A, B), flags="g")
row("smooth_l1", lambda a, b: F.smooth_l1_loss(a, b), None, (A, B), flags="g")
row("bce", lambda p, t: F.binary_cross_entropy(p, t),
    lambda p, t: -(t * np.log(p) + (1 - t) * np.log(1 - p)).mean(), (U, (U > 0.5).astype("float32")), flags="g")
row("bce_logits", lambda x, t: F.binary_cross_entropy_with_logits(x, t),
    lambda x, t: (np.maximum(x, 0) - x * t + np.log1p(np.exp(-np.abs(x)))).mean(), (A, (B > 0).astype("float32")), flags="g")
row("cross_entropy", lambda x: F.cross_entropy(x, paddle.to_tensor(I64[:4])),
    lambda x: -(sps.log_softmax(x, -1)[np.arange(4), I64[:4]]).mean(), (A,), flags="g")
row("nll_loss", lambda x: F.nll_loss(x, paddle.to_tensor(I64[:4])), lambda x: -x[np.arange(4), I64[:4]].mean(),
    (sps.log_softmax(A, -1).astype("float32"),), flags="g")
row("kl_div", lambda x, t: F.kl_div(x, t, reduction="batchmean"), None,
    (sps.log_softmax(A, -1).astype("float32"), sps.softmax(B, -1).astype("float32")), flags="g")
row("cosine_similarity", lambda a, b: F.cosine_similarity(a, b, axis=1), None, (A, B), flags="g")

# ---- norm layers (functional, eval-mode refs) ----
row("layer_norm", lambda x, w, b: F.layer_norm(x, 6, w, b),
    lambda x, w, b: (x - x.mean(-1, keepdims=True)) / np.sqrt(x.var(-1, keepdims=True) + 1e-5) * w + b,
    (A, np.ones(6, "float32"), np.zeros(6, "float32")), flags="gb")
row("rms_norm_f", lambda x, w: paddle.incubate.nn.functional.fused_rms_norm(x, w),
    lambda x, w: x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w,
    (A, np.ones(6, "float32")), flags="g")

# ---- pooling / conv (small shapes, np oracles) ----
row("avg_pool2d", lambda x: F.avg_pool2d(x, 2, 2),
    lambda v: v.reshape(2, 3, 4, 2, 4, 2).mean(axis=(3, 5)), (IMG,), flags="g")
row("max_pool2d", lambda x: F.max_pool2d(x, 2, 2),
    lambda v: v.reshape(2, 3, 4, 2, 4, 2).max(axis=(3, 5)), (IMG,), flags="g")
row("adaptive_avg_pool2d", lambda x: F.adaptive_avg_pool2d(x, 1),
    lambda v: v.mean(axis=(2, 3), keepdims=True), (IMG,), flags="g")
row("adaptive_max_pool2d", lambda x: F.adaptive_max_pool2d(x, 1),
    lambda v: v.max(axis=(2, 3), keepdims=True), (IMG,), flags="")
row("pixel_shuffle", lambda x: F.pixel_shuffle(x, 2), None, (R.randn(1, 4, 3, 3).astype("float32"),))
row("pixel_unshuffle", lambda x: F.pixel_unshuffle(x, 2), None, (R.randn(1, 1, 4, 4).astype("float32"),)) if hasattr(F, "pixel_unshuffle") else None
row("channel_shuffle", lambda x: F.channel_shuffle(x, 2), None, (R.randn(1, 4, 3, 3).astype("float32"),)) if hasattr(F, "channel_shuffle") else None
row("unfold", lambda x: F.unfold(x, 2), None, (IMG,)) if hasattr(F, "unfold") else None
row("conv2d_id", lambda x, w: F.conv2d(x, w), None, (IMG, R.randn(5, 3, 3, 3).astype("float32") * 0.2), flags="g")
row("conv1d_id", lambda x, w: F.conv1d(x, w), None, (R.randn(2, 3, 10).astype("float32"), R.randn(4, 3, 3).astype("float32") * 0.2), flags="g")
row("conv2d_transpose_id", lambda x, w: F.conv2d_transpose(x, w), None, (IMG, R.randn(3, 2, 3, 3).astype("float32") * 0.2), flags="g")
row("interpolate_nearest", lambda x: F.interpolate(x, scale_factor=2, mode="nearest"),
    lambda v: v.repeat(2, axis=2).repeat(2, axis=3), (IMG,))
row("interpolate_bilinear", lambda x: F.interpolate(x, size=[4, 4], mode="bilinear"), None, (IMG,), flags="g")

# ---- creation ----
row("zeros", lambda: paddle.zeros([2, 3]), lambda: np.zeros((2, 3), "float32"), ())
row("ones", lambda: paddle.ones([2, 3]), lambda: np.ones((2, 3), "float32"), ())
row("full", lambda: paddle.full([2, 2], 7.0), lambda: np.full((2, 2), 7.0, "float32"), ())
row("arange", lambda: paddle.arange(0, 10, 2), lambda: np.arange(0, 10, 2), ())
row("linspace", lambda: paddle.linspace(0, 1, 5), lambda: np.linspace(0, 1, 5, dtype="float32"), ())
row("logspace", lambda: paddle.logspace(0, 2, 3), lambda: np.logspace(0, 2, 3, dtype="float32"), ()) if hasattr(paddle, "logspace") else None
row("eye", lambda: paddle.eye(3, 4), lambda: np.eye(3, 4, dtype="float32"), ())
row("full_like", lambda x: paddle.full_like(x, 2.0), lambda v: np.full_like(v, 2.0), (A,))
row("zeros_like", paddle.zeros_like, np.zeros_like, (A,))
row("ones_like", paddle.ones_like, np.ones_like, (A,))
row("tril_indices", lambda: paddle.tril_indices(3, 3, 0), lambda: np.stack(np.tril_indices(3, 0, 3)), ())
row("triu_indices", lambda: paddle.triu_indices(3, 3, 0), lambda: np.stack(np.triu_indices(3, 0, 3)), ())
row("meshgrid", lambda x, y: paddle.meshgrid(x, y)[0], lambda x, y: np.meshgrid(x, y, indexing="ij")[0], (V1, W1))
row("as_complex", lambda x: paddle.as_complex(x), lambda v: v[..., 0] + 1j * v[..., 1], (R.randn(3, 2).astype("float32"),))
row("as_real", lambda x: paddle.as_real(x), lambda v: np.stack([v.real, v.imag], -1), (R.randn(3).astype("float32") + 1j * R.randn(3).astype("float32"),))

T = [t for t in T if t is not None]

# per-op bf16 tolerance overrides (reference white-list pattern); default
# bf16 tolerance below is rtol=2e-2/atol=2e-2
BF16_TOL = {
    "matmul": (5e-2, 5e-2),
    "linear": (5e-2, 5e-2),
    "softplus": (3e-2, 3e-2),
    "gelu": (3e-2, 3e-2),
    "tan": (8e-2, 8e-2),
}

# r4 (VERDICT r3 next-round #6): bf16 coverage is now the POLICY — every
# table op with float inputs and a closed-form reference sweeps bf16 —
# rather than a hand-picked "b" flag. Exclusions are documented, not
# silent:
BF16_EXCLUDE = {
    # precision-structured ops: the op's DEFINITION needs more than 8
    # mantissa bits at these operating points
    "isclose": "compares at 1e-9 — below bf16 resolution by construction",
    "nextafter": "ULP-stepping is dtype-bit-specific; bf16 ULP != f32 ULP",
    "frexp": "mantissa/exponent decomposition is dtype-bit-specific",
    "erfinv": "diverges near +/-1; bf16 rounding of inputs crosses poles",
    "logit": "diverges near 0/1; input rounding crosses poles",
    # special functions whose jax lowerings are f32-internal but whose
    # magnitude spans overflow bf16's range at our operating points
    "multigammaln": "output magnitude ~1e2 with cancellation",
    "polygamma": "series cancellation below bf16 resolution",
    "gammainc": "continued-fraction cancellation",
    "gammaincc": "continued-fraction cancellation",
    # decompositions: XLA lowers them f32-only; inputs round-trip through
    # bf16 but conditioning amplifies the 2^-8 input error past any
    # meaningful tolerance
    "cholesky": "conditioning amplifies bf16 input rounding",
    "qr": "conditioning amplifies bf16 input rounding",
    "svdvals": "conditioning amplifies bf16 input rounding",
    "eigvalsh": "conditioning amplifies bf16 input rounding",
    "inv": "conditioning amplifies bf16 input rounding",
    "pinv": "conditioning amplifies bf16 input rounding",
    "solve": "conditioning amplifies bf16 input rounding",
    "triangular_solve": "conditioning amplifies bf16 input rounding",
    "lstsq": "conditioning amplifies bf16 input rounding",
    "matrix_power": "repeated products amplify bf16 rounding",
    "det": "product of n values: error compounds past tolerance",
    "slogdet": "lu cancellation",
    "matrix_rank": "rank thresholding flips under input rounding",
    "cond": "ratio of extreme singular values",
    "householder_product": "orthogonality degrades past tolerance",
    "cond2": "ratio of extreme singular values (p=2 path)",
    # discontinuous ops: bf16 input rounding crosses the discontinuity
    "mod": "jump at multiples of the divisor; rounding flips the branch",
    "remainder": "jump at multiples of the divisor",
    # dtype-structural
    "as_complex": "complex pairs have no bfloat16 dtype",
}


def _bf16_eligible(t):
    name, op, ref, arrays, kwargs, flags = t
    if ref is None or name in BF16_EXCLUDE:
        return False
    return all(np.issubdtype(np.asarray(a).dtype, np.floating) for a in arrays)


@pytest.mark.parametrize("name,op,ref,arrays,kwargs,flags", T, ids=[t[0] for t in T])
def test_forward(name, op, ref, arrays, kwargs, flags):
    if ref is None:  # no closed-form ref: op must run and yield finite values
        out = op(*[paddle.to_tensor(a) for a in arrays], **kwargs)
        outs = out if isinstance(out, (tuple, list)) else [out]
        for o in outs:
            assert o is not None
            v = np.asarray(o.numpy(), dtype="float64")
            if name != "nanmedian":  # nan inputs by design
                assert np.isfinite(v).all(), f"{name}: non-finite output"
        return
    check_forward(op, ref, {f"x{i}": a for i, a in enumerate(arrays)}, kwargs, rtol=3e-5, atol=3e-5)


GRAD_ROWS = [t for t in T if "g" in t[5]]


@pytest.mark.parametrize("name,op,ref,arrays,kwargs,flags", GRAD_ROWS, ids=[t[0] for t in GRAD_ROWS])
def test_grad(name, op, ref, arrays, kwargs, flags):
    # int inputs must be BAKED into the row's lambda (see cross_entropy),
    # not silently dropped — dropping changes the op's arity
    assert all(np.issubdtype(a.dtype, np.floating) for a in arrays), (
        f"{name}: grad rows take float-only args; bake int args into the lambda")
    check_grad(op, {f"x{i}": a for i, a in enumerate(arrays)}, kwargs)


BF16_ROWS = [t for t in T if _bf16_eligible(t)]


@pytest.mark.parametrize("name,op,ref,arrays,kwargs,flags", BF16_ROWS, ids=[t[0] for t in BF16_ROWS])
def test_bf16_forward(name, op, ref, arrays, kwargs, flags):
    """bf16 sweep: inputs cast to bfloat16, reference computed in f32,
    compared at bf16-scale tolerance (per-op overrides in BF16_TOL — the
    reference's op_accuracy_white_list pattern)."""
    import ml_dtypes

    rtol, atol = BF16_TOL.get(name, (2e-2, 2e-2))
    ts = [paddle.to_tensor(a.astype(ml_dtypes.bfloat16)) for a in arrays]
    out = op(*ts, **kwargs)
    refv = ref(*arrays, **kwargs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    refs = refv if isinstance(refv, (tuple, list)) else [refv]
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(
            np.asarray(o.numpy(), dtype="float32"), np.asarray(r, dtype="float32"),
            rtol=rtol, atol=atol, err_msg=f"bf16 {name}")


BF16_GRAD_TOL = {}

BF16_GRAD_EXCLUDE = {
    # grads whose formula divides by op-output or (1-x^2)-style terms:
    # bf16 input rounding lands near the pole
    "asin": "grad 1/sqrt(1-x^2) near |x|->1",
    "acos": "grad -1/sqrt(1-x^2) near |x|->1",
    "tan": "grad 1/cos^2 blows past bf16 tolerance away from 0",
    "prod": "grad prod/x_i: divides by near-zero bf16-rounded values",
}

BF16_GRAD_ROWS = [
    t for t in BF16_ROWS if "g" in t[5] and t[0] not in BF16_GRAD_EXCLUDE
]


@pytest.mark.parametrize("name,op,ref,arrays,kwargs,flags", BF16_GRAD_ROWS, ids=[t[0] for t in BF16_GRAD_ROWS])
def test_bf16_grad(name, op, ref, arrays, kwargs, flags):
    """Gradients in the TRAINING dtype: tape runs bf16, oracle is f32
    jax.grad (VERDICT r3 next-round #6 — the low-precision grad axis)."""
    from op_test import check_grad_bf16

    rtol, atol = BF16_GRAD_TOL.get(name, (6e-2, 6e-2))
    check_grad_bf16(op, {f"x{i}": a for i, a in enumerate(arrays)}, kwargs,
                    rtol=rtol, atol=atol)


def test_table_scale():
    """The r3 table + the r2 table must together cover 250+ distinct ops
    (VERDICT: 'grow the numeric table ~3-4x')."""
    import test_ops_numeric_table as t1

    names1 = {r[0] for r in t1.FORWARD_TABLE} | {r[0] for r in t1.GRAD_OPS}
    names2 = {t[0] for t in T}
    assert len(names2) >= 180, len(names2)
    assert len(names1 | names2) >= 230, len(names1 | names2)
    assert len(GRAD_ROWS) >= 70, len(GRAD_ROWS)
    assert len(BF16_ROWS) >= 110, len(BF16_ROWS)
    assert len(BF16_GRAD_ROWS) >= 55, len(BF16_GRAD_ROWS)
