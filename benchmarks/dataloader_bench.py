"""DataLoader worker-mode benchmark (VERDICT r2 next-round #8).

Transform-heavy vision pipeline (PIL resize + jitter + normalize, batch
256): thread+native-ring prefetch vs the r3 multiprocess worker mode.
Python/PIL transforms hold the GIL, which is exactly why the reference
ships shared-memory worker PROCESSES (io/dataloader/dataloader_iter.py).

Run: python benchmarks/dataloader_bench.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset


class VisionDataset(Dataset):
    """PIL-backed transform pipeline: decode-ish + resize + flip + jitter +
    normalize. Deliberately Python/GIL-bound like real vision pipelines."""

    def __init__(self, n=2048, size=96):
        self.n = n
        self.size = size
        rng = np.random.RandomState(0)
        self.raw = rng.randint(0, 255, (64, 128, 128, 3), np.uint8)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        from PIL import Image, ImageEnhance

        img = Image.fromarray(self.raw[i % 64])
        img = img.resize((self.size, self.size), Image.BILINEAR)
        if i % 2:
            img = img.transpose(Image.FLIP_LEFT_RIGHT)
        img = ImageEnhance.Brightness(img).enhance(0.8 + (i % 7) * 0.05)
        img = ImageEnhance.Contrast(img).enhance(0.9 + (i % 5) * 0.04)
        a = np.asarray(img, np.float32) / 255.0
        a = (a - np.array([0.485, 0.456, 0.406], np.float32)) / np.array(
            [0.229, 0.224, 0.225], np.float32)
        return a.transpose(2, 0, 1), np.int64(i % 10)


def consume(it):
    t0 = time.perf_counter()
    n = 0
    for batch in it:
        n += 1
    return n / (time.perf_counter() - t0)


def main():
    ds = VisionDataset()
    batch = 256

    # warm PIL etc.
    _ = ds[0]

    for workers in (4,):
        dl_thread = DataLoader(ds, batch_size=batch, num_workers=workers,
                               use_shared_memory=True)
        # force the legacy thread/ring path regardless of routing
        r_ring = consume(dl_thread._prefetch_iter())
        print(f"thread+ring   (workers={workers}): {r_ring:6.2f} batches/s "
              f"({r_ring * batch:7.0f} img/s)")

        dl_mp = DataLoader(ds, batch_size=batch, num_workers=workers, persistent_workers=True)
        consume(iter(dl_mp))          # epoch 1: pays worker spawn
        r_mp = consume(iter(dl_mp))   # epoch 2: steady state
        print(f"mp workers    (workers={workers}): {r_mp:6.2f} batches/s "
              f"({r_mp * batch:7.0f} img/s, steady-state epoch)")
        print(f"-> {'MP' if r_mp > r_ring else 'THREAD'} wins by {max(r_mp, r_ring) / min(r_mp, r_ring):.2f}x")


if __name__ == "__main__":
    main()
