"""fleet.util — job-level utilities.

Reference parity: python/paddle/distributed/fleet/base/util_factory.py:49
(UtilBase: all_reduce/barrier/all_gather over the job's comm world,
get_file_shard, print_on_rank). TPU-native: the comm world is the
collective process group (XLA collectives / TCPStore bootstrap) — the
SERVER comm worlds belong to the decision-absent PS mode.
"""
from __future__ import annotations

import numpy as np


class UtilBase:
    def __init__(self):
        self.role_maker = None

    def _set_role_maker(self, role_maker):
        self.role_maker = role_maker

    def _require_dist(self):
        from ... import parallel_env

        return parallel_env.get_world_size() > 1

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        """Allreduce a host value across workers (util_factory.py:66)."""
        if comm_world not in ("worker", "server", "all"):
            raise ValueError("comm_world must be one of worker/server/all")
        arr = np.asarray(input)
        if not self._require_dist():
            return arr
        from ... import collective
        from .... import to_tensor

        t = to_tensor(arr)
        op = {
            "sum": collective.ReduceOp.SUM,
            "max": collective.ReduceOp.MAX,
            "min": collective.ReduceOp.MIN,
        }[mode]
        collective.all_reduce(t, op=op)
        return t.numpy()

    def barrier(self, comm_world="worker"):
        """Job barrier (util_factory.py:116)."""
        if not self._require_dist():
            return
        from ... import barrier

        barrier()

    def all_gather(self, input, comm_world="worker"):
        """Gather a scalar from every worker -> list (util_factory.py:157)."""
        if not self._require_dist():
            return [input]
        from ... import collective
        from .... import to_tensor

        t = to_tensor(np.asarray([input], dtype=np.float64))
        out = []
        collective.all_gather(out, t)
        return [o.numpy()[0].item() for o in out]

    def get_file_shard(self, files):
        """This trainer's slice of the file list (util_factory.py:231):
        block-partitioned, remainder spread over the first workers."""
        if not isinstance(files, list):
            raise TypeError("files should be a list of file need to be read.")
        if self.role_maker is not None:
            trainer_id = self.role_maker._worker_index()
            trainers = self.role_maker._worker_num()
        else:
            from ... import parallel_env

            trainer_id = parallel_env.get_rank()
            trainers = max(1, parallel_env.get_world_size())
        remainder = len(files) % trainers
        blocksize = len(files) // trainers
        blocks = [blocksize] * trainers
        for i in range(remainder):
            blocks[i] += 1
        begin = 0
        for i in range(trainers):
            if i == trainer_id:
                return files[begin: begin + blocks[i]]
            begin += blocks[i]
        return []

    def print_on_rank(self, message, rank_id):
        """Print only on the given rank (util_factory.py:290)."""
        if self.role_maker is not None:
            rank = self.role_maker._worker_index()
        else:
            from ... import parallel_env

            rank = parallel_env.get_rank()
        if rank == rank_id:
            print(message)
