"""TensorParallel model wrapper.

Reference parity: fleet/meta_parallel/tensor_parallel.py (TensorParallel:28)
— there it broadcasts non-distributed params across the mp group at init
(so every mp rank starts identical) and syncs grads. TPU-native: params
live once on the controller, non-distributed params are replicated over the
mesh by construction and mp-sharded params (mpu layers) were placed at
creation — the wrapper is a passthrough kept for API parity.
"""
from __future__ import annotations

from ....nn.layer import Layer


class TensorParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)
