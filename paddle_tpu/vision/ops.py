"""Vision ops (reference: python/paddle/vision/ops.py — nms, roi_align,
roi_pool, deform_conv2d, box handling).

TPU-native design: all ops are pure-jax, static-shape, gather/scatter based —
nms is the O(n^2) mask formulation (one [N,N] IoU matrix on the MXU + a scan,
instead of the reference's sequential CUDA kernel), roi_align is bilinear
gather, deform_conv2d is the sampling-grid gather + matmul formulation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.apply import apply, apply_nograd
from ..core.tensor import Tensor


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# boxes
# ---------------------------------------------------------------------------

def box_area(boxes):
    b = _v(boxes)
    return Tensor((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def _iou_matrix(a, b):
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-10)


def box_iou(boxes1, boxes2):
    return Tensor(_iou_matrix(_v(boxes1), _v(boxes2)))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """paddle.vision.ops.nms parity. Returns kept indices (by descending
    score when scores are given, else box order)."""
    b = _v(boxes)
    n = b.shape[0]
    if scores is not None:
        s = _v(scores)
        order = jnp.argsort(-s)
    else:
        order = jnp.arange(n)
    sorted_boxes = b[order]
    if category_idxs is not None:
        # class-aware: offset boxes per category so cross-class boxes never overlap
        cat = _v(category_idxs)[order]
        span = jnp.max(b[:, 2:]) + 1.0
        sorted_boxes = sorted_boxes + (cat.astype(sorted_boxes.dtype) * span)[:, None] * jnp.ones(
            (1, 4), sorted_boxes.dtype
        )
    iou = _iou_matrix(sorted_boxes, sorted_boxes)

    def body(i, keep):
        # suppress i if any kept higher-score box overlaps it too much
        sup = jnp.any(jnp.where(jnp.arange(n) < i, (iou[i] > iou_threshold) & keep, False))
        return keep.at[i].set(~sup)

    keep = jax.lax.fori_loop(0, n, body, jnp.ones(n, bool))
    kept_sorted = jnp.nonzero(keep, size=n, fill_value=-1)[0]
    kept = jnp.where(kept_sorted >= 0, order[jnp.clip(kept_sorted, 0)], -1)
    kept_np = np.asarray(kept)
    kept_np = kept_np[kept_np >= 0]
    if top_k is not None:
        kept_np = kept_np[:top_k]
    return Tensor(jnp.asarray(kept_np, jnp.int64))


# ---------------------------------------------------------------------------
# roi align / pool
# ---------------------------------------------------------------------------

def roi_align(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0, sampling_ratio=-1, aligned=True, name=None):
    """Bilinear-sampled RoIAlign. x: [N,C,H,W]; boxes: [R,4] (x1,y1,x2,y2);
    boxes_num: [N] rois per image."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    ratio = 2 if sampling_ratio <= 0 else sampling_ratio

    bn = _v(boxes_num) if boxes_num is not None else None

    def fn(xv, bv):
        n, c, h, w = xv.shape
        r = bv.shape[0]
        if bn is not None:
            img_idx = jnp.repeat(jnp.arange(n), np.asarray(bn), total_repeat_length=r)
        else:
            img_idx = jnp.zeros((r,), jnp.int32)
        offset = 0.5 if aligned else 0.0
        x1 = bv[:, 0] * spatial_scale - offset
        y1 = bv[:, 1] * spatial_scale - offset
        x2 = bv[:, 2] * spatial_scale - offset
        y2 = bv[:, 3] * spatial_scale - offset
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        # sample grid: [R, ph, ratio] y coords, [R, pw, ratio] x coords
        iy = (jnp.arange(ratio) + 0.5) / ratio
        gy = y1[:, None, None] + (jnp.arange(ph)[None, :, None] + iy[None, None, :]) * bin_h[:, None, None]
        gx = x1[:, None, None] + (jnp.arange(pw)[None, :, None] + iy[None, None, :]) * bin_w[:, None, None]

        def bilinear(img, yy, xx):
            # img: [C,H,W]; yy/xx: [...]: bilinear sample each channel
            y0 = jnp.clip(jnp.floor(yy), 0, h - 1).astype(jnp.int32)
            x0 = jnp.clip(jnp.floor(xx), 0, w - 1).astype(jnp.int32)
            y1i = jnp.clip(y0 + 1, 0, h - 1)
            x1i = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy - y0, 0, 1)
            wx = jnp.clip(xx - x0, 0, 1)
            valid = (yy >= -1) & (yy <= h) & (xx >= -1) & (xx <= w)
            ia = img[:, y0, x0]
            ib = img[:, y0, x1i]
            ic = img[:, y1i, x0]
            id_ = img[:, y1i, x1i]
            out = ia * (1 - wy) * (1 - wx) + ib * (1 - wy) * wx + ic * wy * (1 - wx) + id_ * wy * wx
            return out * valid.astype(out.dtype)

        def one_roi(ri):
            img = xv[img_idx[ri]]  # [C,H,W]
            yy = gy[ri]  # [ph, ratio]
            xx = gx[ri]  # [pw, ratio]
            # full sample grid [ph*ratio, pw*ratio]
            ys = yy.reshape(-1)
            xs = xx.reshape(-1)
            grid_y = jnp.broadcast_to(ys[:, None], (ys.shape[0], xs.shape[0]))
            grid_x = jnp.broadcast_to(xs[None, :], (ys.shape[0], xs.shape[0]))
            samples = bilinear(img, grid_y, grid_x)  # [C, ph*ratio, pw*ratio]
            samples = samples.reshape(c, ph, ratio, pw, ratio)
            return samples.mean((2, 4))  # [C, ph, pw]

        return jax.vmap(one_roi)(jnp.arange(r))

    return apply("roi_align", fn, x, boxes)


def roi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0, name=None):
    """Max-pool RoI (reference roi_pool): nearest bins, max within each."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bn = _v(boxes_num) if boxes_num is not None else None

    def fn(xv, bv):
        n, c, h, w = xv.shape
        r = bv.shape[0]
        if bn is not None:
            img_idx = jnp.repeat(jnp.arange(n), np.asarray(bn), total_repeat_length=r)
        else:
            img_idx = jnp.zeros((r,), jnp.int32)
        x1 = jnp.round(bv[:, 0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(bv[:, 1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.maximum(jnp.round(bv[:, 2] * spatial_scale).astype(jnp.int32), x1 + 1)
        y2 = jnp.maximum(jnp.round(bv[:, 3] * spatial_scale).astype(jnp.int32), y1 + 1)

        def one_roi(ri):
            img = xv[img_idx[ri]]
            # exact bin max via masked reduction over the full feature map
            # (static shapes; XLA fuses the where+max — the TPU-friendly form
            # of the reference's per-bin pixel loop)
            iy = jnp.arange(h, dtype=jnp.float32)
            ix = jnp.arange(w, dtype=jnp.float32)
            biny = jnp.floor((iy - y1[ri]) * ph / jnp.maximum(y2[ri] - y1[ri], 1))
            binx = jnp.floor((ix - x1[ri]) * pw / jnp.maximum(x2[ri] - x1[ri], 1))
            in_y = (iy >= y1[ri]) & (iy < y2[ri])
            in_x = (ix >= x1[ri]) & (ix < x2[ri])
            mask_y = (biny[:, None] == jnp.arange(ph)[None, :]) & in_y[:, None]  # [h, ph]
            mask_x = (binx[:, None] == jnp.arange(pw)[None, :]) & in_x[:, None]  # [w, pw]
            neg = jnp.asarray(-jnp.inf, img.dtype)
            tmp = jnp.max(
                jnp.where(mask_y.T[None, :, :, None], img[:, None, :, :], neg), axis=2
            )  # [c, ph, w]
            out = jnp.max(
                jnp.where(mask_x[None, None, :, :], tmp[:, :, :, None], neg), axis=2
            )  # [c, ph, pw]
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return jax.vmap(one_roi)(jnp.arange(r))

    return apply("roi_pool", fn, x, boxes)


# ---------------------------------------------------------------------------
# deformable conv
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1, deformable_groups=1, groups=1, mask=None, name=None):
    """Deformable conv v1/v2 (reference: vision/ops.py deform_conv2d) as
    bilinear gather + matmul — the canonical TPU formulation."""
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError("deform_conv2d: groups/deformable_groups > 1 not yet supported")

    def fn(xv, ov, wv, *rest):
        rest = list(rest)
        bv = rest.pop(0) if bias is not None else None
        mv = rest.pop(0) if mask is not None else None
        n, c, h, w = xv.shape
        oc, ic, kh, kw = wv.shape
        sh, sw = stride
        ph_, pw_ = padding
        dh, dw = dilation
        oh = (h + 2 * ph_ - dh * (kh - 1) - 1) // sh + 1
        ow = (w + 2 * pw_ - dw * (kw - 1) - 1) // sw + 1
        xp = jnp.pad(xv, ((0, 0), (0, 0), (ph_, ph_), (pw_, pw_)))
        hp, wp = h + 2 * ph_, w + 2 * pw_
        # base sampling positions [oh, ow, kh, kw]
        base_y = (jnp.arange(oh) * sh)[:, None, None, None] + (jnp.arange(kh) * dh)[None, None, :, None]
        base_x = (jnp.arange(ow) * sw)[None, :, None, None] + (jnp.arange(kw) * dw)[None, None, None, :]
        base_y = jnp.broadcast_to(base_y, (oh, ow, kh, kw)).astype(jnp.float32)
        base_x = jnp.broadcast_to(base_x, (oh, ow, kh, kw)).astype(jnp.float32)
        # offsets: [N, 2*kh*kw, oh, ow] (y0,x0,y1,x1,... per kernel point)
        off = ov.reshape(n, kh * kw, 2, oh, ow)
        off_y = jnp.moveaxis(off[:, :, 0], 1, -1).reshape(n, oh, ow, kh, kw)
        off_x = jnp.moveaxis(off[:, :, 1], 1, -1).reshape(n, oh, ow, kh, kw)
        sy = base_y[None] + off_y
        sx = base_x[None] + off_x

        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy = sy - y0
        wx = sx - x0

        def gather(img, yy, xx):
            yi = jnp.clip(yy, 0, hp - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, wp - 1).astype(jnp.int32)
            valid = (yy >= 0) & (yy <= hp - 1) & (xx >= 0) & (xx <= wp - 1)
            return img[:, yi, xi] * valid.astype(img.dtype)  # [C, ...]

        def one_image(img, yy0, xx0, wyy, wxx, m):
            a = gather(img, yy0, xx0)
            b = gather(img, yy0, xx0 + 1)
            cc = gather(img, yy0 + 1, xx0)
            d = gather(img, yy0 + 1, xx0 + 1)
            s = (
                a * (1 - wyy) * (1 - wxx)
                + b * (1 - wyy) * wxx
                + cc * wyy * (1 - wxx)
                + d * wyy * wxx
            )  # [C, oh, ow, kh, kw]
            if m is not None:
                s = s * m[None]
            # contract (C,kh,kw) against weight
            return jnp.einsum("cyxhw,ochw->oyx", s, wv)

        if mv is not None:
            mm = jnp.moveaxis(mv.reshape(n, kh * kw, oh, ow), 1, -1).reshape(n, oh, ow, kh, kw)
        else:
            mm = None
        out = jax.vmap(lambda im, a1, a2, a3, a4, m5: one_image(im, a1, a2, a3, a4, m5))(
            xp, y0, x0, wy, wx, mm if mm is not None else jnp.ones((n, oh, ow, kh, kw), xv.dtype)
        )
        if bv is not None:
            out = out + bv[None, :, None, None]
        return out

    args = [x, offset, weight] + ([bias] if bias is not None else []) + ([mask] if mask is not None else [])
    return apply("deform_conv2d", fn, *args)


# ---------------------------------------------------------------------------
# fpn
# ---------------------------------------------------------------------------

def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level, refer_scale, pixel_offset=False, rois_num=None, name=None):
    """Assign each RoI to an FPN level by scale (reference fpn.py). Returns
    (multi_rois, restore_ind, rois_num_per_level)."""
    rois = _v(fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    scale = jnp.sqrt(jnp.clip((rois[:, 2] - rois[:, 0] + off) * (rois[:, 3] - rois[:, 1] + off), 1e-6))
    level = jnp.floor(jnp.log2(scale / refer_scale + 1e-8)) + refer_level
    level = jnp.clip(level, min_level, max_level).astype(jnp.int32)
    level_np = np.asarray(level)
    rois_np = np.asarray(rois)
    multi_rois, rois_num_per_level, order = [], [], []
    for lv in range(min_level, max_level + 1):
        idx = np.nonzero(level_np == lv)[0]
        multi_rois.append(Tensor(jnp.asarray(rois_np[idx])))
        rois_num_per_level.append(Tensor(jnp.asarray([len(idx)], jnp.int32)))
        order.append(idx)
    order = np.concatenate(order) if order else np.zeros(0, np.int64)
    restore = np.argsort(order)
    return multi_rois, Tensor(jnp.asarray(restore, jnp.int32)), rois_num_per_level
