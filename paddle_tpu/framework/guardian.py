"""Training guardian: state-failure guards for the training loop.

Reference parity: paddle/fluid/framework/details/nan_inf_utils* +
python/paddle/amp/debugging.py (TensorChecker) cover the anomaly-DETECTION
half; the reference leaves recovery to the user. PR 2 hardened the
process/IO failure paths (retries, atomic checkpoints, watchdog); this
module guards the STATE failure paths on top of them:

1. **Numerical anomaly guard** — a jittable fused reduction over
   loss/grads/params (finiteness + an optional abs-magnitude ceiling,
   `FLAGS_guardian_abs_ceiling`) that costs ONE device->host scalar sync per
   step, gated by `FLAGS_check_nan_inf`. The verdict drives a policy knob
   (`FLAGS_guardian_policy` / per-guardian override): `raise` dumps the
   flight recorder and raises, `skip_step` drops the update (counted into
   GradScaler's dynamic-loss-scale bookkeeping via
   `GradScaler.record_external_skip`), `rollback` restores the newest
   last-known-good snapshot. Skipped/rolled-back steps never invoke
   `optimizer.step()`, so the fused-optimizer donated buckets are never
   consumed by a step that is then discarded.

2. **Last-known-good snapshots** — a ring (`FLAGS_lkg_ring`) of cheap
   on-device copies of params + optimizer state, taken every
   `FLAGS_lkg_interval` clean steps. Fused-bucket aware: the snapshot
   covers the FLAT bucket tensors (via `Optimizer._fused_state_entries`),
   not per-tensor views, and copies are real device buffers so a later
   to_static donation can't invalidate them. `rollback()` restores every
   covered tensor bit-identically, resets state born after the snapshot to
   its creation fill (GradScaler-skip semantics), restores the generator
   key, and folds the rollback count into it so the retried steps draw
   fresh-but-deterministic dropout instead of replaying the diverged path.

3. **Cross-rank desync detector** — a periodic all-reduce (MIN and MAX) of
   a per-rank digest vector: one position-sensitive checksum per param and
   per optimizer state bucket, plus the RNG state and step counter.
   Columns where MIN != MAX name exactly WHICH unit diverged; majority
   vote over the gathered matrix names WHICH rank. Detection records the
   (bucket, rank) pair in the flight recorder, dumps it, and aborts through
   the comm-watchdog escalation ladder (so custom timeout/abort handlers
   and the faulthandler stack dump all apply). FaultPlan site
   `guardian.bucket_bitflip` flips one bit in a simulated rank's bucket
   before digesting — the SDC drill.

4. **Flight recorder** — a bounded ring of per-step records (loss,
   grad-norm, lr, skip/rollback/anomaly events, per-op collective latency
   deltas from the telemetry registry) dumped as JSON to a crash dir next
   to the checkpoint (`note_checkpoint_dir`) by any guardian abort and by
   the PR 2 watchdog escalation (`comm_watchdog._default_handler`).

FaultPlan chaos sites: `guardian.grad_nan` (poison one gradient value with
NaN inside `TrainingGuardian.step`, before the check) and
`guardian.bucket_bitflip` (see above).
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax import numpy as jnp

from . import flags as _flags
from . import random as random_mod

POLICIES = ("raise", "skip_step", "rollback")

# anomaly bitmask returned by the fused check
ANOMALY_NONFINITE = 1
ANOMALY_MAGNITUDE = 2


class GuardianAnomaly(FloatingPointError):
    """Raised by the `raise` policy (and the compiled-state hooks) after the
    flight recorder has been dumped."""

    def __init__(self, msg: str, kind: str = "nonfinite", dump_paths=()):
        super().__init__(msg)
        self.kind = kind
        self.dump_paths = list(dump_paths)


# ---------------------------------------------------------------------------
# fused numerics check
# ---------------------------------------------------------------------------


@jax.jit
def _check_impl(grad_vals, other_vals, ceiling):
    """ONE fused reduction over every array: (anomaly bitmask, grad norm).

    Everything reduces on-device to two scalars, so the host pays a single
    tiny transfer per guarded step regardless of model size.
    """
    nonfinite = jnp.zeros((), jnp.bool_)
    over = jnp.zeros((), jnp.bool_)
    gn_sq = jnp.zeros((), jnp.float32)
    use_ceiling = ceiling > 0.0
    for v in grad_vals:
        vf = v.astype(jnp.float32)
        nonfinite = nonfinite | ~jnp.all(jnp.isfinite(vf))
        over = over | (use_ceiling & jnp.any(jnp.abs(vf) > ceiling))
        gn_sq = gn_sq + jnp.sum(jnp.square(vf))
    for v in other_vals:
        vf = v.astype(jnp.float32)
        nonfinite = nonfinite | ~jnp.all(jnp.isfinite(vf))
        over = over | (use_ceiling & jnp.any(jnp.abs(vf) > ceiling))
    flags = nonfinite.astype(jnp.int32) * ANOMALY_NONFINITE
    flags = flags + over.astype(jnp.int32) * ANOMALY_MAGNITUDE
    return flags, jnp.sqrt(gn_sq)


def _floating(values):
    return [v for v in values if jnp.issubdtype(jnp.result_type(v), jnp.floating)]


def check_arrays(grad_vals, other_vals=(), ceiling: float = 0.0):
    """Run the fused numerics check over raw arrays.

    Returns `(mask, grad_norm)` as host scalars: `mask` is a bitwise OR of
    ANOMALY_NONFINITE / ANOMALY_MAGNITUDE (0 = clean) and `grad_norm` the
    global L2 norm over `grad_vals`. Non-floating arrays are skipped (an
    integer step counter cannot go NaN).
    """
    gs = _floating(grad_vals)
    os_ = _floating(other_vals)
    if not gs and not os_:
        return 0, 0.0
    flags, gn = _check_impl(gs, os_, jnp.asarray(float(ceiling), jnp.float32))
    flags, gn = jax.device_get((flags, gn))
    return int(flags), float(gn)


def _anomaly_kind(mask: int) -> str:
    kinds = []
    if mask & ANOMALY_NONFINITE:
        kinds.append("nonfinite")
    if mask & ANOMALY_MAGNITUDE:
        kinds.append("magnitude")
    return "+".join(kinds) or "clean"


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

_recorders: "weakref.WeakSet" = weakref.WeakSet()
_noted_ckpt_dir: List[Optional[str]] = [None]


def note_checkpoint_dir(path: str) -> None:
    """Remember the latest checkpoint root so crash dumps land NEXT TO the
    checkpoint by default (called by distributed.checkpoint.save_state_dict)."""
    _noted_ckpt_dir[0] = os.path.join(str(path), "crash")


def default_crash_dir() -> str:
    env = os.environ.get("PADDLE_TPU_CRASH_DIR")
    if env:
        return env
    if _noted_ckpt_dir[0]:
        return _noted_ckpt_dir[0]
    return os.path.join(os.getcwd(), "paddle_tpu_crash")


class FlightRecorder:
    """Bounded ring of per-step records + events, dumped as JSON on crash.

    Records are plain dicts (already JSON-clean floats/ints/strings); the
    ring length follows `FLAGS_flight_recorder_len` unless overridden.
    """

    def __init__(self, capacity: Optional[int] = None, name: str = "train",
                 crash_dir: Optional[str] = None):
        if capacity is None:
            capacity = int(_flags.get_flag("FLAGS_flight_recorder_len"))
        self.name = name
        self.crash_dir = crash_dir
        self._ring: deque = deque(maxlen=max(int(capacity), 1))
        self._lock = threading.Lock()
        self._seq = 0
        _recorders.add(self)

    def record(self, kind: str, **fields) -> None:
        rec = {"t": time.time(), "kind": kind}
        rec.update(fields)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)

    def record_step(self, step: int, **fields) -> None:
        self.record("step", step=int(step), **fields)

    def record_event(self, event: str, **fields) -> None:
        self.record("event", event=event, **fields)

    def records(self) -> list:
        with self._lock:
            return list(self._ring)

    def dump(self, reason: str = "", crash_dir: Optional[str] = None) -> str:
        """Write the ring as one JSON file; returns the path."""
        d = crash_dir or self.crash_dir or default_crash_dir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"flight_{self.name}_{os.getpid()}_{int(time.time() * 1000)}.json"
        )
        payload = {
            "version": 1,
            "reason": reason,
            "dumped_at": time.time(),
            "name": self.name,
            "pid": os.getpid(),
            "records": self.records(),
        }
        # "was this an OOM-adjacent step": the HBM high-water mark + the
        # newest compiled-program attribution ride every crash dump
        try:
            from ..profiler import perf_attribution as _pa

            payload["peak_hbm_bytes"] = _pa.watermark().get("peak_hbm_bytes")
            payload["perf_report"] = _pa.snapshot_for_crash()
        except Exception:
            pass  # attribution must never mask the dump
        # the full metric registry rides too — LENIENT mode: the dump must
        # survive the very NaN gauge it exists to report (invalid samples
        # are skipped-and-counted with a marker line; CI snapshots stay
        # strict through the default to_json_lines)
        try:
            from .. import telemetry as _tm

            if _tm.enabled():
                payload["telemetry"] = _tm.to_json_lines(strict=False).splitlines()
        except Exception:
            pass
        # the incident-timeline tail rides every crash dump: the triage CLI
        # reads it back with `report --crash-dump`. Same lenient discipline —
        # tail() json-sanitizes so the dump survives a NaN payload field.
        try:
            from ..telemetry import timeline as _tl

            if _tl.enabled():
                payload["timeline"] = _tl.tail(256)
                payload["timeline_dropped"] = _tl.dropped()
        except Exception:
            pass
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, default=str)
            f.write("\n")
        return path


def dump_flight_recorders(reason: str = "", crash_dir: Optional[str] = None) -> list:
    """Dump every live flight recorder (called by the comm-watchdog
    escalation ladder and by guardian aborts); returns the written paths."""
    paths = []
    for rec in list(_recorders):
        try:
            paths.append(rec.dump(reason=reason, crash_dir=crash_dir))
        except Exception:
            pass  # a failing dump must never mask the abort path
    return paths


def _collective_latency_totals() -> dict:
    """op -> (count, sum) cumulative totals from the telemetry registry."""
    from .. import telemetry as _tm

    if not _tm.enabled():
        return {}
    fam = _tm.default_registry().get("paddle_tpu_collective_latency_seconds")
    if fam is None:
        return {}
    totals: dict = {}
    for child in fam.children():
        op = dict(child.labels).get("op", "?")
        c, s = totals.get(op, (0, 0.0))
        totals[op] = (c + child.count, s + child.sum)
    return totals


# ---------------------------------------------------------------------------
# digests (cross-rank desync)
# ---------------------------------------------------------------------------


@jax.jit
def _digest_impl(vals):
    """One order/position-sensitive uint32 checksum per array, computed
    on-device: bitcast to integer lanes, mix with a position hash, wraparound
    sum. A single flipped bit anywhere changes the digest."""
    outs = []
    for v in vals:
        dt = v.dtype
        flat = v.reshape(-1)
        if dt == jnp.bfloat16 or dt == jnp.float16:
            u = jax.lax.bitcast_convert_type(flat, jnp.uint16).astype(jnp.uint32)
        elif dt == jnp.float32:
            u = jax.lax.bitcast_convert_type(flat, jnp.uint32)
        elif dt == jnp.float64:
            u64 = jax.lax.bitcast_convert_type(flat, jnp.uint64)
            u = (u64 ^ jax.lax.shift_right_logical(u64, np.uint64(32))).astype(jnp.uint32)
        else:
            u = flat.astype(jnp.uint32)
        idx = jax.lax.iota(jnp.uint32, u.size)
        mixed = u ^ (idx * np.uint32(0x9E3779B1) + np.uint32(1))
        outs.append(jnp.sum(mixed, dtype=jnp.uint32))
    return jnp.stack(outs) if outs else jnp.zeros((0,), jnp.uint32)


def digest_arrays(arrays) -> np.ndarray:
    """Host uint32 digest vector, one entry per array."""
    if not arrays:
        return np.zeros((0,), np.uint32)
    return np.asarray(jax.device_get(_digest_impl(list(arrays))), np.uint32)


def _flip_one_bit(arr, seed: int, salt: int):
    """Deterministically flip one bit of `arr` (host-side; chaos-drill only)."""
    import random as _random

    a = np.array(np.asarray(arr))  # writable host copy
    buf = a.view(np.uint8).reshape(-1)
    rng = _random.Random(f"{seed}:bitflip:{salt}")
    byte = rng.randrange(buf.size)
    bit = rng.randrange(8)
    buf[byte] ^= np.uint8(1 << bit)
    return jnp.asarray(a)


class DesyncDetector:
    """Periodic cross-rank digest comparison over a collective group.

    Single-controller SPMD note: every rank is a slice of one program, so a
    REAL divergence means silent data corruption (bit flip in HBM, a
    miscompiled replica, host memory rot). The detector rides the stacked
    collective convention: a [nranks, D] digest matrix all-reduced with MIN
    and MAX; any column where they differ names the diverged unit, and the
    majority vote over rows names the rank.
    """

    def __init__(self, optimizer, group=None, recorder: Optional[FlightRecorder] = None):
        self.optimizer = optimizer
        self.group = group
        self.recorder = recorder

    def digest_units(self) -> List[Tuple[str, object]]:
        """[(unit name, raw array)] — params + bucket-aware optimizer state."""
        opt = self.optimizer
        units: List[Tuple[str, object]] = []
        pid2idx = {}
        for i, (_, p) in enumerate(opt._all_params()):
            pid2idx[id(p)] = i
            units.append((p.name or f"param:{i}", p._raw()))
        for name, store in sorted(opt._accumulators.items()):
            for pid, t in store.items():
                units.append((f"accum:{name}:{pid2idx.get(pid, '?')}", t._raw()))
        for bi, st in enumerate(getattr(opt, "_fused_buckets", {}).values()):
            for gi, grp in enumerate(st["groups"]):
                for nm, t in grp["flat"].items():
                    units.append((f"stacked_bucket:{bi}.{gi}:{nm}", t._raw()))
        eng = getattr(opt, "_flat_engine", None)
        if eng is not None:
            units.extend(eng.digest_units())
        return units

    def local_digest(self) -> Tuple[List[str], np.ndarray]:
        units = self.digest_units()
        names = [n for n, _ in units]
        vec = digest_arrays([a for _, a in units])
        # RNG state + step counter ride the digest so seed drift / step skew
        # is caught even when params still agree
        rng_state = np.asarray(random_mod.get_rng_state()).view(np.uint32)
        names.append("rng_state")
        extra = [np.uint32(np.bitwise_xor.reduce(rng_state.reshape(-1)))]
        names.append("step_count")
        extra.append(np.uint32(int(self.optimizer._step_count._raw()) & 0xFFFFFFFF))
        return names, np.concatenate([vec, np.asarray(extra, np.uint32)])

    def check(self, escalate: bool = True) -> Optional[dict]:
        """Run one desync check. Returns None when all ranks agree; else a
        report dict {unit, ranks, units} — after recording it in the flight
        recorder, dumping, and (escalate=True) aborting through the
        comm-watchdog ladder."""
        from .. import telemetry as _tm
        from ..distributed.resilience import fault_injection as _fi

        names, vec = self.local_digest()
        group = self.group
        n = getattr(group, "nranks", 1) if group is not None else 1
        if _tm.enabled():
            _tm.counter(
                "paddle_tpu_guardian_desync_checks_total",
                "cross-rank desync digest comparisons",
            ).inc()
        if n <= 1:
            return None

        mat = np.tile(vec, (n, 1))
        spec = _fi.corrupt_value("guardian.bucket_bitflip")
        if spec is not None:
            # SDC drill: recompute ONE rank's digest over a bit-flipped copy
            # of a bucket (prefer a real bucket unit; else the first unit)
            rank = int(spec.arg) % n
            units = self.digest_units()
            j = next(
                (i for i, (nm, _) in enumerate(units) if "bucket" in nm), 0
            )
            plan = _fi.current_plan()
            flipped = _flip_one_bit(
                units[j][1], plan.seed if plan else 0, spec.fired
            )
            mat[rank, j] = digest_arrays([flipped])[0]

        from ..core.tensor import Tensor
        from ..distributed import collective as _coll

        lo = Tensor(jnp.asarray(mat.astype(np.int64)))
        hi = Tensor(jnp.asarray(mat.astype(np.int64)))
        _coll.all_reduce(lo, op=_coll.ReduceOp.MIN, group=group)
        _coll.all_reduce(hi, op=_coll.ReduceOp.MAX, group=group)
        lo_v = np.asarray(lo._raw())[0]
        hi_v = np.asarray(hi._raw())[0]
        diverged_cols = np.nonzero(lo_v != hi_v)[0]
        if diverged_cols.size == 0:
            return None

        # attribution needs every rank's actual row, not the local tile —
        # gather them (rare path: only after the cheap MIN/MAX detected a
        # mismatch) and majority-vote per diverged column
        gathered_rows: list = []
        _coll.all_gather(
            gathered_rows, Tensor(jnp.asarray(mat.astype(np.int64))), group=group
        )
        gathered = np.stack([np.asarray(t._raw()) for t in gathered_rows])

        report_units = []
        for j in diverged_cols:
            col = gathered[:, int(j)]
            vals, counts = np.unique(col, return_counts=True)
            maxc = counts.max()
            modal = vals[counts == maxc]
            if len(modal) == 1:
                bad = np.nonzero(col != modal[0])[0]
            else:
                # modal tie (e.g. a 2-rank group): majority cannot name the
                # culprit — implicate every rank rather than coin-flip blame
                bad = np.arange(n)
            if bad.size == 0:
                # defensive: detection said the column diverged; never tell
                # the operator "diverged on no rank"
                bad = np.arange(n)
            report_units.append(
                {"unit": names[int(j)], "ranks": [int(r) for r in bad]}
            )
        report = {
            "unit": report_units[0]["unit"],
            "ranks": report_units[0]["ranks"],
            "units": report_units,
            "step": int(self.optimizer._step_count._raw()),
        }
        if _tm.enabled():
            for u in report_units:
                for r in u["ranks"]:
                    _tm.counter(
                        "paddle_tpu_guardian_desync_detected_total",
                        "diverged (unit, rank) pairs caught by the desync digest",
                        ("unit", "rank"),
                    ).labels(unit=u["unit"], rank=str(r)).inc()
        if self.recorder is not None:
            self.recorder.record_event("desync", **report)
        from ..telemetry import timeline as _tl

        # the site label ties an injected bucket_bitflip drill to the
        # desync it must produce (chaos-coverage match key)
        _tl.emit("guardian", "desync", severity="fatal",
                 labels={"site": "guardian.bucket_bitflip"},
                 unit=report["unit"], ranks=list(report["ranks"]),
                 step=report["step"])
        paths = dump_flight_recorders(reason="desync")
        if escalate:
            self._escalate(report, paths)
        return report

    def _escalate(self, report: dict, dump_paths) -> None:
        """Abort through the watchdog ladder: custom timeout/abort handlers,
        faulthandler stack dump, and telemetry flush all apply."""
        from ..distributed.comm_watchdog import CommTask, CommTaskManager

        task = CommTask(
            tid=-1,
            op="guardian.desync",
            info={
                "unit": report["unit"],
                "ranks": report["ranks"],
                "step": report["step"],
                "flight_recorder": list(dump_paths),
            },
            timeout=0.0,
        )
        dump = "\n".join(
            f"desync unit={u['unit']} ranks={u['ranks']}" for u in report["units"]
        )
        CommTaskManager.instance()._handler(task, dump)


# ---------------------------------------------------------------------------
# training guardian
# ---------------------------------------------------------------------------


class TrainingGuardian:
    """Wraps the optimizer step with the anomaly guard, the last-known-good
    ring, the desync detector, and the flight recorder.

    Usage (drop-in for `optimizer.step()` / `scaler.step(optimizer)`)::

        guardian = TrainingGuardian(opt, scaler=scaler, policy="rollback")
        for batch in loader:
            loss = model(batch)
            (scaler.scale(loss) if scaler else loss).backward()
            verdict = guardian.step(loss)   # 'ok' | 'skipped' | 'rolled_back'
            opt.clear_grad()

    The numerics check only runs when FLAGS_check_nan_inf is on; with it off
    the guardian still keeps the flight recorder and LKG ring warm. Under a
    jax trace (to_static replay) the host-sync policies cannot run — the
    guardian degrades to a plain step and the compiled-state hooks in
    jit/api.py + static/executor.py take over detection (those hooks are
    global: they honor FLAGS_guardian_abs_ceiling, not a per-instance
    `ceiling=` override — see check_compiled_state).
    """

    def __init__(self, optimizer, scaler=None, policy: Optional[str] = None,
                 ceiling: Optional[float] = None, lkg_interval: Optional[int] = None,
                 lkg_ring: Optional[int] = None, desync_interval: Optional[int] = None,
                 group=None, crash_dir: Optional[str] = None,
                 recorder: Optional[FlightRecorder] = None, name: str = "train",
                 grad_reducer=None):
        if policy is not None and policy not in POLICIES:
            raise ValueError(f"guardian policy must be one of {POLICIES}, got {policy!r}")
        self.optimizer = optimizer
        self.scaler = scaler
        # async bucketed DP reduction (distributed.grad_reducer): flushed
        # before grads are read so the anomaly check / grad-norm sees the
        # REDUCED gradients, never a half-synced bucket
        self.grad_reducer = grad_reducer
        self._policy = policy
        self._ceiling = ceiling
        self._lkg_interval = lkg_interval
        self._desync_interval = desync_interval
        ring = lkg_ring if lkg_ring is not None else int(_flags.get_flag("FLAGS_lkg_ring"))
        self._snapshots: deque = deque(maxlen=max(int(ring), 1))
        self.recorder = recorder or FlightRecorder(name=name, crash_dir=crash_dir)
        if crash_dir is not None:
            self.recorder.crash_dir = crash_dir
        self.detector = DesyncDetector(optimizer, group=group, recorder=self.recorder)
        self.steps_total = 0
        self.skipped_steps = 0
        self.rollbacks = 0
        self._rollback_count = 0
        self._warned_tracing = False
        self._coll_totals = _collective_latency_totals()

    # ---- config (flag-backed, overridable per instance) ----
    @property
    def policy(self) -> str:
        p = self._policy or str(_flags.get_flag("FLAGS_guardian_policy"))
        if p not in POLICIES:
            raise ValueError(f"FLAGS_guardian_policy must be one of {POLICIES}, got {p!r}")
        return p

    @property
    def ceiling(self) -> float:
        if self._ceiling is not None:
            return float(self._ceiling)
        return float(_flags.get_flag("FLAGS_guardian_abs_ceiling"))

    @property
    def lkg_interval(self) -> int:
        if self._lkg_interval is not None:
            return int(self._lkg_interval)
        return int(_flags.get_flag("FLAGS_lkg_interval"))

    @property
    def desync_interval(self) -> int:
        if self._desync_interval is not None:
            return int(self._desync_interval)
        return int(_flags.get_flag("FLAGS_desync_interval"))

    # ---- the guarded step ----
    def step(self, loss=None) -> str:
        opt = self.optimizer
        self.steps_total += 1
        if self.grad_reducer is not None:
            # check ordering: backward (+ async bucket reduces) → flush →
            # unscale → check → step. Straggler buckets dispatch here; the
            # grads read below are the fully reduced ones.
            self.grad_reducer.flush()
        grads = [p.grad for _, p in opt._all_params() if p.grad is not None]
        if self._tracing(loss, grads):
            # inside a jax trace the one-scalar sync is impossible; the
            # compiled-state hooks catch anomalies after the replay instead
            if not self._warned_tracing:
                self._warned_tracing = True
                import warnings

                warnings.warn(
                    "TrainingGuardian.step is running under a jax trace "
                    "(to_static replay): anomaly policies need a host sync "
                    "and are disabled inside the compiled step; post-run "
                    "compiled-state checks still apply", stacklevel=2,
                )
            self._plain_step()
            return "ok"
        scaler_on = self.scaler is not None and self.scaler.is_enable()
        if scaler_on:
            # unscale first so the check (and any skip decision) sees the
            # true gradients; scaler.step won't re-unscale (id bookkeeping)
            self.scaler.unscale_(opt)
        self._maybe_inject_grad_nan(grads)
        loss_raw = self._loss_raw(loss)
        verdict = "ok"
        mask, grad_norm = 0, None
        if _flags.get_flag("FLAGS_check_nan_inf"):
            t0 = time.perf_counter()
            mask, grad_norm = self._check(loss_raw, grads)
            self._observe_check(time.perf_counter() - t0)
        if mask:
            return self._handle_anomaly(mask, loss_raw, grad_norm)
        self._plain_step()
        self._after_clean_step(loss_raw, grad_norm)
        return verdict

    def _plain_step(self):
        if self.scaler is not None and self.scaler.is_enable():
            self.scaler.step(self.optimizer)
        else:
            self.optimizer.step()

    def _tracing(self, loss, grads) -> bool:
        probes = [loss] + grads
        for t in probes:
            if t is not None and isinstance(getattr(t, "_value", None), jax.core.Tracer):
                return True
        return False

    def _loss_raw(self, loss):
        """Raw UNSCALED loss value. The caller backward()s through the
        GradScaler-scaled loss, but the grads above were unscaled — the
        check (magnitude ceiling!) and the flight recorder must see the same
        de-scaled world, or a 2^15 scale turns every healthy loss into a
        'magnitude' anomaly and corrupts the recorded loss curve."""
        if loss is None or not hasattr(loss, "_raw"):
            return None
        v = loss._raw()
        if self.scaler is not None and self.scaler.is_enable():
            v = v / self.scaler._scale._raw().astype(v.dtype)
        return v

    def _check(self, loss_raw, grads):
        grad_vals = [g._raw() for g in grads]
        other = [p._raw() for _, p in self.optimizer._all_params()]
        if loss_raw is not None:
            other.append(loss_raw)
        return check_arrays(grad_vals, other, self.ceiling)

    def _observe_check(self, dt):
        from .. import telemetry as _tm

        if _tm.enabled():
            _tm.histogram(
                "paddle_tpu_guardian_check_seconds",
                "host wall time of the fused numerics check (incl. the one "
                "scalar sync)",
            ).observe(dt)

    def _maybe_inject_grad_nan(self, grads):
        from ..distributed.resilience import fault_injection as _fi

        spec = _fi.corrupt_value("guardian.grad_nan")
        if spec is None or not grads:
            return
        # remembered until the anomaly check fires, so the resulting
        # anomaly event carries the injection's site label (chaos coverage)
        self._injected_site = "guardian.grad_nan"
        g = grads[0]
        v = g._raw()
        flat = v.reshape(-1).astype(v.dtype)
        poisoned = flat.at[0].set(jnp.nan).reshape(v.shape)
        g._replace_value(poisoned)

    # ---- anomaly handling ----
    def _handle_anomaly(self, mask: int, loss_raw, grad_norm) -> str:
        from .. import telemetry as _tm

        kind = _anomaly_kind(mask)
        policy = self.policy
        step = int(self.optimizer._step_count._raw())
        # anomaly-time HBM probe: OOM-adjacency is exactly what the crash
        # dump needs to answer; no-op when telemetry is off
        try:
            from ..profiler import perf_attribution as _pa

            wm = _pa.sample_watermark(tag=f"anomaly:{kind}", force=True)
        except Exception:
            wm = None
        if self.scaler is not None:
            # the skipped step never reaches scaler.step, which is what
            # normally clears the per-step unscale bookkeeping — clear it
            # here or the NEXT step's grads would silently stay scaled
            self.scaler._unscaled.discard(id(self.optimizer))
        if _tm.enabled():
            _tm.counter(
                "paddle_tpu_guardian_anomalies_total",
                "numerical anomalies caught by the guardian", ("kind", "policy"),
            ).labels(kind=kind, policy=policy).inc()
        self.recorder.record_event(
            "anomaly", anomaly=kind, policy=policy, step=step,
            loss=_loss_float(loss_raw), grad_norm=grad_norm,
            peak_hbm_bytes=(wm or {}).get("peak_hbm_bytes"),
            # anomalous steps consume their wait window too — a starved
            # step that also went NaN should say so in the crash dump
            input_wait_s=_input_wait_delta(),
        )
        try:
            from ..telemetry import timeline as _tl

            inj_site = getattr(self, "_injected_site", None)
            self._injected_site = None
            _tl.emit("guardian", "anomaly",
                     severity="fatal" if policy == "raise" else "error",
                     labels={"site": inj_site} if inj_site else None,
                     anomaly=kind, policy=policy, step=step,
                     loss=_loss_float(loss_raw), grad_norm=grad_norm)
        except Exception:
            pass
        if policy == "skip_step":
            self.skipped_steps += 1
            if _tm.enabled():
                _tm.counter(
                    "paddle_tpu_guardian_steps_skipped_total",
                    "optimizer steps dropped by the skip_step policy",
                ).inc()
            if self.scaler is not None and self.scaler.is_enable():
                self.scaler.record_external_skip()
            return "skipped"
        if policy == "rollback":
            if not self._snapshots:
                # nothing to restore yet — degrade to skip (recorded as such)
                self.recorder.record_event("rollback_unavailable", step=step)
                _tl.emit("guardian", "rollback_unavailable", severity="warn",
                         step=step)
                self.skipped_steps += 1
                if self.scaler is not None and self.scaler.is_enable():
                    self.scaler.record_external_skip()
                return "skipped"
            self.rollback()
            return "rolled_back"
        paths = dump_flight_recorders(reason=f"anomaly:{kind}")
        raise GuardianAnomaly(
            f"training guardian: {kind} anomaly at step {step} "
            f"(policy=raise; flight recorder: {paths})",
            kind=kind, dump_paths=paths,
        )

    # ---- last-known-good ring ----
    def _state_entries(self):
        """[(tensor, fill-or-None)] — every mutable piece of train state:
        params (fill None: they always predate the guardian), optimizer
        accumulators, fused flat/stacked bucket tensors, the step counter,
        and GradScaler bookkeeping."""
        opt = self.optimizer
        out = [(p, None) for _, p in opt._all_params()]
        for name, store in opt._accumulators.items():
            fill = opt._accumulator_fills.get(name, 0.0)
            out.extend((t, fill) for t in store.values())
        out.extend(getattr(opt, "_fused_state_entries", lambda: [])())
        out.append((opt._step_count, None))
        if self.scaler is not None and self.scaler.is_enable():
            out.extend((t, None) for t in self.scaler.state_dict().values())
        return out

    def snapshot(self) -> None:
        """Take one last-known-good on-device snapshot (fused-bucket aware)."""
        from .. import telemetry as _tm

        opt = self.optimizer
        getattr(opt, "_materialize_state", lambda: None)()
        entries = [
            (t, jnp.array(t._raw(), copy=True)) for t, _ in self._state_entries()
        ]
        self._snapshots.append({
            "step": int(opt._step_count._raw()),
            "entries": entries,
            "rng": np.array(random_mod.get_rng_state(), copy=True),
            "wall": time.time(),
        })
        if _tm.enabled():
            _tm.counter(
                "paddle_tpu_guardian_snapshots_total",
                "last-known-good snapshots taken",
            ).inc()

    def rollback(self) -> int:
        """Restore the newest last-known-good snapshot bit-identically.

        State born AFTER the snapshot (lazily-created accumulators, rebuilt
        buckets) resets to its creation fill — the same semantics as
        GradScaler's branchless skip. The generator restores to the snapshot
        key with the rollback count folded in, so the retried steps draw
        deterministic but fresh dropout instead of replaying the diverged
        trajectory. Gradients are cleared: the anomalous grads must not be
        re-applied to the restored params.
        """
        from .. import telemetry as _tm
        from ..telemetry import timeline as _tl

        snap = self._snapshots[-1]
        covered = {id(t): v for t, v in snap["entries"]}
        for t, fill in self._state_entries():
            v = covered.get(id(t))
            if v is not None:
                t._replace_value(v)
            elif fill is not None:
                t._replace_value(jnp.full(t._raw().shape, fill, t._raw().dtype))
        self._rollback_count += 1
        self.rollbacks += 1
        gen = random_mod.default_generator()
        gen.set_state(snap["rng"])
        gen.fold_in(self._rollback_count)
        self.optimizer.clear_grad()
        self.recorder.record_event(
            "rollback", restored_step=snap["step"], rollback=self._rollback_count,
        )
        _tl.emit("guardian", "rollback", severity="warn",
                 restored_step=snap["step"], rollback=self._rollback_count)
        if _tm.enabled():
            _tm.counter(
                "paddle_tpu_guardian_rollbacks_total",
                "rollbacks to a last-known-good snapshot",
            ).inc()
        return snap["step"]

    @property
    def snapshots(self):
        return list(self._snapshots)

    # ---- post-step bookkeeping ----
    def _after_clean_step(self, loss_raw, grad_norm) -> None:
        opt = self.optimizer
        step = int(opt._step_count._raw())
        try:
            from ..profiler import perf_attribution as _pa

            wm = _pa.watermark()
        except Exception:
            wm = {}
        self.recorder.record_step(
            step,
            loss=_loss_float(loss_raw),
            grad_norm=grad_norm,
            lr=float(opt.get_lr()),
            collectives=self._collective_deltas(),
            peak_hbm_bytes=wm.get("peak_hbm_bytes"),
            input_wait_s=_input_wait_delta(),
        )
        interval = self.lkg_interval
        if interval > 0 and step % interval == 0:
            self.snapshot()
        dint = self.desync_interval
        if dint > 0 and step % dint == 0:
            self.check_desync()

    def _collective_deltas(self) -> dict:
        now = _collective_latency_totals()
        prev, self._coll_totals = self._coll_totals, now
        out = {}
        for op, (c, s) in now.items():
            pc, ps = prev.get(op, (0, 0.0))
            if c > pc:
                out[op] = {"calls": c - pc, "mean_s": (s - ps) / (c - pc)}
        return out

    def check_desync(self, escalate: bool = True):
        return self.detector.check(escalate=escalate)


def _input_wait_delta():
    """Per-step input-pipeline wait (`input_wait_s`): how long this step's
    data took to arrive, from the streaming tier's stats accumulator. None
    when no input pipeline has reported a wait (loader-less loops record
    nothing rather than a misleading 0.0). Consuming the delta here also
    closes one (wall, wait) sample of the starved-vs-slow window that
    perf_report()['input_pipeline'] judges."""
    try:
        from ..io.streaming import stats as _instats

        return _instats.take_step_wait()
    except Exception:
        return None


def _loss_float(loss):
    try:
        if loss is None:
            return None
        v = loss._raw() if hasattr(loss, "_raw") else loss
        if isinstance(v, jax.core.Tracer):
            return None
        return float(np.asarray(v).reshape(-1)[0])
    except Exception:
        return None


# ---------------------------------------------------------------------------
# compiled-state hooks (to_static replay / static Executor)
# ---------------------------------------------------------------------------


def check_compiled_state(tensors, origin: str) -> None:
    """Post-run numerics check over the CONCRETE state a compiled step wrote
    back (to_static replay, static Executor). Detection-only at this layer —
    a donated compiled step cannot be skipped after the fact — so an anomaly
    records into every flight recorder, dumps, and raises GuardianAnomaly;
    a caller holding a TrainingGuardian can then rollback() to the last
    known good snapshot (snapshots are real copies, donation-proof).

    This hook is global (it cannot know which guardian instance, if any,
    owns the step), so the magnitude ceiling comes from
    FLAGS_guardian_abs_ceiling alone — a per-instance
    TrainingGuardian(ceiling=...) override applies only to the eager path;
    set the flag too if the ceiling must hold inside compiled steps."""
    vals = []
    for t in tensors:
        v = getattr(t, "_value", t)
        if isinstance(v, jax.core.Tracer):
            return  # nested trace: nothing concrete to check
        deleted = getattr(v, "is_deleted", None)
        if deleted is not None and deleted():
            continue  # donated-away input buffer; its successor is checked
        vals.append(v)
    mask, _ = check_arrays([], vals, float(_flags.get_flag("FLAGS_guardian_abs_ceiling")))
    if not mask:
        return
    from .. import telemetry as _tm

    kind = _anomaly_kind(mask)
    try:
        from ..profiler import perf_attribution as _pa

        _pa.sample_watermark(tag=f"anomaly:{kind}", force=True)
    except Exception:
        pass
    if _tm.enabled():
        _tm.counter(
            "paddle_tpu_guardian_anomalies_total",
            "numerical anomalies caught by the guardian", ("kind", "policy"),
        ).labels(kind=kind, policy=f"compiled:{origin}").inc()
    for rec in list(_recorders):
        rec.record_event("compiled_state_anomaly", anomaly=kind, origin=origin)
    paths = dump_flight_recorders(reason=f"compiled_state:{origin}")
    raise GuardianAnomaly(
        f"training guardian: {kind} in state written back by {origin} "
        f"(flight recorder: {paths})", kind=kind, dump_paths=paths,
    )
