"""Metrics (python/paddle/metric/metrics.py: Metric, Accuracy, Precision,
Recall, Auc; paddle.metric.accuracy functional)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..core.apply import apply_nograd
from ..ops import search


def accuracy(input, label, k=1, correct=None, total=None):  # noqa: A002
    import jax.numpy as jnp

    def f(pred, lbl):
        topk = jnp.argsort(-pred, axis=-1)[..., :k]
        l = lbl.reshape(-1, 1)
        hit = jnp.any(topk == l, axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return apply_nograd("accuracy", f, input, label)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError


class Accuracy(Metric):
    def __init__(self, topk=(1,), name="acc"):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label):
        pred_np = pred.numpy() if isinstance(pred, Tensor) else np.asarray(pred)
        label_np = label.numpy() if isinstance(label, Tensor) else np.asarray(label)
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        maxk = max(self.topk)
        top = np.argsort(-pred_np, axis=-1)[..., :maxk]
        correct = top == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct):
        c = correct.numpy() if isinstance(correct, Tensor) else np.asarray(correct)
        n = c.shape[0] if c.ndim else 1
        for i, k in enumerate(self.topk):
            self.total[i] += c[..., :k].any(-1).sum()
            self.count[i] += n
        res = self.total / np.maximum(self.count, 1)
        return res[0] if len(self.topk) == 1 else res

    def accumulate(self):
        res = (self.total / np.maximum(self.count, 1)).tolist()
        return res[0] if len(self.topk) == 1 else res

    def name(self):
        return [f"{self._name}_top{k}" for k in self.topk] if len(self.topk) > 1 else [self._name]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds) > 0.5).astype(int).reshape(-1)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds) > 0.5).astype(int).reshape(-1)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels).reshape(-1)
        bins = np.round(p * self.num_thresholds).astype(int)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            area += (new_neg - neg) * (pos + new_pos) / 2
            pos, neg = new_pos, new_neg
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name
