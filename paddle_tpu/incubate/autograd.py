"""paddle.incubate.autograd — functional forward/reverse AD.

Reference parity: python/paddle/incubate/autograd/__init__.py:19 (__all__:
vjp, jvp, Jacobian, Hessian, enable_prim, disable_prim, forward_grad, grad)
with semantics from incubate/autograd/functional.py (vjp:22, jvp:80,
Jacobian:170, Hessian:257) and primapi.py (forward_grad:25, grad:108).

TPU-native design: the reference needs a "prim" program transform to get
forward-mode AD in static graphs; here forward mode is native — `jvp`
traces the user function once with `jax.jvp` (one forward pass carrying
tangents, no double-backward graph), falling back to the reference's
double-backward trick over the eager tape only if the function cannot be
jvp-traced (e.g. it calls .numpy() mid-flight). `forward_grad` runs the
double-backward trick over the already-recorded tape (two linear reverse
passes — the tape's create_graph backward makes the first pass itself
differentiable). enable_prim/disable_prim are honest compatibility flags:
jax ALWAYS differentiates through primitive registries, so there is no
separate prim mode to switch on.
"""
from __future__ import annotations

import typing

import jax
import numpy as np
from jax import numpy as jnp

from ..core.tensor import Tensor
from ..ops import creation

__all__ = [
    'vjp',
    'jvp',
    'Jacobian',
    'Hessian',
    'enable_prim',
    'disable_prim',
    'forward_grad',
    'grad',
]

_prim_flag = {"enabled": False}


def enable_prim():
    """Reference utils.py:73. In this framework lowering to differentiable
    primitives is jax's only mode of operation; the flag is kept for API
    compatibility (forward_grad/grad work regardless of it)."""
    _prim_flag["enabled"] = True


def disable_prim():
    """Reference utils.py:99."""
    _prim_flag["enabled"] = False


def prim_enabled():
    """Reference utils.py:39 (exported by module, not __all__)."""
    return _prim_flag["enabled"]


def _as_list(x):
    if x is None:
        return None, False
    if isinstance(x, (list, tuple)):
        return list(x), True
    return [x], False


def _pack(values, was_seq):
    if was_seq:
        return tuple(values)
    return values[0]


def _separate(xs_list):
    """Reference functional.py ``_separate``: break aliasing/dependencies —
    each input becomes an independent leaf, so Jacobian([x, x]) treats the
    two slots as distinct variables."""
    return [Tensor(x._value, stop_gradient=False) for x in xs_list]


def vjp(func, xs, v=None):
    """Vector-Jacobian product (reference functional.py:22): returns
    (func(xs), vjp result). ``v`` defaults to all-ones cotangents."""
    from .. import autograd as _ag

    xs_list, xs_seq = _as_list(xs)
    xs_list = _separate(xs_list)
    ys = func(*xs_list) if xs_seq else func(xs_list[0])
    ys_list, ys_seq = _as_list(ys)
    v_list, _ = _as_list(v)
    if v_list is None:
        v_list = [creation.ones_like(y) for y in ys_list]
    grads = _ag.grad(
        ys_list, xs_list, grad_outputs=v_list, retain_graph=True,
        allow_unused=True,
    )
    grads = [
        g if g is not None else creation.zeros_like(x)
        for g, x in zip(grads, xs_list)
    ]
    return ys, _pack(grads, xs_seq)


def jvp(func, xs, v=None):
    """Jacobian-vector product (reference functional.py:80): one forward
    pass via jax.jvp — true forward-mode AD, not the reference's prim
    transform. Returns (func(xs), jvp result); ``v`` defaults to ones."""
    xs_list, xs_seq = _as_list(xs)
    if v is not None:
        v_list, _ = _as_list(v)
        tangents = tuple(jnp.asarray(t._value, x._value.dtype)
                         for t, x in zip(v_list, xs_list))
    else:
        tangents = tuple(jnp.ones_like(x._value) for x in xs_list)
    primals = tuple(x._value for x in xs_list)

    out_meta = {}

    def pure(*vals):
        txs = [Tensor(val, stop_gradient=False) for val in vals]
        ys = func(*txs) if xs_seq else func(txs[0])
        ys_list, ys_seq = _as_list(ys)
        out_meta["seq"] = ys_seq
        return tuple(y._value for y in ys_list)

    try:
        ys_vals, jvp_vals = jax.jvp(pure, primals, tangents)
    except Exception:
        # function not jvp-traceable (data-dependent host control flow,
        # .numpy() calls, in-place framework state): double-backward trick
        # over the eager tape (reference functional.py:_double_backward_trick)
        return _jvp_double_backward(func, xs_list, xs_seq, tangents)
    ys = _pack([Tensor(val, stop_gradient=False) for val in ys_vals],
               out_meta["seq"])
    jvps = _pack([Tensor(val, stop_gradient=False) for val in jvp_vals],
                 out_meta["seq"])
    return ys, jvps


def _jvp_double_backward(func, xs_list, xs_seq, tangents):
    from .. import autograd as _ag

    xs_live = []
    for x in xs_list:
        t = Tensor(x._value, stop_gradient=False)
        xs_live.append(t)
    ys = func(*xs_live) if xs_seq else func(xs_live[0])
    ys_list, ys_seq = _as_list(ys)
    u = [Tensor(jnp.zeros_like(y._value), stop_gradient=False) for y in ys_list]
    gx = _ag.grad(ys_list, xs_live, grad_outputs=u, create_graph=True,
                  allow_unused=True)
    gx = [g if g is not None else creation.zeros_like(x)
          for g, x in zip(gx, xs_live)]
    v_t = [Tensor(t, stop_gradient=True) for t in tangents]
    jvps = _ag.grad(gx, u, grad_outputs=v_t, allow_unused=True)
    jvps = [j if j is not None else creation.zeros_like(y)
            for j, y in zip(jvps, ys_list)]
    return ys, _pack(jvps, ys_seq)


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode grad of already-computed outputs w.r.t. inputs
    (reference primapi.py:25, which requires static graph + prim mode;
    here it runs on the eager tape directly). Implemented as the
    double-backward trick: the tape from inputs to outputs is linearized
    by one create_graph reverse pass seeded with a variable cotangent u,
    then differentiated w.r.t. u against the tangent."""
    from .. import autograd as _ag

    ys_list, ys_seq = _as_list(outputs)
    xs_list, _ = _as_list(inputs)
    v_list, _ = _as_list(grad_inputs)
    if v_list is None:
        v_list = [creation.ones_like(x) for x in xs_list]
    u = [Tensor(jnp.zeros_like(y._value), stop_gradient=False)
         for y in ys_list]
    gx = _ag.grad(ys_list, xs_list, grad_outputs=u, create_graph=True,
                  retain_graph=True, allow_unused=True)
    gx = [g if g is not None else creation.zeros_like(x)
          for g, x in zip(gx, xs_list)]
    jvps = _ag.grad(gx, u, grad_outputs=v_list, allow_unused=True)
    jvps = [j if j is not None else creation.zeros_like(y)
            for j, y in zip(jvps, ys_list)]
    return _pack(jvps, ys_seq)


def grad(outputs, inputs, grad_outputs=None):
    """Reverse-mode grad (reference primapi.py:108 — the prim-mode
    counterpart of paddle.grad; here one API serves both)."""
    from .. import autograd as _ag

    ys_list, _ = _as_list(outputs)
    xs_list, xs_seq = _as_list(inputs)
    gs = _ag.grad(ys_list, xs_list, grad_outputs=grad_outputs,
                  retain_graph=True, allow_unused=True)
    gs = [g if g is not None else creation.zeros_like(x)
          for g, x in zip(gs, xs_list)]
    return _pack(gs, xs_seq)


def _flatten_ys(func, xs_list, xs_seq, is_batched):
    from ..autograd.functional import _flatten_cat

    ys = func(*xs_list) if xs_seq else func(xs_list[0])
    ys_list, _ = _as_list(ys)
    return _flatten_cat(ys_list, is_batched)


def _eval_separated(func, xs):
    xs_list, xs_seq = _as_list(xs)
    xs_list = _separate(xs_list)
    return xs_list, xs_seq


class Jacobian:
    """Lazily evaluated Jacobian of ``func`` at ``xs`` (reference
    functional.py:170): multiple inputs/outputs are flattened and
    concatenated; rows materialize on first access. Delegates to the
    graduated paddle.autograd machinery (autograd/functional.py)."""

    def __init__(self, func, xs, is_batched=False):
        from ..autograd import functional as _f

        xs_list, xs_seq = _eval_separated(func, xs)
        flat_ys = _flatten_ys(func, xs_list, xs_seq, is_batched)
        self._inner = _f.Jacobian(flat_ys, _pack(xs_list, xs_seq),
                                  is_batched=is_batched)
        self.shape = self._inner.shape

    def __getitem__(self, indexes):
        return self._inner[indexes]

    def __repr__(self):
        return f"Jacobian(shape={self.shape})"


class Hessian:
    """Hessian of a scalar-valued ``func`` at ``xs`` (reference
    functional.py:257): the Jacobian of the gradient. The first reverse
    pass runs with create_graph=True so each Hessian row is one more
    taped reverse pass over it."""

    def __init__(self, func, xs, is_batched=False):
        from .. import autograd as _ag
        from ..autograd import functional as _f

        xs_list, xs_seq = _eval_separated(func, xs)
        ys = func(*xs_list) if xs_seq else func(xs_list[0])
        ys_list, _ = _as_list(ys)
        n = int(np.prod(ys_list[0].shape)) if ys_list[0].ndim else 1
        if len(ys_list) != 1 or (not is_batched and n != 1):
            raise ValueError(
                "Hessian requires a scalar-output func "
                "(or [batch, 1] when is_batched=True)."
            )
        gs = _ag.grad(ys_list, xs_list, create_graph=True, allow_unused=True)
        gs = [g if g is not None else creation.zeros_like(x)
              for g, x in zip(gs, xs_list)]
        flat_g = _f._flatten_cat(gs, is_batched)
        self._inner = _f.Jacobian(flat_g, _pack(xs_list, xs_seq),
                                  is_batched=is_batched)
        self.shape = self._inner.shape

    def __getitem__(self, indexes):
        return self._inner[indexes]

    def __repr__(self):
        return f"Hessian(shape={self.shape})"
