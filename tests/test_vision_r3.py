"""r3 vision namespace completion: transforms (affine/perspective/erase +
random transform classes), ops (psroi_pool, layers, decode_jpeg/read_file)."""
import io

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V
from paddle_tpu.vision import transforms as T


def test_affine_identity_and_translate():
    img = np.arange(48, dtype=np.uint8).reshape(4, 4, 3)
    out = T.affine(img, angle=0, translate=(0, 0), scale=1.0, shear=0)
    np.testing.assert_array_equal(out, img)
    out = T.affine(img, angle=0, translate=(1, 0), scale=1.0, shear=0, fill=0)
    np.testing.assert_array_equal(out[:, 1:], img[:, :3])  # shifted right
    assert (out[:, 0] == 0).all()


def test_affine_rotate90_matches_rot90():
    img = np.arange(25, dtype=np.float32).reshape(5, 5, 1)
    out = T.affine(img, angle=90, translate=(0, 0), scale=1.0, shear=0)
    np.testing.assert_allclose(out[..., 0], np.rot90(img[..., 0], 1), atol=1e-6)


def test_perspective_identity_and_roundtrip():
    img = np.random.RandomState(0).randint(0, 255, (8, 8, 3)).astype(np.uint8)
    corners = [[0, 0], [7, 0], [7, 7], [0, 7]]
    out = T.perspective(img, corners, corners)
    np.testing.assert_array_equal(out, img)


def test_erase_array_and_tensor():
    img = np.ones((6, 6, 3), np.float32)
    out = T.erase(img, 1, 2, 3, 2, v=0.0)
    assert out[1:4, 2:4].sum() == 0 and out.sum() == img.sum() - 3 * 2 * 3

    t = paddle.to_tensor(np.ones((3, 6, 6), np.float32))
    out_t = T.erase(t, 0, 0, 2, 2, v=paddle.to_tensor(np.zeros((3, 2, 2), np.float32)))
    assert float(out_t.numpy()[:, :2, :2].sum()) == 0.0


def test_random_transform_classes():
    np.random.seed(0)
    img = np.random.RandomState(1).randint(0, 255, (16, 16, 3)).astype(np.uint8)
    for cls, arg in [(T.BrightnessTransform, 0.4), (T.ContrastTransform, 0.4),
                     (T.SaturationTransform, 0.4), (T.HueTransform, 0.2)]:
        out = cls(arg)(img)
        assert out.shape == img.shape
        assert cls(0)(img) is img or (np.asarray(cls(0)(img)) == img).all()
    out = T.RandomAffine(degrees=20, translate=(0.1, 0.1), scale=(0.8, 1.2), shear=5)(img)
    assert out.shape == img.shape
    out = T.RandomPerspective(prob=1.0, distortion_scale=0.3)(img)
    assert out.shape == img.shape
    with pytest.raises(ValueError):
        T.HueTransform(0.9)


def test_psroi_pool_uniform_box():
    # constant per-group channels: pooled output must equal the group value
    N, out_c, ph, pw, H, W = 1, 2, 2, 2, 8, 8
    C = out_c * ph * pw
    x = np.zeros((N, C, H, W), np.float32)
    for ch in range(C):
        x[0, ch] = ch  # constant plane per channel
    boxes = np.array([[0.0, 0.0, 8.0, 8.0]], np.float32)
    out = V.psroi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                       paddle.to_tensor(np.array([1], np.int32)), (ph, pw)).numpy()
    assert out.shape == (1, out_c, ph, pw)
    # channel layout: group (c, i, j) reads plane c*ph*pw + i*pw + j
    for c in range(out_c):
        for i in range(ph):
            for j in range(pw):
                assert out[0, c, i, j] == pytest.approx(c * ph * pw + i * pw + j)


def test_roi_layers_and_deform_layer():
    x = paddle.to_tensor(np.random.RandomState(0).randn(1, 4, 8, 8).astype(np.float32))
    boxes = paddle.to_tensor(np.array([[0.0, 0.0, 4.0, 4.0]], np.float32))
    bn = paddle.to_tensor(np.array([1], np.int32))
    out = V.RoIAlign(2)(x, boxes, bn)
    assert tuple(out.shape) == (1, 4, 2, 2)
    out = V.RoIPool(2)(x, boxes, bn)
    assert tuple(out.shape) == (1, 4, 2, 2)

    paddle.seed(0)
    dc = V.DeformConv2D(4, 6, 3, padding=1)
    offset = paddle.to_tensor(np.zeros((1, 18, 8, 8), np.float32))
    out = dc(x, offset)
    assert tuple(out.shape) == (1, 6, 8, 8)
    assert len(dc.parameters()) == 2


def test_read_file_decode_jpeg(tmp_path):
    from PIL import Image

    img = np.random.RandomState(0).randint(0, 255, (10, 12, 3)).astype(np.uint8)
    path = str(tmp_path / "t.jpg")
    Image.fromarray(img).save(path, quality=95)
    data = V.read_file(path)
    assert data.dtype == np.dtype("uint8") and data.numpy().size > 100
    dec = V.decode_jpeg(data).numpy()
    assert dec.shape == (3, 10, 12)
    assert np.abs(dec.astype(int).mean() - img.transpose(2, 0, 1).astype(int).mean()) < 10
