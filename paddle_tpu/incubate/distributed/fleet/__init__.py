"""paddle.incubate.distributed.fleet parity (reference
python/paddle/incubate/distributed/fleet/__init__.py): re-exports the fleet
recompute entry points."""
from ....distributed.fleet.recompute.recompute import (  # noqa: F401
    recompute_hybrid,
    recompute_sequential,
)

__all__ = ["recompute_sequential", "recompute_hybrid"]
