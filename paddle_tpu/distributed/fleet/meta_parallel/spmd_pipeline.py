"""Compiled circular pipeline over the pp mesh axis.

This is the TPU-native answer to the reference's actor/interceptor pipeline
runtime (paddle/fluid/distributed/fleet_executor/: Carrier,
ComputeInterceptor message loops) and NCCL p2p micro-batch exchange
(fleet/meta_parallel/pp_utils/p2p_communication.py): instead of host-driven
per-micro-batch send/recv, the WHOLE schedule compiles into one XLA program
— a lax.scan over time steps where every pp device runs its stage and
hands its activation to the next stage with lax.ppermute (one ICI hop).
All stages stay busy once the pipeline fills (GPipe-style fill/drain of a
circular schedule; 1F1B's memory benefit is obtained by jax.checkpoint on
the stage function + reverse-mode through the scan).

Requirements: every stage has the same structure (stage_fn), per-stage
params stacked on a leading axis sharded over pp, activation shape = input
micro-batch shape.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
from jax import numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pipeline_spmd(stage_fn: Callable, mesh: Mesh, axis: str = "pp", checkpoint_stages: bool = True):
    """Build fn(stacked_params, microbatches) -> outputs.

    stage_fn(params, x) -> y: one stage's computation, y.shape == x.shape.
    stacked_params: pytree with leading stage axis S (sharded over `axis`).
    microbatches: [M, ...] micro-batch stream (replicated over `axis`).
    Returns [M, ...] outputs of the final stage.
    """
    S = mesh.shape[axis]
    fn = jax.checkpoint(stage_fn) if checkpoint_stages else stage_fn

    def per_device(params, mbs):
        # params leaves: [1, ...] local stage slice; mbs: [M, ...] full stream
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        sidx = jax.lax.axis_index(axis)
        M = mbs.shape[0]
        fwd_perm = [(s, (s + 1) % S) for s in range(S)]

        def step(carry, t):
            buf = carry
            # stage 0 ingests micro-batch t (clipped during drain)
            feed = mbs[jnp.clip(t, 0, M - 1)]
            x = jnp.where(sidx == 0, feed, buf)
            y = fn(params, x)
            shifted = jax.lax.ppermute(y, axis, fwd_perm)
            return shifted, y

        init = jnp.zeros_like(mbs[0])
        _, ys = jax.lax.scan(step, init, jnp.arange(M + S - 1))
        return ys[None]  # [1, T, ...] per device -> [S, T, ...] global

    sharded = jax.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(axis),
        check_vma=False,
    )

    def run(stacked_params, microbatches):
        M = microbatches.shape[0]
        ys = sharded(stacked_params, microbatches)  # [S, M+S-1, ...]
        # final stage's outputs for micro-batch m appear at t = m + S - 1
        return ys[S - 1, S - 1 : M + S - 1]

    return run


def stack_stage_params(param_trees, mesh: Mesh, axis: str = "pp"):
    """Stack S per-stage param pytrees on a new leading axis sharded over pp."""
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *param_trees)
    sh = NamedSharding(mesh, P(axis))

    def put(x):
        return jax.device_put(x, NamedSharding(mesh, P(*([axis] + [None] * (x.ndim - 1)))))

    return jax.tree_util.tree_map(put, stacked)
