"""Deployment inference surface — paddle.inference parity.

Reference parity: paddle/fluid/inference/api (AnalysisPredictor,
paddle_inference_api.h Config/Predictor/Tensor; Python surface
python/paddle/inference/__init__.py). TPU-native design: the "analysis +
IR pass pipeline + engine" stack collapses into XLA — a frozen model IS a
serialized StableHLO program (jit.save / static.save_inference_model
artifacts: .pdmodel blob + .pdmeta + optional .pdiparams), and the
predictor is a thin handle-based wrapper that loads it once, caches the
compiled executable, and runs feed->fetch. Config knobs that select CUDA
engines (TensorRT, gpu memory pools, MKLDNN) are accepted and recorded but
inert — XLA owns compilation on TPU.
"""
from __future__ import annotations

import os
import pickle
from typing import Dict, List, Optional

import numpy as np
import jax
from jax import export as jax_export
from jax import numpy as jnp

__all__ = ["Config", "Predictor", "Tensor", "create_predictor", "PrecisionType", "PlaceType"]


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    CPU = 0
    GPU = 1
    TPU = 1  # "the accelerator place"
    XPU = 2
    CUSTOM = 9


class Config:
    """paddle.inference.Config parity (paddle_analysis_config.h). Point it
    at a saved prefix (`Config(prefix)`), an explicit model file pair
    (`Config(model_file, params_file)`), or a directory containing exactly
    one exported model."""

    def __init__(self, model_arg: Optional[str] = None, params_file: Optional[str] = None):
        self._prefix = None
        self._params_file = params_file
        if model_arg is not None:
            if os.path.isdir(model_arg):
                cands = [f for f in os.listdir(model_arg) if f.endswith(".pdmodel")]
                if len(cands) != 1:
                    raise ValueError(
                        f"Config(model_dir): expected exactly one .pdmodel under {model_arg}, found {cands}"
                    )
                self._prefix = os.path.join(model_arg, cands[0][: -len(".pdmodel")])
            else:
                self._prefix = model_arg[: -len(".pdmodel")] if model_arg.endswith(".pdmodel") else model_arg
        self._device = "tpu"
        self._device_id = 0
        self._inert: Dict[str, object] = {}
        self._llm_opts: Dict[str, object] = {}

    # ---- model paths ----
    def set_model(self, model_arg, params_file=None):
        self.__init__(model_arg, params_file)

    def model_dir(self):
        return os.path.dirname(self._prefix or "")

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return self._params_file or ((self._prefix or "") + ".pdiparams")

    # ---- device selection ----
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0, precision=PrecisionType.Float32):
        # "the accelerator": TPU here; memory pools are XLA-owned
        self._device, self._device_id = "tpu", device_id

    def enable_xpu(self, *a, **kw):
        self._device = "tpu"

    def enable_custom_device(self, device_type, device_id=0):
        self._device, self._device_id = device_type, device_id

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device != "cpu"

    def gpu_device_id(self):
        return self._device_id

    # ---- serving engine (paged-KV decode) ----
    def enable_llm_engine(self, max_new_tokens=32, eos_id=None, llm_replicas=1,
                          qos=None, **engine_opts):
        """Route this Config through the serving InferenceEngine (paged KV
        cache + AOT shape buckets + continuous batching) instead of the
        frozen-program Predictor. Automatic when the model path carries a
        `.pdllm` artifact; `engine_opts` forward to InferenceEngine
        (max_seq_len, block_size, num_blocks, max_batch, ...).

        `llm_replicas > 1` backs the predictor with a ReplicaFleet over
        that many engines sharing one weight set: SLO-aware routed,
        replica-failure-surviving, hot-swappable (inference/fleet.py).

        `qos` (a qos.QoSConfig or qos.QoSPolicy) turns on overload
        protection & multi-tenant fairness: per-tenant token buckets,
        weighted-fair dequeue, bounded queues with explicit sheds, and
        the brownout ladder. QoS always runs through a fleet backend
        (of 1 when llm_replicas == 1) so the policy state is shared the
        way a multi-replica deployment shares it."""
        self._llm_opts.update(max_new_tokens=max_new_tokens, eos_id=eos_id,
                              llm_replicas=int(llm_replicas), qos=qos,
                              **engine_opts)

    def llm_replicas(self) -> int:
        return int(self._llm_opts.get("llm_replicas", 1))

    def is_llm(self) -> bool:
        return self._prefix is not None and os.path.exists(self._prefix + ".pdllm")

    # ---- accepted-but-inert engine knobs (CUDA/TRT/MKLDNN specific) ----
    def enable_tensorrt_engine(self, *a, **kw):
        self._inert["tensorrt"] = True

    def enable_mkldnn(self):
        self._inert["mkldnn"] = True

    def switch_ir_optim(self, flag=True):
        self._inert["ir_optim"] = flag

    def enable_memory_optim(self, flag=True):
        self._inert["memory_optim"] = flag

    def set_cpu_math_library_num_threads(self, n):
        self._inert["cpu_threads"] = n

    def summary(self) -> str:
        return (
            f"model: {self.prog_file()}\ndevice: {self._device}:{self._device_id}\n"
            f"inert knobs: {self._inert}"
        )


class Tensor:
    """Predictor I/O handle (paddle_infer.Tensor): host-side staging buffer
    with copy_from_cpu / copy_to_cpu."""

    def __init__(self, name, shape=None, dtype=None):
        self._name = name
        self._declared_shape = shape
        self._dtype = dtype
        self._value = None

    def name(self):
        return self._name

    def reshape(self, shape):
        self._declared_shape = tuple(shape)

    def copy_from_cpu(self, arr):
        a = np.asarray(arr)
        if self._dtype is not None:
            a = a.astype(self._dtype)
        self._value = a

    def copy_to_cpu(self):
        if self._value is None:
            raise RuntimeError(f"output handle '{self._name}' has no data — call Predictor.run() first")
        return np.asarray(self._value)

    def shape(self):
        if self._value is not None:
            return list(np.asarray(self._value).shape)
        return list(self._declared_shape or [])


def save_llm(model, prefix: str) -> str:
    """Save a decode-capable causal LM as a serving artifact:
    `{prefix}.pdllm` (JSON model config) + `{prefix}.pdiparams` (weights).

    Unlike the frozen-StableHLO .pdmodel path, an LLM artifact stays a
    LIVE model — the predictor rebuilds it and serves greedy decode through
    the paged-KV InferenceEngine (prefill/decode shape buckets), which a
    single frozen program cannot express."""
    import json

    import numpy as np

    cfg = getattr(model, "config", None)
    if not isinstance(cfg, dict):
        raise ValueError("save_llm needs a model with a .config dict "
                         "(LlamaForCausalLM-shaped)")
    d = os.path.dirname(prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(prefix + ".pdllm", "w") as f:
        json.dump({"arch": "LlamaForCausalLM", "config": cfg}, f)
    from ..framework import io as fio

    state = {k: np.asarray(v._value) for k, v in model.state_dict().items()}
    fio.save(state, prefix + ".pdiparams")
    return prefix


def load_llm(prefix: str):
    """Rebuild the model saved by save_llm (weights loaded, eval mode)."""
    import json

    with open(prefix + ".pdllm") as f:
        meta = json.load(f)
    if meta.get("arch") != "LlamaForCausalLM":
        raise ValueError(f"unknown LLM artifact arch {meta.get('arch')!r}")
    from ..models.llama import LlamaForCausalLM

    model = LlamaForCausalLM(**meta["config"])
    from ..framework import io as fio

    model.set_state_dict(fio.load(prefix + ".pdiparams"))
    model.eval()
    return model


class LLMPredictor:
    """Predictor surface over the serving engine: Config points at a
    save_llm artifact, `create_predictor` returns this, and run() greedy-
    decodes through the paged-KV continuous-batching stack.

    Inputs: "input_ids" [B, S] int (rows right-padded; give true lengths
    via the optional "seq_lens" [B] handle). Output: "generated_ids"
    [B, max_new_tokens] int32, right-padded with -1 after EOS."""

    def __init__(self, config: Config):
        if config._prefix is None:
            raise ValueError("Config has no model path")
        self._config = config
        self._model = load_llm(config._prefix)
        opts = dict(config._llm_opts)
        self._max_new_tokens = int(opts.pop("max_new_tokens", 32))
        self._eos_id = opts.pop("eos_id", None)
        self._n_replicas = max(1, int(opts.pop("llm_replicas", 1)))
        self._qos = opts.pop("qos", None)
        self._engine_opts = opts
        self._build_backend()
        self._inputs = {
            "input_ids": Tensor("input_ids", dtype=np.int64),
            "seq_lens": Tensor("seq_lens", dtype=np.int64),
        }
        self._outputs = {"generated_ids": Tensor("generated_ids")}

    def _build_backend(self):
        """One engine, or (Config.llm_replicas > 1) a ReplicaFleet of
        engines over the SAME weights — routing/failure-survival/hot-swap
        live in inference/fleet.py; the predictor surface is unchanged."""
        from .engine import InferenceEngine

        engines = [
            InferenceEngine(self._model, **self._engine_opts)
            for _ in range(self._n_replicas)
        ]
        self._engine = engines[0]
        qos = self._qos
        if qos is not None:
            # accept a bare QoSConfig; wrap it in the shared policy object
            from .qos import QoSPolicy

            if not isinstance(qos, QoSPolicy):
                qos = QoSPolicy(qos)
        self._qos = qos
        if self._n_replicas > 1 or qos is not None:
            from .fleet import ReplicaFleet

            self._fleet = ReplicaFleet(engines, eos_id=self._eos_id, qos=qos)
        else:
            self._fleet = None

    def qos(self):
        """The shared QoSPolicy (None when QoS is off) — operational
        surface for shed counts and the brownout rung."""
        return self._qos

    def fleet(self):
        """The backing ReplicaFleet (None for a single-replica predictor) —
        operational surface for request_swap() and health inspection."""
        return self._fleet

    def get_input_names(self):
        return list(self._inputs)

    def get_output_names(self):
        return list(self._outputs)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_handle(self, name):
        return self._outputs[name]

    def run(self, inputs: Optional[list] = None):
        if inputs is not None:
            for n, a in zip(self.get_input_names(), inputs):
                self._inputs[n].copy_from_cpu(a)
        ids = self._inputs["input_ids"]._value
        if ids is None:
            raise RuntimeError("input 'input_ids' not set — copy_from_cpu it first")
        ids = np.asarray(ids)
        if ids.ndim == 1:
            ids = ids[None]
        lens = self._inputs["seq_lens"]._value
        if lens is None:
            lens = np.full((ids.shape[0],), ids.shape[1], np.int64)
        lens = np.asarray(lens).reshape(-1)
        if lens.shape[0] != ids.shape[0]:
            raise ValueError(
                f"seq_lens has {lens.shape[0]} entries for {ids.shape[0]} "
                "input_ids rows — re-copy seq_lens (a stale handle from a "
                "previous run() would silently truncate the batch)"
            )
        prompts = [list(map(int, row[: int(l)])) for row, l in zip(ids, lens)]
        if self._fleet is not None:
            gen = self._fleet.generate(prompts, max_new_tokens=self._max_new_tokens)
        else:
            gen = self._engine.generate(
                prompts, max_new_tokens=self._max_new_tokens, eos_id=self._eos_id
            )
        out = np.full((len(gen), self._max_new_tokens), -1, np.int32)
        for i, g in enumerate(gen):
            out[i, : len(g)] = g
        self._outputs["generated_ids"]._value = out
        if inputs is not None:
            return [out]
        return None

    def clone(self) -> "LLMPredictor":
        # the engine's KV pool is serial per predictor — a clone gets its
        # own pool/engine (or fleet) over the SAME model (weights shared
        # by reference)
        c = LLMPredictor.__new__(LLMPredictor)
        c._config = self._config
        c._model = self._model
        c._max_new_tokens = self._max_new_tokens
        c._eos_id = self._eos_id
        c._n_replicas = self._n_replicas
        # re-normalized by _build_backend: a QoSConfig yields the clone its
        # own fresh policy state, an explicitly shared QoSPolicy stays shared
        c._qos = self._config._llm_opts.get("qos")
        c._engine_opts = dict(self._engine_opts)
        c._build_backend()
        c._inputs = {
            "input_ids": Tensor("input_ids", dtype=np.int64),
            "seq_lens": Tensor("seq_lens", dtype=np.int64),
        }
        c._outputs = {"generated_ids": Tensor("generated_ids")}
        return c

    def clear_intermediate_tensor(self):
        return None

    def try_shrink_memory(self):
        if self._fleet is not None:
            for rep in self._fleet.replicas:
                rep.engine.pool.reset()
        else:
            self._engine.pool.reset()
        return None


class Predictor:
    """paddle_infer.Predictor parity over a frozen StableHLO program."""

    def __init__(self, config: Config):
        if config._prefix is None:
            raise ValueError("Config has no model path")
        self._config = config
        with open(config.prog_file(), "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        with open(config._prefix + ".pdmeta", "rb") as f:
            self._meta = pickle.load(f)
        # feed names: static artifacts record them; jit.save artifacts are
        # positional — synthesize names
        names = self._meta.get("feed_names")
        if names is None:
            names = [f"input_{i}" for i in range(len(self._meta.get("in_dtypes", [])))]
        self._input_names = list(names)
        n_out = self._meta.get("n_fetch", self._meta.get("n_outputs", 1))
        self._output_names = [f"output_{i}" for i in range(n_out)]
        dtypes = self._meta.get("in_dtypes")
        self._inputs = {
            n: Tensor(n, dtype=(dtypes[i] if dtypes else None))
            for i, n in enumerate(self._input_names)
        }
        self._outputs = {n: Tensor(n) for n in self._output_names}

    # ---- handles ----
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_input_handle(self, name) -> Tensor:
        return self._inputs[name]

    def get_output_handle(self, name) -> Tensor:
        return self._outputs[name]

    # ---- run ----
    def run(self, inputs: Optional[list] = None):
        if inputs is not None:  # positional convenience (reference allows it)
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(a)
        raw = []
        for n in self._input_names:
            if self._inputs[n]._value is None:
                raise RuntimeError(f"input '{n}' not set — copy_from_cpu it first")
            raw.append(jnp.asarray(self._inputs[n]._value))
        out = self._exported.call(*raw)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        for n, o in zip(self._output_names, outs):
            self._outputs[n]._value = np.asarray(o)
        if inputs is not None:
            return [self._outputs[n].copy_to_cpu() for n in self._output_names]
        return None

    def clone(self) -> "Predictor":
        """Reference semantics: the clone SHARES the loaded program (no
        re-deserialization) and gets its own I/O buffers."""
        c = Predictor.__new__(Predictor)
        c._config = self._config
        c._exported = self._exported
        c._meta = self._meta
        c._input_names = list(self._input_names)
        c._output_names = list(self._output_names)
        dtypes = self._meta.get("in_dtypes")
        c._inputs = {
            n: Tensor(n, dtype=(dtypes[i] if dtypes else None))
            for i, n in enumerate(c._input_names)
        }
        c._outputs = {n: Tensor(n) for n in c._output_names}
        return c

    def clear_intermediate_tensor(self):
        return None

    def try_shrink_memory(self):
        return None


def create_predictor(config: Config):
    """paddle.inference.create_predictor. A Config pointing at a save_llm
    artifact (`.pdllm` + `.pdiparams`) gets the serving-engine-backed
    LLMPredictor (greedy decode over the paged KV cache); frozen StableHLO
    artifacts keep the program Predictor."""
    if config.is_llm():
        return LLMPredictor(config)
    return Predictor(config)


class DataType:
    """paddle_infer.DataType enum (paddle_tensor.h PaddleDType)."""

    FLOAT64 = -1  # extension: not in the C enum, used by get_num_bytes
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6
    BOOL = 7


def get_num_bytes_of_data_type(dtype) -> int:
    """paddle.inference.get_num_bytes_of_data_type."""
    sizes = {
        DataType.FLOAT64: 8, DataType.FLOAT32: 4, DataType.INT64: 8,
        DataType.INT32: 4, DataType.UINT8: 1, DataType.INT8: 1,
        DataType.FLOAT16: 2, DataType.BFLOAT16: 2, DataType.BOOL: 1,
    }
    if dtype not in sizes:
        raise ValueError(f"unknown inference DataType: {dtype}")
    return sizes[dtype]


def get_version() -> str:
    """paddle.inference.get_version (version banner string)."""
    from .. import version as _v

    return f"version: {_v.full_version}\ncommit: {_v.commit}\n"


def get_trt_compile_version():
    """TensorRT does not exist on TPU — the reference returns the linked
    TRT version; here the triple is zeros (the Config TRT knobs are inert)."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name: str) -> str:
    """Reference maps a legacy fluid op name to its phi kernel name via the
    compat registry. This framework has one dispatch point (core/apply), so
    the op name IS the kernel name; the handful of renamed legacy ops the
    reference table covers are mapped explicitly."""
    legacy = {
        "matmul_v2": "matmul", "elementwise_add": "add",
        "elementwise_sub": "subtract", "elementwise_mul": "multiply",
        "elementwise_div": "divide", "reduce_sum": "sum",
        "reduce_mean": "mean", "fill_constant": "full",
    }
    return legacy.get(op_name, op_name)


class XpuConfig:
    """paddle.inference.XpuConfig parity: accepted-and-inert device knobs
    (kunlun XPU settings have no role on TPU; kept for config portability)."""

    def __init__(self, **kwargs):
        self.device_id = kwargs.pop("device_id", 0)
        self.l3_size = kwargs.pop("l3_size", 0)
        self.l3_autotune_size = kwargs.pop("l3_autotune_size", 0)
        for k, v in kwargs.items():
            setattr(self, k, v)


class PredictorPool:
    """paddle.inference.PredictorPool: `size` predictors sharing one Config.
    The first is the primary; the rest are clones (reference semantics —
    clone shares the loaded program, each handle has its own I/O buffers)."""

    def __init__(self, config: Config, size: int = 1):
        if size < 1:
            raise ValueError("PredictorPool size must be >= 1")
        main = Predictor(config)
        self._preds = [main] + [main.clone() for _ in range(size - 1)]

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]


def convert_to_mixed_precision(
    model_file: str,
    params_file: str,
    mixed_model_file: str,
    mixed_params_file: str,
    mixed_precision=PrecisionType.Half,
    backend=None,
    keep_io_types: bool = True,
    black_list=None,
    **kwargs,
):
    """paddle.inference.convert_to_mixed_precision: rewrite a saved model's
    SEPARATE parameter payload (.pdiparams) to a reduced precision — the
    on-disk/load-time half-sizing that is the point of the conversion.

    SCOPE WARNING (also emitted at runtime): the frozen StableHLO program is
    copied AS-IS. Weights that were baked INTO the program blob at export
    time (constants, not a separate .pdiparams payload) are NOT converted —
    they stay at their exported precision and XLA re-fuses casts at compile
    time. Only the separate parameter payload halves on disk. Reference:
    python/paddle/inference/convert_to_mixed_precision.py."""
    import shutil
    import warnings

    target = {PrecisionType.Half: np.float16, PrecisionType.Bfloat16: "bfloat16"}.get(
        mixed_precision
    )
    if target is None:
        raise ValueError("mixed_precision must be PrecisionType.Half or Bfloat16")
    black = set(black_list or ())
    warnings.warn(
        "convert_to_mixed_precision converts only the SEPARATE parameter "
        f"payload ({params_file!r}); the program blob is copied as-is, so any "
        "weights baked into the program as constants keep their exported "
        "precision and see no size/precision change",
        UserWarning,
        stacklevel=2,
    )
    shutil.copyfile(model_file, mixed_model_file)
    # sidecar meta: derive the prefix from ANY extension (reference passes
    # .pdmodel, but Config accepts arbitrary file names)
    src_meta = os.path.splitext(model_file)[0] + ".pdmeta"
    dst_meta = os.path.splitext(mixed_model_file)[0] + ".pdmeta"
    if os.path.exists(src_meta):
        with open(src_meta, "rb") as f:
            meta = pickle.load(f)
        meta["mixed_precision"] = int(mixed_precision)
        with open(dst_meta, "wb") as f:
            pickle.dump(meta, f)
    from ..framework import io as fio

    params = fio.load(params_file)
    import jax.numpy as _jnp

    def cast(name, a):
        arr = np.asarray(a)
        if name in black or arr.dtype != np.float32:
            return arr
        if target == "bfloat16":
            return np.asarray(_jnp.asarray(arr).astype(_jnp.bfloat16))
        return arr.astype(target)

    converted = {k: cast(k, v) for k, v in params.items()}
    fio.save(converted, mixed_params_file)


__all__ += [
    "DataType", "PredictorPool", "XpuConfig", "get_version",
    "get_trt_compile_version", "get_trt_runtime_version",
    "get_num_bytes_of_data_type", "convert_to_mixed_precision",
    "_get_phi_kernel_name",
    "LLMPredictor", "save_llm", "load_llm",
]
