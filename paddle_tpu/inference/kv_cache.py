"""Paged KV cache: fixed-size blocks in a preallocated per-layer pool.

The serving tier's memory manager (vLLM's PagedAttention layout, SURVEY's
L3c serving rebuild): context KV for every in-flight sequence lives in
fixed-size pages drawn from one preallocated pool per layer, addressed
through a per-sequence block table. Allocation is a host-side free-list
(O(1) alloc/free, no compaction — pages are interchangeable), the device
arrays are functional jax values the compiled prefill/decode steps thread
through, and pool pressure is observable: total/used blocks, alloc/free
counts, allocation failures (the scheduler's preemption trigger), and
internal fragmentation (allocated-but-unwritten slots) all export through
the PR 1 telemetry registry.

Page 0 is RESERVED as the trash page: block tables are padded with 0 past
a sequence's last real page, so masked reads land on a valid page (never a
fault) and padded-position writes scribble somewhere harmless.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

from jax import numpy as jnp

from .. import telemetry
from ..telemetry import metrics as _metrics
from ..telemetry import request_trace as _rt

__all__ = ["BlockPool", "PagedCacheView", "PoolExhausted", "TRASH_PAGE"]

TRASH_PAGE = 0  # reserved: block-table padding + padded-position writes


class PoolExhausted(RuntimeError):
    """alloc() could not find enough free pages — the caller's cue to
    preempt (continuous-batching scheduler) or reject admission."""


def _pool_gauge(state: str):
    return _metrics.gauge(
        "paddle_tpu_kv_pool_blocks",
        "paged KV cache pool occupancy by state",
        label_names=("state",),
    ).labels(state=state)


class PagedCacheView:
    """Functional view of the pool's device arrays for ONE traced step.

    Holds per-layer k/v page arrays (possibly jax tracers), the step's
    block tables [B, M] and seq_lens [B], and applies writes as functional
    `.at[].set` updates stored back on the view — the compiled step returns
    the updated arrays and the engine adopts them into the pool.
    """

    def __init__(self, k_pages: Sequence, v_pages: Sequence, block_tables,
                 seq_lens, block_size: int):
        self.k_pages = list(k_pages)
        self.v_pages = list(v_pages)
        self.block_tables = jnp.asarray(block_tables, jnp.int32)
        self.seq_lens = jnp.asarray(seq_lens, jnp.int32)
        self.block_size = int(block_size)

    @property
    def num_layers(self) -> int:
        return len(self.k_pages)

    def layer(self, idx: int) -> Tuple:
        return self.k_pages[idx], self.v_pages[idx]

    def write(self, idx: int, k_new, v_new, positions) -> None:
        """Scatter new K/V into layer `idx`'s pages.

        k_new/v_new [B, S, Hkv, D]; positions [B, S] int32 absolute token
        positions. Position p of row b lands in page block_tables[b, p//bs]
        slot p % bs; positions past a row's real pages hit table padding
        (the trash page) by construction.
        """
        positions = jnp.asarray(positions, jnp.int32)
        bs = self.block_size
        pages = jnp.take_along_axis(self.block_tables, positions // bs, axis=1)
        slots = positions % bs
        self.k_pages[idx] = self.k_pages[idx].at[pages, slots].set(k_new)
        self.v_pages[idx] = self.v_pages[idx].at[pages, slots].set(v_new)


class BlockPool:
    """Preallocated paged KV pool + host free-list allocator.

    Device layout: per layer, k/v pages of shape
    [num_blocks, block_size, num_kv_heads, head_dim]. `num_blocks` INCLUDES
    the reserved trash page 0; usable capacity is num_blocks - 1 pages.
    """

    def __init__(self, num_blocks: int, block_size: int, num_layers: int,
                 num_kv_heads: int, head_dim: int, dtype=jnp.float32):
        if num_blocks < 2:
            raise ValueError("BlockPool needs >= 2 blocks (page 0 is reserved)")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_layers = int(num_layers)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        shape = (self.num_blocks, self.block_size, self.num_kv_heads, self.head_dim)
        self.k_pages: List = [jnp.zeros(shape, dtype) for _ in range(self.num_layers)]
        self.v_pages: List = [jnp.zeros(shape, dtype) for _ in range(self.num_layers)]
        # LIFO free list: recently-freed (cache-warm) pages hand out first
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        if telemetry.enabled():
            _pool_gauge("total").set(self.num_blocks - 1)
            _pool_gauge("used").set(0)

    # ---- allocator ----
    def blocks_for_tokens(self, n_tokens: int) -> int:
        return max(1, math.ceil(n_tokens / self.block_size))

    def available(self) -> int:
        return len(self._free)

    def used(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def alloc(self, n: int, owner: Optional[int] = None) -> List[int]:
        """`owner` is the request id the pages are charged to (request-trace
        attribution only; the allocator itself is owner-blind)."""
        if n > len(self._free):
            if telemetry.enabled():
                _metrics.counter(
                    "paddle_tpu_kv_pool_alloc_failures_total",
                    "paged KV pool allocations refused for lack of free pages",
                ).inc()
            if _rt.enabled():
                _rt.record_event("kv_pool", "alloc_failure", rid=owner,
                                 n=n, free=len(self._free))
            raise PoolExhausted(
                f"paged KV pool exhausted: want {n} pages, {len(self._free)} free "
                f"of {self.num_blocks - 1}"
            )
        out = [self._free.pop() for _ in range(n)]
        if telemetry.enabled():
            _metrics.counter(
                "paddle_tpu_kv_pool_allocs_total", "paged KV pool pages handed out"
            ).inc(n)
            _pool_gauge("used").set(self.used())
        if _rt.enabled():
            # used-after rides every event: the report reconstructs the
            # pool-occupancy-over-time curve from these alone
            _rt.record_event("kv_pool", "alloc", rid=owner, n=n, used=self.used())
        return out

    def free(self, pages: Sequence[int], owner: Optional[int] = None) -> None:
        for p in pages:
            p = int(p)
            if p == TRASH_PAGE:
                raise ValueError("page 0 is reserved and never allocated")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
            self._free.append(p)
        if telemetry.enabled() and pages:
            _metrics.counter(
                "paddle_tpu_kv_pool_frees_total", "paged KV pool pages returned"
            ).inc(len(pages))
            _pool_gauge("used").set(self.used())
        if _rt.enabled() and pages:
            _rt.record_event("kv_pool", "free", rid=owner,
                             n=len(pages), used=self.used())

    def reset(self) -> None:
        self._free = list(range(self.num_blocks - 1, 0, -1))
        if telemetry.enabled():
            _pool_gauge("used").set(0)

    def note_fragmentation(self, active_tokens: int) -> None:
        """Internal fragmentation: allocated slots minus live tokens — the
        cost of fixed-size pages, the number paged allocation exists to keep
        bounded (vs. one contiguous max-length buffer per sequence)."""
        if telemetry.enabled():
            _metrics.gauge(
                "paddle_tpu_kv_pool_frag_slots",
                "allocated-but-unwritten KV slots (internal fragmentation)",
            ).set(self.used() * self.block_size - int(active_tokens))

    # ---- device-array plumbing ----
    def view(self, block_tables, seq_lens) -> PagedCacheView:
        """Eager-path view over the pool's current arrays: run the model
        with `cache=view`, then `adopt(view.k_pages, view.v_pages)`."""
        return PagedCacheView(
            self.k_pages, self.v_pages, block_tables, seq_lens, self.block_size
        )

    def adopt(self, k_pages: Sequence, v_pages: Sequence) -> None:
        """Install a step's updated page arrays back into the pool."""
        if len(k_pages) != self.num_layers or len(v_pages) != self.num_layers:
            raise ValueError("page-array layer count does not match the pool")
        self.k_pages = list(k_pages)
        self.v_pages = list(v_pages)

    def padded_table(self, pages: Sequence[int], n_cols: int):
        """One sequence's block-table row padded with the trash page."""
        row = list(pages)[:n_cols]
        return row + [TRASH_PAGE] * (n_cols - len(row))
