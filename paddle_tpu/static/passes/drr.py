"""DRR-style declarative pattern rewriting over ProgramGraph.

Reference parity: paddle/fluid/pir/drr (Declarative Rewrite Rule) — a
pattern is a source sub-graph spec plus a result builder; the framework
does the matching, safety analysis, and replacement. TPU-native: the
sub-graph is a list of `OpPat` specs over the recorded op list, matched
through ProgramGraph def-use chains; per-op and per-pattern `where`
predicates read the shape/dtype metadata harvested from the placeholder
Tensors (and may PROBE a recorded op's pure fn on tiny host arrays — the
recorded closure is the ground truth for baked-in attributes like a
matmul's transpose flags).

A match is only legal when every interior var (produced by a matched op,
not a declared root) is consumed exclusively inside the cluster and is not
a liveness root (fetch/grad/opt) — the replacement may then delete the
interior ops without changing any observable value.

The default replacement (`build_cluster_instr`) is a mini-replay of the
matched instrs' own recorded fns — bit-identical by construction, since
the compiled program inlines the exact same jax calls in the exact same
order. Passes that swap in a different kernel (the flash-attention
rewrite) supply their own builder and own numerics contract.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..analysis.graph import ProgramGraph
from ..program import OpInstr
from .pass_base import release_vars


class OpPat:
    """One op of a source pattern.

    kind:    op name or tuple of accepted names.
    ins:     symbols bound against the op's VAR inputs. `ordered=True`
             matches positionally over the var refs (matmul-like ops where
             operand position is semantics); `ordered=False` lets bound
             symbols sit at any position (add/multiply-like commutative
             ops).
    outs:    symbols bound against out_vars positionally; arity must match
             exactly.
    allow_extra_ins: unmatched trailing var inputs (weights, seeds) are
             legal and become externals of the replacement op.
    where:   optional predicate(program, graph, op, binding) -> bool.
    """

    __slots__ = ("kinds", "ins", "outs", "ordered", "allow_extra_ins", "where")

    def __init__(self, kind, ins, outs, ordered=True, allow_extra_ins=True,
                 where: Optional[Callable] = None):
        self.kinds = (kind,) if isinstance(kind, str) else tuple(kind)
        self.ins = list(ins)
        self.outs = list(outs)
        self.ordered = ordered
        self.allow_extra_ins = allow_extra_ins
        self.where = where


class Pattern:
    """A connected sub-DAG spec in dataflow order. `roots` are the output
    symbols that survive the rewrite (they must be produced by the LAST
    spec so the single replacement op can define them at the cluster's
    position without reordering any other op)."""

    def __init__(self, name: str, ops: Sequence[OpPat], roots: Sequence[str],
                 where: Optional[Callable] = None):
        self.name = name
        self.ops = list(ops)
        self.roots = list(roots)
        self.where = where  # (program, graph, binding, op_indices) -> bool
        produced = set()
        for j, spec in enumerate(self.ops):
            if j > 0 and not any(s in produced for s in spec.ins):
                raise ValueError(
                    f"pattern {name!r}: op #{j} is not connected to any "
                    f"earlier op's outputs — patterns must be dataflow-"
                    f"connected"
                )
            produced.update(spec.outs)
        last_outs = set(self.ops[-1].outs)
        bad = [r for r in self.roots if r not in last_outs]
        if bad:
            raise ValueError(
                f"pattern {name!r}: roots {bad} are not outputs of the last "
                f"op — replacement outputs must live at the cluster's end"
            )


class Match:
    __slots__ = ("pattern", "op_indices", "binding")

    def __init__(self, pattern, op_indices, binding):
        self.pattern = pattern
        self.op_indices = list(op_indices)  # in pattern-spec order
        self.binding = dict(binding)        # symbol -> vid

    def root_vids(self) -> List[int]:
        return [self.binding[s] for s in self.pattern.roots]

    def __repr__(self):
        ops = ", ".join(f"op#{i}" for i in self.op_indices)
        return f"Match({self.pattern.name}: {ops})"


# ---------------------------------------------------------------------------
# matching
# ---------------------------------------------------------------------------

def _match_op(spec: OpPat, program, graph, op_index, binding) -> Optional[dict]:
    op = program.ops[op_index]
    if op.name not in spec.kinds:
        return None
    var_refs = [r[1] for r in op.in_refs if r[0] == "var"]
    if len(var_refs) < len(spec.ins):
        return None
    if not spec.allow_extra_ins and len(var_refs) != len(spec.ins):
        return None
    nb = dict(binding)
    if spec.ordered:
        for sym, vid in zip(spec.ins, var_refs):
            if sym in nb:
                if nb[sym] != vid:
                    return None
            else:
                nb[sym] = vid
    else:
        remaining = list(var_refs)
        unbound = []
        for sym in spec.ins:
            if sym in nb:
                if nb[sym] in remaining:
                    remaining.remove(nb[sym])
                else:
                    return None
            else:
                unbound.append(sym)
        if len(remaining) < len(unbound):
            return None
        for sym, vid in zip(unbound, remaining):
            nb[sym] = vid
    if len(op.out_vars) != len(spec.outs):
        return None
    for sym, vid in zip(spec.outs, op.out_vars):
        if sym in nb and nb[sym] != vid:
            return None
        nb[sym] = vid
    if spec.where is not None and not spec.where(program, graph, op, nb):
        return None
    return nb


def _cluster_safe(program, graph: ProgramGraph, op_indices, root_vids) -> bool:
    matched = set(op_indices)
    roots = graph.roots()
    root_set = set(root_vids)
    for i in op_indices:
        for vid in program.ops[i].out_vars:
            if vid in root_set:
                continue
            if vid in roots:
                return False
            for site, si, _pos in graph.uses_of(vid):
                if site != "op" or si not in matched:
                    return False
    return True


def find_matches(program, graph: ProgramGraph, pattern: Pattern,
                 taken=None) -> List[Match]:
    """All non-overlapping matches of `pattern` against the current op
    list. `taken` (mutated) carries op indices already claimed by earlier
    patterns of the same pass run."""
    taken = taken if taken is not None else set()
    matches = []
    specs = pattern.ops

    def extend(j, binding, idxs):
        if j == len(specs):
            root_vids = [binding[s] for s in pattern.roots]
            if not _cluster_safe(program, graph, idxs, root_vids):
                return None
            if pattern.where is not None and not pattern.where(
                    program, graph, binding, list(idxs)):
                return None
            return Match(pattern, idxs, binding)
        spec = specs[j]
        # candidates: consumers of any already-bound input symbol's var
        cand = None
        for sym in spec.ins:
            vid = binding.get(sym)
            if vid is None:
                continue
            sites = {si for site, si, _ in graph.uses_of(vid) if site == "op"}
            cand = sites if cand is None else (cand & sites)
        if not cand:
            return None
        for ci in sorted(cand):
            if ci in taken or ci in idxs:
                continue
            nb = _match_op(spec, program, graph, ci, binding)
            if nb is None:
                continue
            m = extend(j + 1, nb, idxs + [ci])
            if m is not None:
                return m
        return None

    for i0 in range(len(program.ops)):
        if i0 in taken:
            continue
        b0 = _match_op(specs[0], program, graph, i0, {})
        if b0 is None:
            continue
        m = extend(1, b0, [i0])
        if m is not None:
            taken.update(m.op_indices)
            matches.append(m)
    return matches


# ---------------------------------------------------------------------------
# replacement
# ---------------------------------------------------------------------------

def external_refs(program, op_indices) -> Tuple[list, list]:
    """The cluster's inputs seen from outside: every matched in_ref whose
    var is not produced inside the cluster (deduplicated, first-occurrence
    order) plus every literal ref (one position each). Returns
    (refs, per-op arg plans) where a plan entry is ('env', vid) for an
    interior value or ('ext', pos) into the external arg list."""
    produced = set()
    for i in op_indices:
        produced.update(program.ops[i].out_vars)
    refs: list = []
    var_pos: Dict[int, int] = {}
    plans = []
    for i in op_indices:
        plan = []
        for ref in program.ops[i].in_refs:
            if ref[0] == "var" and ref[1] in produced:
                plan.append(("env", ref[1]))
            elif ref[0] == "var":
                pos = var_pos.get(ref[1])
                if pos is None:
                    pos = len(refs)
                    refs.append(ref)
                    var_pos[ref[1]] = pos
                plan.append(("ext", pos))
            else:
                plan.append(("ext", len(refs)))
                refs.append(ref)
        plans.append(plan)
    return refs, plans


def build_cluster_instr(program, match: Match, name: str) -> OpInstr:
    """The default DRR result: ONE op whose fn mini-replays the matched
    instrs' recorded fns over an interior env — the replacement computes
    the exact same jax calls in the exact same order (bit-identical), with
    the cluster collapsed to a single recorded op."""
    instrs = [program.ops[i] for i in match.op_indices]
    refs, plans = external_refs(program, match.op_indices)
    roots = match.root_vids()

    def fused_fn(*vals):
        env = {}
        for instr, plan in zip(instrs, plans):
            args = [env[key] if tag == "env" else vals[key] for tag, key in plan]
            out = instr.fn(*args, **instr.kwargs)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for vid, pos in zip(instr.out_vars, instr.out_positions):
                env[vid] = outs[pos]
        res = tuple(env[v] for v in roots)
        return res if len(res) > 1 else res[0]

    return OpInstr(name, fused_fn, refs, {}, list(roots),
                   list(range(len(roots))), len(roots))


def apply_matches(program, match_builders) -> int:
    """Replace each match's cluster with builder(program, match) — an
    OpInstr defining the match's root vids — inserted where the cluster's
    last op sat (all externals are defined earlier, all consumers read the
    root vids later, so no other op moves). `match_builders` is a list of
    (Match, builder) pairs whose matches must be non-overlapping and whose
    op indices refer to the CURRENT ops list — all replacements land in one
    compaction so no match invalidates another's indices. Interior vars'
    placeholder Tensors are released. Returns the number of ops removed."""
    if not match_builders:
        return 0
    removed_idx = set()
    repl_at: Dict[int, OpInstr] = {}
    interior_vids = []
    for m, builder in match_builders:
        instr = builder(program, m)
        roots = set(m.root_vids())
        if set(instr.out_vars) != roots:
            raise ValueError(
                f"pattern {m.pattern.name!r}: replacement defines "
                f"{instr.out_vars}, expected the match roots {sorted(roots)}"
            )
        removed_idx.update(m.op_indices)
        repl_at[max(m.op_indices)] = instr
        for i in m.op_indices:
            interior_vids.extend(
                v for v in program.ops[i].out_vars if v not in roots
            )
    new_ops = []
    for i, op in enumerate(program.ops):
        if i in repl_at:
            new_ops.append(repl_at[i])
        elif i in removed_idx:
            continue
        else:
            new_ops.append(op)
    n_removed = len(program.ops) - len(new_ops) + len(repl_at)
    program.ops = new_ops
    release_vars(program, interior_vids)
    program._compiled.clear()
    return n_removed
