"""paddle_tpu.Tensor — eager tensor wrapping a jax.Array.

Reference parity: the public Tensor (paddle/phi/api/include/tensor.h) +
eager autograd metadata (paddle/fluid/eager/autograd_meta.h) + python method
patching (python/paddle/base/dygraph/math_op_patch.py,
tensor_patch_methods.py). TPU-native design: storage IS a jax.Array (host or
TPU HBM, possibly sharded across a mesh — the DistTensor global view comes for
free), autograd metadata is (grad_node, out_index), and every method ends in a
traced-or-eager jax computation.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax import numpy as jnp

from ..framework import dtype as dtype_mod
from ..framework.device import Place
from . import state


class Tensor:
    __slots__ = (
        "_value",
        "stop_gradient",
        "grad",
        "_grad_node",
        "_out_index",
        "name",
        "persistable",
        "_backward_hooks",
        "_dist_attr",
        "_dynamic_dims",
        "__weakref__",
    )

    def __init__(self, value, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(value, Tensor):
            value = value._value
        if not isinstance(value, (jax.Array, jax.core.Tracer)):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        self.name = name
        self.persistable = False
        self._backward_hooks = []
        self._dist_attr = None  # (ProcessMesh, placements) for DistTensor
        self._dynamic_dims = None  # static.data placeholders: -1 dim indices
        state.record_create(self)

    # ---- raw value access (trace-recorded) ----
    @property
    def value(self):
        state.record_read(self)
        return self._value

    def _raw(self):
        """Value access WITHOUT trace recording (engine internals)."""
        return self._value

    def set_value(self, value):
        """In-place value replacement (paddle Tensor.set_value). Detaches.
        record_write fires BEFORE mutation so program capture can snapshot
        the pre-write value (needed to undo trace-time side effects)."""
        if isinstance(value, Tensor):
            value = value._value
        elif not isinstance(value, (jax.Array, jax.core.Tracer)):
            value = jnp.asarray(value, dtype=self._value.dtype)
        state.record_write(self)
        self._value = value
        self._grad_node = None
        self._out_index = 0
        return self

    def _replace_value(self, value):
        """Functional-update write used by optimizers / in-place ops: keeps
        autograd detachment semantics of set_value but is the designated
        mutation point recorded by to_static capture."""
        state.record_write(self)
        self._value = value
        self._grad_node = None
        self._out_index = 0
        return self

    def _become(self, other: "Tensor"):
        """Adopt another tensor's value + autograd node (in-place op result).

        stop_gradient only flips to False when the result carries a grad node;
        an in-place update under no_grad() must NOT freeze a trainable param.
        """
        state.record_write(self)
        self._value = other._value
        self._grad_node = other._grad_node
        self._out_index = other._out_index
        if other._grad_node is not None:
            self.stop_gradient = other.stop_gradient
        return self

    # ---- metadata ----
    @property
    def shape(self):
        dyn = getattr(self, "_dynamic_dims", None)
        if dyn:
            return _DynShape(self._value.shape, dyn)
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        devs = getattr(self._value, "devices", None)
        if devs is None or isinstance(self._value, jax.core.Tracer):
            from ..framework.device import _get_current_place

            return _get_current_place()
        return Place(sorted(self._value.devices(), key=lambda d: d.id)[0])

    @property
    def is_leaf(self):
        return self._grad_node is None

    @property
    def T(self):
        from ..ops import manipulation

        return manipulation.transpose(self, list(range(self.ndim))[::-1])

    @property
    def mT(self):
        from ..ops import manipulation

        perm = list(range(self.ndim))
        perm[-1], perm[-2] = perm[-2], perm[-1]
        return manipulation.transpose(self, perm)

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    # ---- host interop ----
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, **kw):
        return self._value.__dlpack__(**kw)

    # ---- autograd ----
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        from . import autograd_engine

        autograd_engine.run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        state.record_grad_write(self)
        self.grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        """Gradient hook on a leaf tensor (paddle Tensor.register_hook).
        Fires when the engine accumulates into this tensor."""
        self._backward_hooks.append(hook)

        class _Removable:
            def __init__(self, hooks, h):
                self._hooks, self._h = hooks, h

            def remove(self):
                if self._h in self._hooks:
                    self._hooks.remove(self._h)

        return _Removable(self._backward_hooks, hook)

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True)
        return t

    def detach_(self):
        self._grad_node = None
        self._out_index = 0
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from .apply import apply

        return apply("clone", lambda x: x + jnp.zeros((), x.dtype), self)

    # ---- device/dtype movement ----
    def to(self, *args, **kwargs):
        """paddle Tensor.to: accepts device str/Place, dtype, or both."""
        device = kwargs.get("device")
        dtype = kwargs.get("dtype")
        blocking = kwargs.get("blocking", None)
        for a in args:
            if isinstance(a, (Place,)) or (isinstance(a, str) and (":" in a or a in ("cpu", "tpu", "gpu", "xpu"))):
                device = a
            elif isinstance(a, bool):
                blocking = a
            else:
                dtype = a
        out = self
        if dtype is not None:
            out = out.astype(dtype)
        if device is not None:
            from ..framework.device import _parse_device

            place = _parse_device(device) if isinstance(device, str) else device
            val = jax.device_put(out._value, place.jax_device)
            t = Tensor(val, stop_gradient=out.stop_gradient)
            t._grad_node, t._out_index = out._grad_node, out._out_index
            out = t
        if blocking:
            jax.block_until_ready(out._value)
        return out

    def cpu(self):
        return self.to(device="cpu")

    def cuda(self, device_id=0, blocking=True):
        return self.to(device=f"tpu:{device_id}")  # gpu requests map to the accelerator

    def tpu(self, device_id=0):
        return self.to(device=f"tpu:{device_id}")

    def pin_memory(self):
        return self

    def astype(self, dtype):
        from .apply import apply

        d = dtype_mod.convert_dtype(dtype)
        return apply("cast", lambda x: x.astype(d), self)

    def cast(self, dtype):
        return self.astype(dtype)

    # ---- python protocol ----
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._value.shape[0]

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        po = _print_options
        try:
            data = np.array2string(
                self.numpy(),
                precision=po["precision"],
                separator=", ",
                threshold=po["threshold"],
                edgeitems=po["edgeitems"],
                max_line_width=po["linewidth"],
                suppress_small=not po["sci_mode"] if po["sci_mode"] is not None else None,
            )
        except Exception:
            data = f"<traced {self._value}>"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"place={self.place!r}{grad_info},\n       {data})"
        )

    def __bool__(self):
        if self.size != 1:
            raise ValueError("truth value of a multi-element Tensor is ambiguous")
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __index__(self):
        return int(self.numpy())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    def __format__(self, spec):
        if self.size == 1:
            return format(self.item(), spec)
        return repr(self)

    # ---- indexing ----
    def _normalize_index(self, idx):
        def conv(i):
            if isinstance(i, Tensor):
                return i._value
            if isinstance(i, (list, np.ndarray)):
                return jnp.asarray(i)
            return i

        if isinstance(idx, tuple):
            return tuple(conv(i) for i in idx)
        return conv(idx)

    def __getitem__(self, idx):
        from .apply import apply

        idx = self._normalize_index(idx)
        return apply("getitem", lambda x: x[idx], self)

    def __setitem__(self, idx, value):
        from .apply import apply

        idx = self._normalize_index(idx)
        if isinstance(value, Tensor):
            new = apply(
                "setitem",
                lambda x, v: x.at[idx].set(v.astype(x.dtype) if v.dtype != x.dtype else v),
                self,
                value,
            )
        else:
            new = apply("setitem", lambda x: x.at[idx].set(value), self)
        self._become(new)

    # dunder arithmetic is patched in ops/_patch.py (math_op_patch analog)


def _ensure_tensor(x, dtype=None) -> Tensor:
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x, dtype=dtype))


class _DynShape(list):
    """Shape of a static.data placeholder with dynamic (-1) dims: reading a
    dynamic dim at Python level would bake the dry-run size into the captured
    Program (silent wrong answers for -1-batch programs) — hard-error instead
    (VERDICT r1 weak #7). Pass -1 to reshape/view, or use paddle.shape() for
    an in-graph shape read."""

    def __init__(self, dims, dynamic):
        super().__init__(int(d) for d in dims)
        self._dynamic = set(dynamic)

    def _check(self, i):
        n = len(self)
        for idx in (self._dynamic if i is None else [i]):
            k = idx % n if isinstance(idx, int) else idx
            if i is None or k in self._dynamic:
                raise RuntimeError(
                    f"static Program: dim {sorted(self._dynamic)} of this "
                    "placeholder is dynamic (-1); reading it in Python would "
                    "bake the dry-run value into the captured program. Use -1 "
                    "in reshape/view or paddle.shape() for an in-graph read."
                )

    def __getitem__(self, i):
        if isinstance(i, int):
            self._check(i)
        elif isinstance(i, slice):
            idxs = range(*i.indices(len(self)))
            for k in idxs:
                self._check(k)
        return super().__getitem__(i)

    def __iter__(self):
        self._check(None) if self._dynamic else None
        return super().__iter__()

    def __eq__(self, other):  # comparisons force a full read
        if self._dynamic:
            self._check(None)
        return super().__eq__(other)

    def __ne__(self, other):
        if self._dynamic:
            self._check(None)
        return super().__ne__(other)

    def __hash__(self):
        return id(self)


# paddle.set_printoptions (reference python/paddle/tensor/to_string.py)
_print_options = {
    "precision": 6,
    "threshold": 60,
    "edgeitems": 3,
    "sci_mode": None,
    "linewidth": 80,
}


def set_printoptions(precision=None, threshold=None, edgeitems=None, sci_mode=None, linewidth=None):
    """Configure Tensor repr formatting (tensor/to_string.py:36)."""
    if precision is not None:
        _print_options["precision"] = int(precision)
    if threshold is not None:
        _print_options["threshold"] = int(threshold)
    if edgeitems is not None:
        _print_options["edgeitems"] = int(edgeitems)
    if sci_mode is not None:
        _print_options["sci_mode"] = bool(sci_mode)
    if linewidth is not None:
        _print_options["linewidth"] = int(linewidth)
