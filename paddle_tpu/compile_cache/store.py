"""Persistent compiled-executable store: the cache half.

Executables are serialized through jax's AOT serialization surface
(`jax.experimental.serialize_executable`) and persisted under an atomic,
CRC-verified directory layout that reuses the PR 2 checkpoint torn-write
discipline:

    <root>/<fingerprint>-<topology_key>/
        payload.bin   pickled (xla payload, in_tree, out_tree)
        meta.json     fingerprint, topology meta, jax version, origin,
                      name, signature, payload crc32 + byte count
        COMPLETE      commit marker (written LAST, fsync'd, then the
                      whole entry dir is atomically renamed into place)

Readers trust nothing: an entry without COMPLETE, with unparsable meta,
with a CRC mismatch, or recorded under a different topology/jax version is
rejected — counted in `paddle_tpu_compile_cache_errors_total{reason}` and
treated as a miss (fresh compile), never a crash or a silently wrong
executable. The read path carries the deterministic-chaos site
``compile_cache.read`` so the FaultPlan suite can prove that contract.

`gc(max_bytes)` evicts least-recently-used entries (restore touches the
COMPLETE marker's mtime) until the store fits the budget — the same
newest-wins pruning stance as checkpoint retention.
"""
from __future__ import annotations

import json
import os
import pickle
import shutil
import threading
import time
import zlib
from typing import List, Optional, Tuple

from .. import telemetry as _tm
from . import fingerprint as _fp

__all__ = [
    "CompileCacheStore",
    "serialization_available",
    "configure",
    "active_store",
    "store_dir",
    "ENV_DIR",
]

ENV_DIR = "PADDLE_TPU_COMPILE_CACHE_DIR"
COMPLETE_MARKER = "COMPLETE"
PAYLOAD = "payload.bin"
META = "meta.json"


def serialization_available() -> bool:
    try:
        from jax.experimental import serialize_executable  # noqa: F401

        return True
    except Exception:
        return False


def _err_counter(reason: str):
    return _tm.counter(
        "paddle_tpu_compile_cache_errors_total",
        "persistent compile-cache entries rejected on read (fell back to "
        "a fresh compile) or failed writes",
        ("reason",),
    ).labels(reason=reason)


_READ_REASONS = ("torn_entry", "bad_meta", "topology_mismatch",
                 "crc_mismatch", "read_failed")


def _count_error(reason: str) -> None:
    if _tm.enabled():
        try:
            _err_counter(reason).inc()
        except Exception:
            pass
    from ..telemetry import timeline as _tl

    # read-path rejections carry the chaos site label so an injected
    # compile_cache.read corruption is matched to the error it caused
    labels = ({"site": "compile_cache.read", "reason": reason}
              if reason in _READ_REASONS else {"reason": reason})
    _tl.emit("compile_cache", "store.error", severity="warn", labels=labels)


def _crc32_bytes(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_file(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


class CompileCacheStore:
    """One on-disk compile cache rooted at `root` (created lazily)."""

    def __init__(self, root: str):
        self.root = str(root)

    # ---- layout helpers ----
    def _entry_dir(self, key: str) -> str:
        return os.path.join(self.root, key)

    def entry_keys(self) -> List[str]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(
            n for n in names
            if not n.startswith(".") and os.path.isdir(self._entry_dir(n))
        )

    # ---- write ----
    def put(self, key: str, compiled, meta: dict) -> bool:
        """Serialize + commit one executable. Returns False (counted) on
        any failure — persistence is an optimization, never a hard
        dependency of the compile path."""
        if not serialization_available():
            _count_error("serialize_unavailable")
            return False
        final = self._entry_dir(key)
        if os.path.exists(os.path.join(final, COMPLETE_MARKER)):
            return True  # another signature-identical compile already won
        tmp = os.path.join(self.root, f".tmp-{key}-{os.getpid()}")
        try:
            from jax.experimental.serialize_executable import serialize

            payload, in_tree, out_tree = serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree),
                                protocol=pickle.HIGHEST_PROTOCOL)
            full_meta = dict(meta)
            full_meta.setdefault("created_at", time.time())
            full_meta["payload_bytes"] = len(blob)
            full_meta["payload_crc32"] = _crc32_bytes(blob)
            os.makedirs(tmp, exist_ok=True)
            _write_file(os.path.join(tmp, PAYLOAD), blob)
            _write_file(
                os.path.join(tmp, META),
                json.dumps(full_meta, sort_keys=True, indent=1).encode(),
            )
            # commit protocol: marker last, fsync entry + parent, atomic
            # rename — a torn write can only ever produce a marker-less
            # (ignored) or invisible entry, never a half-read one
            _write_file(os.path.join(tmp, COMPLETE_MARKER), b"")
            _fsync_dir(tmp)
            try:
                os.replace(tmp, final)
            except OSError:
                # a concurrent writer landed the same key first: keep theirs
                shutil.rmtree(tmp, ignore_errors=True)
                return os.path.exists(os.path.join(final, COMPLETE_MARKER))
            _fsync_dir(self.root)
            return True
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            _count_error("write_failed")
            return False

    # ---- read ----
    def _load_meta(self, key: str) -> Optional[dict]:
        try:
            with open(os.path.join(self._entry_dir(key), META)) as f:
                return json.load(f)
        except Exception:
            return None

    def get(self, key: str, expect_meta: Optional[dict] = None):
        """-> (compiled, meta) or None. Verifies the commit marker, the
        payload CRC, and (when `expect_meta` is given) the recorded
        topology/jax version before deserializing. All failures are
        counted misses, never exceptions."""
        d = self._entry_dir(key)
        try:
            from ..distributed.resilience import fault_injection as _fi

            _fi.fault_point("compile_cache.read", key=key)
            if not os.path.exists(os.path.join(d, COMPLETE_MARKER)):
                if os.path.isdir(d):
                    _count_error("torn_entry")
                return None
            meta = self._load_meta(key)
            if meta is None:
                _count_error("bad_meta")
                return None
            if expect_meta is not None:
                for k in ("jax_version", "platform", "device_count",
                          "mesh_shape", "mesh_devices"):
                    if meta.get("topology", {}).get(k) != expect_meta.get(k):
                        _count_error("topology_mismatch")
                        return None
            with open(os.path.join(d, PAYLOAD), "rb") as f:
                blob = f.read()
            if len(blob) != meta.get("payload_bytes") or \
                    _crc32_bytes(blob) != meta.get("payload_crc32"):
                _count_error("crc_mismatch")
                return None
            if not serialization_available():
                _count_error("serialize_unavailable")
                return None
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            payload, in_tree, out_tree = pickle.loads(blob)
            compiled = deserialize_and_load(payload, in_tree, out_tree)
            # restore = a use: LRU timestamp for gc()
            try:
                os.utime(os.path.join(d, COMPLETE_MARKER))
            except OSError:
                pass
            return compiled, meta
        except Exception:
            _count_error("read_failed")
            return None

    # ---- maintenance (tools/compile_cache.py) ----
    def entry_bytes(self, key: str) -> int:
        total = 0
        d = self._entry_dir(key)
        for name in (PAYLOAD, META, COMPLETE_MARKER):
            try:
                total += os.path.getsize(os.path.join(d, name))
            except OSError:
                pass
        return total

    def verify_entry(self, key: str) -> Tuple[bool, str]:
        """(ok, reason) without deserializing (cheap CRC walk)."""
        d = self._entry_dir(key)
        if not os.path.exists(os.path.join(d, COMPLETE_MARKER)):
            return False, "missing_complete_marker"
        meta = self._load_meta(key)
        if meta is None:
            return False, "bad_meta"
        try:
            with open(os.path.join(d, PAYLOAD), "rb") as f:
                blob = f.read()
        except OSError:
            return False, "missing_payload"
        if len(blob) != meta.get("payload_bytes"):
            return False, "truncated_payload"
        if _crc32_bytes(blob) != meta.get("payload_crc32"):
            return False, "crc_mismatch"
        return True, "ok"

    def stats(self) -> dict:
        keys = self.entry_keys()
        by_origin: dict = {}
        total = 0
        corrupt = 0
        for k in keys:
            nb = self.entry_bytes(k)
            total += nb
            ok, _ = self.verify_entry(k)
            if not ok:
                corrupt += 1
                continue
            meta = self._load_meta(k) or {}
            o = by_origin.setdefault(
                str(meta.get("origin", "unknown")), {"entries": 0, "bytes": 0}
            )
            o["entries"] += 1
            o["bytes"] += nb
        return {
            "root": self.root,
            "entries": len(keys),
            "bytes": total,
            "corrupt": corrupt,
            "by_origin": by_origin,
            "serialization_available": serialization_available(),
        }

    def verify(self) -> dict:
        results = {}
        for k in self.entry_keys():
            ok, reason = self.verify_entry(k)
            results[k] = reason if not ok else "ok"
        bad = {k: r for k, r in results.items() if r != "ok"}
        return {"entries": len(results), "corrupt": len(bad),
                "failures": bad}

    def remove(self, key: str) -> None:
        shutil.rmtree(self._entry_dir(key), ignore_errors=True)

    def gc(self, max_bytes: int) -> dict:
        """Evict LRU entries (corrupt ones first) until total <= max_bytes."""
        keys = self.entry_keys()
        removed = []
        # corrupt entries are dead weight at any budget
        for k in list(keys):
            ok, reason = self.verify_entry(k)
            if not ok:
                self.remove(k)
                removed.append({"key": k, "reason": reason})
                keys.remove(k)

        def _mtime(k):
            try:
                return os.path.getmtime(
                    os.path.join(self._entry_dir(k), COMPLETE_MARKER))
            except OSError:
                return 0.0

        sized = sorted(((k, self.entry_bytes(k), _mtime(k)) for k in keys),
                       key=lambda t: t[2])
        total = sum(nb for _, nb, _ in sized)
        for k, nb, _ in sized:
            if total <= max_bytes:
                break
            self.remove(k)
            removed.append({"key": k, "reason": "lru"})
            total -= nb
        return {"removed": removed, "bytes": total,
                "max_bytes": int(max_bytes)}


# ---------------------------------------------------------------------------
# process-wide active store + in-process executable sharing
# ---------------------------------------------------------------------------

_active: dict = {"store": None, "configured": False}
_shared_lock = threading.Lock()
_MAX_SHARED = 256
_shared: "dict[str, object]" = {}


def configure(root: Optional[str]) -> Optional[CompileCacheStore]:
    """Point the process at a persistent cache directory (None disables).
    The env var PADDLE_TPU_COMPILE_CACHE_DIR configures it implicitly on
    first use — that is how the elastic relaunch path ships the cache
    ahead to restarted workers."""
    _active["configured"] = True
    _active["store"] = CompileCacheStore(root) if root else None
    return _active["store"]


def active_store() -> Optional[CompileCacheStore]:
    if not _active["configured"]:
        root = os.environ.get(ENV_DIR)
        _active["store"] = CompileCacheStore(root) if root else None
        _active["configured"] = True
    return _active["store"]


def store_dir() -> Optional[str]:
    st = active_store()
    return st.root if st is not None else None


def shared_get(key: str):
    """In-process executable registry: fleet replicas with identical
    signatures reuse one compiled object instead of each paying the
    compile (counted `outcome=shared` by the caller)."""
    with _shared_lock:
        return _shared.get(key)


def shared_put(key: str, compiled) -> None:
    with _shared_lock:
        if key not in _shared and len(_shared) >= _MAX_SHARED:
            _shared.pop(next(iter(_shared)))  # FIFO bound; sharing is a hint
        _shared[key] = compiled


def clear_shared() -> None:
    with _shared_lock:
        _shared.clear()


def make_meta(origin: str, name: str, fingerprint: str,
              signature: Optional[str] = None, mesh=None,
              extra: Optional[dict] = None) -> dict:
    """Entry meta: the key inputs recorded verbatim so `get()` can
    re-verify and tools can report by origin."""
    meta = {
        "origin": str(origin),
        "name": str(name),
        "fingerprint": fingerprint,
        "signature": signature,
        "topology": _fp.topology_meta(mesh),
    }
    if extra:
        meta.update(extra)
    return meta
