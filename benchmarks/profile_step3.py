"""A/B: is the Pallas flash-attention kernel the backward-time sink at
seq=128? Same model, two compiled step variants, one process.

Run: python benchmarks/profile_step3.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import ErnieForMaskedLM, ErnieModel
from paddle_tpu.ops import pallas as pallas_ops


def slope(fn, n1=8, n2=24):
    fn(3)
    t1 = fn(n1)
    t2 = fn(n2)
    return (t2 - t1) / (n2 - n1)


def main():
    batch, seq = 64, 128
    paddle.seed(0)
    model = ErnieForMaskedLM(
        ErnieModel(
            vocab_size=40000, hidden_size=768, num_hidden_layers=12,
            num_attention_heads=12, intermediate_size=3072,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        )
    )
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(), weight_decay=0.01)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 40000, (batch, seq)).astype(np.int64))
    labels = paddle.to_tensor(rng.randint(0, 40000, (batch, seq)).astype(np.int64))

    def make_step():
        @paddle.jit.to_static
        def full_step(ids, labels):
            with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
                loss, _ = model(ids, labels=labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss
        return full_step

    def timed(stepfn):
        def run(n):
            t0 = time.perf_counter()
            for _ in range(n):
                loss = stepfn(ids, labels)
            float(loss.numpy())
            return time.perf_counter() - t0
        return run

    step_flash = make_step()
    s_flash = slope(timed(step_flash))
    print(f"flash pallas:  {s_flash*1000:.2f} ms/step")

    orig = pallas_ops.flash_attention_usable
    pallas_ops.flash_attention_usable = lambda *a, **k: False
    try:
        step_ref = make_step()
        s_ref = slope(timed(step_ref))
        print(f"xla sdpa ref:  {s_ref*1000:.2f} ms/step")
    finally:
        pallas_ops.flash_attention_usable = orig

    s_flash2 = slope(timed(step_flash))
    print(f"flash again (drift): {s_flash2*1000:.2f} ms/step")


if __name__ == "__main__":
    main()
