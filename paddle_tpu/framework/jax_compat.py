"""Version-compat aliases for jax APIs that moved between releases.

The framework is written against current jax (the TPU driver image), but
CI-style CPU environments may carry an older release where several APIs
live under their pre-promotion names:

  - ``jax.shard_map``            <- ``jax.experimental.shard_map.shard_map``
  - ``jax.enable_x64``           <- ``jax.experimental.enable_x64``
  - ``pltpu.CompilerParams``     <- ``pltpu.TPUCompilerParams``

Import the name from here instead of guessing the spelling at each call
site; each alias resolves to the new name when present and falls back to
the old one. (Before round 6 these spellings collection-errored the whole
flash/ring/pipeline test files on older-jax environments.)
"""
from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax < 0.5: experimental namespace, and check_vma was check_rep
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _experimental_shard_map(f, *args, **kwargs)

if hasattr(jax, "enable_x64"):
    enable_x64 = jax.enable_x64
else:
    from jax.experimental import enable_x64  # noqa: F401


def tpu_compiler_params():
    """The Pallas TPU CompilerParams class under either name."""
    from jax.experimental.pallas import tpu as pltpu

    return getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
