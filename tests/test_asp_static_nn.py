"""incubate.asp 2:4 sparsity + static.nn layer builders."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.incubate import asp


def test_create_and_check_masks():
    w = np.random.RandomState(0).randn(8, 16).astype("float32")
    mask = asp.create_mask(w)
    assert mask.shape == w.shape and mask.reshape(-1, 4).sum(1).max() == 2
    pruned = w * mask
    assert asp.check_mask_1d(pruned)
    assert abs(asp.calculate_density(pruned) - 0.5) < 1e-6
    assert not asp.check_mask_1d(w)  # dense fails the check


def test_prune_model_and_decorated_optimizer_keeps_sparsity():
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(), paddle.nn.Linear(32, 4))
    ratios = asp.prune_model(net)
    assert ratios  # some weights pruned
    w0 = net[0].weight.numpy()
    assert asp.check_mask_1d(w0)
    opt = asp.decorate(paddle.optimizer.SGD(0.1, parameters=net.parameters()))
    x = paddle.to_tensor(np.random.RandomState(1).randn(4, 16).astype("float32"))
    for _ in range(3):
        net(x).sum().backward()
        opt.step()
        opt.clear_grad()
    # sparsity pattern survives optimizer steps
    assert asp.check_mask_1d(net[0].weight.numpy())
    # and weights did train (nonzeros changed)
    assert not np.allclose(net[0].weight.numpy(), w0)


def test_check_mask_2d():
    m = np.zeros((4, 4), "float32")
    m[0, 0] = m[1, 1] = 1.0
    assert asp.check_mask_2d(m)
    m[2, 0] = m[3, 0] = m[0, 1] = 1.0  # column 0 now has 3 nonzeros
    assert not asp.check_mask_2d(m)


def test_static_nn_builders():
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [-1, 8], "float32")
        h = static.nn.fc(x, 16, activation="relu")
        ids = static.data("ids", [-1, 4], "int64")
        emb = static.nn.embedding(ids, size=[100, 8])
        img = static.data("img", [2, 3, 8, 8], "float32")
        bn = static.nn.batch_norm(static.nn.conv2d(img, 4, 3, padding=1), is_test=True)
    exe = static.Executor()
    out, e, b = exe.run(
        main,
        feed={
            "x": np.ones((2, 8), "float32"),
            "ids": np.zeros((2, 4), "int64"),
            "img": np.zeros((2, 3, 8, 8), "float32"),
        },
        fetch_list=[h, emb, bn],
    )
    assert out.shape == (2, 16) and (out >= 0).all()
    assert e.shape == (2, 4, 8)
    assert b.shape == (2, 4, 8, 8)
