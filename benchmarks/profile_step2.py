"""Step decomposition round 2: where do the ms go inside the program?

One model, one process. Measures, back-to-back:
  A. peak (16k x 16k chained bf16 matmul)
  S. achieved TFLOP/s for the model's ACTUAL matmul shapes (the shape-
     limited ceiling the MFU metric is fighting)
  1. fwd only (no_grad) slope
  2. fwd+bwd, grads kept live (not cleared -> backward can't be DCE'd)
  3. full step (fwd+bwd+AdamW)
  4. full step, batch 128 (same model, retraced)

Run: python benchmarks/profile_step2.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models import ErnieForMaskedLM, ErnieModel


def slope(fn, n1=8, n2=24):
    fn(3)
    t1 = fn(n1)
    t2 = fn(n2)
    return (t2 - t1) / (n2 - n1)


def chain_rate(m, k, n, iters=30):
    """Achieved TFLOP/s for an (m,k)@(k,n) bf16 matmul, chained in one jit."""
    a = jnp.asarray(np.random.randn(m, k), jnp.bfloat16)
    b = jnp.asarray(np.random.randn(k, n) * 0.01, jnp.bfloat16)

    @jax.jit
    def chain(a, b):
        def body(i, acc):
            c = a @ b          # (m, n)
            return acc + jnp.sum(c[:1, :1].astype(jnp.float32)) * 1e-9
        return jax.lax.fori_loop(0, iters, body, 0.0)

    float(chain(a, b))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(chain(a, b))
        best = min(best, time.perf_counter() - t0)
    return 2 * m * k * n * iters / best


def main():
    from bench import _measured_peak_flops
    peak = _measured_peak_flops()
    print(f"A. peak (16k cube): {peak/1e12:.1f} TF/s")

    # model matmul shapes at batch 64 x seq 128 (tokens = 8192)
    T = 8192
    for (m, k, n, tag) in [
        (T, 768, 768, "qkv/proj"),
        (T, 768, 3072, "ffn up"),
        (T, 3072, 768, "ffn down"),
        (T, 768, 40000, "lm head"),
    ]:
        r = chain_rate(m, k, n)
        print(f"S. ({m},{k})@({k},{n}) {tag}: {r/1e12:.1f} TF/s ({r/peak*100:.0f}% of peak)")

    batch, seq = 64, 128
    paddle.seed(0)
    model = ErnieForMaskedLM(
        ErnieModel(
            vocab_size=40000, hidden_size=768, num_hidden_layers=12,
            num_attention_heads=12, intermediate_size=3072,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        )
    )
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(), weight_decay=0.01)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 40000, (batch, seq)).astype(np.int64))
    labels = paddle.to_tensor(rng.randint(0, 40000, (batch, seq)).astype(np.int64))

    n_params = sum(p.size for p in model.parameters())
    pos = model.ernie.embeddings.position_embeddings.weight.size
    tok = model.ernie.embeddings.token_type_embeddings.weight.size
    fpt = 6 * (n_params - pos - tok)

    def timed(stepfn, i, l):
        def run(n):
            t0 = time.perf_counter()
            for _ in range(n):
                loss = stepfn(i, l)
            float(loss.numpy())
            return time.perf_counter() - t0
        return run

    @paddle.jit.to_static
    def fwd_only(ids, labels):
        with paddle.no_grad(), paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            loss, _ = model(ids, labels=labels)
        return loss

    s1 = slope(timed(fwd_only, ids, labels))
    print(f"1. fwd only: {s1*1000:.2f} ms (bound ~{fpt*batch*seq/3/peak*1000:.1f})")

    @paddle.jit.to_static
    def fwd_bwd(ids, labels):
        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            loss, _ = model(ids, labels=labels)
        loss.backward()
        return loss

    # grads accumulate across steps -> backward output is live every step
    s2 = slope(timed(fwd_bwd, ids, labels))
    for p in model.parameters():
        p.clear_gradient()
    print(f"2. fwd+bwd (grads live): {s2*1000:.2f} ms (bound ~{fpt*batch*seq/peak*1000:.1f})")

    @paddle.jit.to_static
    def full_step(ids, labels):
        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            loss, _ = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    s3 = slope(timed(full_step, ids, labels))
    print(f"3. full step: {s3*1000:.2f} ms  (MFU {fpt*batch*seq/s3/peak:.3f})")

    # batch 128: same model/opt, new inputs -> retrace
    ids2 = paddle.to_tensor(rng.randint(0, 40000, (128, seq)).astype(np.int64))
    labels2 = paddle.to_tensor(rng.randint(0, 40000, (128, seq)).astype(np.int64))
    s4 = slope(timed(full_step, ids2, labels2), n1=5, n2=13)
    print(f"4. full step batch=128: {s4*1000:.2f} ms  (MFU {fpt*128*seq/s4/peak:.3f})")

    s3b = slope(timed(full_step, ids, labels))
    print(f"3'. full step batch=64 again (drift): {s3b*1000:.2f} ms")


if __name__ == "__main__":
    main()
