"""paddle.device.xpu (reference: python/paddle/device/xpu/__init__.py).
XPU is not part of the TPU build; synchronize exists and raises like a
paddle build without XPU support."""


def synchronize(device=None):
    raise RuntimeError("synchronize for XPU: not compiled with XPU (TPU build)")


__all__ = ['synchronize']
