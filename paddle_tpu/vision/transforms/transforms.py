"""Transform classes (reference: python/paddle/vision/transforms/transforms.py)."""
from __future__ import annotations

import numbers
import random

import numpy as np

from . import functional as F


class BaseTransform:
    """Reference BaseTransform: callable on img or (img, target) pairs."""

    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data

    def __repr__(self):
        return "Compose(" + ", ".join(repr(t) for t in self.transforms) + ")"


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else img


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = F.pad(arr, (0, 0, max(0, tw - w), max(0, th - h)), self.fill, self.padding_mode)
            arr = np.asarray(img)
            h, w = arr.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(arr, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3), interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * random.uniform(*self.scale)
            aspect = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                patch = F.crop(arr, top, left, ch, cw)
                return F.resize(patch, self.size, self.interpolation)
        return F.resize(F.center_crop(arr, min(h, w)), self.size, self.interpolation)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False, center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, center=self.center, fill=self.fill)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.brightness = brightness
        self.contrast = contrast
        self.hue = hue
        self.saturation = saturation

    def _apply_image(self, img):
        if self.brightness:
            img = F.adjust_brightness(img, random.uniform(max(0, 1 - self.brightness), 1 + self.brightness))
        if self.contrast:
            img = F.adjust_contrast(img, random.uniform(max(0, 1 - self.contrast), 1 + self.contrast))
        if self.saturation:
            img = F.adjust_saturation(img, random.uniform(max(0, 1 - self.saturation), 1 + self.saturation))
        if self.hue:
            img = F.adjust_hue(img, random.uniform(-self.hue, self.hue))
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3), value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        arr = np.array(img, copy=True)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[-1]
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        area = h * w
        for _ in range(10):
            target = area * random.uniform(*self.scale)
            aspect = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            eh, ew = int(round(np.sqrt(target / aspect))), int(round(np.sqrt(target * aspect)))
            if eh < h and ew < w:
                top, left = random.randint(0, h - eh), random.randint(0, w - ew)
                if chw:
                    arr[:, top : top + eh, left : left + ew] = self.value
                else:
                    arr[top : top + eh, left : left + ew] = self.value
                break
        return arr


# ---------------------------------------------------------------------------
# r3 transform completion (vision namespace parity audit)
# ---------------------------------------------------------------------------

class BrightnessTransform(BaseTransform):
    """Random brightness in [max(0, 1-value), 1+value] (reference
    transforms.BrightnessTransform)."""

    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("brightness value should be non-negative")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value should be non-negative")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("saturation value should be non-negative")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_saturation(img, factor)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(-self.value, self.value)
        return F.adjust_hue(img, factor)


class RandomAffine(BaseTransform):
    """Random rotation + translation + scale + shear (reference
    transforms.RandomAffine)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        arr = F._np(img)
        h, w = arr.shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        scale = np.random.uniform(*self.scale) if self.scale is not None else 1.0
        if self.shear is not None:
            sh = self.shear if isinstance(self.shear, (list, tuple)) else (-self.shear, self.shear)
            if len(sh) == 2:
                shear = (np.random.uniform(sh[0], sh[1]), 0.0)
            else:
                shear = (np.random.uniform(sh[0], sh[1]), np.random.uniform(sh[2], sh[3]))
        else:
            shear = (0.0, 0.0)
        return F.affine(img, angle, (tx, ty), scale, shear,
                        interpolation=self.interpolation, center=self.center, fill=self.fill)


class RandomPerspective(BaseTransform):
    """Random projective distortion (reference transforms.RandomPerspective)."""

    def __init__(self, prob=0.5, distortion_scale=0.5, interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _get_params(self, w, h):
        d = self.distortion_scale
        hd = int(d * h / 2)
        wd = int(d * w / 2)
        start = [[0, 0], [w - 1, 0], [w - 1, h - 1], [0, h - 1]]
        end = [
            [np.random.randint(0, wd + 1), np.random.randint(0, hd + 1)],
            [w - 1 - np.random.randint(0, wd + 1), np.random.randint(0, hd + 1)],
            [w - 1 - np.random.randint(0, wd + 1), h - 1 - np.random.randint(0, hd + 1)],
            [np.random.randint(0, wd + 1), h - 1 - np.random.randint(0, hd + 1)],
        ]
        return start, end

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = F._np(img)
        h, w = arr.shape[:2]
        start, end = self._get_params(w, h)
        return F.perspective(img, start, end, interpolation=self.interpolation, fill=self.fill)
