"""Sequence parallelism (Megatron-SP) utilities.

Reference parity: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py
(ScatterOp:85, GatherOp:97, AllGatherOp:111, ReduceScatterOp:127,
ColumnSequenceParallelLinear:395, RowSequenceParallelLinear:517,
register_sequence_parallel_allreduce_hooks:192).

TPU-native design: "activations sharded along the sequence dim between TP
regions" is a sharding constraint on the seq axis over the mp mesh axis; the
all-gather entering a TP matmul and the reduce-scatter leaving it are
GSPMD-inserted when layouts demand them. The PyLayer forward/backward pairs
(scatter fwd/gather bwd etc.) collapse into differentiable relayouts — the
vjp of a resharding is the opposite resharding, which is exactly the
reference's autograd pairing.

Specs compile through the unified `distributed.sharding.spec_layout` table
(SpecLayout.seq_activation / replicated) like the mp layers.
"""
from __future__ import annotations

from ....core.tensor import Tensor
from ....nn.initializer import Constant, XavierUniform
from ....nn.layer import Layer
from ...sharding import spec_layout as _sl
from ..base.topology import get_hybrid_communicate_group
from ..meta_parallel.parallel_layers.mp_layers import ColumnParallelLinear, RowParallelLinear
from . import collective_matmul as _cm


def _mesh():
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        raise RuntimeError("fleet.init must run before sequence-parallel ops")
    return hcg.mesh


def _relayout(t: Tensor, spec) -> Tensor:
    return _sl.constrain(t, spec, _mesh())


def _seq_spec(ndim: int, seq_axis: int = 0):
    return _sl.layout().seq_activation(ndim, seq_axis)


def _rep_spec(ndim: int):
    return _sl.layout().replicated(ndim)


class ScatterOp:
    """[s, b, h] replicated -> seq-sharded over mp (bwd: gather)."""

    @staticmethod
    def apply(input, axis=0):  # noqa: A002
        return _relayout(input, _seq_spec(len(input.shape), axis))


class GatherOp:
    """seq-sharded -> replicated (bwd: scatter)."""

    @staticmethod
    def apply(input, axis=0):  # noqa: A002
        return _relayout(input, _rep_spec(len(input.shape)))


class AllGatherOp:
    """seq all-gather entering a TP block (bwd: reduce-scatter)."""

    @staticmethod
    def apply(input):  # noqa: A002
        return _relayout(input, _rep_spec(len(input.shape)))


class ReduceScatterOp:
    """partial-sum -> seq-sharded sum leaving a TP block (bwd: all-gather).
    GSPMD fuses the pending matmul reduction with the scatter layout."""

    @staticmethod
    def apply(input):  # noqa: A002
        return _relayout(input, _seq_spec(len(input.shape)))


def scatter(input, axis=0):  # noqa: A002
    return ScatterOp.apply(input, axis)


def all_gather(input):  # noqa: A002
    return AllGatherOp.apply(input)


def reduce_scatter(input):  # noqa: A002
    return ReduceScatterOp.apply(input)


def mark_as_sequence_parallel_parameter(param):
    param.sequence_parallel = True


def is_sequence_parallel_parameter(param):
    return getattr(param, "sequence_parallel", False)


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1, fuse_sequence_parallel_allreduce=False):
    """Reference :192 — grad allreduce over mp for params marked
    sequence-parallel (LayerNorm weights etc. that see seq-sharded
    activations).

    Under GSPMD those grads arrive already SUMMED over mp (the contraction
    over the sharded seq axis emits the reduction inside backward), so the
    hook reduces with AVG — mathematically the identity on synchronized
    grads, which makes the registration idempotent here while exercising
    the exact bucketed dispatch a multi-process backend needs. With
    fuse_sequence_parallel_allreduce=True the marked params go through ONE
    AsyncBucketedGradReducer (size/dtype buckets, reduce dispatched as each
    bucket's backward completes); False registers per-param hooks — one
    collective per param, the reference's unfused shape. Both paths honor
    accumulation_steps: the unfused hooks count arrivals per param and
    reduce the ACCUMULATED grad only on each Nth backward.

    Returns the AsyncBucketedGradReducer on both paths (so callers can
    flush()/no_sync()/stop() uniformly), or None when nothing is marked.
    """
    from ...grad_reducer import AsyncBucketedGradReducer

    # frozen marked params need no grad sync (and the reducer skips
    # stop_gradient params anyway — counting them would desync the
    # per-param-bucket assertion below)
    params = [p for p in model.parameters()
              if is_sequence_parallel_parameter(p) and not p.stop_gradient]
    if not params:
        return None
    hcg = get_hybrid_communicate_group()
    group = hcg.get_model_parallel_group() if hcg is not None else None
    # re-registration must not stack hook sets (same hazard DataParallel
    # guards against): stop the previous reducer before attaching a new one
    prev = getattr(model, "_seq_parallel_grad_reducer", None)
    if prev is not None:
        prev.stop()
    if fuse_sequence_parallel_allreduce:
        reducer = AsyncBucketedGradReducer(
            params, group=group, op="avg",
            accumulation_steps=accumulation_steps,
        )
    else:
        # unfused: same reducer machinery, bucket cap 0 → every param its
        # own bucket → one collective per param (the reference's unfused
        # shape) with a single maintained accumulate/dispatch/unstack
        # implementation
        reducer = AsyncBucketedGradReducer(
            params, group=group, op="avg",
            accumulation_steps=accumulation_steps, bucket_bytes=0,
        )
        assert len(reducer.bucket_sizes) == len(params)
    model._seq_parallel_grad_reducer = reducer
    return reducer


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Column-parallel linear whose input arrives seq-sharded: all-gather
    (layout change) in, column-sharded out (reference :395)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__(
            in_features, out_features, weight_attr=weight_attr, has_bias=has_bias,
            gather_output=gather_output, fuse_matmul_bias=fuse_matmul_bias,
            mp_group=mp_group, name=name,
        )

    def forward(self, x):
        sub = _cm.enabled()
        if sub and not self.gather_output and _cm.usable(x, self.weight, self._mesh, self._axis, "ag_mm"):
            # decomposed ag→mm: each ring step matmuls the seq shard it
            # holds while the next shard's ppermute is in flight — the
            # all-gather never materializes as a standalone blocking op
            return _cm.ag_matmul(x, self.weight, self.bias, self._mesh, self._axis, sub)
        x = AllGatherOp.apply(x)
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """Row-parallel linear whose output leaves seq-sharded: reduce-scatter
    out (reference :517)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__(
            in_features, out_features, weight_attr=weight_attr, has_bias=has_bias,
            input_is_parallel=input_is_parallel, fuse_matmul_bias=fuse_matmul_bias,
            mp_group=mp_group, name=name,
        )

    def forward(self, x):
        sub = _cm.enabled()
        if sub and self.input_is_parallel and _cm.usable(x, self.weight, self._mesh, self._axis, "mm_rs"):
            # decomposed mm→rs: the partial-sum accumulator rides the ring;
            # step k's block matmul overlaps step k-1's ppermute
            return _cm.matmul_rs(x, self.weight, self.bias, self._mesh, self._axis, sub)
        out = super().forward(x)
        return ReduceScatterOp.apply(out)
