"""MoE / expert-parallelism tests.

Model: reference test/collective/collective_global_scatter.py + the MoELayer
usage in python/paddle/incubate/distributed/models/moe/. Numerics are checked
against a straightforward per-token loop reference (no capacity drops when
capacity is ample).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.distributed.models.moe import (
    ExpertLayer,
    GShardGate,
    MoELayer,
    NaiveGate,
    SwitchGate,
    count_by_gate,
    global_gather,
    global_scatter,
    limit_by_capacity,
    prune_gate_by_capacity,
)
from paddle_tpu.nn.layer import Layer


def _make_moe(d_model=16, d_hidden=32, num_expert=4, gate=None, **kw):
    paddle.seed(0)
    experts = [ExpertLayer(d_model, d_hidden) for _ in range(num_expert)]
    return MoELayer(d_model=d_model, experts=experts, gate=gate, **kw)


def _dense_reference(moe, x):
    """Per-token top-k loop, no capacity limit (ample-capacity oracle)."""
    probs = moe.gate(paddle.Tensor(x)).numpy()
    k = moe.gate.top_k
    out = np.zeros_like(x)
    expert_outs = []
    for e in moe.experts:
        expert_outs.append(e(paddle.Tensor(x)).numpy())
    for t in range(x.shape[0]):
        idx = np.argsort(-probs[t])[:k]
        w = probs[t][idx]
        if moe.gate.normalize_gate:
            w = w / (w.sum() + 1e-9)
        for j, ei in enumerate(idx):
            out[t] += w[j] * expert_outs[ei][t]
    return out


class TestGates:
    def test_naive_gate_shapes(self):
        paddle.seed(0)
        g = NaiveGate(8, num_expert=4, world_size=1, topk=2)
        p = g(paddle.rand([10, 8]))
        assert p.shape == [10, 4]
        np.testing.assert_allclose(p.numpy().sum(-1), np.ones(10), rtol=1e-5)

    def test_gate_kinds(self):
        for cls, kw in [(GShardGate, {}), (SwitchGate, {})]:
            g = cls(8, num_expert=4, world_size=1, **kw)
            assert g.tot_expert == 4


class TestMoELayer:
    def test_forward_matches_dense_reference(self):
        moe = _make_moe()
        moe.eval()
        # ample capacity: eval factor covers all tokens
        moe.gate.capacity_factor = (4.0, 4.0)
        x = np.random.RandomState(0).randn(12, 16).astype("float32")
        out = moe(paddle.Tensor(x))
        assert out.shape == [12, 16]
        ref = _dense_reference(moe, x)
        np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-5)

    def test_3d_input_roundtrip_shape(self):
        moe = _make_moe()
        x = paddle.rand([2, 6, 16])
        out = moe(x)
        assert out.shape == [2, 6, 16]

    def test_capacity_drops_tokens(self):
        moe = _make_moe(gate={"type": "switch", "top_k": 1})
        moe.eval()
        moe.gate.capacity_factor = (0.25, 0.25)  # capacity 1 token per expert
        x = paddle.rand([16, 16])
        out = moe(x)
        # dropped tokens produce zero rows; with cap=1/expert at most 4 rows survive
        nz = np.abs(out.numpy()).sum(-1) > 1e-7
        assert nz.sum() <= 4

    def test_aux_loss_differentiable(self):
        moe = _make_moe(gate={"type": "gshard", "top_k": 2})
        x = paddle.rand([8, 16])
        x.stop_gradient = False
        out = moe(x)
        loss = out.mean() + 0.01 * moe.l_aux
        loss.backward()
        gw = moe.gate.gate_weight.grad
        assert gw is not None and np.isfinite(gw.numpy()).all()
        assert moe.experts[0].htoh4_weight.grad is not None

    def test_generic_expert_path(self):
        class MyExpert(Layer):
            def __init__(self):
                super().__init__()
                self.fc = paddle.nn.Linear(16, 16)

            def forward(self, x):
                return paddle.nn.functional.relu(self.fc(x))

        paddle.seed(1)
        moe = MoELayer(d_model=16, experts=[MyExpert() for _ in range(2)],
                       gate={"type": "naive", "top_k": 1})
        out = moe(paddle.rand([6, 16]))
        assert out.shape == [6, 16]

    def test_jit_compiles(self):
        moe = _make_moe()
        moe.eval()
        fn = paddle.jit.to_static(lambda t: moe(t))
        x = paddle.rand([8, 16])
        np.testing.assert_allclose(fn(x).numpy(), moe(x).numpy(), rtol=2e-4, atol=2e-5)

    def test_ep_sharded_under_fleet(self):
        """Expert dim sharded over the dp axis of an 8-device mesh compiles+runs."""
        from paddle_tpu.distributed import fleet

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1, "pp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        try:
            moe = _make_moe(num_expert=8, ep_axis="dp")
            fn = paddle.jit.to_static(lambda t: moe(t))
            x = paddle.rand([16, 16])
            out = fn(x)
            assert out.shape == [16, 16]
        finally:
            fleet._reset_for_tests() if hasattr(fleet, "_reset_for_tests") else None


class TestRoutingUtils:
    def test_count_by_gate(self):
        idx = paddle.to_tensor(np.array([0, 1, 1, 3, 0, 2], dtype="int64"))
        pos, local, global_ = count_by_gate(idx, num_expert=4)
        np.testing.assert_array_equal(local.numpy(), [2, 2, 1, 1])
        np.testing.assert_array_equal(global_.numpy(), local.numpy())
        # expert-sorted order: tokens of expert0 first (stable)
        np.testing.assert_array_equal(pos.numpy(), [0, 4, 1, 2, 5, 3])

    def test_limit_by_capacity(self):
        ec = paddle.to_tensor(np.array([5, 1, 3, 0], dtype="int64"))
        out = limit_by_capacity(ec, capacity=2)
        np.testing.assert_array_equal(out.numpy(), [2, 1, 2, 0])

    def test_prune_gate_by_capacity(self):
        idx = paddle.to_tensor(np.array([0, 0, 0, 1], dtype="int64"))
        ec = paddle.to_tensor(np.array([2, 1], dtype="int64"))
        pruned = prune_gate_by_capacity(idx, ec, n_expert=2, n_worker=1)
        np.testing.assert_array_equal(pruned.numpy(), [0, 0, -1, 1])

    def test_global_scatter_gather_identity(self):
        x = paddle.rand([4, 8])
        lc = paddle.to_tensor(np.array([2, 2], dtype="int64"))
        y = global_scatter(x, lc, lc)
        z = global_gather(y, lc, lc)
        np.testing.assert_allclose(z.numpy(), x.numpy())

    def test_global_scatter_multirank_rejected(self):
        class FakeGroup:
            nranks = 2

        with pytest.raises(NotImplementedError):
            global_scatter(paddle.rand([2, 2]), None, None, group=FakeGroup())
