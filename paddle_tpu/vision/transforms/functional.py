"""Functional image transforms over numpy HWC arrays.

Reference parity: python/paddle/vision/transforms/functional.py (+ the
cv2/PIL backends there). TPU-native design: transforms are host-side numpy
(they run in DataLoader workers feeding the device pipeline; no PIL/cv2 in
the image), `to_tensor` does the single HWC->CHW device transfer.
"""
from __future__ import annotations

import numbers

import numpy as np

from ...core.tensor import Tensor


def _np(img):
    if isinstance(img, Tensor):
        return img.numpy()
    return np.asarray(img)


def to_tensor(pic, data_format="CHW") -> Tensor:
    arr = _np(pic)
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    else:
        arr = arr.astype(np.float32)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return Tensor(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _np(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    return Tensor(out) if isinstance(img, Tensor) else out


def hflip(img):
    arr = _np(img)
    return arr[:, ::-1, :] if arr.ndim == 3 else arr[:, ::-1]


def vflip(img):
    arr = _np(img)
    return arr[::-1]


def resize(img, size, interpolation="bilinear"):
    """Bilinear/nearest resize in numpy (no cv2/PIL in the TPU image)."""
    arr = _np(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    h, w = arr.shape[:2]
    if isinstance(size, int):
        # shorter side -> size, keep aspect (reference semantics)
        if h < w:
            oh, ow = size, max(1, int(round(w * size / h)))
        else:
            oh, ow = max(1, int(round(h * size / w))), size
    else:
        oh, ow = size
    if (oh, ow) == (h, w):
        return arr[:, :, 0] if squeeze else arr
    if interpolation == "nearest":
        ys = np.clip(np.round(np.arange(oh) * h / oh).astype(int), 0, h - 1)
        xs = np.clip(np.round(np.arange(ow) * w / ow).astype(int), 0, w - 1)
        out = arr[ys][:, xs]
    else:  # bilinear, align_corners=False convention
        y = (np.arange(oh) + 0.5) * h / oh - 0.5
        x = (np.arange(ow) + 0.5) * w / ow - 0.5
        y0 = np.clip(np.floor(y).astype(int), 0, h - 1)
        x0 = np.clip(np.floor(x).astype(int), 0, w - 1)
        y1 = np.clip(y0 + 1, 0, h - 1)
        x1 = np.clip(x0 + 1, 0, w - 1)
        wy = np.clip(y - y0, 0, 1)[:, None, None]
        wx = np.clip(x - x0, 0, 1)[None, :, None]
        a = arr[y0][:, x0].astype(np.float32)
        b = arr[y0][:, x1].astype(np.float32)
        c = arr[y1][:, x0].astype(np.float32)
        d = arr[y1][:, x1].astype(np.float32)
        out = a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx + c * wy * (1 - wx) + d * wy * wx
        if arr.dtype == np.uint8:
            out = np.clip(np.round(out), 0, 255).astype(np.uint8)
        else:
            out = out.astype(arr.dtype)
    return out[:, :, 0] if squeeze else out


def crop(img, top, left, height, width):
    arr = _np(img)
    return arr[top : top + height, left : left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = _np(img)
    h, w = arr.shape[:2]
    th, tw = output_size
    top = max(0, (h - th) // 2)
    left = max(0, (w - tw) // 2)
    return crop(arr, top, left, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _np(img)
    if isinstance(padding, int):
        padding = (padding, padding, padding, padding)
    if len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    left, top, right, bottom = padding
    spec = [(top, bottom), (left, right)] + [(0, 0)] * (arr.ndim - 2)
    if padding_mode == "constant":
        return np.pad(arr, spec, mode="constant", constant_values=fill)
    return np.pad(arr, spec, mode={"reflect": "reflect", "edge": "edge", "symmetric": "symmetric"}[padding_mode])


def adjust_brightness(img, brightness_factor):
    arr = _np(img).astype(np.float32) * brightness_factor
    return np.clip(arr, 0, 255).astype(np.uint8) if _np(img).dtype == np.uint8 else arr


def adjust_contrast(img, contrast_factor):
    arr = _np(img).astype(np.float32)
    mean = arr.mean()
    out = (arr - mean) * contrast_factor + mean
    return np.clip(out, 0, 255).astype(np.uint8) if _np(img).dtype == np.uint8 else out


def adjust_saturation(img, saturation_factor):
    arr = _np(img).astype(np.float32)
    gray = (arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114)[..., None]
    out = gray + (arr - gray) * saturation_factor
    return np.clip(out, 0, 255).astype(np.uint8) if _np(img).dtype == np.uint8 else out


def adjust_hue(img, hue_factor):
    """Approximate hue rotation in RGB space (no colorsys per pixel)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = _np(img).astype(np.float32)
    theta = hue_factor * 2 * np.pi
    c, s = np.cos(theta), np.sin(theta)
    # YIQ rotation matrix
    t_yiq = np.array([[0.299, 0.587, 0.114], [0.596, -0.274, -0.322], [0.211, -0.523, 0.312]], np.float32)
    t_rgb = np.linalg.inv(t_yiq)
    rot = np.array([[1, 0, 0], [0, c, -s], [0, s, c]], np.float32)
    m = t_rgb @ rot @ t_yiq
    out = arr @ m.T
    return np.clip(out, 0, 255).astype(np.uint8) if _np(img).dtype == np.uint8 else out


def rotate(img, angle, interpolation="nearest", expand=False, center=None, fill=0):
    """Nearest-neighbor rotation (host-side; detection aug). expand=True
    enlarges the canvas to hold the whole rotated image."""
    arr = _np(img)
    h, w = arr.shape[:2]
    if expand:
        rad_c = np.deg2rad(angle)
        oh = int(np.ceil(abs(h * np.cos(rad_c)) + abs(w * np.sin(rad_c))))
        ow = int(np.ceil(abs(w * np.cos(rad_c)) + abs(h * np.sin(rad_c))))
        ocy, ocx = (oh - 1) / 2, (ow - 1) / 2
        icy, icx = (h - 1) / 2, (w - 1) / 2
    else:
        oh, ow = h, w
        if center is None:
            ocy = icy = (h - 1) / 2
            ocx = icx = (w - 1) / 2
        else:
            ocy = icy = center[1]
            ocx = icx = center[0]
    rad = -np.deg2rad(angle)
    cos_a, sin_a = np.cos(rad), np.sin(rad)
    yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    ys = cos_a * (yy - ocy) - sin_a * (xx - ocx) + icy
    xs = sin_a * (yy - ocy) + cos_a * (xx - ocx) + icx
    yi = np.round(ys).astype(int)
    xi = np.round(xs).astype(int)
    valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
    out = np.full((oh, ow) + arr.shape[2:], fill, arr.dtype)
    out[valid] = arr[np.clip(yi, 0, h - 1)[valid], np.clip(xi, 0, w - 1)[valid]]
    return out


def to_grayscale(img, num_output_channels=1):
    arr = _np(img).astype(np.float32)
    gray = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    gray = gray[..., None]
    if num_output_channels == 3:
        gray = np.repeat(gray, 3, axis=-1)
    return np.clip(gray, 0, 255).astype(np.uint8) if _np(img).dtype == np.uint8 else gray


def _inverse_warp(arr, inv_matrix, oh=None, ow=None, fill=0):
    """Nearest-neighbor inverse warp: output (y, x) samples input at
    inv_matrix @ [x, y, 1] (host-side numpy, like rotate above)."""
    h, w = arr.shape[:2]
    oh = oh if oh is not None else h
    ow = ow if ow is not None else w
    yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    ones = np.ones_like(xx, np.float64)
    pts = np.stack([xx, yy, ones], 0).reshape(3, -1).astype(np.float64)
    m = np.asarray(inv_matrix, np.float64)
    src = m @ pts
    if m.shape[0] == 3:  # projective: divide by w
        src = src[:2] / np.maximum(np.abs(src[2:3]), 1e-9) * np.sign(src[2:3])
    xs = src[0].reshape(oh, ow)
    ys = src[1].reshape(oh, ow)
    yi = np.round(ys).astype(int)
    xi = np.round(xs).astype(int)
    valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
    out = np.full((oh, ow) + arr.shape[2:], fill, arr.dtype)
    out[valid] = arr[np.clip(yi, 0, h - 1)[valid], np.clip(xi, 0, w - 1)[valid]]
    return out


def affine(img, angle, translate, scale, shear, interpolation="nearest", center=None, fill=0):
    """Affine warp (reference vision/transforms/functional.py affine):
    rotation + translation + isotropic scale + shear, about `center`."""
    arr = _np(img)
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2, (w - 1) / 2) if center is None else (center[1], center[0])
    rot = -np.deg2rad(angle)  # positive angle = counter-clockwise (rotate() convention)
    sx, sy = (np.deg2rad(s) for s in (shear if isinstance(shear, (list, tuple)) else (shear, 0.0)))
    # forward matrix (x, y): T(center) R S Shear T(-center) + translate
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    m = np.array([[a, b, 0.0], [c, d, 0.0]], np.float64) * scale
    # inverse mapping about center with translation
    full = np.eye(3)
    full[:2, :2] = m[:, :2]
    full[0, 2] = cx + translate[0] - (full[0, 0] * cx + full[0, 1] * cy)
    full[1, 2] = cy + translate[1] - (full[1, 0] * cx + full[1, 1] * cy)
    inv = np.linalg.inv(full)
    return _inverse_warp(arr, inv[:2], fill=fill)


def _perspective_coeffs(startpoints, endpoints):
    """Solve the 8-dof homography mapping endpoints -> startpoints."""
    a = []
    b = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        b += [sx, sy]
    coeffs = np.linalg.lstsq(np.asarray(a, np.float64), np.asarray(b, np.float64), rcond=None)[0]
    return np.concatenate([coeffs, [1.0]]).reshape(3, 3)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """Projective warp mapping startpoints -> endpoints (reference
    functional.perspective; points are [[x, y], ...] corners)."""
    arr = _np(img)
    inv = _perspective_coeffs(startpoints, endpoints)
    return _inverse_warp(arr, inv, fill=fill)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase the [i:i+h, j:j+w] region with value(s) v (reference
    functional.erase). Tensor images are CHW (erased on-device); arrays/PIL
    are HWC host-side."""
    if isinstance(img, Tensor):
        from ...core.apply import apply
        from jax import numpy as jnp

        vv = v._value if isinstance(v, Tensor) else jnp.asarray(v)

        def f(x):
            region = jnp.broadcast_to(vv.astype(x.dtype), x[..., i:i + h, j:j + w].shape)
            return x.at[..., i:i + h, j:j + w].set(region)

        out = apply("erase", f, img)
        if inplace:
            img._become(out)
            return img
        return out
    arr = _np(img)
    out = arr if inplace else arr.copy()
    out[i:i + h, j:j + w] = np.asarray(v, out.dtype)
    return out
