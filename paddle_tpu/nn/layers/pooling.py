"""Pooling layers (python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from ..layer import Layer
from .. import functional as F


def _pool_layer(fn_name, has_stride=True):
    class _Pool(Layer):
        def __init__(self, kernel_size=None, stride=None, padding=0, **kwargs):
            super().__init__()
            self.kernel_size = kernel_size
            self.stride = stride
            self.padding = padding
            self.kwargs = {k: v for k, v in kwargs.items() if k != "name"}

        def forward(self, x):
            return getattr(F, fn_name)(x, self.kernel_size, self.stride, self.padding, **self.kwargs)

    _Pool.__name__ = fn_name.title().replace("_", "")
    return _Pool


MaxPool1D = _pool_layer("max_pool1d")
MaxPool2D = _pool_layer("max_pool2d")
MaxPool3D = _pool_layer("max_pool3d")
AvgPool1D = _pool_layer("avg_pool1d")
AvgPool2D = _pool_layer("avg_pool2d")
AvgPool3D = _pool_layer("avg_pool3d")


def _adaptive_pool_layer(fn_name):
    class _Pool(Layer):
        def __init__(self, output_size, **kwargs):
            super().__init__()
            self.output_size = output_size

        def forward(self, x):
            return getattr(F, fn_name)(x, self.output_size)

    _Pool.__name__ = fn_name.title().replace("_", "")
    return _Pool


AdaptiveAvgPool1D = _adaptive_pool_layer("adaptive_avg_pool1d")
AdaptiveAvgPool2D = _adaptive_pool_layer("adaptive_avg_pool2d")
AdaptiveAvgPool3D = _adaptive_pool_layer("adaptive_avg_pool3d")
AdaptiveMaxPool1D = _adaptive_pool_layer("adaptive_max_pool1d")
AdaptiveMaxPool2D = _adaptive_pool_layer("adaptive_max_pool2d")
AdaptiveMaxPool3D = _adaptive_pool_layer("adaptive_max_pool3d")
