"""Cheap canonicalization passes: scalar constant folding and redundant
cast/reshape elimination.

Reference parity: the constant_folding_pass + identity-op eliminations of
paddle/fluid/pir/transforms/general. TPU-native: "folding" reads the value
the eager capture already computed — an op whose inputs are all literals
evaluated to a concrete placeholder Tensor at record time, so the fold is
a lookup, not an interpreter. Redundancy checks read the shape/dtype
metadata harvested from the placeholder Tensors; a candidate whose
metadata is missing (or whose input carries dynamic feed dims) is left
alone — canonicalization must never guess.
"""
from __future__ import annotations

import numpy as np

from ..analysis.graph import EFFECTFUL_OPS
from .pass_base import (
    PassStats,
    ProgramPass,
    clone_op_with_inputs,
    register_pass,
    release_vars,
)


def _forward_uses(program, graph, out_vid, new_ref) -> bool:
    """Rewire every consumer of `out_vid` to `new_ref` (('var', vid) or
    ('lit', value)). Only op-site uses can be rewired; returns False (no
    rewrite) when the var escapes (fetch/grad/opt use or liveness root)."""
    if out_vid in graph.roots():
        return False
    uses = graph.uses_of(out_vid)
    if any(site != "op" for site, _si, _pos in uses):
        return False
    by_op = {}
    for _site, si, _pos in uses:
        by_op.setdefault(si, program.ops[si])
    for si, op in by_op.items():
        refs = [new_ref if (r[0] == "var" and r[1] == out_vid) else r
                for r in op.in_refs]
        program.ops[si] = clone_op_with_inputs(op, refs)
    return True


@register_pass
class ConstantFoldScalarsPass(ProgramPass):
    """Fold ops whose inputs are ALL literals and whose outputs are all
    scalars: the recorded placeholder value IS the constant (computed once
    at capture time), so consumers read it as a literal and the op goes
    away. Scalar-only on purpose — folding a big array would pin a copy of
    it into every consumer's in_refs."""

    name = "constant_fold_scalars"

    def run(self, program, ctx) -> PassStats:
        folded = 0
        # fixpoint: folding one op can make its consumer all-literal
        for _ in range(8):
            graph = ctx.graph()
            victims = []
            for i, op in enumerate(program.ops):
                if not op.out_vars or op.name in EFFECTFUL_OPS:
                    continue
                if any(r[0] == "var" for r in op.in_refs):
                    continue
                metas = [graph.vars.get(v) for v in op.out_vars]
                if any(m is None or m.shape != () for m in metas):
                    continue
                if any(program._var_tensors.get(v) is None for v in op.out_vars):
                    continue
                victims.append(i)
            did = 0
            for i in victims:
                op = program.ops[i]
                # all-or-nothing per op: EVERY output must be forwardable,
                # or the op stays (a half-forwarded op would lose an output)
                if any(v in graph.roots() for v in op.out_vars) or any(
                    site != "op"
                    for v in op.out_vars
                    for site, _si, _pos in graph.uses_of(v)
                ):
                    continue
                for vid in op.out_vars:
                    value = np.asarray(program._var_tensors[vid]._raw())
                    _forward_uses(program, graph, vid, ("lit", value))
                release_vars(program, op.out_vars)
                did += 1
                program.ops[i] = None  # mark; compacted below
            if did:
                program.ops = [op for op in program.ops if op is not None]
                folded += did
                ctx.invalidate()
                program._compiled.clear()
            else:
                break
        return PassStats(matches=folded, rewritten_ops=folded)


@register_pass
class RedundantCastReshapeElimPass(ProgramPass):
    """Remove casts whose output dtype equals the input's and reshapes
    whose output shape equals the input's (per the harvested placeholder
    metadata): consumers read the producer directly. Skipped when the
    input rides a dynamic feed dim — the dry-run metadata then understates
    the runtime shape and equality proves nothing."""

    name = "redundant_cast_reshape_elim"

    def run(self, program, ctx) -> PassStats:
        removed_total = 0
        for _ in range(8):
            graph = ctx.graph()
            did = 0
            for i, op in enumerate(program.ops):
                if op.name not in ("cast", "reshape"):
                    continue
                var_ins = [r[1] for r in op.in_refs if r[0] == "var"]
                if len(var_ins) != 1 or len(op.out_vars) != 1:
                    continue
                src, dst = var_ins[0], op.out_vars[0]
                mi, mo = graph.vars.get(src), graph.vars.get(dst)
                if mi is None or mo is None:
                    continue
                if mi.shape is None or mi.shape != mo.shape:
                    continue
                if mi.dtype is None or mi.dtype != mo.dtype:
                    continue
                src_t = program._var_tensors.get(src)
                if src_t is not None and getattr(src_t, "_dynamic_dims", None):
                    continue
                if not _forward_uses(program, graph, dst, ("var", src)):
                    continue
                program.ops[i] = None
                release_vars(program, [dst])
                did += 1
            if did:
                program.ops = [op for op in program.ops if op is not None]
                removed_total += did
                ctx.invalidate()
                program._compiled.clear()
            else:
                break
        return PassStats(matches=removed_total, rewritten_ops=removed_total)
