"""hapi Model.fit/evaluate/predict + callbacks + summary.

Mirrors the reference's test/legacy_test/test_model.py style: a small MNIST-shaped
classifier trained on synthetic data through the high-level API.
"""
import io as _io
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy


class RandomDataset(Dataset):
    def __init__(self, n=64, num_classes=4, feat=8, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, feat).astype("float32")
        self.y = rng.randint(0, num_classes, (n, 1)).astype("int64")

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


def _mlp(feat=8, num_classes=4):
    return paddle.nn.Sequential(
        paddle.nn.Linear(feat, 16),
        paddle.nn.ReLU(),
        paddle.nn.Linear(16, num_classes),
    )


def test_fit_decreases_loss(tmp_path):
    net = _mlp()
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=model.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss(), Accuracy())
    ds = RandomDataset(n=64)
    first = model.train_batch([ds.x[:16]], [ds.y[:16]])
    logs = model.fit(ds, epochs=4, batch_size=16, verbose=0, shuffle=False)
    assert "loss" in logs
    first_loss = first[0][0] if isinstance(first, tuple) else first[0]
    assert logs["loss"] < first_loss


def test_evaluate_and_predict():
    model = paddle.Model(_mlp())
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss(), Accuracy(topk=(1, 2)))
    ds = RandomDataset(n=32)
    res = model.evaluate(ds, batch_size=8, verbose=0)
    assert "acc_top1" in res and "acc_top2" in res
    assert 0.0 <= res["acc_top1"] <= res["acc_top2"] <= 1.0

    out = model.predict(ds, batch_size=8, stack_outputs=True, verbose=0)
    assert out[0].shape == (32, 4)


def test_save_load_checkpoint(tmp_path):
    model = paddle.Model(_mlp())
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=model.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    ds = RandomDataset(n=16)
    model.fit(ds, epochs=1, batch_size=8, verbose=0, save_dir=str(tmp_path / "ckpt"))
    assert os.path.exists(tmp_path / "ckpt" / "final.pdparams")
    assert os.path.exists(tmp_path / "ckpt" / "final.pdopt")

    model2 = paddle.Model(_mlp())
    opt2 = paddle.optimizer.Adam(learning_rate=0.01, parameters=model2.parameters())
    model2.prepare(opt2, paddle.nn.CrossEntropyLoss())
    model2.load(str(tmp_path / "ckpt" / "final"))
    w1 = model.network.state_dict()
    w2 = model2.network.state_dict()
    for k in w1:
        np.testing.assert_allclose(w1[k].numpy(), w2[k].numpy())


def test_early_stopping_stops():
    model = paddle.Model(_mlp())
    opt = paddle.optimizer.SGD(learning_rate=0.0, parameters=model.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss(), Accuracy())
    ds = RandomDataset(n=16)
    es = paddle.callbacks.EarlyStopping(monitor="loss", patience=0, verbose=0, save_best_model=False)
    model.fit(ds, eval_data=ds, epochs=10, batch_size=8, verbose=0, callbacks=[es])
    # lr=0 -> no improvement -> must stop well before 10 epochs
    assert model.stop_training


def test_summary():
    net = _mlp()
    res = paddle.summary(net, (1, 8))
    # 8*16+16 + 16*4+4 = 212
    assert res["total_params"] == 212
    assert res["trainable_params"] == 212


def test_visualdl_jsonl(tmp_path):
    model = paddle.Model(_mlp())
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    ds = RandomDataset(n=16)
    cb = paddle.callbacks.VisualDL(log_dir=str(tmp_path / "vdl"))
    model.fit(ds, epochs=1, batch_size=8, verbose=0, callbacks=[cb])
    assert os.path.exists(tmp_path / "vdl" / "scalars.jsonl")


def test_reduce_lr_on_plateau():
    model = paddle.Model(_mlp())
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    ds = RandomDataset(n=16)
    cb = paddle.callbacks.ReduceLROnPlateau(monitor="loss", factor=0.5, patience=0, verbose=0, cooldown=0)
    # force "no improvement": two evals with the same data and lr applied
    cb.set_model(model)
    cb.best = -np.inf  # any observed loss counts as non-improvement (mode=min->best starts inf; set to -inf)
    cb.monitor_op = lambda a, b: False
    cb.on_eval_end({"loss": 1.0})
    assert abs(opt.get_lr() - 0.05) < 1e-7
