"""Replica fleet: SLO-aware routing, replica failure survival, and
zero-downtime weight hot-swap.

"Millions of users" is N engines behind a router, not one. A `ReplicaFleet`
fronts N `InferenceEngine` + `ContinuousBatchingScheduler` replicas with
the three properties a production fleet needs at steady state:

- **Routing** (`fleet.route` FaultPlan site): session affinity first — a
  request's KV pages live on exactly one replica, so follow-on requests of
  the same `Request.session` route home while that replica is healthy —
  otherwise least-expected-drain-time: queue depth weighted by the
  replica's EWMA step latency (a slow replica with a short queue can be a
  worse bet than a fast one with a longer queue; this is the SLO-aware
  part). With no healthy replica the request is HELD at the fleet (never
  dropped) and flushed on the next step that finds one.

- **Replica health** (`fleet.replica_step.<idx>` FaultPlan sites): every
  replica step runs through a deterministic chaos point; a raised fault or
  real exception opens the circuit one notch (healthy -> draining: no new
  admissions, in-flight work keeps stepping), `breaker_threshold`
  consecutive failures open it fully (-> down). A replica whose step takes
  longer than `heartbeat_deadline_s` (its OWN wall time — a shared tick
  clock would blame a stalled peer on healthy replicas) counts a failure
  through the same breaker (the slow/hung-step shape a delay fault
  produces; set the deadline above worst-case first-step compile). A
  down replica is EVACUATED: every in-flight and queued request is reset
  via the scheduler's preemption-resume path (generated tokens fold into
  the prompt, K/V is recomputed from it on the new home) and re-dispatched
  to a healthy replica — zero lost requests, session affinity broken only
  by death.

- **Zero-downtime weight hot-swap**: `request_swap(source)` streams a
  topology-portable `step_<N>/` checkpoint (PR 7 reshard-on-load) into ONE
  drained replica at a time — drain (stop admissions, migrate its waiting
  queue, finish in-flight decode), swap under the engine's PINNED
  out_shardings (cache-page layouts stay valid, no recompile), re-admit,
  next replica. The rest of the fleet absorbs traffic, so the rollout
  costs a bounded p99 blip, never an outage; a swapped replica's logits
  are byte-identical to a cold-started engine on the same weights (pinned
  shardings + identical programs — asserted in tests and the
  `dryrun_multichip fleet_swap` scenario).

Telemetry: replica-state and per-replica queue gauges, routing /
evacuation / failure / swap counters, per-replica step-latency and
swap-drain histograms; request-level TTFT/TPOT land in the PR 8 serving
histograms (the schedulers observe them), so fleet p99s come from the same
families the single-replica tier exports.
"""
from __future__ import annotations

import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import telemetry
from ..telemetry import metrics as _metrics
from ..telemetry import request_trace as _rt
from ..distributed.resilience import fault_injection as _fi
from .qos import QoSPolicy
from .scheduler import (
    ContinuousBatchingScheduler,
    Request,
    _req_counter,
    percentiles,
)

__all__ = ["ReplicaFleet", "ReplicaStatus", "NoHealthyReplica", "fleet_replay"]


class ReplicaStatus:
    HEALTHY = "healthy"
    DRAINING = "draining"
    DOWN = "down"

    ALL = (HEALTHY, DRAINING, DOWN)


class NoHealthyReplica(RuntimeError):
    """Every replica is down and work is outstanding — the fleet cannot
    make progress (the caller's cue to escalate/restart, not spin)."""


def _replicas_gauge(state: str):
    return _metrics.gauge(
        "paddle_tpu_fleet_replicas",
        "fleet replicas by health state",
        label_names=("state",),
    ).labels(state=state)


def _queue_gauge(replica: int, state: str):
    return _metrics.gauge(
        "paddle_tpu_fleet_replica_queue",
        "per-replica scheduler occupancy",
        label_names=("replica", "state"),
    ).labels(replica=str(replica), state=state)


def _routed_counter(reason: str):
    return _metrics.counter(
        "paddle_tpu_fleet_routed_total",
        "routing decisions by reason (affinity = session home, "
        "least_loaded = SLO-aware pick, evacuated = re-dispatch off a dead "
        "replica, migrated = drained off a swapping replica, held = no "
        "healthy replica, queued at the fleet, requeued = held request "
        "flushed to a recovered replica)",
        label_names=("reason",),
    ).labels(reason=reason)


def _swap_counter(event: str):
    return _metrics.counter(
        "paddle_tpu_fleet_swaps_total",
        "weight hot-swap lifecycle events",
        label_names=("event",),
    ).labels(event=event)


def _failure_counter(replica: int, reason: str):
    return _metrics.counter(
        "paddle_tpu_fleet_replica_failures_total",
        "replica step failures feeding the circuit breaker, by cause "
        "(step = chaos fault or real exception, heartbeat = step wall "
        "time over the deadline)",
        label_names=("replica", "reason"),
    ).labels(replica=str(replica), reason=reason)


def _evac_counter():
    return _metrics.counter(
        "paddle_tpu_fleet_evacuated_requests_total",
        "in-flight/queued requests re-dispatched off a dead replica "
        "(recompute-from-prompt on the new home)",
    )


_STEP_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)


def _step_hist(replica: int):
    return _metrics.histogram(
        "paddle_tpu_fleet_step_seconds",
        "per-replica scheduler step latency (the fleet-level tail the "
        "router's EWMA scoring tracks)",
        label_names=("replica",),
        buckets=_STEP_BUCKETS,
    ).labels(replica=str(replica))


def _drain_hist():
    return _metrics.histogram(
        "paddle_tpu_fleet_swap_drain_seconds",
        "per-replica drain+swap duration during a weight rollout (the "
        "blip window)",
        buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
    )


class _Replica:
    """One engine + scheduler behind the router, plus its health record."""

    def __init__(self, idx: int, engine, sched: ContinuousBatchingScheduler):
        self.idx = idx
        self.engine = engine
        self.sched = sched
        self.status = ReplicaStatus.HEALTHY
        self.consecutive_failures = 0
        self.ewma_step_s = 0.0
        self.draining_for_swap = False

    def depth(self) -> int:
        return len(self.sched.waiting) + len(self.sched.running)

    def busy(self) -> bool:
        return bool(self.sched.waiting or self.sched.running)


class ReplicaFleet:
    """Serving front over N replicas; duck-types the scheduler surface
    (`submit` / `step` / `idle` / `finished`), so the single-replica replay
    and predictor plumbing drive a fleet unchanged."""

    def __init__(
        self,
        engines: Sequence,
        *,
        eos_id: Optional[int] = None,
        max_running: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
        breaker_threshold: int = 2,
        heartbeat_deadline_s: Optional[float] = None,
        session_cache_size: int = 4096,
        prefix_cache: bool = True,
        spec_decode=None,
        qos: Optional[QoSPolicy] = None,
    ):
        if not engines:
            raise ValueError("ReplicaFleet needs at least one engine")
        self.clock = clock
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.heartbeat_deadline_s = heartbeat_deadline_s
        self.session_cache_size = max(1, int(session_cache_size))
        # round 19: ONE QoSPolicy instance is shared by every replica's
        # scheduler — token buckets, fair-share debt, and the brownout
        # ladder are fleet-wide (a tenant can't dodge its quota by
        # spraying replicas), and the held queue below shares its bounds
        self.qos = qos
        # round 17: every replica's scheduler gets the prefix cache (on by
        # default — session affinity already routes a conversation to the
        # replica holding its warm pages, so hits compound) and, opt-in,
        # speculative decoding
        self.replicas: List[_Replica] = [
            _Replica(
                i,
                eng,
                ContinuousBatchingScheduler(
                    eng, eos_id=eos_id, max_running=max_running, clock=clock,
                    prefix_cache=prefix_cache, spec_decode=spec_decode,
                    qos=qos,
                ),
            )
            for i, eng in enumerate(engines)
        ]
        self.finished: List[Request] = []
        self.submitted_total = 0
        self.evacuated_total = 0
        self.failures_total = 0
        self.swaps_completed = 0
        # [(start, end)] fleet-clock windows of completed rollouts — the
        # bench slices pooled inter-token intervals on these to report the
        # swap-blip p99
        self.swap_windows: List[tuple] = []
        self._pending: List[Request] = []  # held: no healthy replica yet
        self._held_shed = 0  # sheds off the held list (bounded _pending)
        # affinity is a performance hint, so the home map is a bounded LRU:
        # an unbounded dict would grow by one entry per session ever seen,
        # exactly the steady state a long-lived fleet serves
        self._session_home: "OrderedDict[object, int]" = OrderedDict()
        self._swap: Optional[dict] = None
        self._swap_t0: Optional[float] = None
        if telemetry.enabled():
            self._sync_gauges()

    # ---- scheduler-surface aggregates ----
    @property
    def preempted_total(self) -> int:
        return sum(r.sched.preempted_total for r in self.replicas)

    @property
    def shed_total(self) -> int:
        return self._held_shed + sum(r.sched.shed_total for r in self.replicas)

    def idle(self) -> bool:
        # an in-progress swap keeps the fleet non-idle so replay loops
        # drive the drain -> swap -> re-admit machine to completion even
        # after the traffic tail finished
        return (
            not self._pending
            and self._swap is None
            and all(
                r.status == ReplicaStatus.DOWN or r.sched.idle()
                for r in self.replicas
            )
        )

    def healthy(self) -> List[_Replica]:
        return [r for r in self.replicas if r.status == ReplicaStatus.HEALTHY]

    def prewarm(self) -> dict:
        """Compile (or restore) every replica's shape buckets before
        traffic. Replicas sharing a model signature compile each bucket
        ONCE: the first replica pays the miss (or a persistent-cache
        restore), the rest adopt the executable from the in-process shared
        registry (ledger outcome=shared) — N-replica fleet cold start costs
        one replica's compiles, not N. Returns per-replica bucket stats."""
        return {
            r.idx: r.engine.prewarm()
            for r in self.replicas
            if hasattr(r.engine, "prewarm")
        }

    # ---- routing ----
    def _score(self, rep: _Replica) -> float:
        """Expected time for a new request to start making progress:
        occupancy weighted by the replica's recent step latency. A pure
        queue-depth router sends traffic to a degraded-but-short replica;
        weighting by the EWMA keeps the p99 honest."""
        return (rep.depth() + 1) * max(rep.ewma_step_s, 1e-6)

    def _route(self, req: Request, *, reason_override: Optional[str] = None) -> Optional[_Replica]:
        # the chaos site models CLIENT-facing routing failures (submit()
        # raises to the caller, who still owns the request); internal
        # re-dispatch of evacuated/migrated/held requests must never fault
        # here — the request exists only in a local list at that point, so
        # a raise would silently lose it and void the zero-loss invariant
        if reason_override is None:
            _fi.fault_point("fleet.route", rid=req.rid)
        healthy = self.healthy()
        if not healthy:
            if telemetry.enabled():
                _routed_counter("held").inc()
            return None
        rep = None
        reason = reason_override or "least_loaded"
        if req.session is not None and reason_override is None:
            home = self._session_home.get(req.session)
            if home is not None and self.replicas[home].status == ReplicaStatus.HEALTHY:
                rep = self.replicas[home]
                reason = "affinity"
        if rep is None:
            rep = min(healthy, key=lambda r: (self._score(r), r.idx))
        if req.session is not None:
            self._session_home[req.session] = rep.idx
            self._session_home.move_to_end(req.session)
            while len(self._session_home) > self.session_cache_size:
                self._session_home.popitem(last=False)
        if _rt.enabled() and _rt.sampled(req.rid):
            # lands in the request's own chrome lane: WHY it went where it
            # went (affinity home vs SLO-scored pick vs evacuation target)
            _rt.record_event("request", "route", t=self.clock(), rid=req.rid,
                             replica=rep.idx, reason=reason)
        if telemetry.enabled():
            _routed_counter(reason).inc()
        return rep

    def submit(self, req: Request) -> None:
        # TTL-sweep the held list on EVERY submit, not only in step(): a
        # fully-down fleet raises NoHealthyReplica out of step(), after
        # which callers stop stepping — without this sweep, expired work
        # would sit in _pending forever and the outcome="expired" counter
        # contract would silently stop holding on a dead fleet
        self._expire_pending(self.clock())
        rep = self._route(req)  # a chaos raise leaves the request unstamped
        if rep is None:
            # held at the fleet: the TTL clock starts NOW — acceptance —
            # since no scheduler will stamp it until it routes
            if req.submitted_time is None:
                req.submitted_time = self.clock()
            if req.trace is None:
                req.trace = _rt.start(req.rid, req.submitted_time,
                                      prompt_len=req.prompt_len,
                                      max_new=req.max_new_tokens)
            if req.trace is not None and req.trace.phase_name is None:
                # held time is queue time with a cause: no healthy replica
                req.trace.phase("queue", self.clock(), cause="held")
            # the held line shares the QoS waiting bound: a dead fleet
            # must shed the lowest eligible class explicitly, not grow
            # an unbounded list nobody is draining
            if self.qos is not None and self.qos.queue_full(len(self._pending)):
                victim = self.qos.queue_full_victim(self._pending, req)
                if victim is not req:
                    self._pending.remove(victim)
                    self._pending.append(req)
                self.qos.note_shed("queue_full")
                self._held_shed += 1
                self._finish_held(victim, self.clock(), "shed",
                                  reason="queue_full")
            else:
                self._pending.append(req)
        else:
            # the scheduler stamps submitted_time itself AFTER its own
            # validation, so a reject leaves the request entirely
            # untouched (TTL clock included) with the caller
            rep.sched.submit(req)
        # counted only once the request is safely queued: a route chaos
        # raise or a validation reject leaves it with the caller, and
        # counting it would inflate the zero-loss `lost` accounting when
        # the caller retries
        self.submitted_total += 1

    def _finish_held(self, req: Request, now: float, outcome: str,
                     reason: str = "") -> None:
        """Terminal disposition of a request that never left the fleet's
        held list (no pages, no scheduler): same trace-close + counter
        contract every scheduler-side terminal path honors."""
        req.outcome = outcome
        if outcome == "shed":
            req.shed_reason = reason
        req.finish_time = now
        self.finished.append(req)
        if req.trace is not None:
            extra = {"reason": reason} if reason else {}
            req.trace.close(now, outcome, generated=0,
                            preemptions=req.preemptions, **extra)
        if telemetry.enabled():
            _req_counter().labels(event=outcome, reason=reason).inc()

    def _expire_pending(self, now: float) -> None:
        """TTL sweep over requests HELD at the fleet — a deadline must
        bind even while no replica can take the work (run from submit()
        as well as step(), so a dead fleet still expires its holds)."""
        for req in list(self._pending):
            if (
                req.deadline_s is not None
                and req.submitted_time is not None
                and now - req.submitted_time > req.deadline_s
            ):
                self._pending.remove(req)
                self._finish_held(req, now, "expired")

    def cancel(self, rid: int) -> bool:
        """Client cancellation, fleet-wide: whichever replica (or the held
        queue) owns `rid` drops it and frees its pages. The terminal record
        is harvested into fleet.finished IMMEDIATELY — idle() ignores the
        schedulers' finished lists, so waiting for the next step() would
        strand a cancel that empties the fleet."""
        for i, req in enumerate(self._pending):
            if req.rid == rid:
                self._pending.pop(i)
                self._finish_held(req, self.clock(), "cancelled")
                return True
        for rep in self.replicas:
            if rep.sched.cancel(rid):
                self.finished.extend(rep.sched.finished)
                rep.sched.finished = []
                return True
        return False

    def _redispatch(self, req: Request, reason: str) -> None:
        rep = self._route(req, reason_override=reason)
        if rep is None:
            self._pending.append(req)
            return
        try:
            rep.sched.submit(req)
        except Exception:
            # a replica that can't legally take this request (heterogeneous
            # engine limits) must neither crash the tick nor silently drop
            # the REST of the evacuation/held list — park it; the next tick
            # retries (possibly onto a different replica) and its TTL can
            # still expire it, so nothing is ever lost unaccounted
            self._pending.append(req)

    def _flush_pending(self) -> None:
        if not self._pending or not self.healthy():
            return
        held, self._pending = self._pending, []
        for req in held:
            # internal path (no chaos site, no re-count): a request that
            # still can't route lands back in _pending, never on the floor
            self._redispatch(req, reason="requeued")

    # ---- health ----
    def _note_failure(self, rep: _Replica, reason: str) -> None:
        rep.consecutive_failures += 1
        self.failures_total += 1
        if telemetry.enabled():
            _failure_counter(rep.idx, reason).inc()
        if rep.consecutive_failures >= self.breaker_threshold:
            self._kill(rep)
        elif rep.status == ReplicaStatus.HEALTHY:
            # circuit half-open: stop admissions, keep stepping in-flight
            # work — one good step closes it again
            rep.status = ReplicaStatus.DRAINING

    def _kill(self, rep: _Replica) -> None:
        rep.status = ReplicaStatus.DOWN
        rep.draining_for_swap = False
        _rt.record_event("fleet", "replica_down", t=self.clock(),
                         replica=rep.idx,
                         failures=rep.consecutive_failures)
        # break session affinity: homes on a dead replica re-route freely
        for s, idx in list(self._session_home.items()):
            if idx == rep.idx:
                del self._session_home[s]
        evacuated = rep.sched.evacuate()
        self.evacuated_total += len(evacuated)
        if telemetry.enabled() and evacuated:
            _evac_counter().inc(len(evacuated))
        for req in evacuated:
            self._redispatch(req, reason="evacuated")
        # a dead replica can't finish its drain — hand the swap machine on
        sw = self._swap
        if sw is not None:
            if sw.get("active") == rep.idx:
                sw["active"] = None
            if rep.idx in sw["queue"]:
                sw["queue"].remove(rep.idx)

    # ---- weight hot-swap ----
    def request_swap(self, source, state_key: Optional[str] = "model") -> None:
        """Begin a zero-downtime rollout: every live replica, one at a
        time, is drained and re-weighted from `source` — a checkpoint root
        or `step_<N>/` path (streamed via `load_weights_from_checkpoint`),
        or a name->array mapping (applied via `load_weights`). Progress
        happens inside step(); the fleet stays serving throughout."""
        if self._swap is not None:
            raise RuntimeError("a weight swap is already in progress")
        self._swap = {
            "source": source,
            "state_key": state_key,
            "queue": [r.idx for r in self.replicas if r.status != ReplicaStatus.DOWN],
            "active": None,
            "t_active": None,
            "swapped": 0,
        }
        self._swap_t0 = self.clock()
        if telemetry.enabled():
            _swap_counter("requested").inc()
        # the rollout starts NOW, not at the next tick: the first target
        # drains (and, if already idle, swaps) synchronously so no request
        # routed after this call lands on about-to-be-swapped weights
        self._advance_swap(self.clock())

    def swap_in_progress(self) -> bool:
        return self._swap is not None

    def _perform_swap(self, rep: _Replica) -> None:
        src = self._swap["source"]
        if isinstance(src, str):
            rep.engine.load_weights_from_checkpoint(
                src, state_key=self._swap["state_key"]
            )
        else:
            rep.engine.load_weights(src)
        if telemetry.enabled():
            _metrics.gauge(
                "paddle_tpu_fleet_weights_version",
                "engine weights_version per replica (a half-finished "
                "rollout is visible as a version split)",
                label_names=("replica",),
            ).labels(replica=str(rep.idx)).set(rep.engine.weights_version)

    def _advance_swap(self, now: float) -> None:
        sw = self._swap
        if sw is None:
            return
        if sw["active"] is None:
            while sw["queue"]:
                idx = sw["queue"].pop(0)
                rep = self.replicas[idx]
                if rep.status == ReplicaStatus.DOWN:
                    continue
                rep.status = ReplicaStatus.DRAINING
                rep.draining_for_swap = True
                rep.sched.drain()
                # its waiting queue holds no pages — migrate it now so
                # those requests don't wait out the drain
                waiting, rep.sched.waiting = list(rep.sched.waiting), []
                for req in waiting:
                    self._redispatch(req, reason="migrated")
                sw["active"] = idx
                sw["t_active"] = now
                if telemetry.enabled():
                    _swap_counter("drain_started").inc()
                return
            # queue empty, nothing active: the rollout is over — but it
            # only COUNTS as completed if at least one replica was actually
            # re-weighted (every target dying mid-rollout must not report
            # a successful swap, nor record a blip window over nothing)
            self._swap = None
            if sw["swapped"]:
                self.swap_windows.append((self._swap_t0, now))
                self.swaps_completed += 1
                _rt.record_span("fleet", "swap_rollout", self._swap_t0, now,
                                swapped=sw["swapped"])
                if telemetry.enabled():
                    _swap_counter("completed").inc()
            elif telemetry.enabled():
                _swap_counter("aborted").inc()
            return
        rep = self.replicas[sw["active"]]
        # keep the drain target's waiting queue empty EVERY tick, not just
        # at drain start: pool-pressure preemption during the drain
        # re-queues its victim LOCALLY, where blocked admission would
        # otherwise deadlock the swap (waiting never empties)
        if rep.sched.waiting:
            waiting, rep.sched.waiting = list(rep.sched.waiting), []
            for req in waiting:
                self._redispatch(req, reason="migrated")
        if not rep.sched.running and not rep.sched.waiting:
            try:
                self._perform_swap(rep)
            except Exception:
                # a failed load must not wedge the fleet: abort the rollout
                # cleanly — the target resumes serving its OLD weights (an
                # earlier-swapped replica keeps the new ones: the version
                # split is visible in the weights_version gauge) — and the
                # error surfaces to the operator
                rep.sched.resume_admission()
                rep.status = ReplicaStatus.HEALTHY
                rep.draining_for_swap = False
                self._swap = None
                if telemetry.enabled():
                    _swap_counter("failed").inc()
                raise
            sw["swapped"] += 1
            rep.sched.resume_admission()
            rep.status = ReplicaStatus.HEALTHY
            rep.draining_for_swap = False
            rep.consecutive_failures = 0
            # the per-replica drain window: requests whose queue/preempt
            # time overlaps these spans get it attributed as swap_overlap
            _rt.record_span("fleet", "swap_drain", sw["t_active"], now,
                            replica=rep.idx)
            if telemetry.enabled():
                _swap_counter("replica_swapped").inc()
                _drain_hist().observe(max(0.0, now - sw["t_active"]))
            sw["active"] = None
            # pick the next target immediately: a one-replica fleet must
            # finish its swap on THIS step, not leak an extra idle tick
            self._advance_swap(now)

    # ---- the fleet tick ----
    def step(self) -> int:
        """One fleet tick: advance any rollout, flush held requests, step
        every live replica through its chaos site, harvest finished work.
        Returns tokens produced across the fleet."""
        now = self.clock()
        self._advance_swap(now)
        self._expire_pending(now)
        self._flush_pending()
        # fatal only when every replica is fully DOWN: a merely-DRAINING
        # (half-open) replica is alive and one good step re-opens it, so
        # raising there would crash a fleet mid-recovery
        if self._pending and all(
            r.status == ReplicaStatus.DOWN for r in self.replicas
        ):
            raise NoHealthyReplica(
                f"{len(self._pending)} request(s) held with every replica down"
            )
        produced = 0
        for rep in self.replicas:
            if rep.status == ReplicaStatus.DOWN:
                continue
            if not rep.busy():
                # a half-open circuit with NOTHING in flight has no step
                # left to prove itself on — close it here, or the replica
                # is skipped forever (no traffic routes to a non-healthy
                # replica, so it would never become busy again)
                if rep.status == ReplicaStatus.DRAINING and not rep.draining_for_swap:
                    rep.consecutive_failures = 0
                    rep.status = ReplicaStatus.HEALTHY
                continue
            try:
                # the delay fault sleeps INSIDE this point — measuring from
                # before it is what lets a delay spec trip the heartbeat
                # breaker (a hung/slow step, not an exception)
                t0 = self.clock()
                _fi.fault_point(f"fleet.replica_step.{rep.idx}", replica=rep.idx)
                produced += rep.sched.step()
                dt = self.clock() - t0
            except Exception:
                self._note_failure(rep, reason="step")
                continue
            rep.ewma_step_s = (
                dt if rep.ewma_step_s == 0.0 else 0.8 * rep.ewma_step_s + 0.2 * dt
            )
            if telemetry.enabled():
                _step_hist(rep.idx).observe(dt)
            # heartbeat = the replica's OWN step wall time: charging a
            # shared tick clock would blame a stalled peer's 10 s on every
            # healthy replica stepped after it. A deadline miss is a breaker
            # failure even though the step "succeeded"; set the deadline
            # above worst-case first-step compile time.
            if (
                self.heartbeat_deadline_s is not None
                and dt > self.heartbeat_deadline_s
            ):
                self._note_failure(rep, reason="heartbeat")
                continue
            rep.consecutive_failures = 0
            if rep.status == ReplicaStatus.DRAINING and not rep.draining_for_swap:
                rep.status = ReplicaStatus.HEALTHY  # circuit closes
        for rep in self.replicas:
            if rep.sched.finished:
                self.finished.extend(rep.sched.finished)
                rep.sched.finished = []
        if telemetry.enabled():
            self._sync_gauges()
        return produced

    def _sync_gauges(self) -> None:
        counts = {s: 0 for s in ReplicaStatus.ALL}
        for rep in self.replicas:
            counts[rep.status] += 1
            _queue_gauge(rep.idx, "running").set(len(rep.sched.running))
            _queue_gauge(rep.idx, "waiting").set(len(rep.sched.waiting))
        for s, n in counts.items():
            _replicas_gauge(s).set(n)
        _metrics.gauge(
            "paddle_tpu_fleet_held_requests",
            "requests held at the fleet for want of a healthy replica",
        ).set(len(self._pending))

    # ---- convenience: batch greedy generation through the fleet ----
    def generate(self, prompts, max_new_tokens=16) -> List[List[int]]:
        """Greedy-decode every prompt across the fleet; returns generated
        ids per prompt (full output even across preemption/evacuation)."""
        if isinstance(max_new_tokens, int):
            max_new_tokens = [max_new_tokens] * len(prompts)
        reqs = [
            Request(rid=i, prompt=list(p), max_new_tokens=int(m))
            for i, (p, m) in enumerate(zip(prompts, max_new_tokens))
        ]
        for r in reqs:
            self.submit(r)
        while not self.idle():
            self.step()
        # this call's requests are read back directly — drop them from the
        # harvest list, or a long-lived fleet-backed predictor accumulates
        # every request (prompt + tokens) it ever served
        own = {id(r) for r in reqs}
        self.finished = [r for r in self.finished if id(r) not in own]
        self.submitted_total -= len(reqs)
        return [r.prompt[r.prompt_len:] + list(r.generated) for r in reqs]


def fleet_replay(
    fleet: ReplicaFleet,
    requests: Sequence[Request],
    *,
    events: Sequence[tuple] = (),
    clock: Optional[Callable[[], float]] = None,
    max_wall_s: float = 600.0,
) -> Dict:
    """scheduler.replay with mid-run chaos hooks: feed `requests` honoring
    their arrival_time offsets, and fire each `(completed_threshold, fn)`
    event once when that many requests have finished — the deterministic
    trigger the bench/dryrun use to start a weight swap or install a
    replica-kill FaultPlan mid-traffic. Returns the replay stats plus
    fleet accounting (lost/duplicated counts, swap-window p99).

    `clock` defaults to the FLEET's clock: the replay's t0/arrival pacing,
    the schedulers' token timestamps, and the swap windows must share one
    time base or every latency stat is cross-clock garbage."""
    clock = clock or fleet.clock
    pending = sorted(requests, key=lambda r: (r.arrival_time, r.rid))
    fired = [False] * len(events)

    def fire_due():
        for j, (threshold, fn) in enumerate(events):
            if not fired[j] and len(fleet.finished) >= threshold:
                fired[j] = True
                fn()

    t0 = clock()
    rt0 = time.monotonic()
    i = 0
    while i < len(pending) or not fleet.idle():
        now = clock() - t0
        # the watchdog runs on REAL wall time: a frozen/manual fleet clock
        # would otherwise turn the idle-wait into an unbreakable busy-loop
        if time.monotonic() - rt0 > max_wall_s:
            raise TimeoutError(f"fleet replay exceeded {max_wall_s}s wall budget")
        while i < len(pending) and pending[i].arrival_time <= now:
            fleet.submit(pending[i])
            i += 1
        fire_due()
        if fleet.idle():
            if i < len(pending):
                time.sleep(min(0.001, max(0.0, pending[i].arrival_time - now)))
            continue
        fleet.step()
        # re-check AFTER the step too: a threshold first reached by the
        # final (fleet-emptying) step must still fire — and if the fired
        # event starts a swap, idle() goes false and the loop drives it
        fire_due()
    wall = clock() - t0

    done = list(fleet.finished)
    rids = [r.rid for r in done]
    completed = [r for r in done if r.outcome == "completed"]
    ttfts = [
        r.first_token_time - (t0 + r.arrival_time)
        for r in completed
        if r.first_token_time is not None
    ]
    itls = [(iv, t) for r in completed
            for iv, t in zip(np.diff(r.token_times), r.token_times[1:])]
    swap_itls = [
        iv
        for iv, t in itls
        for (ws, we) in fleet.swap_windows
        if ws <= t <= we
    ]
    total_tokens = sum(
        (len(r.prompt) - r.prompt_len) + len(r.generated) for r in completed
    )
    out = {
        "n_requests": len(done),
        "completed": len(completed),
        "lost": fleet.submitted_total - len(set(rids)),
        "duplicated": len(rids) - len(set(rids)),
        "generated_tokens": int(total_tokens),
        "wall_s": round(wall, 4),
        "tokens_per_sec": round(total_tokens / wall, 2) if wall > 0 else None,
        "preempted": fleet.preempted_total,
        "evacuated": fleet.evacuated_total,
        "replica_failures": fleet.failures_total,
        "swaps_completed": fleet.swaps_completed,
    }
    out.update(percentiles("ttft_ms", [t * 1000 for t in ttfts]))
    out.update(percentiles("tpot_ms", [iv * 1000 for iv, _ in itls]))
    out.update(percentiles("tpot_swap_ms", [iv * 1000 for iv in swap_itls]))
    return out
