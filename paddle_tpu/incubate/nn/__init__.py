"""paddle.incubate.nn namespace (reference: python/paddle/incubate/nn/)."""
from . import functional  # noqa: F401
from .layer import (  # noqa: F401
    FusedFeedForward,
    FusedLinear,
    FusedMultiHeadAttention,
    FusedTransformerEncoderLayer,
)
