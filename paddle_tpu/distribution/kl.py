"""KL divergence registry (reference: python/paddle/distribution/kl.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import _wrap

_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    def decorator(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn

    return decorator


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        for (tp, tq), f in _KL_REGISTRY.items():
            if isinstance(p, tp) and isinstance(q, tq):
                fn = f
                break
    if fn is None:
        raise NotImplementedError(f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


# ---- standard pairs ----
from .normal import Normal  # noqa: E402
from .uniform import Uniform  # noqa: E402
from .categorical import Categorical  # noqa: E402
from .bernoulli import Bernoulli  # noqa: E402
from .beta import Beta  # noqa: E402
from .dirichlet import Dirichlet  # noqa: E402
from .exponential import Exponential  # noqa: E402
from .gamma import Gamma  # noqa: E402
from .geometric import Geometric  # noqa: E402
from .laplace import Laplace  # noqa: E402
from .poisson import Poisson  # noqa: E402


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return _wrap(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    lo = p.low >= q.low
    hi = p.high <= q.high
    kl = jnp.log((q.high - q.low) / (p.high - p.low))
    return _wrap(jnp.where(lo & hi, kl, jnp.inf))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    pp = jnp.exp(p._log_norm)
    return _wrap(jnp.sum(pp * (p._log_norm - q._log_norm), -1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    pa = jnp.clip(p.probs_v, 1e-7, 1 - 1e-7)
    qa = jnp.clip(q.probs_v, 1e-7, 1 - 1e-7)
    return _wrap(pa * (jnp.log(pa) - jnp.log(qa)) + (1 - pa) * (jnp.log1p(-pa) - jnp.log1p(-qa)))


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    gl = jax.scipy.special.gammaln
    dg = jax.scipy.special.digamma
    pa, pb = p.alpha, p.beta
    qa, qb = q.alpha, q.beta
    t = (
        gl(qa) + gl(qb) - gl(qa + qb) - (gl(pa) + gl(pb) - gl(pa + pb))
        + (pa - qa) * dg(pa)
        + (pb - qb) * dg(pb)
        + (qa + qb - pa - pb) * dg(pa + pb)
    )
    return _wrap(t)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    gl = jax.scipy.special.gammaln
    dg = jax.scipy.special.digamma
    pa, qa = p.concentration, q.concentration
    pa0 = jnp.sum(pa, -1)
    t = (
        gl(pa0)
        - jnp.sum(gl(pa), -1)
        - gl(jnp.sum(qa, -1))
        + jnp.sum(gl(qa), -1)
        + jnp.sum((pa - qa) * (dg(pa) - dg(pa0)[..., None]), -1)
    )
    return _wrap(t)


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    r = q.rate / p.rate
    return _wrap(jnp.log(1 / r) + r - 1)


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    gl = jax.scipy.special.gammaln
    dg = jax.scipy.special.digamma
    t = (
        (p.concentration - q.concentration) * dg(p.concentration)
        - gl(p.concentration)
        + gl(q.concentration)
        + q.concentration * (jnp.log(p.rate) - jnp.log(q.rate))
        + p.concentration * (q.rate / p.rate - 1)
    )
    return _wrap(t)


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    pp, qp = p.probs_v, q.probs_v
    return _wrap(
        (jnp.log(pp) - jnp.log(qp)) + (1 - pp) / pp * (jnp.log1p(-pp) - jnp.log1p(-qp))
    )


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    # log(b2/b1) + (b1*exp(-|u1-u2|/b1) + |u1-u2|)/b2 - 1
    d = jnp.abs(p.loc - q.loc)
    return _wrap(
        jnp.log(q.scale / p.scale) + (p.scale * jnp.exp(-d / p.scale) + d) / q.scale - 1
    )


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    return _wrap(p.rate * (jnp.log(p.rate) - jnp.log(q.rate)) - p.rate + q.rate)
