"""reference cinn/runtime: low-level IR jit hooks; XLA owns codegen here."""


class CinnLowerLevelIrJit:
    def __init__(self, *a, **k):
        raise RuntimeError("CINN runtime is subsumed by XLA")


class Module:
    def __init__(self, *a, **k):
        raise RuntimeError("CINN runtime is subsumed by XLA")


__all__ = ["CinnLowerLevelIrJit", "Module"]
