from ..recompute.recompute import recompute, recompute_sequential  # noqa: F401
from . import sequence_parallel_utils  # noqa: F401
from .fs import FS, HDFSClient, LocalFS  # noqa: F401
from .ps_util import DistributedInfer  # noqa: F401

__all__ = ["LocalFS", "recompute", "DistributedInfer", "HDFSClient"]
