"""paddle.sparse.nn.functional.

Reference parity: python/paddle/sparse/nn/functional/__init__.py (conv.py
conv2d:413 / conv3d:195 / subm_conv2d:517 / subm_conv3d:301, pooling.py
max_pool3d, activation.py, transformer.py attention) over
paddle/phi/kernels/sparse/. Convs run the rulebook engine
(sparse/conv_engine.py): host-built dense int32 gather/scatter tables,
one MXU matmul per kernel offset. Ops thread tape-connected values
Tensors (SparseTensor._grad_values) so sparse nets train end-to-end.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ....core.apply import apply
from ....core.tensor import Tensor
from ... import SparseTensor
from ...conv_engine import build_rulebook, conv_values, pool_values, _check_concrete

__all__ = [
    'conv2d',
    'conv3d',
    'subm_conv2d',
    'subm_conv3d',
    'max_pool3d',
    'relu',
    'relu6',
    'leaky_relu',
    'softmax',
    'attention',
]


def _coo(x):
    if not isinstance(x, SparseTensor) or not x.is_sparse_coo():
        raise ValueError("expected a sparse COO tensor (NDHWC/NHWC layout)")
    return x._mat


def _wrap_with_values(indices, values_t, shape):
    st = SparseTensor(
        jsparse.BCOO((values_t._value, jnp.asarray(indices)), shape=tuple(shape)),
        kind="coo",
    )
    st._grad_values = values_t
    return st


def _conv(x, weight, bias, stride, padding, dilation, groups, subm, nd, name):
    if groups != 1:
        raise NotImplementedError("sparse conv: only groups=1 is supported")
    mat = _coo(x)
    _check_concrete(mat.indices, "indices")
    coords = np.asarray(mat.indices)
    spatial = tuple(int(s) for s in x.shape[1:1 + nd])
    w = weight if isinstance(weight, Tensor) else Tensor(jnp.asarray(weight))
    kernel = tuple(int(k) for k in w.shape[:nd])
    if subm and (stride not in (1, [1] * nd, tuple([1] * nd))):
        raise ValueError("submanifold conv requires stride 1")
    out_coords, pairs, out_spatial = build_rulebook(
        coords, spatial, kernel, stride, padding, dilation, subm)
    n_out = len(out_coords)
    feats = x.values()
    cout = int(w.shape[-1])

    args = [feats, w] + ([bias] if bias is not None else [])

    def fn(f, wv, *rest):
        return conv_values(f, wv, pairs, n_out, rest[0] if rest else None)

    out_vals = apply(name, fn, *args)
    out_shape = (int(x.shape[0]),) + tuple(out_spatial) + (cout,)
    return _wrap_with_values(out_coords, out_vals, out_shape)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Sparse 3-D convolution (reference functional/conv.py:195)."""
    if data_format != "NDHWC":
        raise ValueError("sparse conv3d only supports NDHWC")
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 subm=False, nd=3, name="sparse_conv3d")


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold sparse 3-D conv (reference functional/conv.py:301):
    output active sites == input active sites."""
    if data_format != "NDHWC":
        raise ValueError("sparse subm_conv3d only supports NDHWC")
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 subm=True, nd=3, name="sparse_subm_conv3d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    """Sparse 2-D convolution (reference functional/conv.py:413)."""
    if data_format != "NHWC":
        raise ValueError("sparse conv2d only supports NHWC")
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 subm=False, nd=2, name="sparse_conv2d")


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    """Submanifold sparse 2-D conv (reference functional/conv.py:517)."""
    if data_format != "NHWC":
        raise ValueError("sparse subm_conv2d only supports NHWC")
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 subm=True, nd=2, name="sparse_subm_conv2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """Sparse max pooling (reference functional/pooling.py): only active
    sites participate — scatter-max over the same rulebook tables."""
    if data_format != "NDHWC":
        raise ValueError("sparse max_pool3d only supports NDHWC")
    if ceil_mode:
        raise NotImplementedError("sparse max_pool3d: ceil_mode not supported")
    mat = _coo(x)
    _check_concrete(mat.indices, "indices")
    coords = np.asarray(mat.indices)
    spatial = tuple(int(s) for s in x.shape[1:4])
    stride = stride if stride is not None else kernel_size
    out_coords, pairs, out_spatial = build_rulebook(
        coords, spatial, kernel_size, stride, padding, 1, subm=False)
    n_out = len(out_coords)
    feats = x.values()
    out_vals = apply("sparse_max_pool3d",
                     lambda f: pool_values(f, pairs, n_out), feats)
    out_shape = (int(x.shape[0]),) + tuple(out_spatial) + (int(x.shape[-1]),)
    return _wrap_with_values(out_coords, out_vals, out_shape)


def _unary(x, fn, name):
    """Zero-preserving activation over stored values, tape-threaded."""
    mat = x._mat
    v = x.values()
    out_vals = apply(name, fn, v)
    if isinstance(mat, jsparse.BCSR):
        st = SparseTensor(
            jsparse.BCSR((out_vals._value, mat.indices, mat.indptr), shape=mat.shape),
            kind="csr")
    else:
        st = SparseTensor(
            jsparse.BCOO((out_vals._value, mat.indices), shape=mat.shape),
            kind="coo")
    st._grad_values = out_vals
    return st


def relu(x, name=None):
    return _unary(x, jax.nn.relu, "sparse_relu")


def relu6(x, name=None):
    return _unary(x, lambda v: jnp.clip(v, 0.0, 6.0), "sparse_relu6")


def leaky_relu(x, negative_slope=0.01, name=None):
    return _unary(x, lambda v: jnp.where(v >= 0, v, negative_slope * v),
                  "sparse_leaky_relu")


def softmax(x, axis=-1, name=None):
    """Sparse softmax over the last axis (reference functional/
    activation.py softmax): zeros are -inf — only stored values in each row
    participate. CSR rows via indptr segments; 2-D COO via row segment-ids
    (segment reductions lower to one XLA scatter, TPU-friendly)."""
    if axis != -1:
        raise ValueError("sparse softmax only supports axis=-1")
    mat = x._mat
    v = x.values()
    if isinstance(mat, jsparse.BCSR):
        nrows = int(mat.shape[-2])
        counts = jnp.diff(mat.indptr)
        seg = jnp.repeat(jnp.arange(nrows), counts,
                         total_repeat_length=int(mat.nse))

        def fn(vals):
            mx = jax.ops.segment_max(vals, seg, num_segments=nrows)
            e = jnp.exp(vals - mx[seg])
            s = jax.ops.segment_sum(e, seg, num_segments=nrows)
            return e / s[seg]

        out_vals = apply("sparse_softmax_csr", fn, v)
        st = SparseTensor(
            jsparse.BCSR((out_vals._value, mat.indices, mat.indptr), shape=mat.shape),
            kind="csr")
        st._grad_values = out_vals
        return st
    # COO: segment = all dims but the last
    idx = mat.indices
    lead_shape = mat.shape[:-1]
    strides = np.cumprod([1] + list(lead_shape[::-1]))[::-1][1:]
    seg = (idx[:, :-1] * jnp.asarray(np.asarray(strides), idx.dtype)).sum(-1)
    nseg = int(np.prod(lead_shape))

    def fn(vals):
        mx = jax.ops.segment_max(vals, seg, num_segments=nseg)
        e = jnp.exp(vals - mx[seg])
        s = jax.ops.segment_sum(e, seg, num_segments=nseg)
        return e / s[seg]

    out_vals = apply("sparse_softmax_coo", fn, v)
    st = SparseTensor(jsparse.BCOO((out_vals._value, idx), shape=mat.shape),
                      kind="coo")
    st._grad_values = out_vals
    return st


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-mask attention (reference functional/transformer.py:
    attention over phi sparse fused_attention): softmax(QK^T/sqrt(d) +
    masks) evaluated at sparse_mask's CSR nonzeros, then @ V. Delegates to
    the CSR sparse_attention kernel path (nn/functional/attention.py)."""
    from ....nn.functional.attention import sparse_attention

    b, h, s, d = (int(v) for v in query.shape)
    offset = sparse_mask.crows()
    columns = sparse_mask.cols()
    from ....ops import manipulation as _mp

    off = _mp.reshape(offset, [b, h, -1]) if offset.ndim == 1 else offset
    col = _mp.reshape(columns, [b, h, -1]) if columns.ndim == 1 else columns
    return sparse_attention(query, key, value, off, col,
                            key_padding_mask=key_padding_mask,
                            attn_mask=attn_mask)
