"""paddle.incubate.autotune.set_config parity
(reference: python/paddle/incubate/autotune.py).

The reference toggles kernel autotuning (cuDNN algo search), dataloader
worker tuning, and AMP list tuning. TPU-native: kernel search is XLA's
autotuner (latency-hiding scheduler + dot fusion autotuning are always on);
what remains meaningful here is dataloader tuning: DataLoader consults
get_config() at iteration start and deepens its prefetch when enabled.
"""
from __future__ import annotations

import json
import warnings

_config = {
    "kernel": {"enable": False, "tuning_range": [1, 10]},
    "layout": {"enable": False},
    "dataloader": {"enable": False},
}


def set_config(config=None):
    global _config
    if config is None:
        _config = {k: dict(v, enable=True) for k, v in _config.items()}
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    for key in ("kernel", "layout", "dataloader"):
        if key in config:
            if not isinstance(config[key], dict):
                warnings.warn(f"autotune config [{key}] must be a dict; ignored")
                continue
            _config[key].update(config[key])


def get_config():
    return {k: dict(v) for k, v in _config.items()}
