"""The `*_` inplace op family.

Reference parity: python/paddle/tensor/math.py / manipulation.py /
logic.py inplace variants (``abs_`` ... ``where_``), generated from the
``@inplace_apis_in_dygraph_only`` wrappers there.

TPU-native design: jax arrays are immutable, so "inplace" is a Tensor
IDENTITY contract, not a buffer contract: ``x.op_()`` rebinds x's value to
the op's result (``Tensor._become``) and returns x. Under ``to_static``
capture the _become write is recorded as a state mutation, so compiled
programs carry the update exactly like any other parameter write; XLA's
buffer donation then makes it a true in-place buffer reuse on-device.

Inplace comparison/logical variants change dtype (paddle semantics: the
result REPLACES x, bool result included) — _become carries the new dtype.
"""
from __future__ import annotations

import math as _pymath

import numpy as np
from jax import numpy as jnp

from ..core.tensor import Tensor, _ensure_tensor
from ..core.apply import apply
from . import creation, linalg, logic, manipulation, math, search

_MODULES = (math, manipulation, logic, search, creation, linalg)


def _resolve(name):
    for m in _MODULES:
        fn = getattr(m, name, None)
        if fn is not None:
            return fn
    raise AttributeError(f"inplace generator: no base op `{name}`")


def _make_inplace(name):
    base = _resolve(name)

    def op_(x, *args, **kwargs):
        x._become(base(x, *args, **kwargs))
        return x

    op_.__name__ = name + "_"
    op_.__qualname__ = name + "_"
    op_.__doc__ = (
        f"Inplace variant of :func:`{name}` (rebinds x to the result and "
        f"returns x; see module docstring for the TPU inplace contract)."
    )
    return op_


# every name here has its base op in one of _MODULES; the variant is purely
# mechanical. Ops whose inplace form needs custom argument order or has no
# base (random fills) are defined explicitly below.
_MECHANICAL = [
    "abs", "acos", "acosh", "addmm", "asin", "asinh", "atan", "atanh",
    "bitwise_and", "bitwise_left_shift", "bitwise_not", "bitwise_or",
    "bitwise_right_shift", "bitwise_xor",
    "cast", "copysign", "cos", "cosh", "cumprod", "cumsum",
    "digamma", "divide", "equal", "erf", "expm1",
    "flatten", "floor_divide", "floor_mod", "frac",
    "erfinv", "gammainc", "gammaincc", "gammaln", "gcd",
    "greater_equal", "greater_than", "hypot", "i0",
    "index_add", "index_put", "lcm", "ldexp", "lerp", "less_equal", "less_than",
    "lgamma", "log", "log10", "log1p", "log2",
    "logical_and", "logical_not", "logical_or", "logical_xor", "logit",
    "masked_scatter", "mod", "multigammaln", "multiply",
    "nan_to_num", "neg", "not_equal", "polygamma", "pow",
    "put_along_axis", "remainder", "renorm", "sigmoid",
    "sin", "sinh", "square", "tan", "tanh", "tril", "triu", "trunc",
]

_g = globals()
for _name in _MECHANICAL:
    _g[_name + "_"] = _make_inplace(_name)


def t_(x, name=None):
    """Inplace transpose of a 0/1/2-D tensor (tensor/linalg.py t_)."""
    x._become(manipulation.t(x))
    return x


def transpose_(x, perm, name=None):
    """Inplace permute (tensor/manipulation.py transpose_)."""
    x._become(manipulation.transpose(x, perm))
    return x


def where_(condition, x=None, y=None, name=None):
    """Inplace select: x becomes where(condition, x, y) (tensor/search.py)."""
    out = manipulation.where(condition, x, y)
    x._become(out)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    """Fill x with N(mean, std) samples (tensor/random.py normal_)."""
    from ..framework import random as random_mod

    shape = tuple(x._value.shape)

    def fn(v):
        import jax

        key = random_mod.next_key()
        return (jax.random.normal(key, shape, jnp.float32) * std + mean).astype(v.dtype)

    x._become(apply("normal_", fn, x))
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    """Fill x with U(min, max) samples (tensor/random.py uniform_)."""
    from ..framework import random as random_mod

    shape = tuple(x._value.shape)

    def fn(v):
        import jax

        key = random_mod.next_key()
        return jax.random.uniform(
            key, shape, jnp.float32, minval=min, maxval=max
        ).astype(v.dtype)

    x._become(apply("uniform_", fn, x))
    return x


def cauchy_(x, loc=0, scale=1, name=None):
    """Fill x with Cauchy(loc, scale) samples via inverse-CDF of a uniform
    draw (tensor/creation.py:2892)."""
    from ..framework import random as random_mod

    shape = tuple(x._value.shape)

    def fn(v):
        import jax

        key = random_mod.next_key()
        u = jax.random.uniform(key, shape, jnp.float32, minval=1e-7, maxval=1.0 - 1e-7)
        return (loc + scale * jnp.tan(_pymath.pi * (u - 0.5))).astype(v.dtype)

    x._become(apply("cauchy_", fn, x))
    return x


def geometric_(x, probs, name=None):
    """Fill x with Geometric(probs) samples (support {1, 2, ...}) via
    inverse-CDF (tensor/creation.py:2926)."""
    from ..framework import random as random_mod

    shape = tuple(x._value.shape)
    p = probs._value if isinstance(probs, Tensor) else probs

    def fn(v):
        import jax

        key = random_mod.next_key()
        u = jax.random.uniform(key, shape, jnp.float32, minval=1e-7, maxval=1.0 - 1e-7)
        return jnp.ceil(jnp.log(u) / jnp.log1p(-jnp.asarray(p, jnp.float32))).astype(v.dtype)

    x._become(apply("geometric_", fn, x))
    return x


__all__ = (
    [n + "_" for n in _MECHANICAL]
    + ["t_", "transpose_", "where_", "normal_", "uniform_", "cauchy_", "geometric_", "exponential_"]
)


def exponential_(x, lam=1.0, name=None):
    """Fill x with Exponential(lam) samples via inverse-CDF
    (tensor/random patch family; reference Tensor.exponential_)."""
    from ..framework import random as random_mod

    shape = tuple(x._value.shape)

    def fn(v):
        import jax

        key = random_mod.next_key()
        u = jax.random.uniform(key, shape, jnp.float32, minval=1e-7, maxval=1.0 - 1e-7)
        return (-jnp.log1p(-u) / lam).astype(v.dtype)

    x._become(apply("exponential_", fn, x))
    return x


def patch_tensor_inplace():
    """Attach every inplace op as a Tensor method (reference: the
    monkey-patch tables in tensor/__init__.py tensor_method_func)."""
    for n in __all__:
        fn = _g[n]
        if n == "where_":
            # method form: x.where_(y, condition) per tensor patch semantics
            def m(self, y, condition, _fn=fn):
                return _fn(condition, self, y)

            setattr(Tensor, n, m)
        else:
            setattr(Tensor, n, fn)
