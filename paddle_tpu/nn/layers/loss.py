"""Loss layers (python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from ..layer import Layer
from .. import functional as F


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(
            input, label, weight=self.weight, ignore_index=self.ignore_index,
            reduction=self.reduction, soft_label=self.soft_label, axis=self.axis,
            use_softmax=self.use_softmax, label_smoothing=self.label_smoothing,
        )


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.nll_loss(input, label, self.weight, self.ignore_index, self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight, self.reduction, self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input, label):  # noqa: A002
        return F.kl_div(input, label, self.reduction, self.log_target)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):  # noqa: A002
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths, norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths, self.blank, self.reduction, norm_by_times)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):  # noqa: A002
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):  # noqa: A002
        return F.triplet_margin_loss(input, positive, negative, *self.args)


class SigmoidFocalLoss(Layer):
    def __init__(self, alpha=0.25, gamma=2.0, normalizer=None, reduction="sum", name=None):
        super().__init__()
        self.alpha, self.gamma, self.normalizer, self.reduction = alpha, gamma, normalizer, reduction

    def forward(self, logit, label):
        return F.sigmoid_focal_loss(logit, label, self.normalizer, self.alpha, self.gamma, self.reduction)


# ---------------------------------------------------------------------------
# r3 loss layers (namespace parity audit; reference nn/layer/loss.py)
# ---------------------------------------------------------------------------

class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):  # noqa: A002
        return F.gaussian_nll_loss(input, label, variance, self.full, self.epsilon, self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8, reduction="mean", name=None):
        super().__init__()
        self.log_input, self.full, self.epsilon, self.reduction = log_input, full, epsilon, reduction

    def forward(self, input, label):  # noqa: A002
        return F.poisson_nll_loss(input, label, self.log_input, self.full, self.epsilon, self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):  # noqa: A002
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin, self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_label_soft_margin_loss(input, label, self.weight, self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean", name=None):
        super().__init__()
        self.p, self.margin, self.weight, self.reduction = p, margin, weight, reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_margin_loss(input, label, self.p, self.margin, self.weight, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False, reduction="mean", name=None):
        super().__init__()
        self.distance_function, self.margin, self.swap, self.reduction = (
            distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):  # noqa: A002
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin, self.swap, self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid loss layer owning the tree weights
    (reference nn/layer/loss.py HSigmoidLoss: weight [C, D], bias [C, 1]
    with C = num_classes-1 for the default tree)."""

    def __init__(self, feature_size, num_classes, weight_attr=None, bias_attr=None,
                 is_custom=False, is_sparse=False, name=None):
        super().__init__()
        if not is_custom and num_classes < 2:
            raise ValueError("num_classes must be >= 2 for the default tree")
        c = num_classes if is_custom else num_classes - 1
        self._num_classes = num_classes
        self._is_custom = is_custom
        self.weight = self.create_parameter([c, feature_size], attr=weight_attr)
        self.bias = self.create_parameter([c, 1], attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):  # noqa: A002
        from ...ops.manipulation import reshape as _reshape

        # state_dict keeps the reference's [C, 1] bias; the functional
        # contract (and its per-node add) is flat [C]
        return F.hsigmoid_loss(
            input, label, self._num_classes, self.weight, _reshape(self.bias, [-1]),
            path_table=path_table, path_code=path_code)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean", name=None):
        super().__init__()
        self.blank, self.fastemit_lambda, self.reduction = blank, fastemit_lambda, reduction

    def forward(self, input, label, input_lengths, label_lengths):  # noqa: A002
        return F.rnnt_loss(
            input, label, input_lengths, label_lengths, self.blank,
            self.fastemit_lambda, self.reduction)
