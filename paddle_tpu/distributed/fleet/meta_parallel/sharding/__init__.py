from .group_sharded_stage2 import (  # noqa: F401
    GroupShardedOptimizerStage2,
    GroupShardedStage2,
)
from .group_sharded_stage3 import GroupShardedStage3  # noqa: F401
from . import group_sharded_utils  # noqa: F401
