"""Fused ops (reference: python/paddle/incubate/nn/functional/ —
fused_rms_norm.py, swiglu.py, fused_transformer.py, fused_rotary_position_
embedding.py, fused_dropout_add.py).

TPU-native: "fused" here means (a) a Pallas kernel where the fusion is
genuinely profitable (rms_norm: one VMEM pass instead of two reductions) and
(b) jit-scoped jnp expressions elsewhere — XLA fuses elementwise chains into
the surrounding matmuls on its own, so the CUDA-style mega-kernels of the
reference collapse to composition.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ....core.apply import apply
from ....core.tensor import Tensor

_BLOCK_R = 256


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


# ---------------------------------------------------------------------------
# rms_norm — Pallas kernel
# ---------------------------------------------------------------------------

def _rms_norm_ref(x, w, b, eps):
    ms = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    out = x.astype(jnp.float32) * jax.lax.rsqrt(ms + eps)
    out = out * w.astype(jnp.float32)
    if b is not None:
        out = out + b.astype(jnp.float32)
    return out.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _rms_norm_pallas_2d(x, w, b, eps, has_bias):
    """Pallas forward + reference-impl backward: pallas_call has no built-in
    AD rule, so the vjp recomputes through _rms_norm_ref (same pattern as
    flash_attention_bshd in ops/pallas.py)."""
    return _rms_norm_pallas_fwd_impl(x, w, b, eps, has_bias)


def _rms_norm_fwd_rule(x, w, b, eps, has_bias):
    return _rms_norm_pallas_fwd_impl(x, w, b, eps, has_bias), (x, w, b)


def _rms_norm_bwd_rule(eps, has_bias, res, g):
    x, w, b = res
    _, vjp = jax.vjp(lambda a, ww, bb: _rms_norm_ref(a, ww, bb if has_bias else None, eps), x, w, b)
    return vjp(g)


_rms_norm_pallas_2d.defvjp(_rms_norm_fwd_rule, _rms_norm_bwd_rule)


@functools.partial(jax.jit, static_argnames=("eps", "has_bias"))
def _rms_norm_pallas_fwd_impl(x, w, b, eps, has_bias):
    """Rows-normalize [R, D] in one VMEM pass (pallas_guide.md pattern:
    block rows, keep the row reduction in-register)."""
    from jax.experimental import pallas as pl

    r, d = x.shape

    def kernel(x_ref, w_ref, b_ref, o_ref):
        xb = x_ref[...].astype(jnp.float32)
        ms = jnp.mean(xb * xb, axis=-1, keepdims=True)
        out = xb * jax.lax.rsqrt(ms + eps) * w_ref[...].astype(jnp.float32)
        if has_bias:
            out = out + b_ref[...].astype(jnp.float32)
        o_ref[...] = out.astype(o_ref.dtype)

    block_r = _BLOCK_R
    while r % block_r:
        block_r //= 2
        if block_r == 0:
            return _rms_norm_ref(x, w, b if has_bias else None, eps)
    bz = b if has_bias else jnp.zeros_like(w)
    return pl.pallas_call(
        kernel,
        grid=(r // block_r,),
        in_specs=[
            pl.BlockSpec((block_r, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_r, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((r, d), x.dtype),
    )(x, w, bz)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1, **kw):
    """paddle.incubate.nn.functional.fused_rms_norm parity."""

    def fn(xv, wv, *rest):
        bv = rest[0] if norm_bias is not None else None
        if begin_norm_axis not in (-1, xv.ndim - 1):
            raise NotImplementedError("fused_rms_norm normalizes the last axis")
        d = xv.shape[-1]
        lead = xv.shape[:-1]
        x2 = xv.reshape(-1, d)
        rows = x2.shape[0]
        use_pallas = _on_tpu() and d % 128 == 0 and rows % 8 == 0
        if use_pallas:
            from ...ops.pallas import enable_x64  # version-compat alias

            with enable_x64(False):  # Mosaic rejects i64 index types
                bz = bv if bv is not None else jnp.zeros_like(wv)
                out = _rms_norm_pallas_2d(x2, wv, bz, float(epsilon), bv is not None)
        else:
            out = _rms_norm_ref(x2, wv, bv, float(epsilon))
        return out.reshape(*lead, d)

    args = [x, norm_weight] + ([norm_bias] if norm_bias is not None else [])
    return apply("fused_rms_norm", fn, *args)


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, begin_norm_axis=-1, **kw):
    # the canonical layer norm lives in nn/functional/norm.py; begin_norm_axis
    # selects how many trailing axes normalize (reference semantics)
    from ....nn.functional.norm import layer_norm as _layer_norm

    ndim = len(x.shape)
    begin = begin_norm_axis % ndim
    if begin == ndim - 1:
        return _layer_norm(x, int(x.shape[-1]), norm_weight, norm_bias, epsilon)
    # multi-axis case: reference stores weight/bias flat over prod(trailing
    # dims) — flatten, normalize, restore
    shape = [int(d) for d in x.shape]
    lead, prod = shape[:begin], 1
    for d in shape[begin:]:
        prod *= d
    out = _layer_norm(x.reshape(lead + [prod]), prod, norm_weight, norm_bias, epsilon)
    return out.reshape(shape)


# ---------------------------------------------------------------------------
# activations / glu
# ---------------------------------------------------------------------------

def swiglu(x, y=None, name=None):
    """reference swiglu.py: silu(x) * y; with y=None, x splits in half."""
    if y is None:
        return apply("swiglu", lambda v: (lambda a, b: jax.nn.silu(a) * b)(*jnp.split(v, 2, axis=-1)), x)
    return apply("swiglu", lambda a, b: jax.nn.silu(a) * b, x, y)


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    acts = {
        "gelu": jax.nn.gelu,
        "relu": jax.nn.relu,
        "silu": jax.nn.silu,
        "swiglu": lambda v: (lambda a, b: jax.nn.silu(a) * b)(*jnp.split(v, 2, axis=-1)),
        "geglu": lambda v: (lambda a, b: jax.nn.gelu(a) * b)(*jnp.split(v, 2, axis=-1)),
    }
    act = acts[act_method]
    if bias is None:
        return apply(f"fused_bias_{act_method}", act, x)
    return apply(f"fused_bias_{act_method}", lambda v, b: act(v + b), x, bias)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", seed=None, name=None):
    """reference fused_dropout_add.py: dropout(x) + y. Delegates to the
    canonical dropout (nn/functional/common.py) so mode semantics — incl.
    downscale_in_infer's (1-p) eval scaling — stay in one place; XLA fuses
    the add."""
    from ....nn.functional.common import dropout as _dropout

    return _dropout(x, p=p, training=training, mode=mode) + y


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def fn(xv, wv, *rest):
        w = wv.T if transpose_weight else wv
        out = xv @ w
        if rest:
            out = out + rest[0]
        return out

    args = [x, weight] + ([bias] if bias is not None else [])
    return apply("fused_linear", fn, *args)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False, activation="gelu"):
    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "none": lambda v: v}
    act = acts[activation]

    def fn(xv, yv, bv):
        a = xv.T if trans_x else xv
        b = yv.T if trans_y else yv
        return act(a @ b + bv)

    return apply("fused_linear_activation", fn, x, y, bias)


# ---------------------------------------------------------------------------
# rotary embedding
# ---------------------------------------------------------------------------

def fused_rotary_position_embedding(
    q, k=None, v=None, sin=None, cos=None, position_ids=None, use_neox_rotary_style=True, name=None
):
    """reference fused_rotary_position_embedding.py. q/k/v: [B, S, H, D];
    sin/cos: [1, S, 1, D] (auto-built when not given)."""

    def build_sincos(s, d, dtype):
        inv = 1.0 / (10000.0 ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
        t = jnp.arange(s, dtype=jnp.float32)
        freqs = jnp.outer(t, inv)  # [S, D/2]
        emb = jnp.concatenate([freqs, freqs], axis=-1) if use_neox_rotary_style else jnp.repeat(freqs, 2, axis=-1)
        return jnp.sin(emb).astype(dtype)[None, :, None, :], jnp.cos(emb).astype(dtype)[None, :, None, :]

    def rotate(xv, sinv, cosv):
        if use_neox_rotary_style:
            half = xv.shape[-1] // 2
            x1, x2 = xv[..., :half], xv[..., half:]
            rot = jnp.concatenate([-x2, x1], axis=-1)
        else:
            x1 = xv[..., 0::2]
            x2 = xv[..., 1::2]
            rot = jnp.stack([-x2, x1], axis=-1).reshape(xv.shape)
        return xv * cosv + rot * sinv

    ref = next(t for t in (q, k, v) if t is not None)
    s_len, d = int(ref.shape[1]), int(ref.shape[-1])
    if sin is None or cos is None:
        sv, cv = build_sincos(s_len, d, jnp.float32)
    else:
        sv = sin._value if isinstance(sin, Tensor) else jnp.asarray(sin)
        cv = cos._value if isinstance(cos, Tensor) else jnp.asarray(cos)
    if position_ids is not None:
        pid = position_ids._value if isinstance(position_ids, Tensor) else jnp.asarray(position_ids)
        sv = jnp.take(sv[0, :, 0, :], pid, axis=0)[:, :, None, :]
        cv = jnp.take(cv[0, :, 0, :], pid, axis=0)[:, :, None, :]
    sv32, cv32 = sv.astype(jnp.float32), cv.astype(jnp.float32)

    def fn(xv):
        return rotate(xv.astype(jnp.float32), sv32, cv32).astype(xv.dtype)

    outs = [apply("fused_rope", fn, t) if t is not None else None for t in (q, k, v)]
    return tuple(outs)


# ---------------------------------------------------------------------------
# attention / ffn blocks
# ---------------------------------------------------------------------------

def fused_multi_head_attention(
    x,
    qkv_weight,
    linear_weight,
    pre_layer_norm=False,
    pre_ln_scale=None,
    pre_ln_bias=None,
    ln_scale=None,
    ln_bias=None,
    pre_ln_epsilon=1e-5,
    qkv_bias=None,
    linear_bias=None,
    cache_kv=None,
    attn_mask=None,
    dropout_rate=0.0,
    attn_dropout_rate=0.0,
    ln_epsilon=1e-5,
    training=True,
    num_heads=None,
    name=None,
):
    """reference fused_transformer.py fused_multi_head_attention:
    (pre-LN ->) qkv matmul -> attention -> out proj (-> post-LN), flash
    attention kernel when shapes allow. qkv_weight: [3, H, D, E]."""
    from ....nn.functional.attention import scaled_dot_product_attention

    if cache_kv is not None:
        raise NotImplementedError("fused_multi_head_attention: cache_kv (incremental decode) not yet supported")
    xin = x
    if pre_layer_norm:
        xin = fused_layer_norm(x, pre_ln_scale, pre_ln_bias, pre_ln_epsilon)

    def qkv_fn(xv, wv, *rest):
        b, s, e = xv.shape
        three, h, d, _ = wv.shape
        qkv = jnp.einsum("bse,thde->bsthd", xv, wv)
        if rest:
            qkv = qkv + rest[0][None, None]
        return qkv

    args = [xin, qkv_weight] + ([qkv_bias] if qkv_bias is not None else [])
    qkv = apply("fused_qkv", qkv_fn, *args)
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    ctx = scaled_dot_product_attention(q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate if training else 0.0)

    def proj_fn(cv, wv, *rest):
        b, s, h, d = cv.shape
        out = cv.reshape(b, s, h * d) @ wv
        if rest:
            out = out + rest[0]
        return out

    args = [ctx, linear_weight] + ([linear_bias] if linear_bias is not None else [])
    out = apply("fused_attn_proj", proj_fn, *args)
    if dropout_rate and training:
        from ....nn.functional.common import dropout as _dropout

        out = _dropout(out, p=dropout_rate, training=True)
    out = out + x  # residual (reference adds residual inside the fused op)
    if not pre_layer_norm:
        out = fused_layer_norm(out, ln_scale, ln_bias, ln_epsilon)
    return out


def fused_feedforward(
    x,
    linear1_weight,
    linear2_weight,
    linear1_bias=None,
    linear2_bias=None,
    ln1_scale=None,
    ln1_bias=None,
    ln2_scale=None,
    ln2_bias=None,
    dropout1_rate=0.5,
    dropout2_rate=0.5,
    activation="relu",
    ln1_epsilon=1e-5,
    ln2_epsilon=1e-5,
    pre_layer_norm=False,
    training=True,
    name=None,
):
    """reference fused_transformer.py fused_feedforward: (pre-LN ->) linear
    -> act -> dropout -> linear -> dropout -> residual (-> post-LN)."""
    from ....nn.functional.common import dropout as _dropout

    xin = x
    if pre_layer_norm:
        xin = fused_layer_norm(x, ln1_scale, ln1_bias, ln1_epsilon)
    h = fused_linear(xin, linear1_weight, linear1_bias)
    if activation != "none":
        h = fused_bias_act(h, None, act_method=activation)
    if dropout1_rate and training:
        h = _dropout(h, p=dropout1_rate, training=True)
    h = fused_linear(h, linear2_weight, linear2_bias)
    if dropout2_rate and training:
        h = _dropout(h, p=dropout2_rate, training=True)
    out = x + h
    if not pre_layer_norm:
        out = fused_layer_norm(out, ln2_scale, ln2_bias, ln2_epsilon)
    return out


# ---------------------------------------------------------------------------
# fused linear + softmax cross-entropy (the LM-head loss)
# ---------------------------------------------------------------------------

def _flce_fwd_impl(h, W, b, labels, ignore_index, transpose_weight):
    """h [N,H]; W [H,V] (or [V,H] with transpose_weight); b [V] or None.

    All big intermediates stay in h.dtype (bf16 under AMP) — the f32 work
    (logsumexp, label logit) runs through f32-accumulated reductions that XLA
    fuses into the logits' consumer, so no [N,V] f32 buffer is materialized
    (the unfused path materializes four of them on a 40k vocab)."""
    cdt = h.dtype
    Wc = W.astype(cdt)
    z = (h @ Wc.T) if transpose_weight else (h @ Wc)  # [N, V]
    if b is not None:
        z = z + b.astype(cdt)
    m = jnp.max(z, axis=-1).astype(jnp.float32)
    sumexp = jnp.sum(jnp.exp(z.astype(jnp.float32) - m[:, None]), axis=-1)
    lse = m + jnp.log(sumexp)

    valid = labels != ignore_index
    lab = jnp.where(valid, labels, 0)
    # label logit in f32 via a row-gathered dot (exact even when z is bf16)
    W_lab = (W[lab] if transpose_weight else W[:, lab].T).astype(jnp.float32)
    ll = jnp.sum(h.astype(jnp.float32) * W_lab, axis=-1)
    if b is not None:
        ll = ll + b.astype(jnp.float32)[lab]
    n_valid = jnp.maximum(jnp.sum(valid), 1)
    loss = jnp.sum(jnp.where(valid, lse - ll, 0.0)) / n_valid
    return loss, (z, lse, lab, valid, n_valid)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flce(h, W, b, labels, ignore_index, transpose_weight):
    loss, _ = _flce_fwd_impl(h, W, b, labels, ignore_index, transpose_weight)
    return loss


def _flce_fwd(h, W, b, labels, ignore_index, transpose_weight):
    loss, (z, lse, lab, valid, n_valid) = _flce_fwd_impl(
        h, W, b, labels, ignore_index, transpose_weight
    )
    return loss, (h, W, b, z, lse, lab, valid, n_valid)


def _flce_bwd(ignore_index, transpose_weight, res, g):
    h, W, b, z, lse, lab, valid, n_valid = res
    cdt = z.dtype
    scale = (g / n_valid.astype(jnp.float32)) * valid.astype(jnp.float32)  # [N]
    # dz = (softmax(z) - onehot(lab)) * scale as ONE elementwise chain from
    # the saved (possibly bf16) z. The one-hot is an iota compare, not a
    # scatter: a scatter forces dz to materialize as its own [N,V] buffer,
    # while this chain fuses straight into the dh/dW matmul operand reads
    # (profiled: the scatter form cost an extra [N,V] round-trip per step)
    col = jax.lax.broadcasted_iota(jnp.int32, z.shape, 1)
    onehot = (col == lab[:, None].astype(jnp.int32)).astype(jnp.float32)
    p_scaled = jnp.exp(z.astype(jnp.float32) - lse[:, None]) * scale[:, None]
    dz = (p_scaled - onehot * scale[:, None]).astype(cdt)
    Wc = W.astype(cdt)
    dh = (dz @ Wc if transpose_weight else dz @ Wc.T).astype(h.dtype)
    if transpose_weight:
        dW = jnp.dot(dz.T, h, preferred_element_type=jnp.float32)
    else:
        dW = jnp.dot(h.T, dz, preferred_element_type=jnp.float32)
    dW = dW.astype(W.dtype)
    db = jnp.sum(dz.astype(jnp.float32), axis=0).astype(b.dtype) if b is not None else None
    return dh, dW, db, None


_flce.defvjp(_flce_fwd, _flce_bwd)


def fused_linear_cross_entropy(
    x, weight, labels, bias=None, ignore_index=-100, transpose_weight=False, name=None
):
    """Fused LM-head: mean softmax cross-entropy of ``x @ weight (+ bias)``
    against int labels, without materializing f32 logits (and with the label
    logit computed in f32 regardless of compute dtype).

    Reference parity: the role of paddle's fused
    ``cross_entropy_with_softmax`` + fused_linear epilogue used by LLM heads
    (paddle/phi/kernels/fusion/, python/paddle/incubate/nn/functional/);
    redesigned as one XLA-fused custom-vjp op.

    x: [N, H] (or [..., H] — leading dims are flattened)
    weight: [H, V], or [V, H] with transpose_weight=True (tied embeddings)
    labels: int [N] (or [...]), entries equal to ignore_index are masked out
    Returns the scalar mean loss over non-ignored labels.
    """
    def fn(xv, wv, lv, *rest):
        bv = rest[0] if rest else None
        H = xv.shape[-1]
        xf = xv.reshape((-1, H))
        lf = lv.reshape((-1,))
        return _flce(xf, wv, bv, lf, ignore_index, transpose_weight)

    args = [x, weight, labels] + ([bias] if bias is not None else [])
    return apply("fused_linear_cross_entropy", fn, *args)


# ---------------------------------------------------------------------------
# decode-time fused attention with kv cache (LLM serving path)
# ---------------------------------------------------------------------------

def masked_multihead_attention(
    x,
    cache_kv=None,
    bias=None,
    src_mask=None,
    cum_offsets=None,
    sequence_lengths=None,
    rotary_tensor=None,
    beam_cache_offset=None,
    qkv_out_scale=None,
    out_shift=None,
    out_smooth=None,
    seq_len=1,
    rotary_emb_dims=0,
    use_neox_rotary_style=False,
    compute_dtype="default",
    out_scale=-1,
    quant_round_type=1,
    quant_max_bound=127.0,
    quant_min_bound=-127.0,
):
    """Single-step decode attention with kv-cache append (reference
    incubate/nn/functional/masked_multihead_attention.py; CUDA kernel
    phi/fusion/masked_multihead_attention). x is the current token's fused
    qkv [B, 3*H*D]; cache_kv [2, B, H, max_seq, D]; sequence_lengths [B]
    gives each sample's current cache fill. Returns (out [B, H*D],
    cache_kv_out). Quant paths (qkv_out_scale/out_shift/...) are CUDA int8
    serving tricks — not supported."""
    for unsupported in (qkv_out_scale, out_shift, out_smooth, beam_cache_offset, cum_offsets):
        if unsupported is not None:
            raise NotImplementedError("masked_multihead_attention: quant/beam paths not supported")
    from ....core.tensor import Tensor as _T

    x = x if isinstance(x, _T) else _T(jnp.asarray(x))
    cache = cache_kv if isinstance(cache_kv, _T) else _T(jnp.asarray(cache_kv))

    def fn(xv, ckv, *rest):
        r = list(rest)
        bias_v = r.pop(0) if bias is not None else None
        mask_v = r.pop(0) if src_mask is not None else None
        seqlen_v = r.pop(0) if sequence_lengths is not None else None
        rot_v = r.pop(0) if rotary_tensor is not None else None
        _, B, H, S, D = ckv.shape
        qkv = xv
        if bias_v is not None:
            qkv = qkv + bias_v
        qkv = qkv.reshape(B, 3, H, D)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [B, H, D]
        pos = (
            seqlen_v.reshape(B).astype(jnp.int32)
            if seqlen_v is not None
            else jnp.zeros((B,), jnp.int32)
        )
        if rotary_emb_dims > 0 and rot_v is not None:
            # rotary_tensor [2, B, 1, max_seq, D]: cos/sin at each position
            cos = jnp.take_along_axis(
                rot_v[0, :, 0], pos[:, None, None], axis=1
            )  # [B, 1, D]
            sin = jnp.take_along_axis(rot_v[1, :, 0], pos[:, None, None], axis=1)

            def rope(t):
                if use_neox_rotary_style:
                    half = D // 2
                    t1, t2 = t[..., :half], t[..., half:]
                    rt = jnp.concatenate([-t2, t1], axis=-1)
                else:
                    t1 = t[..., 0::2]
                    t2 = t[..., 1::2]
                    rt = jnp.stack([-t2, t1], axis=-1).reshape(t.shape)
                return t * cos + rt * sin

            q, k = rope(q), rope(k)
        # append k/v at each sample's position
        bidx = jnp.arange(B)
        new_k = ckv[0].at[bidx, :, pos, :].set(k)
        new_v = ckv[1].at[bidx, :, pos, :].set(v)
        scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
        logits = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32), new_k.astype(jnp.float32)) * scale
        sidx = jnp.arange(S)[None, None, :]
        valid = sidx <= pos[:, None, None]
        logits = jnp.where(valid, logits, -1e30)
        if mask_v is not None:
            logits = logits + mask_v.reshape(B, 1, -1)[:, :, :S].astype(jnp.float32)
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhs,bhsd->bhd", p.astype(new_v.dtype), new_v)
        return out.reshape(B, H * D), jnp.stack([new_k, new_v])

    args = [x, cache]
    for t in (bias, src_mask, sequence_lengths, rotary_tensor):
        if t is not None:
            args.append(t if isinstance(t, Tensor) else Tensor(jnp.asarray(t)))
    out, new_cache = apply("masked_multihead_attention", fn, *args, n_outputs=2)
    # reference semantics: cache updated in place
    cache._replace_value(new_cache._raw())
    return out, cache


def block_multihead_attention(
    qkv,
    key_cache,
    value_cache,
    seq_lens_encoder,
    seq_lens_decoder,
    seq_lens_this_time,
    padding_offsets,
    cum_offsets,
    cu_seqlens_q,
    cu_seqlens_k,
    block_tables,
    pre_key_cache=None,
    pre_value_cache=None,
    cache_k_quant_scales=None,
    cache_v_quant_scales=None,
    cache_k_dequant_scales=None,
    cache_v_dequant_scales=None,
    qkv_out_scale=None,
    qkv_bias=None,
    out_shift=None,
    out_smooth=None,
    max_enc_len_this_time=None,
    max_dec_len_this_time=None,
    rope_emb=None,
    mask=None,
    tgt_mask=None,
    max_seq_len=-1,
    block_size=64,
    use_neox_style=False,
    **quant_kwargs,
):
    """Paged-KV-cache attention (reference block_multihead_attention.py;
    CUDA kernel phi/fusion/block_multi_head_attention). Host-orchestrated
    TPU version: per sample, prefill (seq_lens_encoder > 0) runs causal
    self-attention over the packed tokens and writes k/v into the sample's
    cache pages via block_tables; decode (seq_lens_decoder > 0) appends one
    token into the current page and attends over the gathered pages.

    Supported serving paths (r3): cachekv-int8 (uint8 caches, dynamic
    per-(batch,head) scales computed at prefill and written back into the
    quant/dequant scale tensors, or static caller-provided scales; the
    +128-offset uint8 layout of the reference test oracle), rotary
    embedding via `rope_emb` [2, B|1, max_seq, 1, D/2] (cos, sin; non-neox
    interleaved pairs) or [..., D] (neox halves), additive prefill `mask`
    [B, 1, S, S] and decode `tgt_mask`. Still rejected: pre-cache and the
    int8-activation (qkv_out_scale/out_shift/out_smooth) epilogues.
    Returns (out, qkv, key_cache, value_cache); caches + dynamic scales
    updated in place."""
    use_dynamic_cachekv_quant = quant_kwargs.pop("use_dynamic_cachekv_quant", False)
    quant_max_bound = float(quant_kwargs.pop("quant_max_bound", 127.0) or 127.0)
    for unsupported in (pre_key_cache, pre_value_cache, qkv_out_scale, out_shift, out_smooth):
        if unsupported is not None:
            raise NotImplementedError(
                "block_multihead_attention: pre-cache / int8-activation"
                " epilogue paths not supported"
            )
    import numpy as np
    from ....core.tensor import Tensor as _T

    def _np(t):
        return np.asarray(t._raw() if isinstance(t, _T) else t)

    qkv_t = qkv if isinstance(qkv, _T) else _T(jnp.asarray(qkv))
    qv = qkv_t._raw()
    if qkv_bias is not None:
        qv = qv + (qkv_bias._raw() if isinstance(qkv_bias, _T) else jnp.asarray(qkv_bias))
    kc = key_cache._raw() if isinstance(key_cache, _T) else jnp.asarray(key_cache)
    vc = value_cache._raw() if isinstance(value_cache, _T) else jnp.asarray(value_cache)
    enc = _np(seq_lens_encoder).reshape(-1)
    dec = _np(seq_lens_decoder).reshape(-1)
    this = _np(seq_lens_this_time).reshape(-1)
    tables = _np(block_tables)
    B = enc.shape[0]
    nb_heads, bs, hd = kc.shape[1], kc.shape[2], kc.shape[3]
    H = nb_heads
    token_dim = qv.shape[-1] // 3
    D = token_dim // H

    quant = kc.dtype == jnp.uint8
    if quant:
        kqs = jnp.asarray(_np(cache_k_quant_scales), jnp.float32) if cache_k_quant_scales is not None else None
        vqs = jnp.asarray(_np(cache_v_quant_scales), jnp.float32) if cache_v_quant_scales is not None else None
        kdq = jnp.asarray(_np(cache_k_dequant_scales), jnp.float32) if cache_k_dequant_scales is not None else None
        vdq = jnp.asarray(_np(cache_v_dequant_scales), jnp.float32) if cache_v_dequant_scales is not None else None
        if kqs is None or vqs is None:
            raise ValueError("uint8 caches require cache_k/v_quant_scales")

        def _quantize(x, qs_ih):  # away-from-zero round, +128 uint8 offset
            q_ = jnp.sign(x.astype(jnp.float32)) * jnp.floor(
                jnp.abs(x.astype(jnp.float32)) * qs_ih[:, None] + 0.5
            )
            return jnp.clip(q_ + 128.0, 0.0, 255.0).astype(jnp.uint8)

        def _dequantize(x, dq_ih):
            return (x.astype(jnp.float32) - 128.0) * dq_ih[:, None]

    rope = None
    if rope_emb is not None:
        re_ = jnp.asarray(_np(rope_emb), jnp.float32)  # [2, B|1, S, 1, D/2 or D]
        rope = (re_[0], re_[1])

    def _apply_rope(x, positions):
        """x [n, H, D]; positions len-n ints."""
        cos, sin = rope
        bsel = 0 if cos.shape[0] == 1 else None  # broadcast batch
        c = cos[bsel if bsel is not None else i, np.asarray(positions), 0]  # [n, D/2|D]
        s = sin[bsel if bsel is not None else i, np.asarray(positions), 0]
        xf = x.astype(jnp.float32)
        if c.shape[-1] == D // 2:
            if use_neox_style:
                c2 = jnp.concatenate([c, c], -1)[:, None, :]
                s2 = jnp.concatenate([s, s], -1)[:, None, :]
                x1, x2 = xf[..., : D // 2], xf[..., D // 2:]
                rot = jnp.concatenate([-x2, x1], -1)
                return (xf * c2 + rot * s2).astype(x.dtype)
            xp = xf.reshape(x.shape[0], H, D // 2, 2)
            x0, x1 = xp[..., 0], xp[..., 1]
            c2, s2 = c[:, None, :], s[:, None, :]
            o0 = x0 * c2 - x1 * s2
            o1 = x1 * c2 + x0 * s2
            return jnp.stack([o0, o1], -1).reshape(x.shape).astype(x.dtype)
        c2, s2 = c[:, None, :], s[:, None, :]
        x1, x2 = xf[..., : D // 2], xf[..., D // 2:]
        rot = jnp.concatenate([-x2, x1], -1)
        return (xf * c2 + rot * s2).astype(x.dtype)

    mask_v = jnp.asarray(_np(mask), jnp.float32) if mask is not None else None
    tgt_v = jnp.asarray(_np(tgt_mask), jnp.float32) if tgt_mask is not None else None

    outs = []
    tok = 0
    scale = 1.0 / float(np.sqrt(D))
    for i in range(B):
        n = int(this[i])
        if n == 0:
            continue
        cur = qv[tok : tok + n].reshape(n, 3, H, D)
        q, k, v = cur[:, 0], cur[:, 1], cur[:, 2]  # [n, H, D]
        if enc[i] > 0:
            if rope is not None:
                pos_ids = list(range(n))
                q = _apply_rope(q, pos_ids)
                k = _apply_rope(k, pos_ids)
            # prefill: causal self-attention over this sample's n tokens
            lg = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
            if mask_v is not None:
                lg = lg + mask_v[i, 0, :n, :n][None]
            else:
                cm = jnp.tril(jnp.ones((n, n), bool))
                lg = jnp.where(cm[None], lg, -1e30)
            o = jnp.einsum("hqk,khd->qhd", jax.nn.softmax(lg, -1).astype(v.dtype), v)
            if quant:
                if use_dynamic_cachekv_quant:
                    kmax = jnp.maximum(jnp.max(jnp.abs(k.astype(jnp.float32)), axis=(0, 2)), 1e-6)
                    vmax = jnp.maximum(jnp.max(jnp.abs(v.astype(jnp.float32)), axis=(0, 2)), 1e-6)
                    kqs = kqs.at[i].set(quant_max_bound / kmax)
                    vqs = vqs.at[i].set(quant_max_bound / vmax)
                    kdq = kdq.at[i].set(kmax / quant_max_bound) if kdq is not None else None
                    vdq = vdq.at[i].set(vmax / quant_max_bound) if vdq is not None else None
                kq = _quantize(jnp.moveaxis(k, 1, 0).reshape(H, -1), kqs[i]).reshape(H, n, D)
                vq = _quantize(jnp.moveaxis(v, 1, 0).reshape(H, -1), vqs[i]).reshape(H, n, D)
            for t_ in range(n):
                page = int(tables[i, t_ // bs])
                slot = t_ % bs
                kc = kc.at[page, :, slot, :].set(kq[:, t_] if quant else k[t_])
                vc = vc.at[page, :, slot, :].set(vq[:, t_] if quant else v[t_])
        else:
            # decode: append one token at position dec[i], attend over cache
            pos = int(dec[i])
            if rope is not None:
                q = _apply_rope(q, [pos])
                k = _apply_rope(k, [pos])
            page = int(tables[i, pos // bs])
            slot = pos % bs
            if quant:
                kc = kc.at[page, :, slot, :].set(
                    _quantize(k[0], kqs[i]))
                vc = vc.at[page, :, slot, :].set(
                    _quantize(v[0], vqs[i]))
            else:
                kc = kc.at[page, :, slot, :].set(k[0])
                vc = vc.at[page, :, slot, :].set(v[0])
            npages = pos // bs + 1
            pages = tables[i, :npages].astype(np.int64)
            ks = kc[jnp.asarray(pages)].transpose(1, 0, 2, 3).reshape(H, npages * bs, D)
            vs = vc[jnp.asarray(pages)].transpose(1, 0, 2, 3).reshape(H, npages * bs, D)
            ks, vs = ks[:, : pos + 1], vs[:, : pos + 1]
            if quant:
                kd = kdq[i] if kdq is not None else 1.0 / kqs[i]
                vd = vdq[i] if vdq is not None else 1.0 / vqs[i]
                ks = _dequantize(ks.reshape(H, -1), kd).reshape(H, pos + 1, D).astype(v.dtype)
                vs = _dequantize(vs.reshape(H, -1), vd).reshape(H, pos + 1, D).astype(v.dtype)
            lg = jnp.einsum("qhd,hkd->hqk", q.astype(jnp.float32), ks.astype(jnp.float32)) * scale
            if tgt_v is not None:
                lg = lg + tgt_v[i].reshape(-1)[: pos + 1][None, None, :]
            o = jnp.einsum("hqk,hkd->qhd", jax.nn.softmax(lg, -1).astype(vs.dtype), vs)
        outs.append(o.reshape(n, H * D))
        tok += n
    out = _T(jnp.concatenate(outs) if outs else jnp.zeros((0, token_dim), qv.dtype))
    if isinstance(key_cache, _T):
        key_cache._replace_value(kc)
        value_cache._replace_value(vc)
    if quant and use_dynamic_cachekv_quant:
        for t, vnew in (
            (cache_k_quant_scales, kqs), (cache_v_quant_scales, vqs),
            (cache_k_dequant_scales, kdq), (cache_v_dequant_scales, vdq),
        ):
            if isinstance(t, _T) and vnew is not None:
                t._replace_value(vnew)
    return out, qkv_t, key_cache, value_cache


def variable_length_memory_efficient_attention(
    query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
    causal=False, pre_cache_length=0,
):
    """Variable-length batched attention (reference
    incubate/nn/functional/variable_length_memory_efficient_attention.py —
    the CUTLASS varlen kernel). TPU-native: one fully vectorized masked
    attention over the padded [B, H, S, D] batch — padding positions are
    masked at -inf and zeroed in the output, which XLA fuses without any
    per-sample host loop.

    query [B, H, Sq, D]; key/value [B, Hkv, Sk, D] (Hkv may divide H — GQA);
    seq_lens / kv_seq_lens [B] or [B, 1]; mask [B, 1, Sq, Sk] additive.
    """
    if pre_cache_length:
        raise NotImplementedError(
            "variable_length_memory_efficient_attention: pre_cache_length != 0 "
            "not supported — concatenate the pre-cache into key/value instead"
        )
    from ....core.tensor import Tensor as _T

    q = query if isinstance(query, _T) else _T(jnp.asarray(query))
    k = key if isinstance(key, _T) else _T(jnp.asarray(key))
    v = value if isinstance(value, _T) else _T(jnp.asarray(value))
    sl = seq_lens if isinstance(seq_lens, _T) else _T(jnp.asarray(seq_lens))
    kvl = kv_seq_lens if isinstance(kv_seq_lens, _T) else _T(jnp.asarray(kv_seq_lens))
    args = [q, k, v, sl, kvl] + ([mask if isinstance(mask, _T) else _T(jnp.asarray(mask))] if mask is not None else [])

    def fn(qv, kv, vv, slv, kvlv, *rest):
        B, H, Sq, D = qv.shape
        Hkv, Sk = kv.shape[1], kv.shape[2]
        if Hkv != H:  # GQA: repeat kv heads
            rep = H // Hkv
            kv = jnp.repeat(kv, rep, axis=1)
            vv = jnp.repeat(vv, rep, axis=1)
        sc = scale if scale is not None else 1.0 / math.sqrt(D)
        lg = jnp.einsum("bhqd,bhkd->bhqk", qv.astype(jnp.float32), kv.astype(jnp.float32)) * sc
        if rest:
            lg = lg + rest[0].astype(jnp.float32)
        kpos = jnp.arange(Sk)[None, None, None, :]
        kvalid = kpos < kvlv.reshape(-1)[:, None, None, None]
        lg = jnp.where(kvalid, lg, -jnp.inf)
        if causal:
            qpos = jnp.arange(Sq)[None, None, :, None]
            lg = jnp.where(qpos + (Sk - Sq) >= kpos, lg, -jnp.inf)
        p = jax.nn.softmax(lg, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(vv.dtype), vv)
        qvalid = jnp.arange(Sq)[None, None, :, None] < slv.reshape(-1)[:, None, None, None]
        return jnp.where(qvalid, out, jnp.zeros((), out.dtype))

    return apply("variable_length_memory_efficient_attention", fn, *args)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False, name=None):
    """reference fused_matmul_bias.py: matmul + bias epilogue (XLA fuses)."""
    def fn(xv, yv, *rest):
        a = jnp.swapaxes(xv, -1, -2) if transpose_x else xv
        b = jnp.swapaxes(yv, -1, -2) if transpose_y else yv
        out = a @ b
        return out + rest[0] if rest else out

    args = [x, y] + ([bias] if bias is not None else [])
    return apply("fused_matmul_bias", fn, *args)


def fused_bias_dropout_residual_layer_norm(
    x, residual, bias=None, ln_scale=None, ln_bias=None, dropout_rate=0.5,
    ln_epsilon=1e-5, training=True, mode="upscale_in_train", name=None,
):
    """reference fused_transformer.py fused_bias_dropout_residual_layer_norm:
    layer_norm(residual + dropout(x + bias))."""
    from ....nn.functional.common import dropout as _dropout
    from ....nn.functional.norm import layer_norm as _layer_norm
    from ....ops import math as _m

    h = x if bias is None else _m.add(x, bias)
    h = _dropout(h, p=dropout_rate, training=training, mode=mode)
    h = _m.add(h, residual)
    d = int(h.shape[-1])
    return _layer_norm(h, d, ln_scale, ln_bias, ln_epsilon)


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias, act_type):
    """reference fused_ec_moe.py: dense-evaluated MoE FFN — every expert's
    FFN over every token, combined with softmax gate weights. On the MXU a
    dense einsum over a modest expert count beats gather/scatter routing."""
    if act_type not in ("gelu", "relu"):
        raise ValueError("fused_ec_moe act_type must be gelu or relu")

    def fn(xv, gv, w0, b0, w1, b1):
        act = jax.nn.gelu if act_type == "gelu" else jax.nn.relu
        # h[e, b, s, f] = act(x @ w0[e] + b0[e])
        h = jnp.einsum("bsd,edf->ebsf", xv, w0) + b0[:, None]
        h = act(h)
        # fixed reference layout: bmm1_weight [E, FF, D]
        out_e = jnp.einsum("ebsf,efd->ebsd", h, w1) + b1[:, None]
        probs = jax.nn.softmax(gv.astype(jnp.float32), axis=-1).astype(xv.dtype)
        return jnp.einsum("ebsd,bse->bsd", out_e, probs)

    return apply("fused_ec_moe", fn, x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias)


def fused_multi_transformer(
    x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
    linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights, ffn1_biases,
    ffn2_weights, ffn2_biases, pre_layer_norm=True, epsilon=1e-5,
    cache_kvs=None, pre_caches=None, seq_lens=None, rotary_embs=None,
    time_step=None, attn_mask=None, dropout_rate=0.0, rotary_emb_dims=0,
    activation="gelu", training=False, mode="upscale_in_train",
    trans_qkvw=True, ring_id=-1, name=None,
):
    """reference fused_transformer.py:964 — N fused transformer layers in
    one call (the serving fast path). Standard-precision path with optional
    decode kv caches (cache layout [2, B, H, max_seq, D], time_step = write
    position); rotary/pre_cache paths raise loudly. One XLA program does the
    fusing the CUDA mega-kernel does by hand."""
    for unsupported, what in (
        (rotary_embs, "rotary_embs"), (pre_caches, "pre_caches"),
        (seq_lens, "seq_lens (mask padded positions via attn_mask instead)"),
    ):
        if unsupported is not None:
            raise NotImplementedError(f"fused_multi_transformer: {what} not supported")
    from ....nn.functional.common import dropout as _dropout
    from ....nn.functional.norm import layer_norm as _layer_norm
    from ....ops import math as _m, manipulation as _mp
    from ....core.tensor import Tensor as _T
    import math as _pm

    n_layers = len(qkv_weights)
    out = x
    new_caches = []
    ts = int(time_step.numpy()) if isinstance(time_step, _T) else time_step

    for i in range(n_layers):
        residual = out
        h = _layer_norm(out, int(out.shape[-1]), ln_scales[i], ln_biases[i], epsilon) if pre_layer_norm else out

        def attn_fn(hv, qkvw, *rest):
            b, s, d = hv.shape
            qkvb = rest[0] if qkv_biases is not None and qkv_biases[i] is not None else None
            w = qkvw
            if trans_qkvw:  # [3, H, Dh, d] -> project via einsum
                three, H, Dh, _ = w.shape
                qkv = jnp.einsum("bsd,thed->bsthe", hv, w)
            else:           # [d, 3, H, Dh]
                _, three, H, Dh = w.shape
                qkv = jnp.einsum("bsd,dthe->bsthe", hv, w)
            if qkvb is not None:
                qkv = qkv + qkvb.reshape(1, 1, 3, H, Dh)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]     # [B,S,H,Dh]
            qh, kh, vh = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))  # [B,H,S,Dh]
            cache = rest[-1] if cache_kvs is not None else None
            if cache is not None and ts is not None:
                # decode: append this step at position ts, attend over cache
                ck = cache[0].astype(kh.dtype)
                cv = cache[1].astype(vh.dtype)
                ck = jax.lax.dynamic_update_slice(ck, kh, (0, 0, ts, 0))
                cv = jax.lax.dynamic_update_slice(cv, vh, (0, 0, ts, 0))
                kh2, vh2 = ck[:, :, : ts + 1], cv[:, :, : ts + 1]
                new_cache = jnp.stack([ck, cv])
            else:
                kh2, vh2 = kh, vh
                new_cache = None
                if cache is not None:  # prefill into the cache
                    ck = jax.lax.dynamic_update_slice(
                        cache[0].astype(kh.dtype), kh, (0, 0, 0, 0))
                    cv = jax.lax.dynamic_update_slice(
                        cache[1].astype(vh.dtype), vh, (0, 0, 0, 0))
                    new_cache = jnp.stack([ck, cv])
            scale = 1.0 / _pm.sqrt(q.shape[-1])
            logits = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32), kh2.astype(jnp.float32)) * scale
            if attn_mask is not None:
                mv = attn_mask._raw() if isinstance(attn_mask, _T) else jnp.asarray(attn_mask)
                logits = logits + mv[:, :, :logits.shape[2], :logits.shape[3]].astype(jnp.float32)
            elif cache is None or ts is None:
                sq, sk = logits.shape[-2], logits.shape[-1]
                cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
                logits = jnp.where(cm, logits, -1e30)
            p = jax.nn.softmax(logits, -1).astype(vh2.dtype)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, vh2)
            o = jnp.swapaxes(o, 1, 2).reshape(b, s, -1)
            return (o, new_cache) if new_cache is not None else o

        args = [h, qkv_weights[i]]
        if qkv_biases is not None and qkv_biases[i] is not None:
            args.append(qkv_biases[i])
        if cache_kvs is not None:
            args.append(cache_kvs[i])
        attn_out = apply(f"fmt_attn_{i}", attn_fn, *args,
                         n_outputs=2 if cache_kvs is not None else None)
        if cache_kvs is not None:
            attn_out, cache_out = attn_out
            new_caches.append(cache_out)

        proj = fused_linear(attn_out, linear_weights[i],
                            linear_biases[i] if linear_biases is not None else None)
        proj = _dropout(proj, p=dropout_rate, training=training, mode=mode)
        out = _m.add(residual, proj)
        if not pre_layer_norm:
            out = _layer_norm(out, int(out.shape[-1]), ln_scales[i], ln_biases[i], epsilon)

        residual = out
        h = _layer_norm(out, int(out.shape[-1]), ffn_ln_scales[i], ffn_ln_biases[i], epsilon) if pre_layer_norm else out
        h = fused_linear(h, ffn1_weights[i], ffn1_biases[i] if ffn1_biases is not None else None)
        h = fused_bias_act(h, act_method=activation)
        h = fused_linear(h, ffn2_weights[i], ffn2_biases[i] if ffn2_biases is not None else None)
        h = _dropout(h, p=dropout_rate, training=training, mode=mode)
        out = _m.add(residual, h)
        if not pre_layer_norm:
            out = _layer_norm(out, int(out.shape[-1]), ffn_ln_scales[i], ffn_ln_biases[i], epsilon)

    if cache_kvs is not None:
        for c, nc in zip(cache_kvs, new_caches):
            if isinstance(c, _T) and nc is not None:
                c._replace_value(nc._raw() if isinstance(nc, _T) else nc)
        return out, cache_kvs
    return out
