"""paddle.profiler namespace (reference: python/paddle/profiler/__init__.py)."""
from .profiler import (  # noqa: F401
    Profiler,
    ProfilerState,
    ProfilerTarget,
    SummaryView,
    export_chrome_tracing,
    export_protobuf,
    load_profiler_result,
    make_scheduler,
)
from .profiler_statistic import SortedKeys, StatisticData  # noqa: F401
from .utils import RecordEvent, TracerEventType, in_profiler_mode, wrap_optimizers  # noqa: F401
from .timer import benchmark  # noqa: F401
from . import perf_attribution  # noqa: F401
from . import trace_merge  # noqa: F401
from .perf_attribution import (  # noqa: F401
    annotate_module,
    live_array_census,
    perf_report,
    roofline,
)
from .trace_merge import merge_traces  # noqa: F401

__all__ = [
    "annotate_module",
    "live_array_census",
    "merge_traces",
    "perf_attribution",
    "perf_report",
    "roofline",
    "trace_merge",
    "Profiler",
    "ProfilerState",
    "ProfilerTarget",
    "SummaryView",
    "make_scheduler",
    "export_chrome_tracing",
    "export_protobuf",
    "load_profiler_result",
    "SortedKeys",
    "RecordEvent",
    "TracerEventType",
    "in_profiler_mode",
    "wrap_optimizers",
    "benchmark",
]
