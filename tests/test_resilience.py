"""Resilience layer: FaultPlan chaos, RetryPolicy backoff, atomic verified
checkpoints, watchdog escalation ladder — all in-process (tier-1 safe).

The real-subprocess chaos (SIGKILL + elastic relaunch) lives in
test_fault_injection.py / test_chaos_slow.py behind the `slow` marker; these
tests drive the SAME failure paths through the framework's own FaultPlan
injection points instead of hand-rolled monkeypatches.
"""
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import telemetry
from paddle_tpu.distributed import resilience as rz
from paddle_tpu.distributed.checkpoint import (
    CheckpointCorrupt,
    list_steps,
    load_state_dict,
    save_state_dict,
    verify_step,
)
from paddle_tpu.distributed.comm_watchdog import (
    comm_task,
    set_abort_handler,
    set_timeout_handler,
    set_warn_handler,
)
from paddle_tpu.framework import flags as _flags
from paddle_tpu.native.store import TCPStore


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    rz.clear_plan()
    yield
    rz.clear_plan()


@pytest.fixture
def fast_retry():
    old = _flags.get_flags([
        "FLAGS_store_retry_max_attempts", "FLAGS_store_retry_base_s",
        "FLAGS_store_retry_max_s", "FLAGS_store_retry_deadline_s",
    ])
    _flags.set_flags({
        "FLAGS_store_retry_max_attempts": 5,
        "FLAGS_store_retry_base_s": 0.002,
        "FLAGS_store_retry_max_s": 0.01,
        "FLAGS_store_retry_deadline_s": 5.0,
    })
    yield
    _flags.set_flags(old)


def _counter_value(name, **labels):
    fam = telemetry.default_registry().get(name)
    if fam is None:
        return 0
    for child in fam.children():
        if dict(child.labels) == {k: str(v) for k, v in labels.items()}:
            return child.value
    return 0


# ---------------------------------------------------------------------------
# FaultPlan
# ---------------------------------------------------------------------------


def test_plan_compact_and_json_spec_parse():
    p = rz.plan_from_spec("store.connect=fail*2;ckpt.write_shard=corrupt;x=delay*3:0.5")
    assert [(s.site, s.action, s.times) for s in p.specs] == [
        ("store.connect", "fail", 2),
        ("ckpt.write_shard", "corrupt", 1),
        ("x", "delay", 3),
    ]
    assert p.specs[2].arg == 0.5
    p2 = rz.plan_from_spec(
        '[{"site": "store.set", "action": "delay", "times": null, "arg": 0.05}]'
    )
    assert p2.specs[0].times is None and p2.specs[0].arg == 0.05
    # arg without an explicit *times (documented grammar)
    p3 = rz.plan_from_spec("store.set=delay:0.05")
    assert (p3.specs[0].action, p3.specs[0].times, p3.specs[0].arg) == ("delay", 1, 0.05)
    with pytest.raises(ValueError):
        rz.FaultPlan().add("s", "explode")


def test_fail_n_times_then_clean():
    rz.install_plan(rz.FaultPlan().add("site.a", "fail", times=2))
    for _ in range(2):
        with pytest.raises(rz.FaultInjected):
            rz.fault_point("site.a")
    rz.fault_point("site.a")  # exhausted: clean
    assert rz.current_plan().triggered["site.a"] == 2


def test_glob_site_and_delay():
    rz.install_plan(rz.FaultPlan().add("store.*", "delay", times=1, arg=0.05))
    t0 = time.monotonic()
    rz.fault_point("store.set", key="k")
    assert time.monotonic() - t0 >= 0.05
    rz.fault_point("store.set", key="k")  # exhausted


def test_corrupt_is_seeded_and_deterministic(tmp_path):
    payload = bytes(range(256)) * 4
    out = []
    for run in range(2):
        fp = tmp_path / f"f{run}.bin"
        fp.write_bytes(payload)
        rz.install_plan(rz.FaultPlan(seed=7).add("ckpt.write_shard", "corrupt", times=1))
        assert rz.corrupt_file("ckpt.write_shard", str(fp))
        out.append(fp.read_bytes())
        rz.clear_plan()
    assert out[0] == out[1] != payload  # same seed -> same flips


def test_env_activation(tmp_path):
    # a fresh plan-state module picks the plan up from the environment (the
    # path a launched worker subprocess takes)
    import importlib

    from paddle_tpu.distributed.resilience import fault_injection as fi

    os.environ["PADDLE_TPU_FAULT_PLAN"] = "env.site=fail*1"
    try:
        fi._env_checked = False
        fi._active = None
        with pytest.raises(fi.FaultInjected):
            fi.fault_point("env.site")
        fi.fault_point("env.site")  # exhausted
    finally:
        del os.environ["PADDLE_TPU_FAULT_PLAN"]
        fi.install_plan(None)


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------


def test_retry_heals_transient_failures_with_backoff():
    calls = {"n": 0}
    sleeps = []

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 3:
            raise ConnectionError("flap")
        return "ok"

    policy = rz.RetryPolicy(max_attempts=6, base_s=0.1, max_backoff_s=0.4,
                            deadline_s=30.0, sleep=sleeps.append)
    assert policy.call(flaky, site="test.flaky") == "ok"
    assert calls["n"] == 4
    # full jitter: each delay in [0, min(cap, base * 2**attempt)]
    assert len(sleeps) == 3
    for i, d in enumerate(sleeps):
        assert 0.0 <= d <= min(0.4, 0.1 * 2**i)
    assert _counter_value("paddle_tpu_retry_attempts_total", site="test.flaky") >= 4
    assert _counter_value("paddle_tpu_retry_retries_total", site="test.flaky") >= 3


def test_retry_gives_up_with_descriptive_error_and_counter():
    policy = rz.RetryPolicy(max_attempts=3, base_s=0.001, max_backoff_s=0.002,
                            deadline_s=30.0, sleep=lambda s: None)
    before = _counter_value("paddle_tpu_retry_giveups_total", site="test.dead")
    with pytest.raises(rz.RetryError) as ei:
        policy.call(lambda: (_ for _ in ()).throw(ConnectionError("down")), site="test.dead")
    assert ei.value.attempts == 3 and isinstance(ei.value.last, ConnectionError)
    assert "test.dead" in str(ei.value) and "3 attempt" in str(ei.value)
    assert _counter_value("paddle_tpu_retry_giveups_total", site="test.dead") == before + 1


def test_retry_respects_overall_deadline():
    policy = rz.RetryPolicy(max_attempts=1000, base_s=0.2, max_backoff_s=0.2,
                            deadline_s=0.05, sleep=lambda s: None)
    t = {"n": 0}

    def fail():
        t["n"] += 1
        time.sleep(0.03)
        raise TimeoutError("x")

    with pytest.raises(rz.RetryError):
        policy.call(fail, site="test.deadline")
    assert t["n"] < 10  # deadline cut it off long before 1000 attempts


def test_non_transient_error_propagates_immediately():
    policy = rz.RetryPolicy(max_attempts=5, retry_on=(ConnectionError,))
    with pytest.raises(KeyError):
        policy.call(lambda: (_ for _ in ()).throw(KeyError("real answer")), site="t")


# ---------------------------------------------------------------------------
# TCPStore under chaos (acceptance: ops survive N injected failures, backoff
# visible in telemetry)
# ---------------------------------------------------------------------------


@pytest.fixture
def store_pair():
    master = TCPStore("127.0.0.1", 0, is_master=True)
    client = TCPStore("127.0.0.1", master.port, is_master=False)
    yield master, client
    client.close()
    master.close()


def test_store_connect_survives_injected_failures(fast_retry):
    master = TCPStore("127.0.0.1", 0, is_master=True)
    before = _counter_value("paddle_tpu_retry_retries_total", site="store.connect")
    rz.install_plan(rz.FaultPlan().add("store.connect", "fail", times=3))
    client = TCPStore("127.0.0.1", master.port, is_master=False)
    client.set("k", b"v")
    assert client.get("k") == b"v"
    assert _counter_value("paddle_tpu_retry_retries_total", site="store.connect") >= before + 3
    client.close()
    master.close()


def test_store_ops_survive_injected_failures(fast_retry, store_pair):
    _, client = store_pair
    rz.install_plan(
        rz.FaultPlan()
        .add("store.set", "fail", times=2)
        .add("store.add", "fail", times=2)
        .add("store.get", "fail", times=1)
    )
    client.set("k2", b"w")
    assert client.add("cnt", 5) == 5
    assert client.get("k2") == b"w"
    assert _counter_value("paddle_tpu_retry_retries_total", site="store.set") >= 2
    assert _counter_value("paddle_tpu_retry_retries_total", site="store.add") >= 2


def test_store_exhaustion_error_is_descriptive(fast_retry, store_pair):
    _, client = store_pair
    rz.install_plan(rz.FaultPlan().add("store.set", "fail", times=None))
    with pytest.raises(RuntimeError) as ei:
        client.set("doomed", b"x")
    msg = str(ei.value)
    assert "TCPStore.set" in msg and "doomed" in msg
    assert f"{client.host}:{client.port}" in msg
    assert "attempts=" in msg and "elapsed=" in msg


def test_store_op_reconnects_after_dead_socket(fast_retry, store_pair):
    """A dead cached per-thread socket must heal: drop + re-dial + retry
    instead of the old bare RuntimeError('connection lost')."""
    _, client = store_pair
    c = client._client
    client._lib.pt_store_client_shutdown(c)  # kill the cached socket under it
    client.set("after-death", b"alive")
    assert client.get("after-death") == b"alive"
    assert client._client is not c  # a fresh connection was dialed


def test_store_wait_heals_across_reconnect(fast_retry, store_pair):
    master, client = store_pair
    master.set("ready", b"1")
    c = client._client
    client._lib.pt_store_client_shutdown(c)
    client.wait("ready", timeout=5.0)  # dead socket -> re-dial -> wait succeeds


def test_store_wait_redial_survives_injected_connect_faults(fast_retry, store_pair):
    master, client = store_pair
    master.set("ready2", b"1")
    c = client._client
    client._drop_client(c)
    rz.install_plan(rz.FaultPlan().add("store.connect", "fail", times=2))
    client.wait("ready2", timeout=5.0)  # FaultInjected on re-dial is retried, not fatal


# ---------------------------------------------------------------------------
# atomic checkpoints (acceptance: torn/corrupt latest step -> newest complete
# restores, driven by FaultPlan)
# ---------------------------------------------------------------------------


def _save(root, value, shape=(3, 4)):
    sd = {"w": paddle.to_tensor(np.full(shape, value, "float32"))}
    return save_state_dict(sd, str(root))


def _load_w(root, shape=(3, 4)):
    tgt = {"w": paddle.zeros(list(shape))}
    load_state_dict(tgt, str(root))
    return float(tgt["w"].numpy()[0, 0])


def test_each_save_lands_in_its_own_step_dir(tmp_path):
    p0 = _save(tmp_path, 1.0)
    p1 = _save(tmp_path, 2.0)
    assert os.path.basename(p0) == "step_0" and os.path.basename(p1) == "step_1"
    assert list_steps(str(tmp_path)) == [0, 1]
    # stale shards cannot interleave: the two steps are disjoint directories
    assert set(os.listdir(p0)) & set(os.listdir(p1)) == set(os.listdir(p0))
    assert _load_w(tmp_path) == 2.0


def test_torn_save_falls_back_to_previous_complete_step(tmp_path):
    """The SIGKILL-mid-save shape: the fault plan kills the save before its
    metadata/completeness marker lands; load must reject the torn step via
    the integrity check and restore the newest COMPLETE one."""
    _save(tmp_path, 1.0)
    rz.install_plan(rz.FaultPlan().add("ckpt.write_metadata", "fail", times=1))
    with pytest.raises(rz.FaultInjected):
        _save(tmp_path, 9.0)
    rz.clear_plan()
    assert _load_w(tmp_path) == 1.0  # previous checkpoint still loadable


def test_kill_before_publish_leaves_previous_step(tmp_path):
    _save(tmp_path, 3.0)
    rz.install_plan(rz.FaultPlan().add("ckpt.publish", "fail", times=1))
    with pytest.raises(rz.FaultInjected):
        _save(tmp_path, 9.0)
    rz.clear_plan()
    assert list_steps(str(tmp_path)) == [0]  # torn temp dir never published
    assert _load_w(tmp_path) == 3.0


def test_corrupt_shard_detected_by_crc_and_skipped(tmp_path):
    before = _counter_value("paddle_tpu_ckpt_fallbacks_total", reason="corrupt")
    _save(tmp_path, 1.0)
    rz.install_plan(rz.FaultPlan().add("ckpt.write_shard", "corrupt", times=1))
    _save(tmp_path, 9.0)  # publishes, but its shard bytes are rotten
    rz.clear_plan()
    assert list_steps(str(tmp_path)) == [0, 1]
    assert _load_w(tmp_path) == 1.0  # CRC mismatch -> newest COMPLETE wins
    assert _counter_value("paddle_tpu_ckpt_fallbacks_total", reason="corrupt") == before + 1
    with pytest.raises(CheckpointCorrupt, match="CRC32 mismatch"):
        verify_step(os.path.join(str(tmp_path), "step_1"))


def test_all_steps_corrupt_raises(tmp_path):
    rz.install_plan(rz.FaultPlan().add("ckpt.write_shard", "corrupt", times=None))
    _save(tmp_path, 1.0)
    rz.clear_plan()
    with pytest.raises(CheckpointCorrupt, match="no complete, uncorrupted"):
        _load_w(tmp_path)


def test_overwrite_crash_between_renames_falls_back_to_old(tmp_path):
    """A same-step overwrite that dies between its two renames leaves only
    `step_<N>.old` — the loader must use that complete copy, not strand."""
    _save(tmp_path, 5.0, )
    step = os.path.join(str(tmp_path), "step_0")
    os.rename(step, step + ".old")  # the mid-overwrite crash window
    assert list_steps(str(tmp_path)) == [0]
    assert _load_w(tmp_path) == 5.0


def test_legacy_flat_checkpoint_still_loads(tmp_path):
    import shutil

    step = _save(tmp_path / "root", 4.0)
    legacy = tmp_path / "flat"
    legacy.mkdir()
    for f in os.listdir(step):
        if f != "COMPLETE":
            shutil.copy(os.path.join(step, f), legacy)
    assert _load_w(legacy) == 4.0


def test_step_dirs_shadow_stale_legacy_flat_files(tmp_path):
    """A pre-upgrade flat checkpoint at the root must not mask newer step
    saves written alongside it."""
    import shutil

    step0 = _save(tmp_path, 1.0)
    for f in os.listdir(step0):  # stale flat copy at the root
        if f != "COMPLETE":
            shutil.copy(os.path.join(step0, f), tmp_path)
    _save(tmp_path, 2.0)
    assert _load_w(tmp_path) == 2.0  # step_1 wins over the root's flat files


def test_resume_loop_with_framework_checkpoint(tmp_path):
    """The relaunch contract end-to-end, in process: train, die mid-save,
    resume from the newest complete step, converge to the same weights."""
    rng = np.random.RandomState(0)
    w_true = np.array([[1.0], [-2.0], [3.0], [0.5]], np.float32)
    X = rng.randn(64, 4).astype(np.float32)
    Y = X @ w_true

    def run(root, fault_plan=None, die_at=None):
        steps_done = 0
        w = np.zeros((4, 1), np.float32)
        if list_steps(str(root)):
            sd = {"w": paddle.zeros([4, 1]), "step": paddle.zeros([1])}
            load_state_dict(sd, str(root))
            w = sd["w"].numpy().copy()
            steps_done = int(sd["step"].numpy()[0]) + 1
        for step in range(steps_done, 8):
            grad = 2.0 * X.T @ (X @ w - Y) / X.shape[0]
            w = w - 0.2 * grad
            if fault_plan is not None and step == die_at:
                rz.install_plan(fault_plan)
            try:
                save_state_dict(
                    {"w": paddle.to_tensor(w), "step": paddle.to_tensor([float(step)])},
                    str(root), step=step,
                )
            except rz.FaultInjected:
                rz.clear_plan()
                return w, step, True  # "process died" mid-save
        return w, step, False

    ref, _, _ = run(tmp_path / "ref")
    faulty_root = tmp_path / "faulty"
    plan = rz.FaultPlan().add("ckpt.write_metadata", "fail", times=1)
    w1, died_step, died = run(faulty_root, fault_plan=plan, die_at=4)
    assert died and died_step == 4
    w2, _, _ = run(faulty_root)  # relaunch: resumes from step_3, not scratch
    np.testing.assert_allclose(w2, ref, rtol=1e-6)
    assert list_steps(str(faulty_root)) == [0, 1, 2, 3, 4, 5, 6, 7]


# ---------------------------------------------------------------------------
# watchdog escalation ladder
# ---------------------------------------------------------------------------


@pytest.fixture
def ladder_hooks():
    events = []
    prev_warn = set_warn_handler(lambda t: events.append(("warn", t.op)))
    prev_abort = set_abort_handler(lambda t: events.append(("abort", t.op)))
    yield events
    set_warn_handler(prev_warn)
    set_abort_handler(None if prev_abort is None else prev_abort)


def test_watchdog_ladder_warn_dump_abort_ordering(ladder_hooks, capfd):
    _flags.set_flags({"FLAGS_comm_watchdog_warn_s": 0.15})
    try:
        with comm_task("collective.all_reduce", timeout=0.5, ranks=(0, 1)):
            time.sleep(0.9)
    finally:
        _flags.set_flags({"FLAGS_comm_watchdog_warn_s": 300.0})
    assert ladder_hooks == [
        ("warn", "collective.all_reduce"),
        ("abort", "collective.all_reduce"),
    ]
    err = capfd.readouterr().err
    # ladder ordering on the wire too: warn < task dump < thread stacks < abort
    i_warn = err.index("soft deadline")
    i_dump = err.index("HUNG COLLECTIVE DETECTED")
    i_stacks = err.index("all thread stacks")
    i_abort = err.index("aborting process")
    assert i_warn < i_dump < i_stacks < i_abort
    assert "Thread" in err  # faulthandler actually dumped stacks


def test_watchdog_warn_counts_in_telemetry(ladder_hooks):
    before = _counter_value("paddle_tpu_comm_tasks_warned_total", op="test.slowpoke")
    _flags.set_flags({"FLAGS_comm_watchdog_warn_s": 0.1})
    try:
        with comm_task("test.slowpoke", timeout=60.0):
            time.sleep(0.35)  # passes soft deadline, never the hard one
    finally:
        _flags.set_flags({"FLAGS_comm_watchdog_warn_s": 300.0})
    assert ladder_hooks == [("warn", "test.slowpoke")]
    assert _counter_value("paddle_tpu_comm_tasks_warned_total", op="test.slowpoke") == before + 1


def test_custom_timeout_handler_still_replaces_ladder(ladder_hooks):
    fired = []
    prev = set_timeout_handler(lambda task, dump: fired.append(task.op))
    try:
        with comm_task("test.hang", timeout=0.1):
            time.sleep(0.3)
    finally:
        set_timeout_handler(None if prev is None else prev)
    assert fired == ["test.hang"]
    assert ladder_hooks == []  # custom handler replaced dump+abort entirely


def test_injected_collective_delay_trips_watchdog(ladder_hooks):
    """A FaultPlan delay on eager collective dispatch past the hard deadline
    drives the full ladder through the REAL collective entry point."""
    dist.init_parallel_env()
    fired = []
    prev = set_timeout_handler(lambda task, dump: fired.append((task.op, dump)))
    rz.install_plan(rz.FaultPlan().add("collective.all_reduce", "delay", times=1, arg=0.5))
    try:
        x = paddle.to_tensor(np.ones((8, 4), np.float32))
        dist.all_reduce(x)  # watchdog sees the injected 0.5s stall... but
        # the default deadline is 600s, so no fire; now tighten and re-inject
        _flags.set_flags({"FLAGS_comm_watchdog_timeout_s": 0.15})
        rz.install_plan(rz.FaultPlan().add("collective.all_reduce", "delay", times=1, arg=0.6))
        dist.all_reduce(x)
    finally:
        _flags.set_flags({"FLAGS_comm_watchdog_timeout_s": 600.0})
        set_timeout_handler(None if prev is None else prev)
    assert fired and fired[0][0] == "collective.all_reduce"
    assert "collective.all_reduce" in fired[0][1]


# ---------------------------------------------------------------------------
# launcher backoff knobs (unit level; the subprocess path is in the slow lane)
# ---------------------------------------------------------------------------


def test_backoff_delay_shape():
    import random

    rng = random.Random(0)
    for attempt in range(8):
        d = rz.backoff_delay(attempt, 0.5, 30.0, rng)
        assert 0.0 <= d <= min(30.0, 0.5 * 2**attempt)


def test_controller_healthy_window_resets_budget(tmp_path):
    from paddle_tpu.distributed.launch import CollectiveController, Context, parse_args

    script = tmp_path / "noop.py"
    script.write_text("pass\n")
    args = parse_args([
        "--max_restart", "3", "--restart_healthy_window", "0.01",
        "--restart_backoff", "0", str(script),
    ])
    ctrl = CollectiveController(Context(args))
    ctrl.build_pod()
    for c in ctrl.pod.containers:
        c.restarts = 2
    ctrl.consecutive_restarts = 2
    ctrl.last_restart_t = time.monotonic() - 1.0  # healthy past the window
    ctrl._maybe_reset_restart_budget()
    assert all(c.restarts == 0 for c in ctrl.pod.containers)
    assert ctrl.consecutive_restarts == 0 and ctrl.last_restart_t is None


def test_default_store_policy_reads_flags(fast_retry):
    p = rz.default_store_policy()
    assert p.max_attempts == 5 and p.base_s == 0.002
    assert p.max_backoff_s == 0.01 and p.deadline_s == 5.0


# ---------------------------------------------------------------------------
# tier-1 smoke: inject -> observe retry counters in a schema-valid snapshot
# ---------------------------------------------------------------------------


def test_fault_injection_telemetry_smoke(fast_retry, store_pair, tmp_path):
    _, client = store_pair
    rz.install_plan(rz.FaultPlan().add("store.set", "fail", times=2))
    client.set("smoke", b"1")
    snap = telemetry.dump_snapshot(str(tmp_path / "m.jsonl"))
    text = open(snap).read()
    assert telemetry.validate_snapshot(text) > 0
    rows = [json.loads(l) for l in text.splitlines() if l.strip()]
    by_name = {}
    for r in rows:
        by_name.setdefault(r["name"], []).append(r)
    retries = {
        r["labels"]["site"]: r["value"]
        for r in by_name.get("paddle_tpu_retry_retries_total", [])
    }
    faults = {
        (r["labels"]["site"], r["labels"]["action"]): r["value"]
        for r in by_name.get("paddle_tpu_faults_injected_total", [])
    }
    assert retries.get("store.set", 0) >= 2
    assert faults.get(("store.set", "fail"), 0) >= 2
