"""Graph pass & fusion framework (static/passes): golden to_text
before/after dumps per shipped pass, DRR pattern matching + safety,
deliberately-miscompiling mutant passes that verify() must catch (with the
pass named), passes-on == passes-off identity on eager-converted tiny-Llama
captures (eval AND train), Executor/export integration, per-pass
telemetry, print-after-pass diffs, and custom pass registration."""
import math
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static, telemetry
from paddle_tpu.core.apply import apply
from paddle_tpu.jit import capture_program
from paddle_tpu.nn import functional as F
from paddle_tpu.ops import manipulation as manip
from paddle_tpu.static import passes
from paddle_tpu.static.analysis import ProgramVerifyError, verify
from paddle_tpu.static.passes.pass_base import PassStats, ProgramPass, clone_op_with_inputs


def _counter_value(name, **labels):
    fam = telemetry.default_registry().get(name)
    if fam is None:
        return 0
    child = fam.labels(**labels) if labels else fam._default()
    return child.value


def _run_pass(main, pass_name, fetch_vids):
    """Run ONE registered pass over a clone; returns (work, stats)."""
    work = main.clone()
    p = passes.get_pass(pass_name)
    ctx = passes.PassContext(work, fetch_vars=fetch_vids)
    stats = p.run(work, ctx)
    return work, stats


def _replay(prog, feeds, fetch_vid):
    import jax.numpy as jnp

    env = prog.replay_env(
        {prog.feed_vars[n]: jnp.asarray(a) for n, a in feeds.items()},
        [prog._var_tensors[v]._value for v in prog.param_vars],
    )
    return np.asarray(env[fetch_vid])


def _golden(text):
    return textwrap.dedent(text).strip("\n")


# ---------------------------------------------------------------------------
# golden to_text before/after dumps — one per shipped pass
# ---------------------------------------------------------------------------

def test_golden_dce():
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [2, 3], "float32")
        y = x * 2.0
        F.softmax(y) * 3.0  # two dead ops
    fv = [main._id2var[id(y)]]
    before = main.to_text(fetch_vars=fv)
    assert before == main.to_text(fetch_vars=fv)  # stable across renders
    assert before == _golden("""
        program {  # 3 ops, 1 feeds, 2 params, 0 grad_requests, 0 opt_updates
          feed  %v0 'x' : float32[2, 3]
          param %v1 : float32[]
          param %v4 : float32[]
          %v2 = multiply(%v0, %v1) : float32[2, 3]  # op#0
          %v3 = softmax(%v2) : float32[2, 3]  # op#1
          %v5 = multiply(%v3, %v4) : float32[2, 3]  # op#2
          fetch %v2
        }""")
    work, stats = _run_pass(main, "dead_op_elimination", fv)
    assert (stats.matches, stats.rewritten_ops) == (2, 2)
    assert work.to_text(fetch_vars=fv) == _golden("""
        program {  # 1 ops, 1 feeds, 2 params, 0 grad_requests, 0 opt_updates
          feed  %v0 'x' : float32[2, 3]
          param %v1 : float32[]
          param %v4 : float32[]
          %v2 = multiply(%v0, %v1) : float32[2, 3]  # op#0
          fetch %v2
        }""")
    assert len(main.ops) == 3  # the caller's program is untouched


def test_golden_constant_fold_scalars():
    from jax import numpy as jnp

    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [2], "float32")
        c = apply("const_three", lambda: jnp.float32(3.0))
        y = x * c
    fv = [main._id2var[id(y)]]
    assert main.to_text(fetch_vars=fv) == _golden("""
        program {  # 2 ops, 1 feeds, 0 params, 0 grad_requests, 0 opt_updates
          feed  %v0 'x' : float32[2]
          %v1 = const_three() : float32[]  # op#0
          %v2 = multiply(%v0, %v1) : float32[2]  # op#1
          fetch %v2
        }""")
    work, stats = _run_pass(main, "constant_fold_scalars", fv)
    assert (stats.matches, stats.rewritten_ops) == (1, 1)
    assert work.to_text(fetch_vars=fv) == _golden("""
        program {  # 1 ops, 1 feeds, 0 params, 0 grad_requests, 0 opt_updates
          feed  %v0 'x' : float32[2]
          %v2 = multiply(%v0, array(3., dtype=float32)) : float32[2]  # op#0
          fetch %v2
        }""")
    xv = np.array([1.5, -2.0], "float32")
    np.testing.assert_array_equal(
        _replay(main, {"x": xv}, fv[0]), _replay(work, {"x": xv}, fv[0])
    )


def test_golden_redundant_cast_reshape_elim():
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [2, 3], "float32")
        y = manip.cast(x, "float32")       # same dtype: redundant
        z = manip.reshape(y, [2, 3])       # same shape: redundant
        w = z * 2.0
    fv = [main._id2var[id(w)]]
    assert main.to_text(fetch_vars=fv) == _golden("""
        program {  # 3 ops, 1 feeds, 1 params, 0 grad_requests, 0 opt_updates
          feed  %v0 'x' : float32[2, 3]
          param %v3 : float32[]
          %v1 = cast(%v0) : float32[2, 3]  # op#0
          %v2 = reshape(%v1) : float32[2, 3]  # op#1
          %v4 = multiply(%v2, %v3) : float32[2, 3]  # op#2
          fetch %v4
        }""")
    work, stats = _run_pass(main, "redundant_cast_reshape_elim", fv)
    assert (stats.matches, stats.rewritten_ops) == (2, 2)
    assert work.to_text(fetch_vars=fv) == _golden("""
        program {  # 1 ops, 1 feeds, 1 params, 0 grad_requests, 0 opt_updates
          feed  %v0 'x' : float32[2, 3]
          param %v3 : float32[]
          %v4 = multiply(%v0, %v3) : float32[2, 3]  # op#0
          fetch %v4
        }""")
    xv = np.random.RandomState(0).randn(2, 3).astype("float32")
    np.testing.assert_array_equal(
        _replay(main, {"x": xv}, fv[0]), _replay(work, {"x": xv}, fv[0])
    )


def _rope_sdpa_program():
    from paddle_tpu.models.llama import _rope

    main = static.Program()
    with static.program_guard(main, static.Program()):
        q = static.data("q", [1, 8, 4, 16], "float32")
        k = static.data("k", [1, 8, 4, 16], "float32")
        v = static.data("v", [1, 8, 4, 16], "float32")
        qk = apply("rope", lambda qv, kv: _rope(qv, kv), q, k)
        out = F.scaled_dot_product_attention(
            qk[0], qk[1], v, is_causal=True, training=False
        )
    return main, [main._id2var[id(out)]]


def test_golden_fuse_attention_rope_sdpa():
    main, fv = _rope_sdpa_program()
    assert main.to_text(fetch_vars=fv) == _golden("""
        program {  # 2 ops, 3 feeds, 0 params, 0 grad_requests, 0 opt_updates
          feed  %v0 'q' : float32[1, 8, 4, 16]
          feed  %v1 'k' : float32[1, 8, 4, 16]
          feed  %v2 'v' : float32[1, 8, 4, 16]
          %v3, %v4 = rope(%v0, %v1) : float32[1, 8, 4, 16], float32[1, 8, 4, 16]  # op#0
          %v5 = scaled_dot_product_attention(%v3, %v4, %v2) : float32[1, 8, 4, 16]  # op#1
          fetch %v5
        }""")
    work, stats = _run_pass(main, "fuse_attention", fv)
    assert (stats.matches, stats.rewritten_ops) == (1, 2)
    assert work.to_text(fetch_vars=fv) == _golden("""
        program {  # 1 ops, 3 feeds, 0 params, 0 grad_requests, 0 opt_updates
          feed  %v0 'q' : float32[1, 8, 4, 16]
          feed  %v1 'k' : float32[1, 8, 4, 16]
          feed  %v2 'v' : float32[1, 8, 4, 16]
          %v5 = fused_rope_flash_attention(%v0, %v1, %v2) : float32[1, 8, 4, 16]  # op#0
          fetch %v5
        }""")
    # mini-replay composition: bit-identical to the unfused chain
    rng = np.random.RandomState(1)
    feeds = {n: rng.randn(1, 8, 4, 16).astype("float32") for n in "qkv"}
    np.testing.assert_array_equal(
        _replay(main, feeds, fv[0]), _replay(work, feeds, fv[0])
    )


def test_golden_fuse_norm_matmul():
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [2, 8], "float32")
        norm = paddle.nn.RMSNorm(8)
        w2 = paddle.to_tensor(
            np.random.RandomState(0).randn(8, 4).astype("float32")
        )
        y = paddle.matmul(norm(x), w2)
    fv = [main._id2var[id(y)]]
    assert main.to_text(fetch_vars=fv) == _golden("""
        program {  # 2 ops, 1 feeds, 2 params, 0 grad_requests, 0 opt_updates
          feed  %v0 'x' : float32[2, 8]
          param %v1 : float32[8]
          param %v3 : float32[8, 4]
          %v2 = rms_norm(%v0, %v1) : float32[2, 8]  # op#0
          %v4 = matmul(%v2, %v3) : float32[2, 4]  # op#1
          fetch %v4
        }""")
    work, stats = _run_pass(main, "fuse_norm_matmul", fv)
    assert (stats.matches, stats.rewritten_ops) == (1, 2)
    assert work.to_text(fetch_vars=fv) == _golden("""
        program {  # 1 ops, 1 feeds, 2 params, 0 grad_requests, 0 opt_updates
          feed  %v0 'x' : float32[2, 8]
          param %v1 : float32[8]
          param %v3 : float32[8, 4]
          %v4 = fused_rms_norm_matmul(%v0, %v1, %v3) : float32[2, 4]  # op#0
          fetch %v4
        }""")
    xv = np.random.RandomState(2).randn(2, 8).astype("float32")
    np.testing.assert_array_equal(
        _replay(main, {"x": xv}, fv[0]), _replay(work, {"x": xv}, fv[0])
    )


def test_golden_fuse_bias_dropout_residual():
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [2, 8], "float32")
        b = static.data("b", [8], "float32")
        r = static.data("r", [2, 8], "float32")
        t = x + b
        d = F.dropout(t, p=0.3, training=True)
        y = d + r
    fv = [main._id2var[id(y)]]
    assert main.to_text(fetch_vars=fv) == _golden("""
        program {  # 3 ops, 3 feeds, 0 params, 0 grad_requests, 0 opt_updates
          feed  %v0 'x' : float32[2, 8]
          feed  %v1 'b' : float32[8]
          feed  %v2 'r' : float32[2, 8]
          %v3 = add(%v0, %v1) : float32[2, 8]  # op#0
          %v4 = dropout(%v3) : float32[2, 8]  # op#1
          %v5 = add(%v4, %v2) : float32[2, 8]  # op#2
          fetch %v5
        }""")
    work, stats = _run_pass(main, "fuse_bias_dropout_residual", fv)
    assert (stats.matches, stats.rewritten_ops) == (1, 3)
    assert work.to_text(fetch_vars=fv) == _golden("""
        program {  # 1 ops, 3 feeds, 0 params, 0 grad_requests, 0 opt_updates
          feed  %v0 'x' : float32[2, 8]
          feed  %v1 'b' : float32[8]
          feed  %v2 'r' : float32[2, 8]
          %v5 = fused_bias_dropout_residual(%v0, %v1, %v2) : float32[2, 8]  # op#0
          fetch %v5
        }""")
    # the fused fn replays the recorded dropout fn with its captured RNG
    # key: bit-identical mask, bit-identical outputs
    rng = np.random.RandomState(3)
    feeds = {"x": rng.randn(2, 8).astype("float32"),
             "b": rng.randn(8).astype("float32"),
             "r": rng.randn(2, 8).astype("float32")}
    np.testing.assert_array_equal(
        _replay(main, feeds, fv[0]), _replay(work, feeds, fv[0])
    )


# ---------------------------------------------------------------------------
# unfused attention chain -> Pallas flash dispatch (probed pattern)
# ---------------------------------------------------------------------------

def _unfused_attention_program(scale=None):
    d = 16
    main = static.Program()
    with static.program_guard(main, static.Program()):
        q = static.data("q", [1, 2, 8, d], "float32")
        k = static.data("k", [1, 2, 8, d], "float32")
        v = static.data("v", [1, 2, 8, d], "float32")
        s = paddle.matmul(q, k, transpose_y=True)
        s = paddle.scale(s, scale if scale is not None else 1.0 / math.sqrt(d))
        p = F.softmax(s)
        out = paddle.matmul(p, v)
    return main, [main._id2var[id(out)]]


def test_unfused_attention_chain_rewrites_to_flash():
    main, fv = _unfused_attention_program()
    work, stats = _run_pass(main, "fuse_attention", fv)
    assert (stats.matches, stats.rewritten_ops) == (1, 4)
    assert [op.name for op in work.ops] == ["fused_flash_attention"]
    rng = np.random.RandomState(4)
    feeds = {n: rng.randn(1, 2, 8, 16).astype("float32") for n in "qkv"}
    a, b = _replay(main, feeds, fv[0]), _replay(work, feeds, fv[0])
    # the flash path legitimately reassociates the softmax reduction:
    # fp tolerance, not bit identity (the one shipped pattern with that
    # contract)
    np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5)


def test_unfused_attention_wrong_scale_does_not_match():
    # the probe harvests the recorded scale factor from the op's closure;
    # anything but 1/sqrt(D) must NOT be rewritten into the flash kernel
    main, fv = _unfused_attention_program(scale=0.5)
    work, stats = _run_pass(main, "fuse_attention", fv)
    assert stats.matches == 0
    assert [op.name for op in work.ops] == ["matmul", "scale", "softmax", "matmul"]


def test_fusion_blocked_when_interior_var_is_fetched():
    # fetching the rope output pins it as a liveness root: the cluster may
    # not be collapsed (the interior value must stay observable)
    main, fv = _rope_sdpa_program()
    rope_out = main.ops[0].out_vars[0]
    work, stats = _run_pass(main, "fuse_attention", [fv[0], rope_out])
    assert stats.matches == 0
    assert len(work.ops) == 2


# ---------------------------------------------------------------------------
# mutant passes: one deliberately-miscompiling rewrite per pass class,
# caught by the post-pass verify with the pass NAMED
# ---------------------------------------------------------------------------

class _MutantFusionUndefinedRead(ProgramPass):
    """Fusion-class mutant: the 'replacement' reads a var no site defines."""

    name = "mutant_fusion_undefined_read"

    def run(self, program, ctx):
        op = program.ops[-1]
        program.ops[-1] = clone_op_with_inputs(
            op, [("var", 999999)] + list(op.in_refs[1:])
        )
        return PassStats(matches=1, rewritten_ops=1)


class _MutantCanonicalizeDoubleDefine(ProgramPass):
    """Canonicalize-class mutant: 'simplifies' by emitting a second op that
    re-binds an existing var (SSA violation)."""

    name = "mutant_canonicalize_double_define"

    def run(self, program, ctx):
        op = program.ops[0]
        program.ops.append(clone_op_with_inputs(op, list(op.in_refs)))
        return PassStats(matches=1, rewritten_ops=1)


class _MutantDceRemovesLiveOp(ProgramPass):
    """DCE-class mutant: removes the producer of the fetch target."""

    name = "mutant_dce_removes_live_op"

    def run(self, program, ctx):
        program.ops = program.ops[:-1]
        return PassStats(matches=1, rewritten_ops=1)


@pytest.mark.parametrize("mutant,check", [
    (_MutantFusionUndefinedRead, "undefined-var"),
    (_MutantCanonicalizeDoubleDefine, "single-assignment"),
    (_MutantDceRemovesLiveOp, "dangling-fetch"),
])
def test_mutant_pass_caught_by_verify_with_pass_named(mutant, check):
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [2, 3], "float32")
        y = F.softmax(x * 2.0)
    fv = [main._id2var[id(y)]]
    mgr = passes.PassManager([mutant()])
    with pytest.raises(ProgramVerifyError, match=mutant.name) as ei:
        mgr.run(main.clone(), fetch_vars=fv)
    assert check in [d.check for d in ei.value.diagnostics]
    assert f"after pass '{mutant.name}'" in str(ei.value)


def test_post_pipeline_verify_context_named():
    # run_default_pipeline's final verify re-checks the REWRITTEN program;
    # corrupting the clone's fetch target surfaces as 'post-pipeline'
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [2], "float32")
        y = x * 2.0
    with pytest.raises(ProgramVerifyError, match="post-pipeline|dangling-fetch"):
        passes.run_default_pipeline(main, fetch_vars=[987654])


# ---------------------------------------------------------------------------
# eager-converted tiny-Llama captures: the acceptance criteria
# ---------------------------------------------------------------------------

def _tiny_llama(**kw):
    from paddle_tpu.models.llama import LlamaForCausalLM

    cfg = dict(vocab_size=64, hidden_size=32, num_hidden_layers=2,
               num_attention_heads=4, intermediate_size=48)
    cfg.update(kw)
    return LlamaForCausalLM(**cfg)


def test_llama_eval_capture_matches_two_fusion_patterns():
    """Acceptance: the eager-converted capture (ZERO model-code changes via
    capture_program) hits >= 2 fusion patterns, visible in
    paddle_tpu_pass_matches_total, with outputs identical to passes-off."""
    model = _tiny_llama()
    model.eval()
    ids = paddle.to_tensor((np.arange(8) % 64).reshape(1, 8).astype("int64"))
    program, feed_names, fetch_list = capture_program(
        model, ids, feed_names=["ids"]
    )
    n_ops = len(program.ops)
    fa0 = _counter_value("paddle_tpu_pass_matches_total", **{"pass": "fuse_attention"})
    nm0 = _counter_value("paddle_tpu_pass_matches_total", **{"pass": "fuse_norm_matmul"})
    exe = static.Executor()
    feed = {"ids": ids.numpy()}
    (on,) = exe.run(program, feed=feed, fetch_list=fetch_list)
    # two distinct fusion patterns matched: one attention cluster per layer
    # plus the final norm -> lm_head projection
    assert _counter_value(
        "paddle_tpu_pass_matches_total", **{"pass": "fuse_attention"}
    ) == fa0 + 2
    assert _counter_value(
        "paddle_tpu_pass_matches_total", **{"pass": "fuse_norm_matmul"}
    ) == nm0 + 1
    assert len(program.ops) == n_ops  # the recorded capture is untouched
    paddle.set_flags({"FLAGS_program_passes": False})
    try:
        (off,) = exe.run(program, feed=feed, fetch_list=fetch_list)
    finally:
        paddle.set_flags({"FLAGS_program_passes": True})
    np.testing.assert_array_equal(np.asarray(on), np.asarray(off))


def test_llama_train_capture_passes_on_off_identity():
    """Acceptance: the TRAIN capture (loss + SGD minimize) produces
    bit-identical losses AND updated weights with the pipeline on vs off —
    grads flow through the fused ops unchanged."""
    model = _tiny_llama()
    ids_np = (np.arange(16) % 64).reshape(2, 8).astype("int64")
    main = static.Program()
    with static.program_guard(main, static.Program()):
        ids = static.data("ids", [2, 8], "int64")
        labels = static.data("labels", [2, 8], "int64")
        loss, _ = model(ids, labels=labels)
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        opt.minimize(loss)
    import jax.numpy as jnp

    def snapshot():
        return (
            {v: np.asarray(main._var_tensors[v]._value) for v in main.param_vars},
            [[np.asarray(a._value) for a in u.accum_tensors]
             for u in main.opt_updates],
        )

    def restore(state):
        params, accums = state
        for v, val in params.items():
            main._var_tensors[v]._replace_value(jnp.asarray(val))
        for u, vals in zip(main.opt_updates, accums):
            for a, val in zip(u.accum_tensors, vals):
                a._replace_value(jnp.asarray(val))

    exe = static.Executor()
    feed = {"ids": ids_np, "labels": ids_np}
    s0 = snapshot()
    losses_on = [
        np.asarray(exe.run(main, feed=feed, fetch_list=[loss])[0])
        for _ in range(2)
    ]
    w_on = model.parameters()[0].numpy().copy()
    restore(s0)
    paddle.set_flags({"FLAGS_program_passes": False})
    try:
        losses_off = [
            np.asarray(exe.run(main, feed=feed, fetch_list=[loss])[0])
            for _ in range(2)
        ]
        w_off = model.parameters()[0].numpy().copy()
    finally:
        paddle.set_flags({"FLAGS_program_passes": True})
    assert losses_on[1] != losses_on[0]  # the update really ran
    for a, b in zip(losses_on, losses_off):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(w_on, w_off)


def test_export_runs_pipeline(tmp_path):
    runs0 = _counter_value(
        "paddle_tpu_pass_runs_total", **{"pass": "dead_op_elimination"})
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", [2, 4], "float32")
        lin = paddle.nn.Linear(4, 2)
        y = lin(x)
        F.softmax(y)  # dead at export
    path = str(tmp_path / "model")
    static.save_inference_model(path, [x], [y], program=main)
    assert _counter_value(
        "paddle_tpu_pass_runs_total", **{"pass": "dead_op_elimination"}
    ) == runs0 + 1
    prog, feed_names, _fetches = static.load_inference_model(path)
    xv = np.random.RandomState(5).randn(2, 4).astype("float32")
    (got,) = static.Executor().run(prog, feed={"x": xv}, fetch_list=None)
    exe = static.Executor()
    (want,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_flag_off_skips_pipeline_entirely():
    runs0 = _counter_value(
        "paddle_tpu_pass_runs_total", **{"pass": "fuse_attention"})
    main, fv = _rope_sdpa_program()
    exe = static.Executor()
    rng = np.random.RandomState(6)
    feed = {n: rng.randn(1, 8, 4, 16).astype("float32") for n in "qkv"}
    paddle.set_flags({"FLAGS_program_passes": False})
    try:
        exe.run(main, feed=feed, fetch_list=[main._var_tensors[fv[0]]])
    finally:
        paddle.set_flags({"FLAGS_program_passes": True})
    assert _counter_value(
        "paddle_tpu_pass_runs_total", **{"pass": "fuse_attention"}) == runs0


def test_pass_telemetry_schema():
    main, fv = _rope_sdpa_program()
    runs0 = _counter_value(
        "paddle_tpu_pass_runs_total", **{"pass": "fuse_attention"})
    rw0 = _counter_value(
        "paddle_tpu_pass_rewritten_ops_total", **{"pass": "fuse_attention"})
    work, res = passes.run_default_pipeline(main, fetch_vars=fv)
    assert _counter_value(
        "paddle_tpu_pass_runs_total", **{"pass": "fuse_attention"}) == runs0 + 1
    assert _counter_value(
        "paddle_tpu_pass_rewritten_ops_total", **{"pass": "fuse_attention"}
    ) == rw0 + 2
    hist = telemetry.default_registry().get("paddle_tpu_pass_seconds")
    assert hist is not None
    # the pipeline summary is the bench detail.passes shape
    s = res.summary()
    assert s["matches"]["fuse_attention"] == 1
    assert s["rewritten_ops"]["fuse_attention"] == 2
    assert s["pipeline_ms"] > 0
    # verify ran after the rewriting pass AND post-pipeline: clean program
    assert verify(work, fetch_vars=fv) == []


def test_print_after_pass_diff(capsys):
    main, fv = _rope_sdpa_program()
    mgr = passes.PassManager(print_after={"fuse_attention"})
    mgr.run(main.clone(), fetch_vars=fv)
    err = capsys.readouterr().err
    assert "fuse_attention: before" in err
    assert "-  %v3, %v4 = rope(%v0, %v1)" in err
    assert "+  %v5 = fused_rope_flash_attention(%v0, %v1, %v2)" in err


def test_flag_toggle_recompiles_not_cache_hit():
    """FLAGS_program_passes is part of compiled identity: toggling it must
    MISS the compile cache and re-run (or skip) the pipeline — replaying
    the other mode's cached artifact would make every on/off identity
    comparison vacuous (a miscompiling pass could never be detected)."""
    main, fv = _rope_sdpa_program()
    exe = static.Executor()
    rng = np.random.RandomState(7)
    feed = {n: rng.randn(1, 8, 4, 16).astype("float32") for n in "qkv"}
    fetch = [main._var_tensors[fv[0]]]
    miss0 = _counter_value(
        "paddle_tpu_executor_compile_cache_total", result="miss")
    runs0 = _counter_value(
        "paddle_tpu_pass_runs_total", **{"pass": "fuse_attention"})
    exe.run(main, feed=feed, fetch_list=fetch)       # miss, pipeline runs
    paddle.set_flags({"FLAGS_program_passes": False})
    try:
        exe.run(main, feed=feed, fetch_list=fetch)   # MISS again, no pipeline
    finally:
        paddle.set_flags({"FLAGS_program_passes": True})
    exe.run(main, feed=feed, fetch_list=fetch)       # HIT the passes-on entry
    assert _counter_value(
        "paddle_tpu_executor_compile_cache_total", result="miss") == miss0 + 2
    assert _counter_value(
        "paddle_tpu_pass_runs_total", **{"pass": "fuse_attention"}) == runs0 + 1


def test_register_custom_pass_in_default_pipeline():
    from paddle_tpu.static.passes import pass_base

    calls = []

    class _ProbePass(ProgramPass):
        name = "test_probe_pass"

        def run(self, program, ctx):
            calls.append(len(program.ops))
            return PassStats()

    passes.register_pass(_ProbePass, before="fuse_attention")
    try:
        names = [p.name for p in passes.default_pipeline()]
        assert names.index("test_probe_pass") == names.index("fuse_attention") - 1
        main, fv = _rope_sdpa_program()
        passes.run_default_pipeline(main, fetch_vars=fv)
        assert calls == [2]  # ran, before fusion collapsed the cluster
    finally:
        pass_base._REGISTRY.pop("test_probe_pass", None)
        pass_base.PIPELINE_ORDER.remove("test_probe_pass")


# ---------------------------------------------------------------------------
# round 20: fuse_moe — the dispatch -> expert FFN -> combine cluster
# ---------------------------------------------------------------------------

def _tiny_moe(num_expert=4, top_k=2):
    from paddle_tpu.incubate.distributed.models.moe import ExpertLayer, MoELayer

    paddle.seed(0)
    return MoELayer(
        d_model=16,
        experts=[ExpertLayer(16, 32) for _ in range(num_expert)],
        gate={"type": "gshard", "top_k": top_k},
    )


def test_fuse_moe_pattern_matches_and_preserves_outputs():
    """The tentpole pattern: a captured MoE forward records the fixed-arity
    moe_dispatch_ec -> moe_expert_ffn -> moe_combine_ec chain and fuse_moe
    collapses it into one cluster instr — with moe_routing left OUTSIDE
    (its l_aux / dropped outputs escape to loss/telemetry, which
    _cluster_safe must respect) and outputs identical passes-on vs off."""
    moe = _tiny_moe()
    moe.eval()
    x = paddle.Tensor(np.random.RandomState(0).randn(12, 16).astype("float32"))
    program, feed_names, fetch_list = capture_program(moe, x, feed_names=["x"])
    kinds = [op.name for op in program.ops]
    for k in ("moe_routing", "moe_dispatch_ec", "moe_expert_ffn",
              "moe_combine_ec"):
        assert k in kinds, f"capture missing recorded op {k}"

    fv = [program.resolve_fetch(fetch_list[0])]
    work, res = passes.run_default_pipeline(program, fetch_vars=fv,
                                            feed_names=feed_names)
    assert res.matches.get("fuse_moe") == 1
    new_kinds = [op.name for op in work.ops]
    assert "fused_moe_dispatch_expert_combine" in new_kinds
    # routing survives un-fused: its aux outputs are liveness roots
    assert "moe_routing" in new_kinds
    assert "moe_dispatch_ec" not in new_kinds
    assert "moe_combine_ec" not in new_kinds

    exe = static.Executor()
    feed = {"x": x.numpy()}
    (on,) = exe.run(program, feed=feed, fetch_list=fetch_list)
    paddle.set_flags({"FLAGS_program_passes": False})
    try:
        (off,) = exe.run(program, feed=feed, fetch_list=fetch_list)
    finally:
        paddle.set_flags({"FLAGS_program_passes": True})
    np.testing.assert_array_equal(np.asarray(on), np.asarray(off))


def test_fuse_moe_skipped_when_aux_consumed_inside_would_break():
    """Safety: if the captured graph ALSO fetches the expert-FFN
    intermediate (an outside consumer of an interior var), the cluster is
    unsafe and the pattern must NOT rewrite — correctness over coverage."""
    moe = _tiny_moe()
    moe.eval()
    x = paddle.Tensor(np.random.RandomState(1).randn(8, 16).astype("float32"))
    program, feed_names, fetch_list = capture_program(moe, x, feed_names=["x"])
    # find the expert-FFN op's output var and fetch it too
    eo_vid = None
    for op in program.ops:
        if op.name == "moe_expert_ffn":
            eo_vid = op.out_vars[0]
    assert eo_vid is not None
    fv = [program.resolve_fetch(fetch_list[0]), eo_vid]
    work, res = passes.run_default_pipeline(program, fetch_vars=fv,
                                            feed_names=feed_names)
    assert res.matches.get("fuse_moe", 0) == 0
    assert "fused_moe_dispatch_expert_combine" not in [op.name for op in work.ops]
