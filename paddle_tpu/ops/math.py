"""Elementwise + reduction math ops.

Reference parity: python/paddle/tensor/math.py (and the corresponding PHI
kernels in paddle/phi/kernels/). Kernels are jnp/lax — XLA fuses elementwise
chains into single TPU loops, so there is no fused-op zoo to maintain.
"""
from __future__ import annotations

import numpy as np
import jax
from jax import numpy as jnp

from ..core.apply import apply, apply_nograd
from ..core.tensor import Tensor, _ensure_tensor
from ..framework import dtype as dtype_mod


def _t(x):
    return _ensure_tensor(x)


def _binop(opname, fn):
    def op(x, y, name=None):
        x, y = _binary_promote(x, y)
        return apply(opname, fn, x, y)

    op.__name__ = opname
    return op


def _binary_promote(x, y):
    """Paddle-style scalar handling: python scalars follow the tensor dtype."""
    if isinstance(x, Tensor) and not isinstance(y, Tensor):
        if isinstance(y, (int, float, bool, np.number)) and not isinstance(y, np.ndarray):
            if isinstance(y, (bool, np.bool_)):
                y = Tensor(jnp.asarray(y))
            elif isinstance(y, (int, np.integer)):
                y = Tensor(jnp.asarray(y, dtype=x._value.dtype if jnp.issubdtype(x._value.dtype, jnp.number) else None))
            else:
                d = x._value.dtype
                if not jnp.issubdtype(d, jnp.inexact):
                    d = dtype_mod.get_default_dtype()
                y = Tensor(jnp.asarray(y, dtype=d))
        else:
            y = _t(y)
    elif isinstance(y, Tensor) and not isinstance(x, Tensor):
        y2, x2 = _binary_promote(y, x)
        return x2, y2
    else:
        x, y = _t(x), _t(y)
    return x, y


add = _binop("add", jnp.add)
subtract = _binop("subtract", jnp.subtract)
multiply = _binop("multiply", jnp.multiply)
divide = _binop("divide", jnp.true_divide)
floor_divide = _binop("floor_divide", jnp.floor_divide)
mod = _binop("mod", jnp.mod)
remainder = mod
floor_mod = mod
pow_op = _binop("pow", jnp.power)
maximum = _binop("maximum", jnp.maximum)
minimum = _binop("minimum", jnp.minimum)
fmax = _binop("fmax", jnp.fmax)
fmin = _binop("fmin", jnp.fmin)
atan2 = _binop("atan2", jnp.arctan2)
heaviside = _binop("heaviside", jnp.heaviside)
copysign = _binop("copysign", jnp.copysign)
hypot = _binop("hypot", jnp.hypot)
nextafter = _binop("nextafter", jnp.nextafter)
ldexp = _binop("ldexp", lambda x, y: jnp.ldexp(x, y.astype(jnp.int32)))
logaddexp = _binop("logaddexp", jnp.logaddexp)
gcd = _binop("gcd", jnp.gcd)
lcm = _binop("lcm", jnp.lcm)


def pow(x, y, name=None):  # noqa: A001 - paddle API name
    return pow_op(x, y)


def divide_no_nan(x, y):
    x, y = _binary_promote(x, y)
    return apply("divide_no_nan", lambda a, b: jnp.where(b == 0, jnp.zeros((), a.dtype), a / jnp.where(b == 0, 1, b)), x, y)


def _unop(opname, fn):
    def op(x, name=None):
        return apply(opname, fn, _t(x))

    op.__name__ = opname
    return op


exp = _unop("exp", jnp.exp)
expm1 = _unop("expm1", jnp.expm1)
log = _unop("log", jnp.log)
log2 = _unop("log2", jnp.log2)
log10 = _unop("log10", jnp.log10)
log1p = _unop("log1p", jnp.log1p)
sqrt = _unop("sqrt", jnp.sqrt)
rsqrt = _unop("rsqrt", jax.lax.rsqrt)
abs = _unop("abs", jnp.abs)  # noqa: A001
absolute = abs
neg = _unop("neg", jnp.negative)
negative = neg
sign = _unop("sign", jnp.sign)
sgn = sign
sin = _unop("sin", jnp.sin)
cos = _unop("cos", jnp.cos)
tan = _unop("tan", jnp.tan)
asin = _unop("asin", jnp.arcsin)
acos = _unop("acos", jnp.arccos)
atan = _unop("atan", jnp.arctan)
arcsin, arccos, arctan = asin, acos, atan
sinh = _unop("sinh", jnp.sinh)
cosh = _unop("cosh", jnp.cosh)
tanh = _unop("tanh", jnp.tanh)
asinh = _unop("asinh", jnp.arcsinh)
acosh = _unop("acosh", jnp.arccosh)
atanh = _unop("atanh", jnp.arctanh)
floor = _unop("floor", jnp.floor)
ceil = _unop("ceil", jnp.ceil)
trunc = _unop("trunc", jnp.trunc)
frac = _unop("frac", lambda x: x - jnp.trunc(x))
reciprocal = _unop("reciprocal", jnp.reciprocal)
square = _unop("square", jnp.square)
erf = _unop("erf", jax.scipy.special.erf)
erfinv = _unop("erfinv", jax.scipy.special.erfinv)
lgamma = _unop("lgamma", jax.scipy.special.gammaln)
digamma = _unop("digamma", jax.scipy.special.digamma)
polygamma_impl = jax.scipy.special.polygamma
i0 = _unop("i0", jax.scipy.special.i0)
i0e = _unop("i0e", jax.scipy.special.i0e)
i1 = _unop("i1", jax.scipy.special.i1)
i1e = _unop("i1e", jax.scipy.special.i1e)
deg2rad = _unop("deg2rad", jnp.deg2rad)
rad2deg = _unop("rad2deg", jnp.rad2deg)
angle = _unop("angle", jnp.angle)
conj = _unop("conj", jnp.conj)
real = _unop("real", jnp.real)
imag = _unop("imag", jnp.imag)
sigmoid = _unop("sigmoid", jax.nn.sigmoid)
logit_raw = None
exponent_bits = None


def polygamma(x, n):
    return apply("polygamma", lambda v: polygamma_impl(n, v), _t(x))


def round(x, decimals=0, name=None):  # noqa: A001
    return apply("round", lambda v: jnp.round(v, decimals), _t(x))


def rint(x):
    return apply("rint", jnp.rint, _t(x))


def logit(x, eps=None):
    def f(v):
        if eps is not None:
            v = jnp.clip(v, eps, 1.0 - eps)
        return jnp.log(v / (1.0 - v))

    return apply("logit", f, _t(x))


def clip(x, min=None, max=None, name=None):  # noqa: A001
    x = _t(x)
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return apply("clip", lambda v: jnp.clip(v, mn, mx), x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x = _t(x)
    s = scale.value if isinstance(scale, Tensor) else scale

    def f(v):
        out = v * jnp.asarray(s, v.dtype) + bias if bias_after_scale else (v + bias) * jnp.asarray(s, v.dtype)
        return out

    out = apply("scale", f, x)
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


def increment(x, value=1.0):
    x._become(add(x, value))
    return x


def stanh(x, scale_a=0.67, scale_b=1.7159):
    return apply("stanh", lambda v: scale_b * jnp.tanh(scale_a * v), _t(x))


def multiplex(inputs, index):
    vals = [_t(i).value for i in inputs]
    idx = _t(index).value.reshape(-1)
    stacked = jnp.stack(vals, axis=0)
    return Tensor(stacked[idx, jnp.arange(stacked.shape[1])])


def addmm(input, x, y, beta=1.0, alpha=1.0):
    return apply(
        "addmm",
        lambda i, a, b: beta * i + alpha * (a @ b),
        _t(input), _t(x), _t(y),
    )


def inner(x, y):
    return apply("inner", lambda a, b: jnp.inner(a, b), *_binary_promote(x, y))


def outer(x, y):
    return apply("outer", lambda a, b: jnp.outer(a, b), *_binary_promote(x, y))


def dot(x, y):
    def f(a, b):
        if a.ndim == 1:
            return jnp.sum(a * b)
        return jnp.sum(a * b, axis=-1)

    return apply("dot", f, *_binary_promote(x, y))


def kron(x, y):
    return apply("kron", jnp.kron, *_binary_promote(x, y))


def cross(x, y, axis=9):
    def f(a, b):
        ax = axis
        if ax == 9:
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)

    return apply("cross", f, *_binary_promote(x, y))


def trace(x, offset=0, axis1=0, axis2=1):
    return apply("trace", lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2), _t(x))


def lerp(x, y, weight):
    if isinstance(weight, Tensor):
        # weight is a differentiable input (reference lerp_grad computes
        # dweight) — it must flow through apply, not be baked as a constant
        return apply("lerp", lambda a, b, w: a + w * (b - a), *_binary_promote(x, y), weight)
    return apply("lerp", lambda a, b: a + weight * (b - a), *_binary_promote(x, y))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return apply("nan_to_num", lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf), _t(x))


# ---- reductions ----

def _axes(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = axis.numpy().tolist()
        return tuple(a) if isinstance(a, list) else int(a)
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    x = _t(x)
    d = dtype_mod.convert_dtype(dtype) if dtype is not None else None

    def f(v):
        if d is None and jnp.issubdtype(v.dtype, jnp.bool_):
            return jnp.sum(v, axis=_axes(axis), keepdims=keepdim, dtype=jnp.int64)
        return jnp.sum(v, axis=_axes(axis), keepdims=keepdim, dtype=d)

    return apply("sum", f, x)


def mean(x, axis=None, keepdim=False, name=None):
    return apply("mean", lambda v: jnp.mean(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    d = dtype_mod.convert_dtype(dtype) if dtype is not None else None
    return apply("prod", lambda v: jnp.prod(v, axis=_axes(axis), keepdims=keepdim, dtype=d), _t(x))


def max(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply("max", lambda v: jnp.max(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def min(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply("min", lambda v: jnp.min(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def amax(x, axis=None, keepdim=False):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False):
    return min(x, axis, keepdim)


def nansum(x, axis=None, dtype=None, keepdim=False):
    d = dtype_mod.convert_dtype(dtype) if dtype is not None else None
    return apply("nansum", lambda v: jnp.nansum(v, axis=_axes(axis), keepdims=keepdim, dtype=d), _t(x))


def nanmean(x, axis=None, keepdim=False):
    return apply("nanmean", lambda v: jnp.nanmean(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def count_nonzero(x, axis=None, keepdim=False):
    return apply_nograd("count_nonzero", lambda v: jnp.count_nonzero(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def logsumexp(x, axis=None, keepdim=False):
    return apply("logsumexp", lambda v: jax.scipy.special.logsumexp(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def cumsum(x, axis=None, dtype=None, name=None):
    d = dtype_mod.convert_dtype(dtype) if dtype is not None else None

    def f(v):
        if axis is None:
            return jnp.cumsum(v.reshape(-1), dtype=d)
        return jnp.cumsum(v, axis=_axes(axis), dtype=d)

    return apply("cumsum", f, _t(x))


def cumprod(x, dim=None, dtype=None, name=None):
    d = dtype_mod.convert_dtype(dtype) if dtype is not None else None

    def f(v):
        if dim is None:
            return jnp.cumprod(v.reshape(-1), dtype=d)
        return jnp.cumprod(v, axis=dim, dtype=d)

    return apply("cumprod", f, _t(x))


def cummax(x, axis=None, dtype=dtype_mod.int64):
    x = _t(x)

    def f(v):
        ax = axis if axis is not None else 0
        vv = v.reshape(-1) if axis is None else v
        vals = jax.lax.associative_scan(jnp.maximum, vv, axis=ax)
        return vals

    vals = apply("cummax_vals", f, x)
    # indices via argmax of running max equality
    def fi(v):
        ax = axis if axis is not None else 0
        vv = v.reshape(-1) if axis is None else v
        vals_ = jax.lax.associative_scan(jnp.maximum, vv, axis=ax)
        n = vv.shape[ax]
        idx = jnp.arange(n).reshape([-1 if i == (ax % vv.ndim) else 1 for i in range(vv.ndim)])
        eq = vv == vals_
        first = jax.lax.associative_scan(jnp.maximum, jnp.where(eq, idx, -1), axis=ax)
        return first.astype(dtype_mod.convert_dtype(dtype))

    idxs = apply_nograd("cummax_idx", fi, x)
    return vals, idxs


def cummin(x, axis=None, dtype=dtype_mod.int64):
    neg_vals, idxs = cummax(neg(_t(x)), axis=axis, dtype=dtype)
    return neg(neg_vals), idxs


def logcumsumexp(x, axis=None):
    def f(v):
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else axis
        return jax.lax.associative_scan(jnp.logaddexp, vv, axis=ax)

    return apply("logcumsumexp", f, _t(x))


def diff(x, n=1, axis=-1, prepend=None, append=None):
    p = prepend.value if isinstance(prepend, Tensor) else prepend
    a = append.value if isinstance(append, Tensor) else append
    return apply("diff", lambda v: jnp.diff(v, n=n, axis=axis, prepend=p, append=a), _t(x))


# ---- checks (non-differentiable) ----

def isnan(x):
    return apply_nograd("isnan", jnp.isnan, _t(x))


def isinf(x):
    return apply_nograd("isinf", jnp.isinf, _t(x))


def isfinite(x):
    return apply_nograd("isfinite", jnp.isfinite, _t(x))


def isneginf(x):
    return apply_nograd("isneginf", jnp.isneginf, _t(x))


def isposinf(x):
    return apply_nograd("isposinf", jnp.isposinf, _t(x))


def isreal(x):
    return apply_nograd("isreal", jnp.isreal, _t(x))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return apply_nograd("isclose", lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), _t(x), _t(y))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_nograd("allclose", lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), _t(x), _t(y))


def equal_all(x, y):
    return apply_nograd("equal_all", lambda a, b: jnp.array_equal(a, b), _t(x), _t(y))


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply_nograd("any", lambda v: jnp.any(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply_nograd("all", lambda v: jnp.all(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    """paddle.std (python/paddle/tensor/stat.py): sample std, ddof=1 default."""
    return apply(
        "std",
        lambda v: jnp.std(v, axis=_axes(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        _t(x),
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(
        "var",
        lambda v: jnp.var(v, axis=_axes(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        _t(x),
    )


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """paddle.trapezoid (python/paddle/tensor/math.py)."""
    if x is not None:
        return apply("trapezoid", lambda yv, xv: jnp.trapezoid(yv, xv, axis=axis), _t(y), _t(x))
    return apply("trapezoid", lambda yv: jnp.trapezoid(yv, dx=dx if dx is not None else 1.0, axis=axis), _t(y))


def _cumtrap(yv, xv=None, dx=1.0, axis=-1):
    yv = jnp.moveaxis(yv, axis, -1)
    if xv is not None:
        d = jnp.diff(jnp.moveaxis(xv, axis, -1) if xv.ndim == yv.ndim else xv)
    else:
        d = dx
    avg = (yv[..., 1:] + yv[..., :-1]) / 2.0
    return jnp.moveaxis(jnp.cumsum(avg * d, axis=-1), -1, axis)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return apply("cumulative_trapezoid", lambda yv, xv: _cumtrap(yv, xv, axis=axis), _t(y), _t(x))
    return apply("cumulative_trapezoid", lambda yv: _cumtrap(yv, dx=dx if dx is not None else 1.0, axis=axis), _t(y))


def renorm(x, p, axis, max_norm, name=None):
    """paddle.renorm: clamp the p-norm of each slice along `axis` to max_norm."""

    def fn(v):
        moved = jnp.moveaxis(v, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        # sanitize BEFORE the power: d/dx (sum|x|^p)^(1/p) is nan at 0, and
        # where() cannot stop reverse-mode nans from the untaken branch
        # (zero rows appear routinely, e.g. ASP-pruned weights)
        sumsq = jnp.sum(jnp.abs(flat) ** p, axis=1)
        safe = jnp.maximum(sumsq, 1e-24)
        norms = jnp.where(sumsq > 0, safe ** (1.0 / p), 0.0)
        scale = jnp.where(norms > max_norm, max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)

    return apply("renorm", fn, _t(x))


def vander(x, n=None, increasing=False, name=None):
    def fn(v):
        cols = v.shape[0] if n is None else n
        out = jnp.vander(v, cols, increasing=increasing)
        return out

    return apply("vander", fn, _t(x))


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, (list, tuple)):
        ax = tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in ax)
    return apply("tensordot", lambda a, b: jnp.tensordot(a, b, axes=ax), _t(x), _t(y))


# ---------------------------------------------------------------------------
# r3 API-parity additions (VERDICT r2 Missing #1)
# ---------------------------------------------------------------------------

def add_n(inputs, name=None):
    """Elementwise sum of a list of tensors (tensor/math.py:1920)."""
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    ts = [_t(i) for i in inputs]

    def fn(*vals):
        out = vals[0]
        for v in vals[1:]:
            out = out + v
        return out

    return apply("add_n", fn, *ts)


def gammaln(x, name=None):
    """Log of the absolute gamma function (tensor/math.py gammaln)."""
    return apply("gammaln", lambda v: jax.scipy.special.gammaln(v), _t(x))


def gammainc(x, y, name=None):
    """Regularized lower incomplete gamma P(x, y) (tensor/math.py:5152)."""
    return apply("gammainc", lambda a, b: jax.scipy.special.gammainc(a, b), _t(x), _t(y))


def gammaincc(x, y, name=None):
    """Regularized upper incomplete gamma Q(x, y) (tensor/math.py:5091)."""
    return apply("gammaincc", lambda a, b: jax.scipy.special.gammaincc(a, b), _t(x), _t(y))


def multigammaln(x, p, name=None):
    """Log multivariate gamma (tensor/math.py:5242)."""
    return apply("multigammaln", lambda v: jax.scipy.special.multigammaln(v, p), _t(x))


def frexp(x, name=None):
    """Mantissa/exponent decomposition: x = m * 2**e (tensor/math.py:6504)."""
    def fn(v):
        m, e = jnp.frexp(v)
        return m, e.astype(v.dtype)

    return apply("frexp", fn, _t(x))


def signbit(x, name=None):
    """True where the sign bit is set (tensor/math.py:7596)."""
    return apply_nograd("signbit", lambda v: jnp.signbit(v), _t(x))


def combinations(x, r=2, with_replacement=False, name=None):
    """All r-length combinations of a 1-D tensor (tensor/math.py:7530).

    The index set depends only on the (static) length, so it is computed
    host-side with itertools and baked in as a constant gather — no
    data-dependent shapes under jit."""
    import itertools

    x = _t(x)
    n = x._value.shape[0]
    picker = itertools.combinations_with_replacement if with_replacement else itertools.combinations
    idx = np.asarray(list(picker(range(n), r)), dtype=np.int32).reshape(-1, r)

    def fn(v):
        return v[jnp.asarray(idx)]

    return apply("combinations", fn, x)
