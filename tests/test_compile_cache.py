"""Round 18: compilation-lifecycle observability + persistent compile cache.

Two halves under test: the compile-event LEDGER (every lower()/compile()
across the four entry points emits origin/fingerprint/outcome events with
paddle_tpu_compile_* telemetry; hits are counter-only) and the persistent
STORE (executables serialized under the PR 2 torn-write discipline, keyed
by (program fingerprint, topology meta, jax version), restored instead of
recompiled — with every corruption mode falling back to a fresh compile,
counted, never a crash or a wrong executable).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import compile_cache as cc
from paddle_tpu import telemetry as tm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def telemetry_on():
    was = tm.enabled()
    tm.enable()
    yield
    if not was:
        tm.disable()


@pytest.fixture
def store(tmp_path, telemetry_on):
    """A configured persistent store in a tmp dir, deconfigured after."""
    st = cc.configure(str(tmp_path / "cache"))
    yield st
    cc.configure(None)


@pytest.fixture(scope="module")
def tiny_model():
    from paddle_tpu.models.llama import llama_tiny

    paddle.seed(0)
    m = llama_tiny(num_key_value_heads=2)
    m.eval()
    return m


def _tiny_engine(model, **kw):
    from paddle_tpu.inference.engine import InferenceEngine

    kw.setdefault("max_seq_len", 32)
    kw.setdefault("block_size", 8)
    kw.setdefault("max_batch", 2)
    kw.setdefault("decode_batch_buckets", (2,))
    return InferenceEngine(model, **kw)


def _mk_exec(scale=2.0, n=4):
    f = jax.jit(lambda x: x * scale)
    return f.lower(jax.ShapeDtypeStruct((n,), jnp.float32)).compile()


def _err_count(reason):
    fam = tm.default_registry().get("paddle_tpu_compile_cache_errors_total")
    if fam is None:
        return 0
    return sum(c.value for c in fam.children()
               if dict(c.labels).get("reason") == reason)


# ---------------------------------------------------------------------------
# fingerprints + topology keys
# ---------------------------------------------------------------------------

def test_fingerprint_stability_and_aval_signature():
    assert cc.fingerprint_text("abc") == cc.fingerprint_text("abc")
    assert cc.fingerprint_text("abc") != cc.fingerprint_text("abd")
    s1 = cc.aval_signature([jax.ShapeDtypeStruct((2, 3), jnp.float32)])
    s2 = cc.aval_signature([jax.ShapeDtypeStruct((2, 3), jnp.bfloat16)])
    s3 = cc.aval_signature([jax.ShapeDtypeStruct((3, 2), jnp.float32)])
    assert len({s1, s2, s3}) == 3  # dtype and shape both participate


def test_entry_key_separates_disjoint_same_shape_submeshes():
    """The fleet-sharing bugfix: two replicas on DISJOINT same-shape
    submeshes compile executables pinned to different devices — their cache
    keys must differ or replica B runs on replica A's devices."""
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device test mesh")
    m1 = Mesh(np.array(devs[:4]).reshape(2, 2), ("dp", "tp"))
    m2 = Mesh(np.array(devs[4:8]).reshape(2, 2), ("dp", "tp"))
    meta1, meta2 = cc.topology_meta(m1), cc.topology_meta(m2)
    assert meta1["mesh_shape"] == meta2["mesh_shape"]
    assert meta1["mesh_devices"] != meta2["mesh_devices"]
    assert cc.entry_key("f" * 32, meta1) != cc.entry_key("f" * 32, meta2)


# ---------------------------------------------------------------------------
# ledger
# ---------------------------------------------------------------------------

def test_ledger_events_and_hit_counter_only(telemetry_on):
    cc.reset()
    before = cc.summary()
    serial0 = cc.ledger.last_serial()
    cc.record("serving", "prefill_8", "miss", seconds=0.25, fingerprint="ab")
    cc.record("serving", "prefill_8", "hit")
    cc.record("serving", "prefill_8", "hit")
    cc.record("serving", "prefill_8", "persist", seconds=0.01)
    evs = cc.events(since_serial=serial0)
    # hits are counter-only: per-dispatch events would flood the bounded
    # store out of its rare compile-path events
    assert [e["outcome"] for e in evs] == ["miss", "persist"]
    assert evs[0]["seconds"] == 0.25 and evs[0]["fingerprint"] == "ab"
    after = cc.summary()
    assert after["hits"] - before["hits"] == 2
    assert after["misses"] - before["misses"] == 1
    assert after["available"]


def test_ledger_disabled_records_nothing():
    was = tm.enabled()
    tm.disable()
    try:
        serial0 = cc.ledger.last_serial()
        assert cc.record("serving", "x", "miss", seconds=1.0) is None
        assert cc.events(since_serial=serial0) == []
    finally:
        if was:
            tm.enable()


def test_ledger_dump_roundtrip(tmp_path, telemetry_on):
    cc.reset()
    cc.record("to_static", "step", "miss", seconds=0.5)
    p = cc.ledger.dump_json(str(tmp_path / "ledger.json"))
    doc = cc.ledger.load_dump(p)
    assert doc["version"] == 1
    assert any(e["origin"] == "to_static" for e in doc["events"])
    assert doc["summary"]["available"]


# ---------------------------------------------------------------------------
# store: atomic layout, corruption fallback, chaos site
# ---------------------------------------------------------------------------

def test_store_roundtrip_and_verify(store):
    ex = _mk_exec()
    key = cc.entry_key("a" * 32)
    assert store.put(key, ex, cc.make_meta("serving", "t", "a" * 32))
    got = store.get(key, expect_meta=cc.topology_meta())
    assert got is not None
    restored, meta = got
    x = jnp.arange(4, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(restored(x)),
                                  np.asarray(ex(x)))
    assert meta["origin"] == "serving"
    assert store.verify() == {"entries": 1, "corrupt": 0, "failures": {}}


def test_store_topology_mismatch_rejected(store):
    key = cc.entry_key("b" * 32)
    assert store.put(key, _mk_exec(), cc.make_meta("serving", "t", "b" * 32))
    wrong = dict(cc.topology_meta())
    wrong["jax_version"] = "0.0.0-other"
    n0 = _err_count("topology_mismatch")
    assert store.get(key, expect_meta=wrong) is None
    assert _err_count("topology_mismatch") == n0 + 1


@pytest.mark.parametrize("corruption,reason", [
    ("truncate", "crc_mismatch"),
    ("flip", "crc_mismatch"),
    ("unmark", "torn_entry"),
    ("bad_meta", "bad_meta"),
])
def test_store_corruption_falls_back_counted(store, corruption, reason):
    """Every torn/corrupt shape is a counted miss, never a crash or a
    wrong executable."""
    key = cc.entry_key("c" * 32)
    assert store.put(key, _mk_exec(), cc.make_meta("serving", "t", "c" * 32))
    d = os.path.join(store.root, key)
    if corruption == "truncate":
        with open(os.path.join(d, "payload.bin"), "r+b") as f:
            f.truncate(10)
    elif corruption == "flip":
        with open(os.path.join(d, "payload.bin"), "r+b") as f:
            b = bytearray(f.read())
            b[len(b) // 2] ^= 0xFF
            f.seek(0)
            f.write(bytes(b))
    elif corruption == "unmark":
        os.remove(os.path.join(d, "COMPLETE"))
    else:
        with open(os.path.join(d, "meta.json"), "w") as f:
            f.write("{not json")
    n0 = _err_count(reason)
    assert store.get(key, expect_meta=cc.topology_meta()) is None
    assert _err_count(reason) == n0 + 1
    if corruption != "unmark":
        ok, why = store.verify_entry(key)
        assert not ok and why != "ok"


def test_store_read_chaos_site_is_counted_miss(store):
    """FaultPlan site `compile_cache.read`: an injected read fault surfaces
    as a counted miss (the caller compiles fresh), never an exception."""
    from paddle_tpu.distributed.resilience import fault_injection as fi

    key = cc.entry_key("d" * 32)
    assert store.put(key, _mk_exec(), cc.make_meta("serving", "t", "d" * 32))
    n0 = _err_count("read_failed")
    fi.install_plan(fi.FaultPlan().add("compile_cache.read", "fail", times=1))
    try:
        assert store.get(key, expect_meta=cc.topology_meta()) is None
    finally:
        fi.clear_plan()
    assert _err_count("read_failed") == n0 + 1
    # plan exhausted: the same entry restores fine
    assert store.get(key, expect_meta=cc.topology_meta()) is not None


def test_store_gc_corrupt_first_then_lru(store):
    keys = [cc.entry_key(ch * 32) for ch in "efg"]
    for k in keys:
        assert store.put(k, _mk_exec(), cc.make_meta("serving", "t", k[:32]))
    os.remove(os.path.join(store.root, keys[1], "COMPLETE"))
    rep = store.gc(max_bytes=store.entry_bytes(keys[0]))
    reasons = {r["key"]: r["reason"] for r in rep["removed"]}
    assert reasons[keys[1]] == "missing_complete_marker"  # corrupt goes first
    assert sum(1 for r in reasons.values() if r == "lru") >= 1
    assert store.stats()["bytes"] <= store.entry_bytes(keys[0]) * 2


# ---------------------------------------------------------------------------
# engine: persist -> restore, in-process sharing
# ---------------------------------------------------------------------------

def test_engine_cold_persist_then_warm_restore(tiny_model, store):
    prompt = list(range(1, 7))
    cold = _tiny_engine(tiny_model)
    cold.prewarm()
    cold_ids = cold.generate([prompt], max_new_tokens=4)
    n_buckets = cold.bucket_stats["compiles"]
    assert n_buckets >= 2  # prefill buckets + the decode bucket
    evs = [e for e in cc.events() if e["origin"] == "serving"]
    assert {e["outcome"] for e in evs} == {"miss", "persist"}
    # the relaunch: no in-process executables survive
    del cold
    cc.clear_shared()
    cc.reset()
    warm = _tiny_engine(tiny_model)
    warm.prewarm()
    warm_ids = warm.generate([prompt], max_new_tokens=4)
    assert warm.bucket_stats.get("compiles", 0) == 0
    assert warm.bucket_stats.get("restored", 0) == n_buckets
    evs = [e for e in cc.events() if e["origin"] == "serving"]
    assert evs and all(e["outcome"] == "restore" for e in evs)
    assert warm_ids == cold_ids


def test_engine_inprocess_sharing_outcome_shared(tiny_model, telemetry_on):
    cc.clear_shared()
    cc.reset()
    a = _tiny_engine(tiny_model)
    a.prewarm()
    n = a.bucket_stats["compiles"]
    b = _tiny_engine(tiny_model)
    b.prewarm()
    assert b.bucket_stats.get("compiles", 0) == 0
    assert b.bucket_stats.get("shared", 0) == n
    shared_evs = cc.events(outcome="shared")
    assert len([e for e in shared_evs if e["origin"] == "serving"]) == n
    # and the shared executable really answers
    ids_a = a.generate([[1, 2, 3]], max_new_tokens=3)
    ids_b = b.generate([[1, 2, 3]], max_new_tokens=3)
    assert ids_a == ids_b


def test_fleet_prewarm_compiles_once(tiny_model, telemetry_on):
    """Satellite 1: a same-signature replica fleet compiles each bucket
    ONCE — replica 0 pays the misses, the rest adopt via the shared
    registry."""
    from paddle_tpu.inference.fleet import ReplicaFleet

    cc.clear_shared()
    cc.reset()
    engines = [_tiny_engine(tiny_model) for _ in range(2)]
    fl = ReplicaFleet(engines)
    stats = fl.prewarm()
    assert stats[0]["compiles"] >= 2 and stats[0].get("shared", 0) == 0
    assert stats[1].get("compiles", 0) == 0
    assert stats[1].get("shared", 0) == stats[0]["compiles"]


# ---------------------------------------------------------------------------
# the other entry points: to_static, static Executor, fused optimizer
# ---------------------------------------------------------------------------

def test_to_static_ledger_and_persistent_restore(store):
    from paddle_tpu import nn

    def build():
        paddle.seed(11)
        m = nn.Linear(4, 2)
        return m, paddle.jit.to_static(lambda x: m(x) * 2)

    x = paddle.ones([2, 4])
    serial0 = cc.ledger.last_serial()
    _, f1 = build()
    f1(x)  # first call is the eager recording run; compile is on call 2
    out1 = f1(x).numpy()
    evs = [e for e in cc.events(since_serial=serial0)
           if e["origin"] == "to_static"]
    assert [e["outcome"] for e in evs] == ["miss", "persist"]
    assert evs[0]["fingerprint"]
    # a fresh capture of the same program restores instead of recompiling
    serial1 = cc.ledger.last_serial()
    _, f2 = build()
    f2(x)
    out2 = f2(x).numpy()
    evs = [e for e in cc.events(since_serial=serial1)
           if e["origin"] == "to_static"]
    assert [e["outcome"] for e in evs] == ["restore"]
    np.testing.assert_array_equal(out1, out2)


def test_static_executor_ledger_and_restore(store):
    from paddle_tpu import static

    def run_once():
        main = static.Program()
        with static.program_guard(main, static.Program()):
            x = static.data("x", [2, 4], "float32")
            y = paddle.matmul(x, paddle.ones([4, 2])) + 1.0
        exe = static.Executor()
        feed = np.arange(8, dtype="float32").reshape(2, 4)
        (out,) = exe.run(main, feed={"x": feed}, fetch_list=[y])
        return out

    serial0 = cc.ledger.last_serial()
    out1 = run_once()
    evs = [e for e in cc.events(since_serial=serial0)
           if e["origin"] == "static_executor"]
    assert [e["outcome"] for e in evs] == ["miss", "persist"]
    serial1 = cc.ledger.last_serial()
    out2 = run_once()  # same program text + avals -> disk restore
    evs = [e for e in cc.events(since_serial=serial1)
           if e["origin"] == "static_executor"]
    assert [e["outcome"] for e in evs] == ["restore"]
    np.testing.assert_array_equal(out1, out2)


def test_fused_optimizer_ledger_event(telemetry_on):
    from paddle_tpu import nn

    paddle.set_flags({"FLAGS_fused_optimizer": True})
    try:
        paddle.seed(3)
        m = nn.Linear(8, 8)
        opt = paddle.optimizer.AdamW(0.01, parameters=m.parameters())
        serial0 = cc.ledger.last_serial()
        loss = (m(paddle.ones([2, 8])) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    finally:
        paddle.set_flags({"FLAGS_fused_optimizer": False})
    evs = [e for e in cc.events(since_serial=serial0)
           if e["origin"] == "fused_optimizer"]
    assert evs and all(e["outcome"] == "miss" for e in evs)
    assert all(e["fingerprint"] for e in evs)


# ---------------------------------------------------------------------------
# report surfaces: perf_report section, cold-start decomposition, CLIs
# ---------------------------------------------------------------------------

def test_perf_report_compilation_section(telemetry_on):
    from paddle_tpu.profiler import perf_attribution as pa

    cc.record("serving", "prefill_8", "miss", seconds=0.1)
    rep = pa.perf_report()
    pa.validate_report(rep)
    comp = rep["compilation"]
    assert comp["available"]
    assert "serving" in comp["by_origin"]
    # a malformed section fails validation
    bad = dict(rep)
    bad["compilation"] = {"available": True}  # missing the rollup keys
    with pytest.raises(ValueError, match="compilation section"):
        pa.validate_report(bad)


def test_cold_start_report_decomposition(telemetry_on):
    """Components are contiguous by construction, so they sum to the wall
    (consistency == 1.0 on a synthetic airtight timeline)."""
    cc.reset()
    t0 = 100.0
    cc.ledger.mark("engine_load_start", t0)
    cc.ledger.span("engine_init", t0, t0 + 0.5)
    cc.ledger.span("prewarm", t0 + 0.5, t0 + 3.0)
    cc.record("serving", "prefill_8", "miss", seconds=1.0)
    cc.ledger._events[-1]["t_end"] = t0 + 1.8  # land inside the prewarm span
    cc.record("serving", "prefill_8", "persist", seconds=0.2)
    cc.ledger._events[-1]["t_end"] = t0 + 2.0
    cc.ledger.mark("first_token", t0 + 3.4)
    rep = cc.cold_start_report()
    assert rep["available"]
    assert abs(rep["wall_s"] - 3.4) < 1e-6
    comps = rep["components"]
    assert abs(sum(comps.values()) - rep["wall_s"]) <= 0.05 * rep["wall_s"]
    assert abs(rep["consistency"] - 1.0) <= 0.05
    assert comps["engine_init_s"] == pytest.approx(0.5)
    assert comps["prewarm_compile_s"] == pytest.approx(1.0)
    assert comps["prewarm_persist_s"] == pytest.approx(0.2)
    # no timeline -> explicitly unavailable, never a crash
    cc.reset_timeline()
    assert not cc.cold_start_report()["available"]


def test_report_cli_subprocess(tmp_path, telemetry_on):
    cc.reset()
    t0 = 10.0
    cc.ledger.mark("engine_load_start", t0)
    cc.ledger.span("engine_init", t0, t0 + 0.2)
    cc.ledger.mark("first_token", t0 + 1.0)
    dump = cc.ledger.dump_json(str(tmp_path / "dump.json"))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.compile_cache", "report",
         "-i", dump, "--json"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    rep = json.loads(r.stdout)
    assert rep["available"] and abs(rep["wall_s"] - 1.0) < 1e-6
    # unreadable dump -> exit 2 with a message, not a traceback
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.compile_cache", "report",
         "-i", str(tmp_path / "nope.json")],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env)
    assert r.returncode == 2 and "unreadable" in r.stderr


def test_tools_cli_subprocess(tmp_path, store):
    """tools/compile_cache.py stats/verify/gc over a real store dir."""
    for ch in "xy":
        assert store.put(cc.entry_key(ch * 32), _mk_exec(),
                         cc.make_meta("serving", "t", ch * 32))
    os.remove(os.path.join(store.root, cc.entry_key("y" * 32), "COMPLETE"))
    tool = os.path.join(REPO, "tools", "compile_cache.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run(*args):
        return subprocess.run([sys.executable, tool, *args],
                              capture_output=True, text=True, timeout=120,
                              cwd=REPO, env=env)

    r = run("stats", "--dir", store.root)
    assert r.returncode == 0, r.stderr[-2000:]
    st = json.loads(r.stdout)
    assert st["entries"] == 2 and st["corrupt"] == 1
    r = run("verify", "--dir", store.root)
    assert r.returncode == 1  # corrupt entry -> nonzero for cron wrappers
    assert json.loads(r.stdout)["corrupt"] == 1
    r = run("gc", "--dir", store.root, "--max-bytes", "0")
    assert r.returncode == 0, r.stderr[-2000:]
    assert len(json.loads(r.stdout)["removed"]) == 2
    r = run("verify", "--dir", store.root)
    assert r.returncode == 0
    # env-var default dir (no --dir)
    env2 = dict(env, PADDLE_TPU_COMPILE_CACHE_DIR=store.root)
    r = subprocess.run([sys.executable, tool, "stats"], capture_output=True,
                       text=True, timeout=120, cwd=REPO, env=env2)
    assert r.returncode == 0 and json.loads(r.stdout)["entries"] == 0


def test_elastic_relaunch_ships_cache_dir(tmp_path, store, monkeypatch):
    """Ship-ahead: the elastic relaunch exports the controller's compile
    cache dir to every restarted worker, so post-scale engines restore
    their buckets instead of recompiling."""
    import paddle_tpu.distributed.launch.controller as ctrl_mod
    from paddle_tpu.compile_cache.store import ENV_DIR
    from paddle_tpu.distributed.launch import (
        CollectiveController,
        Context,
        parse_args,
    )
    from tests.test_launch import _StubElastic

    assert cc.store_dir() == store.root
    script = tmp_path / "w.py"
    script.write_text("import time; time.sleep(0.1)\n")
    args = parse_args([
        "--nnodes", "2", "--node_rank", "0", "--nproc_per_node", "1",
        "--restart_backoff", "0.01", "--max_restart", "2",
        "--poll_interval", "0.1", str(script),
    ])
    controller = CollectiveController(Context(args))
    controller.elastic = _StubElastic(["hostA"])
    controller.build_pod()
    monkeypatch.setattr(ctrl_mod.time, "sleep", lambda d: None)
    try:
        assert controller._elastic_restart() is True
        env = controller.pod.containers[0].env
        assert env[ENV_DIR] == store.root
    finally:
        controller.pod.stop(force=True)
