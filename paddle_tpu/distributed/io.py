"""paddle.distributed.io (reference python/paddle/distributed/io.py):
persistable save/load around the static executor. TPU-native: persistables
are the Program's parameter tensors; the distributed variants collapse to
the single-program save because GSPMD keeps a global view of sharded
tensors (no per-rank split files needed)."""
from __future__ import annotations

import os

from ..framework import io as fio


def is_persistable(var):
    """reference io.py:357: parameters and persistable buffers persist;
    temporaries don't. Keyed on Parameter identity / the persistable flag —
    NOT stop_gradient (a frozen param persists; a tape temporary doesn't)."""
    from ..nn.layer import Parameter

    return isinstance(var, Parameter) or bool(getattr(var, "persistable", False))


def save_persistables(executor, dirname, main_program=None, filename=None):
    """reference io.py:392: save every persistable of the program."""
    from ..static import default_main_program

    prog = main_program or default_main_program()
    params = prog.all_parameters()
    state = {
        (p.name or f"param_{i}"): p for i, p in enumerate(params)
    }
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, filename or "__persistables__")
    fio.save(state, path)
    return path


def load_persistables(executor, dirname, main_program=None, filename=None):
    """reference io.py:132."""
    from ..static import default_main_program

    prog = main_program or default_main_program()
    path = os.path.join(dirname, filename or "__persistables__")
    state = fio.load(path)
    params = prog.all_parameters()
    by_name = {(p.name or f"param_{i}"): p for i, p in enumerate(params)}
    for name, value in state.items():
        if name in by_name:
            by_name[name].set_value(value)
    return state


def load_inference_model_distributed(dirname, executor, **kwargs):
    """reference io.py:464: the distributed variant of
    static.load_inference_model — one artifact here (global-view tensors)."""
    from ..static import load_inference_model

    return load_inference_model(dirname, executor, **kwargs)
