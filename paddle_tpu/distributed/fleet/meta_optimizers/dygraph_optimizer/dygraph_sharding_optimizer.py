"""ZeRO stage-1 optimizer wrapper (dygraph sharding).

Reference parity: fleet/meta_optimizers/dygraph_optimizer/
dygraph_sharding_optimizer.py (DygraphShardingOptimizer) — there: params are
partitioned per sharding rank (greedy by size), each rank keeps optimizer
state only for its partition, updated params broadcast after step; optional
reduce-scatter ("reduce_overlap") of grads. TPU-native design: the partition
IS a placement — every accumulator is sharded over the sharding axis (GSPMD
tiles the update and all-gathers params where used), so the greedy
param-to-rank assignment, broadcast loop and fused buffers disappear.
"""
from __future__ import annotations

from ...meta_parallel.sharding import group_sharded_utils as utils


class DygraphShardingOptimizer:
    def __init__(self, optimizer, hcg=None, **kw):
        self._inner_opt = optimizer
        # ZeRO shards per-accumulator; the flat fused path would hide them
        optimizer.disable_fusion()
        self._hcg = hcg
        if hcg is not None and "sharding" in hcg.mesh.shape:
            self._mesh, self._axis = hcg.mesh, "sharding"
        else:
            self._mesh = utils.group_mesh(None)
            self._axis = utils.group_axis_name(None)

    @property
    def inner_opt(self):
        return self._inner_opt

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def _shard_states(self):
        for _, by_param in self._inner_opt._accumulators.items():
            for t in by_param.values():
                utils.place_sharded(t, self._mesh, self._axis)

    def step(self):
        self._inner_opt.step()
        self._shard_states()

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        # base Optimizer.minimize contract: no clear_grad, returns (None, None)
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero: bool = False):
        self._inner_opt.clear_grad(set_to_zero)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        self._inner_opt.set_state_dict(sd)
        self._shard_states()
