"""Flat-bucket engine for the one-pass Pallas optimizer (FLAGS_fused_optimizer).

Reference parity: the role of fleet's tensor_fusion_helper + the GPU
multi_tensor_adam path — parameters (with their grads, moment1, moment2)
are flattened into a small number of contiguous same-(dtype, weight-decay,
lr-scale) buckets at first step(), and each bucket updates through ONE
Pallas kernel (`ops.fused_optimizer.fused_adamw_apply`) that streams the
param/m/v/grad tiles through VMEM exactly once.

Differences from the stacked-group fusion in `Adam._apply_fused` (which
stays the default — this engine is opt-in via FLAGS_fused_optimizer):

  - buckets span *heterogeneous shapes*: a param→(bucket, offset, shape)
    index map reconstitutes per-param views for state_dict round-trips;
  - moment1/moment2 live PERSISTENTLY flat — only the param/grad gather and
    param scatter touch per-tensor layout, and those are single
    concat/slice ops XLA schedules around the kernel;
  - the global-norm clip enters the kernel as one scalar operand instead of
    scaling every gradient tensor first;
  - beta-pow bias corrections are per-bucket scalars;
  - moment2 may be stored bfloat16 (optimizer moment2_dtype='bfloat16')
    with the flat-index stochastic rounding from ops/fused_optimizer.

State contract: `state_dict()` output is identical in keys and shapes to
the per-tensor path (`moment1_i` / `moment2_i` / `beta1_pow_i` / ...), so
checkpoints move freely between fused and unfused runs, matching the
stacked buckets' fusion-agnostic format.
"""
from __future__ import annotations

import time
from collections import defaultdict

import jax.numpy as jnp
import numpy as _np

from ..core.tensor import Tensor
from ..ops.fused_optimizer import fused_adamw_apply, pad_to_tile


def _bucket_array(t, what="bucket state"):
    """Read a flat-bucket Tensor's array, raising a clean error when the
    buffer was donated to a compiled step and consumed (FLAGS_to_static_donate
    adopts the output buffer; the old array is deleted on device)."""
    import jax

    v = t._value
    # tracers (under to_static capture) have no liveness to check
    deleted = None if isinstance(v, jax.core.Tracer) else getattr(v, "is_deleted", None)
    if deleted is not None and deleted():
        raise RuntimeError(
            f"fused-optimizer {what} was donated to a to_static compiled step "
            "and its buffer is gone; read optimizer state before the step or "
            "set FLAGS_to_static_donate=False to keep copying semantics"
        )
    return v


class FlatAdamWEngine:
    """Per-optimizer flat-bucket store + step executor for Adam/AdamW."""

    def __init__(self, opt):
        self.opt = opt
        # key -> bucket dict; key = (dtype, wd_value, lr_scale, need_clip)
        self.buckets: dict = {}

    # ---- partitioning ----
    def _partition(self, entries):
        """Split (p, g, wd, lr_scale) entries into flat-fusable buckets and a
        per-param remainder (same gates as Adam._fuse_partition, widened to
        bfloat16 params — the kernel computes in f32 and stores back bf16,
        matching the per-tensor cast chain)."""
        from ..regularizer import L1Decay
        from .optimizer import _wd_value

        buckets = defaultdict(list)
        rest = []
        for p, g, wd, s in entries:
            fusable = (
                not isinstance(wd, L1Decay)
                and p._value.dtype in (jnp.float32, jnp.bfloat16)
                and getattr(p, "_dist_attr", None) is None
                and tuple(g.value.shape) == tuple(p._value.shape)
            )
            if fusable:
                key = (p._value.dtype, _wd_value(wd), float(s),
                       bool(getattr(p, "need_clip", True)))
                buckets[key].append((p, g))
            else:
                rest.append((p, g, wd, s))
        return buckets, rest

    # ---- bucket lifecycle ----
    def _build_bucket(self, key, plist):
        from .. import telemetry as _tm

        t0 = time.perf_counter()
        opt = self.opt
        ids = tuple(id(p) for p, _ in plist)
        new_ids = set(ids)
        # composition changed (params frozen/unfrozen, groups edited):
        # dissolve every overlapping bucket — flat AND stacked (migration
        # from the default path when the flag flips mid-training) — so its
        # state lands in _pending_state and is inherited below, not zeroed
        for k2, b2 in list(self.buckets.items()):
            if new_ids.intersection(b2["ids"]):
                self._defuse_bucket(b2)
                del self.buckets[k2]
        for old_ids, old_st in list(opt._fused_buckets.items()):
            if new_ids.intersection(old_ids):
                opt._defuse_bucket(old_st)
                del opt._fused_buckets[old_ids]

        index, off = {}, 0
        for p, _ in plist:
            size = int(p._value.size)
            index[id(p)] = (off, size, tuple(p._value.shape))
            off += size
        n, n_pad = off, pad_to_tile(off)
        m2_dtype = opt._m2_dtype

        def gather(name, dtype):
            parts = []
            for p, _ in plist:
                prev = opt._pop_param_state(name, id(p))
                if prev is not None:
                    parts.append(jnp.asarray(prev).astype(dtype).ravel())
                else:
                    parts.append(jnp.zeros((int(p._value.size),), dtype))
            if n_pad > n:
                parts.append(jnp.zeros((n_pad - n,), dtype))
            return jnp.concatenate(parts)

        def gather_scalar(name, fill):
            first = None
            for p, _ in plist:
                prev = opt._pop_param_state(name, id(p))
                if prev is not None and first is None:
                    first = jnp.asarray(prev, jnp.float32).reshape(())
            return first if first is not None else jnp.asarray(fill, jnp.float32)

        bucket = {
            "ids": ids,
            "index": index,
            "n": n,
            "n_pad": n_pad,
            "moment1": Tensor(gather("moment1", jnp.float32)),
            "moment2": Tensor(gather("moment2", m2_dtype)),
            "beta1_pow": Tensor(gather_scalar("beta1_pow", 1.0)),
            "beta2_pow": Tensor(gather_scalar("beta2_pow", 1.0)),
        }
        self.buckets[key] = bucket
        if _tm.enabled():
            _tm.counter(
                "paddle_tpu_fused_optimizer_bucket_builds_total",
                "flat optimizer buckets (re)built",
            ).inc()
            _tm.histogram(
                "paddle_tpu_fused_optimizer_bucket_build_seconds",
                "wall time to flatten one bucket's params/state",
            ).observe(time.perf_counter() - t0)
            _tm.gauge(
                "paddle_tpu_fused_optimizer_bucket_bytes", "flat bucket bytes",
            ).set(sum(
                int(b["moment1"]._value.nbytes + b["moment2"]._value.nbytes)
                for b in self.buckets.values()
            ))
            # after the build-time observe: the attribution AOT compile must
            # not inflate the bucket-build histogram
            self._record_kernel_attribution(key, bucket)
        return bucket

    def _record_kernel_attribution(self, key, bucket):
        """Capture the bucket kernel's XLA cost/memory analysis into the
        attribution layer: one AOT lower+compile of `fused_adamw_apply` at
        the bucket's exact shapes/dtypes. Runs only at bucket (re)build and
        only under telemetry — a one-time compile of a flat elementwise
        program, paid so perf_report can attribute the optimizer's HBM
        traffic per bucket. Best-effort: failure never touches the step."""
        try:
            import time

            import jax

            from ..profiler import perf_attribution as _pa

            opt = self.opt
            dtype, wdv, _lr_scale, _need_clip = key
            n_pad = bucket["n_pad"]
            m2_dtype = bucket["moment2"]._value.dtype
            decoupled = opt._wd_mode == "decoupled"

            def apply_fn(p, m, v, g, lr, c1, c2):
                return fused_adamw_apply(
                    p, m, v, g, lr=lr, clip_scale=1.0, c1=c1, c2=c2, seed=0,
                    beta1=opt._beta1, beta2=opt._beta2, eps=opt._eps,
                    wd=wdv, decoupled=decoupled,
                )

            flat = lambda d: jax.ShapeDtypeStruct((n_pad,), d)  # noqa: E731
            scalar = jax.ShapeDtypeStruct((), jnp.float32)
            t0 = time.perf_counter()
            lowered = jax.jit(apply_fn).lower(
                flat(dtype), flat(jnp.float32), flat(m2_dtype),
                flat(jnp.float32), scalar, scalar, scalar,
            )
            compiled = lowered.compile()
            dt = time.perf_counter() - t0
            name = f"bucket[{_np.dtype(dtype).name},n={n_pad}]"
            _pa.record_compiled(
                "fused_optimizer",
                name,
                lowered=lowered,
                compiled=compiled,
                compile_seconds=dt,
                extra={"n_elems": n_pad, "m2_dtype": str(_np.dtype(m2_dtype))},
            )
            # round 18 compile ledger (observability only — the bucket
            # kernel re-specializes on optimizer state in ways the
            # persistent store's fingerprint can't capture, so no store)
            from .. import compile_cache as _cc

            _cc.record(
                "fused_optimizer", name, "miss", seconds=dt,
                fingerprint=_cc.fingerprint_text(
                    f"fused-optimizer-v1|{name}|wd={wdv}|"
                    f"decoupled={decoupled}|m2={_np.dtype(m2_dtype).name}"
                ),
                signature=f"n={n_pad}",
            )
        except Exception:
            pass

    def _bucket_for(self, key, plist):
        ids = tuple(id(p) for p, _ in plist)
        b = self.buckets.get(key)
        if b is None or b["ids"] != ids:
            b = self._build_bucket(key, plist)
        return b

    # ---- the step ----
    def step(self, groups):
        """groups = [(clip, entries)] with UNCLIPPED grads; entries =
        (p, g, wd, lr_scale)."""
        from .. import telemetry as _tm
        from ..nn.clip import ClipGradByGlobalNorm

        opt = self.opt
        launches_saved = 0
        for clip, entries in groups:
            entries = [(p, g, opt._effective_wd(p, wd), s) for p, g, wd, s in entries]
            scale = None
            if isinstance(clip, ClipGradByGlobalNorm):
                # the norm reduction runs here (one fused XLA reduction over
                # raw grads); the SCALING rides the kernel as a scalar operand
                gs = [g.value for p, g, _, _ in entries if getattr(p, "need_clip", True)]
                if gs:
                    gn = jnp.sqrt(sum(
                        jnp.sum(jnp.square(gv.astype(jnp.float32))) for gv in gs
                    ))
                    scale = clip.clip_norm / jnp.maximum(gn, clip.clip_norm)
            elif clip is not None:
                # per-tensor clips (ByNorm/ByValue) have no scalar form:
                # pre-apply them, then fuse the clipped grads
                pgs = clip([(p, g) for p, g, _, _ in entries])
                entries = [
                    (p, g2, wd, s)
                    for (p, _, wd, s), (_, g2) in zip(entries, pgs)
                ]
            buckets, rest = self._partition(entries)
            for key, plist in buckets.items():
                self._apply_bucket(key, plist, scale)
                launches_saved += max(0, len(plist) - 1)
            for p, g, wd, s in rest:
                if scale is not None and getattr(p, "need_clip", True):
                    g = Tensor(g.value * scale.astype(g.value.dtype))
                opt._apply_one(p, g, wd, s)
        if _tm.enabled():
            _tm.counter(
                "paddle_tpu_fused_optimizer_steps_total",
                "optimizer steps taken through the flat-bucket engine",
                ("optimizer",),
            ).labels(optimizer=type(opt).__name__).inc()
            _tm.counter(
                "paddle_tpu_fused_optimizer_launches_saved_total",
                "per-tensor update launches replaced by bucket kernels",
            ).inc(launches_saved)
            _tm.gauge(
                "paddle_tpu_fused_optimizer_buckets", "live flat buckets",
            ).set(len(self.buckets))

    def _apply_bucket(self, key, plist, clip_scale):
        opt = self.opt
        dtype, wdv, lr_scale, need_clip = key
        b = self._bucket_for(key, plist)
        n, n_pad = b["n"], b["n_pad"]

        g_parts = [g.value.ravel().astype(jnp.float32) for _, g in plist]
        p_parts = [p._value.ravel() for p, _ in plist]
        if n_pad > n:
            g_parts.append(jnp.zeros((n_pad - n,), jnp.float32))
            p_parts.append(jnp.zeros((n_pad - n,), dtype))
        G = jnp.concatenate(g_parts) if len(g_parts) > 1 else g_parts[0]
        P = jnp.concatenate(p_parts) if len(p_parts) > 1 else p_parts[0]

        b1p, b2p = b["beta1_pow"], b["beta2_pow"]
        b1p_new = b1p.value * opt._beta1
        b2p_new = b2p.value * opt._beta2
        seed = opt._m2_key() if opt._m2_dtype == jnp.bfloat16 else 0

        P2, M2, V2 = fused_adamw_apply(
            P,
            _bucket_array(b["moment1"], "moment1 bucket"),
            _bucket_array(b["moment2"], "moment2 bucket"),
            G,
            lr=opt._lr_value(lr_scale),
            clip_scale=clip_scale if (clip_scale is not None and need_clip) else 1.0,
            c1=1.0 - b1p_new,
            c2=1.0 - b2p_new,
            seed=seed,
            beta1=opt._beta1,
            beta2=opt._beta2,
            eps=opt._eps,
            wd=wdv,
            decoupled=opt._wd_mode == "decoupled",
        )
        for p, _ in plist:
            off, size, shape = b["index"][id(p)]
            p._replace_value(P2[off:off + size].reshape(shape))
            p.stop_gradient = False
        b["moment1"]._replace_value(M2)
        b["moment2"]._replace_value(V2)
        b1p._replace_value(b1p_new)
        b2p._replace_value(b2p_new)

    # ---- state plumbing (mirrors the stacked buckets' contracts) ----
    def materialize(self, groups):
        """Force buckets into existence for the current composition without
        updating anything (snapshot/restore consumers — GradScaler)."""
        for clip, entries in groups:
            entries = [
                (p, g, self.opt._effective_wd(p, wd), s) for p, g, wd, s in entries
            ]
            buckets, _ = self._partition(entries)
            for key, plist in buckets.items():
                self._bucket_for(key, plist)

    def _defuse_bucket(self, b):
        m = _bucket_array(b["moment1"], "moment1 bucket")
        v = _bucket_array(b["moment2"], "moment2 bucket")
        for pid, (off, size, shape) in b["index"].items():
            self.opt._pending_state[("moment1", pid)] = m[off:off + size].reshape(shape)
            self.opt._pending_state[("moment2", pid)] = v[off:off + size].reshape(shape)
            self.opt._pending_state[("beta1_pow", pid)] = b["beta1_pow"]._value
            self.opt._pending_state[("beta2_pow", pid)] = b["beta2_pow"]._value

    def defuse_all(self):
        for b in list(self.buckets.values()):
            self._defuse_bucket(b)
        self.buckets.clear()

    def view_into(self, view):
        """Expose bucket state as per-param slices (state_dict format is
        fusion-agnostic, same as the stacked buckets)."""
        for b in self.buckets.values():
            m = _bucket_array(b["moment1"], "moment1 bucket")
            v = _bucket_array(b["moment2"], "moment2 bucket")
            for pid, (off, size, shape) in b["index"].items():
                view.setdefault("moment1", {})[pid] = Tensor(m[off:off + size].reshape(shape))
                view.setdefault("moment2", {})[pid] = Tensor(v[off:off + size].reshape(shape))
                view.setdefault("beta1_pow", {})[pid] = b["beta1_pow"]
                view.setdefault("beta2_pow", {})[pid] = b["beta2_pow"]

    def state_entries(self):
        out = []
        for b in self.buckets.values():
            out.append((b["moment1"], 0.0))
            out.append((b["moment2"], 0.0))
            out.append((b["beta1_pow"], 1.0))
            out.append((b["beta2_pow"], 1.0))
        return out

    def digest_units(self):
        """[(name, array)] for the guardian's cross-rank desync digest: one
        checksum unit per flat bucket tensor, named by the bucket key so a
        detected divergence points at a specific (dtype, wd, lr_scale) bucket
        rather than 'somewhere in the optimizer'."""
        out = []
        for bi, (key, b) in enumerate(sorted(
            self.buckets.items(), key=lambda kv: repr(kv[0])
        )):
            dtype, wdv, lr_scale, _need_clip = key
            tag = f"flat_bucket:{bi}[{_np.dtype(dtype).name},wd={wdv},lrs={lr_scale}]"
            out.append((f"{tag}:moment1", _bucket_array(b["moment1"], "moment1 bucket")))
            out.append((f"{tag}:moment2", _bucket_array(b["moment2"], "moment2 bucket")))
        return out
