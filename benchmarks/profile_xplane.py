"""Per-op device-time breakdown of the headline train step via the XLA
profiler (works on the axon tunnel — device_duration_ps is populated).

Prints total device time per HLO category and the top-N individual ops,
so every millisecond of the step has a name (VERDICT r2 Weak #1).

Run: python benchmarks/profile_xplane.py
"""
import glob
import gzip
import json
import os
import sys
import tempfile
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

import paddle_tpu as paddle


def main():
    from bench import build_train_step

    batch = int(os.environ.get("BENCH_BATCH", 64))
    seq = int(os.environ.get("BENCH_SEQ", 128))
    heads = int(os.environ.get("BENCH_HEADS", 12))
    # same builder as bench.py: the profiled model IS the benchmarked model.
    # BENCH_ATTN_DROPOUT=0.1 matches bench.py's seq-4096 operating point
    # (in-kernel attention dropout — r5); default 0 matches seq-128.
    drop = float(os.environ.get("BENCH_ATTN_DROPOUT", "0"))
    model, train_step, ids, labels = build_train_step(
        batch, seq, heads, attn_dropout=drop
    )

    # warm + compile
    for _ in range(4):
        loss = train_step(ids, labels)
    float(loss.numpy())

    tdir = tempfile.mkdtemp(prefix="xplane_")
    jax.profiler.start_trace(tdir)
    NSTEP = 3
    for _ in range(NSTEP):
        loss = train_step(ids, labels)
    float(loss.numpy())  # force execution inside the trace window
    jax.profiler.stop_trace()

    traces = glob.glob(f"{tdir}/**/*.trace.json.gz", recursive=True)
    d = json.load(gzip.open(traces[0]))
    evs = d["traceEvents"]

    # find the device pid and its "XLA Ops" tid
    dev_pid = next(e["pid"] for e in evs
                   if e.get("ph") == "M" and e.get("name") == "process_name"
                   and "TPU" in e["args"]["name"])
    ops_tid = next(e["tid"] for e in evs
                   if e.get("ph") == "M" and e.get("name") == "thread_name"
                   and e["pid"] == dev_pid and e["args"]["name"] == "XLA Ops")

    cat_time = defaultdict(float)
    op_time = defaultdict(float)
    op_src = {}
    total = 0.0
    for e in evs:
        if e.get("ph") != "X" or e.get("pid") != dev_pid or e.get("tid") != ops_tid:
            continue
        a = e.get("args", {})
        dur_ms = int(a.get("device_duration_ps", 0)) / 1e9
        cat = a.get("hlo_category", "?")
        cat_time[cat] += dur_ms
        op_time[e["name"]] += dur_ms
        if e["name"] not in op_src:
            op_src[e["name"]] = (a.get("tf_op", ""), (a.get("source_stack", "").splitlines() or [""])[0],
                                 a.get("shape_with_layout", ""), int(a.get("bytes_accessed", 0)),
                                 a.get("long_name", "")[:200])
        total += dur_ms

    print(f"== device time over {NSTEP} steps: {total:.2f} ms ({total/NSTEP:.2f} ms/step) ==")
    print("\n-- by HLO category --")
    for cat, t in sorted(cat_time.items(), key=lambda kv: -kv[1]):
        print(f"{t/NSTEP:9.3f} ms/step  {cat}")
    print("\n-- top 20 ops --")
    for name, t in sorted(op_time.items(), key=lambda kv: -kv[1])[:20]:
        tf_op, src, shape, nbytes, long = op_src[name]
        print(f"{t/NSTEP:9.3f} ms/step  {name[:40]:40s} {nbytes/1e6:9.1f} MB  {tf_op[:44]:44s} {src[:50]}")
        print(f"           shape={shape[:110]}")
        print(f"           {long[:160]}")


if __name__ == "__main__":
    main()
