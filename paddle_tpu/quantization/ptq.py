"""PTQ driver (reference: python/paddle/quantization/ptq.py).

PTQ(config).quantize(model) inserts observers; run calibration batches
through the model; convert() replaces observers with fixed-scale fake-quant
on weights and bakes the result.
"""
from __future__ import annotations

import copy

import numpy as np

from ..nn.layer import Layer
from .qat import _QAT_WRAPPERS, _materialize_layer_configs, _walk_and_replace
from .quanted_layers import QuantedConv2D, QuantedLinear
from .quanters import fake_quant

_PTQ_WRAPPERS = _QAT_WRAPPERS  # same wrapper table; one registration point


class PTQ:
    def __init__(self, config):
        self._config = config

    def quantize(self, model: Layer, inplace=False):
        _materialize_layer_configs(self._config, model)
        if not inplace:
            model = copy.deepcopy(model)

        def decide(layer, qualified):
            wrapper = _PTQ_WRAPPERS.get(type(layer))
            if wrapper is None:
                return None
            cfg = self._config._config_for(layer, qualified)
            if cfg is None:
                return None
            return wrapper(layer, cfg)

        _walk_and_replace(model, decide)
        model.eval()
        return model

    def convert(self, model: Layer, inplace=False):
        if not inplace:
            model = copy.deepcopy(model)

        def decide(layer, qualified):
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                inner = layer._inner
                wq = layer.weight_quanter
                if wq is not None:
                    scale = wq.scales()
                    # group/channel-wise observers emit vector scales; the
                    # calibration check is their max
                    if float(np.abs(np.asarray(scale.numpy())).max()) <= 1e-8:
                        import warnings

                        warnings.warn(
                            f"PTQ.convert: observer for {qualified!r} was never calibrated "
                            "(scale ~ 0); run calibration batches before convert. Skipping."
                        )
                        return inner
                    bits = wq.bit_length() if hasattr(wq, "bit_length") else 8
                    inner.weight._replace_value(fake_quant(inner.weight, scale, bits)._value)
                return inner
            return None

        _walk_and_replace(model, decide)
        return model
