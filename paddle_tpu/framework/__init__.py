from . import dtype, device, flags, random  # noqa: F401
